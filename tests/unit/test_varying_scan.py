"""The epoch-tiled varying-weights fused engine (ISSUE 15).

`fused_varying_scan` is `fused_case_scan`'s twin for workloads whose
single-epoch `[Vp, Mp]` block underfills the chip: each grid step
advances a whole epoch tile, with the bond-independent math
(`_consensus_phase` / `_clip_rank_rate`) batched over the tile and only
the bond recurrence sequential. These tests pin its numeric contract on
every bond model in interpret mode (the same program compiles via
Mosaic on chip; on-chip parity rides tools/tpu_parity.py like the other
fused kernels):

- the consensus / incentive surface is BITWISE the per-epoch case scan
  for every tile length (the cross-engine consensus contract);
- dividends/bonds match the case scan and the XLA rung to
  reduction-order rounding (the same class as the existing fused rung's
  XLA contract — tests/unit/test_fused_case_scan.py's tolerances);
- runs sharing one program are bitwise each other: MXU == VPU (the
  default numerics-canary pairing), chunked carry composition at a
  fixed tile, batched == solo lanes, repeated suffix resumes;
- the planner admits, validates, demotes and ladders the new rungs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig, YumaParams
from yuma_simulation_tpu.models.epoch import BondsMode
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.ops.pallas_epoch import (
    VARYING_EPOCH_TILE_MAX,
    _varying_scan_mats,
    fused_case_scan,
    fused_varying_scan,
    fused_varying_scan_eligible,
    varying_scan_epoch_tile,
)
from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.simulation.engine import (
    _simulate_case_fused,
    _simulate_scan,
    simulate,
    simulate_streamed,
)
from yuma_simulation_tpu.simulation.planner import (
    ENGINE_LADDER,
    FUSED_CASE_RUNGS,
    ladder_from,
    plan_dispatch,
    rung_flags,
)

VERSION = "Yuma 1 (paper)"
CFG = YumaConfig()
ON_TPU = jax.default_backend() == "tpu"

ALL_VERSIONS = [
    ("Yuma 0 (subtensor)", {}),
    ("Yuma 1 (paper)", {}),
    ("Yuma 1 (paper) - liquid alpha on", dict(liquid_alpha=True)),
    ("Yuma 2 (Adrian-Fish)", {}),
    ("Yuma 3 (Rhef)", {}),
    ("Yuma 3.1 (Rhef+reset)", {}),
    ("Yuma 3.2 (Rhef+conditional)", {}),
    ("Yuma 4 (Rhef+relative bonds)", {}),
]

ALL_MODES = (
    BondsMode.EMA,
    BondsMode.EMA_PREV,
    BondsMode.EMA_RUST,
    BondsMode.CAPACITY,
    BondsMode.RELATIVE,
)


def _workload(seed=0, E=12, V=6, M=18):
    rng = np.random.default_rng(seed)
    W = jnp.asarray(rng.random((E, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((E, V)) + 0.01, jnp.float32)
    return W, S


def _zero_carry(mode, V, M, lead=()):
    carry = {
        "bonds": jnp.zeros(lead + (V, M), jnp.float32),
        "consensus": jnp.zeros(lead + (M,), jnp.float32),
    }
    if mode is BondsMode.EMA_PREV:
        carry["w_prev"] = jnp.zeros(lead + (V, M), jnp.float32)
    return carry


# ---------------------------------------------------------------------------
# kernel-level parity


@pytest.mark.parametrize(
    "version,params", ALL_VERSIONS, ids=[v for v, _ in ALL_VERSIONS]
)
def test_varying_scan_matches_xla_scan(version, params):
    """Full-save parity vs the XLA engine on every variant, with reset
    metadata armed — the same tolerance contract as the per-epoch fused
    rung's."""
    W, S = _workload()
    ri = jnp.asarray(2, jnp.int32)
    re = jnp.asarray(4, jnp.int32)
    cfg = YumaConfig(yuma_params=YumaParams(**params))
    spec = variant_for_version(version)
    ys_x = _simulate_scan(W, S, ri, re, cfg, spec, save_consensus=True)
    ys_v = _simulate_case_fused(
        W, S, ri, re, cfg, spec, save_consensus=True, varying=True
    )
    assert ys_x.keys() == ys_v.keys()
    for k in ys_x:
        np.testing.assert_allclose(
            np.asarray(ys_v[k]),
            np.asarray(ys_x[k]),
            atol=2e-6,
            rtol=1e-5,
            err_msg=f"{version}: {k}",
        )


@pytest.mark.parametrize("mode", ALL_MODES, ids=[m.name for m in ALL_MODES])
def test_varying_tile_invariance(mode):
    """The tile groups epochs, it must not change the model: the
    consensus/incentive surface is bitwise the per-epoch case scan for
    EVERY tile length; dividends/bonds stay within reduction-order
    rounding of it."""
    W, S = _workload(seed=1)
    ref = fused_case_scan(W, S, mode=mode, save_consensus=True)
    for et in (1, 2, 3, 4, 6, 12):
        got = fused_varying_scan(
            W, S, mode=mode, save_consensus=True, epoch_tile=et
        )
        assert got.keys() == ref.keys()
        for k in ("consensus", "incentives"):
            assert np.array_equal(
                np.asarray(got[k]), np.asarray(ref[k])
            ), (mode, et, k)
        for k in ("dividends_normalized", "bonds", "final_bonds"):
            np.testing.assert_allclose(
                np.asarray(got[k]),
                np.asarray(ref[k]),
                atol=1e-6,
                rtol=1e-5,
                err_msg=f"{mode} tile={et}: {k}",
            )


def test_varying_scan_rejects_non_divisor_tile():
    W, S = _workload(E=10)
    with pytest.raises(ValueError, match="divide"):
        fused_varying_scan(W, S, epoch_tile=4)
    with pytest.raises(ValueError, match=">= 1"):
        fused_varying_scan(W, S, epoch_tile=0)


@pytest.mark.parametrize("mode", ALL_MODES, ids=[m.name for m in ALL_MODES])
def test_varying_mxu_bitwise_vpu(mode):
    """The MXU twin must be BITWISE the VPU twin at the same program —
    this is the pair the default numerics canary compares (one rung
    below the primary on the ladder), so any divergence here would be a
    standing false drift alarm."""
    W, S = _workload(seed=2)
    kw = dict(mode=mode, save_consensus=True, epoch_tile=4)
    vpu = fused_varying_scan(W, S, mxu=False, **kw)
    mxu = fused_varying_scan(W, S, mxu=True, **kw)
    for k in vpu:
        assert np.array_equal(np.asarray(vpu[k]), np.asarray(mxu[k])), (
            mode,
            k,
        )


def test_varying_mxu_bitwise_vpu_liquid():
    W, S = _workload(seed=3)
    cfg_kw = dict(liquid_alpha=True)
    vpu = fused_varying_scan(W, S, epoch_tile=4, mxu=False, **cfg_kw)
    mxu = fused_varying_scan(W, S, epoch_tile=4, mxu=True, **cfg_kw)
    for k in vpu:
        assert np.array_equal(np.asarray(vpu[k]), np.asarray(mxu[k])), k


@pytest.mark.parametrize("mode", ALL_MODES, ids=[m.name for m in ALL_MODES])
def test_varying_chunked_carry_composition(mode):
    """Fixed-tile chunk composition over the carry contract is bitwise
    a single carry-threaded run: the invariance the streaming and
    Monte-Carlo drivers thread slabs on (all chunks share ONE compiled
    program, so there is no cross-program rounding surface)."""
    W, S = _workload(seed=4)
    V, M = 6, 18
    kw = dict(mode=mode, save_bonds=False, save_incentives=False, epoch_tile=4)
    mono = fused_varying_scan(
        W, S, carry=_zero_carry(mode, V, M), epoch_offset=0,
        return_carry=True, **kw,
    )

    def compose(chunks):
        carry = _zero_carry(mode, V, M)
        lo, dn = 0, []
        for c in chunks:
            out = fused_varying_scan(
                W[lo : lo + c], S[lo : lo + c], carry=carry,
                epoch_offset=lo, return_carry=True, **kw,
            )
            carry = {
                "bonds": out["final_bonds"],
                "consensus": out["final_consensus"],
            }
            if mode is BondsMode.EMA_PREV:
                carry["w_prev"] = out["final_w_prev"]
            dn.append(out["dividends_normalized"])
            lo += c
        return np.concatenate(dn), np.asarray(carry["bonds"])

    # Uniform chunking runs ONE compiled program for every chunk:
    # repeated composition is bitwise-identical (what the streaming and
    # Monte-Carlo slab drivers rely on).
    dn_a, bonds_a = compose([4, 4, 4])
    dn_b, bonds_b = compose([4, 4, 4])
    assert np.array_equal(dn_a, dn_b), mode
    assert np.array_equal(bonds_a, bonds_b), mode
    # Across program classes (different chunk lengths, the monolithic
    # dispatch) the bound is reduction-order rounding — the same class
    # as the fused-vs-XLA contract; the consensus surface stays bitwise
    # (pinned by the tile-invariance test).
    for chunks in ([8, 4], [4, 8]):
        dn_c, bonds_c = compose(chunks)
        np.testing.assert_allclose(
            dn_c, np.asarray(mono["dividends_normalized"]),
            atol=1e-6, rtol=1e-5, err_msg=f"{mode} {chunks}",
        )
        np.testing.assert_allclose(
            bonds_c, np.asarray(mono["final_bonds"]),
            atol=1e-6, rtol=1e-5, err_msg=f"{mode} {chunks}",
        )
    np.testing.assert_allclose(
        dn_a, np.asarray(mono["dividends_normalized"]),
        atol=1e-6, rtol=1e-5, err_msg=str(mode),
    )


def test_varying_batched_lanes_bitwise_solo():
    W, S = _workload(seed=5)
    Wb = jnp.stack([W, W[::-1]])
    Sb = jnp.stack([S, S[::-1]])
    batched = fused_varying_scan(
        Wb, Sb, save_consensus=True, epoch_tile=4
    )
    for lane, (Wl, Sl) in enumerate(((W, S), (W[::-1], S[::-1]))):
        solo = fused_varying_scan(Wl, Sl, save_consensus=True, epoch_tile=4)
        for k in ("consensus", "incentives"):
            assert np.array_equal(
                np.asarray(batched[k])[lane], np.asarray(solo[k])
            ), (lane, k)
        for k in ("dividends_normalized", "bonds", "final_bonds"):
            np.testing.assert_allclose(
                np.asarray(batched[k])[lane],
                np.asarray(solo[k]),
                atol=1e-6,
                rtol=1e-5,
            )


@pytest.mark.parametrize(
    "version",
    ["Yuma 3.1 (Rhef+reset)", "Yuma 3.2 (Rhef+conditional)"],
)
def test_varying_reset_fires_like_xla(version):
    """Reset injection across a tile boundary: the rule keys off the
    GLOBAL epoch and the previous epoch's consensus (carried across
    tiles), exactly as the per-epoch engines."""
    W, S = _workload(seed=3)
    W = W.at[3:, :, 3].set(0.0)
    ri = jnp.asarray(3, jnp.int32)
    re = jnp.asarray(5, jnp.int32)
    spec = variant_for_version(version)
    ys_x = _simulate_scan(W, S, ri, re, CFG, spec)
    ys_v = _simulate_case_fused(
        W, S, ri, re, CFG, spec, varying=True
    )
    for k in ys_x:
        np.testing.assert_allclose(
            np.asarray(ys_v[k]), np.asarray(ys_x[k]), atol=2e-6, rtol=1e-5
        )
    ys_off = _simulate_case_fused(
        W, S, jnp.asarray(-1, jnp.int32), jnp.asarray(-1, jnp.int32),
        CFG, spec, varying=True,
    )
    assert not np.allclose(
        np.asarray(ys_v["bonds"][5]), np.asarray(ys_off["bonds"][5])
    )


def test_varying_suffix_resume_randomized():
    """The PR 14 suffix-resume contract on the new rung: resuming from
    a returned carry at randomized checkpoint epochs reproduces the
    same-structured composition bitwise (repeat determinism) and the
    monolithic run to reduction-order rounding."""
    rng = np.random.default_rng(7)
    W, S = _workload(seed=8, E=16)
    mono = fused_varying_scan(
        W, S, save_bonds=False, save_incentives=False, epoch_tile=4,
        carry=_zero_carry(BondsMode.EMA, 6, 18), epoch_offset=0,
        return_carry=True,
    )
    for k in sorted(rng.choice(np.arange(1, 16), size=4, replace=False)):
        k = int(k)

        def run_split():
            pre = fused_varying_scan(
                W[:k], S[:k], save_bonds=False, save_incentives=False,
                carry=_zero_carry(BondsMode.EMA, 6, 18), epoch_offset=0,
                return_carry=True,
            )
            carry = {
                "bonds": pre["final_bonds"],
                "consensus": pre["final_consensus"],
            }
            suf = fused_varying_scan(
                W[k:], S[k:], save_bonds=False, save_incentives=False,
                carry=carry, epoch_offset=k, return_carry=True,
            )
            return np.concatenate(
                [pre["dividends_normalized"], suf["dividends_normalized"]]
            )

        a, b = run_split(), run_split()
        assert np.array_equal(a, b), f"resume at {k} nondeterministic"
        np.testing.assert_allclose(
            a,
            np.asarray(mono["dividends_normalized"]),
            atol=1e-6,
            rtol=1e-5,
            err_msg=f"resume at {k}",
        )


# ---------------------------------------------------------------------------
# admission model + planner


def test_varying_tile_chooser_divisor_and_vmem():
    mode = BondsMode.EMA
    # Small shape: the deepest tile that divides E wins.
    assert varying_scan_epoch_tile((12, 3, 2), mode) == 12
    assert varying_scan_epoch_tile((40, 3, 2), mode) == 10
    assert (
        varying_scan_epoch_tile((1024, 3, 2), mode)
        == VARYING_EPOCH_TILE_MAX
    )
    # Prime epoch counts beyond the cap cannot tile.
    assert varying_scan_epoch_tile((17, 3, 2), mode) == 1
    # The bench flagship: VMEM shrinks the tile below the cap but the
    # divisor structure (2^10) keeps a deep one.
    t = varying_scan_epoch_tile((1024, 256, 4096), mode)
    assert 2 <= t < VARYING_EPOCH_TILE_MAX
    # A shape too large for even a single-epoch tile reports 0.
    assert varying_scan_epoch_tile((4, 2048, 16384), mode) == 0
    # The admission model is monotone in the tile.
    mats = [
        _varying_scan_mats(et, mode, save_bonds=False) for et in (1, 2, 4)
    ]
    assert mats == sorted(mats)
    assert _varying_scan_mats(2, mode, save_bonds=True) > _varying_scan_mats(
        2, mode, save_bonds=False
    )


def test_varying_eligibility_gates():
    spec = variant_for_version(VERSION)
    shape = (12, 6, 18)
    if not ON_TPU:
        # Interpret mode would be slower than XLA, not faster: the
        # auto predicate refuses off-TPU exactly like the case scan's.
        assert not fused_varying_scan_eligible(
            shape, spec.bonds_mode, CFG, jnp.float32
        )
    assert not fused_varying_scan_eligible(
        shape, spec.bonds_mode, CFG, jnp.float64
    )


def test_planner_ladder_and_rungs():
    assert ENGINE_LADDER == (
        "fused_varying_mxu",
        "fused_varying",
        "fused_scan_mxu",
        "fused_scan",
        "xla",
    )
    assert FUSED_CASE_RUNGS == ENGINE_LADDER[:-1]
    assert rung_flags("fused_varying_mxu") == {
        "mxu": True,
        "varying": True,
    }
    assert rung_flags("fused_scan") == {"mxu": False, "varying": False}
    assert ladder_from("fused_varying") == (
        "fused_varying",
        "fused_scan_mxu",
        "fused_scan",
        "xla",
    )


def test_planner_explicit_varying_preconditions():
    from yuma_simulation_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="bisection"):
        plan_dispatch(
            "t", (12, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="fused_varying", consensus_impl="sorted",
        )
    with pytest.raises(ValueError, match="single-core"):
        plan_dispatch(
            "t", (12, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="fused_varying_mxu", mesh=make_mesh(),
        )
    with pytest.raises(ValueError, match="quarantine"):
        plan_dispatch(
            "t", (12, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="fused_varying", quarantine=True,
        )
    with pytest.raises(ValueError, match="miner"):
        plan_dispatch(
            "t", (2, 12, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="fused_varying", has_miner_mask=True,
        )


def test_planner_explicit_varying_rejects_inadmissible_shape():
    """An explicit varying-rung request for a shape no epoch tile can
    fit must fail at PLAN time (the serving tier admits through
    plan_dispatch — a typed 400, not a mid-dispatch kernel error)."""
    with pytest.raises(ValueError, match="any tile"):
        plan_dispatch(
            "t", (8, 2, 2048, 16384), VERSION, CFG, jnp.float32,
            epoch_impl="fused_varying", check_memory=False,
        )


def test_supervisor_canary_rung_stays_in_family():
    """A varying-rung primary must canary against its bitwise partner
    (the VPU twin / itself), never the case-scan family — cross-kernel
    dividends agree only to reduction-order rounding, which the
    fingerprint comparison would flag as drift."""
    from yuma_simulation_tpu.resilience.supervisor import SweepSupervisor

    sup = SweepSupervisor.__new__(SweepSupervisor)
    sup.canary_engine = None
    assert sup._canary_rung("fused_varying_mxu") == "fused_varying"
    assert sup._canary_rung("fused_varying") == "fused_varying"
    # pre-existing pairings unchanged
    assert sup._canary_rung("fused_scan_mxu") == "fused_scan"
    assert sup._canary_rung("xla") == "xla"
    sup.canary_engine = "xla"
    assert sup._canary_rung("fused_varying_mxu") == "xla"


def test_planner_varying_plan_demotes_down_the_ladder():
    plan = plan_dispatch(
        "t", (12, 6, 18), VERSION, CFG, jnp.float32,
        epoch_impl="fused_varying_mxu",
    )
    assert plan.engine == "fused_varying_mxu"
    assert plan.ladder == ENGINE_LADDER
    demoted = plan.demoted("fused_scan")
    assert demoted.engine == "fused_scan"
    assert demoted.ladder == ("fused_scan", "xla")
    with pytest.raises(ValueError, match="walks DOWN"):
        demoted.demoted("fused_varying_mxu")
    # fallback consensus is pre-resolved for the XLA rung.
    assert plan.demoted("xla").consensus_impl == plan.fallback_consensus


def test_planner_ladder_drops_mxu_rungs_beyond_limb_split():
    """Demotion must never land on a rung that raises a caller error:
    beyond V = 2^14 the exact MXU limb split does not cover the shape,
    so `_mxu` rungs are dropped from the demotion walk."""
    plan = plan_dispatch(
        "t", (4, 2**14 + 8, 16), VERSION, CFG, jnp.float32,
        epoch_impl="fused_varying", check_memory=False,
    )
    assert plan.engine == "fused_varying"
    assert plan.ladder == ("fused_varying", "fused_scan", "xla")


def test_planner_auto_stays_xla_off_tpu():
    if ON_TPU:
        pytest.skip("auto resolves to a fused rung on TPU")
    plan = plan_dispatch("t", (12, 6, 18), VERSION, CFG, jnp.float32)
    assert plan.engine == "xla"


# ---------------------------------------------------------------------------
# engine + streaming + numerics integration


def _scenario(E=12, V=6, M=18, seed=0):
    rng = np.random.default_rng(seed)
    return Scenario(
        name="varying",
        validators=[f"v{i}" for i in range(V)],
        base_validator="v0",
        weights=rng.random((E, V, M)).astype(np.float32),
        stakes=(rng.random((E, V)) + 0.01).astype(np.float32),
        num_epochs=E,
    )


def test_simulate_varying_rung_end_to_end():
    sc = _scenario()
    rx = simulate(sc, VERSION, epoch_impl="xla")
    rv = simulate(sc, VERSION, epoch_impl="fused_varying")
    rvm = simulate(sc, VERSION, epoch_impl="fused_varying_mxu")
    np.testing.assert_allclose(
        rv.dividends, rx.dividends, atol=2e-6, rtol=1e-5
    )
    # MXU == VPU at the engine level too (the canary pairing).
    assert np.array_equal(rvm.dividends, rv.dividends)


def test_simulate_varying_suffix_resume_state_contract():
    sc = _scenario(E=12)
    full = simulate(
        sc, VERSION, epoch_impl="fused_varying", return_state=True
    )
    pre_sc = _scenario(E=12)
    pre_sc.weights, pre_sc.stakes, pre_sc.num_epochs = (
        sc.weights[:6],
        sc.stakes[:6],
        6,
    )
    pre = simulate(
        pre_sc, VERSION, epoch_impl="fused_varying", return_state=True
    )
    suf_sc = _scenario(E=12)
    suf_sc.weights, suf_sc.stakes, suf_sc.num_epochs = (
        sc.weights[6:],
        sc.stakes[6:],
        6,
    )
    suf = simulate(
        suf_sc, VERSION, epoch_impl="fused_varying",
        initial_state=pre.final_state, epoch_offset=6,
    )
    np.testing.assert_allclose(
        np.concatenate([pre.dividends, suf.dividends]),
        full.dividends,
        atol=1e-6,
        rtol=1e-5,
    )
    assert set(full.final_state) == {"bonds", "consensus"}


def test_simulate_streamed_varying_rung():
    sc = _scenario(E=16)
    mono = simulate(sc, VERSION, epoch_impl="fused_varying")
    chunks = [
        (sc.weights[lo : lo + 4], sc.stakes[lo : lo + 4])
        for lo in range(0, 16, 4)
    ]
    streamed = simulate_streamed(
        chunks, VERSION, save_bonds=False, save_incentives=False,
        epoch_impl="fused_varying",
    )
    rep = simulate_streamed(
        list(chunks), VERSION, save_bonds=False, save_incentives=False,
        epoch_impl="fused_varying",
    )
    # Streamed runs are deterministic (bitwise repeatable) and agree
    # with the monolithic dispatch to reduction-order rounding.
    assert np.array_equal(streamed.dividends, rep.dividends)
    np.testing.assert_allclose(
        streamed.dividends, mono.dividends, atol=1e-6, rtol=1e-5
    )


def test_varying_numerics_capture_streams():
    """The in-scan NumericsSketch capture rides the varying rung with
    the SAME sketch spelling; the consensus stream (phase-1 surface) is
    bitwise the case scan's, so cross-tile canaries on that stream can
    never false-alarm."""
    W, S = _workload(seed=9)
    ri = jnp.asarray(-1, jnp.int32)
    spec = variant_for_version(VERSION)
    ys_v = _simulate_case_fused(
        W, S, ri, ri, CFG, spec, save_consensus=True, varying=True,
        capture_numerics=True,
    )
    ys_c = _simulate_case_fused(
        W, S, ri, ri, CFG, spec, save_consensus=True, varying=False,
        capture_numerics=True,
    )
    assert set(ys_v["numerics"]) == {"dividends", "consensus"}
    cons_v = ys_v["numerics"]["consensus"]
    cons_c = ys_c["numerics"]["consensus"]
    assert np.array_equal(
        np.asarray(cons_v.fingerprint), np.asarray(cons_c.fingerprint)
    )


# ---------------------------------------------------------------------------
# Monte-Carlo integration


def test_mc_batched_varying_rung_matches_oracle():
    from yuma_simulation_tpu.parallel.sharded import (
        montecarlo_per_epoch_batched,
    )

    key = jax.random.PRNGKey(5)
    args = (key, 3, 8, 6, 18, VERSION)
    oracle = montecarlo_per_epoch_batched(
        *args, consensus_impl="bisect", epoch_impl="xla"
    )
    for impl in ("fused_varying", "fused_varying_mxu"):
        got = montecarlo_per_epoch_batched(
            *args, consensus_impl="bisect", epoch_impl=impl
        )
        np.testing.assert_allclose(
            got, oracle, atol=2e-6, rtol=1e-5, err_msg=impl
        )
    # chunk-length invariance on the varying rung: reduction-order
    # rounding across slab programs (epoch-ordered accumulation).
    a = montecarlo_per_epoch_batched(
        *args, consensus_impl="bisect", epoch_impl="fused_varying",
        chunk_epochs=4,
    )
    b = montecarlo_per_epoch_batched(
        *args, consensus_impl="bisect", epoch_impl="fused_varying",
        chunk_epochs=8,
    )
    np.testing.assert_allclose(a, b, atol=2e-6, rtol=1e-5)


def test_mc_total_dividends_single_device_delegates():
    """montecarlo_total_dividends(auto, per_epoch) on a one-device mesh
    routes through the planned batched driver — bitwise the shard_map
    tier on the XLA rung (shared step function, shared key scheme)."""
    from yuma_simulation_tpu.parallel import make_mesh
    from yuma_simulation_tpu.parallel.sharded import (
        montecarlo_per_epoch_batched,
        montecarlo_total_dividends,
    )

    mesh = make_mesh()
    if int(mesh.devices.size) != 1:
        pytest.skip("single-device delegation path")
    key = jax.random.PRNGKey(11)
    auto = montecarlo_total_dividends(
        key, 3, 6, 6, 18, VERSION, mesh=mesh,
        weights_mode="per_epoch", consensus_impl="bisect",
    )
    shard_tier = montecarlo_total_dividends(
        key, 3, 6, 6, 18, VERSION, mesh=mesh,
        weights_mode="per_epoch", consensus_impl="bisect",
        epoch_impl="xla",
    )
    batched = montecarlo_per_epoch_batched(
        key, 3, 6, 6, 18, VERSION, consensus_impl="bisect"
    )
    assert np.array_equal(auto, batched)
    if not ON_TPU:
        # Off-TPU the delegated path runs the batched XLA oracle,
        # which is pinned bitwise against the shard body.
        assert np.array_equal(auto, shard_tier)


# ---------------------------------------------------------------------------
# on-chip variants (gated like every other fused-kernel battery)


@pytest.mark.skipif(not ON_TPU, reason="real-TPU Mosaic compile only")
def test_varying_scan_compiles_on_chip():
    W, S = _workload(seed=10, E=16, V=16, M=256)
    out = fused_varying_scan(W, S, epoch_tile=4, save_bonds=False)
    assert np.isfinite(np.asarray(out["dividends_normalized"])).all()
    mx = fused_varying_scan(W, S, epoch_tile=4, save_bonds=False, mxu=True)
    assert np.array_equal(
        np.asarray(out["dividends_normalized"]),
        np.asarray(mx["dividends_normalized"]),
    )


@pytest.mark.skipif(not ON_TPU, reason="real-TPU planner auto only")
def test_planner_auto_prefers_varying_rung_on_chip():
    plan = plan_dispatch("t", (1024, 256, 4096), VERSION, CFG, jnp.float32)
    assert plan.engine == "fused_varying_mxu"
    assert any("epoch-tiled" in r for r in plan.reasons)
