"""Two-process `jax.distributed` smoke: the multi-host join path.

Every other multi-chip test runs single-process on 8 virtual devices —
the one thing that differs on a real pod (the coordinator join in
`parallel/mesh.py::initialize_distributed`, cross-process collectives)
had no coverage. This spawns TWO separate Python processes, each with 4
virtual CPU devices, joined through a local coordinator:

- `initialize_distributed` must report 2 processes / 8 global devices;
- a `shard_map` psum over the global `make_mesh` data axis must cross
  the process boundary (each process holds half the shards; the Gloo
  CPU collective backend carries the reduction);
- a real framework sweep (`_sharded_batch_scan` over a scenario batch
  sharded across both processes) must match the single-process engine,
  with the result gathered cross-process by resharding to replicated.

Runs as a subprocess battery because `jax.distributed.initialize` must
happen before the backend is touched — impossible inside the already-
initialized test process (tests/conftest.py has claimed the 8-device
CPU platform).
"""

import os
import socket
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORKER = r"""
import os, sys
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from yuma_simulation_tpu.parallel.mesh import (
    DATA_AXIS,
    initialize_distributed,
    make_mesh,
)

initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
assert jax.distributed.is_initialized()
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4
assert jax.device_count() == 8
mesh = make_mesh()  # (data=8, model=1) over the global devices

# Cross-process psum: device d contributes d, total = sum(range(8)) = 28.
f = jax.jit(
    shard_map(
        lambda x: jax.lax.psum(jnp.sum(x), DATA_AXIS),
        mesh=mesh,
        in_specs=P(DATA_AXIS),
        out_specs=P(),
    )
)
x = jax.device_put(
    np.arange(8, dtype=np.float32), NamedSharding(mesh, P(DATA_AXIS))
)
assert float(np.asarray(f(x))) == 28.0

# Real sweep sharded across both processes, gathered by resharding to
# replicated (a cross-process all-gather), compared to the local engine.
from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.parallel.sharded import _sharded_batch_scan
from yuma_simulation_tpu.scenarios import cases
from yuma_simulation_tpu.simulation.engine import _simulate_scan
from yuma_simulation_tpu.simulation.sweep import stack_scenarios

cfg = YumaConfig()
spec = variant_for_version("Yuma 1 (paper)")
W, S, ri, re = stack_scenarios([cases[0]] * 8)
shard = NamedSharding(mesh, P(DATA_AXIS))
W, S = (jax.device_put(np.asarray(a), shard) for a in (W, S))
ri, re = (jax.device_put(np.asarray(a), shard) for a in (ri, re))
ys = _sharded_batch_scan(W, S, ri, re, cfg, spec, mesh)
gather = jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, P()))
div = np.asarray(gather(ys["dividends"]))  # [8, E, V], now replicated

local = np.asarray(
    _simulate_scan(
        jnp.asarray(np.asarray(W.addressable_shards[0].data)[0]),
        jnp.asarray(np.asarray(S.addressable_shards[0].data)[0]),
        jnp.asarray(-1, jnp.int32),
        jnp.asarray(-1, jnp.int32),
        cfg,
        spec,
    )["dividends"]
)
for b in range(8):
    np.testing.assert_allclose(div[b], local, rtol=2e-6, atol=2e-7)
print(f"WORKER{pid}_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(port: int, tmp: str):
    """Spawn both workers with file-backed stdout/stderr (a crashing
    worker's full traceback can exceed the 64 KB pipe buffer; an
    undrained pipe would deadlock it inside the distributed barrier)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [REPO, env.get("PYTHONPATH", "")] if p
    )
    # The workers set their own platform/device-count env before
    # importing jax; scrub the conftest's in-process settings.
    env.pop("JAX_ENABLE_X64", None)
    procs, files = [], []
    for pid in range(2):
        out = open(os.path.join(tmp, f"w{pid}.out"), "w+")
        err = open(os.path.join(tmp, f"w{pid}.err"), "w+")
        files.append((out, err))
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER, str(pid), "2", str(port)],
                cwd=REPO,
                env=env,
                stdout=out,
                stderr=err,
                text=True,
            )
        )
    results = []
    for pid, p in enumerate(procs):
        try:
            rc = p.wait(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        out, err = files[pid]
        out.seek(0)
        err.seek(0)
        results.append((pid, rc, out.read(), err.read()))
        out.close()
        err.close()
    return results


@pytest.mark.slow
def test_two_process_distributed_smoke():
    results = None
    for attempt in range(2):
        results = _run_workers(_free_port(), tempfile.mkdtemp())
        # Bind-close-reuse port selection is racy (another process can
        # claim the port before worker 0's coordinator binds it); a
        # failed join surfaces as the is_initialized assert in both
        # workers — retry once with a fresh port before failing.
        join_failed = all(
            rc != 0 and "is_initialized" in err for _, rc, _, err in results
        )
        if not join_failed:
            break
    for pid, rc, out, err in results:
        assert rc == 0, f"worker {pid} failed:\n{err[-4000:]}"
        assert f"WORKER{pid}_OK" in out
