"""Multi-process `jax.distributed` battery: join, scale, failure, recovery.

Every other multi-chip test runs single-process on 8 virtual devices —
what differs on a real pod (the coordinator join in
`parallel/mesh.py::initialize_distributed`, cross-process collectives,
a peer dying, resuming a half-done sweep) is covered here (r4 verdict
item 7):

- 2-process and 4-process smokes: `initialize_distributed` must report
  the right process/device counts, a `shard_map` psum must cross the
  process boundaries, and a real framework sweep (`_sharded_batch_scan`
  over a scenario batch sharded across all processes) must match the
  single-process engine.
- failure detection: a worker that dies before the barrier must make
  the surviving peer's EXPLICIT-coordinator join raise within its
  timeout (never silently degrade to a single-process run), and a full
  restart of the job must then succeed — the documented recovery model
  (restart + `CheckpointedSweep` resume, utils/checkpoint.py).
- checkpointed recovery: a Monte-Carlo sweep killed mid-run resumes
  from its chunk snapshots bitwise-identically
  (test_checkpointed_montecarlo_kill_and_resume, in-process on the
  8-device mesh).

Runs as a subprocess battery because `jax.distributed.initialize` must
happen before the backend is touched — impossible inside the already-
initialized test process (tests/conftest.py has claimed the 8-device
CPU platform).
"""

import os
import socket
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORKER = r"""
import os, sys
pid, nproc, port, devcount, mode = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], int(sys.argv[4]),
    sys.argv[5],
)
if mode == "crash":
    # Dies before ever touching jax — the peer's join must detect it.
    os._exit(9)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devcount}"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from yuma_simulation_tpu.parallel.mesh import (
    DATA_AXIS,
    initialize_distributed,
    make_mesh,
)

if mode == "detect":
    # The peer never joins: an explicit-coordinator join must RAISE
    # within the timeout (not degrade to a 1-process run that would
    # silently simulate 1/N of the workload as if complete).
    try:
        initialize_distributed(
            f"127.0.0.1:{port}", nproc, pid, initialization_timeout=20
        )
    except RuntimeError as e:
        assert "refusing to degrade" in str(e), e
        print("FAILURE_DETECTED", flush=True)
        sys.exit(0)
    print("JOIN_UNEXPECTEDLY_SUCCEEDED", flush=True)
    sys.exit(3)

initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
assert jax.distributed.is_initialized()
assert jax.process_count() == nproc, jax.process_count()
assert jax.local_device_count() == devcount
assert jax.device_count() == nproc * devcount
mesh = make_mesh()  # (data=global devices, model=1)
nglobal = nproc * devcount

# Cross-process psum: device d contributes d, total = sum(range(n)).
f = jax.jit(
    shard_map(
        lambda x: jax.lax.psum(jnp.sum(x), DATA_AXIS),
        mesh=mesh,
        in_specs=P(DATA_AXIS),
        out_specs=P(),
    )
)
x = jax.device_put(
    np.arange(nglobal, dtype=np.float32), NamedSharding(mesh, P(DATA_AXIS))
)
assert float(np.asarray(f(x))) == float(sum(range(nglobal)))

# Real sweep sharded across all processes, gathered by resharding to
# replicated (a cross-process all-gather), compared to the local engine.
from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.parallel.sharded import _sharded_batch_scan
from yuma_simulation_tpu.scenarios import cases
from yuma_simulation_tpu.simulation.engine import _simulate_scan
from yuma_simulation_tpu.simulation.sweep import stack_scenarios

cfg = YumaConfig()
spec = variant_for_version("Yuma 1 (paper)")
W, S, ri, re = stack_scenarios([cases[0]] * nglobal)
shard = NamedSharding(mesh, P(DATA_AXIS))
W, S = (jax.device_put(np.asarray(a), shard) for a in (W, S))
ri, re = (jax.device_put(np.asarray(a), shard) for a in (ri, re))
ys = _sharded_batch_scan(W, S, ri, re, cfg, spec, mesh)
gather = jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, P()))
div = np.asarray(gather(ys["dividends"]))  # [n, E, V], now replicated

local = np.asarray(
    _simulate_scan(
        jnp.asarray(np.asarray(W.addressable_shards[0].data)[0]),
        jnp.asarray(np.asarray(S.addressable_shards[0].data)[0]),
        jnp.asarray(-1, jnp.int32),
        jnp.asarray(-1, jnp.int32),
        cfg,
        spec,
    )["dividends"]
)
for b in range(nglobal):
    np.testing.assert_allclose(div[b], local, rtol=2e-6, atol=2e-7)
print(f"WORKER{pid}_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(
    port: int,
    tmp: str,
    *,
    nproc: int = 2,
    devcount: int = 4,
    modes: dict[int, str] | None = None,
    timeout: int = 600,
):
    """Spawn the workers with file-backed stdout/stderr (a crashing
    worker's full traceback can exceed the 64 KB pipe buffer; an
    undrained pipe would deadlock it inside the distributed barrier)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [REPO, env.get("PYTHONPATH", "")] if p
    )
    # The workers set their own platform/device-count env before
    # importing jax; scrub the conftest's in-process settings.
    env.pop("JAX_ENABLE_X64", None)
    modes = modes or {}
    procs, files = [], []
    for pid in range(nproc):
        out = open(os.path.join(tmp, f"w{pid}.out"), "w+")
        err = open(os.path.join(tmp, f"w{pid}.err"), "w+")
        files.append((out, err))
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-c", WORKER,
                    str(pid), str(nproc), str(port), str(devcount),
                    modes.get(pid, "smoke"),
                ],
                cwd=REPO,
                env=env,
                stdout=out,
                stderr=err,
                text=True,
            )
        )
    results = []
    for pid, p in enumerate(procs):
        try:
            rc = p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        out, err = files[pid]
        out.seek(0)
        err.seek(0)
        results.append((pid, rc, out.read(), err.read()))
        out.close()
        err.close()
    return results


def _smoke(nproc: int, devcount: int):
    results = None
    for attempt in range(2):
        results = _run_workers(
            _free_port(), tempfile.mkdtemp(), nproc=nproc, devcount=devcount
        )
        # Bind-close-reuse port selection is racy (another process can
        # claim the port before worker 0's coordinator binds it); a
        # failed join surfaces as initialize_distributed's explicit-
        # coordinator RuntimeError (or, in older layouts, the
        # is_initialized assert) — retry once with a fresh port.
        join_failed = all(
            rc != 0
            and ("refusing to degrade" in err or "is_initialized" in err)
            for _, rc, _, err in results
        )
        if not join_failed:
            break
    for pid, rc, out, err in results:
        assert rc == 0, f"worker {pid} failed:\n{err[-4000:]}"
        assert f"WORKER{pid}_OK" in out


@pytest.mark.slow
def test_two_process_distributed_smoke():
    _smoke(nproc=2, devcount=4)


@pytest.mark.slow
def test_four_process_distributed_smoke():
    # 4 processes x 2 local devices = the same 8-device data mesh, now
    # with three process boundaries inside every collective.
    _smoke(nproc=4, devcount=2)


@pytest.mark.slow
def test_process_failure_detected_then_restart_recovers():
    """A peer that dies before the barrier must be DETECTED by the
    survivor (explicit-coordinator join raises within its timeout; no
    silent single-process degrade), and the documented recovery — start
    the job again — must succeed."""
    results = _run_workers(
        _free_port(),
        tempfile.mkdtemp(),
        nproc=2,
        devcount=4,
        modes={0: "detect", 1: "crash"},
        timeout=180,
    )
    by_pid = {pid: (rc, out, err) for pid, rc, out, err in results}
    rc, out, err = by_pid[1]
    assert rc == 9  # the crashed peer
    rc, out, err = by_pid[0]
    # Two loud, bounded detection paths exist in practice: either the
    # join raises and initialize_distributed's refusing-to-degrade
    # RuntimeError surfaces (rc 0 after our handler prints the marker),
    # or JAX's coordination-service client LOG(FATAL)s the process with
    # the documented "detected fatal errors ... DEADLINE_EXCEEDED"
    # message before Python sees an exception. Both satisfy the
    # failure-detection contract; a SILENT outcome — rc 0 without the
    # marker (the old degrade-to-single-process behavior) — is the
    # failure mode this test exists to forbid.
    if rc == 0:
        assert "FAILURE_DETECTED" in out, (
            f"survivor exited 0 without detecting the failure:\n{out}"
        )
    else:
        assert (
            "detected fatal errors" in err or "DEADLINE_EXCEEDED" in err
        ), f"survivor failed for an unrelated reason:\n{err[-4000:]}"
    # Recovery: a full restart of the same job shape comes up green.
    _smoke(nproc=2, devcount=4)


@pytest.mark.slow
def test_checkpointed_montecarlo_kill_and_resume(tmp_path):
    """The stated pod recovery model end-to-end (utils/checkpoint.py):
    a chunked Monte-Carlo sweep dies mid-run (chunk fn never returns —
    exception, process kill, preemption are all the same to the
    snapshot protocol, which also survives a stale partial temp file),
    then a fresh driver pointed at the same directory resumes and the
    concatenated result is BITWISE the uninterrupted run."""
    import jax
    import numpy as np

    from yuma_simulation_tpu.parallel import make_mesh, montecarlo_total_dividends
    from yuma_simulation_tpu.utils.checkpoint import CheckpointedSweep

    mesh = make_mesh()  # data=8 over the virtual CPU devices
    cfg_fp = {"v": "Yuma 1 (paper)", "shape": [4, 8], "epochs": 6, "mc": 16}

    def chunk_fn(i: int) -> np.ndarray:
        return montecarlo_total_dividends(
            jax.random.key(100 + i), 16, 6, 4, 8, "Yuma 1 (paper)",
            mesh=mesh, weights_mode="per_epoch",
        )

    # Uninterrupted oracle.
    clean = CheckpointedSweep(tmp_path / "clean", 4, config=cfg_fp)
    expected = clean.run(chunk_fn)

    # Interrupted run: the driver dies inside chunk 2.
    crash_dir = tmp_path / "crashed"

    def dying_fn(i: int) -> np.ndarray:
        if i == 2:
            raise KeyboardInterrupt("simulated preemption")
        return chunk_fn(i)

    sweep = CheckpointedSweep(crash_dir, 4, config=cfg_fp)
    with pytest.raises(KeyboardInterrupt):
        sweep.run(dying_fn)
    assert sweep.completed_chunks() == [0, 1]
    # A hard kill can also abandon a half-written temp file; the resume
    # protocol must ignore it (only published chunk_*.npz names count).
    (crash_dir / "partial_00002.tmp").write_bytes(b"\x00garbage")

    resumed = CheckpointedSweep(crash_dir, 4, config=cfg_fp)
    assert resumed.completed_chunks() == [0, 1]
    got = resumed.run(chunk_fn)
    np.testing.assert_array_equal(got, expected)
    # Config drift in the same directory must fail loudly, not reuse
    # stale chunks.
    with pytest.raises(ValueError, match="different"):
        CheckpointedSweep(crash_dir, 4, config={"v": "other"})
