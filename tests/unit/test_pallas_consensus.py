"""Pallas consensus kernel: bit-parity with the XLA bisection.

Runs in interpreter mode on the CPU test mesh; on TPU the same kernel is
compiled (the values are dyadic rationals, exact in f32, so parity is
bitwise on both paths).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.epoch import BondsMode, yuma_epoch
from yuma_simulation_tpu.ops.consensus import stake_weighted_median
from yuma_simulation_tpu.ops.pallas_consensus import stake_weighted_median_pallas


@pytest.mark.parametrize(
    "shape", [(3, 2), (5, 7), (16, 130), (64, 512)]
)
def test_pallas_matches_bisection(shape):
    V, M = shape
    rng = np.random.default_rng(V * M)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    W = W / W.sum(axis=1, keepdims=True)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    S = S / S.sum()
    ref = np.asarray(stake_weighted_median(W, S, 0.5))
    got = np.asarray(stake_weighted_median_pallas(W, S, 0.5, interpret=True))
    np.testing.assert_array_equal(ref, got)


def test_pallas_kappa_and_zero_columns():
    W = jnp.asarray(
        [[0.9, 0.0, 0.1], [0.2, 0.0, 0.8], [0.2, 0.0, 0.8]], jnp.float32
    )
    S = jnp.asarray([0.6, 0.2, 0.2], jnp.float32)
    for kappa in (0.3, 0.5, 0.7):
        ref = np.asarray(stake_weighted_median(W, S, kappa))
        got = np.asarray(
            stake_weighted_median_pallas(W, S, kappa, interpret=True)
        )
        np.testing.assert_array_equal(ref, got)
    # the all-zero column converges to the grid floor 2^-17 on both paths
    assert got[1] == np.float32(2.0**-17)


def test_epoch_with_pallas_impl_matches_default():
    rng = np.random.default_rng(9)
    W = jnp.asarray(rng.random((8, 16)), jnp.float32)
    S = jnp.asarray(rng.random(8) + 0.01, jnp.float32)
    base = yuma_epoch(W, S, None, YumaConfig(), bonds_mode=BondsMode.EMA)
    pall = yuma_epoch(
        W, S, None, YumaConfig(), bonds_mode=BondsMode.EMA,
        consensus_impl="pallas",
    )
    for key in ("server_consensus_weight", "server_incentive", "validator_reward"):
        np.testing.assert_array_equal(
            np.asarray(base[key]), np.asarray(pall[key]), err_msg=key
        )
