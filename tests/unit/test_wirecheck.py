"""wirecheck: per-gate CLI regression tests + the live-repo-clean gate.

Each of the four wire-contract gates gets a violating tmp-tree that
must fail ``--check`` with the producer/consumer chain named in the
finding, mirroring the violating/clean fixture pairs in
``test_jaxlint.py`` (JX301-JX303 corpus entries). JX304 is inherently
two-input — a tree plus a lock — so its pair lives here as CLI
round-trips: ``--update`` then ``--check`` exits 0, hand-deleting a
locked field exits 1. The final tests run the real CLI over the repo
with the committed ``SCHEMAS.lock.json`` and require exit 0.
"""

import json
import os

import pytest

from tools.wirecheck.cli import main

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: a self-consistent one-producer/one-consumer ledger tree
_CLEAN_TREE = {
    "host.py": """
class Host:
    def ok(self, unit):
        self.ledger.append("unit_ok", unit=unit, stalls=2)
""",
    "obsfix.py": """
def report(records):
    oks = [r for r in records if r.get("event") == "unit_ok"]
    return [(r.get("unit"), r.get("stalls")) for r in oks]
""",
}


def _write_tree(root, files):
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src, encoding="utf-8")
    return str(root)


def _check(root, lock, *extra):
    return main([root, "--lock", str(lock), "--check", *extra])


def test_update_then_check_round_trips(tmp_path, capsys):
    root = _write_tree(tmp_path / "pkg", _CLEAN_TREE)
    lock = tmp_path / "SCHEMAS.lock.json"
    assert main([root, "--lock", str(lock), "--update"]) == 0
    payload = json.loads(lock.read_text())
    assert payload["version"] == 1
    assert sorted(payload["schemas"]["ledger"]["unit_ok"]) == sorted(
        ["event", "t", "run_id", "span_id", "parent_id", "unit", "stalls"]
    )
    assert _check(root, lock) == 0


def test_missing_lock_is_a_usage_error(tmp_path, capsys):
    root = _write_tree(tmp_path / "pkg", _CLEAN_TREE)
    assert _check(root, tmp_path / "nope.lock.json") == 2
    assert "not found" in capsys.readouterr().err


def test_deleting_a_locked_field_fails_check(tmp_path, capsys):
    """JX304, field removal: the additive-only contract — a field
    frozen in the lock that the tree no longer produces is a
    regression, and the finding points at the sanctioned escape hatch
    (``--update``)."""
    root = _write_tree(tmp_path / "pkg", _CLEAN_TREE)
    lock = tmp_path / "SCHEMAS.lock.json"
    assert main([root, "--lock", str(lock), "--update"]) == 0
    payload = json.loads(lock.read_text())
    payload["schemas"]["ledger"]["unit_ok"].append("operator_note")
    lock.write_text(json.dumps(payload))
    assert _check(root, lock) == 1
    out = capsys.readouterr().out
    assert "operator_note" in out and "JX304" in out
    assert "--update" in out


def test_deleting_a_locked_record_fails_check(tmp_path, capsys):
    root = _write_tree(tmp_path / "pkg", _CLEAN_TREE)
    lock = tmp_path / "SCHEMAS.lock.json"
    assert main([root, "--lock", str(lock), "--update"]) == 0
    payload = json.loads(lock.read_text())
    payload["schemas"]["ledger"]["unit_gone"] = ["event", "unit"]
    lock.write_text(json.dumps(payload))
    assert _check(root, lock) == 1
    out = capsys.readouterr().out
    assert "unit_gone" in out and "no longer produced" in out


def test_orphan_read_fails_with_producer_chain(tmp_path, capsys):
    """JX301: a consumed field with no producer exits non-zero and the
    finding names the event's real producer sites."""
    tree = dict(_CLEAN_TREE)
    tree["obsfix.py"] = """
def report(records):
    oks = [r for r in records if r.get("event") == "unit_ok"]
    return [r.get("stall_count") for r in oks]
"""
    root = _write_tree(tmp_path / "pkg", tree)
    lock = tmp_path / "SCHEMAS.lock.json"
    assert main([root, "--lock", str(lock), "--update"]) == 0
    assert _check(root, lock) == 1
    out = capsys.readouterr().out
    assert "JX301" in out and "stall_count" in out
    assert "producers of 'unit_ok'" in out and "host.py" in out


def test_unmapped_typed_error_fails_with_reach_chain(tmp_path, capsys):
    """JX302: a ResilienceError subclass raised on a serve-reachable
    path with no HTTP mapping exits non-zero; the finding shows the
    reachability chain."""
    root = _write_tree(
        tmp_path / "pkg",
        {
            "serve/handler.py": """
class ResilienceError(Exception):
    pass


class QuotaBlown(ResilienceError):
    pass


def check(payload):
    if not payload:
        raise QuotaBlown("over budget")


def handle_request(payload):
    check(payload)
    return 200, {"status": "ok"}
""",
        },
    )
    lock = tmp_path / "SCHEMAS.lock.json"
    assert main([root, "--lock", str(lock), "--update"]) == 0
    assert _check(root, lock) == 1
    out = capsys.readouterr().out
    assert "JX302" in out and "QuotaBlown" in out
    assert "via" in out and "check" in out


def test_one_sided_annotation_fails_both_directions(tmp_path, capsys):
    """JX303: a scored-but-never-advertised annotation field AND an
    advertised-but-never-read one both exit non-zero, each naming the
    other side's sites."""
    root = _write_tree(
        tmp_path / "pkg",
        {
            "serve/minirouter.py": """
class Pool:
    def heartbeat(self, slot):
        self.leases.annotate(
            slot, {"worker_id": "w0", "inflight": 0, "magic": 1}
        )


def claim_score(ad):
    return (ad.get("inflight"), ad.get("worker_id"), ad.get("crystal"))
""",
        },
    )
    lock = tmp_path / "SCHEMAS.lock.json"
    assert main([root, "--lock", str(lock), "--update"]) == 0
    assert _check(root, lock) == 1
    out = capsys.readouterr().out
    assert out.count("JX303") == 2
    assert "crystal" in out and "advertised at:" in out  # orphan score
    assert "magic" in out and "dead wire weight" in out  # dead weight
    assert "minirouter.py" in out


def test_suppression_silences_and_strict_sweeps(tmp_path, capsys):
    """JX3xx rides jaxlint's suppression machinery: a per-line
    disable pragma silences the finding, and a stale one fails
    ``--strict``. (The pragma is assembled at runtime so the scanner
    doesn't read THIS file's fixture strings as suppressions.)"""
    pragma = "# jaxlint: " + "disable=JX303"
    root = _write_tree(
        tmp_path / "pkg",
        {
            "serve/minirouter.py": f"""
class Pool:
    def heartbeat(self, slot):
        self.leases.annotate(
            slot,
            {{"worker_id": "w0", "magic": 1}},  {pragma}
        )


def claim_score(ad):
    return (ad.get("worker_id"),)
""",
        },
    )
    lock = tmp_path / "SCHEMAS.lock.json"
    assert main([root, "--lock", str(lock), "--update"]) == 0
    assert _check(root, lock) == 0
    capsys.readouterr()
    # drop the dead-weight field: the suppression goes stale and only
    # --strict turns that into a failure
    (tmp_path / "pkg" / "serve" / "minirouter.py").write_text(
        f"""
class Pool:
    def heartbeat(self, slot):
        self.leases.annotate(
            slot,
            {{"worker_id": "w0"}},  {pragma}
        )


def claim_score(ad):
    return (ad.get("worker_id"),)
""",
        encoding="utf-8",
    )
    assert main([root, "--lock", str(lock), "--update"]) == 0
    assert _check(root, lock) == 0
    assert _check(root, lock, "--strict") == 1
    assert "unused suppression" in capsys.readouterr().out


def test_json_payload_and_artifact(tmp_path, capsys):
    root = _write_tree(tmp_path / "pkg", _CLEAN_TREE)
    lock = tmp_path / "SCHEMAS.lock.json"
    artifact = tmp_path / "wirecheck.json"
    assert main([root, "--lock", str(lock), "--update"]) == 0
    capsys.readouterr()
    assert (
        main(
            [root, "--lock", str(lock), "--check", "--json",
             "--artifact", str(artifact)]
        )
        == 0
    )
    printed = json.loads(capsys.readouterr().out)
    on_disk = json.loads(artifact.read_text())
    assert printed == on_disk
    assert printed["findings"] == [] and printed["lock_regressions"] == []
    assert "unit_ok" in printed["schemas"]["ledger"]


@pytest.mark.parametrize("verb", ["--check", "--update"])
def test_missing_path_is_usage_error(tmp_path, capsys, verb):
    assert main([str(tmp_path / "ghost"), verb]) == 2


def test_live_repo_is_clean_against_committed_lock(capsys):
    """The acceptance gate: ``python -m tools.wirecheck --check`` over
    all three roots with the committed SCHEMAS.lock.json exits 0 — no
    orphan reads, no unmapped typed errors, no one-sided annotations,
    no lock regressions, and (--strict) no rotting JX3xx
    suppressions."""
    roots = [
        os.path.join(REPO, "yuma_simulation_tpu"),
        os.path.join(REPO, "tools"),
        os.path.join(REPO, "tests"),
    ]
    lock = os.path.join(REPO, "SCHEMAS.lock.json")
    rc = main([*roots, "--lock", lock, "--check", "--strict"])
    out = capsys.readouterr().out
    assert rc == 0, f"wirecheck --check failed on the live tree:\n{out}"


def test_live_lock_matches_live_tree_exactly(capsys):
    """The committed lock is regenerable: the current tree's schemas
    must be a superset of the lock (additive evolution) AND the lock
    must not lag — a PR that grows a schema must also run --update, or
    the next --update produces diff noise on an unrelated change."""
    from tools.jaxlint.analyzer import iter_python_files
    from tools.jaxlint.program import Program, parse_unit
    from tools.wirecheck.extract import extract_index
    from tools.wirecheck.gates import schemas_of

    roots = [
        os.path.join(REPO, "yuma_simulation_tpu"),
        os.path.join(REPO, "tools"),
        os.path.join(REPO, "tests"),
    ]
    units = [
        parse_unit(f.read_text(encoding="utf-8"), str(f))
        for f in iter_python_files(roots)
    ]
    current = schemas_of(extract_index(Program(units)))
    with open(os.path.join(REPO, "SCHEMAS.lock.json")) as fh:
        locked = json.load(fh)["schemas"]
    assert current == locked, (
        "SCHEMAS.lock.json is stale — run `python -m tools.wirecheck "
        "--update` and commit the diff"
    )
