"""Incident intelligence (ISSUE 20): the time-series store, the robust
anomaly detectors, cross-signal incident correlation, and the
incidentreport gate.

The contract under test: detector math is provably quiet on clean
series (declared windows + min-samples) and fires ONCE per excursion
with reseed-after-recovery; the time-series merge is order-independent
and deduplicable across process bundles via the monotone ``seq``
stamp; correlation opens exactly one incident per (cause class,
subject) with the matching typed ledger event as its suspected cause
and ZERO incidents on clean ledgers; incident state is durable
(``incidents.jsonl``, torn-tail tolerant, restart-merged); and
``incidentreport --check`` fails when an incident is deleted out from
under its cause (tamper) or lacks a cause candidate."""

from __future__ import annotations

import json
import random

from yuma_simulation_tpu.telemetry.anomaly import (
    AnomalyEngine,
    CounterStallDetector,
    MadDetector,
    RateOfChangeDetector,
    SaturationDetector,
    default_replay_engine,
)
from yuma_simulation_tpu.telemetry.incident import (
    IncidentEngine,
    correlate,
    latest_incidents,
    load_incidents,
    open_incident_count,
)
from yuma_simulation_tpu.telemetry.timeseries import (
    TimeSeriesStore,
    store_from_metrics,
)

VERSION = "Yuma 2 (Adrian-Fish)"


def _snapshots(n, *, source, start=0.0, gauge=5.0, jitter=None):
    """n metrics.jsonl-shaped records with monotone seq, 1s apart."""
    rng = jitter or (lambda i: 0.0)
    return [
        {
            "t": start + i,
            "seq": i + 1,
            "source": source,
            "counters": {"windows_swept_total": float(i)},
            "gauges": {"replay_staleness_seconds": gauge + rng(i)},
        }
        for i in range(n)
    ]


# ------------------------------------------------------ time-series store


class TestTimeSeriesStore:
    def test_merge_is_order_independent_and_deduped(self):
        """The satellite property: randomized interleavings of the same
        multi-process record set (duplicates included) fold to the SAME
        series."""
        a = _snapshots(20, source="router")
        b = _snapshots(20, source="worker", start=0.5, gauge=7.0)
        reference = TimeSeriesStore()
        reference.ingest_many(a + b)
        for trial in range(6):
            rng = random.Random(trial)
            shuffled = a + b + rng.sample(a, 10)  # replayed duplicates
            rng.shuffle(shuffled)
            store = TimeSeriesStore()
            new = store.ingest_many(shuffled)
            assert new == 40  # every duplicate dropped
            for key in reference.keys():
                assert store.series(key) == reference.series(key), (
                    f"series {key} diverged under interleaving {trial}"
                )

    def test_ring_is_bounded(self):
        store = TimeSeriesStore(capacity=8)
        store.ingest_many(_snapshots(50, source="a"))
        series = store.series("gauge:replay_staleness_seconds")
        assert len(series) == 8
        assert series[-1][0] == 49.0  # newest retained

    def test_sketch_quantiles_extracted(self):
        from yuma_simulation_tpu.telemetry.slo import LatencySketch

        sk = LatencySketch()
        for v in (0.01, 0.02, 0.04, 0.08, 0.5):
            sk.observe(v)
        store = TimeSeriesStore()
        store.ingest_snapshot(
            {
                "t": 1.0,
                "seq": 1,
                "dispatch_sketches": {
                    "xla|E4xV3xM5|cpu": {"sketch": sk.to_json()}
                },
            },
            source="s",
        )
        p50 = store.latest("sketch:xla|E4xV3xM5|cpu:p50")
        p99 = store.latest("sketch:xla|E4xV3xM5|cpu:p99")
        assert p50 is not None and p99 is not None
        assert p99[1] >= p50[1] > 0

    def test_registry_snapshots_carry_monotone_seq(self, tmp_path):
        from yuma_simulation_tpu.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("windows_swept_total").inc()
        r1 = reg.append_snapshot(tmp_path / "m.jsonl")
        r2 = reg.publish_snapshot(tmp_path / "m2.jsonl")
        r3 = reg.append_snapshot(tmp_path / "m.jsonl")
        assert r1["seq"] < r2["seq"] < r3["seq"]
        on_disk = [
            json.loads(line)
            for line in (tmp_path / "m.jsonl").read_text().splitlines()
        ]
        assert [r["seq"] for r in on_disk] == [r1["seq"], r3["seq"]]
        # store_from_metrics round-trips the stamped records
        store = store_from_metrics(on_disk, source="p")
        assert len(store.series("counter:windows_swept_total")) == 2


# ------------------------------------------------------------- detectors


class TestDetectors:
    def test_mad_single_outlier_fires_once(self):
        det = MadDetector("g", window=16, min_samples=8, threshold=6.0,
                          mad_floor=0.5)
        fired = []
        for i in range(20):
            a = det.observe(float(i), 5.0 + (i % 3) * 0.1)
            assert a is None, "clean series must stay quiet"
        a = det.observe(20.0, 500.0)
        fired.append(a)
        assert a is not None and a.kind == "mad" and a.value == 500.0
        # still in the same excursion: latched, no re-fire
        assert det.observe(21.0, 400.0) is None

    def test_mad_level_shift_fires_once_and_reseeds_after_recovery(self):
        det = MadDetector("g", window=16, min_samples=8, threshold=6.0,
                          mad_floor=0.5)
        for i in range(12):
            det.observe(float(i), 10.0 + (i % 2) * 0.2)
        shift = [det.observe(12.0 + i, 60.0) for i in range(10)]
        assert sum(a is not None for a in shift) == 1, (
            "a sustained level shift is ONE anomaly, not one per sample"
        )
        # recovery: samples rejoin the baseline, latch releases...
        for i in range(12):
            assert det.observe(30.0 + i, 10.0 + (i % 2) * 0.2) is None
        # ...and the NEXT excursion is a fresh firing
        again = det.observe(50.0, 60.0)
        assert again is not None

    def test_mad_quiet_below_min_samples(self):
        det = MadDetector("g", window=16, min_samples=8)
        for i in range(7):
            assert det.observe(float(i), 1e9 * i) is None

    def test_rate_of_change_fires_on_slope(self):
        det = RateOfChangeDetector("g", max_per_second=10.0, min_samples=2)
        assert det.observe(0.0, 0.0) is None
        assert det.observe(1.0, 5.0) is None
        a = det.observe(2.0, 500.0)
        assert a is not None and a.kind == "rate_of_change"
        assert det.observe(3.0, 505.0) is None  # slope back under

    def test_counter_stall_needs_advancing_activity(self):
        store = TimeSeriesStore()
        quiet = CounterStallDetector(
            "counter:windows_swept_total",
            "counter:cycles_total",
            horizon_seconds=10.0,
        )
        # target frozen but activity frozen too: nothing was asked
        for i in range(30):
            store.ingest_snapshot(
                {"t": float(i), "seq": i + 1,
                 "counters": {"windows_swept_total": 4.0,
                              "cycles_total": 2.0}},
                source="a",
            )
        assert quiet.scan(store) == []
        # activity advances while the target stays frozen: a real stall
        store2 = TimeSeriesStore()
        det = CounterStallDetector(
            "counter:windows_swept_total",
            "counter:cycles_total",
            horizon_seconds=10.0,
        )
        for i in range(30):
            store2.ingest_snapshot(
                {"t": float(i), "seq": i + 1,
                 "counters": {"windows_swept_total": 4.0,
                              "cycles_total": float(i)}},
                source="a",
            )
        fired = det.scan(store2)
        assert len(fired) == 1 and fired[0].kind == "counter_stall"
        assert det.scan(store2) == []  # latched until the target moves

    def test_saturation_fires_after_consecutive_samples(self):
        det = SaturationDetector("gauge:queue_depth", capacity=100.0,
                                 min_samples=3)
        store = TimeSeriesStore()
        depths = [50, 96, 97, 40, 98, 99, 97, 96]
        for i, d in enumerate(depths):
            store.ingest_snapshot(
                {"t": float(i), "seq": i + 1,
                 "gauges": {"queue_depth": float(d)}},
                source="a",
            )
        fired = det.scan(store)
        # the 40 resets the run: only the second streak reaches 3
        assert len(fired) == 1 and fired[0].t == 6.0

    def test_detectors_survive_ring_eviction(self):
        """Scanning must cursor by sample IDENTITY, not an index into
        the ring: once the bounded ring fills (with scans interleaved so
        eviction happens between them), a 1000x level shift must still
        fire — an index cursor pins at len(series) and goes blind."""
        store = TimeSeriesStore(capacity=16)
        det = MadDetector("gauge:replay_staleness_seconds", window=8,
                          min_samples=4, threshold=8.0, mad_floor=1.0)
        seq = 0

        def feed(n, gauge):
            nonlocal seq
            for _ in range(n):
                seq += 1
                store.ingest_snapshot(
                    {"t": float(seq), "seq": seq,
                     "gauges": {"replay_staleness_seconds": gauge}},
                    source="ctl",
                )

        feed(8, 5.0)
        assert det.scan(store) == []
        for _ in range(6):  # 96 more clean samples through 16 slots
            feed(16, 5.0)
            assert det.scan(store) == []
        feed(3, 5000.0)
        fired = det.scan(store)
        assert len(fired) == 1 and fired[0].kind == "mad", (
            "detector went blind after ring eviction"
        )

    def test_default_replay_engine_quiet_on_clean_feed(self):
        """The clean false-positive bound: steady staleness jitter on
        the default controller wiring produces ZERO anomalies."""
        engine = default_replay_engine()
        store = TimeSeriesStore()
        rng = random.Random(7)
        for i in range(200):
            store.ingest_snapshot(
                {"t": float(i), "seq": i + 1,
                 "gauges": {
                     "replay_staleness_seconds": 3.0 + rng.random()
                 }},
                source="ctl",
            )
        assert engine.scan(store) == []


# ------------------------------------------------------------ correlation


def _ledger_records():
    return [
        {"event": "subnet_quarantined", "t": 10.0, "netuid": 7,
         "block": 1100, "reason": "digest mismatch", "run_id": "r1",
         "span_id": "s1"},
        {"event": "subnet_stalled", "t": 20.0, "netuid": 3,
         "stalled_seconds": 40.0, "run_id": "r1", "span_id": "s2"},
        {"event": "anomaly_detected", "t": 24.0, "kind": "mad",
         "series": "gauge:replay_staleness_seconds", "run_id": "r1",
         "span_id": "s2"},
        {"event": "slo_alert", "t": 26.0, "slo": "replay_fresh",
         "run_id": "r1"},
        {"event": "controller_restarted", "t": 40.0, "run": "r0",
         "run_id": "r2", "span_id": "s9"},
        {"event": "watermark_advanced", "t": 50.0, "netuid": 5,
         "block": 1200, "run_id": "r2"},
    ]


class TestCorrelation:
    def test_each_cause_class_yields_exactly_one_incident(self):
        incidents = correlate(_ledger_records())
        by_class = {i.cause_class: i for i in incidents}
        assert set(by_class) == {
            "snapshot-corruption", "subnet-stall", "process-loss"
        }
        assert by_class["snapshot-corruption"].cause["event"] == (
            "subnet_quarantined"
        )
        assert by_class["subnet-stall"].subject == "netuid=3"
        # recurrence of the same (class, subject) folds, never forks
        doubled = _ledger_records() + [
            {"event": "subnet_stalled", "t": 70.0, "netuid": 3,
             "stalled_seconds": 90.0, "run_id": "r2"}
        ]
        assert len(correlate(doubled)) == 3

    def test_symptoms_attach_and_never_open(self):
        incidents = correlate(_ledger_records())
        stall = next(i for i in incidents if i.cause_class == "subnet-stall")
        kinds = [s["kind"] for s in stall.symptoms]
        assert "anomaly" in kinds  # span-adjacent detector firing
        # symptom-only ledgers open NOTHING (the control-arm bound)
        assert correlate([
            {"event": "anomaly_detected", "t": 1.0, "series": "g"},
            {"event": "slo_alert", "t": 2.0, "slo": "serve_ok"},
            {"event": "unit_ok", "t": 3.0, "unit": 4},
        ]) == []

    def test_resolution_states(self):
        incidents = correlate(_ledger_records())
        by_class = {i.cause_class: i for i in incidents}
        # quarantine IS the mitigation
        assert by_class["snapshot-corruption"].state == "resolved"
        assert by_class["snapshot-corruption"].resolution == "quarantined"
        # progress after restart resolves the process loss
        assert by_class["process-loss"].state == "resolved"
        assert by_class["process-loss"].resolution == "watermark_advanced"
        # the stalled subnet never resumed
        assert by_class["subnet-stall"].state == "open"
        # a subject-matched recovery resolves the stall
        recovered = correlate(
            _ledger_records()
            + [{"event": "subnet_ingested", "t": 90.0, "netuid": 3,
                "new_blocks": 2, "head_block": 1300}]
        )
        stall = next(
            i for i in recovered if i.cause_class == "subnet-stall"
        )
        assert stall.state == "resolved"

    def test_latest_incidents_keeps_last_record_per_id(self):
        opened = {"incident": "subnet-stall:netuid=3", "state": "open",
                  "opened_t": 1.0}
        resolved = dict(opened, state="resolved", resolved_t=9.0)
        assert latest_incidents([opened, resolved]) == [resolved]
        assert latest_incidents([resolved, opened]) == [opened]


# ------------------------------------------------- durable incident state


class TestDurableState:
    def test_record_incident_appends_and_survives_torn_tail(self, tmp_path):
        from yuma_simulation_tpu.telemetry.flight import (
            FlightRecorder,
            INCIDENTS_NAME,
            load_bundle,
        )

        rec = FlightRecorder(tmp_path)
        rec.record_incident(
            {"incident": "subnet-stall:netuid=3", "state": "open",
             "opened_t": 1.0, "cause_class": "subnet-stall"}
        )
        rec.record_incident(
            {"incident": "subnet-stall:netuid=3", "state": "resolved",
             "opened_t": 1.0, "resolved_t": 5.0,
             "cause_class": "subnet-stall"}
        )
        with open(tmp_path / INCIDENTS_NAME, "ab") as fh:
            fh.write(b'{"incident": "torn')  # SIGKILL mid-append
        bundle = load_bundle(tmp_path)
        assert len(bundle.incidents) == 2  # torn tail dropped, not fatal
        current = load_incidents(tmp_path)
        assert len(current) == 1 and current[0]["state"] == "resolved"
        assert open_incident_count(tmp_path) == 0

    def test_engine_ticks_open_resolve_and_restart_dedupe(self, tmp_path):
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger
        from yuma_simulation_tpu.telemetry.flight import FlightRecorder
        from yuma_simulation_tpu.telemetry.metrics import MetricsRegistry

        ledger = FailureLedger(tmp_path / "ledger.jsonl")
        recorder = FlightRecorder(tmp_path)
        reg = MetricsRegistry()
        engine = IncidentEngine(ledger, recorder, registry=reg,
                                anomaly_engine=AnomalyEngine())
        ledger.append("subnet_stalled", netuid=3, head_block=1100,
                      stalled_seconds=40.0)
        incidents = engine.tick(now=100.0)
        assert [i.state for i in incidents] == ["open"]
        assert reg.snapshot()["gauges"]["incidents_open"] == 1
        opened = ledger.entries("incident_opened")
        assert len(opened) == 1
        assert opened[0]["cause_event"] == "subnet_stalled"
        # idempotent: an unchanged ledger appends no new transitions
        engine.tick(now=101.0)
        assert len(ledger.entries("incident_opened")) == 1
        assert len(load_incidents(tmp_path)) == 1
        # recovery flips the state durably and emits incident_resolved
        ledger.append("subnet_ingested", netuid=3, new_blocks=2,
                      head_block=1200)
        engine.tick(now=102.0)
        assert len(ledger.entries("incident_resolved")) == 1
        assert load_incidents(tmp_path)[0]["state"] == "resolved"
        assert reg.snapshot()["gauges"]["incidents_open"] == 0
        # a restarted engine reloads prior state: no duplicate appends
        engine2 = IncidentEngine(ledger, recorder, registry=reg,
                                 anomaly_engine=AnomalyEngine())
        engine2.tick(now=103.0)
        assert len(ledger.entries("incident_opened")) == 1
        assert len(ledger.entries("incident_resolved")) == 1

    def test_anomalies_are_ledgered_with_counter(self, tmp_path):
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger
        from yuma_simulation_tpu.telemetry.flight import FlightRecorder
        from yuma_simulation_tpu.telemetry.metrics import MetricsRegistry

        ledger = FailureLedger(tmp_path / "ledger.jsonl")
        reg = MetricsRegistry()
        gauge = reg.gauge("replay_staleness_seconds")
        engine = IncidentEngine(
            ledger, FlightRecorder(tmp_path), registry=reg
        )
        for i in range(20):
            gauge.set(3.0 + (i % 2) * 0.2)
            engine.feed_snapshot(now=float(i))
        gauge.set(5000.0)
        fired = engine.feed_snapshot(now=30.0)
        assert fired == 1
        records = ledger.entries("anomaly_detected")
        assert len(records) == 1
        assert records[0]["series"] == "gauge:replay_staleness_seconds"
        assert reg.snapshot()["counters"]["anomalies_total"] == 1

    def test_live_snapshots_dedupe_by_seq_not_clock(self, tmp_path):
        """feed_snapshot stamps the same monotone seq the persisted
        snapshot paths use, so two snapshots landing on the same
        rounded wall clock (coarse or stepped clock) are both retained
        instead of collapsing as (source, t) duplicates."""
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger
        from yuma_simulation_tpu.telemetry.flight import FlightRecorder
        from yuma_simulation_tpu.telemetry.metrics import MetricsRegistry

        ledger = FailureLedger(tmp_path / "ledger.jsonl")
        reg = MetricsRegistry()
        gauge = reg.gauge("replay_staleness_seconds")
        engine = IncidentEngine(
            ledger, FlightRecorder(tmp_path), registry=reg,
            anomaly_engine=AnomalyEngine(),
        )
        gauge.set(1.0)
        engine.feed_snapshot(now=100.0)
        gauge.set(2.0)
        engine.feed_snapshot(now=100.0)  # clock did not advance
        series = engine.store.series("gauge:replay_staleness_seconds")
        assert [v for _t, v in series] == [1.0, 2.0]


# ----------------------------------------------------- controller restart


class TestControllerRestart:
    def test_stale_open_run_becomes_process_loss_incident(self, tmp_path):
        from yuma_simulation_tpu.replay.archive import SnapshotArchive
        from yuma_simulation_tpu.replay.controller import (
            ControllerConfig,
            ReplayController,
        )
        from yuma_simulation_tpu.replay.statecache import StateCache

        def controller():
            # empty archive: cycles observe/tick without compiling
            return ReplayController(
                SnapshotArchive(tmp_path / "archive"),
                StateCache(tmp_path / "cache"),
                ControllerConfig(
                    store_root=tmp_path / "store",
                    versions=(VERSION,),
                    flight_rotation=True,
                ),
                bundle_dir=tmp_path / "bundle",
            )

        first = controller()
        first.run_cycle()
        # SIGKILL: the run marker stays open, close() never runs
        second = controller()
        assert second._stale_runs == [first.run.run_id]
        second.run_cycle()
        restarts = second.ledger.entries("controller_restarted")
        assert [r["run"] for r in restarts] == [first.run.run_id]
        current = load_incidents(tmp_path / "bundle")
        classes = {r["cause_class"] for r in current}
        assert "process-loss" in classes
        second.close()
        # a THIRD clean start sees only the crashed run as stale and
        # folds into the SAME deduped incident — no second incident
        third = controller()
        third.run_cycle()
        third.close()
        assert len([
            r for r in load_incidents(tmp_path / "bundle")
            if r["cause_class"] == "process-loss"
        ]) == 1


# --------------------------------------------------------- incidentreport


def _faulted_bundle(tmp_path):
    """A bundle with a runtime-correlated incident on disk."""
    from yuma_simulation_tpu.resilience.supervisor import FailureLedger
    from yuma_simulation_tpu.telemetry.flight import FlightRecorder
    from yuma_simulation_tpu.telemetry.metrics import MetricsRegistry

    ledger = FailureLedger(tmp_path / "ledger.jsonl")
    engine = IncidentEngine(
        ledger, FlightRecorder(tmp_path), registry=MetricsRegistry(),
        anomaly_engine=AnomalyEngine(),
    )
    ledger.append("subnet_quarantined", netuid=7, block=1100,
                  key="k", reason="digest mismatch")
    ledger.append("subnet_stalled", netuid=3, head_block=1100,
                  stalled_seconds=40.0)
    engine.tick(now=50.0)
    return tmp_path


class TestIncidentReport:
    def test_check_passes_on_correlated_bundle(self, tmp_path, capsys):
        from tools.incidentreport import main

        bundle = _faulted_bundle(tmp_path)
        assert main([str(bundle), "--check"]) == 0
        out = capsys.readouterr().out
        assert "snapshot-corruption:netuid=7" in out
        assert "subnet-stall:netuid=3" in out

    def test_tamper_orphans_the_cause_and_fails(self, tmp_path, capsys):
        from tools.incidentreport import main
        from yuma_simulation_tpu.telemetry.flight import INCIDENTS_NAME

        bundle = _faulted_bundle(tmp_path)
        path = bundle / INCIDENTS_NAME
        kept = [
            line
            for line in path.read_text().splitlines()
            if "subnet-stall" not in line
        ]
        path.write_text("\n".join(kept) + "\n")
        assert main([str(bundle), "--check"]) == 1
        err = capsys.readouterr().err
        assert "uncorrelated cause" in err and "subnet_stalled" in err

    def test_malformed_state_exits_2(self, tmp_path, capsys):
        from tools.incidentreport import main
        from yuma_simulation_tpu.telemetry.flight import INCIDENTS_NAME

        bundle = _faulted_bundle(tmp_path)
        path = bundle / INCIDENTS_NAME
        garbled = path.read_text().replace('"open"', '"exploded"')
        path.write_text(garbled)
        assert main([str(bundle), "--check"]) == 2

    def test_expect_none_pins_control_arms(self, tmp_path, capsys):
        from tools.incidentreport import main

        clean = tmp_path / "clean"
        clean.mkdir()
        assert main([str(clean), "--expect-none"]) == 0
        faulted = _faulted_bundle(tmp_path / "faulted")
        assert main([str(faulted), "--expect-none"]) == 1

    def test_offline_correlation_covers_bundles_without_sink(
        self, tmp_path, capsys
    ):
        """Drill bundles have no runtime engine: --check derives the
        incidents from the ledger and still gates cause presence."""
        from tools.incidentreport import main
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger

        (tmp_path / "b").mkdir()
        ledger = FailureLedger(tmp_path / "b" / "ledger.jsonl")
        ledger.append("unit_stalled", unit=4, attempt=1)
        assert main([str(tmp_path / "b"), "--check"]) == 0
        out = capsys.readouterr().out
        assert "engine-stall" in out and "offline correlation" in out


# ---------------------------------------------------------- surfaces


class TestSurfaces:
    def test_ops_debug_incidents(self, tmp_path):
        from yuma_simulation_tpu.telemetry.ops import OpsPlane

        plane = OpsPlane(tmp_path)
        assert plane.debug_incidents() == {"incidents": [], "open": 0}
        _faulted_bundle(tmp_path)
        snap = plane.debug_incidents()
        assert snap["open"] >= 1
        assert {r["incident"] for r in snap["incidents"]} >= {
            "subnet-stall:netuid=3"
        }

    def test_obsreport_renders_incident_section(self, tmp_path):
        from tools.obsreport import render_incidents
        from yuma_simulation_tpu.telemetry.flight import load_bundle

        bundle = load_bundle(_faulted_bundle(tmp_path))
        lines = render_incidents(bundle)
        text = "\n".join(lines)
        assert "incident intelligence:" in text
        assert "subnet-stall:netuid=3" in text
        # clean bundles render nothing
        clean = tmp_path / "clean"
        clean.mkdir()
        assert render_incidents(load_bundle(clean)) == []

    def test_fleet_report_counts_incident_events(self, tmp_path):
        from yuma_simulation_tpu.fabric.health import (
            FLEET_CROSS_CHECKED_COUNTS,
            FleetHealthReport,
        )

        assert "incidents_opened" in FLEET_CROSS_CHECKED_COUNTS
        # additive defaults: pre-0.24 call sites construct without them
        report = FleetHealthReport(
            fleet="f", num_units=0, units_published=0, hosts_seen=(),
            hosts_finished=(), hosts_lost=(), units_stolen=0,
            units_abandoned=0, units_duplicate=0, stalls_killed=0,
            engine_demotions=0, mesh_shrinks=0, lanes_quarantined=0,
        )
        assert report.incidents_opened == 0
        assert report.anomalies_detected == 0

    def test_follow_read_cost_is_o_new_bytes(self, tmp_path):
        """The --follow satellite: after the initial catch-up, a poll
        with nothing new reads ZERO bytes, and one appended record
        costs one record's bytes — not a bundle re-read — however much
        history the segmented bundle holds."""
        from tools.obsreport import BundleTailer
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger
        from yuma_simulation_tpu.telemetry.flight import (
            FlightRecorder,
            RotationPolicy,
        )
        from yuma_simulation_tpu.telemetry.runctx import RunContext, span

        rec = FlightRecorder(
            tmp_path,
            rotation=RotationPolicy(
                max_segment_bytes=2048, max_segment_age_seconds=0.0
            ),
        )
        run = RunContext()
        with run.activate():
            for _ in range(40):
                with span("cycle"):
                    pass
                rec.record(run)
        ledger = FailureLedger(tmp_path / "ledger.jsonl")
        for i in range(50):
            ledger.append("window_swept", netuid=i, version="v",
                          block_from=0, block_to=1, suffix_epochs=1,
                          total_epochs=1, resumed=False, units=1,
                          canaries=0, drift=0, store="s")
        tailer = BundleTailer(tmp_path)
        events = tailer.poll()
        assert tailer.ledger == 50 and tailer.spans > 0
        baseline = tailer.bytes_read
        assert baseline > 0
        # idle tick: zero bytes
        tailer.poll()
        assert tailer.bytes_read == baseline
        # one new record: one record's worth of bytes, not O(bundle)
        ledger.append("window_swept", netuid=99, version="v",
                      block_from=0, block_to=1, suffix_epochs=1,
                      total_epochs=1, resumed=False, units=1,
                      canaries=0, drift=0, store="s")
        new = tailer.poll()
        assert [k for k, _ in new] == ["ledger"]
        delta = tailer.bytes_read - baseline
        assert 0 < delta < 1024, (
            f"one appended record cost {delta} bytes — the tailer is "
            "re-reading history"
        )
        del events

    def test_follow_shrink_rescan_dedupes_by_content(self, tmp_path):
        """When a sink SHRINKS (atomic republish that repaired a line
        before the cursor), the rescan must dedupe re-read records by
        content — a fixed skip count misaligns the moment the rewrite
        changed any line, swallowing the repaired record or replaying
        an old one."""
        from tools.obsreport import _FileCursor

        path = tmp_path / "ledger.jsonl"
        path.write_bytes(
            b'{"event": "a", "t": 1.0}\n'
            b'xxxx garbled beyond saving, longer than its repair xxxx\n'
            b'{"event": "c", "t": 3.0}\n'
        )
        cur = _FileCursor(path)
        assert [r["event"] for r in cur.read_new()] == ["a", "c"]
        # the writer repairs the garbled middle line: the file shrinks
        path.write_bytes(
            b'{"event": "a", "t": 1.0}\n'
            b'{"event": "b", "t": 2.0}\n'
            b'{"event": "c", "t": 3.0}\n'
        )
        assert [r["event"] for r in cur.read_new()] == ["b"], (
            "shrink rescan must emit exactly the repaired record"
        )
        # the tail keeps working after the rescan retires
        with open(path, "ab") as fh:
            fh.write(b'{"event": "d", "t": 4.0}\n')
        assert [r["event"] for r in cur.read_new()] == ["d"]
