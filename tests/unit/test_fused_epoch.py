"""Fused Pallas epoch kernel: parity with the unfused XLA epoch.

Runs in interpreter mode on the CPU test mesh (the kernel auto-selects
interpret off-TPU); on TPU the same program compiles via Mosaic. The VPU
reduction path is asserted tight (reduction-order-only deviation); the
MXU path's looser contract is documented in pallas_epoch.py and exercised
on-chip by the benchmark.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.epoch import BondsMode, yuma_epoch
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.ops.normalize import normalize_weight_rows
from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_epoch
from yuma_simulation_tpu.simulation.engine import simulate_constant, simulate_scaled

MODES = (BondsMode.EMA, BondsMode.EMA_RUST, BondsMode.EMA_PREV)


def _case(rng, V, M):
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    S_n = S / S.sum()
    B0 = jnp.asarray(rng.random((V, M)), jnp.float32) * 0.1
    return W, S_n, B0


@pytest.mark.parametrize("shape", [(3, 2), (8, 16), (16, 130)])
@pytest.mark.parametrize("mode", MODES, ids=lambda m: m.name)
@pytest.mark.parametrize("first", [False, True])
def test_fused_epoch_matches_yuma_epoch(shape, mode, first):
    import jax

    # EMA_RUST under the x64 parity harness exercises the double-single
    # emulation of the f64 quantization divide (_rust64_quantize) against
    # the XLA engine's real f64 divide.
    V, M = shape
    rng = np.random.default_rng(V * M + first)
    W, S_n, B0 = _case(rng, V, M)
    cfg = YumaConfig()

    clip = None
    kw = {}
    if mode is BondsMode.EMA_PREV:
        Wp = normalize_weight_rows(jnp.asarray(rng.random((V, M)), jnp.float32))
        clip, kw["W_prev"] = Wp, Wp

    ref = yuma_epoch(
        W, S_n, B0, cfg, bonds_mode=mode, first_epoch=jnp.asarray(first), **kw
    )
    B_f, D_f, inc_f = fused_ema_epoch(
        W,
        S_n,
        B0,
        kappa=cfg.kappa,
        bond_penalty=cfg.bond_penalty,
        bond_alpha=cfg.bond_alpha,
        first_epoch=first,
        clip_base=clip,
        mode=mode,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(B_f), np.asarray(ref["validator_ema_bond"]), atol=2e-7
    )
    np.testing.assert_allclose(
        np.asarray(D_f),
        np.asarray(ref["validator_reward_normalized"]),
        atol=2e-7,
    )
    np.testing.assert_allclose(
        np.asarray(inc_f), np.asarray(ref["server_incentive"]), atol=2e-7
    )


@pytest.mark.parametrize(
    "version",
    ["Yuma 1 (paper)", "Yuma 2 (Adrian-Fish)"],
)
def test_simulate_scaled_fused_matches_xla(version):
    V, M, E = 8, 16, 12
    rng = np.random.default_rng(7)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version(version)

    t_xla, b_xla = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="xla")
    t_fused, b_fused = simulate_scaled(
        W, S, scales, cfg, spec, epoch_impl="fused"
    )
    np.testing.assert_allclose(
        np.asarray(t_fused), np.asarray(t_xla), rtol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(b_fused), np.asarray(b_xla), atol=2e-6
    )


def test_simulate_scaled_fused_scan_liquid_overrides_match_xla():
    """In-kernel consensus-quantile overrides on the fused_ema_scan path
    (simulate_scaled / simulate_scaled_batch): the override must (a)
    actually change the output vs the no-override config — silent
    dropping of the static kwargs through the pallas_call partial is
    exactly the wiring bug this guards — and (b) match the XLA oracle."""
    from yuma_simulation_tpu.models.config import YumaParams
    from yuma_simulation_tpu.simulation.engine import simulate_scaled_batch

    V, M, E = 16, 64, 10
    rng = np.random.default_rng(11)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    # Yuma 4, not Yuma 1: with epoch-constant weights the EMA families
    # sit at their bond fixed point from epoch 0 (B_1 = B_t), so the
    # liquid rate — and hence any override — provably cannot move their
    # outputs (the rejected closed-form shortcut, DESIGN.md). The
    # RELATIVE bonds model accumulates rate-scaled purchases instead,
    # so the override has a real effect to compare.
    spec = variant_for_version("Yuma 4 (Rhef+relative bonds) - liquid alpha on")
    base = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    cfg = YumaConfig(
        yuma_params=YumaParams(
            liquid_alpha=True,
            override_consensus_high=0.03,
            override_consensus_low=0.001,
        )
    )
    t_base, b_base = simulate_scaled(W, S, scales, base, spec, epoch_impl="xla")
    t_xla, b_xla = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="xla")
    t_fused, b_fused = simulate_scaled(
        W, S, scales, cfg, spec, epoch_impl="fused_scan"
    )
    assert float(np.abs(np.asarray(b_xla) - np.asarray(b_base)).max()) > 1e-3, (
        "override had no effect; agreement below would be vacuous"
    )
    np.testing.assert_allclose(np.asarray(t_fused), np.asarray(t_xla), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(b_fused), np.asarray(b_xla), atol=2e-6)
    # batched path shares the kernel but passes the static kwargs through
    # its own call site
    tb_xla, bb_xla = simulate_scaled_batch(
        W[None], S[None], scales, cfg, spec, epoch_impl="xla"
    )
    tb_fused, bb_fused = simulate_scaled_batch(
        W[None], S[None], scales, cfg, spec, epoch_impl="fused_scan"
    )
    np.testing.assert_allclose(
        np.asarray(tb_fused), np.asarray(tb_xla), rtol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(bb_fused), np.asarray(bb_xla), atol=2e-6
    )


@pytest.mark.parametrize(
    "version",
    ["Yuma 0 (subtensor)", "Yuma 1 (paper)", "Yuma 2 (Adrian-Fish)"],
)
def test_simulate_scaled_fused_scan_matches_per_epoch_fused(version):
    """The single-Pallas-program scan (bond state in VMEM scratch across
    grid steps) reproduces the lax.scan-over-fused-epoch path."""
    V, M, E = 8, 16, 12
    rng = np.random.default_rng(7)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version(version)

    t_fused, b_fused = simulate_scaled(
        W, S, scales, cfg, spec, epoch_impl="fused"
    )
    t_scan, b_scan = simulate_scaled(
        W, S, scales, cfg, spec, epoch_impl="fused_scan"
    )
    # Bonds follow the identical op sequence (expect ULP-exact); the total
    # differs only by converting the in-kernel D_n sum once vs per epoch.
    np.testing.assert_allclose(
        np.asarray(b_scan), np.asarray(b_fused), atol=3e-8
    )
    np.testing.assert_allclose(
        np.asarray(t_scan), np.asarray(t_fused), rtol=2e-6
    )


def test_fused_scan_ema_rust_matches_in_f32_subprocess():
    """The EMA_RUST branch of the fused scan can only run in f32 mode
    (the x64 harness skips it above); pin it against the per-epoch fused
    path in a subprocess with x64 off."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    script = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64
import numpy as np
import jax.numpy as jnp
from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.simulation.engine import simulate_scaled

V, M, E = 8, 16, 12
rng = np.random.default_rng(7)
W = jnp.asarray(rng.random((V, M)), jnp.float32)
S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
cfg = YumaConfig()
spec = variant_for_version("Yuma 0 (subtensor)")
t_f, b_f = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="fused")
t_s, b_s = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="fused_scan")
np.testing.assert_allclose(np.asarray(b_s), np.asarray(b_f), atol=3e-8)
np.testing.assert_allclose(np.asarray(t_s), np.asarray(t_f), rtol=2e-6)

# EMA_RUST + liquid alpha (no named version, but "auto" accepts it):
# pin the fused scan against the XLA oracle.
from yuma_simulation_tpu.models.config import YumaParams
liquid_cfg = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
t_x, b_x = simulate_scaled(W, S, scales, liquid_cfg, spec, epoch_impl="xla")
t_l, b_l = simulate_scaled(W, S, scales, liquid_cfg, spec, epoch_impl="fused_scan")
np.testing.assert_allclose(np.asarray(b_l), np.asarray(b_x), atol=2e-6)
np.testing.assert_allclose(np.asarray(t_l), np.asarray(t_x), rtol=2e-5)
print("EMA_RUST_SCAN_OK")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [repo, env.get("PYTHONPATH", "")] if p
    )
    env.pop("JAX_ENABLE_X64", None)
    out = subprocess.run(
        [sys.executable, "-c", script],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert "EMA_RUST_SCAN_OK" in out.stdout


def test_fused_epoch_rejects_clip_base_outside_ema_prev():
    # yuma_epoch ignores W_prev for non-EMA_PREV modes; the fused kernel
    # must refuse the combination rather than silently diverge from it.
    V, M = 4, 8
    rng = np.random.default_rng(2)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S_n = jnp.ones((V,), jnp.float32) / V
    B0 = jnp.zeros((V, M), jnp.float32)
    clip = normalize_weight_rows(jnp.asarray(rng.random((V, M)), jnp.float32))
    with pytest.raises(ValueError, match="EMA_PREV"):
        fused_ema_epoch(
            W, S_n, B0, clip_base=clip, mode=BondsMode.EMA, interpret=True
        )


def test_fused_scan_rejects_empty_epochs():
    from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_scan

    W = jnp.ones((4, 8), jnp.float32)
    S = jnp.ones((4,), jnp.float32) / 4
    with pytest.raises(ValueError, match="at least one epoch"):
        fused_ema_scan(W, S, jnp.zeros((0,), jnp.float32))


def test_fused_scan_rejects_oversized_vmem():
    from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_scan

    W = jnp.ones((4096, 16384), jnp.float32)  # 256 MiB/buffer: over budget
    S = jnp.ones((4096,), jnp.float32) / 4096
    with pytest.raises(ValueError, match="VMEM"):
        fused_ema_scan(W, S, jnp.ones(3, jnp.float32))


def test_simulate_scaled_ones_matches_simulate_constant():
    V, M, E = 8, 16, 12
    rng = np.random.default_rng(11)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")

    t_const, b_const = simulate_constant(W, S, E, cfg, spec)
    t_scaled, b_scaled = simulate_scaled(
        W, S, jnp.ones(E, jnp.float32), cfg, spec, epoch_impl="xla"
    )
    np.testing.assert_array_equal(np.asarray(t_const), np.asarray(t_scaled))
    np.testing.assert_array_equal(np.asarray(b_const), np.asarray(b_scaled))


def test_batched_mxu_scan_bitwise_equals_vpu_scan():
    """The batched fused scan's MXU support (leading dims on the dot's
    batch dimensions) must be bitwise the batched VPU scan — the
    contract simulate_scaled_batch's auto now relies on."""
    from yuma_simulation_tpu.simulation.engine import simulate_scaled_batch

    rng = np.random.default_rng(13)
    B, V, M, E = 3, 16, 64, 6
    W = jnp.asarray(rng.random((B, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((B, V)) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")
    t_v, b_v = simulate_scaled_batch(
        W, S, scales, cfg, spec, epoch_impl="fused_scan"
    )
    t_m, b_m = simulate_scaled_batch(
        W, S, scales, cfg, spec, epoch_impl="fused_scan_mxu"
    )
    np.testing.assert_array_equal(np.asarray(t_m), np.asarray(t_v))
    np.testing.assert_array_equal(np.asarray(b_m), np.asarray(b_v))


def test_rust64_quantize_tracks_f64_oracle_at_large_K():
    """The double-single emulation of Yuma-0's f64 quantization divide
    (`_rust64_quantize`) against a true-f64 oracle, at column sums far
    beyond the golden surface's (K ~ 2^28 vs the goldens' <= 2^18 —
    where the documented ~1e-7 near-boundary risk window is tightest).
    82k random dyadic-grid cells, zero grid flips expected (seeded)."""
    from yuma_simulation_tpu.ops.pallas_epoch import _rust64_quantize

    mismatches = 0
    for seed in range(20):
        rng = np.random.default_rng(seed)
        M = 4096
        k = rng.integers(1, 2**17 + 1, size=M)
        c = (k.astype(np.float64) * 2.0**-17).astype(np.float32)
        c64 = c.astype(np.float64)
        q64 = np.floor(c64 / c64.sum() * 65535.0).astype(np.int64)
        ds = np.asarray(
            _rust64_quantize(
                jnp.asarray(c[None], jnp.float32), jnp.float32, 17
            )
        )[0]
        qds = np.round(ds * 65535.0).astype(np.int64)
        mismatches += int((q64 != qds).sum())
    assert mismatches == 0


def test_fused_yuma0_under_x64_matches_f64_engine():
    # The x64 parity harness (tests/conftest.py) is active here; Yuma-0's
    # float64 quantization divide runs in the fused kernels as the
    # double-single f32 emulation (_rust64_quantize) and must track the
    # XLA engine's real f64 divide.
    import jax

    assert jax.config.jax_enable_x64
    V, M, E = 16, 64, 10
    rng = np.random.default_rng(5)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 0 (subtensor)")
    t_xla, b_xla = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="xla")
    for impl in ("fused", "fused_scan"):
        t_f, b_f = simulate_scaled(W, S, scales, cfg, spec, epoch_impl=impl)
        np.testing.assert_allclose(
            np.asarray(t_f), np.asarray(t_xla), rtol=2e-5, err_msg=impl
        )
        np.testing.assert_allclose(
            np.asarray(b_f), np.asarray(b_xla), atol=2e-6, err_msg=impl
        )


def test_fused_epoch_m_real_excludes_padded_columns():
    # Caller-side padding: columns >= m_real must not perturb the real
    # miners' consensus grid (same contract as yuma_epoch's miner_mask).
    V, M, pad = 8, 16, 5
    rng = np.random.default_rng(13)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    S_n = S / S.sum()
    B0 = jnp.asarray(rng.random((V, M)), jnp.float32) * 0.1
    W_pad = jnp.concatenate([W, jnp.zeros((V, pad), jnp.float32)], axis=1)
    B_pad = jnp.concatenate([B0, jnp.zeros((V, pad), jnp.float32)], axis=1)
    cfg = YumaConfig()
    kw = dict(
        kappa=cfg.kappa, bond_penalty=cfg.bond_penalty,
        bond_alpha=cfg.bond_alpha, first_epoch=False, interpret=True,
    )
    B_a, D_a, inc_a = fused_ema_epoch(W, S_n, B0, **kw)
    B_b, D_b, inc_b = fused_ema_epoch(W_pad, S_n, B_pad, m_real=M, **kw)
    np.testing.assert_array_equal(np.asarray(B_a), np.asarray(B_b)[:, :M])
    np.testing.assert_array_equal(np.asarray(inc_a), np.asarray(inc_b)[:M])
    np.testing.assert_array_equal(np.asarray(D_a), np.asarray(D_b))
    assert np.all(np.asarray(B_b)[:, M:] == 0)


def test_fused_rejects_non_ema_and_liquid():
    V, M, E = 4, 8, 3
    rng = np.random.default_rng(3)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    ones = jnp.ones(E, jnp.float32)
    with pytest.raises(ValueError, match="EMA family"):
        simulate_scaled(
            W, S, ones, YumaConfig(),
            variant_for_version("Yuma 3 (Rhef)"), epoch_impl="fused",
        )
    from yuma_simulation_tpu.models.config import YumaParams

    liquid_cfg = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    with pytest.raises(ValueError, match="liquid alpha"):
        simulate_scaled(
            W, S, ones, liquid_cfg,
            variant_for_version("Yuma 1 (paper)"), epoch_impl="fused",
        )


def test_epoch_impl_auto_selects_and_matches():
    """"auto" must run everywhere: off-TPU it resolves to the XLA path
    (interpret-mode Pallas would be slower, not faster) and matches it
    exactly; eligibility gating is checked directly."""
    import jax

    from yuma_simulation_tpu.models.config import YumaParams
    from yuma_simulation_tpu.ops.pallas_epoch import fused_scan_eligible

    V, M, E = 8, 16, 6
    rng = np.random.default_rng(9)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.ones(E, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")

    t_auto, b_auto = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="auto")
    t_xla, b_xla = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="xla")
    if jax.default_backend() != "tpu":
        np.testing.assert_array_equal(np.asarray(t_auto), np.asarray(t_xla))
        np.testing.assert_array_equal(np.asarray(b_auto), np.asarray(b_xla))

    # E=0 must fall back to the XLA path (zeros), never the fused scan.
    t0, b0 = simulate_scaled(
        W, S, jnp.zeros((0,), jnp.float32), cfg, spec, epoch_impl="auto"
    )
    assert np.all(np.asarray(t0) == 0) and np.all(np.asarray(b0) == 0)

    on_tpu = jax.default_backend() == "tpu"
    assert fused_scan_eligible((256, 4096), BondsMode.EMA, cfg) == on_tpu
    # liquid alpha is never eligible — except CAPACITY, where the XLA
    # oracle ignores it too (models/epoch.py), so the scan is parity-safe
    liquid = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    assert not fused_scan_eligible((256, 4096), BondsMode.EMA, liquid)
    assert not fused_scan_eligible((256, 4096), BondsMode.RELATIVE, liquid)
    assert fused_scan_eligible((256, 4096), BondsMode.CAPACITY, liquid) == on_tpu
    # capacity/relative are eligible on TPU (all five models covered)
    assert fused_scan_eligible((256, 4096), BondsMode.CAPACITY, cfg) == on_tpu
    # over the VMEM budget is never eligible
    assert not fused_scan_eligible((8192, 65536), BondsMode.EMA, cfg)
    # f64 arrays are never eligible (the Pallas kernels are f32-only)
    assert not fused_scan_eligible(
        (256, 4096), BondsMode.EMA, cfg, jnp.float64
    )


@pytest.mark.parametrize(
    "version",
    ["Yuma 3 (Rhef)", "Yuma 4 (Rhef+relative bonds)"],
    ids=["capacity", "relative"],
)
def test_fused_scan_capacity_relative_match_xla(version):
    """The capacity and relative bond models in the single-Pallas-program
    scan reproduce the XLA engine (the per-epoch fused kernels do not
    cover these modes, so the XLA path is the oracle)."""
    V, M, E = 8, 16, 12
    rng = np.random.default_rng(17)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version(version)

    t_xla, b_xla = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="xla")
    t_scan, b_scan = simulate_scaled(
        W, S, scales, cfg, spec, epoch_impl="fused_scan"
    )
    # Yuma 3 bonds sit on the ~1e19 capacity scale -> relative bound.
    np.testing.assert_allclose(
        np.asarray(b_scan), np.asarray(b_xla), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(t_scan), np.asarray(t_xla), rtol=2e-5
    )


def test_fused_scan_capacity_ignores_liquid_like_xla():
    """CAPACITY + liquid_alpha is accepted by the fused scan (the XLA
    kernel ignores liquid alpha for that mode, so results are identical
    to the liquid-off run)."""
    from yuma_simulation_tpu.models.config import YumaParams

    V, M, E = 6, 12, 8
    rng = np.random.default_rng(23)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.ones(E, jnp.float32)
    spec = variant_for_version("Yuma 3 (Rhef)")
    liquid = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    plain = YumaConfig()

    t_liquid, b_liquid = simulate_scaled(
        W, S, scales, liquid, spec, epoch_impl="fused_scan"
    )
    t_plain, b_plain = simulate_scaled(
        W, S, scales, plain, spec, epoch_impl="fused_scan"
    )
    np.testing.assert_array_equal(np.asarray(t_liquid), np.asarray(t_plain))
    np.testing.assert_array_equal(np.asarray(b_liquid), np.asarray(b_plain))


@pytest.mark.parametrize(
    "version,params",
    [
        ("Yuma 1 (paper) - liquid alpha on", dict(liquid_alpha=True)),
        (
            "Yuma 4 (Rhef+relative bonds) - liquid alpha on",
            dict(
                liquid_alpha=True,
                bond_alpha=0.025,
                alpha_high=0.99,
                alpha_low=0.9,
            ),
        ),
        # No named version pairs Yuma 2 with liquid alpha, but "auto"
        # accepts the combination, so pin it too (custom config).
        ("Yuma 2 (Adrian-Fish)", dict(liquid_alpha=True)),
    ],
    ids=["yuma1-liquid", "yuma4-liquid", "yuma2-liquid"],
)
def test_fused_scan_liquid_matches_xla(version, params):
    """Liquid alpha in the fused scan: in-kernel u16-grid order-statistic
    quantiles + the same traced-logit fit as the XLA oracle."""
    from yuma_simulation_tpu.models.config import YumaParams

    V, M, E = 8, 24, 10
    rng = np.random.default_rng(31)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    cfg = YumaConfig(yuma_params=YumaParams(**params))
    spec = variant_for_version(version)

    t_xla, b_xla = simulate_scaled(W, S, scales, cfg, spec, epoch_impl="xla")
    t_scan, b_scan = simulate_scaled(
        W, S, scales, cfg, spec, epoch_impl="fused_scan"
    )
    np.testing.assert_allclose(
        np.asarray(b_scan), np.asarray(b_xla), atol=2e-6
    )
    np.testing.assert_allclose(
        np.asarray(t_scan), np.asarray(t_xla), rtol=2e-5
    )


def test_ema_prev_recompute_variant_bitwise():
    """r4 verdict item 3: the EMA_PREV scan can re-derive the previous
    epoch's normalized weights from `W * scales[e-1]` instead of keeping
    the scratch mat — the two variants must be BITWISE identical (the
    same multiply+normalize on the same inputs)."""
    import yuma_simulation_tpu.ops.pallas_epoch as pe
    from yuma_simulation_tpu.models.epoch import BondsMode

    V, M, E = 8, 24, 12
    rng = np.random.default_rng(5)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S_n = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    S_n = S_n / S_n.sum()
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)

    b1, d1 = pe.fused_ema_scan(W, S_n, scales, mode=BondsMode.EMA_PREV)
    b2, d2 = pe.fused_ema_scan(
        W, S_n, scales, mode=BondsMode.EMA_PREV, recompute_prev=True
    )
    np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


def test_vmem_budget_model_pins_measured_boundaries():
    """The measured v5e VMEM admission model (compiled/failed boundaries
    observed on chip, r5): the scaled scan's EMA_PREV spellings fit
    through B=5 at 256x4096 and fail at B=6; the streamed case scan's
    chip-filling B=4 configs (4 mats incl. the EMA_PREV scratch) are
    admitted, save_bonds at that batch is not. The old `resident * 3 <=
    110 MiB` rule rejected every B=4 256x4096 case-scan config."""
    import yuma_simulation_tpu.ops.pallas_epoch as pe
    from yuma_simulation_tpu.models.epoch import BondsMode

    def unit(B):
        return pe._unit_bytes((B, 256, 4096))

    prev = BondsMode.EMA_PREV
    assert pe._fits_vmem(unit(4), pe._scan_mats(prev, False))
    assert pe._fits_vmem(unit(5), pe._scan_mats(prev, False))  # on-chip OK
    assert not pe._fits_vmem(unit(6), pe._scan_mats(prev, True))  # on-chip fail
    # The streamed case scan at the chip-filling batch (measured on
    # chip: the 4-mat EMA_PREV config compiles, B=6 does not).
    assert pe._fits_vmem(unit(4), pe._case_scan_mats(prev, False))
    assert not pe._fits_vmem(unit(6), pe._case_scan_mats(prev, False))
    assert not pe._fits_vmem(unit(4), pe._case_scan_mats(prev, True))
    assert pe._fits_vmem(unit(4), pe._case_scan_mats(BondsMode.EMA, False))
