"""Bitwise identity fuzz battery: sorted consensus == bisection == Pallas.

The sorted closed form (`ops/consensus.py::stake_weighted_median_sorted`)
claims value-identity with the reference bisection semantics
(reference yumas.py:83-97). This battery proves it bitwise
(`assert_array_equal`, no tolerance) over >250 generated cases covering
the edges where an off-by-one-grid-point bug would hide:

- tied weight columns (duplicated validator rows),
- weights lying exactly on the dyadic 2^-17 grid,
- stake supports exactly equal to kappa (dyadic stakes, kappa=0.5),
- all-zero columns / zero-stake validators / the all-zero matrix,
- kappa in {0.3, 0.5, 0.7} at several shapes and many seeds.

The Pallas kernel runs the same battery (interpret mode on CPU) on a
per-family subset — it is exercised bitwise at every family, just not at
every seed, because interpret mode is slow.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from yuma_simulation_tpu.ops.consensus import (
    stake_weighted_median,
    stake_weighted_median_sorted,
)
from yuma_simulation_tpu.ops.pallas_consensus import stake_weighted_median_pallas

KAPPAS = (0.3, 0.5, 0.7)
SHAPES = ((3, 2), (4, 8), (5, 7), (16, 32), (8, 130), (32, 64))
SEEDS_PER_CASE = 12
GRID = 2.0**-17


def _norm_rows(W):
    s = W.sum(axis=-1, keepdims=True)
    return np.divide(W, s, out=np.zeros_like(W), where=s > 0)


def _random_case(rng, V, M):
    W = _norm_rows(rng.random((V, M), dtype=np.float32))
    S = rng.random(V, dtype=np.float32) + 0.01
    return W, (S / S.sum()).astype(np.float32)


def _tied_case(rng, V, M):
    """Duplicate validator rows so every column has cross-validator ties."""
    W, S = _random_case(rng, V, M)
    half = V // 2
    W[half : 2 * half] = W[:half]
    return W, S


def _grid_case(rng, V, M):
    """Weights exactly on the 2^-17 bisection grid (exact in f32)."""
    k = rng.integers(0, 2**17 + 1, size=(V, M))
    W = (k.astype(np.float64) * GRID).astype(np.float32)
    S = rng.random(V, dtype=np.float32) + 0.01
    return W, (S / S.sum()).astype(np.float32)


def _kappa_edge_case(rng, V, M):
    """Dyadic stakes (multiples of 1/64 summing to exactly 1) so partial
    stake sums land exactly on kappa=0.5 — probing the strict `>` of the
    support test (reference yumas.py:89-91)."""
    cuts = np.sort(rng.choice(np.arange(1, 64), size=V - 1, replace=False))
    parts = np.diff(np.concatenate([[0], cuts, [64]]))
    S = (parts / 64.0).astype(np.float32)
    # few distinct weight levels -> many repeated support evaluations
    levels = rng.choice([0.0, 0.125, 0.25, 0.5, 0.75, 1.0], size=(V, M))
    return levels.astype(np.float32), S


def _zero_case(rng, V, M):
    W, S = _random_case(rng, V, M)
    W[:, rng.integers(0, M)] = 0.0  # an all-zero column
    if M > 1:
        W[:, rng.integers(0, M)] = 0.0
    S[rng.integers(0, V)] = 0.0  # a zero-stake validator
    S = S / S.sum()
    return W, S.astype(np.float32)


FAMILIES = {
    "random": _random_case,
    "ties": _tied_case,
    "grid": _grid_case,
    "kappa_edge": _kappa_edge_case,
    "zeros": _zero_case,
}


def _battery(family):
    """Yield (W[B,V,M], S[B,V], kappa[B]) batches, one per shape."""
    gen = FAMILIES[family]
    for shape_i, (V, M) in enumerate(SHAPES):
        rng = np.random.default_rng(1000 * shape_i + hash(family) % 997)
        Ws, Ss, ks = [], [], []
        for seed in range(SEEDS_PER_CASE):
            W, S = gen(rng, V, M)
            Ws.append(W)
            Ss.append(S)
            ks.append(KAPPAS[seed % len(KAPPAS)])
        yield (
            jnp.asarray(np.stack(Ws)),
            jnp.asarray(np.stack(Ss)),
            jnp.asarray(np.array(ks, np.float32)),
        )


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_sorted_matches_bisection_bitwise(family):
    n = 0
    for W, S, kappa in _battery(family):
        a = np.asarray(stake_weighted_median(W, S, kappa))
        b = np.asarray(stake_weighted_median_sorted(W, S, kappa))
        np.testing.assert_array_equal(a, b, err_msg=f"{family} {W.shape}")
        n += W.shape[0]
    assert n == len(SHAPES) * SEEDS_PER_CASE


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_pallas_matches_bisection_bitwise(family):
    # interpret mode is slow: one seed per (family, shape, kappa) instead
    # of the full battery — still every family x edge x kappa.
    gen = FAMILIES[family]
    for shape_i, (V, M) in enumerate(SHAPES[:4]):
        rng = np.random.default_rng(5000 + 1000 * shape_i + hash(family) % 997)
        for kappa in KAPPAS:
            W, S = gen(rng, V, M)
            Wj, Sj = jnp.asarray(W), jnp.asarray(S)
            a = np.asarray(stake_weighted_median(Wj, Sj, kappa))
            b = np.asarray(
                stake_weighted_median_pallas(Wj, Sj, kappa, interpret=True)
            )
            np.testing.assert_array_equal(
                a, b, err_msg=f"{family} {W.shape} kappa={kappa}"
            )


def test_all_zero_matrix_hits_grid_floor():
    W = jnp.zeros((4, 6), jnp.float32)
    S = jnp.full((4,), 0.25, jnp.float32)
    for fn in (stake_weighted_median, stake_weighted_median_sorted):
        np.testing.assert_array_equal(
            np.asarray(fn(W, S, 0.5)), np.full(6, np.float32(GRID))
        )


def test_near_tie_rounds_onto_kappa_like_the_reference():
    # Stakes [0.4, 0.3, 0.2, 0.1] (normalized f32) make subset sums whose
    # EXACT value is ~7.5e-9 above 0.5 — within half an f32 ulp, so the
    # reference's f32 support tensor rounds onto 0.5 and the strict `>`
    # fails (torch-verified; this pinned the round-3 kernel goldens).
    # The canonical test must reproduce that: exact integer sum, ONE
    # rounding to dtype, then compare (ops/consensus.py::support_rounded).
    S = np.array([0.4, 0.3, 0.2, 0.1], np.float32)
    S = jnp.asarray(S / S.sum())
    # miner 0: validators {0, 3} above any c < 0.8 -> support exactly
    # rounds to 0.5 -> never above -> descend to the grid floor.
    W = jnp.asarray(
        np.array(
            [[0.8, 0.2], [0.0, 1.0], [0.0, 1.0], [0.8, 0.2]], np.float32
        )
    )
    a = np.asarray(stake_weighted_median(W, S, 0.5))
    b = np.asarray(stake_weighted_median_sorted(W, S, 0.5))
    p = np.asarray(stake_weighted_median_pallas(W, S, 0.5, interpret=True))
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, p)
    assert a[0] == np.float32(GRID), a
    # Control: at kappa=0.3 the same rounded support (0.5) IS strictly
    # above, so miner 0's consensus converges to the grid point just
    # above the 0.8 weight level instead of collapsing to the floor.
    c = np.asarray(stake_weighted_median(W, S, 0.3))
    assert c[0] == np.float32(np.ceil(0.8 * 2**17) * GRID), c


def test_support_exactly_kappa_is_not_above():
    # S = [0.5, 0.25, 0.25]; miner 0's support at any c in (0, 0.6) is
    # exactly 0.5 == kappa -> strict `>` fails, bisection walks down.
    W = jnp.asarray(
        [[0.6, 0.4], [0.0, 1.0], [0.0, 1.0]], jnp.float32
    )
    S = jnp.asarray([0.5, 0.25, 0.25], jnp.float32)
    a = np.asarray(stake_weighted_median(W, S, 0.5))
    b = np.asarray(stake_weighted_median_sorted(W, S, 0.5))
    p = np.asarray(stake_weighted_median_pallas(W, S, 0.5, interpret=True))
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, p)
    # support(c) == 0.5 for c < 0.6 exactly: not above, so c_high descends
    # to the smallest grid point above 0.6 for miner 0... support at
    # c >= 0.6 is 0 -> also not above; the whole interval descends to 2^-17.
    assert a[0] == np.float32(GRID)


@pytest.mark.parametrize("V", [512, 2048])
def test_large_v_near_ties_stay_engine_consistent(V):
    """Advisor r4: the canonical fixed-point support rounds each stake
    onto a 2^-30 grid before the exact sum, so the decision can differ
    from a sequentially-accumulated f32 sum by up to ~V * 2^-31 at
    knife-edge ties — a window widest at large V. Fuzz exactly that
    regime: many validators, stake subsets engineered near kappa, and
    require all three engines to stay BITWISE consistent with each
    other (the canonical contract; reference-semantics equivalence at
    the tie itself is pinned by the small hand cases above)."""
    rng = np.random.default_rng(V)
    for trial in range(4):
        # Random stakes; one miner column supported by a random subset
        # whose stake mass lands within a few ulps of kappa = 0.5.
        S = rng.random(V).astype(np.float32) + 0.01
        S = S / S.sum()
        order = rng.permutation(V)
        csum = np.cumsum(S[order])
        k = int(np.searchsorted(csum, 0.5))
        subset = order[: k + 1]
        W = rng.random((V, 8)).astype(np.float32)
        # Miner 0: the subset puts weight above 0.7, everyone else
        # below, so support at c in (0.3, 0.7) is the subset's stake
        # mass — a near-kappa knife edge.
        W[:, 0] = 0.1
        W[subset, 0] = 0.9
        Wj = jnp.asarray(W / W.sum(axis=-1, keepdims=True))
        Sj = jnp.asarray(S)
        a = np.asarray(stake_weighted_median(Wj, Sj, 0.5))
        b = np.asarray(stake_weighted_median_sorted(Wj, Sj, 0.5))
        p = np.asarray(
            stake_weighted_median_pallas(Wj, Sj, 0.5, interpret=True)
        )
        np.testing.assert_array_equal(a, b, err_msg=f"V={V} trial {trial}")
        np.testing.assert_array_equal(a, p, err_msg=f"V={V} trial {trial}")
