"""Telemetry layer: run-scoped tracing, the metrics registry,
device/compile sampling, and the sweep flight recorder — ISSUE 4
acceptance battery.

The combined chaos drill here is the unsharded composition (stall + NaN
lane + torn chunk) producing a full flight-recorder bundle; the sharded
composition adding device loss lives behind the conftest
HAS_JAX_SHARD_MAP probe exactly like the elastic-mesh drills."""

import json
import logging
import threading

import numpy as np
import pytest

from yuma_simulation_tpu.resilience import (
    Deadline,
    FaultPlan,
    NaNFault,
    RetryPolicy,
    StallFault,
    SweepSupervisor,
    inject_faults,
)
from yuma_simulation_tpu.telemetry import (
    CompileTracker,
    MetricsRegistry,
    RunContext,
    check_bundle,
    current_fields,
    ensure_run,
    ledger_counts,
    load_bundle,
    record_device_telemetry,
    record_epoch_rate,
    sample_device_telemetry,
    span,
)
from yuma_simulation_tpu.utils.logging import log_event, parse_event_line

VERSION = "Yuma 1 (paper)"
POLICY = RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0, seed=0)
ROOMY = Deadline(budget_seconds=120.0, grace_seconds=120.0)


# ------------------------------------------------------ RunContext/spans


def test_no_active_run_is_a_noop():
    assert current_fields() == {}
    with span("orphan") as s:
        assert s is None  # spanning without a run costs nothing


def test_span_nesting_and_records():
    with RunContext("run-fixed") as run:
        assert current_fields() == {"run_id": "run-fixed"}
        with span("outer") as outer:
            with span("inner", flavor="x") as inner:
                fields = current_fields()
                assert fields["span_id"] == inner.span_id
                assert fields["parent_id"] == outer.span_id
        records = run.span_records()
    # close order: inner first, then outer
    assert [r["name"] for r in records] == ["inner", "outer"]
    inner_rec, outer_rec = records
    assert inner_rec["parent_id"] == outer_rec["span_id"]
    assert outer_rec["parent_id"] == ""
    assert inner_rec["attrs"] == {"flavor": "x"}
    assert all(r["run_id"] == "run-fixed" for r in records)
    assert all(r["t_end"] >= r["t_start"] for r in records)


def test_span_error_status_and_always_closes():
    with RunContext() as run:
        with pytest.raises(ValueError, match="boom"):
            with span("failing"):
                raise ValueError("boom")
        assert current_fields() == {"run_id": run.run_id}  # span closed
    (rec,) = run.span_records()
    assert rec["status"] == "error"


def test_ensure_run_joins_active_run():
    with RunContext("run-outer") as outer:
        with ensure_run() as joined:
            assert joined is outer  # no second run forked for same work
    with ensure_run() as fresh:
        assert fresh.run_id != "run-outer"


def test_run_context_survives_watchdog_worker_thread():
    """The watchdog copies the caller's contextvars into its worker, so
    records emitted during a supervised dispatch carry the caller's
    run/span identity."""
    from yuma_simulation_tpu.resilience.watchdog import run_with_deadline

    seen = {}

    def dispatch():
        seen.update(current_fields())
        seen["thread"] = threading.current_thread().name
        return 42

    with RunContext("run-wd"):
        with span("dispatching") as s:
            out = run_with_deadline(
                dispatch, Deadline(30.0), label="ctxprop"
            )
    assert out == 42
    assert seen["run_id"] == "run-wd" and seen["span_id"] == s.span_id
    assert seen["thread"].startswith("yuma-watchdog-")


# ------------------------------------- log_event / ledger identity stamp


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.lines: list[str] = []

    def emit(self, record):
        self.lines.append(record.getMessage())


def _captured_event(**fields) -> dict:
    logger = logging.getLogger("yuma_simulation_tpu.test_telemetry")
    logger.propagate = False
    h = _Capture()
    logger.addHandler(h)
    try:
        log_event(logger, "probe", **fields)
    finally:
        logger.removeHandler(h)
    parsed = parse_event_line(h.lines[0])
    assert parsed is not None
    return parsed


def test_log_event_stamps_run_and_span_and_roundtrips():
    """ISSUE 4 satellite: parse_event_line round-trips records carrying
    the new run_id/span_id fields (they are ordinary key=value pairs —
    the format is additive)."""
    with RunContext("run-stamp"):
        with span("work") as s:
            parsed = _captured_event(label="x y")  # quoted value too
    assert parsed == {
        "event": "probe",
        "label": "x y",
        "run_id": "run-stamp",
        "span_id": s.span_id,
    }
    # caller-passed identity wins over the ambient context
    with RunContext("run-ambient"):
        parsed = _captured_event(run_id="run-explicit")
    assert parsed["run_id"] == "run-explicit"
    # and without a run, no identity fields appear at all
    assert "run_id" not in _captured_event(label="bare")


def test_ledger_records_stamped_with_identity(tmp_path):
    from yuma_simulation_tpu.resilience.supervisor import FailureLedger

    led = FailureLedger(tmp_path / "ledger.jsonl")
    with RunContext("run-led"):
        with span("unit0") as s:
            led.append("unit_ok", unit=0)
    led.append("unit_ok", unit=1)  # outside any run: no identity
    rec0, rec1 = (
        json.loads(line)
        for line in (tmp_path / "ledger.jsonl").read_text().splitlines()
    )
    assert rec0["run_id"] == "run-led" and rec0["span_id"] == s.span_id
    assert rec0["t"] > 0
    assert "run_id" not in rec1  # additive format, old shape still valid


# ------------------------------------------------------ metrics registry


def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("epochs_total")
    c.inc()
    c.inc(9)
    assert c.value == 10
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    g = reg.gauge("epochs_per_sec")
    g.set(2.5)
    assert g.value == 2.5
    h = reg.histogram("unit_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(99)
    snap = h.snapshot()
    assert snap["count"] == 3 and snap["sum"] == pytest.approx(99.55)
    assert snap["buckets"] == {"0.1": 1, "1.0": 2, "+Inf": 3}
    # get-or-create returns the same instance; kind mismatch is loud
    assert reg.counter("epochs_total") is c
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("epochs_total")
    with pytest.raises(ValueError, match="Prometheus"):
        reg.counter("bad name!")


def test_counter_thread_safe_under_contention():
    reg = MetricsRegistry()
    c = reg.counter("hits")

    def hammer():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


def test_snapshot_and_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("engine_demotions", help="ladder demotions").inc(3)
    reg.gauge("device_peak_bytes").set(1 << 20)
    reg.histogram("unit_seconds", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap["counters"] == {"engine_demotions": 3}
    assert snap["gauges"] == {"device_peak_bytes": float(1 << 20)}
    assert snap["histograms"]["unit_seconds"]["count"] == 1
    text = reg.prometheus_text()
    assert "# HELP engine_demotions ladder demotions" in text
    assert "# TYPE engine_demotions counter" in text
    assert "engine_demotions 3" in text
    assert "device_peak_bytes 1048576" in text
    assert 'unit_seconds_bucket{le="+Inf"} 1' in text
    assert text.endswith("\n")


def test_prometheus_exposition_conformance():
    """Parse prometheus_text() output and assert the exposition
    contract: for every histogram, bucket lines appear in ascending
    `le` order ending with a cumulative +Inf bucket, counts are
    monotone non-decreasing, the +Inf bucket equals _count, and _sum/
    _count lines exist; HELP text is escaped (no raw newlines); every
    series name matches the metric-name grammar."""
    import re

    reg = MetricsRegistry()
    reg.counter("good_total", help="with\nnewline and back\\slash").inc(2)
    reg.gauge("depth").set(3.5)
    h = reg.histogram("lat_seconds", help="latency", buckets=(0.1, 1.0, 5.0))
    for v in (0.05, 0.5, 0.5, 3.0, 100.0):
        h.observe(v)
    # Non-finite caller bounds are dropped; +Inf still comes from the
    # total, never from a caller bound.
    h2 = reg.histogram(
        "weird_seconds", buckets=(float("inf"), 2.0, float("nan"), 2.0)
    )
    h2.observe(10.0)
    text = reg.prometheus_text()
    lines = text.splitlines()
    assert text.endswith("\n")
    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    bucket_re = re.compile(r'^(\w+)_bucket\{le="([^"]+)"\} (\d+)$')
    for line in lines:
        assert "\n" not in line
        if line.startswith("# HELP"):
            continue
        if line.startswith("# TYPE"):
            assert name_re.match(line.split()[2])
    # HELP escaping: the newline survives as literal backslash-n.
    assert "# HELP good_total with\\nnewline and back\\\\slash" in text

    histograms = {}
    for line in lines:
        m = bucket_re.match(line)
        if m:
            histograms.setdefault(m.group(1), []).append(
                (m.group(2), int(m.group(3)))
            )
    assert set(histograms) == {"lat_seconds", "weird_seconds"}
    for name, buckets in histograms.items():
        les = [le for le, _ in buckets]
        counts = [c for _, c in buckets]
        assert les[-1] == "+Inf", buckets
        finite = [float(le) for le in les[:-1]]
        assert finite == sorted(finite)
        assert all(a <= b for a, b in zip(counts, counts[1:])), buckets
        count_line = next(
            ln for ln in lines if ln.startswith(f"{name}_count ")
        )
        assert int(count_line.split()[1]) == counts[-1]
        assert any(ln.startswith(f"{name}_sum ") for ln in lines)
    # The +Inf bucket counts the overflow observation (100.0 / 10.0).
    assert dict(histograms["lat_seconds"])["+Inf"] == 5
    assert dict(histograms["weird_seconds"]) == {"2.0": 0, "+Inf": 1}


def test_histogram_rejects_all_nonfinite_bounds():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.histogram("bad_seconds", buckets=(float("inf"),))


def test_publish_snapshot_jsonl_accumulates_and_tolerates_torn_tail(tmp_path):
    reg = MetricsRegistry()
    reg.counter("epochs_total").inc(5)
    path = tmp_path / "metrics.jsonl"
    reg.publish_snapshot(path, run_id="run-a")
    reg.counter("epochs_total").inc(5)
    # simulate a torn line from a pre-atomic writer between snapshots
    path.write_text(path.read_text() + '{"torn": ')
    reg.publish_snapshot(path, run_id="run-b")
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [ln["run_id"] for ln in lines] == ["run-a", "run-b"]
    assert lines[0]["counters"]["epochs_total"] == 5
    assert lines[1]["counters"]["epochs_total"] == 10
    assert all("t" in ln for ln in lines)


def test_record_epoch_rate_feeds_registry_and_emits_event(caplog):
    reg = MetricsRegistry()
    with caplog.at_level(logging.INFO):
        rate = record_epoch_rate(
            "probe_run", epochs=100, seconds=4.0, registry=reg
        )
    assert rate == 25.0
    assert reg.counter("epochs_total").value == 100
    assert reg.gauge("epochs_per_sec").value == 25.0
    events = [
        p
        for line in caplog.text.splitlines()
        if (p := parse_event_line(line)) is not None
    ]
    (rec,) = [e for e in events if e["event"] == "epoch_rate"]
    assert rec["label"] == "probe_run"
    assert rec["epochs"] == "100" and rec["epochs_per_sec"] == "25.0"


def test_timed_routes_through_epoch_rate(caplog):
    """ISSUE 4 satellite: `timed` is no longer dead code with drifting
    docs — with `epochs` it reports through the registry and emits one
    event=epoch_rate record."""
    from yuma_simulation_tpu.telemetry import get_registry
    from yuma_simulation_tpu.utils.profiling import timed

    before = get_registry().counter("epochs_total").value
    with caplog.at_level(logging.INFO):
        with timed("timed_probe", epochs=7) as t:
            pass
    assert t.seconds >= 0
    assert get_registry().counter("epochs_total").value == before + 7
    events = [
        p
        for line in caplog.text.splitlines()
        if (p := parse_event_line(line)) is not None
        and p["event"] == "epoch_rate"
    ]
    assert len(events) == 1 and events[0]["label"] == "timed_probe"


# ----------------------------------------- device / compile telemetry


def test_device_sample_degrades_gracefully_on_cpu():
    """ISSUE 4 satellite: memory_stats() is None on every CPU device —
    the sample must say so (None, not 0) and still count devices."""
    sample = sample_device_telemetry()
    assert sample["backend"] == "cpu"
    assert sample["num_devices"] >= 1
    assert sample["device_peak_bytes"] is None
    assert sample["device_bytes_in_use"] is None
    assert sample["live_buffers"] is not None  # introspection exists on CPU


def test_device_sample_handles_absent_memory_stats(monkeypatch):
    """A device object with no memory_stats at all (older runtimes) is
    the same graceful None, not an exception."""
    import jax

    class _BareDevice:
        pass

    monkeypatch.setattr(jax, "devices", lambda: [_BareDevice()])
    sample = sample_device_telemetry()
    assert sample["num_devices"] == 1
    assert sample["device_peak_bytes"] is None


def test_record_device_telemetry_leaves_gauges_untouched_on_none():
    reg = MetricsRegistry()
    reg.gauge("device_peak_bytes").set(777.0)  # a real prior TPU reading
    sample = record_device_telemetry(reg)
    assert sample["device_peak_bytes"] is None  # CPU run
    assert reg.gauge("device_peak_bytes").value == 777.0  # not zeroed
    if sample["live_buffers"] is not None:
        assert reg.gauge("live_buffers").value == sample["live_buffers"]


def test_compile_tracker_counts_new_cache_entries():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x + 1

    reg = MetricsRegistry()
    tracker = CompileTracker(f, registry=reg)
    f(jnp.ones(3))  # new shape -> one compile
    assert tracker.record() == 1
    f(jnp.ones(3))  # warm repeat -> zero
    assert tracker.record() == 0
    assert reg.counter("recompiles").value == 1
    with pytest.raises(TypeError, match="_cache_size"):
        CompileTracker(lambda x: x)
    with pytest.raises(ValueError, match="at least one"):
        CompileTracker()


def test_recompilation_sentinel_feeds_recompiles_counter():
    import jax
    import jax.numpy as jnp

    from yuma_simulation_tpu.telemetry import get_registry
    from yuma_simulation_tpu.utils.profiling import RecompilationSentinel

    @jax.jit
    def g(x):
        return x * 2

    before = get_registry().counter("recompiles").value
    with RecompilationSentinel(g, budget=1, label="telemetry probe"):
        g(jnp.ones(5))
    assert get_registry().counter("recompiles").value == before + 1


# ------------------------------------------------- profile_trace finally


def test_profile_trace_logs_pointer_even_on_failure(tmp_path, caplog):
    """ISSUE 4 satellite: an exception inside the traced region must not
    eat the pointer to the dump that would explain it."""
    from yuma_simulation_tpu.utils import profile_trace

    with caplog.at_level(logging.INFO, "yuma_simulation_tpu.utils.profiling"):
        with pytest.raises(RuntimeError, match="mid-trace"):
            with profile_trace(str(tmp_path / "trace")):
                raise RuntimeError("mid-trace")
    assert any(
        "profiler trace written" in r.getMessage() for r in caplog.records
    )


# ------------------------------------------- the flight-recorder bundle


def _supervisor(**kw):
    kw.setdefault("unit_size", 3)
    kw.setdefault("deadline", ROOMY)
    kw.setdefault("retry_policy", POLICY)
    return SweepSupervisor(**kw)


def test_clean_supervised_sweep_writes_sound_bundle(tmp_path):
    from yuma_simulation_tpu.scenarios import get_cases

    out = _supervisor(directory=tmp_path).run_batch(
        get_cases()[:4], VERSION
    )
    assert out["report"].clean
    bundle = load_bundle(tmp_path)
    assert check_bundle(bundle) == []
    (run_id,) = bundle.run_ids()
    # span chain: sweep -> unit -> attempt -> engine rung
    names = [s["name"] for s in bundle.spans]
    assert any(n.startswith("sweep:") for n in names)
    assert "unit0" in names and "attempt1" in names
    assert any(n.startswith("engine:") for n in names)
    # every ledger record resolves under the one run
    assert all(r["run_id"] == run_id for r in bundle.ledger)
    # one metrics snapshot line with the epoch counters
    (snap,) = bundle.metrics
    assert snap["run_id"] == run_id
    assert snap["counters"]["epochs_total"] > 0
    assert snap["gauges"]["epochs_per_sec"] > 0
    # report.json cross-checks clean
    assert bundle.report["run_id"] == run_id
    assert bundle.report["report"]["stalls_killed"] == 0


@pytest.mark.chaos
def test_chaos_drill_bundle_reconstructs_timeline(tmp_path, caplog):
    """ISSUE 4 acceptance (unsharded composition): the stall + NaN +
    torn-chunk drill produces a flight-recorder bundle where every
    ledger record resolves to a span under ONE run_id and the
    ledger-derived counts match the SweepHealthReport exactly."""
    from yuma_simulation_tpu.scenarios import get_cases
    from yuma_simulation_tpu.telemetry import build_timeline

    cases = get_cases()[:4]
    # Warm-up passes (the chaos pass's tight budget must only ever kill
    # the injected hold — same discipline as test_supervisor).
    _supervisor().run_batch(cases, VERSION)
    with inject_faults(FaultPlan(nan=NaNFault(epoch=2, case=1))):
        _supervisor().run_batch(cases, VERSION)

    plan = FaultPlan(
        stall=StallFault(seconds=1.0, dispatches=1),
        nan=NaNFault(epoch=2, case=1),
        truncate_chunks={1: 10},
    )
    with caplog.at_level(logging.INFO):
        with inject_faults(plan):
            out = _supervisor(
                directory=tmp_path,
                deadline=Deadline(0.15, grace_seconds=60.0),
            ).run_batch(cases, VERSION)
    report = out["report"]
    assert report.stalls_killed == 1
    assert report.units_requeued == 1
    assert report.lanes_quarantined == 1

    bundle = load_bundle(tmp_path)
    assert check_bundle(bundle) == []
    (run_id,) = bundle.run_ids()
    assert bundle.ledger, "the drill must ledger its recovery actions"
    span_ids = {s["span_id"] for s in bundle.spans}
    for rec in bundle.ledger:
        assert rec["run_id"] == run_id
        assert rec["span_id"] in span_ids

    # the ledger-derived counts ARE the report's counts
    derived = ledger_counts(bundle.ledger, run_id)
    assert derived == {
        "stalls_killed": report.stalls_killed,
        "units_requeued": report.units_requeued,
        "engine_demotions": report.engine_demotions,
        "mesh_shrinks": report.mesh_shrinks,
        "lanes_quarantined": report.lanes_quarantined,
        "canaries_run": report.canaries_run,
        "drift_events": report.drift_events,
    }

    # the timeline reconstructs: one sweep root, the stalled attempt's
    # engine span is marked error, and the requeued unit appears twice
    tl = build_timeline(bundle, run_id)
    roots = [tl["spans"][r]["name"] for r in tl["roots"]]
    assert any(n.startswith("sweep:") for n in roots)
    statuses = [
        s["status"]
        for s in tl["spans"].values()
        if s["name"].startswith("engine:")
    ]
    assert "error" in statuses  # the stalled attempt's rung span
    unit1_spans = [
        s for s in tl["spans"].values() if s["name"] == "unit1"
    ]
    assert len(unit1_spans) == 2  # original + requeue
    # and the log stream carries the same run identity end to end
    stamped = [
        p
        for line in caplog.text.splitlines()
        if (p := parse_event_line(line)) is not None
        and p.get("run_id") == run_id
    ]
    assert any(e["event"] == "engine_stalled" for e in stamped)
    assert any(e["event"] == "epoch_rate" for e in stamped)


def test_bundle_sound_under_operator_opened_spans(tmp_path):
    """The README's own usage — the supervisor joining an operator
    RunContext inside an operator span — must yield a sound bundle: the
    still-open outer span is recorded (status=open) so the sweep span's
    parent resolves, and a second sweep in the same run replaces it
    instead of duplicating spans."""
    from yuma_simulation_tpu.scenarios import get_cases

    cases = get_cases()[:4]
    with RunContext() as run:
        with span("nightly"):
            _supervisor(directory=tmp_path).run_batch(cases, VERSION)
            bundle = load_bundle(tmp_path)
            assert check_bundle(bundle) == []
            (nightly,) = [
                s for s in bundle.spans if s["name"] == "nightly"
            ]
            assert nightly["status"] == "open" and nightly["t_end"] is None
            # second sweep in the SAME run: spans merge, not duplicate
            _supervisor(directory=tmp_path).run_batch(cases, VERSION)
    bundle = load_bundle(tmp_path)
    assert check_bundle(bundle) == []
    assert bundle.run_ids() == [run.run_id]
    keys = [(s["run_id"], s["span_id"]) for s in bundle.spans]
    assert len(keys) == len(set(keys)), "republish must not duplicate spans"
    assert len([s for s in bundle.spans if s["name"] == "nightly"]) == 1


def test_ledger_counts_requeued_units_not_events():
    """SweepHealthReport.units_requeued counts UNITS; a unit torn twice
    emits two unit_requeued records but must derive as one."""
    ledger = [
        {"event": "unit_requeued", "unit": 0, "executions": 2,
         "run_id": "run-a", "span_id": "s0001"},
        {"event": "unit_requeued", "unit": 0, "executions": 3,
         "run_id": "run-a", "span_id": "s0002"},
        {"event": "unit_requeued", "unit": 2, "executions": 2,
         "run_id": "run-a", "span_id": "s0003"},
    ]
    assert ledger_counts(ledger, "run-a")["units_requeued"] == 2


@pytest.mark.chaos
def test_resumed_sweep_appends_second_run_to_bundle(tmp_path):
    from yuma_simulation_tpu.scenarios import get_cases

    cases = get_cases()[:4]
    first = _supervisor(directory=tmp_path).run_batch(cases, VERSION)
    second = _supervisor(directory=tmp_path).run_batch(cases, VERSION)
    assert second["report"].units_resumed == 2
    np.testing.assert_array_equal(first["dividends"], second["dividends"])
    bundle = load_bundle(tmp_path)
    assert len(bundle.run_ids()) == 2
    assert check_bundle(bundle) == []  # both runs fully resolvable
    assert len(bundle.metrics) == 2  # one snapshot per run
    # report.json is the LATEST run's
    assert bundle.report["run_id"] == bundle.run_ids()[-1]
    assert bundle.report["report"]["units_resumed"] == 2


@pytest.mark.chaos
def test_failed_sweep_still_publishes_bundle(tmp_path, monkeypatch):
    """A sweep that dies mid-run must leave a bundle whose ledger
    records still resolve — the crash is exactly when the operator
    needs the timeline."""
    import yuma_simulation_tpu.resilience.supervisor as supervisor_mod
    from yuma_simulation_tpu.scenarios import get_cases

    def explode(*a, **k):
        raise ArithmeticError("not an engine failure")

    monkeypatch.setattr(supervisor_mod, "_batch_on_rung", explode)
    with pytest.raises(ArithmeticError):
        _supervisor(directory=tmp_path).run_batch(get_cases()[:2], VERSION)
    bundle = load_bundle(tmp_path)
    assert check_bundle(bundle) == []
    assert any(r["event"] == "unit_failed" for r in bundle.ledger)
    failed = [s for s in bundle.spans if s["status"] == "error"]
    assert failed, "the failing spans must be recorded as errors"


# ------------------------------------------------------------ obsreport


@pytest.mark.chaos
def test_obsreport_renders_and_checks_drill_bundle(tmp_path, capsys):
    from tools.obsreport import main as obsreport_main
    from yuma_simulation_tpu.scenarios import get_cases

    cases = get_cases()[:4]
    _supervisor().run_batch(cases, VERSION)  # warm
    with inject_faults(
        FaultPlan(stall=StallFault(seconds=1.0, dispatches=1))
    ):
        _supervisor(
            directory=tmp_path, deadline=Deadline(0.15, grace_seconds=60.0)
        ).run_batch(cases, VERSION)

    assert obsreport_main([str(tmp_path), "--check"]) == 0
    text = capsys.readouterr().out
    assert "unit_stalled" in text and "sweep:" in text
    assert "ledger-derived counts" in text
    assert "bundle is sound" in text

    assert obsreport_main([str(tmp_path), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["runs"] and payload["ledger"]

    # tamper: a ledger record with no span identity must fail --check
    ledger_path = tmp_path / "ledger.jsonl"
    ledger_path.write_text(
        ledger_path.read_text() + '{"event": "unit_ok", "unit": 9}\n'
    )
    assert obsreport_main([str(tmp_path), "--check"]) == 2
    err = capsys.readouterr().err
    assert "lacks run/span identity" in err


def test_obsreport_empty_directory_reports_gracefully(tmp_path, capsys):
    from tools.obsreport import main as obsreport_main

    assert obsreport_main([str(tmp_path)]) == 0
    assert "no runs recorded" in capsys.readouterr().out


def test_flight_append_spans_then_record_merges(tmp_path):
    """`FlightRecorder.append_spans` (the serving tier's O(batch)
    ingress flush) appends without a whole-file merge; a later full
    `record` with the same runs as `extra_runs` merges by identity —
    appended spans are replaced, not duplicated — and the final bundle
    is sound."""
    from yuma_simulation_tpu.telemetry.flight import FlightRecorder

    rec = FlightRecorder(tmp_path)
    with RunContext("ingress-a") as ra:
        with span("request:r1"):
            pass
    with RunContext("ingress-b") as rb:
        with span("request:r2"):
            pass
    rec.append_spans([ra])
    rec.append_spans([rb])
    appended = load_bundle(tmp_path).spans
    assert {s["run_id"] for s in appended} == {ra.run_id, rb.run_id}

    with RunContext("server") as main:
        with span("lifetime"):
            pass
    rec.record(main, extra_runs=[ra, rb])
    bundle = load_bundle(tmp_path)
    assert check_bundle(bundle) == []
    keys = [(s["run_id"], s["span_id"]) for s in bundle.spans]
    assert len(keys) == len(set(keys)), "append + record must not duplicate"
    assert {s["run_id"] for s in bundle.spans} == {
        ra.run_id,
        rb.run_id,
        main.run_id,
    }


def test_check_bundle_flags_unresolvable_span(tmp_path):
    (tmp_path / "spans.jsonl").write_text(
        json.dumps(
            {
                "span_id": "s0001",
                "parent_id": "",
                "name": "sweep:x",
                "run_id": "run-a",
                "t_start": 1.0,
                "t_end": 2.0,
                "status": "ok",
            }
        )
        + "\n"
    )
    (tmp_path / "ledger.jsonl").write_text(
        json.dumps(
            {
                "event": "unit_ok",
                "unit": 0,
                "run_id": "run-a",
                "span_id": "s0099",
            }
        )
        + "\n"
    )
    problems = check_bundle(load_bundle(tmp_path))
    assert len(problems) == 1 and "does not resolve" in problems[0]


def test_check_bundle_flags_report_mismatch(tmp_path):
    (tmp_path / "spans.jsonl").write_text(
        json.dumps(
            {
                "span_id": "s0001",
                "parent_id": "",
                "name": "sweep:x",
                "run_id": "run-a",
                "t_start": 1.0,
                "t_end": 2.0,
                "status": "ok",
            }
        )
        + "\n"
    )
    (tmp_path / "ledger.jsonl").write_text(
        json.dumps(
            {
                "event": "unit_stalled",
                "unit": 0,
                "run_id": "run-a",
                "span_id": "s0001",
            }
        )
        + "\n"
    )
    (tmp_path / "report.json").write_text(
        json.dumps({"run_id": "run-a", "report": {"stalls_killed": 0}})
    )
    problems = check_bundle(load_bundle(tmp_path))
    assert len(problems) == 1
    assert "stalls_killed" in problems[0] and "derives 1" in problems[0]


# ------------------------------------- sharded composition (gated)


@pytest.mark.chaos
def test_chaos_drill_four_faults_sharded_bundle(tmp_path):
    """ISSUE 4 acceptance, full composition: stall + device loss + NaN
    lane + torn chunk under one supervised SHARDED sweep — the bundle
    resolves completely and the counts (mesh shrink included) match the
    report. Gated on jax.shard_map via the conftest probe."""
    from yuma_simulation_tpu.parallel import make_mesh
    from yuma_simulation_tpu.resilience import DeviceLossFault
    from yuma_simulation_tpu.scenarios import get_cases

    cases = get_cases()[:4]
    mesh = make_mesh()
    lost = mesh.devices.flat[1].id
    _supervisor().run_batch(cases, VERSION, mesh=mesh)  # warm full mesh
    with inject_faults(
        FaultPlan(
            device_loss=DeviceLossFault(device_id=lost),
            nan=NaNFault(epoch=2, case=1),
        )
    ):
        _supervisor().run_batch(cases, VERSION, mesh=mesh)  # warm shrunk

    plan = FaultPlan(
        stall=StallFault(seconds=12.0, dispatches=1),
        device_loss=DeviceLossFault(device_id=lost),
        nan=NaNFault(epoch=2, case=1),
        truncate_chunks={1: 10},
    )
    with inject_faults(plan):
        out = _supervisor(
            directory=tmp_path, deadline=Deadline(1.5, grace_seconds=6.0)
        ).run_batch(cases, VERSION, mesh=mesh)
    report = out["report"]
    assert report.mesh_shrinks >= 1 and report.stalls_killed >= 1
    assert report.lanes_quarantined == 1 and report.units_requeued == 1

    bundle = load_bundle(tmp_path)
    assert check_bundle(bundle) == []
    (run_id,) = bundle.run_ids()
    derived = ledger_counts(bundle.ledger, run_id)
    assert derived["mesh_shrinks"] == report.mesh_shrinks
    assert derived["stalls_killed"] == report.stalls_killed
    assert derived["lanes_quarantined"] == report.lanes_quarantined
    # the mesh walk appears as spans too
    names = [s["name"] for s in bundle.spans]
    assert any(n.startswith("mesh:") for n in names)
