"""RecompilationSentinel: compile-budget enforcement on the hot paths.

The static side of the recompilation story is jaxlint JX001 (str/bool
params must be static); this pins the runtime side: the hot
`simulate_batch` / `sweep_hyperparams` engines must be compile-free on
warm repeat calls, and a hash-unstable static argument (fresh cache key
per call — the classic silent-retrace bug) must fail the budget loudly.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.simulation.engine import _simulate_scan
from yuma_simulation_tpu.simulation.sweep import (
    _simulate_batch_xla,
    config_grid,
    simulate_batch,
    stack_scenarios,
    sweep_hyperparams,
)
from yuma_simulation_tpu.utils.profiling import (
    RecompilationBudgetExceeded,
    RecompilationSentinel,
)


def test_sweep_hyperparams_warm_repeat_is_compile_free():
    case = create_case("Case 2")
    configs, _ = config_grid(bond_penalty=[0.0, 0.5, 1.0])
    args = (case, "Yuma 1 (paper)", configs)
    sweep_hyperparams(*args)  # warm-up: pays the one cold compile
    with RecompilationSentinel(
        _simulate_scan, budget=0, label="sweep_hyperparams warm repeat"
    ) as sentinel:
        ys = sweep_hyperparams(*args)
    assert sentinel.new_entries == 0
    assert np.isfinite(np.asarray(ys["dividends"])).all()


def test_simulate_batch_warm_repeat_is_compile_free():
    cases = get_cases()[:3]
    W, S, ri, re = stack_scenarios(cases)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")
    simulate_batch(W, S, ri, re, cfg, spec)  # warm-up
    with RecompilationSentinel(
        _simulate_batch_xla,
        _simulate_scan,
        budget=0,
        label="simulate_batch warm repeat",
    ) as sentinel:
        simulate_batch(W, S, ri, re, cfg, spec)
    assert sentinel.new_entries == 0


def test_supervised_sweep_warm_repeat_is_compile_free():
    """ISSUE 3 acceptance: the watchdog/supervisor tier adds ZERO
    warm-repeat compiles — running a dispatch on the watchdog's worker
    thread hits the same process-global jit caches, and the supervisor's
    unit partitioning reuses one cache entry per unit shape."""
    from yuma_simulation_tpu.resilience import (
        Deadline,
        RetryPolicy,
        SweepSupervisor,
    )

    cases = get_cases()[:4]
    sup = SweepSupervisor(
        unit_size=2,
        deadline=Deadline(120.0),
        retry_policy=RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0),
    )
    sup.run_batch(cases, "Yuma 1 (paper)")  # warm-up (one cold compile)
    with RecompilationSentinel(
        _simulate_batch_xla,
        _simulate_scan,
        budget=0,
        label="supervised sweep warm repeat",
    ) as sentinel:
        out = sup.run_batch(cases, "Yuma 1 (paper)")
    assert sentinel.new_entries == 0
    assert out["report"].clean


def test_supervised_simulate_warm_repeat_is_compile_free():
    """run_simulation(supervised=True): the deadline-watchdog wrapper
    around the single-scenario driver is also compile-free warm."""
    from yuma_simulation_tpu.simulation.engine import run_simulation

    case = create_case("Case 2")
    run_simulation(case, "Yuma 1 (paper)", supervised=True)  # warm-up
    with RecompilationSentinel(
        _simulate_scan, budget=0, label="supervised run_simulation"
    ) as sentinel:
        run_simulation(case, "Yuma 1 (paper)", supervised=True)
    assert sentinel.new_entries == 0


def test_telemetry_instrumented_sweep_is_compile_free(tmp_path):
    """ISSUE 4 acceptance: the telemetry layer (RunContext + spans +
    metrics + device sampling + flight-recorder bundle) is host-side
    only — a fully instrumented supervised sweep adds ZERO warm-repeat
    compiles over the bare engines."""
    from yuma_simulation_tpu.resilience import (
        Deadline,
        RetryPolicy,
        SweepSupervisor,
    )
    from yuma_simulation_tpu.telemetry import RunContext, load_bundle

    cases = get_cases()[:4]
    sup = SweepSupervisor(
        directory=tmp_path,
        unit_size=2,
        deadline=Deadline(120.0),
        retry_policy=RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0),
    )
    with RunContext("run-warm"):
        sup.run_batch(cases, "Yuma 1 (paper)")  # warm-up (cold compiles)
    with RecompilationSentinel(
        _simulate_batch_xla,
        _simulate_scan,
        budget=0,
        label="telemetry-instrumented sweep warm repeat",
    ) as sentinel:
        with RunContext("run-measured"):
            out = sup.run_batch(cases, "Yuma 1 (paper)")
    assert sentinel.new_entries == 0
    # the instrumentation actually ran: both runs landed in the bundle
    assert {"run-warm", "run-measured"} <= set(load_bundle(tmp_path).run_ids())
    assert out["report"].units_resumed == 2  # warm run's chunks reused


def test_planner_adds_zero_compiles_and_is_cached_stable():
    """ISSUE 6 satellite: dispatch planning is pure host arithmetic —
    a warm-repeat simulate() (which now plans, preflights and records
    on every call) stays compile-free, and repeated planning returns
    identical plans (no hash-unstable decision state)."""
    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.simulation.engine import simulate
    from yuma_simulation_tpu.simulation.planner import plan_dispatch

    case = create_case("Case 2")
    simulate(case, "Yuma 1 (paper)")  # warm-up: the one cold compile
    with RecompilationSentinel(
        _simulate_scan, budget=0, label="planned simulate warm repeat"
    ) as sentinel:
        simulate(case, "Yuma 1 (paper)")
        plans = [
            plan_dispatch(
                "pin",
                np.shape(case.weights),
                "Yuma 1 (paper)",
                YumaConfig(),
                jnp.float32,
            )
            for _ in range(3)
        ]
    assert sentinel.new_entries == 0
    assert plans[0] == plans[1] == plans[2]


def test_streamed_double_buffer_warm_repeat_is_compile_free():
    """The double-buffered streamed driver compiles ONE chunk program
    (plus none for the zero carry) and reuses it: a warm repeat over
    the same chunk split adds zero entries to the donating engine."""
    from yuma_simulation_tpu.simulation.engine import (
        _simulate_scan_streamed,
        simulate_streamed,
    )

    case = create_case("Case 2")
    W = np.asarray(case.weights)
    S = np.asarray(case.stakes)
    chunks = [(W[:20], S[:20]), (W[20:], S[20:])]
    simulate_streamed(list(chunks), "Yuma 1 (paper)", epoch_impl="xla")
    with RecompilationSentinel(
        _simulate_scan_streamed, budget=0, label="streamed warm repeat"
    ) as sentinel:
        simulate_streamed(list(chunks), "Yuma 1 (paper)", epoch_impl="xla")
    assert sentinel.new_entries == 0


class _IdentityHashedSpec:
    """A 'static' argument whose equality is object identity: every
    instance is a fresh jit-cache key — the silent-retrace bug the
    sentinel exists to catch."""


@partial(jax.jit, static_argnames=("spec",))
def _engine_with_unstable_static(x, spec):
    del spec
    return x * 2


def test_sentinel_fails_on_hash_unstable_static_arg():
    x = jnp.ones(8)
    _engine_with_unstable_static(x, _IdentityHashedSpec())  # warm-up
    with pytest.raises(RecompilationBudgetExceeded, match="compile budget"):
        with RecompilationSentinel(
            _engine_with_unstable_static, budget=0, label="unstable static"
        ):
            # a *fresh* spec instance per call -> one new cache entry each
            _engine_with_unstable_static(x, _IdentityHashedSpec())
            _engine_with_unstable_static(x, _IdentityHashedSpec())


def test_sentinel_budget_allows_declared_cold_compiles():
    @jax.jit
    def f(x):
        return x + 1

    with RecompilationSentinel(f, budget=2, label="cold region") as s:
        f(jnp.ones(3))  # 1st shape -> compile
        f(jnp.ones(4))  # 2nd shape -> compile
    assert s.new_entries == 2
    assert s.report[f.__qualname__][1] - s.report[f.__qualname__][0] == 2


def test_sentinel_does_not_mask_region_exception():
    @jax.jit
    def f(x):
        return x + 1

    with pytest.raises(ValueError, match="inner"):
        with RecompilationSentinel(f, budget=0):
            f(jnp.ones(5))  # would blow the budget...
            raise ValueError("inner")  # ...but the real failure wins


def test_sentinel_rejects_unjitted_callables():
    with pytest.raises(TypeError, match="_cache_size"):
        RecompilationSentinel(lambda x: x)
    with pytest.raises(ValueError, match="at least one"):
        RecompilationSentinel()


def test_serve_warm_repeat_is_compile_free():
    """ISSUE 8 acceptance (warm engines): after a bucket's first request
    compiles its donor-packed batched program, every further request in
    the same shape bucket — admission, quota, queue, coalescer, the
    supervised dispatch, response slicing — adds ZERO compiles. The
    whole serving pipeline is host-side around one warm jit cache."""
    from yuma_simulation_tpu.serve import ServeConfig, SimulationService

    svc = SimulationService(
        ServeConfig(coalesce_window_seconds=0.0)
    )
    payload = {"tenant": "warm", "case": "Case 1"}
    try:
        status, _body, _h = svc.handle("simulate", dict(payload))  # warm-up
        assert status == 200
        with RecompilationSentinel(
            _simulate_batch_xla,
            _simulate_scan,
            budget=0,
            label="serve warm repeat",
        ) as sentinel:
            # Same bucket, different tenant AND different case: the
            # bucket key (not the payload) is the compile key.
            for tenant, case in (("warm", "Case 1"), ("other", "Case 2")):
                status, body, _h = svc.handle(
                    "simulate", {"tenant": tenant, "case": case}
                )
                assert status == 200 and body["status"] == "ok"
        assert sentinel.new_entries == 0
    finally:
        svc.close()


def test_dispatch_sketch_seam_is_compile_free():
    """ISSUE 19: the always-on dispatch-timing seam (one host-side
    LatencySketch observation per dispatched region) must add ZERO
    warm-repeat compiles — the sketch never touches traced values."""
    from yuma_simulation_tpu.simulation.engine import simulate
    from yuma_simulation_tpu.telemetry.slo import get_dispatch_stats

    stats = get_dispatch_stats()
    case = create_case("Case 2")
    simulate(case, "Yuma 1 (paper)")  # warm-up
    stats.reset()
    with RecompilationSentinel(
        _simulate_scan, budget=0, label="sketch-instrumented dispatch"
    ) as sentinel:
        simulate(case, "Yuma 1 (paper)")
    assert sentinel.new_entries == 0
    # the seam did observe the warm dispatch it is riding on
    snap = stats.snapshot()
    assert snap and sum(e["dispatches"] for e in snap.values()) >= 1
