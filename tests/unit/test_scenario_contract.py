"""Scenario input contract + case-registry memoization (ISSUE 12
satellites: `Scenario.validate()` rejection paths; `get_cases()` no
longer re-invokes every builder per call, with copy-on-return)."""

import numpy as np
import pytest

from yuma_simulation_tpu.scenarios.base import (
    Scenario,
    ScenarioValidationError,
    class_registry,
    constant_stakes,
    get_cases,
    register_case,
)


def _scenario(weights=None, stakes=None):
    W = np.zeros((4, 2, 2), np.float32)
    W[:, :, 0] = 1.0
    return Scenario(
        name="contract",
        validators=["a", "b"],
        base_validator="a",
        weights=W if weights is None else weights,
        stakes=(
            constant_stakes(4, [0.5, 0.5]) if stakes is None else stakes
        ),
        num_epochs=4,
    )


# ------------------------------------------------------------- validate()


def test_validate_accepts_clean_scenario_and_returns_self():
    s = _scenario()
    assert s.validate(normalized=True) is s


def test_validate_rejects_nan_weight_with_provenance():
    W = np.zeros((4, 2, 2), np.float32)
    W[:, :, 0] = 1.0
    W[2, 1, 0] = np.nan
    with pytest.raises(ScenarioValidationError, match=r"\(2, 1, 0\)"):
        _scenario(weights=W).validate()


def test_validate_rejects_negative_weight():
    W = np.zeros((4, 2, 2), np.float32)
    W[:, :, 0] = 1.0
    W[1, 0, 1] = -0.25
    with pytest.raises(ScenarioValidationError, match="negative weight"):
        _scenario(weights=W).validate()


def test_validate_rejects_nonfinite_stake():
    S = constant_stakes(4, [0.5, 0.5])
    S[3, 0] = np.inf
    with pytest.raises(ScenarioValidationError, match="non-finite stake"):
        _scenario(stakes=S).validate()


def test_validate_rejects_negative_stake():
    S = constant_stakes(4, [0.5, 0.5])
    S[0, 1] = -1.0
    with pytest.raises(ScenarioValidationError, match="negative stake"):
        _scenario(stakes=S).validate()


def test_validate_rejects_all_zero_stake():
    S = np.zeros((4, 2), np.float32)
    with pytest.raises(ScenarioValidationError, match="zero total stake"):
        _scenario(stakes=S).validate()


def test_validate_normalization_tolerance():
    W = np.full((4, 2, 2), 0.55, np.float32)  # rows sum to 1.1
    with pytest.raises(ScenarioValidationError, match="sums to"):
        _scenario(weights=W).validate(normalized=True)
    # the same scenario passes without the normalization contract, and
    # with a tolerance that admits the excess
    _scenario(weights=W).validate()
    _scenario(weights=W).validate(normalized=True, normalization_tol=0.2)


def test_validate_allows_all_zero_rows_under_normalized():
    W = np.zeros((4, 2, 2), np.float32)
    W[:, 0, 0] = 1.0  # validator b abstains every epoch
    _scenario(weights=W).validate(normalized=True)


# ----------------------------------------------------- get_cases() memo


def test_get_cases_returns_equal_but_independent_arrays():
    a, b = get_cases(), get_cases()
    assert len(a) == len(b)
    for sa, sb in zip(a, b):
        np.testing.assert_array_equal(sa.weights, sb.weights)
        np.testing.assert_array_equal(sa.stakes, sb.stakes)
        assert sa.weights is not sb.weights
        assert sa.stakes is not sb.stakes
    # mutating one call's arrays must not leak into the next call
    a[0].weights[:] = -1.0
    c = get_cases()
    np.testing.assert_array_equal(c[0].weights, b[0].weights)


def test_get_cases_materializes_each_builder_once():
    calls = {"n": 0}

    @register_case("_memo_probe")
    def _probe(num_epochs: int = 4, **kw):
        calls["n"] += 1
        return _scenario()

    try:
        first = get_cases()
        second = get_cases()
        assert calls["n"] == 1  # builder ran once across both calls
        assert first[-1].name == second[-1].name == "contract"
    finally:
        class_registry.pop("_memo_probe", None)
    # registry changed again: the cache key rotates and rebuilds
    rebuilt = get_cases()
    assert all(s.name != "contract" for s in rebuilt)


def test_get_cases_invalidates_on_rebind_of_existing_name():
    """Re-registering an EXISTING case name under a new builder must
    rotate the cache (the key covers builders, not just names)."""
    get_cases()  # warm the cache
    original = class_registry["Case 1"]
    try:

        @register_case("Case 1")
        def _override(num_epochs: int = 4, **kw):
            s = _scenario()
            s.name = "overridden"
            return s

        assert get_cases()[0].name == "overridden"
    finally:
        class_registry["Case 1"] = original
    assert get_cases()[0].name != "overridden"
