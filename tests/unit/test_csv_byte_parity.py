"""Byte-level gate on the rendered CSV artifacts.

The full-precision golden surface (test_parity_golden.py) bounds every
value to <1.5e-6, but the artifact the reference actually ships is the
`%.6f`-rendered CSV — and a deviation of a few 1e-7 can flip a rendered
6th decimal on a knife-edge cell. This test renders the framework's CSVs
byte-for-byte as the CLI does and classifies every differing cell
against the reference-rendered goldens via the same logic as
`tools/csv_byte_parity.py` (which writes the CSV_BYTE_PARITY.json
artifact): a differing cell must be a one-unit 6th-decimal rounding of
a <1.5e-6 full-precision deviation, nothing else.
"""

import pytest

from tools.csv_byte_parity import BETAS, classify_beta


@pytest.mark.parametrize("beta", BETAS)
def test_rendered_csv_within_rounding_class(beta):
    res = classify_beta(beta)
    if res["byte_identical"]:
        return
    diffs = res["differing_cells"]
    # The comparison must not be vacuous: the header and case labels must
    # have matched (classify_beta asserts), and differing cells exist.
    assert diffs, "files differ but no cell-level diffs found"
    bad = [d for d in diffs if not d["is_sixth_decimal_rounding"]]
    assert not bad, (
        f"beta={beta}: {len(bad)} differing cells are NOT one-unit "
        f"6th-decimal roundings of <1.5e-6 deviations: {bad[:5]}"
    )
    # Knife-edge flips are a small minority of the surface; a majority
    # differing would mean a real numerical regression even if each cell
    # individually stayed in class.
    assert len(diffs) < 0.25 * res["cells_total"], (
        f"beta={beta}: {len(diffs)}/{res['cells_total']} cells differ"
    )
