"""Byte-level gate on the rendered CSV artifacts.

The full-precision golden surface (test_parity_golden.py) bounds every
value to <1.5e-6, but the artifact the reference actually ships is the
`%.6f`-rendered CSV — and a deviation of a few 1e-7 can flip a rendered
6th decimal on a knife-edge cell. This test renders the framework's CSVs
byte-for-byte as the CLI does and holds them to TWO gates:

1. class: every differing cell must be a one-unit 6th-decimal rounding
   of a <1.5e-6 full-precision deviation (same logic as
   `tools/csv_byte_parity.py`, which writes CSV_BYTE_PARITY.json);
2. pin (r4 verdict item 8): the exact differing-cell list — case,
   column, both rendered strings — must equal the golden list captured
   in `tests/golden/csv_diff_cells.json`. A cell newly differing, a
   cell newly agreeing, or a changed rendered value all fail, so silent
   drift WITHIN the rounding class is impossible. Regenerate the pin
   with `python tools/csv_byte_parity.py --pin
   tests/golden/csv_diff_cells.json` after an intentional numerics
   change, and say why in the commit.
"""

import json
import os

import pytest

from tests.conftest import GOLDEN_DIR
from tools.csv_byte_parity import BETAS, classify_beta, pin_key

_PIN_PATH = os.path.join(GOLDEN_DIR, "csv_diff_cells.json")


@pytest.mark.parametrize("beta", BETAS)
def test_rendered_csv_cells_pinned_exactly(beta):
    with open(_PIN_PATH) as f:
        pinned = json.load(f)[beta]
    res = classify_beta(beta)
    diffs = res["differing_cells"]
    if not res["byte_identical"]:
        # The comparison must not be vacuous: the header and case labels
        # must have matched (classify_beta asserts), and cells exist.
        assert diffs, "files differ but no cell-level diffs found"
    bad = [d for d in diffs if not d["is_sixth_decimal_rounding"]]
    assert not bad, (
        f"beta={beta}: {len(bad)} differing cells are NOT one-unit "
        f"6th-decimal roundings of <1.5e-6 deviations: {bad[:5]}"
    )
    got = sorted(pin_key(d) for d in diffs)
    appeared = sorted(set(got) - set(pinned))
    vanished = sorted(set(pinned) - set(got))
    assert got == pinned, (
        f"beta={beta}: rendered-byte diff drifted from the pinned list "
        f"(tests/golden/csv_diff_cells.json): {len(appeared)} new "
        f"differing cells {appeared[:4]}, {len(vanished)} cells now "
        f"agree {vanished[:4]}. If the numerics change was intentional, "
        "regenerate the pin with tools/csv_byte_parity.py --pin."
    )
