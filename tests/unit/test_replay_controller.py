"""The continuous replay controller's crash-safety contract
(yuma_simulation_tpu/replay/controller.py) and the archive's
cross-process append discipline.

Four batteries:

- **Watermarks / window specs** — monotone advance, torn-tail
  tolerance, spec round-trips, in-flight reuse semantics.
- **Self-healing** — corrupt-blob quarantine (typed, durable, drains
  past the block), stall demotion + recovery, backpressure shedding.
- **Randomized kill points** — the controller is interrupted BETWEEN
  window publication and watermark advance at seed-chosen sweeps,
  restarted cold each time, and must converge to bitwise the
  uninterrupted control run's baselines with every window published
  exactly once (at-least-once sweep, exactly-once publication).
- **Concurrent archive access** — real racing processes: two
  converging appenders of the SAME block sequence (the cross-process
  append lock's lost-update case) while this process reads the
  timeline and digest-verifies blobs throughout.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from yuma_simulation_tpu.replay.archive import (
    ArchiveError,
    SnapshotArchive,
    synthetic_timeline,
)
from yuma_simulation_tpu.replay.controller import (
    ControllerConfig,
    ControllerError,
    ReplayController,
    WatermarkStore,
    WindowSpec,
)
from yuma_simulation_tpu.replay.statecache import StateCache

VERSION = "Yuma 2 (Adrian-Fish)"


def make_controller(tmp_path, **overrides) -> ReplayController:
    defaults = dict(
        store_root=tmp_path / "store",
        versions=(VERSION,),
        epochs_per_snapshot=2,
        stride=2,
        unit_size=1,
        poll_seconds=0.01,
        slow_poll_seconds=0.0,
        stall_deadline_seconds=3600.0,
        freshness_budget_seconds=3600.0,
    )
    defaults.update(overrides)
    return ReplayController(
        SnapshotArchive(tmp_path / "archive"),
        StateCache(tmp_path / "cache"),
        ControllerConfig(**defaults),
    )


def seed(tmp_path, netuid=0, snapshots=2, seed_=11):
    return synthetic_timeline(
        SnapshotArchive(tmp_path / "archive"),
        netuid,
        snapshots=snapshots,
        seed=seed_ + netuid * 1000,
        num_validators=3,
        num_miners=4,
    )


class TestWatermarkStore:
    def test_advance_is_strictly_monotone(self, tmp_path):
        marks = WatermarkStore(tmp_path)
        marks.advance(0, VERSION, block=1100, epochs=4, baseline_key="a")
        with pytest.raises(ControllerError, match="monotone"):
            marks.advance(
                0, VERSION, block=1100, epochs=8, baseline_key="b"
            )
        marks.advance(0, VERSION, block=1200, epochs=8, baseline_key="b")
        assert marks.load(0, VERSION)["baseline_key"] == "b"

    def test_torn_tail_resumes_from_last_valid(self, tmp_path):
        marks = WatermarkStore(tmp_path)
        marks.advance(0, VERSION, block=1100, epochs=4, baseline_key="a")
        marks.advance(0, VERSION, block=1200, epochs=8, baseline_key="b")
        path = marks.path(0, VERSION)
        with open(path, "ab") as f:
            f.write(b'{"netuid": 0, "block": 13')  # SIGKILL mid-write
        wm = WatermarkStore(tmp_path).load(0, VERSION)
        assert wm["block"] == 1200 and wm["baseline_key"] == "b"

    def test_pairs_are_independent(self, tmp_path):
        marks = WatermarkStore(tmp_path)
        marks.advance(0, VERSION, block=1100, epochs=4, baseline_key="a")
        assert marks.load(1, VERSION) is None
        assert marks.load(0, "Yuma 1 (paper)") is None


class TestWindowSpec:
    def test_round_trip(self):
        spec = WindowSpec(
            netuid=3,
            version=VERSION,
            blocks=(1100, 1200),
            epochs_per_snapshot=2,
            epoch_offset=4,
            prior_baseline_key="k",
            base_block=1000,
            scenario_fingerprint="fp",
            store="/s",
        )
        assert WindowSpec.from_json(spec.to_json()) == spec
        never_swept = WindowSpec.from_json(
            {**spec.to_json(), "base_block": None}
        )
        assert never_swept.base_block is None

    def test_corrupt_payload_is_typed(self):
        with pytest.raises(ControllerError, match="corrupt window spec"):
            WindowSpec.from_json({"netuid": "x"})


class TestSelfHealing:
    def test_corrupt_blob_quarantined_and_drained_past(self, tmp_path):
        entries = seed(tmp_path, snapshots=3)
        archive = SnapshotArchive(tmp_path / "archive")
        blob = archive._blob_path(0, entries[1].key)
        blob.write_bytes(blob.read_bytes()[:10])  # torn mid-write
        controller = make_controller(tmp_path)
        report = controller.run_cycle()
        assert report.snapshots_quarantined == 1
        quarantined = controller.ledger.entries("subnet_quarantined")
        assert [(r["netuid"], r["block"]) for r in quarantined] == [
            (0, entries[1].block)
        ]
        # The subnet kept draining: watermark at the head, the
        # quarantined block excluded from the swept window.
        wm = controller.watermarks.load(0, VERSION)
        assert wm["block"] == entries[-1].block
        assert wm["epochs"] == 2 * 2  # two usable snapshots x K
        # Durable across restarts: a fresh controller re-loads the
        # quarantine set without re-probing the blob.
        again = make_controller(tmp_path)
        assert (0, entries[1].block) in again._quarantined

    def test_stall_demotes_then_recovers(self, tmp_path):
        seed(tmp_path, snapshots=2)
        controller = make_controller(
            tmp_path, stall_deadline_seconds=0.05
        )
        controller.run_cycle()  # observes the head, sweeps
        time.sleep(0.1)
        report = controller.run_cycle()  # head static past deadline
        assert report.subnets_stalled == 1
        assert 0 in controller._stalled
        events = controller.ledger.entries("subnet_stalled")
        assert len(events) == 1 and events[0]["netuid"] == 0
        seed(tmp_path, snapshots=3)  # the feed comes back
        report = controller.run_cycle()
        assert 0 not in controller._stalled
        assert report.subnets_stalled == 0

    def test_backlog_sheds_lowest_priority(self, tmp_path):
        seed(tmp_path, netuid=0)
        seed(tmp_path, netuid=1)
        controller = make_controller(
            tmp_path,
            max_windows_per_cycle=1,
            priorities={1: 10},
        )
        report = controller.run_cycle()
        assert report.windows_swept == 1 and report.windows_shed == 1
        # Priority won: subnet 1 swept, subnet 0 shed and still pending.
        assert [s[0] for s in report.swept] == [1]
        assert controller.watermarks.load(0, VERSION) is None
        report = controller.run_cycle()
        assert [s[0] for s in report.swept] == [0]
        assert report.windows_shed == 0

    def test_inflight_reused_only_while_base_matches(self, tmp_path):
        seed(tmp_path, snapshots=2)
        controller = make_controller(tmp_path)
        timeline = controller.archive.timeline(0)
        spec = controller._plan_window(0, VERSION, timeline)
        # Pin it (what sweep_window does first), then re-plan: the
        # identical spec comes back — same blocks, same store.
        controller._pair_dir(0, VERSION).mkdir(
            parents=True, exist_ok=True
        )
        controller._inflight_path(0, VERSION).write_text(
            json.dumps(spec.to_json())
        )
        assert controller._plan_window(0, VERSION, timeline) == spec
        # A mismatching base (the watermark moved) voids the marker.
        controller.watermarks.advance(
            0, VERSION, block=spec.blocks[0], epochs=2, baseline_key=""
        )
        replanned = controller._plan_window(0, VERSION, timeline)
        assert replanned is not None and replanned != spec
        assert replanned.base_block == spec.blocks[0]


class Boom(RuntimeError):
    pass


def drain(tmp_path, *, rng=None, kill_p=0.0, max_cycles=40) -> int:
    """Cycle a (fresh-per-crash) controller until a cycle sweeps
    nothing. With `rng`, each sweep's post-publish point — BETWEEN the
    window's fleet + cache publication and the watermark advance —
    raises with probability `kill_p`, and the controller is rebuilt
    cold, exactly a SIGKILL at the worst instant. Returns the number
    of kills."""
    kills = 0
    controller = make_controller(tmp_path)
    if rng is not None:

        def maybe_boom(netuid, version):
            if rng.random() < kill_p:
                raise Boom()

        controller.test_hooks["post_publish"] = maybe_boom
    for _ in range(max_cycles):
        try:
            report = controller.run_cycle()
        except Boom:
            kills += 1
            controller = make_controller(tmp_path)
            if rng is not None:
                controller.test_hooks["post_publish"] = maybe_boom
            continue
        if report.windows_swept == 0:
            return kills
    raise AssertionError(f"did not drain in {max_cycles} cycles")


@pytest.mark.parametrize("seed_", [0, 1, 2])
def test_randomized_kill_points_converge_bitwise(tmp_path, seed_):
    """Satellite property: interrupt the controller between window
    publication and watermark advance at randomized sweeps; every
    restart resumes from durable state alone and the final baselines
    are bitwise an uninterrupted control run's, with every window
    published exactly once."""
    rng = np.random.default_rng(seed_)
    control_dir = tmp_path / "control"
    chaos_dir = tmp_path / "chaos"
    for phase_snapshots in (2, 3, 4):
        seed(control_dir, snapshots=phase_snapshots)
        seed(chaos_dir, snapshots=phase_snapshots)
        drain(control_dir)
        drain(chaos_dir, rng=rng, kill_p=0.6)

    control = make_controller(control_dir)
    chaos = make_controller(chaos_dir)
    wm_control = control.watermarks.load(0, VERSION)
    wm_chaos = chaos.watermarks.load(0, VERSION)
    assert wm_chaos["block"] == wm_control["block"]
    assert wm_chaos["epochs"] == wm_control["epochs"]
    # Window splits may differ (a killed window re-coalesces with later
    # appends) but the full-prefix baseline is keyed on the timeline
    # fingerprint: identical key -> identical inputs, and the payload
    # must be bitwise identical too.
    assert wm_chaos["baseline_key"] == wm_control["baseline_key"]
    a = chaos.cache.load_baseline(wm_chaos["baseline_key"])
    b = control.cache.load_baseline(wm_control["baseline_key"])
    assert np.array_equal(a["dividends"], b["dividends"])

    # Exactly-once publication: no (block span) swept twice, and the
    # watermark history is strictly monotone through every crash.
    swept = chaos.ledger.entries("window_swept")
    spans = [(r["block_from"], r["block_to"]) for r in swept]
    assert len(spans) == len(set(spans))
    history = [
        r["block"]
        for r in chaos.watermarks.history(0, VERSION)
        if isinstance(r.get("block"), int)
    ]
    assert history == sorted(set(history))


# ------------------------------------------------- concurrent access

_APPENDER = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from yuma_simulation_tpu.replay.archive import (
    SnapshotArchive, synthetic_timeline,
)
archive = SnapshotArchive(sys.argv[1])
# One snapshot at a time so the two processes interleave at every
# block: each append is a full read-modify-write of the index.
for k in range(1, 13):
    synthetic_timeline(
        archive, 0, snapshots=k, seed=11,
        num_validators=3, num_miners=4,
    )
print("appender done", flush=True)
"""


def test_converging_appenders_race_reader(tmp_path):
    """Two real processes append the SAME 12-snapshot sequence to one
    subnet (idempotent convergence — the cross-process append lock's
    lost-update case) while this process reads the timeline and
    digest-verifies blobs throughout. No torn index, no lost entry,
    no unverifiable blob at any instant."""
    archive_dir = tmp_path / "archive"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(pathlib.Path(__file__).parents[2])
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _APPENDER, str(archive_dir)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for _ in range(2)
    ]
    archive = SnapshotArchive(archive_dir)
    deadline = time.time() + 120
    try:
        while any(p.poll() is None for p in procs):
            assert time.time() < deadline, "appenders hung"
            # Reader invariants mid-race: monotone blocks, every
            # indexed blob digest-verifies (blob-before-index order).
            for netuid in archive.subnets():
                timeline = archive.timeline(netuid)
                blocks = [e.block for e in timeline]
                assert blocks == sorted(set(blocks))
                if timeline:
                    archive.load(netuid, timeline[-1].block)
            time.sleep(0.02)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    outs = [p.communicate()[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    final = archive.timeline(0)
    assert [e.block for e in final] == [
        1000 + i * 100 for i in range(12)
    ]
    for e in final:
        archive.load(0, e.block)  # every blob sound after the race


def test_history_rewrite_rejected_across_processes(tmp_path):
    """A process trying to re-archive a block with DIFFERENT bytes is
    rejected with the typed error even when the original writer was
    another process."""
    seed(tmp_path, snapshots=2, seed_=11)
    code = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from yuma_simulation_tpu.replay.archive import (
    ArchiveError, SnapshotArchive,
)
from yuma_simulation_tpu.foundry.metagraph import synthetic_snapshot
archive = SnapshotArchive(sys.argv[1])
snap = synthetic_snapshot(
    999, num_validators=3, num_miners=4, netuid=0, block=1100,
)
try:
    archive.append(snap)
except ArchiveError as exc:
    assert "different contents" in str(exc), exc
    print("rejected", flush=True)
    sys.exit(0)
sys.exit(1)
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(pathlib.Path(__file__).parents[2])
    proc = subprocess.run(
        [sys.executable, "-c", code, str(tmp_path / "archive")],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rejected" in proc.stdout


def test_torn_blob_injection_is_detected(tmp_path):
    """The soak's corruption injector publishes an entry whose blob
    cannot verify — and never heals through idempotent re-appends."""
    from yuma_simulation_tpu.foundry.metagraph import synthetic_snapshot
    from yuma_simulation_tpu.replay.soak import _append_torn

    archive = SnapshotArchive(tmp_path / "archive")
    synthetic_timeline(
        archive, 0, snapshots=2, seed=11, num_validators=3, num_miners=4
    )
    snap = synthetic_snapshot(
        13, num_validators=3, num_miners=4, netuid=0, block=1200
    )
    _append_torn(archive, snap)
    with pytest.raises(ArchiveError, match="corruption"):
        archive.load(0, 1200)
    # The writer's later idempotent rounds re-append the same snapshot;
    # the matching index key must no-op, not republish sound bytes.
    archive.append(snap)
    with pytest.raises(ArchiveError, match="corruption"):
        archive.load(0, 1200)
