"""ApiVer surface lock: the v1 contract the reference intended to test
(reference tests/unit/api/test_setup.py asserts the v1 module exports
nothing; its api module surface is frozen)."""


def test_v1_namespace_exports_nothing():
    import yuma_simulation.v1 as compat_v1
    import yuma_simulation_tpu.v1 as tpu_v1

    for mod in (compat_v1, tpu_v1):
        assert [n for n in vars(mod) if not n.startswith("__")] in ([], ["api"])


_FROZEN_SURFACE = [
    "HTML",
    "Scenario",
    "SimulationClient",
    "SimulationHyperparameters",
    # -- chain replay (0.18.0, additive): the snapshot-timeline
    # archive, the epoch-state cache, what-if specs, and the
    # trailing-window fleet sweep.
    "SnapshotArchive",
    "StateCache",
    "WhatIfSpec",
    "YumaConfig",
    "YumaParams",
    "YumaSimulationNames",
    # -- scenario foundry (0.16.0, additive): the DSL compiler, the
    # metagraph snapshot loader, and the adversarial family builders.
    "cartel_scenario",
    "compile_spec",
    "generate_chart_table",
    "generate_total_dividends_table",
    "load_metagraph_snapshot",
    "run_simulation",
    "serve",
    "stake_churn_scenario",
    "sweep_trailing_window",
    "takeover_scenario",
    "weight_copier_scenario",
]


def test_v1_surface_growth_is_additive():
    """0.16.0 grew the surface; the 0.15.0 names must all survive (the
    ApiVer contract is additive-only growth)."""
    for name in (
        "HTML", "Scenario", "SimulationClient",
        "SimulationHyperparameters", "YumaConfig", "YumaParams",
        "YumaSimulationNames", "generate_chart_table",
        "generate_total_dividends_table", "run_simulation", "serve",
    ):
        assert name in _FROZEN_SURFACE


def test_v1_api_surface_is_frozen():
    from yuma_simulation_tpu.v1 import api

    public = sorted(
        n for n, v in vars(api).items()
        if not n.startswith("_") and (callable(v) or isinstance(v, type))
    )
    assert public == _FROZEN_SURFACE, public
    assert sorted(api.__all__) == _FROZEN_SURFACE


def test_compat_v1_api_surface_is_frozen():
    from yuma_simulation.v1 import api

    public = sorted(
        n for n, v in vars(api).items()
        if not n.startswith("_") and (callable(v) or isinstance(v, type))
    )
    assert public == _FROZEN_SURFACE, public
    assert sorted(api.__all__) == _FROZEN_SURFACE
