"""Multi-chip behavior on the virtual 8-device CPU mesh.

The driver separately dry-runs `__graft_entry__.dryrun_multichip`; these
tests pin the same guarantees in-suite (SURVEY.md §4: the CPU-mesh mode
replaces the reference's absent fake-backend layer): sharded results are
identical to unsharded, Monte-Carlo generation composes with `shard_map`,
and the miner-axis GSPMD path reproduces the single-device kernel.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.epoch import BondsMode, yuma_epoch
from yuma_simulation_tpu.parallel import (
    make_hybrid_mesh,
    make_mesh,
    montecarlo_total_dividends,
    shard_epoch_over_miners,
    simulate_batch_sharded,
)
from yuma_simulation_tpu.scenarios import get_cases
from yuma_simulation_tpu.simulation.sweep import total_dividends_batch


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return make_mesh()  # data=8, model=1


def test_sharded_batch_matches_vmap(mesh8):
    cases = get_cases()
    out = simulate_batch_sharded(cases, "Yuma 1 (paper)", mesh=mesh8)
    ref = total_dividends_batch(cases, "Yuma 1 (paper)")
    np.testing.assert_allclose(
        out["dividends"].sum(axis=1), ref, rtol=1e-5, atol=1e-6
    )


def test_sharded_batch_pads_uneven(mesh8):
    cases = get_cases()[:5]  # 5 scenarios over 8 shards -> pad to 8, trim back
    out = simulate_batch_sharded(cases, "Yuma 2 (Adrian-Fish)", mesh=mesh8)
    assert out["dividends"].shape[0] == 5
    ref = total_dividends_batch(cases, "Yuma 2 (Adrian-Fish)")
    np.testing.assert_allclose(
        out["dividends"].sum(axis=1), ref, rtol=1e-5, atol=1e-6
    )


def test_montecarlo_sharded(mesh8):
    got = montecarlo_total_dividends(
        jax.random.key(0), 16, 8, 4, 8, "Yuma 1 (paper)", mesh=mesh8
    )
    assert got.shape == (16, 4)
    assert np.isfinite(got).all()
    # Same key, same result (deterministic across shardings of the batch).
    again = montecarlo_total_dividends(
        jax.random.key(0), 16, 8, 4, 8, "Yuma 1 (paper)", mesh=mesh8
    )
    np.testing.assert_array_equal(got, again)


def test_montecarlo_batch_indivisible_raises(mesh8):
    with pytest.raises(ValueError, match="divide"):
        montecarlo_total_dividends(
            jax.random.key(0), 13, 4, 4, 8, "Yuma 1 (paper)", mesh=mesh8
        )


@pytest.mark.parametrize(
    "mode", [BondsMode.EMA, BondsMode.CAPACITY, BondsMode.RELATIVE]
)
def test_miner_axis_sharding_matches_single_device(mode):
    mesh = make_mesh(data=1, model=8)
    rng = np.random.default_rng(5)
    W = rng.random((4, 16)).astype(np.float32)
    S = np.asarray([0.4, 0.3, 0.2, 0.1], np.float32)
    B = (rng.random((4, 16)) * (1e18 if mode is BondsMode.CAPACITY else 0.5)).astype(
        np.float32
    )
    cfg = YumaConfig()
    sharded = shard_epoch_over_miners(W, S, B, cfg, mesh=mesh, bonds_mode=mode)
    ref = yuma_epoch(jnp.asarray(W), jnp.asarray(S), jnp.asarray(B), cfg, bonds_mode=mode)
    for key in ("server_consensus_weight", "server_incentive", "validator_reward"):
        np.testing.assert_allclose(
            np.asarray(sharded[key]), np.asarray(ref[key]), rtol=1e-5, atol=1e-6,
            err_msg=key,
        )


def test_mesh_shapes():
    m = make_mesh(data=4, model=2)
    assert dict(m.shape) == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(data=3, model=2)
    # single-slice environment falls back to a flat mesh
    h = make_hybrid_mesh(model=2)
    assert dict(h.shape) == {"data": 4, "model": 2}


def test_graft_entry_dryrun():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 16)
    __graft_entry__.dryrun_multichip(8)
