"""Multi-chip behavior on the virtual 8-device CPU mesh.

The driver separately dry-runs `__graft_entry__.dryrun_multichip`; these
tests pin the same guarantees in-suite (SURVEY.md §4: the CPU-mesh mode
replaces the reference's absent fake-backend layer): sharded results are
identical to unsharded, Monte-Carlo generation composes with `shard_map`,
and the miner-axis GSPMD path reproduces the single-device kernel.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.models.config import (
    YumaConfig,
    YumaParams,
    YumaSimulationNames,
)
from yuma_simulation_tpu.models.epoch import BondsMode, yuma_epoch
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.parallel import (
    make_hybrid_mesh,
    make_mesh,
    montecarlo_total_dividends,
    shard_epoch_over_miners,
    simulate_batch_sharded,
)
from yuma_simulation_tpu.scenarios import get_cases
from yuma_simulation_tpu.scenarios.synthetic import random_subnet_scenario
from yuma_simulation_tpu.simulation.engine import simulate, simulate_constant
from yuma_simulation_tpu.simulation.sweep import total_dividends_batch

_NAMES = YumaSimulationNames()


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return make_mesh()  # data=8, model=1


def test_sharded_batch_matches_vmap(mesh8):
    cases = get_cases()
    out = simulate_batch_sharded(cases, "Yuma 1 (paper)", mesh=mesh8)
    ref = total_dividends_batch(cases, "Yuma 1 (paper)")
    np.testing.assert_allclose(
        out["dividends"].sum(axis=1), ref, rtol=1e-5, atol=1e-6
    )


def test_sharded_batch_pads_uneven(mesh8):
    cases = get_cases()[:5]  # 5 scenarios over 8 shards -> pad to 8, trim back
    out = simulate_batch_sharded(cases, "Yuma 2 (Adrian-Fish)", mesh=mesh8)
    assert out["dividends"].shape[0] == 5
    ref = total_dividends_batch(cases, "Yuma 2 (Adrian-Fish)")
    np.testing.assert_allclose(
        out["dividends"].sum(axis=1), ref, rtol=1e-5, atol=1e-6
    )


def test_montecarlo_sharded(mesh8):
    got = montecarlo_total_dividends(
        jax.random.key(0), 16, 8, 4, 8, "Yuma 1 (paper)", mesh=mesh8
    )
    assert got.shape == (16, 4)
    assert np.isfinite(got).all()
    # Same key, same result (deterministic across shardings of the batch).
    again = montecarlo_total_dividends(
        jax.random.key(0), 16, 8, 4, 8, "Yuma 1 (paper)", mesh=mesh8
    )
    np.testing.assert_array_equal(got, again)


def test_montecarlo_batch_pads_and_trims(mesh8):
    # r4 verdict weak item 6: one batch contract for both entry points —
    # indivisible scenario counts are padded up and trimmed, matching
    # simulate_batch_sharded, not raised on.
    got13 = montecarlo_total_dividends(
        jax.random.key(0), 13, 4, 4, 8, "Yuma 1 (paper)", mesh=mesh8
    )
    assert got13.shape == (13, 4)
    got16 = montecarlo_total_dividends(
        jax.random.key(0), 16, 4, 4, 8, "Yuma 1 (paper)", mesh=mesh8
    )
    np.testing.assert_array_equal(got13, got16[:13])


@pytest.mark.parametrize(
    "version", ["Yuma 1 (paper)", "Yuma 2 (Adrian-Fish)"],
    ids=["yuma1", "yuma2"],
)
def test_montecarlo_per_epoch_weights_matches_engine_oracle(mesh8, version):
    """r4 verdict item 4: the epoch-VARYING Monte-Carlo (fresh
    perturbation every epoch, generated on device inside the shard) must
    reproduce, scenario by scenario, the engine's XLA scan run on the
    identical host-materialized `[E, V, M]` stack — same key discipline
    (`fold_in(scenario_key, epoch)`), same full per-epoch kernel."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.scenarios.base import Scenario
    from yuma_simulation_tpu.simulation.engine import simulate

    E, V, M = 6, 4, 16
    rng = np.random.default_rng(9)
    base_W = jnp.asarray(rng.random((V, M)), jnp.float32)
    base_S = jnp.asarray(rng.random(V) + 0.1, jnp.float32)
    pert = 0.05
    key = jax.random.key(3)
    got = montecarlo_total_dividends(
        key, 16, E, V, M, version, mesh=mesh8,
        base_weights=base_W, base_stakes=base_S, perturbation=pert,
        weights_mode="per_epoch", consensus_impl="bisect",
    )
    assert got.shape == (16, V) and np.isfinite(got).all()
    # Oracle for the first scenario of the first two shards: rebuild the
    # per-epoch weights with the same fold_in discipline and run the
    # monolithic engine.
    shard_keys = jax.random.split(key, 8)
    for shard in (0, 1):
        k = jax.random.split(shard_keys[shard], 2)[0]
        W_e = np.stack(
            [
                np.asarray(
                    jax.nn.relu(
                        base_W
                        + pert
                        * jax.random.normal(
                            jax.random.fold_in(k, e), (V, M), jnp.float32
                        )
                    )
                )
                for e in range(E)
            ]
        )
        scen = Scenario(
            name="oracle",
            validators=[f"v{i}" for i in range(V)],
            base_validator="v0",
            weights=W_e,
            stakes=np.broadcast_to(np.asarray(base_S), (E, V)).copy(),
            num_epochs=E,
        )
        res = simulate(
            scen, version, epoch_impl="xla", consensus_impl="bisect",
            save_bonds=False, save_incentives=False,
        )
        np.testing.assert_array_equal(
            got[shard * 2], res.dividends.sum(axis=0),
            err_msg=f"{version} shard {shard}",
        )


def test_montecarlo_per_epoch_rejects_hoisted(mesh8):
    with pytest.raises(ValueError, match="hoistable"):
        montecarlo_total_dividends(
            jax.random.key(0), 16, 4, 4, 8, "Yuma 1 (paper)", mesh=mesh8,
            weights_mode="per_epoch", epoch_impl="hoisted",
        )
    with pytest.raises(ValueError, match="weights_mode"):
        montecarlo_total_dividends(
            jax.random.key(0), 16, 4, 4, 8, "Yuma 1 (paper)", mesh=mesh8,
            weights_mode="sometimes",
        )


def test_montecarlo_impl_knobs(mesh8):
    # Verdict r2 item 7: the consensus/epoch implementation knobs are
    # exposed. sorted and bisect consensus are bitwise twins (fuzz
    # battery), so forcing either must not change the result; the full
    # per-epoch kernel agrees with the hoisted recurrence to rounding.
    base = montecarlo_total_dividends(
        jax.random.key(1), 16, 8, 4, 8, "Yuma 1 (paper)", mesh=mesh8
    )
    for ci in ("sorted", "bisect"):
        forced = montecarlo_total_dividends(
            jax.random.key(1), 16, 8, 4, 8, "Yuma 1 (paper)",
            mesh=mesh8, consensus_impl=ci,
        )
        np.testing.assert_array_equal(base, forced)
    full = montecarlo_total_dividends(
        jax.random.key(1), 16, 8, 4, 8, "Yuma 1 (paper)",
        mesh=mesh8, epoch_impl="xla",
    )
    np.testing.assert_allclose(base, full, rtol=1e-5, atol=1e-6)
    for kw in (dict(consensus_impl="nope"), dict(epoch_impl="nope")):
        with pytest.raises(ValueError, match="unknown"):
            montecarlo_total_dividends(
                jax.random.key(1), 16, 8, 4, 8, "Yuma 1 (paper)",
                mesh=mesh8, **kw,
            )


def test_montecarlo_shape_gated_consensus_default():
    # The "auto" default switches to bisection at the documented
    # sorted-compile-pathology threshold (DESIGN.md; 512x8192 cells).
    from yuma_simulation_tpu.ops.consensus import (
        SORTED_COMPILE_PATHOLOGY_CELLS,
        default_consensus_impl,
    )

    assert default_consensus_impl(4, 8) == "sorted"
    assert default_consensus_impl(256, 4096) == "sorted"
    assert default_consensus_impl(512, 8192) == "bisect"
    assert default_consensus_impl(8192, 65536) == "bisect"
    assert 512 * 8192 == SORTED_COMPILE_PATHOLOGY_CELLS


@pytest.mark.parametrize(
    "mode", [BondsMode.EMA, BondsMode.CAPACITY, BondsMode.RELATIVE]
)
def test_miner_axis_sharding_matches_single_device(mode):
    mesh = make_mesh(data=1, model=8)
    rng = np.random.default_rng(5)
    W = rng.random((4, 16)).astype(np.float32)
    S = np.asarray([0.4, 0.3, 0.2, 0.1], np.float32)
    B = (rng.random((4, 16)) * (1e18 if mode is BondsMode.CAPACITY else 0.5)).astype(
        np.float32
    )
    cfg = YumaConfig()
    sharded = shard_epoch_over_miners(W, S, B, cfg, mesh=mesh, bonds_mode=mode)
    ref = yuma_epoch(jnp.asarray(W), jnp.asarray(S), jnp.asarray(B), cfg, bonds_mode=mode)
    for key in ("server_consensus_weight", "server_incentive", "validator_reward"):
        np.testing.assert_allclose(
            np.asarray(sharded[key]), np.asarray(ref[key]), rtol=1e-5, atol=1e-6,
            err_msg=key,
        )


@pytest.mark.parametrize(
    "version,params",
    [
        # Liquid alpha exercises the cross-shard quantile sort (VERDICT #5).
        (_NAMES.YUMA_LIQUID, YumaParams(liquid_alpha=True)),
        (_NAMES.YUMA2, YumaParams()),
        (_NAMES.YUMA3, YumaParams()),
        (
            _NAMES.YUMA4_LIQUID,
            YumaParams(
                liquid_alpha=True,
                bond_alpha=0.025,
                alpha_high=0.99,
                alpha_low=0.9,
            ),
        ),
    ],
    ids=["yuma1-liquid", "yuma2", "yuma3", "yuma4-liquid"],
)
def test_miner_sharded_simulate_matches_unsharded(version, params):
    """40-epoch scanned simulation with the miner axis sharded over 8
    devices is BITWISE the single-device run (r4 verdict item 2: "same
    program, same answer, any mesh"). The order-dependent cross-shard
    reductions are gone: the consensus support test and the u16
    quantization sum run on exact canonical integers (ops/consensus.py),
    and every remaining f32 miner-axis sum uses the partition-invariant
    miner_sum spelling (ops/normalize.py) — fixed block partials plus an
    explicit add chain that XLA cannot reassociate."""
    scen = random_subnet_scenario(
        11, num_validators=4, num_miners=32, num_epochs=40
    )
    cfg = YumaConfig(yuma_params=params)
    ref = simulate(scen, version, cfg, save_consensus=True, epoch_impl="xla")
    for shards in (2, 8):
        mesh = make_mesh(data=8 // shards, model=shards)
        got = simulate(scen, version, cfg, save_consensus=True, mesh=mesh)
        for name in ("dividends", "bonds", "incentives", "consensus"):
            np.testing.assert_array_equal(
                getattr(got, name),
                getattr(ref, name),
                err_msg=f"{version} x{shards}: {name}",
            )


@pytest.mark.parametrize("hoist", [False, True], ids=["full", "hoisted"])
def test_miner_sharded_simulate_constant_matches(hoist):
    mesh = make_mesh(data=1, model=8)
    rng = np.random.default_rng(3)
    W = jnp.asarray(rng.random((4, 32)), jnp.float32)
    S = jnp.asarray([0.5, 0.25, 0.15, 0.1], jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version(_NAMES.YUMA)
    total_ref, B_ref = simulate_constant(
        W, S, 40, cfg, spec, hoist_invariant=hoist
    )
    total, B = simulate_constant(
        W, S, 40, cfg, spec, hoist_invariant=hoist, mesh=mesh
    )
    # Bitwise, like the scanned-engine mesh contract (r4 verdict item 2).
    np.testing.assert_array_equal(np.asarray(total), np.asarray(total_ref))
    np.testing.assert_array_equal(np.asarray(B), np.asarray(B_ref))


def test_mesh_shapes():
    m = make_mesh(data=4, model=2)
    assert dict(m.shape) == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(data=3, model=2)
    # single-slice environment falls back to a flat mesh
    h = make_hybrid_mesh(model=2)
    assert dict(h.shape) == {"data": 4, "model": 2}


def test_graft_entry_dryrun():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 16)
    __graft_entry__.dryrun_multichip(8)
