"""Drop-in compatibility package for the reference `yuma_simulation`.

Users of the reference package can keep their import paths: the module
paths, public names and signatures mirror the reference's layout (`yuma_simulation.v1.api`,
`yuma_simulation._internal.{yumas,cases,simulation_utils,charts_utils}` —
reference src/yuma_simulation/), every entry point backed by the
JAX/XLA/Pallas engine in :mod:`yuma_simulation_tpu`.

Caveat (see MIGRATION.md): kernels *accept* torch tensors but *return*
JAX arrays — downstream code that calls torch-only methods on outputs
(``.clone()``, ``.item()`` chains as in the reference's own driver,
reference simulation_utils.py:102-109) needs the small edits MIGRATION.md
lists. "Drop-in" covers import paths and call signatures, not torch-typed
return values.

The reference's top-level ``__init__`` is empty (ApiVer contract,
reference README.md:10-18); so is this one.
"""
