"""Drop-in compatibility package for the reference `yuma_simulation`.

Users of the reference package can switch to the TPU framework without
changing imports: the module paths, public names and signatures mirror the
reference's layout (`yuma_simulation.v1.api`,
`yuma_simulation._internal.{yumas,cases,simulation_utils,charts_utils}` —
reference src/yuma_simulation/), every entry point backed by the
JAX/XLA/Pallas engine in :mod:`yuma_simulation_tpu`.

The reference's top-level ``__init__`` is empty (ApiVer contract,
reference README.md:10-18); so is this one.
"""
