"""Reference-compatible `_internal.simulation_utils`
(reference simulation_utils.py), TPU-backed.

`run_simulation` keeps the reference's signature and return triple
(simulation_utils.py:26-112); the table builders keep their underscored
names (115-316); `generate_total_dividends_table` matches 319-381.
"""

from yuma_simulation_tpu.reporting.tables import (
    generate_draggable_html_table as _generate_draggable_html_table,  # noqa: F401
    generate_ipynb_table as _generate_ipynb_table,  # noqa: F401
    generate_total_dividends_table,  # noqa: F401
)
from yuma_simulation_tpu.simulation.engine import run_simulation  # noqa: F401
