"""Reference-compatible `_internal.charts_utils`
(reference charts_utils.py), TPU-backed.

The four plotters keep their underscored reference names and signatures
(charts_utils.py:48, 125, 201, 304); `_calculate_total_dividends` matches
15-45.
"""

from yuma_simulation_tpu.reporting.charts import (
    plot_bonds as _plot_bonds,  # noqa: F401
    plot_dividends as _plot_dividends,  # noqa: F401
    plot_incentives as _plot_incentives,  # noqa: F401
    plot_validator_server_weights as _plot_validator_server_weights,  # noqa: F401
)
from yuma_simulation_tpu.reporting.tables import (
    calculate_total_dividends as _calculate_total_dividends,  # noqa: F401
)
