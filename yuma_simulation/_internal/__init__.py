"""Internal namespace mirror (empty, as the reference's
_internal/__init__.py)."""
