"""Reference-compatible `_internal.cases` (reference cases.py), TPU-backed.

`cases` is the instantiated default suite in registration order
(cases.py:601); `register_case` / `create_case` / `class_registry` mirror
the factory API (cases.py:6-48). `BaseCase` aliases the dense-array
`Scenario` spec, which still exposes the reference's `weights_epochs` /
`stakes_epochs` list-of-arrays views (cases.py:27-35).
"""

from yuma_simulation_tpu.scenarios import (  # noqa: F401
    BaseCase,
    Scenario,
    cases,
    class_registry,
    create_case,
    get_cases,
    register_case,
)
