"""Reference-compatible `_internal.yumas` (reference yumas.py), TPU-backed.

The five kernel functions keep the reference call signatures
(yumas.py:61, 175, 285, 399, 494) and return the same named-output dicts
(as jax arrays rather than torch tensors).
"""

from yuma_simulation_tpu.models.config import (  # noqa: F401
    SimulationHyperparameters,
    YumaConfig,
    YumaParams,
    YumaSimulationNames,
)
from yuma_simulation_tpu.models.variants import (  # noqa: F401
    Yuma,
    Yuma2,
    Yuma3,
    Yuma4,
    YumaRust,
)
