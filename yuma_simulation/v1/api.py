"""Reference-compatible `yuma_simulation.v1.api`, TPU-backed.

Same public surface as the reference (reference v1/api.py:24-132):
`generate_chart_table(cases, yuma_versions, yuma_hyperparameters,
draggable_table) -> IPython HTML`, plus the promotions the new framework
makes public (`generate_total_dividends_table`, `run_simulation`).
"""

from yuma_simulation_tpu.v1.api import (  # noqa: F401
    HTML,
    Scenario,
    SimulationClient,
    SimulationHyperparameters,
    SnapshotArchive,
    StateCache,
    WhatIfSpec,
    YumaConfig,
    YumaParams,
    YumaSimulationNames,
    cartel_scenario,
    compile_spec,
    generate_chart_table,
    generate_total_dividends_table,
    load_metagraph_snapshot,
    run_simulation,
    serve,
    stake_churn_scenario,
    sweep_trailing_window,
    takeover_scenario,
    weight_copier_scenario,
)

__all__ = [
    "HTML",
    "Scenario",
    "SimulationClient",
    "SimulationHyperparameters",
    "SnapshotArchive",
    "StateCache",
    "WhatIfSpec",
    "YumaConfig",
    "YumaParams",
    "YumaSimulationNames",
    "cartel_scenario",
    "compile_spec",
    "generate_chart_table",
    "generate_total_dividends_table",
    "load_metagraph_snapshot",
    "run_simulation",
    "serve",
    "stake_churn_scenario",
    "sweep_trailing_window",
    "takeover_scenario",
    "weight_copier_scenario",
]
