"""ApiVer v1 namespace (empty module docstring, as the reference's
v1/__init__.py:1-3)."""
