"""Scenario spec + registry.

The reference expresses each scenario as a dataclass whose
`weights_epochs` / `stakes_epochs` *properties* rebuild a list of per-epoch
tensors on every access (reference cases.py:16-48, an O(E^2) pathology in
the epoch loop). Here a scenario is plain data: dense arrays
`weights[E, V, M]` / `stakes[E, V]` built exactly once, which is also what
`lax.scan` wants as its stacked inputs and what `vmap` wants for batched
suites. The registry (`register_case` / `create_case` / `class_registry`)
mirrors the reference's string-keyed factory API (cases.py:6-48).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Callable, Optional

import numpy as np

# name -> builder callable (mirrors reference cases.py:6)
class_registry: dict[str, Callable[..., "Scenario"]] = {}


def register_case(name: str):
    """Register a scenario builder under a case name (cases.py:9-14)."""

    def decorator(builder):
        class_registry[name] = builder
        return builder

    return decorator


def create_case(case_name: str, **kwargs) -> "Scenario":
    """Instantiate a registered case by name (cases.py:44-48)."""
    if case_name not in class_registry:
        raise ValueError(f"Case '{case_name}' is not registered.")
    return class_registry[case_name](**kwargs)


#: Materialized builtin-case cache keyed by the registry's
#: (name, builder) pairs: re-registering a name under a NEW builder (or
#: registering a new case) invalidates it, a plain repeat call reuses
#: the built arrays. `get_cases` COPIES on return.
_CASES_CACHE: dict = {}


def get_cases() -> list["Scenario"]:
    """All registered cases, in registration order (cases.py:601).

    Before 0.16.0 every call re-invoked every registered builder — an
    O(cases) array-construction bill per call that callers paid dozens
    of times per process (the chart suite, every drill, every test
    module importing `scenarios.cases`). The materialized suite is now
    memoized per registry state, and each call returns equal-but-
    INDEPENDENT scenarios (fresh array copies), so a caller mutating
    its suite — padding in place, fault injection — cannot poison the
    cache for the next caller."""
    key = tuple(class_registry.items())
    if key not in _CASES_CACHE:
        _CASES_CACHE.clear()
        _CASES_CACHE[key] = [
            builder() for builder in class_registry.values()
        ]
    return [
        replace(
            s,
            weights=s.weights.copy(),
            stakes=s.stakes.copy(),
            validators=list(s.validators),
            servers=list(s.servers),
        )
        for s in _CASES_CACHE[key]
    ]


class ScenarioValidationError(ValueError):
    """A scenario whose arrays violate the foundry's input contract."""


@dataclass
class Scenario:
    """A fully materialized scenario: dense arrays + display metadata."""

    name: str
    validators: list[str]
    base_validator: str
    weights: np.ndarray  # [E, V, M] float32
    stakes: np.ndarray  # [E, V] float32
    num_epochs: int = 40
    reset_bonds: bool = False
    reset_bonds_index: Optional[int] = None
    reset_bonds_epoch: Optional[int] = None
    servers: list[str] = field(default_factory=lambda: ["Server 1", "Server 2"])
    #: Whether chart tables add the server-incentives row for this case.
    #: The reference keys this off positional indices 9/10 of the full
    #: suite (reference v1/api.py:42-45) — i.e. Cases 10 and 11; carrying
    #: it on the scenario makes it survive case subsets/reordering.
    plot_incentives: bool = False

    def __post_init__(self):
        if self.base_validator not in self.validators:
            raise ValueError(
                f"base_validator '{self.base_validator}' must be in validators list."
            )
        self.weights = np.asarray(self.weights, np.float32)
        self.stakes = np.asarray(self.stakes, np.float32)
        E, V, M = self.weights.shape
        if E != self.num_epochs or self.stakes.shape != (E, V):
            raise ValueError(
                f"inconsistent scenario arrays: weights {self.weights.shape}, "
                f"stakes {self.stakes.shape}, num_epochs {self.num_epochs}"
            )

    @property
    def num_validators(self) -> int:
        return self.weights.shape[1]

    @property
    def num_miners(self) -> int:
        return self.weights.shape[2]

    def validate(
        self,
        *,
        normalized: bool = False,
        normalization_tol: float = 1e-3,
    ) -> "Scenario":
        """The foundry input contract: every generated scenario passes
        through here before it can reach an engine (compile_spec,
        snapshot ingestion, the adversarial builders), so a generator
        bug surfaces as a typed :class:`ScenarioValidationError` with
        provenance instead of a NaN-poisoned batch reduction three
        layers down.

        Checks: weights finite and non-negative; stakes finite and
        non-negative; at least one epoch with positive total stake.
        `normalized=True` additionally requires every non-zero weight
        row to sum to 1 within `normalization_tol` (DSL outputs are
        row-normalized by construction; raw chain snapshots normalize
        during ingestion). Returns self for fluent use."""
        W, S = self.weights, self.stakes
        if not np.isfinite(W).all():
            bad = np.argwhere(~np.isfinite(W))[0]
            raise ScenarioValidationError(
                f"scenario {self.name!r}: non-finite weight at "
                f"(epoch, validator, miner)={tuple(int(i) for i in bad)}"
            )
        if (W < 0).any():
            bad = np.argwhere(W < 0)[0]
            raise ScenarioValidationError(
                f"scenario {self.name!r}: negative weight at "
                f"(epoch, validator, miner)={tuple(int(i) for i in bad)}"
            )
        if not np.isfinite(S).all():
            bad = np.argwhere(~np.isfinite(S))[0]
            raise ScenarioValidationError(
                f"scenario {self.name!r}: non-finite stake at "
                f"(epoch, validator)={tuple(int(i) for i in bad)}"
            )
        if (S < 0).any():
            bad = np.argwhere(S < 0)[0]
            raise ScenarioValidationError(
                f"scenario {self.name!r}: negative stake at "
                f"(epoch, validator)={tuple(int(i) for i in bad)}"
            )
        if not (S.sum(axis=1) > 0).any():
            raise ScenarioValidationError(
                f"scenario {self.name!r}: zero total stake in every epoch"
            )
        if normalized:
            row_sums = W.sum(axis=2)
            off = np.abs(row_sums - 1.0) > normalization_tol
            bad_rows = off & (row_sums != 0.0)
            if bad_rows.any():
                e, v = (int(i) for i in np.argwhere(bad_rows)[0])
                raise ScenarioValidationError(
                    f"scenario {self.name!r}: weight row (epoch {e}, "
                    f"validator {v}) sums to {float(row_sums[e, v]):.6g}, "
                    f"not 1 within {normalization_tol}"
                )
        return self

    # --- reference-compatible list-of-tensors views (cases.py:27-35) ---
    @property
    def weights_epochs(self) -> list[np.ndarray]:
        return list(self.weights)

    @property
    def stakes_epochs(self) -> list[np.ndarray]:
        return list(self.stakes)


#: Back-compat alias: the reference's scenario base class name.
BaseCase = Scenario


def assignment_weights(
    num_epochs: int,
    num_validators: int,
    num_miners: int,
    schedule: list[tuple[range, list[int]]],
) -> np.ndarray:
    """Build `[E, V, M]` one-hot weights from (epoch-range -> server index
    per validator) rules; later rules win on overlap."""
    W = np.zeros((num_epochs, num_validators, num_miners), np.float32)
    for epochs, servers in schedule:
        for e in epochs:
            if 0 <= e < num_epochs:
                W[e] = 0.0
                for v, m in enumerate(servers):
                    W[e, v, m] = 1.0
    return W


def row_weights(
    num_epochs: int,
    schedule: list[tuple[range, list[list[float]]]],
) -> np.ndarray:
    """Build `[E, V, M]` weights from explicit per-epoch row matrices."""
    first = np.asarray(schedule[0][1], np.float32)
    W = np.zeros((num_epochs,) + first.shape, np.float32)
    for epochs, rows in schedule:
        mat = np.asarray(rows, np.float32)
        for e in epochs:
            if 0 <= e < num_epochs:
                W[e] = mat
    return W


def constant_stakes(num_epochs: int, stakes: list[float]) -> np.ndarray:
    return np.tile(np.asarray(stakes, np.float32), (num_epochs, 1))
