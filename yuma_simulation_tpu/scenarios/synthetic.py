"""Synthetic scenario generators: random subnets & Monte-Carlo perturbations.

The reference has no synthetic scenarios (its 14 cases are hand-written);
these generators feed the sweep/Monte-Carlo configurations in BASELINE.json
(8192 randomized weight-perturbation scenarios sharded over a pod). Weight
batches are generated with `jax.random` so they can be produced directly on
device inside a sharded computation.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from yuma_simulation_tpu.scenarios.base import Scenario


def random_subnet_scenario(
    seed: int,
    num_validators: int = 16,
    num_miners: int = 256,
    num_epochs: int = 40,
    stake_concentration: float = 1.0,
    name: Optional[str] = None,
) -> Scenario:
    """A random subnet: Dirichlet-ish stakes, uniform random weight rows."""
    rng = np.random.default_rng(seed)
    stakes = rng.gamma(stake_concentration, size=num_validators).astype(np.float32)
    stakes /= stakes.sum()
    W = rng.random((num_epochs, num_validators, num_miners), dtype=np.float32)
    validators = [f"vali {i} ({stakes[i]:.3f})" for i in range(num_validators)]
    return Scenario(
        name=name or f"random subnet (seed={seed})",
        validators=validators,
        base_validator=validators[0],
        weights=W,
        stakes=np.tile(stakes, (num_epochs, 1)),
        num_epochs=num_epochs,
        servers=[f"Server {i + 1}" for i in range(num_miners)],
    )


def weight_perturbation_batch(
    key: jax.Array,
    base_weights: jnp.ndarray,
    num_scenarios: int,
    sigma: float = 0.05,
) -> jnp.ndarray:
    """`[B, V, M]` multiplicative log-normal perturbations of one weight
    matrix — the Monte-Carlo workload, generated on device."""
    noise = jax.random.normal(
        key, (num_scenarios,) + base_weights.shape, base_weights.dtype
    )
    return base_weights * jnp.exp(sigma * noise)
