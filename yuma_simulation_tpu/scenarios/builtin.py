"""The 14 built-in scenario cases, as data generators.

Behavior-parity ports of the hand-written weight schedules in reference
cases.py:51-597 (each case's docstring cites its source lines). Every case
is 3 validators x 2 miners x 40 epochs unless overridden; the epoch
schedules are expressed as range rules instead of per-epoch if-chains, and
materialize once into dense arrays.
"""

from __future__ import annotations

from yuma_simulation_tpu.scenarios.base import (
    Scenario,
    assignment_weights,
    constant_stakes,
    register_case,
    row_weights,
)

_DEFAULT_STAKES = [0.8, 0.1, 0.1]
_END = 10_000  # open-ended range sentinel, clipped to num_epochs


@register_case("Case 1")
def case_1(num_epochs: int = 40, **kw) -> Scenario:
    """Kappa moves first (reference cases.py:51-84)."""
    return Scenario(
        name="Case 1 - kappa moves first",
        validators=[
            "Big vali. (0.8)",
            "Small lazy vali. (0.1)",
            "Small lazier vali. (0.1)",
        ],
        base_validator="Big vali. (0.8)",
        num_epochs=num_epochs,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, 2), [1, 0, 0]),
                (range(2, 3), [1, 1, 0]),
                (range(3, _END), [1, 1, 1]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 2")
def case_2(num_epochs: int = 40, **kw) -> Scenario:
    """Kappa moves second (reference cases.py:87-120)."""
    return Scenario(
        name="Case 2 - kappa moves second",
        validators=[
            "Big vali. (0.8)",
            "Small eager vali. (0.1)",
            "Small lazy vali. (0.1)",
        ],
        base_validator="Small eager vali. (0.1)",
        num_epochs=num_epochs,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, 2), [0, 1, 0]),
                (range(2, 3), [1, 1, 0]),
                (range(3, _END), [1, 1, 1]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 3")
def case_3(num_epochs: int = 40, **kw) -> Scenario:
    """Kappa moves third (reference cases.py:123-156)."""
    return Scenario(
        name="Case 3 - kappa moves third",
        validators=[
            "Big vali. (0.8)",
            "Small eager vali. (0.1)",
            "Small lazy vali. (0.1)",
        ],
        base_validator="Small eager vali. (0.1)",
        num_epochs=num_epochs,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, 2), [0, 1, 0]),
                (range(2, 3), [0, 1, 1]),
                (range(3, _END), [1, 1, 1]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 4")
def case_4(num_epochs: int = 40, **kw) -> Scenario:
    """All validators switch (reference cases.py:159-188)."""
    return Scenario(
        name="Case 4 - all validators switch",
        validators=[
            "Big vali. (0.8)",
            "Small vali. (0.1)",
            "Small vali 2. (0.1)",
        ],
        base_validator="Big vali. (0.8)",
        num_epochs=num_epochs,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, _END), [1, 1, 1]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 5")
def case_5(num_epochs: int = 40, **kw) -> Scenario:
    """Kappa moves second, then third (reference cases.py:191-238)."""
    return Scenario(
        name="Case 5 - kappa moves second, then third",
        validators=[
            "Big vali. (0.8)",
            "Small eager-eager vali. (0.1)",
            "Small eager-lazy vali. (0.1)",
        ],
        base_validator="Small eager-eager vali. (0.1)",
        num_epochs=num_epochs,
        reset_bonds=True,
        reset_bonds_index=1,
        reset_bonds_epoch=20,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, 2), [0, 1, 1]),
                (range(2, 21), [1, 1, 1]),
                (range(21, 22), [1, 0, 1]),
                (range(22, 23), [1, 0, 0]),
                (range(23, _END), [0, 0, 0]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 6")
def case_6(num_epochs: int = 40, **kw) -> Scenario:
    """Kappa moves second, then all switch back (reference cases.py:241-281)."""
    return Scenario(
        name="Case 6 - kappa moves second, then all validators switch",
        validators=[
            "Big vali. (0.8)",
            "Small eager vali. (0.1)",
            "Small lazy vali. (0.1)",
        ],
        base_validator="Small eager vali. (0.1)",
        num_epochs=num_epochs,
        reset_bonds=True,
        reset_bonds_index=0,
        reset_bonds_epoch=21,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, 2), [0, 1, 0]),
                (range(2, 3), [1, 1, 0]),
                (range(3, 21), [1, 1, 1]),
                (range(21, _END), [0, 0, 0]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 7")
def case_7(num_epochs: int = 40, **kw) -> Scenario:
    """Big vali moves late, then all but one small vali move late
    (reference cases.py:284-327; note epoch 21 follows the code, not its
    comments: A->S2, B->S2, C->S1)."""
    return Scenario(
        name="Case 7 - big vali moves late, then all but one small vali moves late",
        validators=[
            "Big vali. (0.8)",
            "Small eager-lazy vali. (0.1)",
            "Small eager-eager vali. (0.1)",
        ],
        base_validator="Small eager-eager vali. (0.1)",
        num_epochs=num_epochs,
        reset_bonds=True,
        reset_bonds_index=0,
        reset_bonds_epoch=21,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, 2), [0, 1, 1]),
                (range(2, 21), [1, 1, 1]),
                (range(21, 22), [1, 1, 0]),
                (range(22, _END), [0, 0, 0]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 8")
def case_8(num_epochs: int = 40, **kw) -> Scenario:
    """Big vali moves late, then late again (reference cases.py:329-370)."""
    return Scenario(
        name="Case 8 - big vali moves late, then late",
        validators=[
            "Big dishonest lazy vali. (0.8)",
            "Small eager-eager vali. (0.1)",
            "Small eager-eager vali 2. (0.1)",
        ],
        base_validator="Small eager-eager vali. (0.1)",
        num_epochs=num_epochs,
        reset_bonds=True,
        reset_bonds_index=1,
        reset_bonds_epoch=20,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, 2), [0, 1, 1]),
                (range(2, 21), [1, 1, 1]),
                (range(21, 22), [1, 0, 0]),
                (range(22, _END), [0, 0, 0]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 9")
def case_9(num_epochs: int = 40, **kw) -> Scenario:
    """Small validators merge at epoch 6 (reference cases.py:372-403)."""
    stakes = constant_stakes(num_epochs, _DEFAULT_STAKES)
    stakes[6:] = [0.8, 0.2, 0.0]
    return Scenario(
        name="Case 9 - small validators merged in e5",
        validators=[
            "Big vali. (0.8)",
            "Small vali. (0.1/0.2)",
            "Small vali 2. (0.1/0.0)",
        ],
        base_validator="Big vali. (0.8)",
        num_epochs=num_epochs,
        weights=assignment_weights(
            num_epochs, 3, 2, [(range(0, _END), [1, 1, 1])]
        ),
        stakes=stakes,
        **kw,
    )


@register_case("Case 10")
def case_10(num_epochs: int = 40, **kw) -> Scenario:
    """Kappa delayed (reference cases.py:406-439)."""
    return Scenario(
        name="Case 10 - kappa delayed",
        validators=[
            "Big delayed vali. (0.8)",
            "Small eager vali. (0.1)",
            "Small lazy vali. (0.1)",
        ],
        base_validator="Small eager vali. (0.1)",
        num_epochs=num_epochs,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 1), [0, 0, 0]),
                (range(1, 10), [0, 1, 0]),
                (range(10, 11), [1, 1, 0]),
                (range(11, _END), [1, 1, 1]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        plot_incentives=True,
        **kw,
    )


@register_case("Case 11")
def case_11(num_epochs: int = 40, **kw) -> Scenario:
    """Clipping demo with two equal big validators (reference cases.py:442-486)."""
    return Scenario(
        name="Case 11 - clipping demo",
        validators=[
            "Big vali. 1 (0.49)",
            "Big vali. 2 (0.49)",
            "Small vali. (0.02)",
        ],
        base_validator="Big vali. 1 (0.49)",
        num_epochs=num_epochs,
        reset_bonds=True,
        reset_bonds_index=1,
        reset_bonds_epoch=20,
        weights=row_weights(
            num_epochs,
            [
                (range(0, 20), [[0.3, 0.7], [0.6, 0.4], [0.61, 0.39]]),
                (range(20, _END), [[0.3, 0.7], [0.6, 0.4], [0.3, 0.61]]),
            ],
        ),
        stakes=constant_stakes(num_epochs, [0.49, 0.49, 0.02]),
        plot_incentives=True,
        **kw,
    )


@register_case("Case 12")
def case_12(num_epochs: int = 40, **kw) -> Scenario:
    """All switch; a small dishonest vali keeps minimal alt weight
    (reference cases.py:489-530)."""
    return Scenario(
        name=(
            "Case 12 - all validators switch, but small validator/s support "
            "alt miner with minimal weight"
        ),
        validators=[
            "Big vali. (0.8)",
            "Small dishonest vali. (0.1)",
            "Small vali. (0.1)",
        ],
        base_validator="Big vali. (0.8)",
        num_epochs=num_epochs,
        reset_bonds=True,
        reset_bonds_index=1,
        reset_bonds_epoch=20,
        weights=row_weights(
            num_epochs,
            [
                (range(0, 1), [[1.0, 0.0], [0.999, 0.001], [1.0, 0.0]]),
                (range(1, 21), [[0.0, 1.0], [0.001, 0.999], [0.0, 1.0]]),
                (range(21, _END), [[1.0, 0.0], [0.999, 0.001], [1.0, 0.0]]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 13")
def case_13(num_epochs: int = 40, **kw) -> Scenario:
    """Big vali on server 2, small vali(s) split to server 1
    (reference cases.py:533-565)."""
    return Scenario(
        name="Case 13 - Big vali supports server 2, small validator/s support server 1",
        validators=[
            "Big vali. (0.8)",
            "Small vali. (0.1)",
            "Small vali 2. (0.1)",
        ],
        base_validator="Big vali. (0.8)",
        num_epochs=num_epochs,
        reset_bonds=True,
        reset_bonds_index=0,
        reset_bonds_epoch=20,
        weights=row_weights(
            num_epochs,
            [
                (range(0, 21), [[0.0, 1.0], [0.5, 0.5], [0.0, 1.0]]),
                (range(21, _END), [[0.0, 1.0], [0.5, 0.5], [0.5, 0.5]]),
            ],
        ),
        stakes=constant_stakes(num_epochs, _DEFAULT_STAKES),
        **kw,
    )


@register_case("Case 14")
def case_14(num_epochs: int = 40, **kw) -> Scenario:
    """One validator defects to server 2 for a single epoch
    (reference cases.py:568-597)."""
    return Scenario(
        name=(
            "Case 14 - All validators support Server 1, one of them switches "
            "to Server 2 for one epoch"
        ),
        validators=["Vali. 1 (0.33)", "Vali. 2 (0.33)", "Vali. 3 (0.34)"],
        base_validator="Vali. 1 (0.33)",
        num_epochs=num_epochs,
        weights=assignment_weights(
            num_epochs,
            3,
            2,
            [
                (range(0, 20), [0, 0, 0]),
                (range(20, 21), [0, 0, 1]),
                (range(21, _END), [0, 0, 0]),
            ],
        ),
        stakes=constant_stakes(num_epochs, [0.33, 0.33, 0.34]),
        **kw,
    )
