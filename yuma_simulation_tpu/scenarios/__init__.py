"""Scenarios as data: dense per-epoch weight/stake arrays + a case registry."""

from yuma_simulation_tpu.scenarios.base import (  # noqa: F401
    BaseCase,
    Scenario,
    class_registry,
    create_case,
    get_cases,
    register_case,
)
from yuma_simulation_tpu.scenarios import builtin as _builtin  # noqa: F401
from yuma_simulation_tpu.scenarios.synthetic import (  # noqa: F401
    random_subnet_scenario,
    weight_perturbation_batch,
)

#: Instantiated default suite, in registration order (mirrors reference
#: cases.py:601's module-level `cases` list).
cases = get_cases()
