"""yuma_simulation_tpu — a TPU-native (JAX/XLA/Pallas) framework for Yuma consensus simulation.

A ground-up redesign of the capabilities of the reference `yuma-simulation`
package (see /root/reference) for TPU hardware:

- the per-epoch consensus kernel is a single jitted function
  (:mod:`yuma_simulation_tpu.models.epoch`), with the per-miner
  stake-weighted-median bisection vectorized over the whole weight matrix
  (:mod:`yuma_simulation_tpu.ops.consensus`);
- the epoch loop is a :func:`jax.lax.scan`
  (:mod:`yuma_simulation_tpu.simulation.engine`);
- scenario/hyperparameter sweeps are :func:`jax.vmap` batches
  (:mod:`yuma_simulation_tpu.simulation.sweep`);
- pod scale-out shards the scenario batch over an ICI mesh with
  :func:`jax.shard_map` (:mod:`yuma_simulation_tpu.parallel`);
- a Pallas TPU kernel fuses the consensus bisection into one VMEM-resident
  pass (:mod:`yuma_simulation_tpu.ops.pallas_consensus`).

Public, versioned API surface lives under :mod:`yuma_simulation_tpu.v1`
(mirroring the reference's ApiVer contract, reference README.md:10-18).
"""

__version__ = "0.1.0"

from yuma_simulation_tpu.models.config import (  # noqa: F401
    SimulationHyperparameters,
    YumaConfig,
    YumaParams,
    YumaSimulationNames,
)
