"""The simulation service core: admission -> queue -> coalesce -> dispatch.

The long-lived, transport-agnostic heart of the serving tier (the HTTP
layer in :mod:`.server` is a thin adapter over :meth:`SimulationService
.handle`). One process, one service, one telemetry
:class:`..telemetry.runctx.RunContext` for its whole lifetime — every
request rides the pipeline:

1. **admission** (:mod:`.admission`): validate + price through the
   planner and the analytic HBM preflight, zero compiles — typed
   :class:`..resilience.errors.AdmissionRejected` -> structured 400;
2. **backpressure** (:mod:`.quotas`): per-tenant token bucket, then the
   global bounded run queue — typed `QueueOverflow` -> 429 +
   ``Retry-After``, never an unbounded backlog;
3. **coalescing** (:mod:`.coalescer`): same shape bucket within the
   window -> one donor-packed batched dispatch, per-request lanes
   sliced back bitwise;
4. **supervised execution**: every dispatch runs through
   :class:`..resilience.supervisor.SweepSupervisor` — the request's
   deadline threads into the watchdog, NaN lanes quarantine into
   ``"partial"`` responses, device loss shrinks the mesh into a
   ``degraded`` flag, engine failures demote down the ladder — and the
   per-rung :class:`.lifecycle.CircuitBreaker` re-anchors future plans
   below a rung that keeps failing, recovering via half-open probes.

The failure contract is total: every request receives a typed JSON
response — result, partial-with-quarantine, 429, structured rejection,
or structured failure — never a bare 500. The service's flight bundle
(spans + request ledger + metrics snapshot, published at close and
gated by ``obsreport --check``) is the ops record of all of it.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import math
import pathlib
import threading
import time
from typing import Optional

import numpy as np

from yuma_simulation_tpu.resilience.errors import (
    AdmissionRejected,
    EngineFailure,
    QueueOverflow,
    SloShed,
    classify_failure,
)
from yuma_simulation_tpu.serve.admission import AdmissionTicket, admit
from yuma_simulation_tpu.serve.coalescer import (
    gather_group,
    slice_simulate_response,
)
from yuma_simulation_tpu.serve.lifecycle import CircuitBreaker, warmup
from yuma_simulation_tpu.serve.quotas import BoundedRunQueue, TenantQuotas
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Service knobs; the CLI (:mod:`.__main__`) exposes the subset an
    operator tunes. Defaults are sized for the CPU smoke/test scale —
    a production deployment raises the queue and quota bounds."""

    queue_limit: int = 64
    coalesce_window_seconds: float = 0.05
    max_batch: int = 8
    tenant_rate: float = 20.0
    tenant_burst: int = 10
    #: tenant -> (rate, burst) negotiated quota overrides.
    tenant_overrides: Optional[dict] = None
    default_deadline_seconds: float = 120.0
    breaker_threshold: int = 3
    breaker_cooldown_seconds: float = 30.0
    #: Flight-bundle directory (spans + request ledger + metrics). None
    #: disables the on-disk bundle (tests); production sets it.
    bundle_dir: Optional[str] = None
    #: `(epochs, V, M)` shapes to pre-compile at startup (warm engines).
    warmup_shapes: tuple = ()
    #: AOT executable-cache directory (:mod:`..simulation.aot`). When
    #: set, warmup preloads published executables instead of compiling,
    #: misses publish for the next worker, and JAX's persistent
    #: compilation cache is enabled beside it as the fallback tier.
    #: None (default) leaves the legacy always-compile path untouched.
    executable_cache_dir: Optional[str] = None
    #: Optional device mesh for sharded dispatch (elastic shrink rides
    #: the supervisor's existing path).
    mesh: object = None
    elastic: bool = True
    drain_estimate_seconds: float = 0.25
    #: SLO objectives this service evaluates (:mod:`..telemetry.slo`).
    #: None = the process engine with its default specs; a tuple of
    #: `SLOSpec` builds a service-owned engine over exactly those.
    slo_specs: Optional[tuple] = None
    #: While any `degrade=True` SLO fast-burns, requests with
    #: ``priority`` below this floor shed 429 BEFORE touching the
    #: queue (observability driving degradation). Default floor 1:
    #: normal traffic (priority 0) sheds, negotiated priority>=1 rides.
    shed_priority_below: int = 1
    #: tenant -> maximum accepted ``priority`` (the negotiated
    #: ceiling). When set, admission clamps the untrusted payload
    #: field to the tenant's entry (absent tenants to 0) so a client
    #: cannot opt out of SLO-driven shedding by claiming priority.
    #: None (default) trusts the payload — single-operator deployments.
    tenant_priority: Optional[dict] = None
    #: Retry-After for SLO-driven sheds (seconds).
    slo_shed_retry_after: float = 5.0
    #: Signed-API-key keyfile (:mod:`.apikeys`): JSON mapping
    #: ``tenant -> secret``. When set, every POST must carry a valid
    #: ``X-Api-Key`` — the verified key RESOLVES the tenant id before
    #: the negotiated quota/priority tables, so the payload's claimed
    #: ``tenant``/``priority`` is never trusted; unauthenticated
    #: requests get a typed 401. None (default) keeps the legacy
    #: payload-claimed tenant — single-operator deployments.
    api_keys_path: Optional[str] = None
    #: Background numerics-canary cadence: every this-many seconds of
    #: dispatcher idle time, re-execute one warm shape bucket on the
    #: plan's primary rung AND its demoted rung and compare per-epoch
    #: fingerprints (:mod:`..telemetry.numerics`). Confirmed drift is a
    #: typed ``engine_drift`` ledger event, a bad ``engine_drift_ok``
    #: SLO event (fast-burns -> `/healthz` degraded), and a breaker
    #: failure on the primary rung — plans re-anchor below a rung whose
    #: bits diverge from its own fallback. 0 disables (the default:
    #: a canary re-pays a bucket's compute).
    canary_interval_seconds: float = 0.0
    #: Chain-replay mount (:mod:`..replay`): when BOTH directories are
    #: set, the service answers ``POST /v1/whatif`` (admitted and
    #: priced suffix-sized through the planner like every other
    #: request) and ``GET /v1/replay[/NETUID]`` index reads. None
    #: (default) leaves the replay tier unmounted — what-ifs reject
    #: with a typed ``replay_unconfigured``.
    replay_archive_dir: Optional[str] = None
    replay_cache_dir: Optional[str] = None
    #: trailing window (snapshots) a what-if replays; None = the whole
    #: timeline.
    replay_window: Optional[int] = None
    replay_epochs_per_snapshot: int = 4
    #: carry-checkpoint stride (epochs) of cached baselines.
    replay_stride: int = 8
    #: LRU bound on cached baseline trajectories.
    replay_max_baselines: int = 64
    #: Continuous-telemetry rotation for the flight bundle
    #: (:class:`..telemetry.flight.RotationPolicy`): ``True`` opts in
    #: with default bounds, a policy instance pins them, ``None``
    #: (default) defers to the ``YUMA_TPU_FLIGHT_ROTATE`` env opt-in —
    #: i.e. rotation stays OFF unless explicitly requested, and
    #: monolithic bundles keep their exact legacy layout.
    flight_rotation: object = None
    #: Test-only: construct the service without its dispatcher thread
    #: (so queue-bound behavior can be observed deterministically).
    start_dispatcher: bool = True


class _Pending:
    """One admitted request waiting for its dispatch: the ticket plus
    the handler's rendezvous (`done` event, resolved status/body) and
    the critical-path timestamps the dispatcher stamps as the request
    moves — queue wait / coalesce wait / compile / execute become
    request-span children and the ``Server-Timing`` response header."""

    __slots__ = (
        "ticket",
        "done",
        "status",
        "response",
        "synthetic",
        "t_enqueued",
        "t_taken",
        "t_exec_start",
        "t_exec_end",
        "compile_seconds",
    )

    def __init__(self, ticket: AdmissionTicket, synthetic: bool = False):
        self.ticket = ticket
        self.done = threading.Event()
        self.status: Optional[int] = None
        self.response: Optional[dict] = None
        self.synthetic = synthetic
        self.t_enqueued = time.time()
        self.t_taken: Optional[float] = None
        self.t_exec_start: Optional[float] = None
        self.t_exec_end: Optional[float] = None
        self.compile_seconds = 0.0

    def resolve(self, status: int, body: dict) -> None:
        self.status = status
        self.response = body
        # Stamp the execute end HERE (not only in the dispatcher's
        # finally): the handler thread wakes on `done` and must never
        # observe a half-stamped critical path.
        if self.t_exec_start is not None and self.t_exec_end is None:
            self.t_exec_end = time.time()
        self.done.set()


class SimulationService:
    """See the module docstring. Thread-safe: `handle` is called from
    the HTTP server's per-connection threads; one dispatcher thread
    drains the queue."""

    def __init__(
        self,
        config: Optional[ServeConfig] = None,
        registry=None,
        slo_engine=None,
    ):
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger
        from yuma_simulation_tpu.telemetry.metrics import get_registry
        from yuma_simulation_tpu.telemetry.runctx import RunContext
        from yuma_simulation_tpu.telemetry.slo import SLOEngine, get_slo_engine

        self.config = config if config is not None else ServeConfig()
        self.registry = registry if registry is not None else get_registry()
        if self.config.executable_cache_dir:
            # Cold-start economics (simulation.aot): activate the AOT
            # executable cache + the persistent-compilation-cache tier
            # BEFORE warmup, so the warmup pass below loads published
            # executables instead of re-paying every compile — this is
            # what takes a worker from process start to first dispatch
            # in well under a second once the cache is warm.
            from yuma_simulation_tpu.simulation.aot import (
                configure_executable_cache,
            )

            configure_executable_cache(self.config.executable_cache_dir)
        self.run = RunContext()
        # Tenant identity (apikeys): load eagerly so a bad keyfile
        # fails construction, not the first request.
        self.keyring = None
        if self.config.api_keys_path:
            from yuma_simulation_tpu.serve.apikeys import ApiKeyring

            self.keyring = ApiKeyring.load(self.config.api_keys_path)
        self._slo_installed = False
        if slo_engine is not None:
            self.slo = slo_engine
        elif self.config.slo_specs is not None:
            self.slo = SLOEngine(
                self.config.slo_specs, registry=self.registry
            )
            # Operator-declared objectives replace the process engine so
            # the supervisor's unit durations and the sentinel's compile
            # seconds (which feed through `observe_duration` -> the
            # process engine) land on THESE specs, not the defaults.
            # Restored at close() if still installed.
            from yuma_simulation_tpu.telemetry.slo import set_slo_engine

            self._slo_previous = set_slo_engine(self.slo)
            self._slo_installed = True
        else:
            self.slo = get_slo_engine()
        # SLO transitions are typed ledger events: alert + recovery land
        # in the request ledger under their own span. Unhooked at
        # close() so a later service sharing the process engine can
        # claim the hook.
        if self.slo.on_transition is None:
            self.slo.on_transition = self._slo_transition
        #: Per-request ingress runs continuing REMOTE traces — held for
        #: the bundle publish so their spans resolve; flushed to disk in
        #: batches so a long-lived server's memory stays bounded. The
        #: publish lock serializes flush vs close: two concurrent
        #: read-merge-write passes over one spans.jsonl would drop
        #: whichever batch lands first.
        self._ingress_lock = threading.Lock()
        self._ingress_runs: list = []
        self._publish_lock = threading.Lock()
        self.started_t = time.time()
        self.quotas = TenantQuotas(
            rate=self.config.tenant_rate,
            burst=self.config.tenant_burst,
            overrides=self.config.tenant_overrides,
        )
        self.queue = BoundedRunQueue(
            self.config.queue_limit,
            drain_estimate_seconds=self.config.drain_estimate_seconds,
            registry=self.registry,
        )
        self.breaker = CircuitBreaker(
            threshold=self.config.breaker_threshold,
            cooldown_seconds=self.config.breaker_cooldown_seconds,
            registry=self.registry,
        )
        bundle_dir = self.config.bundle_dir
        if bundle_dir is not None:
            pathlib.Path(bundle_dir).mkdir(parents=True, exist_ok=True)
        # Continuous-telemetry mode: resolve the rotation policy ONCE
        # (config wins, env opt-in otherwise) and thread it through
        # every FlightRecorder this service constructs, so the flush
        # path and the close publish agree on the bundle's layout.
        self._rotation = None
        if bundle_dir is not None:
            from yuma_simulation_tpu.telemetry.flight import (
                FlightRecorder,
                RotationPolicy,
                rotation_from_env,
            )

            fr = self.config.flight_rotation
            if fr is True:
                self._rotation = RotationPolicy()
            elif fr:
                self._rotation = fr
            else:
                self._rotation = rotation_from_env()
            if self._rotation is not None:
                # Pin the service's lifetime run: retention must never
                # reclaim a sealed segment this run's records live in
                # while the service is still up.
                FlightRecorder(
                    bundle_dir, rotation=self._rotation
                ).mark_run_open(self.run.run_id)
        # The live ops plane (GET /debug/vars, /debug/spans, POST
        # /debug/profile): transport-free; the HTTP layer mounts it.
        from yuma_simulation_tpu.telemetry.ops import OpsPlane

        self.ops = OpsPlane(
            bundle_dir,
            registry=self.registry,
            slo_engine=self.slo,
            run=self.run,
        )
        self.ledger = FailureLedger(
            pathlib.Path(bundle_dir) / "ledger.jsonl"
            if bundle_dir is not None
            else None
        )
        self._ledger_lock = threading.Lock()
        # Eager registration: the acceptance surface (queue depth, shed
        # count, breaker state) must appear on /metrics from request
        # zero, not after the first increment.
        self._requests_total = self.registry.counter(
            "serve_requests_total", help="serving-tier requests handled"
        )
        self._admission_rejected = self.registry.counter(
            "serve_admission_rejected",
            help="typed admission rejections (pre-compile)",
        )
        self._coalesced_lanes = self.registry.counter(
            "serve_coalesced_lanes",
            help="requests donor-packed into a shared dispatch",
        )
        self._request_seconds = self.registry.histogram(
            "serve_request_seconds",
            help="request wall time, admission to reply",
        )
        # The background numerics canary (ticked from the dispatcher's
        # idle loop): warm shape buckets round-robined, per-tick state
        # surfaced on /healthz, serialized sketch records stashed for
        # the bundle's numerics.jsonl.
        self._canary_lock = threading.Lock()
        self._canary_buckets: dict[str, tuple] = {}
        self._canary_order: list[str] = []
        self._canary_idx = 0
        self._canary_last = time.monotonic()
        self._canary_state: dict = {
            "ticks": 0, "drift": 0, "last_bucket": None,
        }
        self._numerics_lock = threading.Lock()
        self._numerics_records: list = []
        self._canary_ticks_metric = self.registry.counter(
            "serve_canary_ticks",
            help="background numerics-canary bucket re-executions",
        )
        self._canary_drift_metric = self.registry.counter(
            "serve_canary_drift",
            help="canary comparisons that confirmed numerics drift",
        )
        for shape in self.config.warmup_shapes:
            self._remember_canary_bucket(shape, "Yuma 1 (paper)")
        # The chain-replay mount (ISSUE 14): archive + state cache
        # behind one facade; what-ifs dispatch through the ordinary
        # admission -> queue -> dispatcher pipeline, so quotas, SLO
        # shedding, deadlines, and the flight bundle cover them too.
        self.replay = None
        if self.config.replay_archive_dir and self.config.replay_cache_dir:
            from yuma_simulation_tpu.replay import ReplayService

            self.replay = ReplayService(
                self.config.replay_archive_dir,
                self.config.replay_cache_dir,
                window=self.config.replay_window,
                epochs_per_snapshot=self.config.replay_epochs_per_snapshot,
                stride=self.config.replay_stride,
                max_baselines=self.config.replay_max_baselines,
            )
        self._counter = itertools.count(1)
        self._stopping = False
        self._closed = False
        if self.config.warmup_shapes:
            with self.run.activate():
                warmup(self.config.warmup_shapes)
        self._dispatcher: Optional[threading.Thread] = None
        if self.config.start_dispatcher:
            self.start_dispatcher()

    def start_dispatcher(self) -> None:
        """Start the queue-draining dispatcher thread (idempotent).
        Split from construction so tests — and a future multi-process
        pre-fork — can fill the queue deterministically first."""
        if self._dispatcher is not None:
            return
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name="yuma-serve-dispatcher",
            daemon=True,
        )
        self._dispatcher.start()

    # -- bookkeeping -----------------------------------------------------

    def _append_ledger(self, event: str, **fields) -> None:
        with self._ledger_lock:
            self.ledger.append(event, **fields)

    def _append_ledger_rootspan(self, event: str, **fields) -> None:
        """A ledger record under its own fresh root span of the SERVICE
        run — for records born outside any request span (401s rejected
        before the pipeline, pool lifecycle events), which must still
        resolve under ``obsreport --check``'s span gate."""
        from yuma_simulation_tpu.telemetry.runctx import span

        with self.run.activate():
            with span(f"{event}:{fields.get('request', '')}", root=True):
                self._append_ledger(event, **fields)

    def _slo_transition(self, rec: dict) -> None:
        """The burn-rate engine's alert hook: every transition is a
        typed ledger record under its own span of the SERVICE run (a
        transition may fire from any handler thread, traced or not)."""
        from yuma_simulation_tpu.telemetry.runctx import span

        with self.run.activate():
            # root=True: a transition may fire mid-request of a CONTINUED
            # trace, where the innermost span belongs to the caller's
            # run — inheriting it would record an unresolvable parent.
            with span(f"slo:{rec['slo']}", root=True, state=rec["to"]):
                self._append_ledger(
                    "slo_alert" if rec["to"] != "ok" else "slo_recovered",
                    slo=rec["slo"],
                    state=rec["to"],
                    was=rec["from"],
                    burn_rate=rec["burn_rate"],
                )

    def mint_request_id(self) -> str:
        """Process-unique request id — the HTTP layer mints one per
        connection-handled request so even pre-pipeline rejections
        (404/413/bad JSON) echo ``X-Request-Id``."""
        return f"r{next(self._counter):06d}"

    def _remember_ingress(self, run) -> None:
        """Keep a completed ingress run (a remote trace's request spans)
        for the bundle publish; flush batches to disk so memory stays
        bounded on a long-lived server."""
        flush = None
        with self._ingress_lock:
            self._ingress_runs.append(run)
            if len(self._ingress_runs) > 256:
                flush, self._ingress_runs = self._ingress_runs, []
        if flush and self.config.bundle_dir is not None:
            from yuma_simulation_tpu.telemetry.flight import FlightRecorder

            try:
                with self._publish_lock:
                    # Append-only (no whole-file merge) so the unlucky
                    # 257th request's handler thread pays O(batch), not
                    # O(total-spans); close() merge-republishes. Under
                    # rotation everything lands in the LIVE segment, so
                    # the cost stays O(batch) however many sealed
                    # segments have accumulated.
                    rec = FlightRecorder(
                        self.config.bundle_dir, rotation=self._rotation
                    )
                    rec.append_spans(flush)
                    rec.snapshot_metrics(
                        self.registry, run_id=self.run.run_id
                    )
                    rec.record_slo(self.slo, run_id=self.run.run_id)
                    with self._numerics_lock:
                        nrecs = self._numerics_records
                        self._numerics_records = []
                    # Append-only here too: close() merge-dedupes.
                    rec.append_numerics(nrecs, run_id=self.run.run_id)
            except Exception:
                logger.warning(
                    "ingress span flush failed for %s",
                    self.config.bundle_dir,
                    exc_info=True,
                )

    # -- the request pipeline -------------------------------------------

    def handle(
        self, kind: str, payload, *, request_id=None, trace=None,
        api_key=None,
    ) -> tuple[int, dict, dict]:
        """One request, end to end; returns `(status, body, headers)`.
        Total by construction: every exit path is a typed JSON body
        carrying ``X-Request-Id`` (and ``Server-Timing`` with the
        request's critical-path breakdown once it was dispatched).

        `trace` (a :class:`..telemetry.propagation.TraceContext` or a
        raw traceparent header value) JOINS the caller's distributed
        trace: the request span tree roots under the caller's span in
        the caller's run, published into this server's flight bundle."""
        from yuma_simulation_tpu.telemetry.propagation import (
            TraceContext,
            child_run,
            span_prefix_for,
        )
        from yuma_simulation_tpu.telemetry.runctx import span

        if isinstance(trace, str):
            trace = TraceContext.from_traceparent(trace)
        rid = request_id if request_id else self.mint_request_id()
        t0 = time.perf_counter()
        t_wall0 = time.time()
        self._requests_total.inc()
        if self.keyring is not None:
            # Keys configured: the VERIFIED key is the tenant identity.
            # The payload's claimed tenant/priority is overwritten (not
            # merely clamped) before admission ever sees it — an
            # unauthenticated request is a typed 401, never a silent
            # fall-through to the anonymous tenant's quota.
            resolved = self.keyring.resolve(api_key)
            if resolved is None:
                self._append_ledger_rootspan(
                    "request_done",
                    request=rid,
                    tenant="<unauthenticated>",
                    endpoint=kind,
                    status=401,
                    outcome="rejected",
                )
                return (
                    401,
                    {
                        "status": "rejected",
                        "error": "Unauthenticated",
                        "message": "a valid X-Api-Key is required by "
                        "this deployment",
                        "request_id": rid,
                    },
                    {"X-Request-Id": rid},
                )
            if isinstance(payload, dict):
                payload = dict(payload, tenant=resolved)
            else:
                payload = {"tenant": resolved}
        tenant = (
            payload.get("tenant", "anonymous")
            if isinstance(payload, dict)
            else "anonymous"
        )
        if trace is not None:
            run = child_run(trace, prefix=span_prefix_for())
            cm = run
            ingress = run
        else:
            run = self.run
            cm = self.run.activate()
            ingress = None
        with cm:
            with span(
                f"request:{rid}", tenant=tenant, endpoint=kind, request=rid
            ) as s:
                pending = None
                try:
                    status, body, headers, pending = self._handle_inner(
                        kind, payload, rid, tenant
                    )
                except BaseException as exc:  # noqa: BLE001 — typed below
                    # The no-bare-500 backstop: anything the pipeline
                    # did not already structure becomes a typed failure
                    # body here.
                    logger.exception("unhandled serve failure for %s", rid)
                    status, body = self._failure_response(exc, rid)
                    headers = {}
                if s is not None:
                    s.attrs["status"] = status
                    s.attrs["outcome"] = body.get("status", "?")
                timing = self._record_phases(
                    run, s, pending, t_wall0, time.time()
                )
                headers = dict(headers)
                headers.setdefault("X-Request-Id", rid)
                if timing:
                    headers.setdefault("Server-Timing", timing)
                self._append_ledger(
                    "request_done",
                    request=rid,
                    tenant=tenant,
                    endpoint=kind,
                    status=status,
                    outcome=body.get("status", "?"),
                )
        elapsed = time.perf_counter() - t0
        self._request_seconds.observe(elapsed)
        # The SLO signals: request latency, error rate (5xx), shed rate.
        self.slo.observe("serve_request_seconds", elapsed)
        self.slo.event("serve_request_ok", status < 500)
        self.slo.event("serve_admitted", status != 429)
        if ingress is not None:
            self._remember_ingress(ingress)
        return status, body, headers

    def _record_phases(
        self, run, request_span, pending, t_wall0: float, t_wall1: float
    ) -> str:
        """Synthesize the request's critical-path child spans from the
        dispatcher's timestamps and return the ``Server-Timing`` header
        value (RFC 9211 metric syntax, durations in ms)."""
        parts = []
        parent = request_span.span_id if request_span is not None else ""

        def phase(name: str, t0, t1, **attrs) -> None:
            if t0 is None or t1 is None or t1 < t0:
                return
            run.record_span(name, t0, t1, parent_id=parent, **attrs)
            parts.append(f"{name};dur={1000.0 * (t1 - t0):.1f}")

        if pending is not None and pending.t_exec_end is not None:
            phase("queue", pending.t_enqueued, pending.t_taken)
            phase("coalesce", pending.t_taken, pending.t_exec_start)
            if pending.compile_seconds > 0 and pending.t_exec_start is not None:
                phase(
                    "compile",
                    pending.t_exec_start,
                    pending.t_exec_start + pending.compile_seconds,
                )
            else:
                parts.append("compile;dur=0.0")
            phase(
                "execute",
                pending.t_exec_start,
                pending.t_exec_end,
                compile_s=round(pending.compile_seconds, 6),
            )
        parts.append(f"total;dur={1000.0 * (t_wall1 - t_wall0):.1f}")
        return ", ".join(parts)

    def _handle_inner(
        self, kind: str, payload, rid: str, tenant: str
    ) -> tuple[int, dict, dict, Optional[_Pending]]:
        if self._stopping:
            return (
                503,
                {
                    "status": "shutting_down",
                    "error": "ServiceUnavailable",
                    "message": "the service is draining; retry elsewhere",
                    "request_id": rid,
                },
                {"Retry-After": "5"},
                None,
            )
        try:
            ticket = admit(
                payload,
                request_id=rid,
                kind=kind,
                default_deadline_seconds=self.config.default_deadline_seconds,
                # Price sweeps at the unit size _execute_sweep dispatches.
                max_unit_lanes=self.config.max_batch * 8,
                tenant_priority=self.config.tenant_priority,
                replay=self.replay,
            )
        except AdmissionRejected as exc:
            self._admission_rejected.inc()
            body = {
                "status": "rejected",
                "error": "AdmissionRejected",
                "reason": exc.reason,
                "message": str(exc),
                "request_id": rid,
            }
            if exc.suggestion:
                body["suggestion"] = exc.suggestion
            return 400, body, {}, None

        # Deterministic overload drill (test-only hook, one `is None`
        # check in production): push the armed burst of synthetic
        # requests through the same quota/queue path first, so the shed
        # and breaker responses below are exercised under real pressure.
        from yuma_simulation_tpu.resilience import faults

        overload = faults.active_overload_fault()
        if overload is not None:
            self._inject_overload(overload)

        try:
            # SLO-driven degradation FIRST: while a degrade=True SLO
            # fast-burns, lowest-priority work sheds here — before it
            # can fill the queue and before the quota spends a token on
            # work the service has already decided to drop.
            burning = self.slo.degraded()
            if burning and ticket.priority < self.config.shed_priority_below:
                raise SloShed(
                    f"SLO fast burn ({', '.join(burning)}): shedding "
                    f"priority<{self.config.shed_priority_below} work",
                    retry_after=self.config.slo_shed_retry_after,
                    slos=burning,
                )
            try:
                self.quotas.admit(ticket.tenant)
            except QueueOverflow:
                # The queue's put() counts its own sheds; quota sheds
                # ride the same counter from here.
                self.queue.record_shed()
                raise
            pending = _Pending(ticket)
            self.queue.put(pending)
        except QueueOverflow as exc:
            retry_after = max(0.1, exc.retry_after)
            if isinstance(exc, SloShed):
                self.queue.record_shed()
            shed_fields = {}
            if isinstance(exc, SloShed):
                shed_fields["slos"] = list(exc.slos)
            self._append_ledger(
                "request_shed",
                request=rid,
                tenant=ticket.tenant,
                retry_after=round(retry_after, 3),
                **shed_fields,
            )
            body = {
                "status": "shed",
                "error": type(exc).__name__,
                "message": str(exc),
                "retry_after": retry_after,
                "request_id": rid,
            }
            if isinstance(exc, SloShed):
                body["slo"] = list(exc.slos)
            return (
                429,
                body,
                {"Retry-After": str(int(math.ceil(retry_after)))},
                None,
            )

        if not pending.done.wait(self._wall_cap(ticket)):
            return (
                504,
                {
                    "status": "failed",
                    "error": "DeadlineExhausted",
                    "message": "the request did not complete within its "
                    "deadline envelope",
                    "retryable": True,
                    "request_id": rid,
                },
                {},
                pending,
            )
        headers = {}
        assert pending.status is not None and pending.response is not None
        if "retry_after" in pending.response:
            headers["Retry-After"] = str(
                int(math.ceil(pending.response["retry_after"]))
            )
        return pending.status, pending.response, headers, pending

    def _wall_cap(self, ticket: AdmissionTicket) -> float:
        """The handler's rendezvous bound: generous enough for a full
        supervised ladder walk (attempts x rungs x (budget + grace)),
        finite so a lost dispatcher cannot hold a connection forever."""
        return 12.0 * ticket.deadline_seconds + 60.0

    def _inject_overload(self, fault) -> None:
        """The armed OverloadFault's synthetic burst: N tiny admitted
        tickets through the real queue (sheds counted on the same
        metrics the drill asserts on). Synthetic pendings execute and
        are dropped — nobody waits on them."""
        for i in range(fault.requests):
            try:
                ticket = admit(
                    {
                        "tenant": fault.tenant,
                        "case": "Case 1",
                        "deadline_seconds": 30,
                    },
                    request_id=f"synthetic-{i:04d}",
                    kind="simulate",
                    default_deadline_seconds=30.0,
                )
            except AdmissionRejected:  # pragma: no cover — Case 1 is valid
                return
            try:
                self.queue.put(_Pending(ticket, synthetic=True))
            except QueueOverflow:
                continue  # counted by the queue; keep pushing the burst

    # -- the background numerics canary ---------------------------------

    def _remember_canary_bucket(self, shape, version: str) -> None:
        """Register a warm `(E, V, M)` shape as a canary target (warmup
        shapes at startup, every successfully dispatched simulate shape
        thereafter)."""
        try:
            E, V, M = (int(d) for d in shape)
        except (TypeError, ValueError):
            return
        key = f"{E}x{V}x{M}"
        with self._canary_lock:
            if key in self._canary_buckets:
                # Most-recently-dispatched rotates to the back, so the
                # eviction below sheds the coldest bucket, not a hot one.
                self._canary_order.remove(key)
            self._canary_buckets[key] = ((E, V, M), version)
            self._canary_order.append(key)
            # LRU bound: a hostile (or merely varied) client shedding a
            # fresh shape per request must not grow the rotation — or
            # the per-tick cold compiles that come with it — without
            # limit. 32 warm buckets is far past any real serving mix.
            while len(self._canary_order) > 32:
                evicted = self._canary_order.pop(0)
                del self._canary_buckets[evicted]

    def _stash_numerics(self, records) -> None:
        """Hold serialized sketch records for the bundle publish (close
        + the periodic ingress flush); bounded — the on-disk merge keys
        by (unit, stream, role, label), so only the newest capture per
        identity survives anyway."""
        if not records:
            return
        with self._numerics_lock:
            self._numerics_records.extend(records)
            del self._numerics_records[:-4096]

    def _maybe_canary(self) -> None:
        """Dispatcher-idle hook: tick the canary when the interval has
        elapsed. Never raises — the canary observes the service, it must
        not take it down."""
        if self.config.canary_interval_seconds <= 0 or self._stopping:
            return
        now = time.monotonic()
        with self._canary_lock:
            due = (
                bool(self._canary_order)
                and now - self._canary_last
                >= self.config.canary_interval_seconds
            )
            if due:
                self._canary_last = now
        if not due:
            return
        try:
            self.run_canary_once()
        except Exception:
            logger.warning("serve numerics canary tick failed", exc_info=True)

    def run_canary_once(self) -> Optional[dict]:
        """Force one canary tick through the next warm bucket (the smoke
        drill's deterministic entry point; production ticks ride the
        dispatcher's idle loop on ``canary_interval_seconds``). Returns
        the canary state snapshot, or None when nothing could run (no
        warm buckets, numerics capture disabled)."""
        from yuma_simulation_tpu.telemetry.numerics import numerics_enabled

        if not numerics_enabled():
            return None
        with self._canary_lock:
            if not self._canary_order:
                return None
            key = self._canary_order[self._canary_idx % len(self._canary_order)]
            self._canary_idx += 1
            shape, version = self._canary_buckets[key]
        return self._canary_tick(key, shape, version)

    def _canary_tick(self, key: str, shape: tuple, version: str) -> dict:
        """One cross-engine canary comparison on a warm bucket: the
        plan's primary rung vs its demoted rung over the same
        deterministic workload, compared fingerprint-by-fingerprint per
        epoch. See ``ServeConfig.canary_interval_seconds`` for what a
        confirmed drift drives."""
        import jax

        from yuma_simulation_tpu.models.config import YumaConfig
        from yuma_simulation_tpu.models.variants import variant_for_version
        from yuma_simulation_tpu.resilience import faults
        from yuma_simulation_tpu.scenarios.base import Scenario
        from yuma_simulation_tpu.simulation.planner import plan_dispatch
        from yuma_simulation_tpu.simulation.sweep import (
            simulate_batch,
            stack_scenarios,
        )
        from yuma_simulation_tpu.telemetry.numerics import (
            compare_sketches,
            sketch_records,
            to_host,
        )
        from yuma_simulation_tpu.telemetry.runctx import span

        E, V, M = shape
        spec = variant_for_version(version)
        config = YumaConfig()
        validators = [f"v{i}" for i in range(V)]
        scenario = Scenario(
            name=f"canary:{key}",
            validators=validators,
            base_validator=validators[0],
            weights=np.zeros((E, V, M), np.float32),
            stakes=np.ones((E, V), np.float32),
            num_epochs=E,
        )
        W, S, ri, re = stack_scenarios([scenario])
        plan = plan_dispatch(
            f"serve_canary:{key}", (1, E, V, M), spec, config, W.dtype,
            check_memory=False,
        )
        ladder = self.breaker.filter_ladder(plan.ladder)
        primary_rung = ladder[0]
        canary_rung = ladder[1] if len(ladder) > 1 else ladder[-1]
        label = f"canary:{key}"
        with self.run.activate():
            # root=True: the tick runs on the dispatcher thread between
            # requests; it must not parent under whatever span a traced
            # request last left behind.
            with span(
                label, root=True, primary=primary_rung, canary=canary_rung
            ):
                try:
                    ys_a = jax.block_until_ready(
                        simulate_batch(
                            W, S, ri, re, config, spec,
                            epoch_impl=primary_rung,
                        )
                    )
                    with faults.canary_scope():
                        ys_b = jax.block_until_ready(
                            simulate_batch(
                                W, S, ri, re, config, spec,
                                epoch_impl=canary_rung,
                            )
                        )
                except BaseException:
                    # A tick that DIED is not drift evidence; release a
                    # half-open probe latch the filter may have taken.
                    self.breaker.abort_probe(primary_rung)
                    raise
                primary = to_host(ys_a["numerics"])
                canary = to_host(ys_b["numerics"])
                self._stash_numerics(
                    sketch_records(
                        primary, unit=0, lanes=(0, 1), engine=primary_rung,
                        role="primary", label=label,
                    )
                    + sketch_records(
                        canary, unit=0, lanes=(0, 1), engine=canary_rung,
                        role="canary", label=label,
                    )
                )
                divergences = compare_sketches(primary, canary)
                self._canary_ticks_metric.inc()
                with self._canary_lock:
                    self._canary_state["ticks"] += 1
                    self._canary_state["last_bucket"] = key
                self.slo.event("engine_drift_ok", not divergences)
                if not divergences:
                    self.breaker.record_success(primary_rung)
                    self._append_ledger(
                        "canary_ok",
                        bucket=key,
                        primary_engine=primary_rung,
                        canary_engine=canary_rung,
                    )
                else:
                    self._canary_drift_metric.inc(len(divergences))
                    with self._canary_lock:
                        self._canary_state["drift"] += len(divergences)
                    # Confirmed drift counts as a primary-rung failure:
                    # after `threshold` confirming ticks the rung trips
                    # open fleet-wide and plans re-anchor below it.
                    self.breaker.record_failure(primary_rung)
                    for stream, lanes in sorted(divergences.items()):
                        first = lanes[0]
                        self._append_ledger(
                            "engine_drift",
                            bucket=key,
                            stream=stream,
                            primary_engine=primary_rung,
                            canary_engine=canary_rung,
                            lanes=[
                                [
                                    d["lane"],
                                    d["first_divergent_epoch"],
                                    d["ulp_distance"],
                                ]
                                for d in lanes
                            ],
                        )
                        log_event(
                            logger,
                            "engine_drift",
                            level=logging.ERROR,
                            bucket=key,
                            stream=stream,
                            primary=primary_rung,
                            canary=canary_rung,
                            lane=first["lane"],
                            epoch=first["first_divergent_epoch"],
                            ulp=first["ulp_distance"],
                        )
        with self._canary_lock:
            return dict(self._canary_state)

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        with self.run.activate():
            while True:
                item = self.queue.get(timeout=0.05)
                if item is None:
                    if self._stopping:
                        return
                    self._maybe_canary()
                    continue
                item.t_taken = time.time()
                if self._stopping:
                    item.resolve(
                        503,
                        {
                            "status": "shutting_down",
                            "error": "ServiceUnavailable",
                            "message": "the service is draining",
                            "request_id": item.ticket.request_id,
                        },
                    )
                    continue
                group = gather_group(
                    self.queue,
                    item,
                    window_seconds=self.config.coalesce_window_seconds,
                    # The sharded path stacks raw shapes (no donor-pack
                    # miner masks), so a mesh-backed service dispatches
                    # solo — bucket-mates may differ in raw [V, M].
                    max_batch=(
                        1 if self.config.mesh is not None
                        else self.config.max_batch
                    ),
                )
                self._execute_group(group)

    def _execute_group(self, group: list) -> None:
        from yuma_simulation_tpu.telemetry.runctx import span

        first = group[0].ticket
        now = time.time()
        compile_hist = self.registry.histogram(
            "compile_seconds",
            help=(
                "wall seconds of sentinel regions that added "
                "jit-cache entries (compile-time upper bound)"
            ),
        )
        compile_before = compile_hist.snapshot()["sum"]
        for p in group:
            # Coalesce-gathered members were taken off the queue by
            # gather_group, not the dispatcher's get(): stamp them now.
            if p.t_taken is None:
                p.t_taken = now
            p.t_exec_start = now
        with span(
            f"dispatch:{first.kind}",
            requests=[p.ticket.request_id for p in group],
            bucket=first.plan.bucket.key,
        ):
            try:
                if first.kind == "simulate":
                    self._execute_simulate(group)
                elif first.kind == "sweep":
                    self._execute_sweep(group[0])
                elif first.kind == "whatif":
                    self._execute_whatif(group[0])
                else:
                    self._execute_table(group[0])
            except BaseException as exc:  # noqa: BLE001 — typed below
                logger.warning(
                    "serve dispatch failed for %s",
                    [p.ticket.request_id for p in group],
                    exc_info=True,
                )
                for p in group:
                    status, body = self._failure_response(
                        exc, p.ticket.request_id
                    )
                    p.resolve(status, body)
            finally:
                t_end = time.time()
                compile_delta = max(
                    0.0, compile_hist.snapshot()["sum"] - compile_before
                )
                for p in group:
                    if p.t_exec_end is None:
                        p.t_exec_end = t_end
                    p.compile_seconds = compile_delta

    def _remaining_or_fail(self, group: list) -> Optional[float]:
        """The batch's conservative remaining deadline (the tightest
        member's). Exhausted -> every member resolved 504 and None."""
        remaining = min(p.ticket.remaining_seconds() for p in group)
        if remaining <= 0.05:
            for p in group:
                p.resolve(
                    504,
                    {
                        "status": "failed",
                        "error": "DeadlineExhausted",
                        "message": "the deadline expired while queued",
                        "retryable": True,
                        "request_id": p.ticket.request_id,
                    },
                )
            return None
        return remaining

    def _supervisor(
        self, *, engine: str, quarantine: bool, remaining: float, unit_size: int
    ):
        from yuma_simulation_tpu.resilience.retry import default_retry_policy
        from yuma_simulation_tpu.resilience.supervisor import SweepSupervisor
        from yuma_simulation_tpu.resilience.watchdog import Deadline

        return SweepSupervisor(
            directory=None,
            unit_size=unit_size,
            deadline=Deadline(
                budget_seconds=max(0.1, remaining),
                grace_seconds=max(0.1, remaining),
            ),
            retry_policy=default_retry_policy(),
            quarantine=quarantine,
            elastic=self.config.elastic,
            engine=engine,
        )

    def _feed_breaker(self, start_rung: str, report) -> None:
        if report.engine_demotions > 0:
            self.breaker.record_failure(start_rung)
            for rung in report.engines_used:
                if rung != start_rung:
                    self.breaker.record_success(rung)
        else:
            self.breaker.record_success(start_rung)

    def _execute_simulate(self, group: list) -> None:
        remaining = self._remaining_or_fail(group)
        if remaining is None:
            return
        first = group[0].ticket
        ladder = self.breaker.filter_ladder(first.plan.ladder)
        start = ladder[0]
        # The plan this dispatch actually runs: the admission plan,
        # re-anchored below any tripped rung. record() stamps it (with
        # the breaker's WHY) on the dispatch span, so flight bundles
        # show which rung ran and on what grounds.
        plan = first.plan.demoted(start)
        plan.record()
        quarantine = first.quarantine and start == "xla"
        pack = start == "xla" and self.config.mesh is None
        real = sum(1 for p in group if not p.synthetic)
        sup = self._supervisor(
            engine=start,
            quarantine=quarantine,
            remaining=remaining,
            unit_size=max(1, len(group)),
        )
        try:
            out = sup.run_batch(
                [p.ticket.scenario for p in group],
                first.version,
                first.config,
                mesh=self.config.mesh if start == "xla" else None,
                tag=f"serve:{first.plan.bucket.key}",
                pack=pack,
            )
        except BaseException as exc:
            typed = classify_failure(exc)
            if isinstance(typed, EngineFailure):
                self.breaker.record_failure(start)
            else:
                # A failure the breaker must not count (caller error,
                # unclassified crash) still has to release a half-open
                # probe latch, or the rung stays dead forever.
                self.breaker.abort_probe(start)
            raise
        report = out["report"]
        self._feed_breaker(start, report)
        self._stash_numerics(out.get("numerics_records"))
        self._remember_canary_bucket(
            np.shape(first.scenario.weights), first.version
        )
        if real > 1:
            self._coalesced_lanes.inc(real)
        dividends = np.asarray(out["dividends"])
        entries = out["quarantine"].entries
        for lane, p in enumerate(group):
            if p.synthetic:
                p.resolve(200, {"status": "ok", "synthetic": True})
                continue
            p.resolve(
                200,
                slice_simulate_response(
                    dividends,
                    lane,
                    p.ticket,
                    quarantine_entries=entries,
                    report=report,
                    coalesced=real,
                ),
            )

    def _execute_sweep(self, pending: _Pending) -> None:
        remaining = self._remaining_or_fail([pending])
        if remaining is None:
            return
        t = pending.ticket
        from yuma_simulation_tpu.simulation.sweep import config_grid

        configs, points = config_grid(**t.axes)
        sup = self._supervisor(
            engine="xla",
            quarantine=t.quarantine,
            remaining=remaining,
            unit_size=max(1, min(len(points), self.config.max_batch * 8)),
        )
        out = sup.run_grid(
            t.scenario, t.version, configs, tag=f"serve:sweep:{t.request_id}"
        )
        # Re-label the numerics captures by shape bucket, not request id:
        # the on-disk merge keys by label, so per-request labels would
        # grow numerics.jsonl without bound on a long-lived server
        # (newest capture per bucket is all the drift render needs —
        # spans keep the per-request identity).
        self._stash_numerics(
            [
                {**rec, "label": f"serve:sweep:{t.plan.bucket.key}"}
                for rec in out.get("numerics_records") or ()
            ]
        )
        report = out["report"]
        dividends = np.asarray(out["dividends"])  # [P, E, V]
        entries = out["quarantine"].entries
        quarantined_points = sorted({e.case for e in entries})
        body = {
            "status": "partial" if quarantined_points else "ok",
            "request_id": t.request_id,
            "tenant": t.tenant,
            "points": points,
            "total_dividends": dividends.sum(axis=1).tolist(),  # [P, V]
            "degraded": not report.clean,
            "report": {
                "stalls_killed": report.stalls_killed,
                "engine_demotions": report.engine_demotions,
                "mesh_shrinks": report.mesh_shrinks,
                "units_retried": report.units_retried,
                "lanes_quarantined": report.lanes_quarantined,
                "engines_used": list(report.engines_used),
            },
        }
        if quarantined_points:
            body["quarantined_points"] = [int(i) for i in quarantined_points]
        pending.resolve(200, body)

    def _execute_whatif(self, pending: _Pending) -> None:
        remaining = self._remaining_or_fail([pending])
        if remaining is None:
            return
        t = pending.ticket
        assert self.replay is not None and t.whatif is not None
        from yuma_simulation_tpu.resilience.watchdog import (
            Deadline,
            run_with_deadline,
        )

        result = run_with_deadline(
            lambda: self.replay.whatif(t.whatif),
            Deadline(budget_seconds=max(0.1, remaining)),
            label=f"serve:whatif:{t.request_id}",
        )
        full_epochs = result.epochs_simulated + result.epochs_saved
        # The per-request replay ledger record obsreport's replay
        # section aggregates: cache effectiveness and the suffix-vs-full
        # epoch savings, per tenant.
        self._append_ledger(
            "whatif_served",
            request=t.request_id,
            tenant=t.tenant,
            netuid=t.whatif.netuid,
            version=t.whatif.version,
            cache_hit=result.cache_hit,
            resume_epoch=result.resume_epoch,
            suffix_epochs=result.epochs_simulated,
            full_epochs=full_epochs,
            epochs_saved=result.epochs_saved,
        )
        delta = result.dividend_delta
        pending.resolve(
            200,
            {
                "status": "ok",
                "request_id": t.request_id,
                "tenant": t.tenant,
                "netuid": t.whatif.netuid,
                "version": t.whatif.version,
                "spec_key": t.whatif.spec_key(),
                "from_epoch": t.whatif.from_epoch,
                "cache_hit": result.cache_hit,
                "resume_epoch": result.resume_epoch,
                "epochs_simulated": result.epochs_simulated,
                "epochs_saved": result.epochs_saved,
                "total_dividend_delta": [
                    float(x) for x in result.total_dividend_delta
                ],
                "total_incentive_delta": [
                    float(x) for x in result.total_incentive_delta
                ],
                "max_abs_dividend_delta": float(np.abs(delta).max()),
                "baseline_key": result.baseline_key,
            },
        )

    def replay_get(self, path: str) -> tuple[int, dict]:
        """The read-only replay surface (``GET /v1/replay`` index,
        ``GET /v1/replay/NETUID`` one timeline + its cached baselines)
        — index/meta reads only, served inline by the HTTP thread."""
        from yuma_simulation_tpu.replay import ArchiveError

        if self.replay is None:
            return 404, {
                "status": "rejected",
                "error": "ReplayUnconfigured",
                "message": "this deployment mounts no replay tier",
            }
        tail = path[len("/v1/replay"):].strip("/")
        try:
            if not tail:
                return 200, {"status": "ok", **self.replay.index()}
            if not tail.isdigit():
                return 404, {
                    "status": "rejected",
                    "error": "NotFound",
                    "message": f"no replay route {path!r} (want "
                    "/v1/replay or /v1/replay/NETUID)",
                }
            return 200, {
                "status": "ok",
                **self.replay.timeline_info(int(tail)),
            }
        except ArchiveError as exc:
            return 404, {
                "status": "rejected",
                "error": "UnknownSubnet",
                "message": str(exc),
            }

    def _execute_table(self, pending: _Pending) -> None:
        remaining = self._remaining_or_fail([pending])
        if remaining is None:
            return
        t = pending.ticket
        from yuma_simulation_tpu.models.config import YumaParams
        from yuma_simulation_tpu.reporting.tables import (
            generate_total_dividends_table,
        )
        from yuma_simulation_tpu.resilience.watchdog import (
            Deadline,
            run_with_deadline,
        )
        from yuma_simulation_tpu.scenarios.base import get_cases

        versions = [(v, t.config.yuma_params or YumaParams()) for v in t.versions]
        df = run_with_deadline(
            lambda: generate_total_dividends_table(
                get_cases(), versions, t.config.simulation
            ),
            Deadline(budget_seconds=max(0.1, remaining)),
            label=f"serve:table:{t.request_id}",
        )
        pending.resolve(
            200,
            {
                "status": "ok",
                "request_id": t.request_id,
                "tenant": t.tenant,
                "versions": list(t.versions),
                "csv": df.to_csv(index=False),
            },
        )

    def _failure_response(self, exc: BaseException, rid: str) -> tuple[int, dict]:
        """Every non-admission failure as a typed body: classified
        engine failures are client-retryable 503s (the ladder already
        did its best — a later request may find a recovered rung),
        anything else a structured 503 naming the type. Never a bare
        500 with a traceback."""
        typed = classify_failure(exc)
        name = type(typed if typed is not None else exc).__name__
        return (
            503,
            {
                "status": "failed",
                "error": name,
                "message": str(exc)[:500],
                "retryable": isinstance(typed, EngineFailure),
                "request_id": rid,
            },
        )

    # -- ops surface -----------------------------------------------------

    def healthz(self) -> dict:
        slo_states = self.slo.evaluate()
        fast = sorted(
            name
            for name, s in slo_states.items()
            if s["state"] == "fast_burn"
        )
        degraded = [n for n in fast if slo_states[n]["degrade"]]
        if self._stopping:
            status = "draining"
        elif fast:
            status = "degraded"
        else:
            status = "ok"
        return {
            "status": status,
            # Readiness: a fast-burning service is alive but should not
            # receive fresh low-priority traffic.
            "ready": not self._stopping and not fast,
            "uptime_seconds": round(time.time() - self.started_t, 3),
            "run_id": self.run.run_id,
            "queue_depth": len(self.queue),
            "queue_limit": self.queue.limit,
            "breaker": self.breaker.snapshot(),
            "requests_total": int(self._requests_total.value),
            "slo": {
                "states": {
                    name: s["state"] for name, s in slo_states.items()
                },
                "fast_burn": fast,
                "degraded": degraded,
            },
            "canary": self._canary_snapshot(),
            # Open-incident count from the bundle's durable
            # incidents.jsonl; the incidents_open gauge mirrors it so
            # /metrics scrapes agree with /healthz. 0 on a clean host
            # (the sink is never created without an incident).
            "incidents_open": self._incidents_open(),
        }

    def _incidents_open(self) -> int:
        from yuma_simulation_tpu.telemetry.incident import (
            open_incident_count,
        )

        if self.config.bundle_dir is None:
            return 0
        try:
            count = open_incident_count(self.config.bundle_dir)
        except Exception:  # noqa: BLE001 — health must answer anyway
            logger.warning("incident count failed", exc_info=True)
            return 0
        self.registry.gauge(
            "incidents_open",
            help="correlated incidents currently open in this bundle",
        ).set(count)
        return count

    def warm_buckets(self) -> list[str]:
        """The `ExVxM` shape buckets this process holds warm (warmup
        shapes + every successfully dispatched simulate shape, most
        recent last) — advertised by scale-out workers so the router's
        claim scoring can prefer a worker that already traced the
        requested shape."""
        with self._canary_lock:
            return list(self._canary_order)

    def _canary_snapshot(self) -> dict:
        with self._canary_lock:
            return dict(
                self._canary_state,
                buckets=len(self._canary_order),
                enabled=self.config.canary_interval_seconds > 0,
            )

    def metrics_text(self) -> str:
        return self.registry.prometheus_text()

    def close(self) -> None:
        """Graceful shutdown: stop admitting, drain the queue (queued
        requests resolve with a structured shutting-down 503), publish
        the flight bundle. Idempotent."""
        if self._closed:
            return
        self._stopping = True
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=30.0)
        # The dispatcher drains on its way out; anything still queued
        # (dispatcher never started, or died) resolves here.
        for item in self.queue.take_matching(lambda _p: True):
            item.resolve(
                503,
                {
                    "status": "shutting_down",
                    "error": "ServiceUnavailable",
                    "message": "the service is draining",
                    "request_id": item.ticket.request_id,
                },
            )
        self._closed = True
        # Release the process-global SLO hooks: a later service in the
        # same process must be able to claim the transition hook, and
        # the supervisor/sentinel `observe_duration` feeds must fall
        # back to whatever engine was installed before us.
        # `==`, not `is`: each attribute access mints a fresh bound
        # method; equality compares the underlying (self, func) pair.
        if self.slo.on_transition == self._slo_transition:
            self.slo.on_transition = None
        if self._slo_installed:
            from yuma_simulation_tpu.telemetry.slo import (
                peek_slo_engine,
                set_slo_engine,
            )

            if peek_slo_engine() is self.slo:
                set_slo_engine(self._slo_previous)
        if self.config.bundle_dir is not None:
            from yuma_simulation_tpu.telemetry.flight import FlightRecorder

            with self._ingress_lock:
                ingress, self._ingress_runs = self._ingress_runs, []
            with self._numerics_lock:
                nrecs = self._numerics_records
                self._numerics_records = []
            try:
                with self._publish_lock:
                    recorder = FlightRecorder(
                        self.config.bundle_dir, rotation=self._rotation
                    )
                    recorder.record(
                        self.run,
                        registry=self.registry,
                        extra_runs=ingress,
                        slo_engine=self.slo,
                    )
                    recorder.record_numerics(nrecs, run_id=self.run.run_id)
                    if self._rotation is not None:
                        # Graceful exit: release the retention pin and
                        # seal the tail so the bundle on disk is whole
                        # (no torn live segment for the next reader).
                        recorder.mark_run_closed(self.run.run_id)
                        recorder.seal_live_segment()
            except Exception:
                logger.warning(
                    "serve flight-bundle publish failed for %s",
                    self.config.bundle_dir,
                    exc_info=True,
                )
        try:
            # Stop any in-flight profile window so the trace publishes
            # rather than tears with the process.
            self.ops.close()
        except Exception:
            logger.warning("ops-plane close failed", exc_info=True)
        log_event(
            logger,
            "serve_closed",
            level=logging.INFO,
            requests=int(self._requests_total.value),
        )
