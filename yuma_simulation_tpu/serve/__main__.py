"""CLI: ``python -m yuma_simulation_tpu.serve`` — run (or smoke) the
warm-engine simulation service.

Foreground mode serves until interrupted; ``--smoke`` is the CI lane:
start a server on an ephemeral port, fire one of each contract-defining
request — a happy path, a structured admission rejection, a quota shed
(429 + Retry-After), and a coalesced same-bucket pair — then shut down
gracefully and leave the flight bundle for ``python -m tools.obsreport
BUNDLE --check`` to gate. Exit 0 only when every expectation held.
"""

from __future__ import annotations

import argparse
import concurrent.futures


def _build_config(args, **overrides) -> "ServeConfig":  # noqa: F821
    from yuma_simulation_tpu.serve.service import ServeConfig

    return ServeConfig(
        **overrides,
        queue_limit=args.queue_limit,
        coalesce_window_seconds=args.coalesce_window,
        max_batch=args.max_batch,
        tenant_rate=args.tenant_rate,
        tenant_burst=args.tenant_burst,
        default_deadline_seconds=args.deadline,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_seconds=args.breaker_cooldown,
        bundle_dir=args.bundle_dir,
        warmup_shapes=tuple(
            tuple(int(d) for d in shape.split("x"))
            for shape in (args.warmup or [])
        ),
        canary_interval_seconds=args.canary_interval,
        executable_cache_dir=args.executable_cache,
        replay_archive_dir=args.replay_archive,
        replay_cache_dir=args.replay_cache,
        replay_epochs_per_snapshot=args.replay_epochs_per_snapshot,
        replay_stride=args.replay_stride,
        api_keys_path=getattr(args, "api_keys", None),
        flight_rotation=getattr(args, "rotate_flight", False) or None,
    )


def run_smoke(args) -> int:
    """The serve smoke drill (see module docstring). CPU-safe."""
    from yuma_simulation_tpu.serve.server import (
        SimulationClient,
        SimulationServer,
        wait_until_ready,
    )
    from yuma_simulation_tpu.utils import setup_logging

    setup_logging()
    failures: list[str] = []

    def expect(cond: bool, what: str) -> None:
        print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    # The greedy tenant gets a NON-REFILLING bucket (rate 0): its burst
    # deterministically exhausts regardless of how fast this runner
    # executes requests — the shed path must not depend on a race
    # between the drill loop and the refill clock.
    server = SimulationServer(
        _build_config(
            args, tenant_overrides={"greedy": (0.0, args.tenant_burst)}
        )
    ).start()
    try:
        expect(wait_until_ready(server.url), "server answers /healthz")
        client = SimulationClient(server.url, tenant="smoke")

        # Happy path: a built-in case through the full pipeline, with
        # the 0.13.0 correlation contract — an X-Request-Id echo that
        # resolves into the flight bundle and a Server-Timing
        # critical-path breakdown.
        r = client.simulate(case="Case 1")
        expect(
            r.status == 200 and r.body.get("status") == "ok",
            f"happy path simulate -> 200 ok (got {r.status} "
            f"{r.body.get('status')})",
        )
        expect(
            r.request_id is not None,
            f"happy path echoes X-Request-Id (got {r.request_id})",
        )
        timing = r.server_timing
        expect(
            "execute" in timing and "queue" in timing,
            f"happy path returns Server-Timing critical path "
            f"(got {sorted(timing)})",
        )

        # Structured admission rejection: malformed payload, typed 400 —
        # STILL carrying the request id (rejections must correlate too).
        r = client.simulate(weights=[[1.0]])  # wrong rank, no stakes
        expect(
            r.status == 400 and r.body.get("error") == "AdmissionRejected",
            f"malformed payload -> 400 AdmissionRejected (got {r.status} "
            f"{r.body.get('error')})",
        )
        expect(
            r.request_id is not None,
            "rejection echoes X-Request-Id",
        )

        # Quota shed: exhaust one tenant's burst back-to-back; the
        # bucket refills at tenant_rate, so with the smoke's small burst
        # a tight loop must see a 429 with Retry-After.
        greedy = SimulationClient(server.url, tenant="greedy")
        shed = None
        for _ in range(args.tenant_burst + 2):
            r = greedy.simulate(case="Case 2")
            if r.status == 429:
                shed = r
                break
        expect(
            shed is not None
            and shed.retry_after is not None
            and shed.body.get("error") == "QueueOverflow",
            "tenant burst -> 429 QueueOverflow with Retry-After",
        )

        # Coalescing: two same-bucket requests in flight together ride
        # one donor-packed dispatch (coalesced=2 on both responses).
        with concurrent.futures.ThreadPoolExecutor(2) as pool:
            futs = [
                pool.submit(
                    SimulationClient(server.url, tenant=f"t{i}").simulate,
                    case="Case 3",
                )
                for i in range(2)
            ]
            results = [f.result() for f in futs]
        expect(
            all(r.status == 200 for r in results)
            and max(r.body.get("coalesced", 1) for r in results) >= 2,
            "concurrent same-bucket pair -> coalesced dispatch",
        )

        # The acceptance metrics surface on /metrics.
        metrics = client.metrics()
        for series in (
            "serve_queue_depth",
            "serve_requests_shed",
            "serve_breaker_open",
        ):
            expect(series in metrics, f"/metrics exposes {series}")

        # The SLO surface: /healthz reflects burn state (healthy here).
        h = client.healthz()
        expect(
            h.body.get("ready") is True and "slo" in h.body,
            f"/healthz reports SLO readiness (got {h.body.get('slo')})",
        )

        # The numerics canary (0.14.0): one forced tick through a warm
        # bucket must compare the primary rung against its demoted
        # fallback drift-clean, and /healthz must surface the tick.
        state = server.service.run_canary_once()
        expect(
            state is not None
            and state.get("ticks", 0) >= 1
            and state.get("drift", 0) == 0,
            f"numerics canary tick drift-clean (got {state})",
        )
        h = client.healthz()
        expect(
            h.body.get("canary", {}).get("ticks", 0) >= 1
            and h.body.get("status") == "ok",
            f"/healthz surfaces the canary tick, still ok "
            f"(got {h.body.get('canary')})",
        )
    finally:
        server.close()
        if args.executable_cache:
            # The cold-start artifact: process-lifetime cache stats
            # (hits/misses/builds) beside the artifacts, so the CI
            # cold-start lane asserts on run 2's copy.
            from yuma_simulation_tpu.simulation.aot import active_cache

            cache = active_cache()
            if cache is not None:
                cache.write_stats()

    if failures:
        print(f"\nserve smoke FAILED ({len(failures)} expectation(s))")
        return 1
    print("\nserve smoke passed")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m yuma_simulation_tpu.serve",
        description=__doc__.split("\n\n")[0],
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--bundle-dir",
        default=None,
        help="flight-bundle directory (spans + request ledger + metrics)",
    )
    parser.add_argument("--queue-limit", type=int, default=64)
    parser.add_argument("--coalesce-window", type=float, default=0.05)
    parser.add_argument("--max-batch", type=int, default=8)
    parser.add_argument("--tenant-rate", type=float, default=20.0)
    parser.add_argument("--tenant-burst", type=int, default=10)
    parser.add_argument("--deadline", type=float, default=120.0)
    parser.add_argument("--breaker-threshold", type=int, default=3)
    parser.add_argument("--breaker-cooldown", type=float, default=30.0)
    parser.add_argument(
        "--canary-interval",
        type=float,
        default=0.0,
        help="background numerics-canary cadence in seconds (0 "
        "disables): re-execute a warm shape bucket on the demoted "
        "rung and compare per-epoch fingerprints",
    )
    parser.add_argument(
        "--warmup",
        action="append",
        metavar="ExVxM",
        help="pre-compile this shape at startup (repeatable), e.g. 40x3x2",
    )
    parser.add_argument(
        "--executable-cache",
        default=None,
        metavar="DIR",
        help="AOT executable-cache directory (simulation.aot): warmup "
        "preloads published executables, misses publish for the next "
        "worker, and JAX's persistent compilation cache is enabled "
        "beside it — the cold-start knob (README 'Cold start')",
    )
    parser.add_argument(
        "--replay-archive",
        default=None,
        metavar="DIR",
        help="snapshot-timeline archive root (replay/): mounts "
        "POST /v1/whatif and GET /v1/replay when --replay-cache is "
        "also set",
    )
    parser.add_argument(
        "--replay-cache",
        default=None,
        metavar="DIR",
        help="epoch-state cache root for what-if suffix resume",
    )
    parser.add_argument(
        "--replay-epochs-per-snapshot", type=int, default=4,
        help="epochs each archived snapshot contributes to the replay "
        "scenario",
    )
    parser.add_argument(
        "--replay-stride", type=int, default=8,
        help="carry-checkpoint stride (epochs) of cached baselines",
    )
    parser.add_argument(
        "--replay-controller",
        default=None,
        metavar="DIR",
        help="co-host the continuous replay controller: sweep the "
        "mounted --replay-archive into this store root (durable "
        "watermarks, incremental fleet windows) on a background "
        "thread, keeping the shared --replay-cache warm for what-ifs",
    )
    parser.add_argument(
        "--replay-versions",
        nargs="+",
        default=["Yuma 2 (Adrian-Fish)"],
        help="Yuma variants the co-hosted controller sweeps",
    )
    parser.add_argument(
        "--api-keys",
        default=None,
        metavar="PATH",
        help="signed-API-key keyfile (JSON tenant -> secret): requests "
        "must present a valid X-Api-Key and the verified tenant "
        "replaces any payload claim (typed 401 otherwise)",
    )
    parser.add_argument(
        "--rotate-flight",
        action="store_true",
        help="segmented flight-recorder rotation for the bundle: "
        "spans/metrics/numerics append into crash-safe size/age-bounded "
        "segments under BUNDLE/segments/ (default: monolithic files; "
        "YUMA_TPU_FLIGHT_ROTATE=1 also opts in)",
    )
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="CI smoke: ephemeral port, contract-defining requests, "
        "graceful shutdown, exit nonzero on any miss",
    )
    # -- horizontal scale-out (PR 16) ---------------------------------
    scale = parser.add_argument_group(
        "scale-out",
        "worker-pool mode (one warm worker claiming a lease slot), "
        "router mode (the stateless front placing onto the pool), and "
        "the kill-a-worker chaos drill",
    )
    scale.add_argument(
        "--worker-pool",
        default=None,
        metavar="DIR",
        help="join this pool directory as a WORKER: claim "
        "--worker-slot, serve on an ephemeral port, heartbeat "
        "state-cache/warm-bucket advertisements",
    )
    scale.add_argument(
        "--worker-slot", type=int, default=0,
        help="pool slot (lease unit) this worker claims",
    )
    scale.add_argument(
        "--worker-id", default=None,
        help="stable worker identity (default: worker-<pid>)",
    )
    scale.add_argument(
        "--worker-ttl", type=float, default=3.0,
        help="lease TTL seconds: miss heartbeats this long and the "
        "router treats the worker as dead",
    )
    scale.add_argument(
        "--router",
        action="store_true",
        help="run the stateless ROUTER: spawn --workers warm workers "
        "into --worker-pool and place every request by state-cache "
        "affinity",
    )
    scale.add_argument(
        "--workers", type=int, default=2,
        help="initial worker count for --router",
    )
    scale.add_argument(
        "--max-workers", type=int, default=8,
        help="pool slot ceiling (router + autoscaler)",
    )
    scale.add_argument(
        "--no-affinity",
        action="store_true",
        help="router: round-robin placement instead of "
        "state-cache-affinity claim scoring",
    )
    scale.add_argument(
        "--worker-arg",
        action="append",
        default=None,
        metavar="ARG",
        help="extra CLI arg forwarded to every spawned worker "
        "(repeatable; '{worker_id}' substitutes)",
    )
    scale.add_argument(
        "--scaleout-drill",
        action="store_true",
        help="CI chaos lane: 3 workers + router, kill one mid-load, "
        "prove typed reroutes + affinity + autoscaler, merge and "
        "gate every flight bundle; exit nonzero on any miss",
    )
    args = parser.parse_args(argv)

    if args.smoke:
        return run_smoke(args)
    if args.scaleout_drill:
        from yuma_simulation_tpu.serve.drill import run_scaleout_drill

        return run_scaleout_drill(args)
    if args.worker_pool and not args.router:
        from yuma_simulation_tpu.serve.worker import run_worker

        return run_worker(args)

    from yuma_simulation_tpu.utils import setup_logging

    setup_logging()
    if args.router:
        from yuma_simulation_tpu.serve.router import (
            RouterConfig,
            RouterService,
        )
        from yuma_simulation_tpu.serve.server import SimulationServer

        if not args.worker_pool:
            parser.error("--router requires --worker-pool DIR")
        router = RouterService(
            RouterConfig(
                pool_dir=args.worker_pool,
                workers=args.workers,
                max_workers=args.max_workers,
                worker_args=tuple(args.worker_arg or ()),
                lease_ttl_seconds=args.worker_ttl,
                bundle_dir=args.bundle_dir,
                api_keys_path=args.api_keys,
                affinity=not args.no_affinity,
                default_deadline_seconds=args.deadline,
                max_batch=args.max_batch,
                replay_archive_dir=args.replay_archive,
                replay_cache_dir=args.replay_cache,
                replay_epochs_per_snapshot=(
                    args.replay_epochs_per_snapshot
                ),
                replay_stride=args.replay_stride,
            )
        )
        router.start_workers()
        server = SimulationServer(
            service=router, host=args.host, port=args.port
        )
        print(
            f"routing on {server.url} "
            f"({args.workers} workers; Ctrl-C to stop)"
        )
        server.serve_forever()
        return 0

    from yuma_simulation_tpu.serve.server import SimulationServer

    server = SimulationServer(
        _build_config(args), host=args.host, port=args.port
    )
    stop = None
    if args.replay_controller:
        if not (args.replay_archive and args.replay_cache):
            parser.error(
                "--replay-controller requires --replay-archive and "
                "--replay-cache"
            )
        import threading

        from yuma_simulation_tpu.replay import (
            ControllerConfig,
            ReplayController,
            SnapshotArchive,
            StateCache,
        )

        # The co-hosted controller sweeps into the SAME cache the
        # what-if handlers resume from, so serving traffic rides warm
        # carries the standing sweep keeps extending.
        controller = ReplayController(
            SnapshotArchive(args.replay_archive),
            StateCache(args.replay_cache),
            ControllerConfig(
                store_root=args.replay_controller,
                versions=tuple(args.replay_versions),
                epochs_per_snapshot=args.replay_epochs_per_snapshot,
                stride=args.replay_stride,
            ),
        )
        stop = threading.Event()
        threading.Thread(
            target=controller.run_forever,
            kwargs={"stop": stop.is_set},
            name="replay-controller",
            daemon=True,
        ).start()
        print(f"replay controller sweeping into {args.replay_controller}")
    print(f"serving on {server.url} (Ctrl-C to stop)")
    try:
        server.serve_forever()
    finally:
        if stop is not None:
            stop.set()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
