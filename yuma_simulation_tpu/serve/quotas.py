"""Per-tenant token buckets + the global bounded run queue.

The backpressure half of the serving tier's robustness spine: a burst of
hostile (or merely enthusiastic) traffic must shed load with a typed
:class:`..resilience.errors.QueueOverflow` — surfaced as ``429`` +
``Retry-After`` — instead of growing an unbounded backlog that takes the
whole process down. Two bounds, checked in order:

- **tenant quota** (:class:`TenantQuotas`): a token bucket per tenant
  (`burst` capacity, `rate` tokens/second refill), so one tenant's
  flood cannot starve the others — the rejected tenant's
  ``retry_after`` is exactly the time until its next token;
- **global run queue** (:class:`BoundedRunQueue`): a hard bound on
  admitted-but-undispatched work; at the bound, new requests shed with
  a ``retry_after`` scaled to the queue's current drain estimate.

Both feed the metrics registry (``serve_queue_depth`` gauge,
``serve_requests_shed`` counter) so overload is visible on `/metrics`
while it is happening, not after. Clocks are injectable for
deterministic tests; everything is thread-safe (handlers run on the
HTTP server's per-connection threads).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Optional

from yuma_simulation_tpu.resilience.errors import QueueOverflow


class TokenBucket:
    """A classic token bucket: `burst` capacity, `rate` tokens/second.

    :meth:`try_acquire` returns 0.0 when a token was taken, else the
    seconds until one becomes available (the client's ``Retry-After``).
    `rate=0` makes the bucket non-refilling — `burst` requests total,
    then permanent shed (drill configurations)."""

    def __init__(
        self,
        rate: float,
        burst: int,
        clock: Callable[[], float] = time.monotonic,
    ):
        if burst < 1:
            raise ValueError("TokenBucket burst must be >= 1")
        if rate < 0:
            raise ValueError("TokenBucket rate must be >= 0")
        self.rate = float(rate)
        self.burst = int(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._stamp = clock()
        self._lock = threading.Lock()

    def try_acquire(self) -> float:
        with self._lock:
            now = self._clock()
            if self.rate > 0:
                self._tokens = min(
                    float(self.burst),
                    self._tokens + (now - self._stamp) * self.rate,
                )
            self._stamp = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            if self.rate <= 0:
                # Non-refilling bucket: "try again much later" rather
                # than a divide-by-zero or a lying small number.
                return 60.0
            return (1.0 - self._tokens) / self.rate


class TenantQuotas:
    """Get-or-create a :class:`TokenBucket` per tenant and admit through
    it. `overrides` maps tenant -> (rate, burst) for tenants with
    negotiated quotas; everyone else shares the default shape (each
    tenant still gets its OWN bucket — the default is a shape, not a
    shared pool).

    The bucket table is BOUNDED (`max_tenants`, LRU eviction of
    non-override tenants): tenant is a free-form request field, and a
    hostile client minting a fresh tenant per request must not grow the
    long-lived service's memory without bound. Evicting an idle bucket
    merely resets that tenant to a full burst — a small quota give-away
    under active eviction pressure, never a shed of legitimate work."""

    def __init__(
        self,
        rate: float = 20.0,
        burst: int = 10,
        overrides: Optional[dict] = None,
        clock: Callable[[], float] = time.monotonic,
        max_tenants: int = 10_000,
    ):
        if max_tenants < 1:
            raise ValueError("max_tenants must be >= 1")
        self.rate = rate
        self.burst = burst
        self.overrides = dict(overrides or {})
        self.max_tenants = max_tenants
        self._clock = clock
        self._buckets: collections.OrderedDict[str, TokenBucket] = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()

    def bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                rate, burst = self.overrides.get(
                    tenant, (self.rate, self.burst)
                )
                b = TokenBucket(rate, burst, clock=self._clock)
                self._buckets[tenant] = b
                while len(self._buckets) > self.max_tenants:
                    # Oldest-used first; negotiated-override tenants are
                    # pinned (their quota state must survive a flood).
                    for victim in self._buckets:
                        if victim not in self.overrides:
                            del self._buckets[victim]
                            break
                    else:
                        break
            else:
                self._buckets.move_to_end(tenant)
            return b

    def admit(self, tenant: str) -> None:
        """Take one token for `tenant` or raise a typed
        :class:`QueueOverflow` carrying the exact refill wait."""
        wait = self.bucket(tenant).try_acquire()
        if wait > 0:
            raise QueueOverflow(
                f"tenant {tenant!r} exceeded its request quota; "
                f"retry in {wait:.2f}s",
                retry_after=wait,
            )


class BoundedRunQueue:
    """The global admitted-work queue, bounded hard.

    A plain deque + condition (not `queue.Queue`) so the dispatcher can
    take items selectively (the coalescer peeks for bucket-mates) and
    the depth gauge updates under the same lock as the mutation.
    `put()` never blocks: at the bound it raises a typed
    :class:`QueueOverflow` whose ``retry_after`` is the current depth
    times `drain_estimate_seconds` (a deliberately simple model — the
    point is a monotone, honest signal, not a scheduler)."""

    def __init__(
        self,
        limit: int,
        *,
        drain_estimate_seconds: float = 0.25,
        registry=None,
    ):
        if limit < 1:
            raise ValueError("BoundedRunQueue limit must be >= 1")
        self.limit = int(limit)
        self.drain_estimate_seconds = drain_estimate_seconds
        self._items: Deque = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        if registry is None:
            from yuma_simulation_tpu.telemetry.metrics import get_registry

            registry = get_registry()
        self._depth_gauge = registry.gauge(
            "serve_queue_depth", help="serving run-queue occupancy"
        )
        self._shed_counter = registry.counter(
            "serve_requests_shed",
            help="requests shed with 429 (tenant quota or queue bound)",
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def record_shed(self) -> None:
        """Count a shed that happened upstream of the queue (tenant
        quota, overload drill) on the same ``serve_requests_shed``
        series — one counter for every 429, wherever it was decided."""
        self._shed_counter.inc()

    def put(self, item) -> None:
        with self._lock:
            if len(self._items) >= self.limit:
                depth = len(self._items)
                self._shed_counter.inc()
                raise QueueOverflow(
                    f"run queue at its bound ({depth}/{self.limit}); "
                    "shedding",
                    retry_after=max(
                        self.drain_estimate_seconds,
                        depth * self.drain_estimate_seconds,
                    ),
                    queue_depth=depth,
                )
            self._items.append(item)
            self._depth_gauge.set(len(self._items))
            self._not_empty.notify()

    def get(self, timeout: Optional[float] = None):
        """Pop the oldest item, waiting up to `timeout`; None on
        timeout (the dispatcher's idle tick)."""
        with self._lock:
            if not self._items:
                self._not_empty.wait(timeout)
            if not self._items:
                return None
            item = self._items.popleft()
            self._depth_gauge.set(len(self._items))
            return item

    def take_matching(self, predicate, limit: Optional[int] = None) -> list:
        """Remove and return up to `limit` queued items satisfying
        `predicate` (queue order preserved; items beyond the limit stay
        queued) — the coalescer's bucket-mate sweep."""
        with self._lock:
            taken = []
            for i in self._items:
                if limit is not None and len(taken) >= limit:
                    break
                if predicate(i):
                    taken.append(i)
            if taken:
                for i in taken:
                    self._items.remove(i)
                self._depth_gauge.set(len(self._items))
            return taken
