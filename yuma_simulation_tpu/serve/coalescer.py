"""Shape-bucket request coalescing: many tenants, one warm dispatch.

The throughput half of the serving tier. Heterogeneous tenants send
heterogeneous shapes; compiled programs are per-shape. Left alone, a
busy service would trace one program per ragged request — exactly the
cold-compile storm a warm-engine service exists to avoid. Instead,
requests that land in the same planner :class:`ShapeBucket` within a
short window are DONOR-PACKED (:func:`..simulation.sweep.pack_scenarios`
— the PR 6 mechanism, unchanged) into one batched dispatch riding one
warm compiled shape, and each request's lanes are sliced back out.

The bitwise contract is inherited, not re-proven: `pack_scenarios` pads
with zero stakes and mask-excluded miner columns, and
tests/unit/test_planner.py pins that a packed lane is bit-for-bit the
same scenario dispatched alone through the same bucket. Coalescing
therefore changes LATENCY GROUPING only, never results — pinned again
end-to-end by tests/unit/test_serve.py's soak test.

This module owns the two pure pieces (grouping and result slicing);
the dispatcher loop that drives them lives in :mod:`.service`.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

import numpy as np


def gather_group(
    queue,
    first,
    *,
    window_seconds: float,
    max_batch: int,
    sleep: Callable[[float], None] = time.sleep,
) -> list:
    """The dispatch group for `first`: itself, plus every queued request
    sharing its coalesce key after one `window_seconds` gathering pause
    (bounded by `max_batch`). Requests with no key (sweeps, tables,
    fused-engine requests) always dispatch alone, and a zero window
    disables gathering without disabling the shared-bucket packing of
    whatever already queued."""
    key = first.ticket.coalesce_key
    if key is None or max_batch <= 1:
        return [first]
    if window_seconds > 0:
        sleep(window_seconds)
    mates = queue.take_matching(
        lambda p: p.ticket.coalesce_key == key, limit=max_batch - 1
    )
    return [first] + mates


def slice_simulate_response(
    dividends: np.ndarray,
    lane: int,
    ticket,
    *,
    quarantine_entries: Sequence,
    report,
    coalesced: int,
) -> dict:
    """One request's response body out of a (possibly packed) batched
    result: crop the lane to the scenario's own `[E, V]` view (padding
    is exact zeros by the packing contract, so cropping loses nothing),
    attach the request's OWN quarantine provenance (local epoch/tensor —
    lane indices are an internal detail), and summarize what degraded.

    `status` is the graceful-degradation contract: ``"ok"`` for a clean
    lane — even if a *different* tenant's lane was quarantined —
    ``"partial"`` when THIS lane was masked from some epoch on."""
    E, V, _ = ticket.scenario.weights.shape
    lane_div = np.asarray(dividends[lane])[:E, :V]
    mine = [
        {"epoch": int(e.epoch), "tensor": str(e.tensor)}
        for e in quarantine_entries
        if e.case == lane
    ]
    degraded = bool(
        mine
        or report.stalls_killed
        or report.engine_demotions
        or report.mesh_shrinks
    )
    body = {
        "status": "partial" if mine else "ok",
        "request_id": ticket.request_id,
        "tenant": ticket.tenant,
        "engine": ",".join(report.engines_used),
        "coalesced": int(coalesced),
        "degraded": degraded,
        "dividends": lane_div.tolist(),
        "total_dividends": lane_div.sum(axis=0).tolist(),
        "report": {
            "stalls_killed": report.stalls_killed,
            "engine_demotions": report.engine_demotions,
            "mesh_shrinks": report.mesh_shrinks,
            "units_retried": report.units_retried,
            "lanes_quarantined": len(mine),
            "engines_used": list(report.engines_used),
        },
    }
    if mine:
        body["quarantine"] = mine
    return body
