"""The scale-out chaos drill (``--scaleout-drill``): the CI proof that
the horizontal serving tier survives a worker killed mid-load.

What it stages, on one machine with real processes and real sockets:

1. a tiny synthetic snapshot archive (two subnets), a shared AOT
   executable cache, and a 3-worker pool behind one
   :class:`.router.RouterService` front;
2. **affinity proof**: repeated what-ifs for one subnet all route to
   the worker that built the baseline (``X-Worker`` stable,
   ``cache_hit`` true, suffix epochs saved > 0), while the
   affinity-OFF control router round-robins the same traffic onto
   cold workers that must rebuild — with bitwise-identical deltas
   either way;
3. **kill drill**: a concurrent simulate load while one worker is
   SIGKILLed mid-flight — every response must be a typed 200 bitwise
   equal to a solo single-process reference, with ``worker_lost`` +
   ``request_rerouted`` ledgered and ``serve_reroutes_total`` > 0.
   Zero client-visible transport errors;
4. **autoscaler proof**: a synthetic fast-burn SLO makes one
   :meth:`.autoscaler.Autoscaler.tick` spawn a worker that pays ZERO
   AOT builds (the shared executable cache is its warmup), and idling
   makes a later tick retire it gracefully;
5. every flight bundle (router, control router, each worker) merges
   into ONE bundle directory for ``python -m tools.obsreport --check``
   / ``sloreport`` / ``driftreport`` to gate — the cross-process trace
   must stitch (no orphan spans) and every ledger record must resolve.

Exit 0 only when every expectation held.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import pathlib
import shutil
import statistics
import tempfile
import time

#: The version every drill request runs (a registered Yuma version).
VERSION = "Yuma 2 (Adrian-Fish)"


def _merge_bundle_dirs(dirs, out_dir: pathlib.Path) -> list[str]:
    """Concatenate sibling bundles' jsonl streams into one on-disk
    bundle (dedup is the reader's job — identities are unique by
    construction). ``slo.json``/``report.json`` keep the FIRST
    bundle's copy (caller passes the router first)."""
    from yuma_simulation_tpu.telemetry.flight import (
        COSTS_NAME,
        LEDGER_NAME,
        METRICS_NAME,
        NUMERICS_NAME,
        REPORT_NAME,
        SLO_NAME,
        SPANS_NAME,
    )

    out_dir.mkdir(parents=True, exist_ok=True)
    # Only PUBLISHED bundles merge: a SIGKILLed worker leaves its
    # crash-safe ledger.jsonl behind but its spans died with the
    # process, so its torn bundle (ledger, no spans) would only add
    # unresolvable records — its loss is the ROUTER's `worker_lost`
    # ledger entry, which does resolve.
    published = [
        pathlib.Path(d)
        for d in dirs
        if (pathlib.Path(d) / SPANS_NAME).exists()
    ]
    for name in (
        LEDGER_NAME, SPANS_NAME, METRICS_NAME, COSTS_NAME, NUMERICS_NAME,
    ):
        lines = []
        for d in published:
            path = d / name
            if path.exists():
                text = path.read_text()
                lines.extend(
                    ln for ln in text.splitlines() if ln.strip()
                )
        if lines:
            (out_dir / name).write_text("\n".join(lines) + "\n")
    for name in (SLO_NAME, REPORT_NAME):
        for d in published:
            path = d / name
            if path.exists():
                shutil.copyfile(path, out_dir / name)
                break
    return [str(d) for d in published]


class _FakeBurn:
    """A hand-cranked SLO engine for the autoscaler phase: `degraded()`
    returns whatever the drill set, nothing else consulted."""

    def __init__(self):
        self.burning: tuple = ()

    def degraded(self) -> tuple:
        return self.burning


def run_scaleout_drill(args) -> int:
    """See the module docstring. CPU-safe; ~3 worker subprocesses."""
    from yuma_simulation_tpu.replay import SnapshotArchive
    from yuma_simulation_tpu.replay.archive import synthetic_timeline
    from yuma_simulation_tpu.serve.autoscaler import Autoscaler
    from yuma_simulation_tpu.serve.router import RouterConfig, RouterService
    from yuma_simulation_tpu.serve.server import (
        SimulationClient,
        SimulationServer,
        wait_until_ready,
    )
    from yuma_simulation_tpu.serve.service import (
        ServeConfig,
        SimulationService,
    )
    from yuma_simulation_tpu.utils import setup_logging

    setup_logging()
    failures: list[str] = []

    def expect(cond: bool, what: str) -> None:
        print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    merged_dir = pathlib.Path(args.bundle_dir or "scaleout-bundle")
    work = pathlib.Path(tempfile.mkdtemp(prefix="yuma-scaleout-"))
    print(f"scale-out drill workspace: {work}")

    # -- stage: archive + pool ----------------------------------------
    archive_dir = work / "archive"
    arch = SnapshotArchive(archive_dir)
    synthetic_timeline(
        arch, 1, snapshots=2, seed=0, num_validators=3, num_miners=4
    )
    synthetic_timeline(
        arch, 2, snapshots=2, seed=1, num_validators=3, num_miners=4
    )
    exec_cache = work / "exec-cache"
    worker_args = (
        "--replay-archive", str(archive_dir),
        "--replay-cache", str(work / "caches" / "{worker_id}"),
        "--replay-epochs-per-snapshot", "2",
        "--replay-stride", "2",
        "--executable-cache", str(exec_cache),
        "--queue-limit", "64",
        "--tenant-rate", "1000",
        "--tenant-burst", "1000",
        "--coalesce-window", "0.0",
        "--deadline", "120",
    )
    config = RouterConfig(
        pool_dir=str(work / "pool"),
        workers=3,
        max_workers=5,
        worker_args=worker_args,
        lease_ttl_seconds=1.5,
        bundle_dir=str(work / "router-bundle"),
        affinity=True,
        reroute_attempts=3,
        default_deadline_seconds=120.0,
        forward_timeout=60.0,
        replay_archive_dir=str(archive_dir),
        replay_cache_dir=str(work / "router-scratch"),
        replay_epochs_per_snapshot=2,
        replay_stride=2,
    )
    router = RouterService(config)
    control = RouterService(
        dataclasses.replace(
            config,
            affinity=False,
            bundle_dir=str(work / "control-bundle"),
            replay_cache_dir=str(work / "control-scratch"),
        )
    )
    # The solo single-process reference the routed answers must match
    # bitwise (same serve knobs, no pool).
    solo = SimulationService(
        ServeConfig(
            coalesce_window_seconds=0.0,
            tenant_rate=1000.0,
            tenant_burst=1000,
            replay_archive_dir=str(archive_dir),
            replay_cache_dir=str(work / "solo-cache"),
            replay_epochs_per_snapshot=2,
            replay_stride=2,
        )
    )
    front = SimulationServer(service=router)
    control_front = SimulationServer(service=control)
    heartbeat = config.lease_ttl_seconds / 3.0

    def wait_ads(predicate, timeout: float = 20.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate(router.pool.scan()):
                return True
            time.sleep(heartbeat / 2.0)
        return False

    killed_worker = None
    try:
        router.start_workers()
        front.start()
        control_front.start()
        expect(
            wait_until_ready(front.url), "router answers /healthz"
        )
        client = SimulationClient(front.url, tenant="drill")
        h = client.healthz()
        expect(
            h.body.get("role") == "router"
            and h.body.get("workers", {}).get("live") == 3,
            f"3 workers live behind the router "
            f"(got {h.body.get('workers')})",
        )

        # -- phase A: state-cache affinity ----------------------------
        def whatif_spec(netuid: int, factor: float) -> dict:
            return {
                "netuid": netuid,
                "version": VERSION,
                "from_epoch": 2,
                "stake_scale": [[0, factor]],
            }

        r = client.whatif(whatif_spec(1, 2.0))
        expect(
            r.status == 200 and r.body.get("status") == "ok",
            f"first what-if builds the baseline (got {r.status} "
            f"{r.body.get('error', r.body.get('status'))})",
        )
        holder = r.headers.get("X-Worker")
        expect(bool(holder), f"routed response names its worker ({holder})")
        # Let the holder's next heartbeat advertise the new prefix.
        expect(
            wait_ads(
                lambda ads: any(
                    ad.get("worker_id") == holder
                    and ad.get("held_prefixes")
                    for ad in ads
                )
            ),
            "holder advertises its state-cache prefix",
        )
        on_durs: list[float] = []
        on_workers: set = set()
        hits_saved = 0
        on_deltas = []
        for i in range(3):
            t0 = time.perf_counter()
            r = client.whatif(whatif_spec(1, 3.0 + i))
            on_durs.append(time.perf_counter() - t0)
            on_workers.add(r.headers.get("X-Worker"))
            if r.body.get("cache_hit"):
                hits_saved += int(r.body.get("epochs_saved", 0))
            on_deltas.append(r.body.get("total_dividend_delta"))
        expect(
            on_workers == {holder},
            f"repeated what-ifs all route to the checkpoint holder "
            f"(got {sorted(on_workers)} vs {holder})",
        )
        expect(
            hits_saved > 0,
            f"affinity hits resume from held suffix state "
            f"(epochs saved {hits_saved})",
        )
        affinity_hits = router.registry.counter("affinity_hits_total").value
        expect(
            affinity_hits >= 3,
            f"affinity_hits_total counted the placements "
            f"({affinity_hits})",
        )

        # Control arm: same shape of traffic on subnet 2 through the
        # affinity-OFF router — round-robin lands cold workers that
        # must rebuild the baseline the holder already has.
        control_client = SimulationClient(
            control_front.url, tenant="drill"
        )
        seed = client.whatif(whatif_spec(2, 2.0))  # seed ONE holder
        expect(
            seed.status == 200,
            f"subnet-2 baseline seeded (got {seed.status})",
        )
        off_durs: list[float] = []
        off_misses = 0
        off_deltas = []
        for i in range(3):
            t0 = time.perf_counter()
            r = control_client.whatif(whatif_spec(2, 3.0 + i))
            off_durs.append(time.perf_counter() - t0)
            if r.status == 200 and not r.body.get("cache_hit"):
                off_misses += 1
            off_deltas.append(r.body.get("total_dividend_delta"))
        expect(
            off_misses >= 1,
            f"affinity-off round-robin pays cold rebuilds "
            f"({off_misses} misses)",
        )
        # Bitwise cross-worker proof: the SAME spec served twice by
        # the round-robin control (two different workers — one a cold
        # rebuild, one a held-suffix resume) must agree exactly, and
        # the routed affinity answer must equal the solo reference.
        dup_a = control_client.whatif(whatif_spec(2, 9.0))
        dup_b = control_client.whatif(whatif_spec(2, 9.0))
        expect(
            dup_a.status == 200
            and dup_b.status == 200
            and dup_a.body.get("total_dividend_delta")
            == dup_b.body.get("total_dividend_delta"),
            "same what-if on two workers is bitwise identical",
        )
        solo_w_status, solo_w_body, _ = solo.handle(
            "whatif",
            {"whatif": whatif_spec(1, 3.0), "tenant": "drill"},
        )
        expect(
            solo_w_status == 200
            and solo_w_body.get("total_dividend_delta") == on_deltas[0],
            "routed affinity what-if is bitwise the solo reference",
        )
        p50_on = statistics.median(on_durs)
        p50_off = statistics.median(off_durs)
        print(
            f"     what-if p50: affinity on {p50_on * 1000:.1f} ms, "
            f"off {p50_off * 1000:.1f} ms"
        )

        # -- phase B: kill a worker mid-load --------------------------
        solo_status, solo_body, _ = solo.handle(
            "simulate", {"case": "Case 1", "tenant": "drill"}
        )
        expect(
            solo_status == 200 and solo_body.get("status") == "ok",
            "solo reference simulate succeeds",
        )
        killed_worker = holder
        results = []
        with concurrent.futures.ThreadPoolExecutor(4) as pool:
            futs = [
                pool.submit(
                    SimulationClient(front.url, tenant="drill").simulate,
                    case="Case 1",
                )
                for _ in range(16)
            ]
            time.sleep(0.2)
            expect(
                router.pool.kill(killed_worker),
                f"SIGKILLed worker {killed_worker} mid-load",
            )
            results = [f.result() for f in futs]
        bad = [
            (r.status, r.body.get("error"))
            for r in results
            if r.status != 200 or r.body.get("status") != "ok"
        ]
        expect(
            not bad,
            f"all 16 concurrent requests answered 200 ok through the "
            f"kill (bad: {bad})",
        )
        mismatched = sum(
            1
            for r in results
            if r.body.get("dividends") != solo_body.get("dividends")
            or r.body.get("total_dividends")
            != solo_body.get("total_dividends")
        )
        expect(
            mismatched == 0,
            f"every routed response is bitwise the solo reference "
            f"({mismatched} mismatched)",
        )
        reroutes = router.registry.counter("serve_reroutes_total").value
        expect(
            reroutes > 0,
            f"serve_reroutes_total > 0 after the kill ({reroutes})",
        )
        ledger_events = [e.get("event") for e in router.ledger.entries()]
        expect(
            "worker_lost" in ledger_events,
            "worker_lost ledgered for the killed worker",
        )
        expect(
            "request_rerouted" in ledger_events,
            "request_rerouted ledgered for the moved requests",
        )

        # -- phase C: SLO-burn autoscaler -----------------------------
        burn = _FakeBurn()
        scaler = Autoscaler(
            router,
            slo_engine=burn,
            min_workers=2,
            max_workers=4,
            idle_retire_seconds=0.8,
            cooldown_seconds=0.0,
        )
        burn.burning = ("serve_request_seconds",)
        live_before = len(router.pool.live())
        outcome = scaler.tick()
        expect(
            outcome == "spawn",
            f"fast-burn tick spawns a worker (got {outcome!r})",
        )
        expect(
            wait_ads(
                lambda ads: sum(1 for a in ads if a["alive"])
                == live_before + 1
            ),
            "spawned worker joins the pool",
        )
        spawned = [
            ad
            for ad in router.pool.live()
            if ad.get("started_t", 0) == max(
                a.get("started_t", 0) for a in router.pool.live()
            )
        ]
        expect(
            spawned and int(spawned[0].get("aot_builds", -1)) == 0,
            f"spawned worker paid ZERO AOT builds (ad: "
            f"{spawned[0].get('aot_builds') if spawned else '?'})",
        )
        burn.burning = ()
        scaler.tick()  # records idle_since for everyone
        time.sleep(1.0)
        outcome = scaler.tick()
        expect(
            outcome == "retire",
            f"idle tick retires the youngest worker (got {outcome!r})",
        )
        ledger_events = [e.get("event") for e in router.ledger.entries()]
        expect(
            "worker_spawned" in ledger_events
            and "worker_retired" in ledger_events,
            "worker_spawned + worker_retired ledgered",
        )
    finally:
        control_front.close()
        front.close()
        solo.close()

    # -- merge + gate the flight bundles ------------------------------
    worker_bundles = sorted(
        (work / "pool" / "workers").glob("*/bundle")
    )
    merged_from = _merge_bundle_dirs(
        [work / "router-bundle", work / "control-bundle", *worker_bundles],
        merged_dir,
    )
    # The killed worker publishes NO bundle (that is the point of
    # SIGKILL) — everyone else must have.
    expect(
        len(merged_from) >= 3,
        f"router + control + surviving workers published bundles "
        f"({len(merged_from)} merged into {merged_dir})",
    )
    killed_bundle = str(
        work / "pool" / "workers" / str(killed_worker) / "bundle"
    )
    expect(
        killed_bundle not in merged_from,
        "SIGKILLed worker published no bundle (its spans died with it)",
    )
    from yuma_simulation_tpu.telemetry.flight import (
        check_bundle,
        check_stitched,
        load_bundle,
    )

    bundle = load_bundle(merged_dir)
    problems = check_bundle(bundle)
    expect(
        not problems,
        f"merged bundle passes check_bundle ({problems[:3]})",
    )
    stitched = check_stitched([bundle])
    expect(
        not stitched,
        f"cross-process trace stitches with no orphan spans "
        f"({stitched[:3]})",
    )
    lost_ads = [
        e
        for e in bundle.ledger
        if e.get("event") == "worker_lost"
        and e.get("worker") == killed_worker
    ]
    expect(
        bool(lost_ads),
        "merged ledger pins the kill to the killed worker id",
    )

    if failures:
        print(f"\nscale-out drill FAILED ({len(failures)} expectation(s))")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"\nscale-out drill passed (merged bundle: {merged_dir})")
    return 0
