"""SLO-burn-driven pool sizing for the horizontal serving tier.

The burn-rate engine (:mod:`..telemetry.slo`) already decides *when
the service is in trouble* — a fast-burning ``degrade=True`` SLO is
the page-worthy signal the admission tier sheds on. The autoscaler
closes the loop the other way: instead of (only) shedding demand,
**add supply**. Each :meth:`Autoscaler.tick`:

- **scale up** when any degrade-eligible SLO is fast-burning and the
  pool is below ``max_workers`` — one worker per tick (spawns are
  AOT-preloaded via the shared executable cache, so a new worker is
  serving in well under a second; adding one at a time keeps the
  control loop stable);
- **scale down** when a worker has sat idle (zero in-flight, no
  fast burn) past ``idle_retire_seconds`` and the pool is above
  ``min_workers`` — retired gracefully (drain + bundle publish), the
  most-recently-spawned first so long-lived workers keep their warm
  caches;
- **hold** otherwise. Consecutive spawns are separated by at least
  ``cooldown_seconds`` so one burn episode cannot stampede the pool
  to ``max_workers`` before the first new worker absorbs any load.

Deliberately synchronous and dependency-injected (`clock`,
`slo_engine`, any pool exposing ``live()/spawn()/retire()``): the unit
tests drive it with fakes and the router's serve loop ticks it from a
plain background thread (:meth:`start`)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


class Autoscaler:
    """See the module docstring. ``router`` is a
    :class:`.router.RouterService` (or any object exposing
    ``pool.live()``, ``spawn_worker(reason=...)`` and
    ``retire_worker(worker_id, reason=...)`` — the ledger entries ride
    those methods)."""

    def __init__(
        self,
        router,
        slo_engine=None,
        *,
        min_workers: int = 1,
        max_workers: int = 4,
        idle_retire_seconds: float = 300.0,
        cooldown_seconds: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        from yuma_simulation_tpu.telemetry.slo import get_slo_engine

        if min_workers < 0 or max_workers < max(1, min_workers):
            raise ValueError(
                f"need 0 <= min_workers <= max_workers (got "
                f"{min_workers}..{max_workers})"
            )
        self.router = router
        self.slo = slo_engine if slo_engine is not None else get_slo_engine()
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.idle_retire_seconds = float(idle_retire_seconds)
        self.cooldown_seconds = float(cooldown_seconds)
        self.clock = clock
        self._last_spawn_t: Optional[float] = None
        #: worker_id -> clock() when it was first seen idle; cleared
        #: the moment it reports in-flight work again.
        self._idle_since: dict[str, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- one control-loop step ----------------------------------------

    def tick(self) -> Optional[str]:
        """One decision: returns ``"spawn"``, ``"retire"``, or ``None``
        (hold). Never raises past a failed spawn — supply problems must
        not kill the serve loop that ticks it."""
        now = self.clock()
        ads = self.router.pool.live()
        burning = tuple(self.slo.degraded())
        # -- up --
        if burning and len(ads) < self.max_workers:
            if (
                self._last_spawn_t is None
                or now - self._last_spawn_t >= self.cooldown_seconds
            ):
                self._last_spawn_t = now
                try:
                    ad = self.router.spawn_worker(
                        reason=f"slo_fast_burn:{','.join(burning)}"
                    )
                except Exception:  # noqa: BLE001 — see docstring
                    logger.warning("autoscale spawn failed", exc_info=True)
                    return None
                log_event(
                    logger,
                    "autoscale_up",
                    worker=ad.get("worker_id", "?"),
                    burning=",".join(burning),
                    live=len(ads) + 1,
                )
                return "spawn"
            return None
        # -- down --
        live_ids = set()
        for ad in ads:
            worker_id = str(ad.get("worker_id", ""))
            live_ids.add(worker_id)
            if int(ad.get("inflight", 0)) > 0 or burning:
                self._idle_since.pop(worker_id, None)
            else:
                self._idle_since.setdefault(worker_id, now)
        for gone in set(self._idle_since) - live_ids:
            self._idle_since.pop(gone, None)
        if len(ads) > self.min_workers:
            # Youngest-first: long-lived workers keep their warm caches.
            for ad in sorted(
                ads,
                key=lambda a: float(a.get("started_t", 0.0)),
                reverse=True,
            ):
                worker_id = str(ad.get("worker_id", ""))
                idle_t = self._idle_since.get(worker_id)
                if (
                    idle_t is not None
                    and now - idle_t >= self.idle_retire_seconds
                ):
                    self._idle_since.pop(worker_id, None)
                    if self.router.retire_worker(
                        worker_id, reason="idle"
                    ):
                        log_event(
                            logger,
                            "autoscale_down",
                            worker=worker_id,
                            idle_seconds=round(now - idle_t, 3),
                            live=len(ads) - 1,
                        )
                        return "retire"
        return None

    # -- background mode ----------------------------------------------

    def start(self, interval_seconds: float = 1.0) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval_seconds):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — keep the loop alive
                    logger.warning("autoscaler tick failed", exc_info=True)

        self._thread = threading.Thread(
            target=loop, name="yuma-serve-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
