"""The stateless serving router: horizontal scale-out for the
warm-engine service (PR 16 tentpole).

One front-end process admits/prices every request through the SAME
:func:`.admission.admit` path the single-process service uses (typed
400s, zero compiles), then places it on one of N warm
:mod:`.worker` processes discovered through the
:class:`..fabric.lease.LeaseStore` directory the workers heartbeat
into. The router holds NO request state worth preserving — every
placement decision is recomputed from the latest advertisements, so a
router restart loses nothing but in-flight sockets.

**Claim scoring** (:func:`claim_score` — pure, unit-testable): a
request is routed to the live worker with the highest

``(suffix_epochs_saved, warm_bucket, -inflight, stable_host_hash)``

- *suffix_epochs_saved*: for what-ifs, how many baseline epochs the
  worker's held :class:`..replay.statecache.StateCache` prefix lets it
  skip (``min(max held checkpoint, perturb epoch)``) — the whole point
  of affinity: repeated what-ifs land on the worker already holding
  the carry checkpoints;
- *warm_bucket*: the worker already traced this request's ``ExVxM``
  shape bucket (no compile on its critical path);
- *-inflight*: least-loaded among equals;
- *stable_host_hash*: a deterministic tiebreak so equal workers don't
  flap placement between heartbeats.

A dead worker (stale lease, torn/absent ad, ``retired`` flag) NEVER
wins: :func:`claim_score` returns ``None`` for it. A worker that dies
**mid-request** surfaces as a transport failure on the forward leg;
the router ledgers the typed ``worker_lost`` + ``request_rerouted``
events and retries the surviving workers — the client sees the
survivor's answer, never a connection reset. Only when every live
worker has been tried does the router answer, and even then it is the
typed, retryable :class:`..resilience.errors.WorkerLost` 503, not a
bare error.

Run it: ``python -m yuma_simulation_tpu.serve --router --worker-pool
DIR --workers N`` (see ``--scaleout-drill`` for the chaos proof).
"""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import json
import logging
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import uuid
from dataclasses import dataclass
from typing import Optional, Sequence, Union

from yuma_simulation_tpu.fabric.lease import LeaseStore
from yuma_simulation_tpu.resilience.errors import (
    AdmissionRejected,
    ClientRetriesExhausted,
    WorkerLost,
)
from yuma_simulation_tpu.serve.admission import admit
from yuma_simulation_tpu.serve.server import (
    SimulationClient,
    wait_until_ready,
)
from yuma_simulation_tpu.serve.worker import (
    pool_leases_dir,
    worker_bundle_dir,
)
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

#: request kind -> worker POST route (the inverse of server._ROUTES).
_KIND_PATHS = {
    "simulate": "/v1/simulate",
    "sweep": "/v1/sweep",
    "table": "/v1/table",
    "whatif": "/v1/whatif",
}

#: Transport-level failures on a forward leg that mean "this worker is
#: gone", triggering a reroute (NOT a client-visible error).
_FORWARD_FAILURES = (
    ClientRetriesExhausted,
    urllib.error.URLError,
    ConnectionError,
    OSError,
)


# -- claim scoring (pure) ------------------------------------------------


def stable_host_hash(worker_id: str) -> int:
    """Deterministic per-worker tiebreak: equal-scored workers must not
    flap placement between heartbeats (stability keeps their caches
    divergent in a USEFUL way — each keeps winning its own tenants)."""
    digest = hashlib.sha256(worker_id.encode()).digest()
    return int.from_bytes(digest[:8], "big")


def canonical_key(key) -> str:
    """Content-addressed baseline keys cross a JSON boundary on their
    way through the heartbeat ad (tuples become lists, nested ones
    too): compare them in JSON form so a held prefix matches its own
    key regardless of which side of the wire it sits on."""
    return json.dumps(key, default=str, separators=(",", ":"))


def suffix_epochs_saved(
    ad: dict,
    baseline_key: Optional[Sequence],
    perturb_epoch: Optional[int],
) -> int:
    """Baseline epochs this worker's held state-cache prefix would let
    a what-if skip: the best on-disk carry checkpoint at or before the
    perturbation epoch, 0 when it holds nothing useful."""
    if baseline_key is None:
        return 0
    want = canonical_key(baseline_key)
    best = 0
    for held in ad.get("held_prefixes", ()):
        key = held.get("key")
        if key is None or canonical_key(key) != want:
            continue
        for cp in held.get("checkpoints", ()):
            cp = int(cp)
            if perturb_epoch is not None and cp > int(perturb_epoch):
                continue
            best = max(best, cp)
    return best


def claim_score(
    ad: dict,
    *,
    baseline_key: Optional[Sequence] = None,
    perturb_epoch: Optional[int] = None,
    bucket: Optional[str] = None,
) -> Optional[tuple]:
    """The placement score for one advertisement, or ``None`` when the
    worker can never win (not alive, or draining). Higher is better;
    compare tuples lexicographically."""
    if not ad.get("alive") or ad.get("retired"):
        return None
    saved = suffix_epochs_saved(ad, baseline_key, perturb_epoch)
    warm = (
        1 if bucket and bucket in tuple(ad.get("warm_buckets", ())) else 0
    )
    return (
        saved,
        warm,
        -int(ad.get("inflight", 0)),
        stable_host_hash(str(ad.get("worker_id", ""))),
    )


def rank_claims(
    ads: Sequence[dict],
    *,
    baseline_key: Optional[Sequence] = None,
    perturb_epoch: Optional[int] = None,
    bucket: Optional[str] = None,
) -> list[dict]:
    """Live workers best-first; dead ones dropped entirely."""
    scored = []
    for ad in ads:
        score = claim_score(
            ad,
            baseline_key=baseline_key,
            perturb_epoch=perturb_epoch,
            bucket=bucket,
        )
        if score is not None:
            scored.append((score, ad))
    scored.sort(key=lambda pair: pair[0], reverse=True)
    return [ad for _, ad in scored]


# -- the worker pool -----------------------------------------------------


class WorkerPool:
    """Spawns, observes, and retires the worker processes behind one
    pool directory. Discovery is reading the lease directory — the
    pool object is NOT the source of truth (a worker some other
    operator started is just as routable), it only owns the processes
    it spawned."""

    def __init__(
        self,
        pool_dir: Union[str, pathlib.Path],
        *,
        max_slots: int = 8,
        ttl_seconds: float = 3.0,
        worker_args: Sequence[str] = (),
        python: str = sys.executable,
        registry=None,
        spawn_wait_seconds: float = 120.0,
    ):
        self.directory = pathlib.Path(pool_dir)
        self.max_slots = int(max_slots)
        self.ttl_seconds = float(ttl_seconds)
        self.worker_args = tuple(worker_args)
        self.python = python
        self.spawn_wait_seconds = float(spawn_wait_seconds)
        # Observer-only store: the router never claims a slot, it only
        # reads claims + ads. host_id still matters for tombstones.
        self.leases = LeaseStore(
            pool_leases_dir(self.directory),
            f"router-{os.getpid()}",
            ttl_seconds=ttl_seconds,
        )
        self._procs: dict[str, subprocess.Popen] = {}
        self._lost: set[str] = set()
        self._lock = threading.Lock()
        self._gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                "serve_workers_live",
                help="live serve workers (fresh lease + ad)",
            )

    def scan(self) -> list[dict]:
        """Every advertised worker with an ``alive`` verdict attached:
        fresh un-stealable lease, ad from the SAME holder, not retired,
        not marked lost by a failed forward."""
        ads = []
        with self._lock:
            lost = set(self._lost)
        for slot in range(self.max_slots):
            ad = self.leases.read_annotation(slot)
            if ad is None:
                continue
            info = self.leases.read(slot)
            alive = (
                info is not None
                and not self.leases.is_stealable(info)
                and info.host == ad.get("worker_id")
                and not ad.get("retired")
                and ad.get("worker_id") not in lost
                and bool(ad.get("url"))
            )
            ads.append(dict(ad, alive=alive, slot=slot))
        if self._gauge is not None:
            self._gauge.set(sum(1 for a in ads if a["alive"]))
        return ads

    def live(self) -> list[dict]:
        return [ad for ad in self.scan() if ad["alive"]]

    def _free_slot(self) -> int:
        for slot in range(self.max_slots):
            info = self.leases.read(slot)
            if info is None or self.leases.is_stealable(info):
                return slot
        raise RuntimeError(
            f"no free slot: all {self.max_slots} pool slots hold live "
            "leases"
        )

    def spawn(
        self, *, extra_argv: Sequence[str] = (), wait: bool = True
    ) -> dict:
        """Start one worker process on a free slot and (by default)
        block until its first advertisement answers ``/healthz``.
        Returns the worker's ad."""
        slot = self._free_slot()
        worker_id = f"w{slot}-{uuid.uuid4().hex[:6]}"
        argv = [
            self.python,
            "-m",
            "yuma_simulation_tpu.serve",
            "--worker-pool",
            str(self.directory),
            "--worker-slot",
            str(slot),
            "--worker-id",
            worker_id,
            "--worker-ttl",
            str(self.ttl_seconds),
        ]
        # "{worker_id}" templating lets per-worker paths (a private
        # replay cache, a private bundle) ride one shared argv.
        for arg in (*self.worker_args, *extra_argv):
            argv.append(str(arg).replace("{worker_id}", worker_id))
        logdir = worker_bundle_dir(self.directory, worker_id).parent
        logdir.mkdir(parents=True, exist_ok=True)
        logfile = open(logdir / "worker.log", "ab")
        try:
            proc = subprocess.Popen(
                argv, stdout=logfile, stderr=subprocess.STDOUT
            )
        finally:
            logfile.close()
        with self._lock:
            self._procs[worker_id] = proc
        log_event(
            logger, "worker_spawning", worker=worker_id, slot=slot,
            pid=proc.pid,
        )
        if not wait:
            return {"worker_id": worker_id, "slot": slot, "alive": False}
        deadline = time.monotonic() + self.spawn_wait_seconds
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"worker {worker_id} exited rc={proc.returncode} "
                    f"before advertising (see {logdir / 'worker.log'})"
                )
            for ad in self.scan():
                if ad.get("worker_id") == worker_id and ad["alive"]:
                    if wait_until_ready(ad["url"], timeout=5.0):
                        return ad
            time.sleep(0.05)
        proc.kill()
        raise RuntimeError(
            f"worker {worker_id} did not become ready within "
            f"{self.spawn_wait_seconds:.0f}s"
        )

    def mark_lost(self, worker_id: str) -> bool:
        """Record a worker observed dead on a forward leg so routing
        stops considering it before its lease even expires. Returns
        True the FIRST time (callers ledger ``worker_lost`` once)."""
        with self._lock:
            if worker_id in self._lost:
                return False
            self._lost.add(worker_id)
        return True

    def owned(self) -> list[str]:
        with self._lock:
            return list(self._procs)

    def retire(self, worker_id: str, *, timeout: float = 30.0) -> bool:
        """Graceful SIGTERM retire of a pool-owned worker: it flips its
        ad, drains, publishes its bundle, releases its slot."""
        with self._lock:
            proc = self._procs.get(worker_id)
        if proc is None:
            return False
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        with self._lock:
            self._procs.pop(worker_id, None)
        return True

    def kill(self, worker_id: str) -> bool:
        """SIGKILL (the chaos drill's mid-request crash): no drain, no
        release — the lease goes stale and the router reroutes."""
        with self._lock:
            proc = self._procs.get(worker_id)
        if proc is None or proc.poll() is not None:
            return False
        proc.kill()
        proc.wait(timeout=10.0)
        return True

    def close(self) -> None:
        for worker_id in self.owned():
            try:
                self.retire(worker_id)
            except Exception:  # noqa: BLE001 — best-effort teardown
                logger.warning(
                    "retire of %s failed", worker_id, exc_info=True
                )


# -- the router service --------------------------------------------------


@dataclass(frozen=True)
class RouterConfig:
    """Everything the stateless front-end needs. ``worker_args`` is
    forwarded verbatim to every spawned worker's CLI (with
    ``{worker_id}`` substituted), so the pool's serve knobs — replay
    mounts, executable cache, warmup shapes — live in ONE place."""

    pool_dir: str = "serve-pool"
    workers: int = 2
    max_workers: int = 8
    worker_args: tuple = ()
    lease_ttl_seconds: float = 3.0
    bundle_dir: Optional[str] = None
    api_keys_path: Optional[str] = None
    #: affinity=False routes purely by load (the drill's control arm).
    affinity: bool = True
    #: extra placement attempts after the first (each on a distinct
    #: worker) before the typed WorkerLost 503.
    reroute_attempts: int = 3
    default_deadline_seconds: float = 120.0
    max_batch: int = 8
    tenant_priority: Optional[dict] = None
    forward_timeout: float = 120.0
    spawn_wait_seconds: float = 120.0
    #: Router-side replay mount (read-only pricing + affinity keys):
    #: MUST use the same archive + replay geometry as the workers or
    #: the content-addressed baseline keys will not match theirs.
    replay_archive_dir: Optional[str] = None
    replay_cache_dir: Optional[str] = None
    replay_window: Optional[int] = None
    replay_epochs_per_snapshot: int = 4
    replay_stride: int = 8
    replay_max_baselines: int = 64


class RouterService:
    """Drop-in for :class:`.service.SimulationService` behind
    :class:`.server.SimulationServer` (same ``handle`` contract), but
    ``handle`` PLACES work instead of executing it."""

    def __init__(self, config: Optional[RouterConfig] = None, registry=None):
        from yuma_simulation_tpu.resilience.supervisor import FailureLedger
        from yuma_simulation_tpu.telemetry.metrics import get_registry
        from yuma_simulation_tpu.telemetry.runctx import RunContext
        from yuma_simulation_tpu.telemetry.slo import get_slo_engine

        self.config = config if config is not None else RouterConfig()
        self.registry = registry if registry is not None else get_registry()
        self.run = RunContext()
        self.slo = get_slo_engine()
        self.keyring = None
        if self.config.api_keys_path:
            from yuma_simulation_tpu.serve.apikeys import ApiKeyring

            self.keyring = ApiKeyring.load(self.config.api_keys_path)
        bundle_dir = self.config.bundle_dir
        if bundle_dir is not None:
            pathlib.Path(bundle_dir).mkdir(parents=True, exist_ok=True)
        self.ledger = FailureLedger(
            pathlib.Path(bundle_dir) / "ledger.jsonl"
            if bundle_dir is not None
            else None
        )
        self._ledger_lock = threading.Lock()
        self._requests_total = self.registry.counter(
            "serve_requests_total", help="serving-tier requests handled"
        )
        self._admission_rejected = self.registry.counter(
            "serve_admission_rejected",
            help="typed admission rejections (pre-compile)",
        )
        self._request_seconds = self.registry.histogram(
            "serve_request_seconds",
            help="request wall time, admission to reply",
        )
        self._reroutes = self.registry.counter(
            "serve_reroutes_total",
            help="forward legs rerouted off a lost worker",
        )
        self._affinity_hits = self.registry.counter(
            "affinity_hits_total",
            help="requests placed on a worker holding useful state "
            "(cache prefix or warm bucket)",
        )
        self.replay = None
        if self.config.replay_archive_dir and self.config.replay_cache_dir:
            from yuma_simulation_tpu.replay import ReplayService

            self.replay = ReplayService(
                self.config.replay_archive_dir,
                self.config.replay_cache_dir,
                window=self.config.replay_window,
                epochs_per_snapshot=self.config.replay_epochs_per_snapshot,
                stride=self.config.replay_stride,
                max_baselines=self.config.replay_max_baselines,
            )
        self.pool = WorkerPool(
            self.config.pool_dir,
            max_slots=self.config.max_workers,
            ttl_seconds=self.config.lease_ttl_seconds,
            worker_args=self.config.worker_args,
            registry=self.registry,
            spawn_wait_seconds=self.config.spawn_wait_seconds,
        )
        self._clients: dict[str, SimulationClient] = {}
        self._clients_lock = threading.Lock()
        self._ingress_lock = threading.Lock()
        self._ingress_runs: list = []
        self._publish_lock = threading.Lock()
        self._counter = itertools.count(1)
        #: affinity-off placement cursor (plain round-robin).
        self._rr = itertools.count()
        self.started_t = time.time()
        self._stopping = False
        self._closed = False

    # -- bookkeeping --------------------------------------------------

    def _append_ledger(self, event: str, **fields) -> None:
        with self._ledger_lock:
            self.ledger.append(event, **fields)

    def _append_ledger_rootspan(self, event: str, **fields) -> None:
        with self._rootspan(f"{event}:{fields.get('request', '')}"):
            self._append_ledger(event, **fields)

    @contextlib.contextmanager
    def _rootspan(self, name: str):
        """A fresh root span of the ROUTER run, for ledger records born
        outside any request span (pool lifecycle) — they must still
        resolve under ``obsreport --check``'s span gate."""
        from yuma_simulation_tpu.telemetry.runctx import span

        with self.run.activate():
            with span(name, root=True):
                yield

    def mint_request_id(self) -> str:
        return f"g{next(self._counter):06d}"

    def _remember_ingress(self, run) -> None:
        flush = None
        with self._ingress_lock:
            self._ingress_runs.append(run)
            if len(self._ingress_runs) > 256:
                flush, self._ingress_runs = self._ingress_runs, []
        if flush and self.config.bundle_dir is not None:
            from yuma_simulation_tpu.telemetry.flight import FlightRecorder

            try:
                with self._publish_lock:
                    FlightRecorder(self.config.bundle_dir).append_spans(
                        flush
                    )
            except Exception:  # noqa: BLE001 — telemetry must not kill serving
                logger.warning(
                    "router ingress flush failed", exc_info=True
                )

    # -- pool lifecycle ----------------------------------------------

    def start_workers(self, count: Optional[int] = None) -> list[dict]:
        """Bring up the initial fleet (``RouterConfig.workers`` by
        default); each spawn is a ledgered ``worker_spawned``."""
        ads = []
        for _ in range(self.config.workers if count is None else count):
            ads.append(self.spawn_worker())
        return ads

    def spawn_worker(self, *, reason: str = "startup") -> dict:
        ad = self.pool.spawn()
        with self._rootspan("worker_spawned:"):
            self._append_ledger(
                "worker_spawned",
                request="",
                worker=ad.get("worker_id", "?"),
                slot=ad.get("slot", -1),
                url=ad.get("url", ""),
                reason=reason,
                aot_builds=int(ad.get("aot_builds", 0)),
            )
        return ad

    def retire_worker(self, worker_id: str, *, reason: str = "idle") -> bool:
        ok = self.pool.retire(worker_id)
        if ok:
            with self._rootspan("worker_retired:"):
                self._append_ledger(
                    "worker_retired",
                    request="",
                    worker=worker_id,
                    reason=reason,
                )
        return ok

    # -- the request path --------------------------------------------

    def handle(
        self, kind: str, payload, *, request_id=None, trace=None,
        api_key=None,
    ) -> tuple[int, dict, dict]:
        """Same contract as ``SimulationService.handle``: one typed
        ``(status, body, headers)`` for every input, no bare errors."""
        from yuma_simulation_tpu.telemetry.propagation import (
            TraceContext,
            child_run,
            span_prefix_for,
        )
        from yuma_simulation_tpu.telemetry.runctx import span

        if isinstance(trace, str):
            trace = TraceContext.from_traceparent(trace)
        rid = request_id if request_id else self.mint_request_id()
        t0 = time.perf_counter()
        self._requests_total.inc()
        if self.keyring is not None:
            resolved = self.keyring.resolve(api_key)
            if resolved is None:
                self._append_ledger_rootspan(
                    "request_done",
                    request=rid,
                    tenant="<unauthenticated>",
                    endpoint=kind,
                    status=401,
                    outcome="rejected",
                )
                return (
                    401,
                    {
                        "status": "rejected",
                        "error": "Unauthenticated",
                        "message": "a valid X-Api-Key is required by "
                        "this deployment",
                        "request_id": rid,
                    },
                    {"X-Request-Id": rid},
                )
            if isinstance(payload, dict):
                payload = dict(payload, tenant=resolved)
            else:
                payload = {"tenant": resolved}
        tenant = (
            payload.get("tenant", "anonymous")
            if isinstance(payload, dict)
            else "anonymous"
        )
        if trace is not None:
            run = child_run(trace, prefix=span_prefix_for())
            cm = run
            ingress = run
        else:
            run = self.run
            cm = self.run.activate()
            ingress = None
        with cm:
            with span(
                f"request:{rid}", tenant=tenant, endpoint=kind, request=rid
            ) as s:
                try:
                    status, body, headers, worker, affinity = self._route(
                        kind, payload, rid, tenant
                    )
                except BaseException:  # noqa: BLE001 — no-bare-500 backstop
                    logger.exception(
                        "unhandled router failure for %s", rid
                    )
                    status = 500
                    body = {
                        "status": "failed",
                        "error": "RouterError",
                        "message": "unexpected router failure",
                        "retryable": True,
                        "request_id": rid,
                    }
                    headers, worker, affinity = {}, None, False
                if s is not None:
                    s.attrs["status"] = status
                    s.attrs["outcome"] = body.get("status", "?")
                    if worker:
                        s.attrs["worker"] = worker
                headers = dict(headers)
                headers.setdefault("X-Request-Id", rid)
                self._append_ledger(
                    "request_done",
                    request=rid,
                    tenant=tenant,
                    endpoint=kind,
                    status=status,
                    outcome=body.get("status", "?"),
                    worker=worker or "",
                    affinity=bool(affinity),
                )
        elapsed = time.perf_counter() - t0
        self._request_seconds.observe(elapsed)
        self.slo.observe("serve_request_seconds", elapsed)
        self.slo.event("serve_request_ok", status < 500)
        self.slo.event("serve_admitted", status != 429)
        if ingress is not None:
            self._remember_ingress(ingress)
        return status, body, headers

    def _route(
        self, kind: str, payload, rid: str, tenant: str
    ) -> tuple[int, dict, dict, Optional[str], bool]:
        from yuma_simulation_tpu.telemetry.runctx import span

        if self._stopping:
            return (
                503,
                {
                    "status": "shutting_down",
                    "error": "ServiceUnavailable",
                    "message": "the router is draining; retry elsewhere",
                    "request_id": rid,
                },
                {"Retry-After": "5"},
                None,
                False,
            )
        # Admission FIRST, in the router process: malformed or
        # un-runnable work is a typed 400 before any forward leg, and
        # the ticket's plan/spec is what affinity scores against.
        try:
            ticket = admit(
                payload,
                request_id=rid,
                kind=kind,
                default_deadline_seconds=(
                    self.config.default_deadline_seconds
                ),
                max_unit_lanes=self.config.max_batch * 8,
                tenant_priority=self.config.tenant_priority,
                replay=self.replay,
            )
        except AdmissionRejected as exc:
            self._admission_rejected.inc()
            body = {
                "status": "rejected",
                "error": "AdmissionRejected",
                "reason": exc.reason,
                "message": str(exc),
                "request_id": rid,
            }
            if exc.suggestion:
                body["suggestion"] = exc.suggestion
            return 400, body, {}, None, False

        baseline_key = None
        perturb_epoch = None
        bucket = None
        if self.config.affinity:
            plan_bucket = getattr(ticket.plan, "bucket", None)
            if plan_bucket is not None:
                bucket = (
                    f"{plan_bucket.epochs}x{plan_bucket.V}"
                    f"x{plan_bucket.M}"
                )
            if ticket.whatif is not None and self.replay is not None:
                try:
                    desc = self.replay.describe(ticket.whatif)
                    baseline_key = desc["key"]
                    perturb_epoch = int(ticket.whatif.from_epoch)
                except Exception:  # noqa: BLE001 — affinity is best-effort
                    logger.warning(
                        "affinity describe failed for %s", rid,
                        exc_info=True,
                    )

        forward_payload = (
            dict(payload, tenant=ticket.tenant)
            if isinstance(payload, dict)
            else {"tenant": ticket.tenant}
        )
        attempted: list[str] = []
        for attempt in range(self.config.reroute_attempts + 1):
            ads = [
                ad
                for ad in self.pool.scan()
                if ad.get("worker_id") not in attempted
            ]
            if self.config.affinity:
                ranked = rank_claims(
                    ads,
                    baseline_key=baseline_key,
                    perturb_epoch=perturb_epoch,
                    bucket=bucket,
                )
            else:
                # No affinity: plain round-robin over the live workers
                # (slot order) — the drill's control arm, and the
                # neutral policy for state-free deployments.
                alive = sorted(
                    (ad for ad in ads if ad["alive"]),
                    key=lambda a: int(a.get("slot", 0)),
                )
                if alive:
                    start = next(self._rr) % len(alive)
                    ranked = alive[start:] + alive[:start]
                else:
                    ranked = []
            if not ranked:
                break
            ad = ranked[0]
            worker_id = str(ad.get("worker_id", "?"))
            attempted.append(worker_id)
            score = claim_score(
                ad,
                baseline_key=baseline_key,
                perturb_epoch=perturb_epoch,
                bucket=bucket,
            )
            affinity_hit = bool(score) and (score[0] > 0 or score[1] > 0)
            with span(
                f"route:{worker_id}",
                request=rid,
                worker=worker_id,
                attempt=attempt,
                affinity=affinity_hit,
            ):
                try:
                    resp = self._forward(ad, kind, forward_payload, tenant)
                except _FORWARD_FAILURES as exc:
                    lost = WorkerLost(
                        f"worker {worker_id} lost mid-request "
                        f"{rid}: {exc}",
                        worker_id=worker_id,
                        attempts=attempt + 1,
                    )
                    if self.pool.mark_lost(worker_id):
                        self._append_ledger(
                            "worker_lost",
                            request=rid,
                            worker=worker_id,
                            error=type(exc).__name__,
                            message=str(lost)[:200],
                        )
                        log_event(
                            logger,
                            "worker_lost",
                            worker=worker_id,
                            request=rid,
                        )
                    self._reroutes.inc()
                    self._append_ledger(
                        "request_rerouted",
                        request=rid,
                        tenant=tenant,
                        worker=worker_id,
                        attempt=attempt,
                    )
                    continue
            if affinity_hit:
                self._affinity_hits.inc()
            headers = {
                k: v
                for k, v in resp.headers.items()
                if k in ("Retry-After", "Server-Timing")
            }
            headers["X-Worker"] = worker_id
            return resp.status, dict(resp.body), headers, worker_id, (
                affinity_hit
            )
        # Every live worker tried (or none left): typed + retryable.
        return (
            503,
            {
                "status": "failed",
                "error": "WorkerLost",
                "message": (
                    f"no live worker could serve request {rid} "
                    f"({len(attempted)} attempt(s): "
                    f"{', '.join(attempted) or 'no live workers'})"
                ),
                "retryable": True,
                "request_id": rid,
            },
            {"Retry-After": "1"},
            None,
            False,
        )

    def _forward(self, ad: dict, kind: str, payload: dict, tenant: str):
        """One forward leg to one worker. ``retries=0``: the router's
        reroute loop IS the retry policy (retrying the same dead
        worker would just burn the deadline)."""
        url = str(ad["url"])
        with self._clients_lock:
            client = self._clients.get(url)
            if client is None:
                client = SimulationClient(
                    url,
                    tenant=tenant,
                    timeout=self.config.forward_timeout,
                    retries=0,
                )
                self._clients[url] = client
        path = _KIND_PATHS.get(kind)
        if path is None:
            raise AdmissionRejected(  # pragma: no cover — admit() gates kinds
                f"unknown kind {kind!r}"
            )
        return client._request("POST", path, payload)

    # -- ops surface --------------------------------------------------

    def replay_get(self, path: str) -> tuple[int, dict]:
        """GET /v1/replay[/NETUID] — answered from the router's own
        read-only replay mount (index reads, no state materialized)."""
        from yuma_simulation_tpu.replay import ArchiveError

        if self.replay is None:
            return 404, {
                "status": "rejected",
                "error": "ReplayUnconfigured",
                "message": "this deployment mounts no replay tier",
            }
        tail = path[len("/v1/replay"):].strip("/")
        try:
            if not tail:
                return 200, {"status": "ok", **self.replay.index()}
            if not tail.isdigit():
                return 404, {
                    "status": "rejected",
                    "error": "NotFound",
                    "message": f"no replay route {path!r}",
                }
            return 200, {
                "status": "ok",
                **self.replay.timeline_info(int(tail)),
            }
        except (ArchiveError, KeyError, ValueError) as exc:
            return 404, {
                "status": "rejected",
                "error": "NotFound",
                "message": str(exc)[:200],
            }

    def healthz(self) -> dict:
        ads = self.pool.scan()
        live = [ad for ad in ads if ad["alive"]]
        return {
            "status": "draining" if self._stopping else (
                "ok" if live else "degraded"
            ),
            "ready": not self._stopping and bool(live),
            "role": "router",
            "uptime_seconds": round(time.time() - self.started_t, 3),
            "run_id": self.run.run_id,
            "requests_total": int(self._requests_total.value),
            "workers": {
                "live": len(live),
                "advertised": len(ads),
                "ids": sorted(ad["worker_id"] for ad in live),
            },
            "affinity": self.config.affinity,
        }

    def metrics_text(self) -> str:
        return self.registry.prometheus_text()

    def close(self) -> None:
        """Drain: stop placing, retire the owned workers gracefully,
        publish the router's own flight bundle."""
        if self._closed:
            return
        self._closed = True
        self._stopping = True
        self.pool.close()
        if self.config.bundle_dir is not None:
            from yuma_simulation_tpu.telemetry.flight import (
                METRICS_NAME,
                FlightRecorder,
            )

            with self._ingress_lock:
                ingress = self._ingress_runs
                self._ingress_runs = []
            try:
                with self._publish_lock:
                    rec = FlightRecorder(self.config.bundle_dir)
                    rec.record(self.run, extra_runs=ingress)
                    self.registry.publish_snapshot(
                        pathlib.Path(self.config.bundle_dir)
                        / METRICS_NAME,
                        run_id=self.run.run_id,
                    )
                    rec.record_slo(self.slo, run_id=self.run.run_id)
            except Exception:  # noqa: BLE001 — teardown telemetry is best-effort
                logger.warning(
                    "router bundle publish failed", exc_info=True
                )
        log_event(
            logger,
            "router_stopped",
            requests=int(self._requests_total.value),
        )
