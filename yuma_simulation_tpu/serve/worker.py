"""One warm worker of the horizontal serving pool (PR 16).

A worker is the full single-process serving stack
(:class:`.service.SimulationService` behind
:class:`.server.SimulationServer`) made *discoverable*: it claims one
pool **slot** as a lease unit in the shared
:class:`..fabric.lease.LeaseStore` directory and heartbeats an
**advertisement** beside the claim — its URL, pid, in-flight depth,
the :class:`..replay.statecache.StateCache` prefixes it physically
holds (content-addressed baseline keys + on-disk carry checkpoints),
and its warm ``ExVxM`` shape buckets. The router
(:mod:`.router`) never talks to a registry service: liveness is the
lease protocol's existing mtime-freshness rule, and placement quality
is whatever the last heartbeat advertised. A SIGKILLed worker simply
stops renewing; within one TTL its claim is stealable and the router
stops scoring it — the same crash semantics the fleet tier already
proved for simulation units.

Lifecycle:

- **claim**: ``try_claim(slot)`` — losing the race to a live worker is
  a typed startup failure, not a silent double-bind;
- **serve**: the ordinary HTTP front on an ephemeral port (the ad is
  how anyone learns the port);
- **heartbeat**: every ``ttl/3`` seconds, ``renew(slot)`` +
  ``annotate(slot, ad)``. A torn/missed renewal raises the lease
  tier's typed :class:`..resilience.errors.LeaseExpired` and the
  worker exits rather than serve unclaimed;
- **retire** (SIGTERM): advertise ``retired=True`` (the router stops
  routing NEW work immediately), drain via ``SimulationServer.close``
  (in-flight finishes, flight bundle publishes), release the slot.

Run one: ``python -m yuma_simulation_tpu.serve --worker-pool DIR
--worker-slot N`` (the router's :class:`.router.WorkerPool` spawns
exactly this).
"""

from __future__ import annotations

import logging
import os
import pathlib
import signal
import threading
import time
from typing import Optional, Union

from yuma_simulation_tpu.fabric.lease import LeaseStore
from yuma_simulation_tpu.resilience.errors import LeaseExpired
from yuma_simulation_tpu.serve.server import SimulationServer
from yuma_simulation_tpu.serve.service import ServeConfig
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

#: Subdirectory of the pool root holding slot leases + advertisements.
LEASES_DIR = "leases"
#: Subdirectory of the pool root holding per-worker flight bundles.
WORKERS_DIR = "workers"


def pool_leases_dir(pool_dir: Union[str, pathlib.Path]) -> pathlib.Path:
    return pathlib.Path(pool_dir) / LEASES_DIR


def worker_bundle_dir(
    pool_dir: Union[str, pathlib.Path], worker_id: str
) -> pathlib.Path:
    """Where a worker publishes its flight bundle: the router merges
    every worker's bundle with its own at drill/ops time."""
    return pathlib.Path(pool_dir) / WORKERS_DIR / worker_id / "bundle"


class ServeWorker:
    """One pool member: slot lease + HTTP server + heartbeat ads.

    ``ttl_seconds`` is the liveness contract: miss renewals for one TTL
    and the router treats the worker as dead. The heartbeat runs at
    ``ttl/3`` so a single slow beat never looks like a crash."""

    def __init__(
        self,
        pool_dir: Union[str, pathlib.Path],
        slot: int,
        worker_id: str,
        config: Optional[ServeConfig] = None,
        *,
        host: str = "127.0.0.1",
        ttl_seconds: float = 3.0,
    ):
        self.pool_dir = pathlib.Path(pool_dir)
        self.slot = int(slot)
        self.worker_id = worker_id
        self.ttl_seconds = float(ttl_seconds)
        self.leases = LeaseStore(
            pool_leases_dir(self.pool_dir),
            worker_id,
            ttl_seconds=ttl_seconds,
        )
        claim = self.leases.try_claim(self.slot)
        if claim is None:
            raise RuntimeError(
                f"pool slot {self.slot} is already held by a live "
                f"worker (pool {self.pool_dir})"
            )
        self._stop = threading.Event()
        self._expired = False
        self.started_t = time.time()
        # The server construction IS the warmup (AOT preload, replay
        # mount): only once it returns is the worker worth advertising.
        self.server = SimulationServer(config, host=host, port=0)

    # -- the advertisement --------------------------------------------

    def advertisement(self, *, retired: bool = False) -> dict:
        """The heartbeat payload the router scores claims from. Every
        field is a *hint* — the lease freshness beside it is the only
        liveness truth."""
        from yuma_simulation_tpu.simulation.aot import process_stats

        service = self.server.service
        held = []
        if service.replay is not None:
            try:
                held = service.replay.cache.held_prefixes()
            except Exception:  # noqa: BLE001 — ads must never kill a beat
                logger.warning(
                    "held-prefix enumeration failed", exc_info=True
                )
        return {
            "worker_id": self.worker_id,
            "slot": self.slot,
            "url": self.server.url,
            # pid / heartbeat_t / requests_total are operator
            # forensics (read by humans off the lease file when a slot
            # wedges), deliberately not placement inputs — reviewed
            # wirecheck asymmetry, not drift.
            "pid": os.getpid(),  # jaxlint: disable=JX303
            "started_t": self.started_t,
            "heartbeat_t": time.time(),  # jaxlint: disable=JX303
            "inflight": len(service.queue),
            "requests_total": int(service._requests_total.value),  # jaxlint: disable=JX303
            "held_prefixes": held,
            "warm_buckets": service.warm_buckets(),
            # Cold-start proof for the autoscaler drill: a worker
            # spawned against a warm executable cache must advertise
            # zero AOT builds.
            "aot_builds": int(process_stats().builds),
            "retired": bool(retired),
        }

    def heartbeat(self, *, retired: bool = False) -> None:
        """One beat: renew the claim, then refresh the ad. Raises the
        typed :class:`LeaseExpired` when the claim was lost — the
        worker must stop serving rather than run unclaimed."""
        self.leases.renew(self.slot)
        self.leases.annotate(self.slot, self.advertisement(retired=retired))

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "ServeWorker":
        self.server.start()
        self.heartbeat()
        log_event(
            logger,
            "worker_ready",
            worker=self.worker_id,
            slot=self.slot,
            url=self.server.url,
        )
        return self

    def stop(self) -> None:
        """Request a graceful retire (signal-handler safe)."""
        self._stop.set()

    def run(self) -> int:
        """Serve until stopped (SIGTERM/SIGINT) or the lease is lost.
        Returns the process exit code."""
        signal.signal(signal.SIGTERM, lambda *_: self.stop())
        signal.signal(signal.SIGINT, lambda *_: self.stop())
        self.start()
        interval = max(0.05, self.ttl_seconds / 3.0)
        try:
            while not self._stop.wait(interval):
                try:
                    self.heartbeat()
                except LeaseExpired:
                    # Someone stole the slot (we stalled past TTL, or
                    # an operator tombstoned us): serving on would mean
                    # two workers answering one slot's traffic.
                    self._expired = True
                    log_event(
                        logger,
                        "worker_lease_lost",
                        worker=self.worker_id,
                        slot=self.slot,
                    )
                    break
        finally:
            self.close()
        return 1 if self._expired else 0

    def close(self) -> None:
        """Graceful retire: flip the ad to ``retired`` (routers stop
        placing new work immediately — before the drain), drain +
        publish the bundle, release the slot."""
        if not self._expired:
            try:
                self.heartbeat(retired=True)
            except LeaseExpired:
                self._expired = True
        self.server.close()
        if not self._expired:
            self.leases.release(self.slot)
        log_event(
            logger,
            "worker_stopped",
            worker=self.worker_id,
            slot=self.slot,
            expired=self._expired,
        )


def run_worker(args) -> int:
    """The ``--worker-pool`` CLI mode (see :mod:`.__main__`)."""
    from yuma_simulation_tpu.serve.__main__ import _build_config
    from yuma_simulation_tpu.utils.logging import setup_logging

    setup_logging()
    worker_id = args.worker_id or f"worker-{os.getpid()}"
    if not args.bundle_dir:
        args.bundle_dir = str(
            worker_bundle_dir(args.worker_pool, worker_id)
        )
    config = _build_config(args)
    worker = ServeWorker(
        args.worker_pool,
        args.worker_slot,
        worker_id,
        config,
        host=args.host,
        ttl_seconds=args.worker_ttl,
    )
    return worker.run()
