"""Signed-API-key tenant identity for the serving tier.

The tenant field was payload-claimed from day one — fine for a
single-operator deployment, but the moment negotiated quotas and
priority ceilings exist (``ServeConfig.tenant_overrides`` /
``tenant_priority``), an unauthenticated client can claim any tenant it
likes and ride someone else's quota. This module closes that hole with
stdlib-only HMAC keys:

- the keyfile (``ServeConfig.api_keys_path``) is JSON mapping
  ``tenant -> secret`` (hex or any string; operators mint and rotate it
  out of band);
- a client presents ``X-Api-Key: <tenant>.<signature>`` where the
  signature is ``HMAC_SHA256(secret, tenant)`` hex — :func:`mint_api_key`
  builds it, so a key is a stable signed credential, not the secret
  itself on the wire in raw form;
- the service verifies with :func:`hmac.compare_digest` (constant-time)
  and resolves the TENANT from the key — when keys are configured, the
  payload's ``tenant`` claim is overwritten before admission, so the
  negotiated-priority/quota tables key on a verified identity;
- a missing/garbled/forged key is a typed 401 (``Unauthenticated``),
  never a silent fall-through to the anonymous tenant.

Deliberately boring: no expiry, no scopes, no key ids — that belongs to
a real IAM integration. What this buys is the invariant the scale-out
tier needs: payload-claimed tenant/priority is NEVER trusted when keys
are configured.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import pathlib
from typing import Optional, Union

logger = logging.getLogger(__name__)


def mint_api_key(tenant: str, secret: str) -> str:
    """The credential a client sends as ``X-Api-Key``:
    ``<tenant>.<HMAC_SHA256(secret, tenant) hex>``."""
    sig = hmac.new(
        secret.encode(), tenant.encode(), hashlib.sha256
    ).hexdigest()
    return f"{tenant}.{sig}"


class ApiKeyring:
    """The server half: a loaded keyfile + constant-time verification.

    Immutable after load (rotation = reload + swap); empty keyrings
    refuse construction so "configured but empty" fails loudly at
    startup instead of 401-ing every tenant at runtime."""

    def __init__(self, keys: dict):
        clean = {}
        for tenant, secret in (keys or {}).items():
            if not isinstance(tenant, str) or not tenant:
                raise ValueError("api keyfile: tenant must be a non-empty string")
            if not isinstance(secret, str) or not secret:
                raise ValueError(
                    f"api keyfile: tenant {tenant!r} has an empty secret"
                )
            clean[tenant] = secret
        if not clean:
            raise ValueError("api keyfile holds no keys")
        self._keys = clean

    @classmethod
    def load(cls, path: Union[str, pathlib.Path]) -> "ApiKeyring":
        data = json.loads(pathlib.Path(path).read_text())
        if not isinstance(data, dict):
            raise ValueError(f"api keyfile {path}: expected a JSON object")
        return cls(data)

    def __len__(self) -> int:
        return len(self._keys)

    def resolve(self, api_key: Optional[str]) -> Optional[str]:
        """The verified tenant id for ``api_key``, or None when the key
        is absent, malformed, names an unknown tenant, or fails its
        signature check (one code path for all four — a prober learns
        nothing from WHICH check failed)."""
        if not api_key or not isinstance(api_key, str):
            return None
        tenant, sep, sig = api_key.rpartition(".")
        if not sep or not tenant:
            return None
        secret = self._keys.get(tenant)
        if secret is None:
            return None
        expected = hmac.new(
            secret.encode(), tenant.encode(), hashlib.sha256
        ).hexdigest()
        if not hmac.compare_digest(expected, sig):
            return None
        return tenant
