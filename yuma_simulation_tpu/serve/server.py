"""The HTTP adapter: stdlib `http.server` over :class:`.service
.SimulationService`, plus the matching stdlib client.

Deliberately dependency-free (ROADMAP item 1 allows FastAPI/grpc; the
stdlib server means tier-1 CI exercises the full serving stack on CPU
with nothing installed). `ThreadingHTTPServer` gives one thread per
connection — the service core is thread-safe and does the real
bounding, so the transport stays dumb:

- ``POST /v1/simulate`` / ``POST /v1/sweep`` / ``POST /v1/table`` —
  JSON request -> :meth:`..serve.service.SimulationService.handle`;
- ``GET /healthz`` — liveness + queue/breaker/SLO burn state (JSON);
- ``GET /metrics`` — the process metrics registry in Prometheus text
  exposition (the PR 4 surface, now scrapeable);
- ``GET /debug/vars`` — the live ops snapshot
  (:meth:`..telemetry.ops.OpsPlane.debug_vars`): metrics + SLO burn
  state + dispatch sketches + recent structured events + profiler and
  segment status;
- ``GET /debug/spans?run=RUN_ID`` — one run's span tree stitched from
  the sealed bundle plus the live run context (defaults to the
  service's own run);
- ``GET /debug/incidents`` — durable correlated-incident state from
  the bundle's ``incidents.jsonl`` (empty list on a clean host);
- ``POST /debug/profile`` — ``{"seconds": N, "mode": "trace"}`` kicks
  one guarded on-demand ``jax.profiler`` window (single-flight; a
  concurrent request gets a typed 409, the artifact registers into the
  flight bundle).

Every response this layer produces is typed JSON (or Prometheus text):
a malformed body is a structured 400, an unknown route a structured
404, and the service's own contract covers the rest — no bare 500s.

Distributed-trace identity (0.13.0): EVERY response — rejections
included — carries ``X-Request-Id``; an inbound ``traceparent`` (+
``baggage``) header joins the caller's trace so the request's span
tree roots under the caller's span
(:mod:`..telemetry.propagation`), and dispatched requests return a
``Server-Timing`` header with the critical-path breakdown (queue /
coalesce / compile / execute). :class:`SimulationClient` generates a
traceparent per call and surfaces the echoed id on
:class:`ServeResponse` so user-side retries are correlatable.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from yuma_simulation_tpu.serve.service import ServeConfig, SimulationService

logger = logging.getLogger(__name__)

#: POST routes -> request kinds the service understands.
_ROUTES = {
    "/v1/simulate": "simulate",
    "/v1/sweep": "sweep",
    "/v1/table": "table",
    "/v1/whatif": "whatif",
}

#: Largest accepted request body (bytes): bounds a hostile
#: Content-Length before any array parsing happens.
MAX_BODY_BYTES = 256 * 1024 * 1024


class _Handler(BaseHTTPRequestHandler):
    server_version = "yuma-serve"
    protocol_version = "HTTP/1.1"

    # Set per server class (see _make_handler).
    service: SimulationService

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        logger.debug("http: " + format, *args)

    def _send_json(
        self, status: int, body: dict, headers: Optional[dict] = None
    ) -> None:
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        merged = dict(headers or {})
        # EVERY response carries the request's identity — rejections
        # included — so a client-side retry loop is correlatable.
        if getattr(self, "_rid", None) and "X-Request-Id" not in merged:
            merged["X-Request-Id"] = self._rid
        for k, v in merged.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802 — http.server API
        self._rid = self.service.mint_request_id()
        try:
            if self.path == "/healthz":
                self._send_json(200, self.service.healthz())
            elif self.path == "/debug/vars":
                # Pure reads under short locks — answering during load
                # never blocks the dispatcher.
                self._send_json(200, self.service.ops.debug_vars())
            elif self.path == "/debug/incidents":
                self._send_json(200, self.service.ops.debug_incidents())
            elif self.path.startswith("/debug/spans"):
                import urllib.parse

                query = urllib.parse.urlparse(self.path).query
                run_id = urllib.parse.parse_qs(query).get("run", [""])[0]
                try:
                    self._send_json(
                        200, self.service.ops.debug_spans(run_id or None)
                    )
                except ValueError as exc:
                    self._send_json(
                        400,
                        {"status": "rejected", "error": "InvalidRequest",
                         "message": str(exc)[:200]},
                    )
            elif self.path == "/v1/replay" or self.path.startswith(
                "/v1/replay/"
            ):
                status, body = self.service.replay_get(self.path)
                self._send_json(status, body)
            elif self.path == "/metrics":
                text = self.service.metrics_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(text)))
                self.send_header("X-Request-Id", self._rid)
                self.end_headers()
                self.wfile.write(text)
            else:
                self._send_json(
                    404,
                    {"status": "rejected", "error": "NotFound",
                     "message": f"no route {self.path!r}"},
                )
        except BrokenPipeError:  # client went away; nothing to answer
            pass

    def _do_debug_profile(self) -> None:
        """POST /debug/profile — outside the admission pipeline (an
        operator action, not tenant traffic): the ops plane's
        single-flight latch is the only gate, a concurrent window is a
        typed 409."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = 0
        raw = self.rfile.read(max(0, min(length, MAX_BODY_BYTES)))
        try:
            payload = json.loads(raw.decode() or "{}")
        except (ValueError, UnicodeDecodeError) as exc:
            self._send_json(
                400,
                {"status": "rejected", "error": "InvalidJSON",
                 "message": str(exc)[:200]},
            )
            return
        from yuma_simulation_tpu.telemetry.ops import ProfileBusyError

        try:
            started = self.service.ops.debug_profile(
                float(payload.get("seconds", 5.0)),
                mode=str(payload.get("mode", "trace")),
            )
        except ProfileBusyError as exc:
            self._send_json(
                409,
                {"status": "busy", "error": "ProfileBusy",
                 "message": str(exc), "active": exc.status},
            )
            return
        except (TypeError, ValueError) as exc:
            self._send_json(
                400,
                {"status": "rejected", "error": "InvalidRequest",
                 "message": str(exc)[:200]},
            )
            return
        self._send_json(200, {"status": "ok", "profile": started})

    def do_POST(self) -> None:  # noqa: N802 — http.server API
        self._rid = self.service.mint_request_id()
        try:
            if self.path == "/debug/profile":
                self._do_debug_profile()
                return
            kind = _ROUTES.get(self.path)
            if kind is None:
                # Responding BEFORE reading the body on a keep-alive
                # connection would leave the unread bytes to be parsed
                # as the next request line — close instead.
                self.close_connection = True
                self._send_json(
                    404,
                    {"status": "rejected", "error": "NotFound",
                     "message": f"no route {self.path!r}"},
                )
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
            except ValueError:
                length = -1
            if length < 0 or length > MAX_BODY_BYTES:
                self.close_connection = True  # body unread; see above
                self._send_json(
                    413,
                    {"status": "rejected", "error": "PayloadTooLarge",
                     "message": f"body must be 0..{MAX_BODY_BYTES} bytes"},
                )
                return
            raw = self.rfile.read(length)
            try:
                payload = json.loads(raw.decode() or "{}")
            except (ValueError, UnicodeDecodeError) as exc:
                self._send_json(
                    400,
                    {"status": "rejected", "error": "InvalidJSON",
                     "message": str(exc)[:200]},
                )
                return
            from yuma_simulation_tpu.telemetry.propagation import (
                BAGGAGE_HEADER,
                TRACEPARENT_HEADER,
                TraceContext,
            )

            trace = TraceContext.from_traceparent(
                self.headers.get(TRACEPARENT_HEADER),
                self.headers.get(BAGGAGE_HEADER),
            )
            status, body, headers = self.service.handle(
                kind,
                payload,
                request_id=self._rid,
                trace=trace,
                api_key=self.headers.get("X-Api-Key"),
            )
            self._send_json(status, body, headers)
        except BrokenPipeError:
            pass


def _make_handler(service: SimulationService) -> type:
    return type("BoundHandler", (_Handler,), {"service": service})


class SimulationServer:
    """The long-lived HTTP front: owns (or wraps) a
    :class:`SimulationService` and serves it on a background thread.
    `port=0` binds an ephemeral port (tests/smoke); :attr:`port` is the
    bound one. `close()` stops the listener THEN drains the service —
    in-flight requests finish, queued ones get the structured
    shutting-down response, the flight bundle publishes."""

    def __init__(
        self,
        config: Optional[ServeConfig] = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        service: Optional[SimulationService] = None,
    ):
        self.service = (
            service if service is not None else SimulationService(config)
        )
        self._httpd = ThreadingHTTPServer(
            (host, port), _make_handler(self.service)
        )
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "SimulationServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="yuma-serve-http",
            daemon=True,
        )
        self._thread.start()
        logger.info("serving on %s", self.url)
        return self

    def serve_forever(self) -> None:
        """Foreground mode (the CLI): serve until interrupted."""
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:  # pragma: no cover — interactive
            pass
        finally:
            self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self.service.close()


@dataclass
class ServeResponse:
    """One client-side result: HTTP status + parsed JSON body (+ the
    Retry-After header, parsed, when the server sent one), plus the
    correlation identity — the server-echoed ``X-Request-Id`` and the
    ``traceparent`` this call sent, so a user-side retry loop can tie
    every attempt to its server-side request span."""

    status: int
    body: dict
    retry_after: Optional[float] = None
    headers: dict = field(default_factory=dict)
    #: the traceparent header value this call sent (one per call).
    traceparent: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.status == 200 and self.body.get("status") in (
            "ok",
            "partial",
        )

    @property
    def request_id(self) -> Optional[str]:
        """The server's ``X-Request-Id`` echo (header first, body
        fallback) — the join key into the server's flight bundle."""
        return self.headers.get("X-Request-Id") or self.body.get(
            "request_id"
        )

    @property
    def server_timing(self) -> dict:
        """The ``Server-Timing`` critical-path breakdown as
        ``{phase: milliseconds}`` (empty when the server sent none)."""
        out: dict = {}
        raw = self.headers.get("Server-Timing", "")
        for item in raw.split(","):
            item = item.strip()
            if not item:
                continue
            name, _, params = item.partition(";")
            for p in params.split(";"):
                k, _, v = p.partition("=")
                if k.strip() == "dur":
                    try:
                        out[name.strip()] = float(v)
                    except ValueError:
                        pass
        return out


#: HTTP statuses the client's bounded retry loop treats as transient
#: (the server said "come back": quota shed / draining / overloaded).
_RETRYABLE_STATUSES = (429, 503)


class SimulationClient:
    """Stdlib client for the serving tier (the v1 helper): JSON over
    urllib, typed :class:`ServeResponse` back — 4xx/5xx are RETURNED
    (the server's typed bodies are the contract), never raised; only
    transport-level failures raise.

    **Bounded retry-with-backoff** (``retries`` > 0): transport-level
    connection resets/refusals and transient HTTP statuses (429/503)
    are retried up to ``retries`` extra attempts with exponential
    backoff — a server-sent ``Retry-After`` overrides the computed
    backoff (capped at ``max_backoff_seconds``), and every attempt
    re-sends the SAME ``traceparent``, so the server-side spans of all
    attempts stitch into one caller trace. When the budget is spent:
    a transport-level failure raises the typed
    :class:`..resilience.errors.ClientRetriesExhausted`; a transient
    HTTP response is RETURNED (its typed body is the contract and must
    reach the caller). ``retries=0`` (default) preserves the legacy
    single-shot behavior — callers who assert on raw 429s (quota
    tests, the smoke drill) see every response.

    ``api_key`` (see :mod:`.apikeys`) rides every request as
    ``X-Api-Key`` against deployments with signed tenant identity."""

    def __init__(
        self,
        base_url: str,
        *,
        tenant: str = "default",
        timeout: float = 120.0,
        retries: int = 0,
        backoff_base: float = 0.1,
        max_backoff_seconds: float = 5.0,
        api_key: Optional[str] = None,
    ):
        if retries < 0:
            raise ValueError("retries must be >= 0")
        self.base_url = base_url.rstrip("/")
        self.tenant = tenant
        self.timeout = timeout
        self.retries = int(retries)
        self.backoff_base = float(backoff_base)
        self.max_backoff_seconds = float(max_backoff_seconds)
        self.api_key = api_key

    def _trace_headers(self) -> dict:
        """One traceparent per call: the caller's active run + innermost
        span when one exists (so the server's request span tree roots
        under the CALLER's trace), else a fresh client-run identity —
        either way the server can be asked "what did my call do"."""
        from yuma_simulation_tpu.telemetry.propagation import (
            BAGGAGE_HEADER,
            TRACEPARENT_HEADER,
            TraceContext,
            current_trace_context,
        )
        from yuma_simulation_tpu.telemetry.runctx import new_run_id

        ctx = current_trace_context()
        if ctx is None:
            ctx = TraceContext(run_id=new_run_id())
        ctx = ctx.with_baggage(tenant=self.tenant)
        return {
            TRACEPARENT_HEADER: ctx.to_traceparent(),
            BAGGAGE_HEADER: ctx.to_baggage(),
        }

    def _request(
        self, method: str, path: str, payload: Optional[dict] = None
    ) -> ServeResponse:
        url = self.base_url + path
        data = None
        # One trace identity for the WHOLE retry loop: every attempt
        # re-sends the same traceparent, so the server-side request
        # spans of attempt 1..N stitch into one caller trace instead of
        # N unrelated ones.
        headers = {"Accept": "application/json"}
        headers.update(self._trace_headers())
        if self.api_key is not None:
            headers["X-Api-Key"] = self.api_key
        if payload is not None:
            data = json.dumps(payload).encode()
            headers["Content-Type"] = "application/json"
        last_exc: Optional[Exception] = None
        response: Optional[ServeResponse] = None
        for attempt in range(self.retries + 1):
            if attempt:
                wait = min(
                    self.max_backoff_seconds,
                    self.backoff_base * (2.0 ** (attempt - 1)),
                )
                # The server's own Retry-After is the honest backoff:
                # honor it (still capped — a hostile or confused server
                # must not park the client for an hour).
                if response is not None and response.retry_after:
                    wait = min(
                        self.max_backoff_seconds, response.retry_after
                    )
                time.sleep(wait)
            req = urllib.request.Request(
                url, data=data, headers=headers, method=method
            )
            try:
                try:
                    with urllib.request.urlopen(
                        req, timeout=self.timeout
                    ) as resp:
                        raw = resp.read()
                        status = resp.status
                        hdrs = dict(resp.headers.items())
                except urllib.error.HTTPError as err:
                    raw = err.read()
                    status = err.code
                    hdrs = dict(err.headers.items()) if err.headers else {}
            except (urllib.error.URLError, ConnectionError, OSError) as exc:
                # Transport-level failure (connection refused/reset,
                # unreachable): retryable while budget remains.
                last_exc = exc
                response = None
                continue
            try:
                body = json.loads(raw.decode() or "{}")
            except ValueError:
                body = {
                    "status": "error",
                    "raw": raw.decode(errors="replace"),
                }
            retry_after = None
            if "Retry-After" in hdrs:
                try:
                    retry_after = float(hdrs["Retry-After"])
                except ValueError:
                    pass
            response = ServeResponse(
                status=status,
                body=body,
                retry_after=retry_after,
                headers=hdrs,
                traceparent=headers.get("traceparent"),
            )
            if status not in _RETRYABLE_STATUSES:
                return response
            # 429/503: transient by contract; fall through to retry.
        if response is not None:
            # Budget spent on transient HTTP statuses: the server's
            # typed body is the contract — return the last one.
            return response
        from yuma_simulation_tpu.resilience.errors import (
            ClientRetriesExhausted,
        )

        raise ClientRetriesExhausted(
            f"{method} {url} failed after {self.retries + 1} attempt(s): "
            f"{last_exc}",
            attempts=self.retries + 1,
            last_error=last_exc,
        )

    def _post(self, path: str, payload: dict) -> ServeResponse:
        payload = dict(payload)
        payload.setdefault("tenant", self.tenant)
        return self._request("POST", path, payload)

    def simulate(self, **payload) -> ServeResponse:
        """POST /v1/simulate — `case=` (a registered case name) or
        `weights=`/`stakes=` arrays, plus `version`, `config`,
        `deadline_seconds`, `engine`, `quarantine` knobs."""
        return self._post("/v1/simulate", payload)

    def sweep(self, **payload) -> ServeResponse:
        """POST /v1/sweep — a scenario plus `axes={field: [values]}`."""
        return self._post("/v1/sweep", payload)

    def table(self, **payload) -> ServeResponse:
        """POST /v1/table — the total-dividends CSV across versions."""
        return self._post("/v1/table", payload)

    def whatif(self, spec: dict, **payload) -> ServeResponse:
        """POST /v1/whatif — `spec` is the
        :class:`..replay.whatif.WhatIfSpec` JSON object (``netuid``,
        ``version``, ``from_epoch``, ``hparams``/``weight_rows``/
        ``stake_scale``); returns per-validator/per-miner dividend
        deltas plus the suffix-resume accounting."""
        return self._post("/v1/whatif", {**payload, "whatif": spec})

    def replay(self, netuid: Optional[int] = None) -> ServeResponse:
        """GET /v1/replay (the archive index) or /v1/replay/NETUID
        (one subnet's timeline + cached baselines)."""
        path = "/v1/replay" if netuid is None else f"/v1/replay/{netuid}"
        return self._request("GET", path)

    def healthz(self) -> ServeResponse:
        return self._request("GET", "/healthz")

    def debug_vars(self) -> ServeResponse:
        """GET /debug/vars — the live ops snapshot."""
        return self._request("GET", "/debug/vars")

    def debug_incidents(self) -> ServeResponse:
        """GET /debug/incidents — durable incident state (postmortems
        live in ``tools/incidentreport.py``; this is the live view)."""
        return self._request("GET", "/debug/incidents")

    def debug_spans(self, run_id: Optional[str] = None) -> ServeResponse:
        """GET /debug/spans[?run=RUN_ID] — one run's live span tree."""
        path = "/debug/spans"
        if run_id:
            import urllib.parse

            path += "?run=" + urllib.parse.quote(run_id)
        return self._request("GET", path)

    def debug_profile(
        self, seconds: float = 5.0, mode: str = "trace"
    ) -> ServeResponse:
        """POST /debug/profile — kick one on-demand profiler window."""
        return self._post(
            "/debug/profile", {"seconds": seconds, "mode": mode}
        )

    def metrics(self) -> str:
        url = self.base_url + "/metrics"
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return resp.read().decode()


def wait_until_ready(
    url: str, *, timeout: float = 10.0, interval: float = 0.05
) -> bool:
    """Poll `/healthz` until the server answers (startup rendezvous for
    tests and the smoke lane)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                url.rstrip("/") + "/healthz", timeout=interval + 1.0
            ):
                return True
        except (urllib.error.URLError, socket.timeout, ConnectionError):
            time.sleep(interval)
    return False
