"""Admission control: validate and PRICE every request before any compile.

The serving tier's first robustness gate. A hostile or mistaken request
must be rejected while it is still cheap — after JSON parsing, before
any trace, compile, or device allocation — with a typed
:class:`..resilience.errors.AdmissionRejected` that tells the client
*why* and, when the analytic HBM preflight produced one, *what would
fit* (its shard-count / ``max_resident_epochs`` suggestion). The
machinery is exactly the planner's
(:func:`..simulation.planner.plan_dispatch` with
``raise_on_reject=False``): pure host arithmetic, zero compiles, so
admission costs microseconds even under a burst.

The output is an :class:`AdmissionTicket`: the parsed request plus its
frozen :class:`..simulation.planner.DispatchPlan` and the coalescing key
(shape bucket + version + config fingerprint) the dispatcher groups
same-bucket tenants by.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import replace
from typing import NoReturn, Optional, Sequence

import numpy as np

from yuma_simulation_tpu.resilience.errors import AdmissionRejected

#: Engines a request may name; "auto" resolves through the planner.
_ENGINES = (
    "auto",
    "xla",
    "fused_scan",
    "fused_scan_mxu",
    "fused_varying",
    "fused_varying_mxu",
)

#: Hard per-request shape ceilings — a parse-time sanity bound so a
#: hostile payload cannot make the server materialize absurd host
#: arrays before the preflight even runs. Generous: the bench flagship
#: (256 x 4096 x 10k epochs) fits with room.
MAX_EPOCHS = 1 << 20
MAX_VALIDATORS = 1 << 14
MAX_MINERS = 1 << 18

#: Hard cap on a sweep's grid cardinality: the cartesian product of the
#: axes is materialized host-side at dispatch, so an unbounded `axes`
#: payload would be exactly the host-memory DoS the array ceilings above
#: exist to stop.
MAX_SWEEP_POINTS = 4096


@dataclasses.dataclass(frozen=True)
class AdmissionTicket:
    """One admitted request, fully decided: what to run, on which plan,
    under which deadline — everything the dispatcher needs without
    re-touching the raw payload."""

    request_id: str
    tenant: str
    kind: str  # "simulate" | "sweep" | "table" | "whatif"
    version: str
    scenario: Optional[object]  # Scenario for simulate/sweep
    config: object  # YumaConfig
    config_key: tuple  # hashable fingerprint of the config overrides
    axes: Optional[dict]  # sweep hyperparameter grid
    versions: Optional[tuple]  # table versions
    plan: object  # DispatchPlan
    engine: str
    quarantine: bool
    deadline_seconds: float
    admitted_t: float  # time.monotonic() at admission
    #: Donor-packing group key: requests sharing it ride one batched
    #: dispatch. None = never coalesced (sweep/table/fused requests).
    coalesce_key: Optional[tuple] = None
    #: Degradation priority: while an SLO fast-burn has the service
    #: shedding, requests below the configured floor are dropped first
    #: (0 = normal traffic; negotiated tenants send higher).
    priority: int = 0
    #: The parsed :class:`..replay.whatif.WhatIfSpec` for
    #: ``kind="whatif"`` requests (None otherwise). The plan above is
    #: SUFFIX-sized for these: admission prices the epochs the dispatch
    #: will actually re-simulate from the cached checkpoint, not the
    #: full baseline length.
    whatif: Optional[object] = None

    def remaining_seconds(self) -> float:
        return self.deadline_seconds - (time.monotonic() - self.admitted_t)


def _reject(
    message: str, *, reason: str = "invalid_request", **kw
) -> NoReturn:
    raise AdmissionRejected(message, reason=reason, **kw)


def _require(payload: dict, field: str):
    if field not in payload:
        _reject(f"request is missing required field {field!r}")
    return payload[field]


def _as_float_array(value, field: str, ndim: int) -> np.ndarray:
    try:
        arr = np.asarray(value, dtype=np.float32)
    except (TypeError, ValueError):
        _reject(f"field {field!r} is not a numeric array")
    if arr.ndim != ndim:
        _reject(
            f"field {field!r} must be {ndim}-dimensional, got shape "
            f"{arr.shape}"
        )
    return arr


def _build_config(overrides: Optional[dict]):
    """A `YumaConfig` from a flat float-field override dict — the same
    field universe `config_grid` sweeps (static/compiled fields are not
    request-settable: they select different compiled programs, which a
    warm-engine service must not let a payload do)."""
    from yuma_simulation_tpu.models.config import (
        SimulationHyperparameters,
        YumaConfig,
        YumaParams,
    )

    sim = SimulationHyperparameters()
    par = YumaParams()
    if not overrides:
        return YumaConfig(simulation=sim, yuma_params=par), ()
    if not isinstance(overrides, dict):
        _reject("field 'config' must be an object of float fields")
    sim_fields = {f for f in vars(sim) if f != "consensus_precision"}
    par_fields = {
        f
        for f in vars(par)
        if f
        not in (
            "liquid_alpha",
            "override_consensus_high",
            "override_consensus_low",
        )
    }
    key = []
    for name, value in sorted(overrides.items()):
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            _reject(f"config field {name!r} must be a number")
        if name in sim_fields:
            sim = replace(sim, **{name: float(value)})
        elif name in par_fields:
            par = replace(par, **{name: float(value)})
        else:
            _reject(
                f"config field {name!r} is not request-settable "
                "(unknown or compile-static)"
            )
        key.append((name, float(value)))
    return YumaConfig(simulation=sim, yuma_params=par), tuple(key)


def _build_scenario(payload: dict, request_id: str):
    """The request's Scenario: a registered case by name, or explicit
    `[E, V, M]` weights + `[E, V]` stakes arrays."""
    from yuma_simulation_tpu.scenarios.base import Scenario, create_case

    case_name = payload.get("case")
    if case_name is not None:
        try:
            return create_case(str(case_name))
        except ValueError as exc:
            _reject(str(exc))
    weights = _as_float_array(_require(payload, "weights"), "weights", 3)
    stakes = _as_float_array(_require(payload, "stakes"), "stakes", 2)
    E, V, M = weights.shape
    if not (1 <= E <= MAX_EPOCHS):
        _reject(f"epochs {E} outside [1, {MAX_EPOCHS}]")
    if not (1 <= V <= MAX_VALIDATORS):
        _reject(f"validators {V} outside [1, {MAX_VALIDATORS}]")
    if not (1 <= M <= MAX_MINERS):
        _reject(f"miners {M} outside [1, {MAX_MINERS}]")
    if stakes.shape != (E, V):
        _reject(
            f"stakes shape {stakes.shape} does not match weights "
            f"[E={E}, V={V}]"
        )
    reset_index = payload.get("reset_bonds_index")
    reset_epoch = payload.get("reset_bonds_epoch")
    for name, val in (
        ("reset_bonds_index", reset_index),
        ("reset_bonds_epoch", reset_epoch),
    ):
        if val is not None and not isinstance(val, int):
            _reject(f"field {name!r} must be an integer epoch/index")
    validators = [f"v{i}" for i in range(V)]
    return Scenario(
        name=f"request:{request_id}",
        validators=validators,
        base_validator=validators[0],
        weights=weights,
        stakes=stakes,
        num_epochs=E,
        reset_bonds_index=reset_index,
        reset_bonds_epoch=reset_epoch,
    )


def _plan_or_reject(
    label: str,
    shape: Sequence[int],
    version: str,
    config,
    *,
    engine: str,
    quarantine: bool,
):
    """Run the planner as the admission pricer: planner `ValueError`s
    (bad impl combinations) become typed rejections, and a preflight
    verdict of "cannot fit" rejects WITH the planner's suggestion —
    before anything compiled."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.simulation.planner import plan_dispatch

    try:
        plan = plan_dispatch(
            label,
            shape,
            version,
            config,
            jnp.float32,
            epoch_impl=engine,
            quarantine=quarantine,
            raise_on_reject=False,
        )
    except (ValueError, KeyError) as exc:
        _reject(str(exc))
    if plan.memory.fits is False:
        _reject(
            f"predicted HBM footprint "
            f"{plan.memory.predicted_bytes / 2**30:.2f} GiB exceeds "
            "device capacity"
            + (
                f" ({plan.memory.capacity_bytes / 2**30:.2f} GiB)"
                if plan.memory.capacity_bytes
                else ""
            ),
            reason="preflight_rejected",
            suggestion=plan.memory.suggestion,
        )
    return plan


def admit(
    payload: dict,
    *,
    request_id: str,
    kind: str,
    default_deadline_seconds: float,
    max_unit_lanes: int = 64,
    tenant_priority: Optional[dict] = None,
    replay=None,
) -> AdmissionTicket:
    """Validate and price one request; returns the ticket or raises a
    typed :class:`AdmissionRejected`. Zero compiles by construction.

    `replay` (a :class:`..replay.ReplayService`, None when the
    deployment mounts no replay tier) admits ``kind="whatif"``: the
    spec parses/validates from the payload's ``whatif`` object, the
    subnet resolves against the archive index, and the plan prices the
    SUFFIX the dispatch will actually simulate — ``describe()`` is
    index/meta reads plus the planner's host arithmetic, so a what-if
    admission stays as compile-free as every other kind."""
    from yuma_simulation_tpu.models.variants import variant_for_version

    if not isinstance(payload, dict):
        _reject("request body must be a JSON object")
    tenant = payload.get("tenant", "anonymous")
    if not isinstance(tenant, str) or not tenant:
        _reject("field 'tenant' must be a non-empty string")
    version = payload.get("version", "Yuma 1 (paper)")
    if kind == "whatif" and "whatif" in payload:
        raw_spec = payload["whatif"]
        if isinstance(raw_spec, dict) and "version" in raw_spec:
            # The what-if's variant rides the spec, not the envelope.
            version = raw_spec["version"]
    try:
        variant_for_version(version)
    except (ValueError, KeyError, TypeError) as exc:
        _reject(f"unknown version {version!r}: {exc}")
    engine = payload.get("engine", "auto")
    if engine not in _ENGINES:
        _reject(f"unknown engine {engine!r}; expected one of {_ENGINES}")
    deadline = payload.get("deadline_seconds", default_deadline_seconds)
    if not isinstance(deadline, (int, float)) or deadline <= 0:
        _reject("field 'deadline_seconds' must be a positive number")
    priority = payload.get("priority", 0)
    if not isinstance(priority, int) or isinstance(priority, bool):
        _reject("field 'priority' must be an integer")
    if tenant_priority is not None:
        # The payload field is untrusted: with a negotiated ceiling
        # table installed, a tenant rides at most its entry (absent
        # tenants at 0), so degradation cannot be opted out of by
        # simply claiming priority in the request body.
        priority = min(priority, int(tenant_priority.get(tenant, 0)))
    config, config_key = _build_config(payload.get("config"))
    quarantine = bool(
        payload.get("quarantine", engine in ("auto", "xla"))
    )
    from yuma_simulation_tpu.simulation.planner import FUSED_CASE_RUNGS

    if quarantine and engine in FUSED_CASE_RUNGS:
        _reject(
            "quarantine rides the XLA scan carry; a fused-engine "
            "request must pass quarantine=false"
        )

    scenario = None
    axes = None
    versions = None
    coalesce_key = None
    whatif_spec = None
    if kind == "whatif":
        if replay is None:
            _reject(
                "this deployment mounts no replay tier (configure "
                "replay_archive_dir/replay_cache_dir to serve what-ifs)",
                reason="replay_unconfigured",
            )
        from yuma_simulation_tpu.replay import ArchiveError, WhatIfError
        from yuma_simulation_tpu.replay.whatif import WhatIfSpec

        try:
            whatif_spec = WhatIfSpec.from_json(_require(payload, "whatif"))
        except WhatIfError as exc:
            _reject(str(exc))
        try:
            desc = replay.describe(whatif_spec)
        except ArchiveError as exc:
            _reject(str(exc), reason="unknown_subnet")
        except WhatIfError as exc:
            _reject(str(exc))
        # Suffix-sized pricing: the dispatch re-simulates only
        # [resume_epoch, E) from the cached checkpoint; that is the
        # footprint admission charges (and the preflight bounds).
        plan = _plan_or_reject(
            f"serve:whatif:{request_id}",
            (
                max(1, desc["suffix_epochs"]),
                desc["validators"],
                desc["miners"],
            ),
            whatif_spec.version,
            config,
            engine="auto",
            quarantine=False,
        )
    elif kind == "simulate":
        scenario = _build_scenario(payload, request_id)
        E, V, M = scenario.weights.shape
        plan = _plan_or_reject(
            f"serve:simulate:{request_id}",
            (E, V, M),
            version,
            config,
            engine=engine,
            quarantine=quarantine,
        )
        if plan.engine == "xla":
            # Donor-packing group: same tile bucket + same epochs +
            # same version/config/quarantine rides ONE batched dispatch
            # (the planner's bucket policy — epochs are data, never
            # bucketed).
            coalesce_key = (
                "simulate",
                version,
                config_key,
                quarantine,
                plan.bucket.epochs,
                plan.bucket.padded_V,
                plan.bucket.padded_M,
            )
    elif kind == "sweep":
        scenario = _build_scenario(payload, request_id)
        raw_axes = _require(payload, "axes")
        if not isinstance(raw_axes, dict) or not raw_axes:
            _reject("field 'axes' must be a non-empty object of lists")
        axes = {}
        points = 1
        for name, values in sorted(raw_axes.items()):
            if not isinstance(values, (list, tuple)) or not values:
                _reject(f"axis {name!r} must be a non-empty list")
            if not all(
                isinstance(v, (int, float)) and not isinstance(v, bool)
                for v in values
            ):
                _reject(f"axis {name!r} values must be numbers")
            axes[name] = [float(v) for v in values]
            points *= len(values)
            if points > MAX_SWEEP_POINTS:
                _reject(
                    f"sweep grid exceeds {MAX_SWEEP_POINTS} points; "
                    "split the axes across requests (or run it through "
                    "the fleet fabric's run_fleet_grid)"
                )
        # Validate the axis names the same way config_grid will.
        from yuma_simulation_tpu.simulation.sweep import config_grid

        try:
            config_grid(**{k: v[:1] for k, v in axes.items()})
        except ValueError as exc:
            _reject(str(exc))
        E, V, M = scenario.weights.shape
        # Price the batch the dispatcher will actually place: the grid
        # partitions into units of at most `max_unit_lanes` lanes, so a
        # large-but-unit-partitioned sweep must not be rejected on a
        # monolithic footprint it never dispatches.
        plan = _plan_or_reject(
            f"serve:sweep:{request_id}",
            (min(points, max_unit_lanes), E, V, M),
            version,
            config,
            engine="xla",
            quarantine=quarantine,
        )
    elif kind == "table":
        from yuma_simulation_tpu.models.config import YumaSimulationNames

        names = vars(YumaSimulationNames()).values()
        raw_versions = payload.get("versions")
        if raw_versions is None:
            versions = (version,)
        else:
            if not isinstance(raw_versions, (list, tuple)) or not raw_versions:
                _reject("field 'versions' must be a non-empty list")
            for v in raw_versions:
                if v not in names:
                    _reject(f"unknown version {v!r} in 'versions'")
            versions = tuple(raw_versions)
        from yuma_simulation_tpu.scenarios.base import get_cases

        suite = get_cases()
        E, V, M = suite[0].weights.shape
        plan = _plan_or_reject(
            f"serve:table:{request_id}",
            (len(suite), E, V, M),
            versions[0],
            config,
            engine="xla",
            quarantine=False,
        )
    else:
        _reject(f"unknown request kind {kind!r}")

    return AdmissionTicket(
        request_id=request_id,
        tenant=tenant,
        kind=kind,
        version=version,
        scenario=scenario,
        config=config,
        config_key=config_key,
        axes=axes,
        versions=versions,
        plan=plan,
        engine=engine,
        quarantine=quarantine,
        deadline_seconds=float(deadline),
        admitted_t=time.monotonic(),
        coalesce_key=coalesce_key,
        priority=priority,
        whatif=whatif_spec,
    )
