"""Serving tier: the warm-engine simulation service (ROADMAP item 1).

The long-lived process that turns the platform into a product: engines
stay warm across requests, heterogeneous tenants coalesce onto shared
compiled shapes, and hostile traffic degrades gracefully instead of
taking the process down. Five modules:

- :mod:`.admission` — validate + price every request through the
  dispatch planner and the analytic HBM preflight BEFORE any compile
  (typed :class:`..resilience.errors.AdmissionRejected` -> 400 with the
  preflight's reshape suggestion);
- :mod:`.quotas` — per-tenant token buckets + the global bounded run
  queue (typed :class:`..resilience.errors.QueueOverflow` -> 429 +
  ``Retry-After``; ``serve_queue_depth``/``serve_requests_shed``
  metrics);
- :mod:`.coalescer` — same-shape-bucket requests donor-packed into one
  batched dispatch, per-request lanes sliced back bitwise;
- :mod:`.lifecycle` — the per-engine-rung circuit breaker (trip ->
  re-anchored plans fleet-wide -> half-open probe -> close) and the
  startup warmup pass;
- :mod:`.service` / :mod:`.server` — the pipeline core and its stdlib
  `http.server` front (``/v1/simulate``, ``/v1/sweep``, ``/v1/table``,
  ``/healthz``, ``/metrics``) plus the stdlib
  :class:`~.server.SimulationClient`.

Run it: ``python -m yuma_simulation_tpu.serve`` (see ``--help``;
``--smoke`` drives the CI smoke lane). README "Serving" has the
operator contract.
"""

from yuma_simulation_tpu.serve.admission import (  # noqa: F401
    AdmissionTicket,
    admit,
)
from yuma_simulation_tpu.serve.lifecycle import (  # noqa: F401
    CircuitBreaker,
    warmup,
)
from yuma_simulation_tpu.serve.quotas import (  # noqa: F401
    BoundedRunQueue,
    TenantQuotas,
    TokenBucket,
)
from yuma_simulation_tpu.serve.server import (  # noqa: F401
    ServeResponse,
    SimulationClient,
    SimulationServer,
    wait_until_ready,
)
from yuma_simulation_tpu.serve.service import (  # noqa: F401
    ServeConfig,
    SimulationService,
)
