"""Serving tier: the warm-engine simulation service (ROADMAP item 1).

The long-lived process that turns the platform into a product: engines
stay warm across requests, heterogeneous tenants coalesce onto shared
compiled shapes, and hostile traffic degrades gracefully instead of
taking the process down. The pipeline modules:

- :mod:`.admission` — validate + price every request through the
  dispatch planner and the analytic HBM preflight BEFORE any compile
  (typed :class:`..resilience.errors.AdmissionRejected` -> 400 with the
  preflight's reshape suggestion);
- :mod:`.quotas` — per-tenant token buckets + the global bounded run
  queue (typed :class:`..resilience.errors.QueueOverflow` -> 429 +
  ``Retry-After``; ``serve_queue_depth``/``serve_requests_shed``
  metrics);
- :mod:`.coalescer` — same-shape-bucket requests donor-packed into one
  batched dispatch, per-request lanes sliced back bitwise;
- :mod:`.lifecycle` — the per-engine-rung circuit breaker (trip ->
  re-anchored plans fleet-wide -> half-open probe -> close) and the
  startup warmup pass;
- :mod:`.service` / :mod:`.server` — the pipeline core and its stdlib
  `http.server` front (``/v1/simulate``, ``/v1/sweep``, ``/v1/table``,
  ``/healthz``, ``/metrics``) plus the stdlib
  :class:`~.server.SimulationClient` (bounded retry-with-backoff via
  ``retries=``).

The horizontal scale-out tier (PR 16) rides on top of that pipeline:

- :mod:`.apikeys` — signed HMAC tenant identity (``X-Api-Key``); the
  verified tenant overwrites the payload claim before admission;
- :mod:`.worker` — one pipeline process per pool slot, heartbeating a
  lease annotated with its held state-cache prefixes and warm shape
  buckets;
- :mod:`.router` — the stateless front-end: admits through the same
  :func:`.admission.admit` path, places by pure claim scoring
  (:func:`.router.claim_score`), reroutes around killed workers;
- :mod:`.autoscaler` — SLO fast-burn adds supply (AOT-preloaded
  spawns), idleness retires it youngest-first.

Run it: ``python -m yuma_simulation_tpu.serve`` (see ``--help``;
``--smoke`` drives the CI smoke lane, ``--router --worker-pool DIR``
the scale-out deployment, ``--scaleout-drill`` its chaos proof).
README "Serving" / "Horizontal serving" has the operator contract.
"""

from yuma_simulation_tpu.serve.admission import (  # noqa: F401
    AdmissionTicket,
    admit,
)
from yuma_simulation_tpu.serve.apikeys import (  # noqa: F401
    ApiKeyring,
    mint_api_key,
)
from yuma_simulation_tpu.serve.autoscaler import Autoscaler  # noqa: F401
from yuma_simulation_tpu.serve.lifecycle import (  # noqa: F401
    CircuitBreaker,
    warmup,
)
from yuma_simulation_tpu.serve.quotas import (  # noqa: F401
    BoundedRunQueue,
    TenantQuotas,
    TokenBucket,
)
from yuma_simulation_tpu.serve.server import (  # noqa: F401
    ServeResponse,
    SimulationClient,
    SimulationServer,
    wait_until_ready,
)
from yuma_simulation_tpu.serve.router import (  # noqa: F401
    RouterConfig,
    RouterService,
    WorkerPool,
    claim_score,
    rank_claims,
)
from yuma_simulation_tpu.serve.service import (  # noqa: F401
    ServeConfig,
    SimulationService,
)
from yuma_simulation_tpu.serve.worker import ServeWorker  # noqa: F401
