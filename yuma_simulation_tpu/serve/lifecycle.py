"""Serving-tier lifecycle: the per-engine-rung circuit breaker + warmup.

The graceful-degradation backstop. The engine ladder already demotes a
FAILING dispatch rung by rung — but on a service, every request that
walks the ladder pays the failing rung's latency (attempts x backoff x
deadline) before landing on the rung that works. The
:class:`CircuitBreaker` remembers: after `threshold` consecutive
batches whose typed failures demoted off a rung, the rung TRIPS OPEN
fleet-wide (process-wide — every tenant, every shape bucket) and new
dispatch plans are re-anchored below it
(:meth:`..simulation.planner.DispatchPlan.demoted`), skipping the
failing rung entirely. After `cooldown_seconds` the rung goes HALF-OPEN:
exactly one probe batch is allowed to try it again — success closes the
rung, failure re-opens it with a fresh cooldown. Classic breaker
semantics, engine-rung granular.

State feeds the metrics registry (``serve_breaker_trips`` counter,
``serve_breaker_open`` gauge) and `/healthz`, so a tripped rung is an
operator-visible event, not a silent slowdown that recovered.

:func:`warmup` is the warm-engine half of the service's name: run one
throwaway dispatch per configured shape bucket at startup, so the first
real tenant request rides a warm jit cache instead of paying the cold
compile inside its own deadline.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional, Sequence

from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


class _RungState:
    __slots__ = ("failures", "opened_at", "probing")

    def __init__(self) -> None:
        self.failures = 0
        self.opened_at: Optional[float] = None
        self.probing = False


class CircuitBreaker:
    """Per-engine-rung trip/half-open/close state (see module docstring).

    Thread-safe; the clock is injectable for deterministic tests. The
    LAST rung of any ladder is never filtered out — a breaker that
    could open every rung would turn "degraded" into "down", which is
    the opposite of its job (the final rung's failures still count, so
    `/healthz` shows it red)."""

    def __init__(
        self,
        threshold: int = 3,
        cooldown_seconds: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
    ):
        if threshold < 1:
            raise ValueError("CircuitBreaker threshold must be >= 1")
        self.threshold = threshold
        self.cooldown_seconds = cooldown_seconds
        self._clock = clock
        self._lock = threading.Lock()
        self._rungs: dict[str, _RungState] = {}
        if registry is None:
            from yuma_simulation_tpu.telemetry.metrics import get_registry

            registry = get_registry()
        self._trips = registry.counter(
            "serve_breaker_trips", help="circuit-breaker engine-rung trips"
        )
        self._open_gauge = registry.gauge(
            "serve_breaker_open", help="engine rungs currently tripped open"
        )

    def _state(self, rung: str) -> _RungState:
        st = self._rungs.get(rung)
        if st is None:
            st = self._rungs[rung] = _RungState()
        return st

    def _publish_open_count(self) -> None:
        self._open_gauge.set(
            sum(1 for s in self._rungs.values() if s.opened_at is not None)
        )

    def filter_ladder(self, ladder: Sequence[str]) -> tuple:
        """The sub-ladder a new dispatch should start at: open rungs are
        skipped unless their cooldown has elapsed, in which case exactly
        ONE caller is admitted as the half-open probe (`probing` latches
        under the lock until that probe reports). The last rung always
        remains available."""
        ladder = tuple(ladder)
        with self._lock:
            for i, rung in enumerate(ladder[:-1]):
                st = self._state(rung)
                if st.opened_at is None:
                    return ladder[i:]
                if (
                    not st.probing
                    and self._clock() - st.opened_at >= self.cooldown_seconds
                ):
                    st.probing = True
                    log_event(
                        logger, "breaker_half_open", rung=rung,
                        level=logging.INFO,
                    )
                    return ladder[i:]
            return ladder[-1:]

    def record_success(self, rung: str) -> None:
        """A batch completed ON `rung` (no demotion off it): close it."""
        with self._lock:
            st = self._state(rung)
            was_open = st.opened_at is not None
            st.failures = 0
            st.opened_at = None
            st.probing = False
            self._publish_open_count()
        if was_open:
            log_event(
                logger, "breaker_closed", rung=rung, level=logging.INFO
            )

    def record_failure(self, rung: str) -> None:
        """A batch's typed failures demoted off `rung` (or its probe
        failed): count toward the threshold / re-open immediately."""
        with self._lock:
            st = self._state(rung)
            st.failures += 1
            tripped = False
            if st.probing:
                # The half-open probe failed: re-open, fresh cooldown.
                st.opened_at = self._clock()
                st.probing = False
                tripped = True
            elif st.opened_at is None and st.failures >= self.threshold:
                st.opened_at = self._clock()
                tripped = True
            if tripped:
                self._trips.inc()
                self._publish_open_count()
            failures = st.failures
        if tripped:
            log_event(
                logger,
                "breaker_tripped",
                rung=rung,
                failures=failures,
                cooldown_s=f"{self.cooldown_seconds:.1f}",
            )

    def abort_probe(self, rung: str) -> None:
        """Un-latch a half-open probe that failed for a reason the
        breaker should NOT count (a caller error, an unclassified
        crash): `probing` clears but the rung stays open with its
        original `opened_at`, so the next caller is immediately
        admitted as a fresh probe. Without this, a probe dying on a
        non-engine failure would leave `probing` latched forever and
        the rung dead for the process lifetime. No-op when the rung is
        not probing."""
        with self._lock:
            st = self._rungs.get(rung)
            if st is None or not st.probing:
                return
            st.probing = False
        log_event(
            logger, "breaker_probe_aborted", rung=rung, level=logging.INFO
        )

    def snapshot(self) -> dict:
        """`{rung: {"state": "closed"|"open"|"half_open", "failures": n}}`
        for `/healthz`."""
        with self._lock:
            out = {}
            for rung, st in self._rungs.items():
                state = "closed"
                if st.opened_at is not None:
                    state = "half_open" if st.probing else "open"
                out[rung] = {"state": state, "failures": st.failures}
            return out


def warmup(
    shapes: Sequence[tuple],
    *,
    version: str = "Yuma 1 (paper)",
    logger_: Optional[logging.Logger] = None,
) -> int:
    """Pre-compile the serving path for each `(epochs, V, M)` shape:
    one throwaway donor-packed batch through the same
    `simulate_batch`/quarantine path real requests ride, so their
    bucket's program is warm before traffic arrives. Returns the number
    of shapes warmed. Failures are logged, never fatal — a service that
    refuses to start because a warmup shape was bad would be less
    available, not more."""
    import numpy as np

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.scenarios.base import Scenario
    from yuma_simulation_tpu.simulation.sweep import (
        pack_scenarios,
        simulate_batch,
    )

    warmed = 0
    spec = variant_for_version(version)
    # Cold-start accounting (simulation.aot): with an executable cache
    # active, each warmup dispatch below resolves through it — hits
    # load published artifacts in milliseconds, misses compile once and
    # publish for the next worker. The before/after stats delta rides
    # the serve_warmed event, so a worker that re-paid compiles it
    # should have loaded is visible in one grep.
    from yuma_simulation_tpu.simulation.aot import process_stats

    stats_before = process_stats().to_json()
    for shape in shapes:
        try:
            E, V, M = (int(d) for d in shape)
            validators = [f"v{i}" for i in range(V)]
            scenario = Scenario(
                name=f"warmup:{E}x{V}x{M}",
                validators=validators,
                base_validator=validators[0],
                weights=np.zeros((E, V, M), np.float32),
                stakes=np.ones((E, V), np.float32),
                num_epochs=E,
            )
            W, S, ri, re, mask = pack_scenarios([scenario])
            simulate_batch(
                W, S, ri, re, YumaConfig(), spec,
                miner_mask=mask, quarantine=True,
            )
            warmed += 1
        except Exception:
            (logger_ or logger).warning(
                "warmup dispatch for shape %s failed", shape, exc_info=True
            )
    if warmed:
        stats_after = process_stats().to_json()
        log_event(
            logger_ or logger,
            "serve_warmed",
            level=logging.INFO,
            shapes=warmed,
            aot_hits=stats_after["hits"] - stats_before["hits"],
            aot_builds=stats_after["builds"] - stats_before["builds"],
        )
    return warmed
