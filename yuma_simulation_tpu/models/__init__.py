"""The Yuma consensus model family: configs, the unified epoch kernel, variants."""

from yuma_simulation_tpu.models.config import (  # noqa: F401
    SimulationHyperparameters,
    YumaConfig,
    YumaParams,
    YumaSimulationNames,
)
from yuma_simulation_tpu.models.epoch import BondsMode, yuma_epoch  # noqa: F401
from yuma_simulation_tpu.models.variants import (  # noqa: F401
    ResetMode,
    VariantSpec,
    Yuma,
    Yuma2,
    Yuma3,
    Yuma4,
    YumaRust,
    variant_for_version,
)
