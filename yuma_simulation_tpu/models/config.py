"""Hyperparameter configuration as JAX pytrees.

The reference keeps two plain dataclasses flattened onto a combined
`YumaConfig` via `setattr` (reference yumas.py:7-45). Here the same shape is
kept but registered as a pytree with `jax.tree_util.register_dataclass`:

- float fields are *data* (pytree leaves) so they can be traced, swept with
  `vmap`, and donated — a `bond_alpha x kappa` grid is one batched config;
- structural fields (`liquid_alpha`, `consensus_precision`, the quantile
  overrides) are *metadata* (static), so each combination compiles its own
  specialized XLA program with no runtime branching.

Flattened attribute access (`config.kappa`, `config.bond_alpha`, ...) is
provided with properties rather than `setattr`, keeping the dataclasses
frozen/hashable-by-structure.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

from jax import tree_util


@tree_util.register_dataclass
@dataclass(frozen=True)
class SimulationHyperparameters:
    """Global sweep-level knobs (reference yumas.py:7-14)."""

    kappa: float = 0.5
    bond_penalty: float = 1.0
    total_epoch_emission: float = 100.0
    validator_emission_ratio: float = 0.41
    total_subnet_stake: float = 1_000_000.0
    consensus_precision: int = dataclasses.field(
        default=100_000, metadata=dict(static=True)
    )


@tree_util.register_dataclass
@dataclass(frozen=True)
class YumaParams:
    """Per-version knobs (reference yumas.py:17-27)."""

    bond_alpha: float = 0.1
    alpha_high: float = 0.9
    alpha_low: float = 0.7
    decay_rate: float = 0.1
    capacity_alpha: float = 0.1
    liquid_alpha: bool = dataclasses.field(default=False, metadata=dict(static=True))
    override_consensus_high: Optional[float] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )
    override_consensus_low: Optional[float] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )


@tree_util.register_dataclass
@dataclass(frozen=True)
class YumaConfig:
    """Combined config with flattened read access (reference yumas.py:29-45)."""

    simulation: SimulationHyperparameters = field(
        default_factory=SimulationHyperparameters
    )
    yuma_params: YumaParams = field(default_factory=YumaParams)

    # --- flattened simulation fields ---
    @property
    def kappa(self):
        return self.simulation.kappa

    @property
    def bond_penalty(self):
        return self.simulation.bond_penalty

    @property
    def total_epoch_emission(self):
        return self.simulation.total_epoch_emission

    @property
    def validator_emission_ratio(self):
        return self.simulation.validator_emission_ratio

    @property
    def total_subnet_stake(self):
        return self.simulation.total_subnet_stake

    @property
    def consensus_precision(self):
        return self.simulation.consensus_precision

    # --- flattened yuma-params fields ---
    @property
    def bond_alpha(self):
        return self.yuma_params.bond_alpha

    @property
    def liquid_alpha(self):
        return self.yuma_params.liquid_alpha

    @property
    def alpha_high(self):
        return self.yuma_params.alpha_high

    @property
    def alpha_low(self):
        return self.yuma_params.alpha_low

    @property
    def decay_rate(self):
        return self.yuma_params.decay_rate

    @property
    def capacity_alpha(self):
        return self.yuma_params.capacity_alpha

    @property
    def override_consensus_high(self):
        return self.yuma_params.override_consensus_high

    @property
    def override_consensus_low(self):
        return self.yuma_params.override_consensus_low


@dataclass(frozen=True)
class YumaSimulationNames:
    """Canonical display names of the 9 built-in versions (yumas.py:48-58).

    These strings are the dispatch keys used throughout the public API, so
    they match the reference byte-for-byte.
    """

    YUMA_RUST: str = "Yuma 0 (subtensor)"
    YUMA: str = "Yuma 1 (paper)"
    YUMA_LIQUID: str = "Yuma 1 (paper) - liquid alpha on"
    YUMA2: str = "Yuma 2 (Adrian-Fish)"
    YUMA3: str = "Yuma 3 (Rhef)"
    YUMA31: str = "Yuma 3.1 (Rhef+reset)"
    YUMA32: str = "Yuma 3.2 (Rhef+conditional)"
    YUMA4: str = "Yuma 4 (Rhef+relative bonds)"
    YUMA4_LIQUID: str = "Yuma 4 (Rhef+relative bonds) - liquid alpha on"
