"""The nine named Yuma versions: dispatch specs + reference-style wrappers.

`run_simulation` in the reference dispatches on the version *display string*
(reference simulation_utils.py:52-93), carrying a variant-specific bond
state and reset rule. :class:`VariantSpec` captures that dispatch table as
static data consumed by the scan engine; the module also exposes
`YumaRust` / `Yuma` / `Yuma2` / `Yuma3` / `Yuma4` functions with the
reference call signatures (yumas.py:61,175,285,399,494) for users porting
notebook code one function at a time.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig, YumaSimulationNames
from yuma_simulation_tpu.models.epoch import BondsMode, yuma_epoch


class ResetMode(enum.Enum):
    """Bond-reset injection rule (reference simulation_utils.py:62-88)."""

    NONE = "none"
    ALWAYS = "always"  # Yuma 3.1: reset at the case's reset epoch
    CONDITIONAL = "conditional"  # Yuma 3.2 / 4: only if the miner's previous
    # epoch consensus weight was exactly zero


@dataclass(frozen=True)
class VariantSpec:
    """Static description of one named version for the scan engine."""

    name: str
    bonds_mode: BondsMode
    reset_mode: ResetMode = ResetMode.NONE
    # Which kernel output is carried as the bond state across epochs.
    bond_state_key: str = "validator_ema_bond"
    # Whether the normalized weights are carried for next epoch's clipping.
    carries_prev_weights: bool = False


_NAMES = YumaSimulationNames()

YUMA_VERSIONS: dict[str, VariantSpec] = {
    _NAMES.YUMA_RUST: VariantSpec(_NAMES.YUMA_RUST, BondsMode.EMA_RUST),
    _NAMES.YUMA: VariantSpec(_NAMES.YUMA, BondsMode.EMA),
    _NAMES.YUMA_LIQUID: VariantSpec(_NAMES.YUMA_LIQUID, BondsMode.EMA),
    _NAMES.YUMA2: VariantSpec(
        _NAMES.YUMA2, BondsMode.EMA_PREV, carries_prev_weights=True
    ),
    _NAMES.YUMA3: VariantSpec(
        _NAMES.YUMA3, BondsMode.CAPACITY, bond_state_key="validator_bonds"
    ),
    _NAMES.YUMA31: VariantSpec(
        _NAMES.YUMA31,
        BondsMode.CAPACITY,
        ResetMode.ALWAYS,
        bond_state_key="validator_bonds",
    ),
    _NAMES.YUMA32: VariantSpec(
        _NAMES.YUMA32,
        BondsMode.CAPACITY,
        ResetMode.CONDITIONAL,
        bond_state_key="validator_bonds",
    ),
    _NAMES.YUMA4: VariantSpec(
        _NAMES.YUMA4,
        BondsMode.RELATIVE,
        ResetMode.CONDITIONAL,
        bond_state_key="validator_bonds",
    ),
    _NAMES.YUMA4_LIQUID: VariantSpec(
        _NAMES.YUMA4_LIQUID,
        BondsMode.RELATIVE,
        ResetMode.CONDITIONAL,
        bond_state_key="validator_bonds",
    ),
}


def canonical_versions(
    yuma4_bond_alpha: float = 0.025,
    yuma4_alpha_high: float = 0.99,
    yuma4_alpha_low: float = 0.9,
) -> list[tuple[str, "YumaParams"]]:
    """The canonical 9-version sweep list with per-version params, as the
    reference's entry-point scripts build it
    (reference scripts/charts_table_generator.py:26-48). Note Yuma 4 runs
    with *base* params there; the bond_alpha=0.025 / [0.9, 0.99] tuning is
    applied only to the liquid-alpha variant
    (charts_table_generator.py:46-47)."""
    from dataclasses import replace

    from yuma_simulation_tpu.models.config import YumaParams

    base = YumaParams()
    liquid = YumaParams(liquid_alpha=True)
    y4_liquid = replace(
        YumaParams(
            bond_alpha=yuma4_bond_alpha,
            alpha_high=yuma4_alpha_high,
            alpha_low=yuma4_alpha_low,
        ),
        liquid_alpha=True,
    )
    return [
        (_NAMES.YUMA_RUST, base),
        (_NAMES.YUMA, base),
        (_NAMES.YUMA_LIQUID, liquid),
        (_NAMES.YUMA2, base),
        (_NAMES.YUMA3, base),
        (_NAMES.YUMA31, base),
        (_NAMES.YUMA32, base),
        (_NAMES.YUMA4, base),
        (_NAMES.YUMA4_LIQUID, y4_liquid),
    ]


def variant_for_version(yuma_version: str) -> VariantSpec:
    """Resolve a display-string version name to its static spec."""
    try:
        return YUMA_VERSIONS[yuma_version]
    except KeyError:
        raise ValueError("Invalid Yuma function.") from None


# --- Reference-signature wrappers (drop-in for yumas.py kernels) ---


def YumaRust(W, S, B_old=None, config: Optional[YumaConfig] = None) -> dict:
    """Yuma 0 (subtensor) epoch — reference yumas.py:61-172."""
    return yuma_epoch(
        jnp.asarray(W), S, B_old, config, bonds_mode=BondsMode.EMA_RUST
    )


def Yuma(W, S, B_old=None, config: Optional[YumaConfig] = None) -> dict:
    """Yuma 1 (paper) epoch — reference yumas.py:175-282."""
    return yuma_epoch(jnp.asarray(W), S, B_old, config, bonds_mode=BondsMode.EMA)


def Yuma2(W, W_prev, S, B_old=None, config: Optional[YumaConfig] = None) -> dict:
    """Yuma 2 (Adrian-Fish) epoch — reference yumas.py:285-396."""
    return yuma_epoch(
        jnp.asarray(W),
        S,
        B_old,
        config,
        bonds_mode=BondsMode.EMA_PREV,
        W_prev=None if W_prev is None else jnp.asarray(W_prev),
    )


def Yuma3(W, S, B_old=None, config: Optional[YumaConfig] = None) -> dict:
    """Yuma 3 (Rhef) epoch — reference yumas.py:399-491."""
    return yuma_epoch(
        jnp.asarray(W), S, B_old, config, bonds_mode=BondsMode.CAPACITY
    )


def Yuma4(W, S, B_old=None, config: Optional[YumaConfig] = None) -> dict:
    """Yuma 4 (relative bonds) epoch — reference yumas.py:494-606."""
    return yuma_epoch(
        jnp.asarray(W), S, B_old, config, bonds_mode=BondsMode.RELATIVE
    )
