"""The unified Yuma epoch kernel: one jittable function, static variant switches.

The reference implements five near-identical kernel functions (`YumaRust`,
`Yuma`, `Yuma2`, `Yuma3`, `Yuma4`, reference yumas.py:61-606) that share
~70% of their body. Here the shared pipeline —

    row-normalize W -> normalize S -> prerank -> bisection consensus ->
    u16 quantization -> clip -> rank / incentive / trust

— is written once, and the five bonds models hang off a static
:class:`BondsMode` switch, so each variant compiles to its own fully fused
XLA program with zero runtime branching. The kernel is written for a single
scenario (`W[V, M]`, `S[V]`); batching over scenarios and hyperparameters is
done *outside* with `jax.vmap`, and pod scale-out with `shard_map`
(see :mod:`yuma_simulation_tpu.simulation` / :mod:`yuma_simulation_tpu.parallel`).

Parity-critical details reproduced from the reference (SURVEY.md §2.2):
epsilon placement, u16 truncation, the float64 quantization divide in the
Yuma-0 variant, strict bisection comparisons, `nan_to_num` placement, the
first-epoch EMA special case, and Yuma 3's `2^64 - 1` capacity constant
entering float32 arithmetic.
"""

from __future__ import annotations

import enum
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.ops.consensus import (
    quantize_u16,
    stake_weighted_median,
    stake_weighted_median_sorted,
)
from yuma_simulation_tpu.ops.liquid import liquid_alpha_rate
from yuma_simulation_tpu.ops.normalize import (
    miner_sum,
    normalize_stake,
    normalize_weight_rows,
)

MAXINT = float(2**64 - 1)


class BondsMode(enum.Enum):
    """The five bonds models behind the nine named versions."""

    EMA_RUST = "ema_rust"  # Yuma 0: col-norm bonds w/ eps, EMA re-normalized
    EMA = "ema"  # Yuma 1: blended-weight bonds, plain EMA
    EMA_PREV = "ema_prev"  # Yuma 2: clip & bond against previous weights
    CAPACITY = "capacity"  # Yuma 3.x: stake-capacity bond purchases
    RELATIVE = "relative"  # Yuma 4: per-(validator, miner) bonds in [0, 1]


_EMA_MODES = (BondsMode.EMA_RUST, BondsMode.EMA, BondsMode.EMA_PREV)


def _rate_vm(rate, like: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a scalar or per-miner `[M]` EMA rate against `[V, M]`."""
    rate = jnp.asarray(rate, like.dtype)
    return rate  # 0-dim and [M] both broadcast correctly against [V, M]


# --- per-epoch bonds updates, shared by the kernel and the hoisted scan ---
#
# Each takes the carried bond state plus epoch-invariant precomputations
# (invariant for *constant weights*, that is — yuma_epoch recomputes them
# every call) and returns the next bond state. Splitting these out lets
# `simulate_constant(hoist_invariant=True)` run the consensus front half
# once and scan only this recurrence.


def ema_bonds_target(S_n, W_n, clip_base, W_clipped, config, bonds_mode):
    """The per-epoch purchase target of the EMA families: column-normalized
    stake-weighted (blended) bonds (reference yumas.py:113-116, 227-229,
    341-343). Returns `(B_target, weight_for_bond_or_None)`."""
    if bonds_mode is BondsMode.EMA_RUST:
        B = S_n[:, None] * W_clipped
        B = B / (B.sum(axis=0) + 1e-6)
        return jnp.nan_to_num(B), None
    beta = jnp.asarray(config.bond_penalty, W_n.dtype)
    bond_base = W_n if bonds_mode is BondsMode.EMA else clip_base
    W_b = (1.0 - beta) * bond_base + beta * W_clipped
    B = S_n[:, None] * W_b
    B = B / B.sum(axis=0)  # no epsilon here (yumas.py:228,342)
    return jnp.nan_to_num(B), W_b


def ema_bonds_update(B_target, B_old, rate, first_epoch, renormalize: bool):
    """EMA toward the target; first epoch adopts the target outright
    (yumas.py:145); Yuma 0 re-normalizes the EMA (yumas.py:147-149)."""
    if B_old is None:
        B_ema = B_target
    else:
        ema = rate * B_target + (1.0 - rate) * B_old
        B_ema = (
            ema if first_epoch is None else jnp.where(first_epoch, B_target, ema)
        )
    if renormalize:
        B_ema = jnp.nan_to_num(B_ema / (B_ema.sum(axis=0) + 1e-6))
    return B_ema


def capacity_bonds_update(B_prev, W_n, S_n, config):
    """Yuma 3.x stake-capacity bond purchase (reference yumas.py:455-472)."""
    dtype = W_n.dtype
    capacity = S_n * jnp.asarray(MAXINT, dtype)
    capacity_per_bond = S_n[:, None] * jnp.asarray(MAXINT, dtype)
    remaining = jnp.clip(capacity_per_bond - B_prev, min=0.0)
    cap_alpha = (jnp.asarray(config.capacity_alpha, dtype) * capacity)[:, None]
    purchase = jnp.minimum(cap_alpha, remaining) * W_n
    B = (1.0 - jnp.asarray(config.decay_rate, dtype)) * B_prev + purchase
    return jnp.minimum(B, capacity_per_bond)


def relative_bonds_update(B_prev, W_n, rate):
    """Yuma 4 relative bonds in [0, 1] (reference yumas.py:574-586)."""
    B_decayed = B_prev * (1.0 - rate)
    remaining = jnp.clip(1.0 - B_decayed, min=0.0)
    purchase = jnp.minimum(rate * W_n, remaining)
    return jnp.clip(B_decayed + purchase, max=1.0)


def yuma_epoch(
    W: jnp.ndarray,
    S: jnp.ndarray,
    B_old: Optional[jnp.ndarray] = None,
    config: Optional[YumaConfig] = None,
    *,
    bonds_mode: BondsMode = BondsMode.EMA,
    W_prev: Optional[jnp.ndarray] = None,
    first_epoch=None,
    miner_mask: Optional[jnp.ndarray] = None,
    consensus_impl: str = "bisect",
    precision_config: Optional[lax.Precision] = lax.Precision.HIGHEST,
) -> dict:
    """One consensus epoch. Returns the reference's named-output dict.

    Args:
      W: raw validator->miner weights `[V, M]`.
      S: raw stake `[V]`.
      B_old: carried bond state `[V, M]`, or None on the first epoch.
      config: hyperparameters (a traced pytree; `liquid_alpha` and the
        quantile overrides are static).
      bonds_mode: static variant switch.
      W_prev: previous epoch's *normalized* weights (EMA_PREV only). None
        means "use this epoch's weights" (the reference's first-epoch
        fallback, yumas.py:299-300).
      first_epoch: for in-scan use where `B_old` is always an array —
        a traced bool selecting the fresh-bond branch of the EMA modes.
        None (default) derives it statically from `B_old is None`.
      miner_mask: optional `[M]` 0/1 mask for padded miner columns in
        heterogeneous `vmap` batches.
      consensus_impl: "bisect" (default; iteration-exact with the
        reference), "sorted" (closed-form sort-based fast path), or
        "pallas" (fused VMEM-resident bisection kernel, TPU; falls back
        to the interpreter off-TPU). All three produce identical values.
      precision_config: matmul precision for the prerank/rank einsums
        (`P`, `R`). The consensus support test no longer uses it — it
        runs on the canonical fixed-point integers
        (ops/consensus.py::support_fixed_stakes), which have no float
        contraction to configure.
    """
    config = config if config is not None else YumaConfig()
    dtype = W.dtype

    W_n = normalize_weight_rows(W)
    S_n = normalize_stake(jnp.asarray(S, dtype))

    # Prerank (stake-weighted column sums of un-clipped weights).
    P = jnp.einsum("v,vm->m", S_n, W_n, precision=precision_config)

    # Consensus + u16 quantization. Yuma 0 performs the normalizing divide
    # in float64 (reference yumas.py:81,97); honored when x64 is enabled,
    # otherwise it degrades to float32 (bench/fast mode).
    if consensus_impl == "sorted":
        C_raw = stake_weighted_median_sorted(
            W_n, S_n, config.kappa, config.consensus_precision
        )
    elif consensus_impl == "pallas":
        from yuma_simulation_tpu.ops.pallas_consensus import (
            stake_weighted_median_pallas,
        )

        C_raw = stake_weighted_median_pallas(
            W_n,
            S_n,
            config.kappa,
            config.consensus_precision,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        C_raw = stake_weighted_median(
            W_n,
            S_n,
            config.kappa,
            config.consensus_precision,
            precision_config=precision_config,
        )
    rust64 = bonds_mode is BondsMode.EMA_RUST and jax.config.jax_enable_x64
    C = quantize_u16(
        C_raw,
        sum_dtype=jnp.float64 if rust64 else None,
        out_dtype=dtype,
        miner_mask=miner_mask,
        # The f32 normalizing sum runs exactly on the dyadic grid ints
        # (order-independent — identical on any miner mesh).
        grid_bits=int(math.ceil(math.log2(config.consensus_precision))),
    )

    # Clip, rank, incentive, trust.
    clip_base = W_n if bonds_mode is not BondsMode.EMA_PREV else (
        W_n if W_prev is None else W_prev
    )
    W_clipped = jnp.minimum(clip_base, C)
    R = jnp.einsum("v,vm->m", S_n, W_clipped, precision=precision_config)
    # Miner-axis reductions use the partition-invariant miner_sum
    # spelling (ops/normalize.py): bitwise identical on any miner mesh.
    incentive = jnp.nan_to_num(R / miner_sum(R))
    T = jnp.nan_to_num(R / P)
    T_v = miner_sum(W_clipped) / miner_sum(W_n)

    out = {
        "weight": W_n,
        "stake": S_n,
        "server_prerank": P,
        "server_consensus_weight": C,
        "consensus_clipped_weight": W_clipped,
        "server_rank": R,
        "server_incentive": incentive,
    }

    # Liquid-alpha EMA rate (EMA families and RELATIVE; yumas.py:118-140 etc.).
    nan = jnp.asarray(jnp.nan, dtype)
    a = b = nan
    bond_alpha = jnp.asarray(config.bond_alpha, dtype)
    if config.liquid_alpha and bonds_mode is not BondsMode.CAPACITY:
        bond_alpha, a, b = liquid_alpha_rate(
            C,
            config.alpha_low,
            config.alpha_high,
            override_consensus_high=config.override_consensus_high,
            override_consensus_low=config.override_consensus_low,
            miner_mask=miner_mask,
        )

    if bonds_mode in _EMA_MODES:
        B, W_b = ema_bonds_target(
            S_n, W_n, clip_base, W_clipped, config, bonds_mode
        )
        if W_b is not None:
            out["weight_for_bond"] = W_b
        B_ema = ema_bonds_update(
            B,
            B_old,
            _rate_vm(bond_alpha, B),
            first_epoch,
            renormalize=bonds_mode is BondsMode.EMA_RUST,
        )
        D = miner_sum(B_ema * incentive)
        out.update(
            server_trust=T,
            validator_trust=T_v,
            validator_bond=B,
            validator_ema_bond=B_ema,
            bond_alpha=bond_alpha,
            alpha_a=a,
            alpha_b=b,
        )

    elif bonds_mode is BondsMode.CAPACITY:
        B_prev = jnp.zeros_like(W_n) if B_old is None else B_old
        B = capacity_bonds_update(B_prev, W_n, S_n, config)
        D = miner_sum(B * incentive)
        out.update(server_trust=T, validator_trust=T_v, validator_bonds=B)

    elif bonds_mode is BondsMode.RELATIVE:
        B_prev = jnp.zeros_like(W_n) if B_old is None else B_old
        B = relative_bonds_update(B_prev, W_n, _rate_vm(bond_alpha, W_n))
        D = S_n * miner_sum(B * incentive)
        out["validator_bonds"] = B

    else:  # pragma: no cover
        raise ValueError(f"unknown bonds mode: {bonds_mode}")

    out["validator_reward"] = D
    out["validator_reward_normalized"] = D / (D.sum() + 1e-6)
    return out
