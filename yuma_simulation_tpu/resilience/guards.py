"""Numerical quarantine: lane-local non-finite containment for batches.

A NaN born in one scenario/config lane of a `vmap` batch contaminates
nothing else *numerically* (lanes are independent), but it contaminates
everything else *operationally*: `total_dividends_batch`-style reducers
sum over the batch, streamed accumulators carry it forward, and the
operator learns only that "the sweep produced NaN" with no idea which of
ten thousand lanes — or which epoch — went bad.

The quarantine is an opt-in health check folded into the scan carry
(`guard_nonfinite` on the XLA scan engine): each epoch, the step's
outputs are `jnp.isfinite`-checked; the first failure latches a
per-lane `(first_bad_epoch, tensor_code)` provenance record into the
carry, and from that epoch on the lane's carry and per-epoch outputs
are masked to zero — the lane is *quarantined*, the rest of the batch
is bit-for-bit what a clean run produces (for healthy lanes every guard
op is `where(False, 0, x)`, i.e. the identity on the same values).
Batched drivers return the partial results plus the per-lane state;
:func:`build_quarantine_report` turns that state into a host-side
report of `(case, epoch, tensor)` entries.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from yuma_simulation_tpu.resilience.errors import NonFiniteOutputError

#: Tensor names in `tensor_code` order — the priority order the per-epoch
#: check walks (a NaN usually poisons several tensors at once; the code
#: records the first in this order so reports are deterministic).
QUARANTINE_TENSORS = ("dividends", "bonds", "consensus", "w_prev", "incentives")


def quarantine_init() -> dict:
    """The per-lane quarantine carry at epoch 0: healthy, no provenance."""
    return {
        "bad": jnp.zeros((), bool),
        "first_bad_epoch": jnp.full((), -1, jnp.int32),
        "tensor_code": jnp.full((), -1, jnp.int32),
    }


def quarantine_step(qstate: dict, epoch, tensors: Sequence[tuple]):
    """Fold one epoch's health check into the quarantine carry.

    `tensors` is a sequence of `(code, array)` with `code` indexing
    :data:`QUARANTINE_TENSORS`. Returns `(new_qstate, mask)` where
    `mask(x)` zeroes `x` iff the lane is (now) quarantined — the
    identity, bitwise, for healthy lanes.
    """
    finite = [jnp.all(jnp.isfinite(t)) for _, t in tensors]
    bad_now = ~jnp.all(jnp.stack(finite))
    code = jnp.full((), -1, jnp.int32)
    for (c, _), ok in reversed(list(zip(tensors, finite))):
        code = jnp.where(ok, code, jnp.int32(c))
    newly = bad_now & ~qstate["bad"]
    new_qstate = {
        "bad": qstate["bad"] | bad_now,
        "first_bad_epoch": jnp.where(
            newly, jnp.asarray(epoch, jnp.int32), qstate["first_bad_epoch"]
        ),
        "tensor_code": jnp.where(newly, code, qstate["tensor_code"]),
    }

    def mask(x):
        return jnp.where(new_qstate["bad"], jnp.zeros_like(x), x)

    return new_qstate, mask


@dataclasses.dataclass(frozen=True)
class QuarantineEntry:
    """One quarantined lane: which case, from which epoch, and the first
    tensor observed non-finite."""

    case: int
    epoch: int
    tensor: str


@dataclasses.dataclass(frozen=True)
class QuarantineReport:
    """Host-side view of a batch's quarantine state.

    `entries` lists quarantined lanes only; `num_cases` is the full
    batch width so `healthy_mask()` can be used to select the valid
    rows of the partial results."""

    entries: tuple
    num_cases: int

    @property
    def quarantined_cases(self) -> tuple:
        return tuple(e.case for e in self.entries)

    def healthy_mask(self) -> np.ndarray:
        mask = np.ones(self.num_cases, bool)
        for e in self.entries:
            mask[e.case] = False
        return mask

    def __bool__(self) -> bool:  # truthy iff anything was quarantined
        return bool(self.entries)


def build_quarantine_report(qstate) -> QuarantineReport:
    """Convert the device-side per-lane quarantine state (the
    `"quarantine"` entry of a guarded batch's outputs — scalar per lane,
    `[B]` after vmap) into a :class:`QuarantineReport`."""
    bad = np.atleast_1d(np.asarray(qstate["bad"]))
    first = np.atleast_1d(np.asarray(qstate["first_bad_epoch"]))
    codes = np.atleast_1d(np.asarray(qstate["tensor_code"]))
    entries = tuple(
        QuarantineEntry(
            case=int(i),
            epoch=int(first[i]),
            tensor=(
                QUARANTINE_TENSORS[int(codes[i])]
                if 0 <= int(codes[i]) < len(QUARANTINE_TENSORS)
                else "unknown"
            ),
        )
        for i in np.flatnonzero(bad)
    )
    return QuarantineReport(entries=entries, num_cases=int(bad.shape[0]))


def assert_all_finite(tree, context: str = "") -> None:
    """Host-side strict check: raise :class:`NonFiniteOutputError` naming
    the first non-finite leaf. For callers who want abort-on-NaN rather
    than quarantine (single-scenario runs, golden pipelines)."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
            where = jax.tree_util.keystr(path)
            raise NonFiniteOutputError(
                f"non-finite values in {where}"
                + (f" ({context})" if context else "")
            )
