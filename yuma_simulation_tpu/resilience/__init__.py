"""Resilience layer: typed failures, the engine-degradation ladder,
numerical quarantine, and deterministic fault injection.

Production-scale sweeps are exactly the workload where one bad case in a
ten-thousand-lane batch, one VMEM-starved fused dispatch, or one torn
checkpoint chunk must not take down everything else. This package holds
the pieces the simulation and sweep layers wire together:

- :mod:`.errors` — the typed failure taxonomy + :func:`classify_failure`;
- :mod:`.retry` — :class:`RetryPolicy` and the explicit engine ladder
  (fused_scan_mxu -> fused_scan -> xla) with jittered bounded retry;
- :mod:`.guards` — the opt-in `jnp.isfinite` quarantine folded into the
  scan carry, plus the host-side :class:`QuarantineReport`;
- :mod:`.watchdog` — the deadline watchdog: supervised dispatch on a
  worker thread, typed `EngineStall` on a missed heartbeat (hangs don't
  raise; this tier makes them);
- :mod:`.supervisor` — the sweep supervisor composing every tier over
  idempotent work units, with the crash-safe :class:`FailureLedger` and
  the :class:`SweepHealthReport`;
- :mod:`.faults` — test-only deterministic fault hooks so every ladder
  rung and recovery path runs in CPU CI.

See README.md "Failure semantics & recovery" for the operator-facing
contract.
"""

from yuma_simulation_tpu.resilience.errors import (  # noqa: F401
    AdmissionRejected,
    CheckpointCorruptionError,
    ClientRetriesExhausted,
    DeviceLossError,
    DistributedInitError,
    EngineCompileError,
    EngineFailure,
    EngineLadderExhausted,
    EngineResourceExhausted,
    EngineStall,
    HostLossError,
    LeaseExpired,
    NonFiniteOutputError,
    QueueOverflow,
    ResilienceError,
    SloShed,
    WorkerLost,
    classify_failure,
)
from yuma_simulation_tpu.resilience.faults import (  # noqa: F401
    DeviceLossFault,
    DriftFault,
    FaultPlan,
    HostCrashFault,
    LeaseTearFault,
    NaNFault,
    OverloadFault,
    StallFault,
    canary_scope,
    inject_faults,
)
from yuma_simulation_tpu.resilience.guards import (  # noqa: F401
    QuarantineEntry,
    QuarantineReport,
    assert_all_finite,
    build_quarantine_report,
)
from yuma_simulation_tpu.resilience.retry import (  # noqa: F401
    ENGINE_LADDER,
    DemotionRecord,
    RetryPolicy,
    default_retry_policy,
    ladder_from,
    run_ladder,
)
from yuma_simulation_tpu.resilience.supervisor import (  # noqa: F401
    FailureLedger,
    SweepHealthReport,
    SweepSupervisor,
    default_deadline,
)
from yuma_simulation_tpu.resilience.watchdog import (  # noqa: F401
    Deadline,
    run_with_deadline,
)
