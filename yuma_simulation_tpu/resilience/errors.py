"""Typed failure taxonomy for the resilience layer.

The engines raise (or surface from XLA/Mosaic) a zoo of stringly-typed
errors: `jaxlib.xla_extension.XlaRuntimeError` with a
`RESOURCE_EXHAUSTED` status for VMEM/HBM OOM, Mosaic lowering aborts for
kernel-compile failures, plain `ValueError` for caller mistakes. The
degradation ladder (:mod:`.retry`) must distinguish "this engine cannot
run this workload here" (demote a rung and retry) from "the caller's
request is wrong" (raise immediately) — so every failure the ladder may
act on is classified into one of the typed exceptions below via
:func:`classify_failure` before any policy decision is made.
"""

from __future__ import annotations

from typing import Optional


class ResilienceError(RuntimeError):
    """Base class of every typed failure the resilience layer raises."""


class EngineFailure(ResilienceError):
    """An engine could not produce a result for an otherwise-valid
    request (compile failure, resource exhaustion). Retryable: the
    ladder may demote to a lower rung."""


class EngineCompileError(EngineFailure):
    """The engine's program failed to compile (Mosaic lowering abort,
    XLA compile failure)."""


class EngineResourceExhausted(EngineFailure):
    """The engine ran out of device resources (VMEM scratch, HBM,
    RESOURCE_EXHAUSTED at dispatch)."""


class EngineLadderExhausted(EngineFailure):
    """Every rung of the degradation ladder failed. Carries the
    per-demotion records so the caller can see the full walk."""

    def __init__(self, message: str, records=()):
        super().__init__(message)
        self.records = tuple(records)


class NonFiniteOutputError(ResilienceError):
    """An engine output contained NaN/Inf and no quarantine was armed to
    contain it (see :mod:`.guards`)."""


class CheckpointCorruptionError(ResilienceError):
    """A checkpoint chunk failed its checksum (or could not be decoded)
    and re-execution did not heal it."""


#: Substrings that identify a resource-exhaustion failure in the raw
#: message of an XLA/Mosaic error. Checked case-insensitively.
_RESOURCE_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "ran out of memory",
    "vmem limit",
    "exceeds available vmem",
    "scoped vmem",
    "allocation failure",
)

#: Substrings that identify a kernel/program compile failure.
_COMPILE_MARKERS = (
    "mosaic failed",
    "mosaic lowering",
    "internal: mosaic",
    "failed to compile",
    "compilation failure",
    "unsupported lowering",
    "xla compilation",
)


def classify_failure(exc: BaseException) -> Optional[EngineFailure]:
    """Map a raw exception onto the engine-failure taxonomy.

    Returns an :class:`EngineFailure` (the exception itself if already
    typed, else a new typed wrapper chaining `exc`) when the failure is
    one the degradation ladder may act on, or ``None`` for everything
    else — caller errors (`ValueError`/`TypeError`), keyboard
    interrupts, and unrecognized runtime errors must propagate untouched
    rather than silently trigger an engine demotion.
    """
    if isinstance(exc, EngineFailure):
        return exc
    if isinstance(exc, (ValueError, TypeError, KeyboardInterrupt)):
        return None
    msg = str(exc).lower()
    if any(marker in msg for marker in _RESOURCE_MARKERS):
        err = EngineResourceExhausted(str(exc))
        err.__cause__ = exc
        return err
    if any(marker in msg for marker in _COMPILE_MARKERS):
        err = EngineCompileError(str(exc))
        err.__cause__ = exc
        return err
    return None
