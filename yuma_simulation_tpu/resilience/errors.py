"""Typed failure taxonomy for the resilience layer.

The engines raise (or surface from XLA/Mosaic) a zoo of stringly-typed
errors: `jaxlib.xla_extension.XlaRuntimeError` with a
`RESOURCE_EXHAUSTED` status for VMEM/HBM OOM, Mosaic lowering aborts for
kernel-compile failures, plain `ValueError` for caller mistakes. The
degradation ladder (:mod:`.retry`) must distinguish "this engine cannot
run this workload here" (demote a rung and retry) from "the caller's
request is wrong" (raise immediately) — so every failure the ladder may
act on is classified into one of the typed exceptions below via
:func:`classify_failure` before any policy decision is made.
"""

from __future__ import annotations

from typing import Optional


class ResilienceError(RuntimeError):
    """Base class of every typed failure the resilience layer raises."""


class EngineFailure(ResilienceError):
    """An engine could not produce a result for an otherwise-valid
    request (compile failure, resource exhaustion). Retryable: the
    ladder may demote to a lower rung."""


class EngineCompileError(EngineFailure):
    """The engine's program failed to compile (Mosaic lowering abort,
    XLA compile failure)."""


class EngineResourceExhausted(EngineFailure):
    """The engine ran out of device resources (VMEM scratch, HBM,
    RESOURCE_EXHAUSTED at dispatch)."""


class EngineStall(EngineFailure):
    """A compile or dispatch exceeded its deadline (hung XLA/Mosaic
    compile, wedged collective, dead coordinator). Raised by the
    watchdog (:mod:`.watchdog`) when a budget expires, and by
    classification of XLA ``DEADLINE_EXCEEDED`` / collective-timeout
    runtime errors. Retryable: a stall on one rung demotes like any
    other engine failure — the lower rungs compile different (smaller)
    programs and do not share the wedged channel."""

    def __init__(self, message: str, budget_seconds: Optional[float] = None):
        super().__init__(message)
        self.budget_seconds = budget_seconds


class DeviceLossError(EngineFailure):
    """A device dropped out of the mesh mid-sweep (ICI link down, chip
    reset, preempted host). Carries the failed device ids so the elastic
    path (:mod:`..parallel.sharded`) can rebuild the mesh over the
    survivors."""

    def __init__(self, message: str, device_ids=()):
        super().__init__(message)
        self.device_ids = tuple(device_ids)


class HostLossError(EngineStall):
    """A WHOLE HOST dropped out of the fleet mid-sweep (SIGKILLed
    worker, preempted VM, coordinator connection lost, heartbeat
    stopped). The fleet analogue of :class:`DeviceLossError` one level
    up (:mod:`..fabric`): the lost host's leased work units are requeued
    by the survivors via lease expiry, exactly as `surviving_mesh`
    rebuilds a mesh over surviving devices.

    Subclasses :class:`EngineStall` deliberately: a host loss first
    SURFACES on the healthy peers as a stall (missed heartbeat, wedged
    collective, dead coordinator channel), so every existing
    stall-handling path — watchdog kill, ladder retry, supervisor
    bookkeeping — handles it unchanged, while fleet-aware callers can
    match the narrower type and steal the dead host's leases instead of
    merely retrying. Retryable by construction: the unit is pure and
    any surviving host can re-execute it."""

    def __init__(self, message: str, host_ids=(), budget_seconds=None):
        super().__init__(message, budget_seconds=budget_seconds)
        self.host_ids = tuple(host_ids)


class WorkerLost(HostLossError):
    """A serve-pool WORKER process died (or became unreachable) with a
    routed request in flight: the router's forward hit a reset/refused
    connection, or the worker's slot lease expired mid-request. The
    scale-out analogue of :class:`HostLossError` on the serving tier —
    and it subclasses it deliberately, so every fleet-aware handler
    (typed, retryable, stall-shaped) treats it identically, while the
    router matches the narrower type to reroute the SAME request onto a
    surviving worker instead of surfacing a client-visible error.
    Retryable by construction: serve requests are pure and idempotent,
    so a reroute re-executes at worst duplicate work, never duplicate
    effects. Carries the dead worker's id and how many reroute attempts
    the router has burned so far."""

    def __init__(self, message: str, *, worker_id: str = "", attempts: int = 0):
        super().__init__(message, host_ids=(worker_id,) if worker_id else ())
        self.worker_id = worker_id
        self.attempts = attempts


class ClientRetriesExhausted(ResilienceError):
    """The client's bounded retry budget is spent and the LAST attempt
    still failed at the transport level (connection reset/refused,
    unreachable server). NOT an :class:`EngineFailure`: nothing
    server-side can act on it — the caller must surface it. Carries the
    attempt count and the last transport error so the caller's log says
    how hard the client tried. HTTP-level 429/503 responses do NOT
    raise this: after the budget is spent they are RETURNED (the
    server's typed body is the contract and must reach the caller)."""

    def __init__(self, message: str, *, attempts: int = 0, last_error=None):
        super().__init__(message)
        self.attempts = attempts
        self.last_error = last_error


class LeaseExpired(ResilienceError):
    """A fleet work-unit lease was lost: the holder's renewal found the
    claim file replaced (stolen after expiry), torn, or gone. NOT an
    :class:`EngineFailure`: the unit now belongs to another host —
    retrying the dispatch here would race the new owner for nothing
    (results are content-addressed and deterministic, so even the race
    is harmless, but the polite move is to abandon and claim other
    work). Carries the unit id and, when known, the usurping holder."""

    def __init__(self, message: str, unit=None, holder=None):
        super().__init__(message)
        self.unit = unit
        self.holder = holder


class DistributedInitError(ResilienceError):
    """A multi-host distributed join failed within its initialization
    timeout (peer crashed before the barrier, wrong coordinator
    address). NOT an :class:`EngineFailure`: there is no lower rung to
    demote to before the backend exists — the caller must decide whether
    to re-launch or abort the job."""


class AdmissionRejected(ResilienceError):
    """A serving-tier request failed admission control BEFORE any
    compile or dispatch: malformed payload, unknown version/engine, or
    an analytic HBM preflight verdict that the shape deterministically
    cannot fit. NOT retryable and NOT an :class:`EngineFailure` — the
    same request is rejected again no matter which rung runs it, so the
    ladder must never burn retries on it (the HTTP layer maps it to a
    structured 4xx). Carries the preflight's way out when there is one
    (``suggestion``: shard count / max_resident_epochs) so the client
    can reshape instead of guessing."""

    def __init__(
        self,
        message: str,
        *,
        reason: str = "invalid_request",
        suggestion: Optional[str] = None,
    ):
        super().__init__(message)
        self.reason = reason
        self.suggestion = suggestion


class QueueOverflow(ResilienceError):
    """The serving tier shed this request: the global run queue is at
    its bound or the tenant's token bucket is empty. Retryable BY THE
    CLIENT — and only by the client: re-dispatching server-side would
    be exactly the unbounded growth the bound exists to prevent, so
    this is NOT an :class:`EngineFailure` and the engine ladder never
    acts on it. ``retry_after`` (seconds) is the backoff the HTTP layer
    surfaces as ``429`` + ``Retry-After``."""

    #: Client-retryable: resubmitting after ``retry_after`` is expected
    #: to succeed once the queue drains / the bucket refills.
    retryable = True

    def __init__(
        self,
        message: str,
        *,
        retry_after: float = 1.0,
        queue_depth: Optional[int] = None,
    ):
        super().__init__(message)
        self.retry_after = float(retry_after)
        self.queue_depth = queue_depth


class SloShed(QueueOverflow):
    """The serving tier shed this request because an SLO is
    FAST-BURNING its error budget (:mod:`..telemetry.slo`) and the
    request's priority sits below the degradation floor — load is
    dropped while there is still budget left, BEFORE the queue
    overflows. Inherits :class:`QueueOverflow`'s whole contract
    (client-retryable, ``retry_after``, 429 + ``Retry-After``, immune
    to engine-failure marker matching); carries the burning SLO names
    so the structured body says WHICH objective forced the shed."""

    def __init__(
        self,
        message: str,
        *,
        retry_after: float = 1.0,
        slos=(),
    ):
        super().__init__(message, retry_after=retry_after)
        self.slos = tuple(slos)


class EngineLadderExhausted(EngineFailure):
    """Every rung of the degradation ladder failed. Carries the
    per-demotion records so the caller can see the full walk."""

    def __init__(self, message: str, records=()):
        super().__init__(message)
        self.records = tuple(records)


class NonFiniteOutputError(ResilienceError):
    """An engine output contained NaN/Inf and no quarantine was armed to
    contain it (see :mod:`.guards`)."""


class CheckpointCorruptionError(ResilienceError):
    """A checkpoint chunk failed its checksum (or could not be decoded)
    and re-execution did not heal it."""


#: Substrings that identify a resource-exhaustion failure in the raw
#: message of an XLA/Mosaic error. Checked case-insensitively.
_RESOURCE_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "ran out of memory",
    "vmem limit",
    "exceeds available vmem",
    "scoped vmem",
    "allocation failure",
)

#: Substrings that identify a hang/timeout failure in the raw message of
#: an XLA runtime error: the status name XLA stamps on an expired
#: operation deadline, plus the collective/channel timeout phrasings the
#: TPU runtime emits when a peer stops participating (a wedged all-gather
#: surfaces on the HEALTHY hosts as one of these, not as a device error).
_STALL_MARKERS = (
    "deadline_exceeded",
    "deadline exceeded",
    "collective operation timed out",
    "collective timed out",
    "channel timed out",
    "channel is in an error state",
    "timed out waiting for",
    "barrier timed out",
    "heartbeat timeout",
)

#: Substrings that identify the loss of a WHOLE HOST rather than a
#: single wedged operation: coordinator-channel loss, stopped
#: heartbeats, and the TCP-level phrasings a dead peer's kernel sends
#: back ("connection reset by peer" et al.). Checked BEFORE the stall
#: markers — a host loss is still stall-shaped (HostLossError subclasses
#: EngineStall, so non-fleet callers behave identically), but the
#: narrower type lets the fleet fabric steal the dead host's leases
#: instead of merely retrying into the void. Deliberately NOT here:
#: bare local-I/O phrasings ("broken pipe", "socket closed") — they
#: appear in ordinary OSErrors (a closed stdout, a dropped log pipe)
#: far more often than in peer-death reports, and classifying those as
#: retryable would silently re-execute units whose real failure is the
#: caller's environment. Raw OSErrors are additionally exempted in
#: :func:`classify_failure` for the same reason: runtime peer-death
#: surfaces as XLA RuntimeErrors, local plumbing as OSError.
_HOST_LOSS_MARKERS = (
    "heartbeat timeout",
    "heartbeat timed out",
    "missed heartbeats",
    "coordinator unreachable",
    "coordinator unavailable",
    "coordination service unavailable",
    "lost connection to coordinator",
    "coordinator disconnected",
    "connection reset by peer",
    "connection refused",
    "peer closed connection",
    "host unreachable",
    "worker task died",
)

#: Substrings that identify a kernel/program compile failure.
_COMPILE_MARKERS = (
    "mosaic failed",
    "mosaic lowering",
    "internal: mosaic",
    "failed to compile",
    "compilation failure",
    "unsupported lowering",
    "xla compilation",
)


def classify_failure(exc: BaseException) -> Optional[EngineFailure]:
    """Map a raw exception onto the engine-failure taxonomy.

    Returns an :class:`EngineFailure` (the exception itself if already
    typed, else a new typed wrapper chaining `exc`) when the failure is
    one the degradation ladder may act on, or ``None`` for everything
    else — caller errors (`ValueError`/`TypeError`), keyboard
    interrupts, and unrecognized runtime errors must propagate untouched
    rather than silently trigger an engine demotion.
    """
    if isinstance(exc, EngineFailure):
        return exc
    if isinstance(exc, ResilienceError):
        # Typed but deliberately NON-retryable (LeaseExpired,
        # DistributedInitError, CheckpointCorruptionError, ...): the
        # type is the decision — its message must never be re-matched
        # against the engine-failure markers.
        return None
    if isinstance(exc, (ValueError, TypeError, KeyboardInterrupt)):
        return None
    msg = str(exc).lower()
    if any(marker in msg for marker in _RESOURCE_MARKERS):
        err = EngineResourceExhausted(str(exc))
        err.__cause__ = exc
        return err
    if not isinstance(exc, OSError) and any(
        marker in msg for marker in _HOST_LOSS_MARKERS
    ):
        # Checked before the generic stall markers: "heartbeat timeout:
        # coordinator unreachable" is a stall AND a host loss, and the
        # narrower type must win so fleet callers can requeue the dead
        # host's leases (non-fleet callers see an EngineStall subclass
        # and behave exactly as before). Raw OSErrors are excluded: a
        # local EPIPE/ECONNRESET from the caller's own plumbing shares
        # these phrasings, and retrying a unit cannot fix the caller's
        # environment — peer death reported by the runtime arrives as a
        # RuntimeError, which still classifies.
        host_err = HostLossError(str(exc))
        host_err.__cause__ = exc
        return host_err
    if any(marker in msg for marker in _STALL_MARKERS):
        # Checked before the compile markers: a hung compile surfaces as
        # "deadline exceeded while compiling", which must classify as a
        # stall (the retry may succeed where the hang was transient),
        # not as a deterministic compile abort.
        err = EngineStall(str(exc))
        err.__cause__ = exc
        return err
    if any(marker in msg for marker in _COMPILE_MARKERS):
        err = EngineCompileError(str(exc))
        err.__cause__ = exc
        return err
    return None
