"""Typed failure taxonomy for the resilience layer.

The engines raise (or surface from XLA/Mosaic) a zoo of stringly-typed
errors: `jaxlib.xla_extension.XlaRuntimeError` with a
`RESOURCE_EXHAUSTED` status for VMEM/HBM OOM, Mosaic lowering aborts for
kernel-compile failures, plain `ValueError` for caller mistakes. The
degradation ladder (:mod:`.retry`) must distinguish "this engine cannot
run this workload here" (demote a rung and retry) from "the caller's
request is wrong" (raise immediately) — so every failure the ladder may
act on is classified into one of the typed exceptions below via
:func:`classify_failure` before any policy decision is made.
"""

from __future__ import annotations

from typing import Optional


class ResilienceError(RuntimeError):
    """Base class of every typed failure the resilience layer raises."""


class EngineFailure(ResilienceError):
    """An engine could not produce a result for an otherwise-valid
    request (compile failure, resource exhaustion). Retryable: the
    ladder may demote to a lower rung."""


class EngineCompileError(EngineFailure):
    """The engine's program failed to compile (Mosaic lowering abort,
    XLA compile failure)."""


class EngineResourceExhausted(EngineFailure):
    """The engine ran out of device resources (VMEM scratch, HBM,
    RESOURCE_EXHAUSTED at dispatch)."""


class EngineStall(EngineFailure):
    """A compile or dispatch exceeded its deadline (hung XLA/Mosaic
    compile, wedged collective, dead coordinator). Raised by the
    watchdog (:mod:`.watchdog`) when a budget expires, and by
    classification of XLA ``DEADLINE_EXCEEDED`` / collective-timeout
    runtime errors. Retryable: a stall on one rung demotes like any
    other engine failure — the lower rungs compile different (smaller)
    programs and do not share the wedged channel."""

    def __init__(self, message: str, budget_seconds: Optional[float] = None):
        super().__init__(message)
        self.budget_seconds = budget_seconds


class DeviceLossError(EngineFailure):
    """A device dropped out of the mesh mid-sweep (ICI link down, chip
    reset, preempted host). Carries the failed device ids so the elastic
    path (:mod:`..parallel.sharded`) can rebuild the mesh over the
    survivors."""

    def __init__(self, message: str, device_ids=()):
        super().__init__(message)
        self.device_ids = tuple(device_ids)


class DistributedInitError(ResilienceError):
    """A multi-host distributed join failed within its initialization
    timeout (peer crashed before the barrier, wrong coordinator
    address). NOT an :class:`EngineFailure`: there is no lower rung to
    demote to before the backend exists — the caller must decide whether
    to re-launch or abort the job."""


class EngineLadderExhausted(EngineFailure):
    """Every rung of the degradation ladder failed. Carries the
    per-demotion records so the caller can see the full walk."""

    def __init__(self, message: str, records=()):
        super().__init__(message)
        self.records = tuple(records)


class NonFiniteOutputError(ResilienceError):
    """An engine output contained NaN/Inf and no quarantine was armed to
    contain it (see :mod:`.guards`)."""


class CheckpointCorruptionError(ResilienceError):
    """A checkpoint chunk failed its checksum (or could not be decoded)
    and re-execution did not heal it."""


#: Substrings that identify a resource-exhaustion failure in the raw
#: message of an XLA/Mosaic error. Checked case-insensitively.
_RESOURCE_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "ran out of memory",
    "vmem limit",
    "exceeds available vmem",
    "scoped vmem",
    "allocation failure",
)

#: Substrings that identify a hang/timeout failure in the raw message of
#: an XLA runtime error: the status name XLA stamps on an expired
#: operation deadline, plus the collective/channel timeout phrasings the
#: TPU runtime emits when a peer stops participating (a wedged all-gather
#: surfaces on the HEALTHY hosts as one of these, not as a device error).
_STALL_MARKERS = (
    "deadline_exceeded",
    "deadline exceeded",
    "collective operation timed out",
    "collective timed out",
    "channel timed out",
    "channel is in an error state",
    "timed out waiting for",
    "barrier timed out",
    "heartbeat timeout",
)

#: Substrings that identify a kernel/program compile failure.
_COMPILE_MARKERS = (
    "mosaic failed",
    "mosaic lowering",
    "internal: mosaic",
    "failed to compile",
    "compilation failure",
    "unsupported lowering",
    "xla compilation",
)


def classify_failure(exc: BaseException) -> Optional[EngineFailure]:
    """Map a raw exception onto the engine-failure taxonomy.

    Returns an :class:`EngineFailure` (the exception itself if already
    typed, else a new typed wrapper chaining `exc`) when the failure is
    one the degradation ladder may act on, or ``None`` for everything
    else — caller errors (`ValueError`/`TypeError`), keyboard
    interrupts, and unrecognized runtime errors must propagate untouched
    rather than silently trigger an engine demotion.
    """
    if isinstance(exc, EngineFailure):
        return exc
    if isinstance(exc, (ValueError, TypeError, KeyboardInterrupt)):
        return None
    msg = str(exc).lower()
    if any(marker in msg for marker in _RESOURCE_MARKERS):
        err = EngineResourceExhausted(str(exc))
        err.__cause__ = exc
        return err
    if any(marker in msg for marker in _STALL_MARKERS):
        # Checked before the compile markers: a hung compile surfaces as
        # "deadline exceeded while compiling", which must classify as a
        # stall (the retry may succeed where the hang was transient),
        # not as a deterministic compile abort.
        err = EngineStall(str(exc))
        err.__cause__ = exc
        return err
    if any(marker in msg for marker in _COMPILE_MARKERS):
        err = EngineCompileError(str(exc))
        err.__cause__ = exc
        return err
    return None
