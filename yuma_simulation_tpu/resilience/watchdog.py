"""Deadline watchdog: bounded wall-clock supervision for jit dispatches.

PR 1's retry ladder recovers from failures that *raise*; this module
covers the ones that *don't*. An XLA/Mosaic compile can hang for hours
on a pathological program, and a dispatch whose collective partner died
blocks forever rather than erroring — on a pod-scale sweep either one
silently wedges the whole job (the Pathways/MegaScale lesson: hang
detection must be first-class, not an operator staring at a flat
utilization graph).

:func:`run_with_deadline` executes a dispatch on a *worker thread* and
watches it from the caller: the worker posts a heartbeat when it
finishes (result or exception); if the heartbeat does not arrive within
the :class:`Deadline` budget, the caller logs one
``event=engine_stalled`` record and raises a typed
:class:`..errors.EngineStall` — which :func:`..errors.classify_failure`
treats as retryable, so a stall inside :func:`..retry.run_ladder`
demotes down the engine ladder exactly like a VMEM exhaustion.

Why a thread and not a signal/alarm: the hang is inside native XLA code
holding no GIL, so no Python-level interruption can unwind it. The
worker is a daemon thread that is *abandoned*, not killed — if the
native call eventually returns, the result is discarded (the
:class:`_Dispatch` records that its deadline already fired and drops
the late value on the floor). Abandonment is safe here because every
dispatch in this framework is functionally pure: the only leaked
resources are the thread stack and the (shared, process-global) jit
cache entry the late compile populates — which the retry then reuses
for free.

Zero cost on the healthy path beyond one thread spawn per supervised
dispatch (~50 us, dwarfed by any real dispatch); jit caches are
process-global, so running a dispatch on a worker thread adds no
compiles (pinned by tests/unit/test_recompilation.py's supervised
budget).
"""

from __future__ import annotations

import contextvars
import dataclasses
import logging
import threading
from typing import Callable, Optional, TypeVar

from yuma_simulation_tpu.resilience.errors import EngineStall
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class Deadline:
    """A wall-clock budget for one supervised dispatch.

    `budget_seconds` is the hard limit: the dispatch (compile included —
    first calls pay the trace+compile inside the budget) must post its
    heartbeat within it. `grace_seconds` is added on retries of the SAME
    work (`attempt > 0` in :meth:`budget_for_attempt`): a retried
    dispatch may legitimately need to recompile after a cache-poisoning
    failure, and killing the retry on the cold-start budget would turn
    one transient stall into a guaranteed ladder walk.
    """

    budget_seconds: float
    grace_seconds: float = 0.0

    def __post_init__(self) -> None:
        if self.budget_seconds <= 0:
            raise ValueError("Deadline budget_seconds must be > 0")
        if self.grace_seconds < 0:
            raise ValueError("Deadline grace_seconds must be >= 0")

    def budget_for_attempt(self, attempt: int) -> float:
        """The budget for retry number `attempt` (0 = first try)."""
        return self.budget_seconds + (self.grace_seconds if attempt else 0.0)


class _Dispatch:
    """One supervised dispatch's shared state between caller and worker.

    The `done` event is the heartbeat; `expired` latches (under `lock`)
    when the caller gives up, so a worker that wakes up late can see its
    result is unwanted and drop it instead of leaking device references
    in a dead thread's frame."""

    def __init__(self) -> None:
        self.done = threading.Event()
        self.lock = threading.Lock()
        self.expired = False
        self.result: object = None
        self.error: Optional[BaseException] = None


def run_with_deadline(
    fn: Callable[[], T],
    deadline: Optional[Deadline],
    *,
    label: str = "",
    attempt: int = 0,
) -> T:
    """Run `fn()` under `deadline`; raise :class:`EngineStall` on expiry.

    `fn` runs on a daemon worker thread while the caller waits on the
    heartbeat. Three outcomes:

    - the worker finishes in time: its return value is returned (or its
      exception re-raised with the original traceback — the retry
      ladder's `classify_failure` sees exactly what a direct call would
      have raised);
    - the budget expires: one ``event=engine_stalled`` record is logged
      and :class:`EngineStall` raised; the worker is abandoned (see the
      module docstring for why that is safe here);
    - `deadline` is None: `fn` runs inline on the caller's thread —
      supervision off, byte-for-byte the unsupervised code path.
    """
    if deadline is None:
        return fn()
    budget = deadline.budget_for_attempt(attempt)
    state = _Dispatch()

    def worker() -> None:
        try:
            # Test-only hang simulation (inert in production — one
            # `is None` check): sleeps HERE, on the worker, so the
            # caller's deadline machinery sees a real missed heartbeat.
            from yuma_simulation_tpu.resilience import faults

            faults.maybe_stall_dispatch()
            result = fn()
            error = None
        except BaseException as exc:  # noqa: BLE001 — reported to caller
            result, error = None, exc
        with state.lock:
            if state.expired:
                # The caller already raised EngineStall for this
                # dispatch; a late result must not be half-published.
                return
            state.result, state.error = result, error
            # set() under the SAME lock as the publish: outside it, the
            # caller could time out between the publish and the set,
            # latch expired, and raise EngineStall for a dispatch whose
            # result was already complete — a burned retry.
            state.done.set()

    # Contextvars do NOT flow into a bare Thread: copy the caller's
    # context so records emitted from the worker (fault hooks, engine
    # log_events) carry the caller's telemetry run/span identity.
    ctx = contextvars.copy_context()
    thread = threading.Thread(
        target=lambda: ctx.run(worker),
        name=f"yuma-watchdog-{label or 'dispatch'}",
        daemon=True,
    )
    thread.start()
    if not state.done.wait(budget):
        with state.lock:
            if not state.done.is_set():
                state.expired = True
        if state.expired:
            from yuma_simulation_tpu.telemetry.metrics import get_registry

            get_registry().counter(
                "stalls_killed", help="watchdog deadline kills"
            ).inc()
            log_event(
                logger,
                "engine_stalled",
                label=label,
                budget_s=f"{budget:.3f}",
                attempt=attempt,
            )
            raise EngineStall(
                f"dispatch {label or '<unnamed>'!s} exceeded its "
                f"{budget:.3f}s deadline (attempt {attempt}); the worker "
                "was abandoned",
                budget_seconds=budget,
            )
        # Lost the race: the worker posted between wait() timing out and
        # the lock — take the result, it arrived within epsilon of the
        # budget.
    if state.error is not None:
        raise state.error
    return state.result  # type: ignore[return-value]
