"""Sweep supervisor: idempotent units under deadline + retry + quarantine.

The top of the resilience stack. The lower tiers each contain ONE
failure class — the ladder contains engine failures, the watchdog
contains hangs, the elastic mesh contains device loss, the quarantine
contains NaN lanes, `CheckpointedSweep` contains torn chunks — but a
pod-scale Monte-Carlo sweep meets all of them in one run, and something
has to compose the tiers, keep the bookkeeping, and tell the operator
what actually happened. That is the :class:`SweepSupervisor`:

- the sweep is partitioned into **idempotent units** (contiguous slices
  of the scenario batch or hyperparameter grid — pure functions of their
  inputs, so re-executing a unit is always safe);
- each unit dispatches under the deadline watchdog, the engine-retry
  ladder, the per-lane quarantine, and (sharded) elastic mesh
  degradation;
- every per-unit outcome is appended to a crash-safe JSONL
  :class:`FailureLedger` (atomic fsync+rename publish via
  :func:`..utils.checkpoint.publish_atomic` — a crash mid-append leaves
  the previous ledger, never a torn line);
- with a `directory`, unit results snapshot through
  :class:`..utils.checkpoint.CheckpointedSweep`, so a killed sweep
  resumes from its completed units and a corrupt chunk requeues its
  unit — the ledger and the chunk store live side by side in the same
  directory;
- the return value carries a :class:`SweepHealthReport`: engines used,
  demotions walked, stalls killed, lanes quarantined, units
  retried/requeued — the operator's one-glance answer to "what degraded
  while I wasn't looking", cross-checkable against the `event=` log
  records and the ledger line by line.

Deadline placement (one watchdog per dispatch, never nested): unsharded
units thread the deadline INTO the engine ladder — each rung attempt
gets its own budget, a stall classifies and retries/demotes like any
engine failure. Sharded units thread it into the elastic dispatch the
same way — one watchdog per MESH ATTEMPT, with the shrink logic on the
caller side of the heartbeat, so each rung of a degradation walk (cold
compile included) gets a fresh budget. An outer budget wrapping an
inner recovery loop would kill the loop mid-recovery — exactly the
false positive a watchdog must not produce.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib
import time
from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from yuma_simulation_tpu.resilience.errors import (
    EngineStall,
    classify_failure,
)
from yuma_simulation_tpu.resilience.guards import (
    QuarantineEntry,
    QuarantineReport,
)
from yuma_simulation_tpu.resilience.retry import (
    RetryPolicy,
    default_retry_policy,
)
from yuma_simulation_tpu.resilience.watchdog import Deadline
from yuma_simulation_tpu.utils.checkpoint import publish_atomic
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

#: Production default dispatch budget: generous enough for a cold
#: XLA/Mosaic compile of the largest supported shapes (minutes-scale on
#: a remote-tunnel runtime), small enough that a genuinely hung compile
#: is killed well inside a sweep's lifetime. Retries get the same again
#: on top (`grace_seconds`) since a retry may recompile from scratch.
DEFAULT_UNIT_BUDGET_SECONDS = 900.0


def canary_stride(fraction: float) -> int:
    """The deterministic unit-index stride a canary fraction selects
    (unit idx % stride == 0 is canaried; no RNG, so a re-run of the
    same sweep canaries the same units). ONE spelling shared with the
    fleet scheduler's fleet-scope selection — a forked rounding rule
    would canary different units per scope. Quantization note: stride
    sampling rounds to the nearest 1/N, so e.g. 0.4 selects every 2nd
    unit (50%) and anything above ~2/3 selects every unit."""
    return max(1, int(round(1.0 / fraction)))


def default_deadline() -> Deadline:
    """The production default unit deadline (15 min + 15 min retry grace)."""
    return Deadline(
        budget_seconds=DEFAULT_UNIT_BUDGET_SECONDS,
        grace_seconds=DEFAULT_UNIT_BUDGET_SECONDS,
    )


class FailureLedger:
    """Crash-safe JSONL of per-unit sweep outcomes.

    Each appended record is one JSON object per line. The whole file is
    re-published atomically per append (temp + fsync + rename — the
    checkpoint layer's primitive), so at every instant the on-disk
    ledger is a complete, parseable prefix of the sweep's history; a
    torn trailing line cannot exist by construction, but a load
    tolerates one anyway (a ledger written by a pre-atomic tool must
    not brick the directory — the torn tail is dropped with a warning).
    Records are small (a few hundred bytes) and units are coarse, so
    rewrite-per-append stays trivial I/O even for thousand-unit sweeps.

    `path=None` keeps the ledger in memory only — same API, no
    durability — for supervised sweeps without a checkpoint directory.
    """

    def __init__(self, path: Optional[str | pathlib.Path] = None):
        self.path = pathlib.Path(path) if path is not None else None
        self._entries: list[dict] = []
        if self.path is not None and self.path.exists():
            for lineno, line in enumerate(self.path.read_text().splitlines()):
                if not line.strip():
                    continue
                try:
                    self._entries.append(json.loads(line))
                except json.JSONDecodeError:
                    # Skip, don't stop: a corrupt MIDDLE line (bit rot,
                    # a non-atomic external writer) must not discard the
                    # valid records after it — the next append would
                    # republish the truncated history and erase them.
                    logger.warning(
                        "dropping undecodable ledger line %d in %s "
                        "(torn write from a non-atomic writer?)",
                        lineno,
                        self.path,
                    )
                    continue

    def append(self, event: str, **fields) -> dict:
        """Append one outcome record and (if durable) publish the
        updated ledger atomically. Returns the record.

        Records are stamped with a wall-clock ``t`` and — when a
        telemetry :class:`..telemetry.runctx.RunContext` is active —
        ``run_id``/``span_id``/``parent_id``, the join key against the
        flight recorder's span tree and the `event=` log stream.
        Purely ADDITIVE keys: pre-telemetry ledger readers still parse
        every record (caller-passed fields of the same name win)."""
        record = {"event": event, **fields}
        record.setdefault("t", round(time.time(), 6))
        try:
            from yuma_simulation_tpu.telemetry.runctx import current_fields

            for key, value in current_fields().items():
                record.setdefault(key, value)
        except Exception:
            pass
        self._entries.append(record)
        if self.path is not None:
            payload = "".join(
                json.dumps(e, sort_keys=True) + "\n" for e in self._entries
            )
            publish_atomic(self.path, payload.encode())
        return record

    def entries(self, event: Optional[str] = None) -> tuple:
        """All records, oldest first; `event` filters by record type."""
        if event is None:
            return tuple(self._entries)
        return tuple(e for e in self._entries if e.get("event") == event)

    def __len__(self) -> int:
        return len(self._entries)


@dataclasses.dataclass(frozen=True)
class SweepHealthReport:
    """What a supervised sweep survived — the operator-facing summary,
    cross-checkable record-for-record against the :class:`FailureLedger`
    and the `event=` log stream. Action counts (stalls, demotions,
    shrinks, retries) cover the units EXECUTED in this run —
    fully-resumed units' history lives in the durable ledger — but
    `lanes_quarantined` covers the RETURNED output, resumed units
    included: their chunks still carry the zero-masked lanes."""

    units_total: int
    units_completed: int
    #: units satisfied from a prior run's checkpoint chunks (resume).
    units_resumed: int
    #: units that needed more than one supervised attempt this run.
    units_retried: int
    #: units re-executed by checkpoint verification (torn/corrupt chunk).
    units_requeued: int
    #: supervised dispatches killed by the deadline watchdog.
    stalls_killed: int
    #: engine-ladder demotions across all units.
    engine_demotions: int
    #: elastic mesh shrinks across all units.
    mesh_shrinks: int
    #: scenario/grid lanes masked by the non-finite quarantine.
    lanes_quarantined: int
    #: engine rungs/paths that produced accepted unit results, sorted.
    engines_used: tuple
    ledger_path: Optional[str] = None
    #: numerics-canary re-executions run (telemetry.numerics): units
    #: re-dispatched on the demoted rung and compared fingerprint-by-
    #: fingerprint against the primary's per-epoch capture.
    canaries_run: int = 0
    #: canary comparisons that CONFIRMED drift — one per (unit, stream)
    #: whose per-epoch fingerprints diverged (typed `engine_drift`
    #: ledger records carry the first divergent epoch + ulp distance).
    drift_events: int = 0

    @property
    def clean(self) -> bool:
        """True iff nothing degraded: no retries, requeues, stalls,
        demotions, shrinks, quarantined lanes or confirmed drift
        (canaries that reproduced the primary's bits are healthy)."""
        return not (
            self.units_retried
            or self.units_requeued
            or self.stalls_killed
            or self.engine_demotions
            or self.mesh_shrinks
            or self.lanes_quarantined
            or self.drift_events
        )


class _UnitOutcome:
    """Mutable accumulator for one unit's recovery actions. Owns the
    unit's ledger bookkeeping for stalls so a kill is recorded whether
    the in-unit ladder absorbs it or it escapes to the unit retry."""

    def __init__(
        self, idx: int = 0, ledger: Optional[FailureLedger] = None
    ) -> None:
        self.idx = idx
        self.ledger = ledger
        self.attempts = 0
        self.stalls = 0
        self.demotions = 0
        self.mesh_shrinks = 0
        self.engine = "xla"
        self.quarantine_entries: tuple = ()
        #: numerics-canary bookkeeping (0.14.0): re-executions run on
        #: this unit and (unit, stream) comparisons that confirmed drift.
        self.canaries = 0
        self.drifts = 0

    def record_stall(
        self, *, attempt: int, rung: str = "", budget_s=None
    ) -> None:
        self.stalls += 1
        if self.ledger is not None:
            self.ledger.append(
                "unit_stalled",
                unit=self.idx,
                attempt=attempt,
                **({"rung": rung} if rung else {}),
                **({"budget_s": budget_s} if budget_s is not None else {}),
            )


@dataclasses.dataclass
class SweepSupervisor:
    """Run partitioned sweeps under full supervision.

    `unit_size` scenarios (or grid points) per idempotent unit;
    `deadline` bounds each supervised dispatch (None disables the
    watchdog — not recommended for unattended sweeps); `retry_policy`
    drives both the in-unit engine ladder and the unit-level retry count
    (`max_attempts_per_rung` supervised attempts per unit, so a ladder
    path gets rungs x attempts^2 total tries in the worst case);
    `quarantine=True` arms the per-lane non-finite guard (forces the XLA
    engine — the fused scan cannot host the guard); `elastic=True` arms
    shrink-and-continue on device loss for sharded units; `engine` is
    the starting ladder rung for unsharded units (must be "xla" under
    quarantine). `directory` makes the sweep durable: unit results
    snapshot through :class:`..utils.checkpoint.CheckpointedSweep`
    (chunk files + checksums) and the ledger publishes to `ledger.jsonl`
    alongside them, so a killed run resumes from its completed units and
    a torn chunk requeues exactly one unit.
    """

    directory: Optional[str | pathlib.Path] = None
    unit_size: int = 64
    deadline: Optional[Deadline] = dataclasses.field(
        default_factory=default_deadline
    )
    retry_policy: RetryPolicy = dataclasses.field(
        default_factory=default_retry_policy
    )
    quarantine: bool = True
    elastic: bool = True
    engine: str = "xla"
    #: Opt-in AOT cost capture (``telemetry.cost``): after the run (the
    #: flight-bundle publish, failure paths included), lower+compile
    #: each engine rung at the sweep's shape and append the
    #: :class:`..telemetry.cost.CostRecord` lines to the bundle's
    #: ``costs.jsonl``. Off by default — it compiles programs, which an
    #: unattended production sweep may not want to pay twice.
    capture_costs: bool = False
    #: Cross-engine numerics-canary fraction (``telemetry.numerics``):
    #: re-execute this fraction of units on the DEMOTED rung (deterministic
    #: stride over unit indices, unit 0 always canaried when > 0) inside
    #: :func:`..faults.canary_scope`, compare per-epoch fingerprints
    #: lane by lane, and ledger a typed ``engine_drift`` record per
    #: diverging (unit, stream) — first divergent epoch + ulp distance
    #: included. 0 disables (the production default is an operator
    #: choice: a canary re-pays the unit's compute on another rung).
    canary_fraction: float = 0.0
    #: Pin the canary's rung; None = one rung below the unit's executed
    #: engine on the demotion ladder (same rung when already at the
    #: bottom — a pure determinism canary, still meaningful: a demoted
    #: RETRY must reproduce the primary's bits).
    canary_engine: Optional[str] = None
    #: On-demand device profiling cadence (PR 19): every Nth unit (unit
    #: 0 included) dispatches under a ``jax.profiler`` trace written to
    #: ``DIRECTORY/profiles/unitNNNN`` and registered into the bundle's
    #: ``profiles.jsonl`` — so a long sweep leaves periodic on-chip
    #: evidence without an operator attaching by hand. Requires a
    #: `directory`; 0 disables (the default — a trace costs runtime).
    profile_every: int = 0

    def __post_init__(self) -> None:
        if self.unit_size < 1:
            raise ValueError("unit_size must be >= 1")
        if self.profile_every < 0:
            raise ValueError(
                f"profile_every must be >= 0, got {self.profile_every}"
            )
        if not (0.0 <= self.canary_fraction <= 1.0):
            raise ValueError(
                "canary_fraction must be in [0, 1], got "
                f"{self.canary_fraction}"
            )
        if self.quarantine and self.engine != "xla":
            raise ValueError(
                "quarantine rides the XLA scan carry; a supervised sweep "
                f"cannot start on engine {self.engine!r} with "
                "quarantine=True (pass quarantine=False to drill fused "
                "rungs)"
            )

    # -- public drivers -------------------------------------------------

    def run_batch(
        self,
        scenarios: Sequence,
        yuma_version: str,
        config=None,
        *,
        mesh=None,
        dtype=jnp.float32,
        tag: str = "",
        pack: bool = False,
    ) -> dict:
        """Supervised :func:`..simulation.sweep.simulate_batch` /
        :func:`..parallel.sharded.simulate_batch_sharded` over a
        scenario suite.

        Returns `{"dividends": [B, E, V], "quarantine":
        QuarantineReport, "report": SweepHealthReport}`. With `mesh`,
        units dispatch sharded (elastic if armed) under one watchdog
        each; without, down the engine ladder starting at `self.engine`
        with per-attempt deadlines. Healthy lanes are bitwise what an
        unfaulted run produces — every recovery action either
        re-executes a pure unit or masks a lane, never perturbs a
        healthy one.

        `pack=True` DONOR-PACKS the suite first
        (:func:`..simulation.sweep.pack_scenarios`): heterogeneous
        scenarios pad to one tile-aligned shape bucket with per-lane
        miner masks, so the whole suite rides one compiled batched
        shape — the serving tier's coalescing path. The returned
        dividends then carry the bucket's padded validator axis (slice
        `[:, :E_i, :V_i]` per lane to recover each scenario's own view;
        padded entries are exact zeros by the padding contract). XLA
        engine only (the fused scan has no per-scenario miner masks)
        and single-host only (the sharded path accounts memory
        per-shard, not per-bucket).
        """
        from yuma_simulation_tpu.models.config import YumaConfig
        from yuma_simulation_tpu.models.variants import variant_for_version
        from yuma_simulation_tpu.simulation.sweep import (
            pack_scenarios,
            stack_scenarios,
        )

        config = config if config is not None else YumaConfig()
        spec = variant_for_version(yuma_version)
        scenarios = list(scenarios)
        units = self._partition(len(scenarios))
        packed = None
        if pack:
            if mesh is not None:
                raise ValueError(
                    "pack=True donor-packs with per-lane miner masks, "
                    "which the sharded dispatch does not thread; use "
                    "mesh=None (or pre-shard the suite)"
                )
            if self.engine != "xla":
                raise ValueError(
                    "pack=True requires engine='xla': the fused case "
                    "scan has no per-scenario miner masks"
                )
            if scenarios:
                packed = pack_scenarios(scenarios, dtype)

        # The sweep-level dispatch plan (simulation.planner), recorded
        # on the sweep span so the flight bundle shows WHY the rung ran
        # before any unit dispatches; each unit's engine-rung span gets
        # its own per-dispatch plan from simulate_batch. Planning is
        # pure host arithmetic — zero compiles (the recompilation pins
        # cover this path).
        plan = None
        canary_expected = None
        if scenarios:
            from yuma_simulation_tpu.simulation.planner import (
                plan_dispatch,
            )

            if packed is not None:
                _, E0, V0, M0 = packed[0].shape
            else:
                E0, V0, M0 = np.shape(scenarios[0].weights)
            lanes0 = min(self.unit_size, len(scenarios))
            import math

            from yuma_simulation_tpu.ops.consensus import (
                dyadic_grid_fits_int32,
            )
            from yuma_simulation_tpu.simulation.planner import (
                EXPECTED_DRIFT_U16_FALLBACK,
            )

            if not dyadic_grid_fits_int32(
                int(M0),
                math.ceil(math.log2(config.consensus_precision)),
            ):
                # Beyond the int32 dyadic bound a fused-vs-XLA canary
                # pairing crosses the DOCUMENTED one-ulp u16-quantize
                # fallback class (ADVICE r5): stamp it expected instead
                # of paging on it. Auto plans never run fused here (the
                # eligibility gates), so this only fires for explicit
                # fused opt-ins.
                canary_expected = EXPECTED_DRIFT_U16_FALLBACK
            plan = plan_dispatch(
                f"supervised_batch:{yuma_version}",
                (lanes0, E0, V0, M0),
                spec,
                config,
                dtype,
                epoch_impl=self.engine if mesh is None else "xla",
                quarantine=self.quarantine,
                has_miner_mask=packed is not None,
                check_memory=mesh is None,
            )

        def dispatch_unit(
            idx: int, lo: int, hi: int, attempt: int, outcome: _UnitOutcome
        ) -> dict:
            unit = scenarios[lo:hi]
            label = f"{tag or 'batch'}:unit{idx}"
            if mesh is not None:
                from yuma_simulation_tpu.parallel.sharded import (
                    simulate_batch_sharded,
                )

                # The deadline goes INTO the elastic dispatch (one
                # watchdog per mesh attempt, shrinks on the caller side
                # of the heartbeat — see the module docstring). On a
                # unit retry the budget is pre-extended by the grace:
                # the sharded walk restarts its shrink count at 0 and
                # would otherwise retry on the cold-start budget.
                dl = self.deadline
                if dl is not None and attempt > 0:
                    dl = Deadline(
                        budget_seconds=dl.budget_for_attempt(attempt),
                        grace_seconds=dl.grace_seconds,
                    )
                ys = simulate_batch_sharded(
                    unit,
                    yuma_version,
                    config,
                    mesh=mesh,
                    quarantine=self.quarantine,
                    dtype=dtype,
                    elastic=self.elastic,
                    deadline=dl,
                )
                out = dict(ys)
                shrinks = out.get("mesh_degradations", ())
                out["_engine_used"] = (
                    "single_device_xla"
                    if shrinks and shrinks[-1].to_devices == 1
                    else "sharded_xla"
                )
                return out
            if packed is not None:
                Wp, Sp, rip, rep, maskp = packed
                return self._ladder_dispatch(
                    lambda rung: _batch_on_rung(
                        Wp[lo:hi],
                        Sp[lo:hi],
                        rip[lo:hi],
                        rep[lo:hi],
                        config,
                        spec,
                        rung,
                        self.quarantine,
                        miner_mask=maskp[lo:hi],
                    ),
                    label=label,
                    outcome=outcome,
                )
            W, S, ri, re = stack_scenarios(unit, dtype)
            return self._ladder_dispatch(
                lambda rung: _batch_on_rung(
                    W, S, ri, re, config, spec, rung, self.quarantine
                ),
                label=label,
                outcome=outcome,
            )

        def canary_dispatch(idx: int, lo: int, hi: int, rung: str) -> dict:
            # The cross-engine canary re-dispatch: the SAME unit, pinned
            # to the demoted rung (for sharded primaries: the unsharded
            # XLA engine — a cross-topology canary; the sharded==
            # unsharded contract is bitwise by construction). Guard
            # state matches the primary so the traced program differs
            # ONLY in the rung under comparison.
            if packed is not None:
                Wp, Sp, rip, rep, maskp = packed
                return _batch_on_rung(
                    Wp[lo:hi], Sp[lo:hi], rip[lo:hi], rep[lo:hi],
                    config, spec, rung, self.quarantine,
                    miner_mask=maskp[lo:hi],
                )
            W, S, ri, re = stack_scenarios(scenarios[lo:hi], dtype)
            return _batch_on_rung(
                W, S, ri, re, config, spec, rung, self.quarantine
            )

        return self._run_units(
            units,
            dispatch_unit,
            num_lanes=len(scenarios),
            tag=tag or f"batch:{yuma_version}",
            canary_dispatch=canary_dispatch,
            canary_expected=canary_expected,
            plan=plan,
            config_fingerprint={
                "driver": "run_batch",
                "version": yuma_version,
                "num_scenarios": len(scenarios),
                "unit_size": self.unit_size,
            },
            cost_request=(
                dict(
                    zip(
                        ("epochs", "V", "M"),
                        np.shape(scenarios[0].weights),
                    ),
                    yuma_version=yuma_version,
                )
                if scenarios
                else None
            ),
        )

    def run_grid(
        self,
        scenario,
        yuma_version: str,
        configs,
        *,
        tag: str = "",
        initial_state: Optional[dict] = None,
        epoch_offset: int = 0,
    ) -> dict:
        """Supervised :func:`..simulation.sweep.sweep_hyperparams` over
        a batched config grid (built with `config_grid`): the grid's
        lanes partition into units exactly like scenarios do, each unit
        re-slicing the batched config pytree (static leaves shared).
        Returns the same `{"dividends", "quarantine", "report"}` shape
        as :meth:`run_batch`, with lanes = grid points.

        `initial_state` / `epoch_offset` thread the suffix-resume
        contract through every unit AND its canary re-execution (the
        replay controller's incremental windows); requires a supervisor
        built with ``quarantine=False`` (the guard rides a monolithic
        carry) and is stamped into the checkpoint fingerprint so a
        resumed directory can never silently mix a suffix sweep with a
        from-zero one."""
        import jax

        leaves = jax.tree.leaves(configs)
        num_points = next(
            (leaf.shape[0] for leaf in leaves if jnp.ndim(leaf) > 0), 1
        )
        units = self._partition(num_points)

        from yuma_simulation_tpu.models.config import YumaConfig
        from yuma_simulation_tpu.simulation.planner import plan_dispatch

        # Each unit vmaps up to unit_size grid lanes over ONE scenario,
        # so the plan's lane count (and its memory footprint) is the
        # unit's, not a single lane's.
        lanes0 = min(self.unit_size, num_points)
        plan = plan_dispatch(
            f"supervised_grid:{yuma_version}",
            (lanes0,) + tuple(np.shape(scenario.weights)),
            yuma_version,
            YumaConfig(),  # grid points vary floats; plan on defaults
            jnp.float32,
            epoch_impl="xla",
            quarantine=self.quarantine,
        )

        def dispatch_unit(
            idx: int, lo: int, hi: int, attempt: int, outcome: _UnitOutcome
        ) -> dict:
            unit_cfg = jax.tree.map(
                lambda leaf: leaf[lo:hi] if jnp.ndim(leaf) > 0 else leaf,
                configs,
            )
            return self._ladder_dispatch(
                lambda rung: _grid_on_xla(
                    scenario, yuma_version, unit_cfg, self.quarantine,
                    initial_state=initial_state,
                    epoch_offset=epoch_offset,
                ),
                label=f"{tag or 'grid'}:unit{idx}",
                outcome=outcome,
                rungs=("xla",),
            )

        def canary_dispatch(idx: int, lo: int, hi: int, rung: str) -> dict:
            # Grid sweeps have a single-rung ladder: the canary is a
            # pure determinism re-execution on the same XLA engine (a
            # demoted RETRY must reproduce the primary's bits).
            del rung
            unit_cfg = jax.tree.map(
                lambda leaf: leaf[lo:hi] if jnp.ndim(leaf) > 0 else leaf,
                configs,
            )
            return _grid_on_xla(
                scenario, yuma_version, unit_cfg, self.quarantine,
                initial_state=initial_state,
                epoch_offset=epoch_offset,
            )

        return self._run_units(
            units,
            dispatch_unit,
            num_lanes=num_points,
            tag=tag or f"grid:{yuma_version}",
            canary_dispatch=canary_dispatch,
            plan=plan,
            config_fingerprint={
                "driver": "run_grid",
                "version": yuma_version,
                "num_points": num_points,
                "unit_size": self.unit_size,
                # Additive suffix-resume identity (None/0 for classic
                # from-zero grids, so existing fingerprints are stable):
                # a resumed checkpoint directory must never satisfy a
                # suffix sweep's units with a from-zero run's results.
                **(
                    {
                        "epoch_offset": int(epoch_offset),
                        "initial_state": _state_digest(initial_state),
                    }
                    if initial_state is not None or epoch_offset
                    else {}
                ),
            },
            cost_request=dict(
                zip(("epochs", "V", "M"), np.shape(scenario.weights)),
                yuma_version=yuma_version,
            ),
        )

    # -- internals ------------------------------------------------------

    def _partition(self, n: int) -> list:
        """Contiguous `(lo, hi)` unit bounds covering `range(n)`."""
        if n < 1:
            raise ValueError("cannot supervise an empty sweep")
        return [
            (lo, min(lo + self.unit_size, n))
            for lo in range(0, n, self.unit_size)
        ]

    def _ladder_dispatch(
        self,
        dispatch: Callable,
        *,
        label: str,
        outcome: _UnitOutcome,
        rungs=None,
    ) -> dict:
        """One unit attempt through the engine ladder. The deadline is
        threaded INTO the ladder (per rung attempt), and `on_failure`
        feeds every classified failure — including same-rung-absorbed
        stalls — into the unit's books."""
        from yuma_simulation_tpu.resilience.retry import run_ladder

        def on_failure(typed, rung, attempt):
            if isinstance(typed, EngineStall):
                outcome.record_stall(
                    attempt=attempt + 1,
                    rung=rung,
                    budget_s=typed.budget_seconds,
                )

        ys, engine_used, records = run_ladder(
            dispatch,
            self.engine,
            self.retry_policy,
            rungs=rungs,
            label=label,
            deadline=self.deadline,
            on_failure=on_failure,
        )
        out = dict(ys)
        out["_engine_used"] = engine_used
        out["_demotions"] = tuple(records)
        return out

    def _run_units(
        self,
        units: list,
        dispatch_unit: Callable,
        *,
        num_lanes: int,
        tag: str,
        config_fingerprint: dict,
        cost_request: Optional[dict] = None,
        plan=None,
        canary_dispatch: Optional[Callable] = None,
        canary_expected: Optional[str] = None,
    ) -> dict:
        from yuma_simulation_tpu.telemetry import (
            FlightRecorder,
            ensure_run,
            get_registry,
            record_device_telemetry,
            record_epoch_rate,
            span,
        )

        directory = (
            pathlib.Path(self.directory) if self.directory is not None else None
        )
        if directory is not None:
            directory.mkdir(parents=True, exist_ok=True)
        ledger = FailureLedger(
            directory / "ledger.jsonl" if directory is not None else None
        )
        # One _UnitOutcome PER EXECUTION (a requeued unit appends a
        # second): the report must account for every recovery action
        # taken, including ones on an execution whose chunk was later
        # torn and redone — last-write-wins would silently drop them.
        outcomes: dict[int, list] = {}
        executions: dict[int, int] = {}
        #: Units whose prior-run snapshot failed verification at resume
        #: (requeued before execution — distinct from within-run
        #: re-entries, which `executions` counts).
        resume_requeued: set[int] = set()
        #: Serialized per-epoch numerics records (telemetry.numerics),
        #: primary + canary roles — published to the bundle's
        #: numerics.jsonl and returned to callers (the fleet scheduler
        #: re-stamps them with fleet-global unit indices).
        numerics_records: list = []

        def unit_fn(idx: int) -> dict:
            if (
                self.profile_every <= 0
                or directory is None
                or idx % self.profile_every != 0
            ):
                return _unit_body(idx)
            # Periodic on-chip evidence: this unit dispatches under a
            # profiler trace, registered into the bundle whether the
            # unit succeeds or not (a failing unit's trace is exactly
            # the one that explains the failure).
            from yuma_simulation_tpu.utils.profiling import profile_trace

            pdir = directory / "profiles" / f"unit{idx:04d}"
            log_event(
                logger,
                "profile_started",
                mode="unit",
                unit=idx,
                artifact=str(pdir),
            )
            try:
                with profile_trace(str(pdir)):
                    return _unit_body(idx)
            finally:
                try:
                    FlightRecorder(directory).record_profile(
                        {
                            "event": "profile_published",
                            "mode": "unit",
                            "unit": idx,
                            "artifact": str(pdir),
                        }
                    )
                    log_event(
                        logger,
                        "profile_published",
                        mode="unit",
                        unit=idx,
                        artifact=str(pdir),
                    )
                except Exception:  # noqa: BLE001 — contained observation
                    logger.warning(
                        "unit profile registration failed", exc_info=True
                    )

        def _unit_body(idx: int) -> dict:
            from yuma_simulation_tpu.telemetry.slo import observe_duration

            lo, hi = units[idx]
            unit_t0 = time.perf_counter()
            with span(f"unit{idx}", lanes=[lo, hi]):
                executions[idx] = executions.get(idx, 0) + 1
                if executions[idx] > 1:
                    # Re-entry within one run = the checkpoint layer
                    # requeued this unit (torn/corrupt chunk detected).
                    ledger.append(
                        "unit_requeued", unit=idx, executions=executions[idx]
                    )
                outcome = _UnitOutcome(idx, ledger)
                outcomes.setdefault(idx, []).append(outcome)
                last = None
                for attempt in range(self.retry_policy.max_attempts_per_rung):
                    outcome.attempts = attempt + 1
                    try:
                        with span(f"attempt{attempt + 1}"):
                            ys = dispatch_unit(idx, lo, hi, attempt, outcome)
                            # Numerics capture + cross-engine canary
                            # BEFORE acceptance, so the unit_ok record
                            # carries this execution's canary/drift
                            # counts. Contained: a capture or canary
                            # failure must never fail the unit it
                            # observes.
                            self._capture_unit_numerics(
                                idx, lo, hi, ys, outcome, ledger,
                                canary_dispatch, numerics_records, tag,
                                canary_expected,
                            )
                            accepted = self._accept_unit(
                                idx, lo, hi, ys, outcome, ledger
                            )
                            # The unit-duration SLO signal: wall time of
                            # the accepted execution, retries included
                            # (what the caller actually waited).
                            unit_seconds = time.perf_counter() - unit_t0
                            observe_duration("unit_seconds", unit_seconds)
                            # The dispatch timing sketch: keyed by the
                            # rung that actually ran (post-demotion),
                            # the plan's shape bucket, and the backend —
                            # what tools/perfattrib.py joins against the
                            # AOT cost records. Never raises.
                            import jax

                            from yuma_simulation_tpu.telemetry.slo import (
                                observe_dispatch,
                            )

                            dshape = np.shape(accepted.get("dividends"))
                            observe_dispatch(
                                engine=outcome.engine or self.engine,
                                bucket=(
                                    plan.bucket.key
                                    if plan is not None
                                    else tag
                                ),
                                backend=jax.default_backend(),
                                seconds=unit_seconds,
                                epochs=(
                                    int(dshape[0] * dshape[1])
                                    if len(dshape) >= 2
                                    else 0
                                ),
                            )
                            return accepted
                    except BaseException as exc:  # noqa: BLE001 — classified
                        typed = classify_failure(exc)
                        if typed is None:
                            ledger.append(
                                "unit_failed",
                                unit=idx,
                                error=type(exc).__name__,
                                message=str(exc)[:500],
                            )
                            raise
                        last = typed
                        if isinstance(typed, EngineStall):
                            outcome.record_stall(
                                attempt=attempt + 1,
                                budget_s=typed.budget_seconds,
                            )
                        else:
                            ledger.append(
                                "unit_retry",
                                unit=idx,
                                attempt=attempt + 1,
                                error=type(typed).__name__,
                            )
                ledger.append(
                    "unit_failed",
                    unit=idx,
                    error=type(last).__name__,
                    message=str(last)[:500],
                )
                assert last is not None
                raise last

        registry = get_registry()
        with ensure_run() as run:
            report = None
            t0 = time.perf_counter()
            try:
                # The span chain under one run: sweep -> unit -> attempt
                # -> engine rung (the rung span lives in run_ladder).
                # Every ledger append above happens under one of these,
                # so obsreport resolves each record to a span.
                with span(
                    f"sweep:{tag}", units=len(units), lanes=num_lanes
                ) as sweep_span:
                    if sweep_span is not None and plan is not None:
                        # The typed dispatch-plan attribute: flight
                        # bundles show WHY this sweep's rung ran
                        # (obsreport renders a "dispatch plans" section
                        # from these).
                        sweep_span.attrs["plan"] = plan.span_attr()
                    if directory is not None:
                        from yuma_simulation_tpu.utils.checkpoint import (
                            CheckpointedSweep,
                        )

                        sweep = CheckpointedSweep(
                            directory,
                            num_chunks=len(units),
                            tag=tag,
                            config=config_fingerprint,
                        )
                        # A chunk torn BETWEEN runs (storage rot, a
                        # crash mid-publish) requeues at resume: ledger
                        # it under the same `unit_requeued` contract as
                        # a within-run tear, so the bundle cross-check
                        # (flight.ledger_counts) and the numerics-stream
                        # replace-not-duplicate rule see one story.
                        for i in sweep.corrupt_chunks():
                            if 0 <= i < len(units):
                                resume_requeued.add(i)
                                ledger.append(
                                    "unit_requeued",
                                    unit=i,
                                    reason="resume_verification_failed",
                                )
                        dividends = sweep.run(
                            lambda i: unit_fn(i)["dividends"]
                        )
                    else:
                        dividends = np.concatenate(
                            [
                                unit_fn(i)["dividends"]
                                for i in range(len(units))
                            ],
                            axis=0,
                        )
                    resumed = sum(
                        1 for i in range(len(units)) if i not in executions
                    )

                    # Quarantine provenance comes from each unit's LAST
                    # execution — the one whose result stands in the
                    # output. Units satisfied from a prior run's chunks
                    # did not execute here, but their chunks still carry
                    # any zero-masked lanes: recover their provenance
                    # from the ledger's unit_ok records, or the caller
                    # would treat masked zeros as genuine dividends.
                    entries: list = []
                    for idx in range(len(units)):
                        if idx in outcomes:
                            entries.extend(
                                outcomes[idx][-1].quarantine_entries
                            )
                        else:
                            entries.extend(
                                _ledger_quarantine_entries(ledger, idx)
                            )
                    quarantine = QuarantineReport(
                        entries=tuple(entries), num_cases=num_lanes
                    )
                    report = self._build_report(
                        units, outcomes, executions, resumed, len(entries),
                        directory, resume_requeued,
                    )
                    # Metrics the supervisor owns (the per-action
                    # counters — stalls, demotions, shrinks, retries —
                    # are incremented at their sources in the watchdog/
                    # ladder/elastic layers, exactly once each).
                    if entries:
                        registry.counter(
                            "quarantined_lanes",
                            help="non-finite lanes masked by the guard",
                        ).inc(len(entries))
                    shape = np.shape(dividends)
                    epochs = (
                        int(shape[0] * shape[1]) if len(shape) >= 2 else None
                    )
                    record_epoch_rate(
                        tag,
                        epochs=epochs,
                        seconds=time.perf_counter() - t0,
                        registry=registry,
                        logger_=logger,
                    )
                    # Device/compile sample at the sweep boundary —
                    # host-level, after every dispatch completed.
                    record_device_telemetry(registry)
                    log_event(
                        logger,
                        "sweep_supervised",
                        level=logging.INFO,
                        tag=tag,
                        units=report.units_total,
                        resumed=report.units_resumed,
                        retried=report.units_retried,
                        requeued=report.units_requeued,
                        stalls=report.stalls_killed,
                        demotions=report.engine_demotions,
                        mesh_shrinks=report.mesh_shrinks,
                        quarantined=report.lanes_quarantined,
                    )
            finally:
                # The flight bundle publishes on failure too: a crashed
                # sweep's spans are exactly the ones worth keeping, and
                # every ledger record written so far must stay
                # resolvable for obsreport --check.
                if directory is not None:
                    try:
                        recorder = FlightRecorder(directory)
                        recorder.record(
                            run, registry=registry, report=report
                        )
                    except Exception:
                        logger.warning(
                            "flight-recorder bundle publish failed for %s",
                            directory,
                            exc_info=True,
                        )
                    else:
                        try:
                            # The numerics stream rides the same
                            # crash-safe bundle (merged by unit/role, so
                            # it survives a failed/resumed sweep exactly
                            # like costs.jsonl — resumed units keep the
                            # prior run's records).
                            recorder.record_numerics(
                                numerics_records, run_id=run.run_id
                            )
                        except Exception:
                            logger.warning(
                                "numerics stream publish failed for %s "
                                "(the flight bundle itself published)",
                                directory,
                                exc_info=True,
                            )
                        if self.capture_costs and cost_request is not None:
                            # Opt-in AOT cost capture into costs.jsonl:
                            # compiles each rung once, so it runs AFTER
                            # the sweep (warm-path compile budgets are
                            # unaffected) and rides the same crash-safe
                            # bundle the report does. Its own guard: a
                            # capture failure must not be misreported
                            # as the bundle (spans/ledger/report) having
                            # failed to publish — by here it published.
                            try:
                                from yuma_simulation_tpu.telemetry.cost import (  # noqa: E501
                                    capture_engine_costs,
                                )

                                recorder.record_costs(
                                    capture_engine_costs(
                                        cost_request["V"],
                                        cost_request["M"],
                                        cost_request["epochs"],
                                        yuma_version=cost_request[
                                            "yuma_version"
                                        ],
                                    ),
                                    run_id=run.run_id,
                                )
                            except Exception:
                                logger.warning(
                                    "AOT cost capture failed for %s (the "
                                    "flight bundle itself published)",
                                    directory,
                                    exc_info=True,
                                )
        return {
            "dividends": dividends,
            "quarantine": quarantine,
            "report": report,
            "numerics_records": numerics_records,
        }

    # -- numerics canary ------------------------------------------------

    def _canary_selected(self, idx: int) -> bool:
        """Deterministic stride selection over unit indices (no RNG —
        a re-run of the same sweep canaries the same units, so resumed
        and fresh runs account identically)."""
        if self.canary_fraction <= 0.0:
            return False
        return idx % canary_stride(self.canary_fraction) == 0

    def _canary_rung(self, primary_engine: str) -> str:
        """The rung the canary re-executes on: pinned, or one below the
        primary on the demotion ladder (same rung at the bottom — a
        determinism canary). Sharded/single-device paths canary on the
        unsharded XLA engine (the sharded == unsharded contract is the
        observable under test there)."""
        if self.canary_engine is not None:
            return self.canary_engine
        from yuma_simulation_tpu.simulation.planner import (
            ENGINE_LADDER,
            ladder_from,
        )

        if primary_engine not in ENGINE_LADDER:
            return "xla"
        if primary_engine in ("fused_varying_mxu", "fused_varying"):
            # The epoch-tiled rungs' bitwise-comparable partner is the
            # VPU twin (an MXU primary) or the rung itself (a
            # determinism canary, like the xla bottom rung): the next
            # ladder rung below is the CASE-scan family, which the
            # varying kernel matches only to reduction-order rounding —
            # pairing them would make every canary a false drift
            # incident (and beyond V = 2^14 the `_mxu` case rung would
            # reject the shape outright).
            return "fused_varying"
        ladder = ladder_from(primary_engine)
        return ladder[1] if len(ladder) > 1 else ladder[0]

    def _capture_unit_numerics(
        self,
        idx: int,
        lo: int,
        hi: int,
        ys: dict,
        outcome: _UnitOutcome,
        ledger: FailureLedger,
        canary_dispatch: Optional[Callable],
        records: list,
        tag: str,
        canary_expected: Optional[str] = None,
    ) -> None:
        """Fetch the unit's in-scan numerics sketches, serialize the
        primary record, and (on selected units) run the cross-engine
        canary. Wholly contained: observability must never fail the
        sweep it observes."""
        sketches = ys.get("numerics")
        if sketches is None:
            return
        try:
            from yuma_simulation_tpu.telemetry.numerics import (
                sketch_records,
                to_host,
            )

            engine = ys.get("_engine_used", self.engine)
            primary = to_host(sketches)
            records.extend(
                sketch_records(
                    primary, unit=idx, lanes=(lo, hi), engine=engine,
                    role="primary", label=tag,
                )
            )
        except Exception:
            logger.warning(
                "numerics capture failed for unit %d", idx, exc_info=True
            )
            return
        if canary_dispatch is None or not self._canary_selected(idx):
            return
        self._run_canary(
            idx, lo, hi, primary, engine, outcome, ledger,
            canary_dispatch, records, tag, canary_expected,
        )

    def _run_canary(
        self,
        idx: int,
        lo: int,
        hi: int,
        primary: dict,
        primary_engine: str,
        outcome: _UnitOutcome,
        ledger: FailureLedger,
        canary_dispatch: Callable,
        records: list,
        tag: str,
        canary_expected: Optional[str] = None,
    ) -> None:
        """Re-execute one accepted unit on the demoted rung inside
        :func:`..faults.canary_scope` and compare per-epoch fingerprints
        lane by lane. Confirmed drift is a typed ``engine_drift`` ledger
        record per diverging (unit, stream) — global lane index, first
        divergent epoch, summed ulp distance — plus a bad
        ``engine_drift_ok`` SLO event and an ``engine_drift_total``
        counter tick; a clean canary feeds the same SLO stream good.

        `canary_expected` names the documented accepted-drift class
        this sweep's shape sits in (today: the u16-quantize fallback
        pairing of an explicit fused opt-in beyond the int32 dyadic
        bound — ADVICE r5). A divergence on a fused-vs-XLA pairing
        under that flag is recorded and rendered but NOT treated as an
        incident: the canary record carries ``expected``, the ledger
        record too, the SLO stream stays good, and ``driftreport
        --check`` passes."""
        from yuma_simulation_tpu.resilience import faults
        from yuma_simulation_tpu.telemetry import get_registry, span
        from yuma_simulation_tpu.telemetry.numerics import (
            compare_sketches,
            sketch_records,
            to_host,
        )
        from yuma_simulation_tpu.telemetry.slo import observe_event

        rung = self._canary_rung(primary_engine)
        registry = get_registry()
        try:
            with span(f"canary{idx}", lanes=[lo, hi], rung=rung):
                with faults.canary_scope():
                    ys_c = canary_dispatch(idx, lo, hi, rung)
                sketches_c = (
                    ys_c.get("numerics") if isinstance(ys_c, dict) else None
                )
                if sketches_c is None:
                    ledger.append(
                        "canary_failed",
                        unit=idx,
                        reason="no numerics capture on canary rung",
                    )
                    return
                from yuma_simulation_tpu.simulation.planner import (
                    FUSED_CASE_RUNGS as fused,
                )
                expected = (
                    canary_expected
                    if (primary_engine in fused) != (rung in fused)
                    else None
                )
                canary = to_host(sketches_c)
                canary_records = sketch_records(
                    canary, unit=idx, lanes=(lo, hi), engine=rung,
                    role="canary", label=tag,
                )
                if expected:
                    for rec in canary_records:
                        rec["expected"] = expected
                records.extend(canary_records)
                divergences = compare_sketches(primary, canary)
                outcome.canaries += 1
                registry.counter(
                    "numerics_canaries",
                    help="cross-engine numerics canary re-executions",
                ).inc()
                ledger.append(
                    "unit_canary",
                    unit=idx,
                    engine=rung,
                    primary_engine=primary_engine,
                    drift_streams=len(divergences),
                )
                if not divergences:
                    observe_event("engine_drift_ok", True)
                    return
                if expected:
                    # The codified accepted-drift class: visible in the
                    # ledger and the numerics stream, but NOT an
                    # incident — no drift count, no bad SLO event, no
                    # degraded report.
                    registry.counter(
                        "engine_drift_expected",
                        help="canary divergences inside a documented "
                        "accepted-drift class",
                    ).inc(len(divergences))
                    observe_event("engine_drift_ok", True)
                    for stream, lanes in sorted(divergences.items()):
                        ledger.append(
                            "engine_drift",
                            unit=idx,
                            stream=stream,
                            primary_engine=primary_engine,
                            canary_engine=rung,
                            expected=expected,
                            lanes=[
                                [
                                    lo + d["lane"],
                                    d["first_divergent_epoch"],
                                    d["ulp_distance"],
                                ]
                                for d in lanes
                            ],
                        )
                    return
                outcome.drifts += len(divergences)
                registry.counter(
                    "engine_drift_total",
                    help="canary comparisons that confirmed numerics drift",
                ).inc(len(divergences))
                observe_event("engine_drift_ok", False)
                for stream, lanes in sorted(divergences.items()):
                    first = lanes[0]
                    ledger.append(
                        "engine_drift",
                        unit=idx,
                        stream=stream,
                        primary_engine=primary_engine,
                        canary_engine=rung,
                        # [global lane, first divergent epoch, ulp
                        # distance] per diverging lane — what
                        # driftreport localizes.
                        lanes=[
                            [
                                lo + d["lane"],
                                d["first_divergent_epoch"],
                                d["ulp_distance"],
                            ]
                            for d in lanes
                        ],
                    )
                    log_event(
                        logger,
                        "engine_drift",
                        level=logging.ERROR,
                        unit=idx,
                        stream=stream,
                        primary=primary_engine,
                        canary=rung,
                        lane=lo + first["lane"],
                        epoch=first["first_divergent_epoch"],
                        ulp=first["ulp_distance"],
                    )
        except Exception:
            logger.warning(
                "numerics canary failed for unit %d", idx, exc_info=True
            )
            try:
                ledger.append("canary_failed", unit=idx, reason="exception")
            except Exception:
                pass

    def _accept_unit(
        self,
        idx: int,
        lo: int,
        hi: int,
        ys: dict,
        outcome: _UnitOutcome,
        ledger: FailureLedger,
    ) -> dict:
        """Fold one successful unit dispatch into the books; returns the
        ys dict (its "dividends" is what the chunk store snapshots)."""
        ys = dict(ys)
        ys.pop("numerics", None)  # fetched by _capture_unit_numerics
        outcome.engine = ys.pop("_engine_used", "xla")
        demotions = ys.pop("_demotions", ())
        outcome.demotions = len(demotions)
        shrinks = ys.pop("mesh_degradations", ())
        outcome.mesh_shrinks = len(shrinks)
        q = ys.get("quarantine")
        if q is not None:
            if not isinstance(q, QuarantineReport):
                from yuma_simulation_tpu.resilience.guards import (
                    build_quarantine_report,
                )

                q = build_quarantine_report(q)
            outcome.quarantine_entries = tuple(
                QuarantineEntry(case=lo + e.case, epoch=e.epoch, tensor=e.tensor)
                for e in q.entries
            )
        ledger.append(
            "unit_ok",
            unit=idx,
            lanes=[lo, hi],
            attempts=outcome.attempts,
            engine=outcome.engine,
            stalls=outcome.stalls,
            demotions=outcome.demotions,
            mesh_shrinks=outcome.mesh_shrinks,
            canaries=outcome.canaries,
            drifts=outcome.drifts,
            # Full provenance, not just lane indices: a later RESUMED
            # run reconstructs its QuarantineReport from these records
            # (the resumed chunks still carry the zero-masked lanes).
            quarantined=[
                [e.case, e.epoch, e.tensor]
                for e in outcome.quarantine_entries
            ],
        )
        ys["dividends"] = np.asarray(ys["dividends"])
        return ys

    def _build_report(
        self, units, outcomes, executions, resumed, lanes_quarantined,
        directory, resume_requeued=frozenset(),
    ) -> SweepHealthReport:
        runs = [o for per_unit in outcomes.values() for o in per_unit]
        final = [per_unit[-1] for per_unit in outcomes.values()]
        return SweepHealthReport(
            units_total=len(units),
            units_completed=len(units),
            units_resumed=resumed,
            units_retried=sum(
                1
                for per_unit in outcomes.values()
                if any(o.attempts > 1 for o in per_unit)
            ),
            # Distinct requeued units, whichever way the tear was
            # detected: within-run re-entry or resume-time verification
            # failure (matches ledger_counts' distinct-unit rule).
            units_requeued=len(
                {i for i, c in executions.items() if c > 1}
                | set(resume_requeued)
            ),
            stalls_killed=sum(o.stalls for o in runs),
            engine_demotions=sum(o.demotions for o in runs),
            mesh_shrinks=sum(o.mesh_shrinks for o in runs),
            lanes_quarantined=lanes_quarantined,
            engines_used=tuple(sorted({o.engine for o in final}))
            or ("resumed",),
            ledger_path=(
                str(directory / "ledger.jsonl") if directory is not None else None
            ),
            canaries_run=sum(o.canaries for o in runs),
            drift_events=sum(o.drifts for o in runs),
        )


def _ledger_quarantine_entries(
    ledger: FailureLedger, idx: int
) -> tuple:
    """Quarantine provenance for a RESUMED unit, from its last
    `unit_ok` ledger record. Tolerates the legacy record shape (bare
    lane indices) by returning unknown-provenance entries."""
    last = None
    for record in ledger.entries("unit_ok"):
        if record.get("unit") == idx:
            last = record
    if last is None:
        return ()
    entries = []
    for item in last.get("quarantined", ()):
        if isinstance(item, (list, tuple)) and len(item) == 3:
            entries.append(
                QuarantineEntry(
                    case=int(item[0]), epoch=int(item[1]), tensor=str(item[2])
                )
            )
        else:
            entries.append(
                QuarantineEntry(case=int(item), epoch=-1, tensor="unknown")
            )
    return tuple(entries)


def _batch_on_rung(
    W, S, ri, re, config, spec, rung, quarantine, miner_mask=None
) -> dict:
    """One `simulate_batch` dispatch pinned to ladder rung `rung`,
    blocked to completion so async failures surface inside the
    supervising try. Module-level so every unit hits the same jitted
    cache entries — the supervisor adds zero warm-repeat compiles.
    `miner_mask` is the donor-packed suites' per-lane consensus mask
    (`run_batch(pack=True)`); XLA rung only."""
    import jax

    from yuma_simulation_tpu.simulation.sweep import simulate_batch
    from yuma_simulation_tpu.telemetry.runctx import dispatch_annotation

    with dispatch_annotation(f"supervised_batch:{rung}"):
        return jax.block_until_ready(
            simulate_batch(
                W, S, ri, re, config, spec, epoch_impl=rung,
                quarantine=quarantine, miner_mask=miner_mask,
            )
        )


def _state_digest(initial_state) -> Optional[str]:
    """Content address of a suffix-resume carry (sorted-key canonical
    npz bytes — the state cache's serialization), for checkpoint and
    fleet-manifest fingerprints: two hosts joining one suffix sweep
    must agree on the EXACT carry, not just its shape."""
    if initial_state is None:
        return None
    import hashlib

    from yuma_simulation_tpu.replay.statecache import serialize_state

    return hashlib.sha256(serialize_state(initial_state)).hexdigest()


def _grid_on_xla(
    scenario,
    yuma_version,
    configs,
    quarantine,
    *,
    initial_state=None,
    epoch_offset: int = 0,
) -> dict:
    """One `sweep_hyperparams` dispatch (grid sweeps have a single-rung
    ladder: the vmap'd XLA engine), blocked to completion."""
    import jax

    from yuma_simulation_tpu.simulation.sweep import sweep_hyperparams
    from yuma_simulation_tpu.telemetry.runctx import dispatch_annotation

    with dispatch_annotation("supervised_grid:xla"):
        return jax.block_until_ready(
            sweep_hyperparams(
                scenario,
                yuma_version,
                configs,
                quarantine=quarantine,
                initial_state=initial_state,
                epoch_offset=epoch_offset,
            )
        )
