"""Deterministic fault injection — test-only hooks for the resilience CI.

Every recovery path in this layer (engine demotion, lane quarantine,
checkpoint requeue) exists because of failures a CPU test run cannot
naturally provoke: Mosaic VMEM exhaustion needs a real chip, NaNs need
pathological inputs, torn checkpoint chunks need a crash at the wrong
instant. These hooks let a test provoke each one *on purpose and
deterministically*, so every ladder rung runs in CI under
`JAX_PLATFORMS=cpu`:

- `FaultPlan.fused_oom_dispatches=N` — the first N fused-engine
  dispatches raise a simulated :class:`..errors.EngineResourceExhausted`
  before the kernel is entered;
- `FaultPlan.nan` — lane `case`'s per-epoch dividends are overwritten
  with NaN at epoch `epoch`, INSIDE the scan step (a traced select the
  engines thread through as a poison operand), so the quarantine carry
  sees the failure exactly where a real numerical blow-up would appear.
  The injection is at the step's outputs rather than its inputs by
  necessity: the consensus kernel is reference-faithfully
  NaN-sanitizing (`nan_to_num` on every bond normalization, `where`
  guards on every divide), so corrupted input weights/stakes are
  swallowed before they can reach an output — verified empirically; a
  genuinely propagating NaN needs a non-finite *hyperparameter*, which
  the quarantine tests also cover via a NaN config-grid lane;
- `FaultPlan.truncate_chunks` / `corrupt_chunks` — a just-published
  checkpoint chunk file is truncated / bit-flipped ONCE (simulating
  disk corruption between runs), so resume-time checksum verification
  and requeue are exercised end to end;
- `FaultPlan.stall` — the watchdog's worker thread sleeps through the
  deadline before the real dispatch (a simulated hung compile: no
  heartbeat, a typed `EngineStall` in the caller);
- `FaultPlan.device_loss` — every elastic sharded dispatch whose mesh
  still routes to the named device raises a simulated
  :class:`..errors.DeviceLossError`, until the mesh is rebuilt without
  it (the semantics of real hardware loss: only shrinking recovers);
- `FaultPlan.host_crash` — SIGKILL the current process (a simulated
  fleet host) after N lease claims, so the fleet drill proves lease
  EXPIRY recovers the dead host's units (no teardown code runs);
- `FaultPlan.lease_tear` — truncate the host's own live lease file
  after N heartbeat renewals (simulated shared-store corruption), so
  torn-lease tolerance and the LeaseExpired abandon path are exercised;
- `FaultPlan.overload` — a one-shot burst of synthetic requests at the
  serving tier's admission layer, so the 429/Retry-After shed path and
  the queue-depth/shed metrics are drill-able on CPU CI;
- `FaultPlan.drift` — flip one lane's dividend by EXACTLY one ulp at a
  target epoch, inside numerics-canary re-executions ONLY
  (:func:`canary_scope` / :func:`active_drift_fault`), so the numerics
  flight recorder's whole drift pipeline — per-epoch fingerprints ->
  cross-engine canary -> typed ``engine_drift`` ledger event -> drift
  SLO -> ``driftreport --check`` — is drill-able on CPU CI.

The hooks are consulted at host level by the engines and
`CheckpointedSweep`; with no plan armed (the production state) each is
a single `is None` check. Arm a plan only via the
:func:`inject_faults` context manager — it is process-global and
test-only by design, never part of a production configuration.

Hooks are INERT while a call is being jax-traced
(:func:`_tracing_now`): a hook firing at trace time would bake the
armed plan (or its absence) into the persistent jit cache of whatever
outer program is being traced — e.g. the sharded `shard_map` batch —
so a later call with the opposite arming state would silently reuse
the wrong executable. Fault injection therefore targets the host-level
entry points only, which is where every resilience test drives it.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import logging
import os
import sys
from typing import Optional

from yuma_simulation_tpu.resilience.errors import (
    DeviceLossError,
    EngineResourceExhausted,
)
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class StallFault:
    """Hold the first `dispatches` supervised dispatches (after letting
    `skip` through) hostage for `seconds` of wall clock — a simulated
    hung compile/collective. The sleep happens on the watchdog's WORKER
    thread (:func:`maybe_stall_dispatch` is called there, host level,
    just before the real dispatch), so a deadline shorter than `seconds`
    sees exactly what a real hang produces: no heartbeat, an abandoned
    worker, a typed `EngineStall` in the caller."""

    seconds: float = 5.0
    dispatches: int = 1
    skip: int = 0


@dataclasses.dataclass(frozen=True)
class DeviceLossFault:
    """Simulate device `device_id` dropping out of the mesh: every
    elastic sharded dispatch whose mesh still contains that device
    raises a :class:`..errors.DeviceLossError` naming it. The fault
    keeps firing until the mesh no longer includes the device — exactly
    the semantics of real hardware loss (retrying on the same mesh
    cannot succeed; only shrinking recovers), so the drill proves the
    degradation actually happened rather than a lucky retry."""

    device_id: int


@dataclasses.dataclass(frozen=True)
class HostCrashFault:
    """SIGKILL the CURRENT PROCESS (a simulated fleet host) after it has
    claimed `after_claims` work-unit leases — the fleet drill's host
    loss. SIGKILL by design: no atexit, no finally, no lease release —
    exactly what a preempted VM or OOM-killed worker leaves behind, so
    the drill proves lease EXPIRY (not polite cleanup) is what recovers
    the unit. Consulted by the fabric scheduler via
    :func:`maybe_crash_host` immediately after a claim is ledgered, so
    the claim is durably visible to the survivors before the host
    dies."""

    after_claims: int = 1


@dataclasses.dataclass(frozen=True)
class LeaseTearFault:
    """Truncate the current host's OWN LIVE lease file to `keep_bytes`
    after its `after_renewals`-th heartbeat renewal — simulated shared-
    filesystem corruption of a claim record. A torn lease is unparseable
    to every scanner, which must treat it as stealable (corrupt claims
    cannot gate work forever); the original holder discovers the theft
    at its next renewal (identity mismatch -> typed
    :class:`..errors.LeaseExpired`) and abandons the unit without
    publishing. Consulted by the fabric lease store via
    :func:`maybe_tear_lease`."""

    after_renewals: int = 1
    keep_bytes: int = 8


@dataclasses.dataclass(frozen=True)
class OverloadFault:
    """Inject a deterministic BURST of synthetic requests at the serving
    tier's admission layer (:mod:`..serve`): the next real request first
    pushes `requests` synthetic tickets (tiny built-in scenario, tenant
    `tenant`) through the same quota + bounded-queue path it is about to
    take, so the shed/backpressure/breaker responses are drill-able on
    CPU CI without a real traffic generator. One-shot per armed plan —
    the burst fires exactly once, consumed via
    :func:`active_overload_fault`."""

    requests: int = 32
    tenant: str = "synthetic-burst"


@dataclasses.dataclass(frozen=True)
class NaNFault:
    """Poison scenario lane `case`'s dividends at epoch `epoch` (global
    epoch index). `case=None` targets a single-scenario run — or every
    lane of a batch."""

    epoch: int
    case: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class DriftFault:
    """Flip scenario lane `case`'s dividend (validator 0) by EXACTLY
    one ulp at global epoch `epoch` — the smallest representable
    cross-engine drift, injected so CI proves the numerics flight
    recorder's whole pipeline (per-epoch fingerprints -> cross-engine
    canary -> typed ``engine_drift`` ledger event -> drift SLO ->
    ``driftreport --check`` exit != 0) detects real drift end to end.

    Scoped to CANARY re-executions only (:func:`canary_scope` /
    :func:`active_drift_fault`): a flip applied to both the primary and
    its canary would cancel in the comparison, so the fault fires only
    while a canary dispatch is executing — exactly modeling a demoted
    rung whose reduction spelling drifted from the primary's.
    `case=None` flips every lane of the canary batch."""

    epoch: int
    case: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A declarative set of faults to inject. Immutable; the mutable
    firing state (dispatch counters, one-shot chunk marks) lives in the
    :class:`_FaultState` the context manager creates."""

    nan: Optional[NaNFault] = None
    #: single-ulp lane flip inside canary re-executions (drift drill).
    drift: Optional[DriftFault] = None
    fused_oom_dispatches: int = 0
    #: fused dispatches to let through before the failures start —
    #: targets a mid-stream chunk rather than the first dispatch.
    fused_oom_skip: int = 0
    #: chunk index -> bytes to KEEP of the published file (truncation).
    truncate_chunks: dict = dataclasses.field(default_factory=dict)
    #: chunk indices whose published file gets one byte flipped.
    corrupt_chunks: tuple = ()
    #: hold supervised dispatches past their deadline (hang simulation).
    stall: Optional[StallFault] = None
    #: drop one device out of the elastic sharded mesh.
    device_loss: Optional[DeviceLossFault] = None
    #: SIGKILL this process (a simulated fleet host) after N lease claims.
    host_crash: Optional[HostCrashFault] = None
    #: truncate this host's live lease file after N heartbeat renewals.
    lease_tear: Optional[LeaseTearFault] = None
    #: burst of synthetic requests at the serve tier's admission layer.
    overload: Optional[OverloadFault] = None


class _FaultState:
    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.fused_dispatches_seen = 0
        self.fused_dispatches_failed = 0
        self.stall_dispatches_seen = 0
        self.stall_dispatches_fired = 0
        self.mangled_chunks: set = set()
        self.claims_seen = 0
        self.renewals_seen = 0
        self.lease_torn = False
        self.overload_fired = False


_ACTIVE: Optional[_FaultState] = None


def _tracing_now() -> bool:
    """Whether we are inside a jax trace (jit/vmap/shard_map body).
    Fault hooks are inert there — see the module docstring."""
    try:
        from jax import core

        return not core.trace_state_clean()
    except Exception:
        # Fail CLOSED (pretend we are tracing, hooks inert): if a jax
        # upgrade moves trace_state_clean, the safe failure mode is a
        # fault test that visibly stops firing — not an armed plan
        # baked into a production jit cache.
        return True


@contextlib.contextmanager
def inject_faults(plan: FaultPlan):
    """Arm `plan` for the duration of the `with` block. Nesting is
    rejected — overlapping plans would make the injected failures
    order-dependent, which defeats the point."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("a FaultPlan is already armed; nesting not supported")
    _ACTIVE = _FaultState(plan)
    try:
        yield _ACTIVE
    finally:
        _ACTIVE = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE.plan if _ACTIVE is not None else None


def maybe_fail_fused_dispatch() -> None:
    """Engine hook: called immediately before each fused-Pallas dispatch.
    Raises a simulated resource-exhaustion for the plan's first N calls."""
    state = _ACTIVE
    if state is None or state.plan.fused_oom_dispatches <= 0:
        return
    if _tracing_now():
        return
    state.fused_dispatches_seen += 1
    if (
        state.fused_dispatches_seen > state.plan.fused_oom_skip
        and state.fused_dispatches_failed < state.plan.fused_oom_dispatches
    ):
        state.fused_dispatches_failed += 1
        log_event(
            logger,
            "fault_injected",
            kind="fused_oom",
            dispatch=state.fused_dispatches_failed,
        )
        raise EngineResourceExhausted(
            "injected fault: simulated RESOURCE_EXHAUSTED on fused dispatch "
            f"{state.fused_dispatches_failed}/{state.plan.fused_oom_dispatches}"
        )


def maybe_stall_dispatch() -> None:
    """Watchdog-worker hook: called on the worker thread immediately
    before a supervised dispatch. Sleeps through the armed plan's stall
    window for its first N supervised calls — the caller's deadline
    expires while this thread is asleep, exactly as it would during a
    real native-code hang."""
    state = _ACTIVE
    if state is None or state.plan.stall is None:
        return
    if _tracing_now():
        return
    plan_stall = state.plan.stall
    state.stall_dispatches_seen += 1
    if (
        state.stall_dispatches_seen > plan_stall.skip
        and state.stall_dispatches_fired < plan_stall.dispatches
    ):
        state.stall_dispatches_fired += 1
        log_event(
            logger,
            "fault_injected",
            kind="stall",
            dispatch=state.stall_dispatches_fired,
            hold_s=f"{plan_stall.seconds:.3f}",
        )
        import time

        time.sleep(plan_stall.seconds)


def maybe_lose_device(devices) -> None:
    """Elastic-dispatch hook: called with the mesh's device list before
    each sharded dispatch. Raises a simulated
    :class:`..errors.DeviceLossError` while the armed plan's lost device
    is still part of the mesh; once the mesh has been rebuilt without
    it, the hook goes quiet — retrying on the degraded mesh succeeds."""
    state = _ACTIVE
    if state is None or state.plan.device_loss is None:
        return
    if _tracing_now():
        return
    lost = state.plan.device_loss.device_id
    if any(getattr(d, "id", None) == lost for d in devices):
        log_event(logger, "fault_injected", kind="device_loss", device=lost)
        raise DeviceLossError(
            f"injected fault: simulated loss of device {lost} "
            "(mesh still routes work to it)",
            device_ids=(lost,),
        )


def active_nan_fault() -> Optional[NaNFault]:
    """Engine hook: the armed plan's NaN fault, or None. The engines
    translate it into a per-lane poison-epoch operand threaded into the
    XLA scan (`-1` = healthy lane), logging one `event=fault_injected`
    record when armed."""
    state = _ACTIVE
    if state is None or state.plan.nan is None:
        return None
    if _tracing_now():
        return None
    f = state.plan.nan
    log_event(
        logger, "fault_injected", kind="nan",
        case="all" if f.case is None else f.case, epoch=f.epoch,
    )
    return f


#: Whether the current (host) execution is a numerics-canary
#: re-dispatch. A ContextVar, not a flag on the fault state: the serve
#: tier's canary tick runs on its dispatcher thread concurrently with
#: request handlers, and only the canary's own dispatch may see the
#: armed DriftFault.
_CANARY_EXECUTION = contextvars.ContextVar(
    "yuma_canary_execution", default=False
)


@contextlib.contextmanager
def canary_scope():
    """Mark the enclosed dispatch as a numerics-canary re-execution —
    the only scope in which :func:`active_drift_fault` fires. Used by
    the supervisor's canary scheduler and the serve tier's background
    canary tick; production primaries never enter it."""
    token = _CANARY_EXECUTION.set(True)
    try:
        yield
    finally:
        _CANARY_EXECUTION.reset(token)


def in_canary_scope() -> bool:
    return _CANARY_EXECUTION.get()


def active_drift_fault() -> Optional[DriftFault]:
    """Engine hook: the armed plan's drift fault, inside a canary scope
    only (see :class:`DriftFault`). The batched XLA engine translates
    it into a per-lane flip-epoch operand (`-1` = clean lane), logging
    one `event=fault_injected` record when armed."""
    state = _ACTIVE
    if state is None or state.plan.drift is None:
        return None
    if not _CANARY_EXECUTION.get():
        return None
    if _tracing_now():
        return None
    f = state.plan.drift
    log_event(
        logger, "fault_injected", kind="drift",
        case="all" if f.case is None else f.case, epoch=f.epoch,
    )
    return f


def active_overload_fault() -> Optional[OverloadFault]:
    """Serve-admission hook: the armed plan's overload burst, exactly
    once per armed plan (a burst that re-fired on every subsequent
    request would never let the drill observe recovery). The serve tier
    translates it into `requests` synthetic admission tickets pushed
    through the real quota + bounded-queue path."""
    state = _ACTIVE
    if state is None or state.plan.overload is None or state.overload_fired:
        return None
    if _tracing_now():
        return None
    state.overload_fired = True
    f = state.plan.overload
    log_event(
        logger,
        "fault_injected",
        kind="overload",
        requests=f.requests,
        tenant=f.tenant,
    )
    return f


def maybe_crash_host(unit) -> None:
    """Fabric-scheduler hook: called (host level) immediately after a
    work-unit lease claim has been ledgered. SIGKILLs the process once
    the armed plan's claim count is reached — no Python teardown runs,
    matching a real preemption/OOM kill. The unit id is logged BEFORE
    the kill so the drill can assert which claim died."""
    state = _ACTIVE
    if state is None or state.plan.host_crash is None:
        return
    if _tracing_now():
        return
    state.claims_seen += 1
    if state.claims_seen >= state.plan.host_crash.after_claims:
        log_event(
            logger, "fault_injected", kind="host_crash", unit=unit,
            claims=state.claims_seen,
        )
        import signal

        # Flush stdio: SIGKILL gives buffered log lines no second chance.
        for stream in (sys.stdout, sys.stderr):
            try:
                stream.flush()
            except Exception:
                pass
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_tear_lease(path, unit) -> None:
    """Lease-store hook: called (host level) after each successful
    heartbeat renewal of this host's own lease. Truncates the live lease
    file ONCE per armed plan — simulated shared-store corruption of a
    claim record — so scanners exercise torn-lease tolerance and the
    holder exercises the LeaseExpired abandon path."""
    state = _ACTIVE
    if state is None or state.plan.lease_tear is None or state.lease_torn:
        return
    if _tracing_now():
        return
    tear = state.plan.lease_tear
    state.renewals_seen += 1
    if state.renewals_seen >= tear.after_renewals:
        state.lease_torn = True
        try:
            data = path.read_bytes()
        except OSError:
            return
        path.write_bytes(data[: tear.keep_bytes])
        log_event(
            logger, "fault_injected", kind="lease_tear", unit=unit,
            kept_bytes=tear.keep_bytes,
        )


def mangle_chunk_file(path, chunk_index: int) -> None:
    """Checkpoint hook: called after a chunk is published (written,
    checksummed, renamed). Truncates or bit-flips the file ONCE per
    chunk per armed plan — modeling corruption that happens between the
    publish and a later read, which is exactly what the checksum
    manifest exists to catch."""
    state = _ACTIVE
    if state is None or chunk_index in state.mangled_chunks:
        return
    plan = state.plan
    if chunk_index in plan.truncate_chunks:
        keep = plan.truncate_chunks[chunk_index]
        state.mangled_chunks.add(chunk_index)
        data = path.read_bytes()
        path.write_bytes(data[:keep])
        log_event(
            logger, "fault_injected", kind="truncate_chunk",
            chunk=chunk_index, kept_bytes=keep,
        )
    elif chunk_index in plan.corrupt_chunks:
        state.mangled_chunks.add(chunk_index)
        with open(path, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            last = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([last[0] ^ 0xFF]))
        log_event(
            logger, "fault_injected", kind="corrupt_chunk", chunk=chunk_index
        )
