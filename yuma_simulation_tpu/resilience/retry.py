"""The engine-degradation ladder: bounded retry with explicit demotion.

Engine selection is resolve-once (the dispatch planner,
:func:`..simulation.planner.plan_dispatch`): "auto" picks the flagship
fused Pallas case scan when eligible, and a failure at compile or
dispatch time aborts the whole run. The ladder makes the fallback
explicit and bounded instead: each case-scan engine has a fixed set of
strictly-less-demanding rungs below it (`DispatchPlan.ladder` — the
planner owns both the choice and the rungs beneath it)

    fused_scan_mxu  ->  fused_scan  ->  xla

and a classified engine failure (:func:`..errors.classify_failure`) on
one rung retries on the same rung up to `max_attempts_per_rung` times
(jittered exponential backoff — transient VMEM pressure from a
co-resident program does clear) before *demoting* one rung, emitting a
structured log record per demotion. Caller errors are never retried.
The bottom rung is the XLA scan, which has no device-resource
preconditions; if it too fails, :class:`..errors.EngineLadderExhausted`
carries the full demotion history.

The ladder deliberately lives OUTSIDE jit: rung choice is a host-side
control decision (each rung is its own compiled program), so retrying
costs nothing on the happy path — one predicate check per call.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, Optional, Sequence

from yuma_simulation_tpu.resilience.errors import (
    EngineLadderExhausted,
    classify_failure,
)

# Rung ordering/eligibility is owned by the dispatch planner since
# 0.10.0 (one decision surface for engine choice AND the ladder below
# it); re-exported here because the ladder is this module's vocabulary
# and existing callers import it from resilience.
from yuma_simulation_tpu.simulation.planner import (  # noqa: F401
    ENGINE_LADDER,
    ladder_from,
)
from yuma_simulation_tpu.telemetry.metrics import get_registry
from yuma_simulation_tpu.telemetry.runctx import span as telemetry_span
from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry knobs for the degradation ladder.

    `max_attempts_per_rung` attempts run on each rung before demotion;
    sleeps between attempts follow `backoff_base * backoff_factor**k`
    with `+/- jitter` fractional noise. `seed=None` (the default) draws
    the jitter PRNG from OS entropy per ladder run, so N replicas that
    fail a shared device simultaneously spread their retries instead of
    redispatching in lockstep; pass an explicit seed only for
    reproducible tests.
    """

    max_attempts_per_rung: int = 2
    backoff_base: float = 0.05
    backoff_factor: float = 2.0
    jitter: float = 0.25
    seed: Optional[int] = None

    def __post_init__(self) -> None:
        if self.max_attempts_per_rung < 1:
            raise ValueError("max_attempts_per_rung must be >= 1")
        if self.backoff_base < 0 or self.backoff_factor < 1:
            raise ValueError("backoff_base >= 0 and backoff_factor >= 1 required")
        if not 0 <= self.jitter < 1:
            raise ValueError("jitter must be in [0, 1)")

    def backoff_seconds(self, attempt: int, rng: random.Random) -> float:
        """Sleep before retry number `attempt` (0-based) on a rung."""
        base = self.backoff_base * self.backoff_factor**attempt
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


def default_retry_policy() -> RetryPolicy:
    """The production default: two attempts per rung, 50 ms base backoff."""
    return RetryPolicy()


@dataclasses.dataclass(frozen=True)
class DemotionRecord:
    """One structured record per ladder demotion (also logged via
    :func:`..utils.logging.log_event` as `event=engine_demoted`)."""

    from_engine: str
    to_engine: str
    attempts: int  # attempts spent on `from_engine` before demoting
    error_type: str
    message: str


def run_ladder(
    dispatch: Callable[[str], object],
    engine: str,
    policy: RetryPolicy,
    *,
    rungs: Optional[Sequence[str]] = None,
    label: str = "",
    deadline=None,
    on_failure: Optional[Callable[[BaseException, str, int], None]] = None,
):
    """Run `dispatch(rung)` down the ladder starting at `engine`.

    Returns `(result, engine_used, demotions)` where `demotions` is the
    list of :class:`DemotionRecord` accumulated on the way down (empty
    on the happy path). Non-engine failures propagate immediately;
    exhausting the ladder raises :class:`EngineLadderExhausted` chaining
    the last rung's failure.

    `deadline` (a :class:`..watchdog.Deadline`, default None = no hang
    supervision) runs every attempt under the deadline watchdog: an
    attempt that posts no heartbeat within its budget raises a typed
    `EngineStall`, which classifies as retryable — so a hung compile or
    wedged dispatch walks the same retry-then-demote ladder as a VMEM
    exhaustion instead of blocking the sweep forever.

    `on_failure(typed, rung, attempt)` is called for every CLASSIFIED
    failure, including ones a same-rung retry then absorbs — the
    supervisor's accounting hook: demotion records alone undercount
    (a stall killed on attempt 1 that succeeds on attempt 2 leaves no
    demotion), and the health report must account for every recovery
    action, not just the ones that moved rungs.
    """
    rungs = tuple(rungs) if rungs is not None else ladder_from(engine)
    # Constructed lazily on the FAILURE path only: the sharded
    # shard_map body re-enters this ladder at trace time, and host-RNG
    # state must not be built under a trace (jaxlint JX010) — the happy
    # path never needs the backoff jitter.
    rng: Optional[random.Random] = None
    demotions: list = []
    last_failure: Optional[BaseException] = None
    for rung_idx, rung in enumerate(rungs):
        last_failure = None
        for attempt in range(policy.max_attempts_per_rung):
            try:
                # One telemetry span per rung attempt — the innermost
                # level of the supervisor's sweep -> unit -> attempt ->
                # engine-rung chain (no-op without an active RunContext).
                with telemetry_span(
                    f"engine:{rung}", attempt=attempt + 1
                ):
                    if deadline is None:
                        return dispatch(rung), rung, demotions
                    from yuma_simulation_tpu.resilience.watchdog import (
                        run_with_deadline,
                    )

                    result = run_with_deadline(
                        # Bind by value: an abandoned (stalled) worker
                        # that wakes later must not dispatch whatever
                        # rung the ladder has since advanced to.
                        lambda r=rung: dispatch(r),
                        deadline,
                        label=f"{label}:{rung}" if label else rung,
                        attempt=attempt,
                    )
                    return result, rung, demotions
            except BaseException as exc:  # noqa: BLE001 — classified below
                typed = classify_failure(exc)
                if typed is None:
                    raise
                if on_failure is not None:
                    on_failure(typed, rung, attempt)
                last_failure = typed
                retries_left = policy.max_attempts_per_rung - attempt - 1
                if retries_left:
                    get_registry().counter(
                        "engine_retries", help="same-rung ladder retries"
                    ).inc()
                    if rng is None:
                        # Reviewed suppression: this only runs on the
                        # FAILURE path, is seeded deterministically from
                        # policy (no trace-time entropy to bake in), and
                        # its draws feed host-side sleep scheduling
                        # only — never a traced value.
                        rng = random.Random(policy.seed)  # jaxlint: disable=JX010
                    delay = policy.backoff_seconds(attempt, rng)
                    log_event(
                        logger,
                        "engine_retry",
                        level=logging.INFO,
                        label=label,
                        engine=rung,
                        attempt=attempt + 1,
                        backoff_s=f"{delay:.3f}",
                        error=type(typed).__name__,
                    )
                    if delay > 0:
                        time.sleep(delay)
        if rung_idx + 1 < len(rungs):
            record = DemotionRecord(
                from_engine=rung,
                to_engine=rungs[rung_idx + 1],
                attempts=policy.max_attempts_per_rung,
                error_type=type(last_failure).__name__,
                message=str(last_failure),
            )
            demotions.append(record)
            get_registry().counter(
                "engine_demotions", help="engine-ladder demotions"
            ).inc()
            log_event(
                logger,
                "engine_demoted",
                label=label,
                from_engine=record.from_engine,
                to_engine=record.to_engine,
                attempts=record.attempts,
                error=record.error_type,
            )
    raise EngineLadderExhausted(
        f"every engine rung failed ({' -> '.join(rungs)}); "
        f"last: {last_failure}",
        records=demotions,
    ) from last_failure
