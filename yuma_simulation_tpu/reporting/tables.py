"""Dividend aggregation + table rendering.

Behavior-parity equivalents of the reference's `_calculate_total_dividends`
(charts_utils.py:15-45), `generate_total_dividends_table`
(simulation_utils.py:319-381) and the two HTML table builders
(simulation_utils.py:115-316) — with one structural upgrade: the
total-dividends table batches all cases of a version through a single
`vmap`'d XLA computation instead of re-entering the Python epoch loop
14 times.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np
import pandas as pd

from yuma_simulation_tpu.models.config import SimulationHyperparameters, YumaConfig, YumaParams
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.simulation.sweep import simulate_batch, stack_scenarios

logger = logging.getLogger(__name__)

_STANDARD_VALIDATORS = ["Validator A", "Validator B", "Validator C"]


def calculate_total_dividends(
    validators: list[str],
    dividends_per_validator: dict[str, list[float]],
    base_validator: str,
    num_epochs: int,
) -> tuple[dict[str, float], dict[str, float]]:
    """Totals + percentage diff vs the base validator
    (reference charts_utils.py:15-45, incl. the zero-base 1e-6 fallback)."""
    totals = {
        v: float(sum(dividends_per_validator.get(v, [])[:num_epochs]))
        for v in validators
    }
    base = totals.get(base_validator)
    if base is None or base == 0.0:
        logger.warning(
            "Base validator '%s' has zero or missing total dividends.",
            base_validator,
        )
        base = 1e-6
    pct = {
        v: 0.0 if v == base_validator else (t - base) / base * 100.0
        for v, t in totals.items()
    }
    return totals, pct


def generate_total_dividends_table(
    cases: Sequence[Scenario],
    yuma_versions: list[tuple[str, YumaParams]],
    simulation_hyperparameters: SimulationHyperparameters,
    *,
    dtype=None,
    epoch_impl: str = "auto",
) -> pd.DataFrame:
    """Per-case total dividends across versions, standardized to
    "Validator A/B/C" columns (reference simulation_utils.py:319-381).

    All cases share the [40, 3, 2] shape, so each version is one batched
    scan over the stacked suite. `dtype`/`epoch_impl` exist for the f64
    oracle experiment (tools/csv_byte_parity.py), which computes this
    exact surface in float64 through the XLA engine — parameterizing
    here keeps the oracle the SAME computation as the shipped artifact.
    """
    import jax.numpy as jnp

    for case in cases:
        if len(case.validators) != 3:
            raise ValueError(
                f"Case '{case.name}' does not have exactly 3 validators."
            )

    W, S, ri, re = stack_scenarios(
        cases, jnp.float32 if dtype is None else dtype
    )
    rows: list[dict[str, object]] = [{"Case": case.name} for case in cases]
    columns = ["Case"]

    for yuma_version, yuma_params in yuma_versions:
        config = YumaConfig(
            simulation=simulation_hyperparameters, yuma_params=yuma_params
        )
        spec = variant_for_version(yuma_version)
        ys = simulate_batch(W, S, ri, re, config, spec, epoch_impl=epoch_impl)
        # Reference totals are Python-float sums of per-epoch float32
        # values; summing in float64 on host matches to well below 1e-6.
        totals = np.asarray(ys["dividends"], np.float64).sum(axis=1)  # [B, V]
        for std in _STANDARD_VALIDATORS:
            columns.append(f"{std} - {yuma_version}")
        for i in range(len(cases)):
            for j, std in enumerate(_STANDARD_VALIDATORS):
                rows[i][f"{std} - {yuma_version}"] = totals[i, j]

    return pd.DataFrame(rows)[columns]


# --- HTML assembly -----------------------------------------------------------


_SCROLL_TABLE_CSS = """
<style>
  body { margin: 0; padding: 0; overflow: hidden; }
  .yuma-table-scroll {
    background: #fff;
    width: 100%;
    height: 100vh;
    overflow: auto;
    border: 1px solid #ccc;
    position: relative;
    user-select: none;
    cursor: grab;
  }
  .yuma-table-scroll:active { cursor: grabbing; }
  .yuma-case-even td { background: #ffffff !important; }
  .yuma-case-odd td { background: #f0f0f0 !important; }
  .yuma-table-scroll img {
    user-select: none;
    -webkit-user-drag: none;
    pointer-events: none;
  }
  table { border-collapse: collapse; margin: 0; width: auto; }
  td, th { padding: 10px; vertical-align: top; text-align: center; }
</style>
"""

_DRAG_SCROLL_JS = """
<script>
  document.addEventListener('DOMContentLoaded', () => {
    const pane = document.querySelector('.yuma-table-scroll');
    let drag = null;
    pane.addEventListener('dragstart', e => e.preventDefault());
    pane.addEventListener('mousedown', e => {
      e.preventDefault();
      drag = {x: e.clientX, y: e.clientY,
              left: pane.scrollLeft, top: pane.scrollTop};
    });
    document.addEventListener('mouseup', () => { drag = null; });
    document.addEventListener('mousemove', e => {
      if (!drag) return;
      e.preventDefault();
      pane.scrollLeft = drag.left - (e.clientX - drag.x);
      pane.scrollTop = drag.top - (e.clientY - drag.y);
    });
  });
</script>
"""

_NOTEBOOK_CSS = """
<style>
  .yuma-table-scroll {
    background: #fff;
    width: 100%;
    overflow-x: auto;
    overflow-y: hidden;
    white-space: nowrap;
    border: 1px solid #ccc;
  }
  table { border-collapse: collapse; table-layout: auto; width: auto; }
  td, th { padding: 10px; vertical-align: top; text-align: center; }
  .yuma-case-even td { background: #ffffff !important; }
  .yuma-case-odd td { background: #f8f8f8 !important; }
</style>
"""


def _table_body(
    summary_table: pd.DataFrame,
    case_row_ranges: list[tuple[int, int, int]],
) -> str:
    def case_index(row: int) -> int:
        for start, end, idx in case_row_ranges:
            if start <= row <= end:
                return idx
        return 0

    head = "".join(f"<th>{col}</th>" for col in summary_table.columns)
    body = []
    for row in range(len(summary_table)):
        parity = "even" if case_index(row) % 2 == 0 else "odd"
        cells = "".join(
            f"<td>{summary_table[col][row]}</td>" for col in summary_table.columns
        )
        body.append(f"<tr class='yuma-case-{parity}'>{cells}</tr>")
    return (
        "<div class='yuma-table-scroll'><table>"
        f"<thead><tr>{head}</tr></thead>"
        f"<tbody>{''.join(body)}</tbody>"
        "</table></div>"
    )


def generate_draggable_html_table(
    table_data: dict[str, list[str]],
    summary_table: pd.DataFrame,
    case_row_ranges: list[tuple[int, int, int]],
) -> str:
    """Standalone HTML chart grid with drag-to-scroll
    (reference simulation_utils.py:115-248)."""
    del table_data  # kept for signature parity; summary_table carries the cells
    return _SCROLL_TABLE_CSS + _DRAG_SCROLL_JS + _table_body(
        summary_table, case_row_ranges
    )


def generate_ipynb_table(
    table_data: dict[str, list[str]],
    summary_table: pd.DataFrame,
    case_row_ranges: list[tuple[int, int, int]],
) -> str:
    """Notebook-friendly chart grid (reference simulation_utils.py:250-316)."""
    del table_data
    return _NOTEBOOK_CSS + _table_body(summary_table, case_row_ranges)
