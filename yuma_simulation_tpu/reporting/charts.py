"""Matplotlib chart generation (host-side, numpy inputs).

Behavior-parity equivalents of the four reference plotters
(charts_utils.py:48-335): same figure geometry, style cycling, tick
layout, normalization rules and base64 embedding, consuming the engine's
numpy outputs directly.
"""

from __future__ import annotations

import base64
import io
from typing import Optional, Sequence

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

from yuma_simulation_tpu.reporting.tables import calculate_total_dividends  # noqa: E402

#: (linestyle, marker, markersize, markeredgewidth) cycled per validator
#: (reference charts_utils.py:391-398).
_STYLE_CYCLE = [("-", "+", 12, 2), ("--", "x", 12, 1), (":", "o", 4, 1)]


def _styles_for(validators: Sequence[str]):
    return {
        v: _STYLE_CYCLE[i % len(_STYLE_CYCLE)] for i, v in enumerate(validators)
    }


def _default_xticks(ax, num_epochs: int) -> None:
    # [0, 1, 2, 5, 10, ...] (reference charts_utils.py:351-355)
    locs = [0, 1, 2] + list(range(5, num_epochs, 5))
    ax.set_xticks(locs)
    ax.set_xticklabels([str(i) for i in locs], fontsize=8)


def _to_base64_img() -> str:
    buf = io.BytesIO()
    plt.savefig(buf, format="png", transparent=True, bbox_inches="tight", dpi=100)
    buf.seek(0)
    encoded = base64.b64encode(buf.read()).decode("ascii")
    buf.close()
    plt.close()
    return (
        f'<img src="data:image/png;base64,{encoded}" '
        'style="max-width:1200px; height:auto;" draggable="false">'
    )


def plot_dividends(
    num_epochs: int,
    validators: Sequence[str],
    dividends_per_validator: dict[str, list[float]],
    case: str,
    base_validator: str,
    to_base64: bool = False,
) -> Optional[str]:
    """Dividend-per-1000-tao trajectories (reference charts_utils.py:48-122)."""
    plt.close("all")
    _, ax = plt.subplots(figsize=(14, 6))
    styles = _styles_for(validators)
    totals, pct = calculate_total_dividends(
        list(validators), dividends_per_validator, base_validator, num_epochs
    )

    x = None
    for idx, (validator, dividends) in enumerate(dividends_per_validator.items()):
        series = np.asarray([float(d) for d in dividends], float)
        if x is None:
            x = np.arange(len(series))
        linestyle, marker, markersize, markeredgewidth = styles[validator]
        diff = pct[validator]
        suffix = (
            f"(+{diff:.1f}%)" if diff > 0 else f"({diff:.1f}%)" if diff < 0 else "(Base)"
        )
        ax.plot(
            x + idx * 0.05,
            series,
            marker=marker,
            markeredgewidth=markeredgewidth,
            markersize=markersize,
            label=f"{validator}: Total = {totals[validator]:.6f} {suffix}",
            alpha=0.7,
            linestyle=linestyle,
        )

    if x is not None:
        _default_xticks(ax, len(x))
    ax.set_xlabel("Time (Epochs)")
    ax.set_ylim(bottom=0)
    ax.set_ylabel("Dividend per 1,000 Tao per Epoch")
    ax.set_title(case)
    ax.grid(True)
    ax.legend()
    if case.startswith("Case 4"):
        # fixed scale for the all-switch case (reference charts_utils.py:114-115)
        ax.set_ylim(0, 0.042)
    plt.subplots_adjust(hspace=0.3)

    if to_base64:
        return _to_base64_img()
    plt.show()
    return None


def _bond_series(
    bonds_per_epoch: Sequence[np.ndarray],
    num_validators: int,
    num_servers: int,
    normalize: bool,
) -> np.ndarray:
    """`[servers, validators, epochs]` bond trajectories, optionally
    normalized across validators per (server, epoch)
    (reference charts_utils.py:358-388)."""
    stacked = np.asarray(
        [np.asarray(b, float) for b in bonds_per_epoch]
    )  # [E, V, M]
    data = stacked.transpose(2, 1, 0)[:num_servers, :num_validators]  # [M, V, E]
    if normalize:
        totals = data.sum(axis=1, keepdims=True)
        data = np.divide(
            data, totals, out=data.copy(), where=totals > 1e-12
        )
    return data


def plot_bonds(
    num_epochs: int,
    validators: Sequence[str],
    servers: Sequence[str],
    bonds_per_epoch: Sequence[np.ndarray],
    case_name: str,
    to_base64: bool = False,
    normalize: bool = False,
) -> Optional[str]:
    """Per-server bond (ratio) trajectories (reference charts_utils.py:125-198)."""
    x = list(range(num_epochs))
    fig, axes = plt.subplots(
        1, len(servers), figsize=(14, 5), sharex=True, sharey=True
    )
    if len(servers) == 1:
        axes = [axes]

    data = _bond_series(bonds_per_epoch, len(validators), len(servers), normalize)
    styles = _styles_for(validators)
    handles, labels = [], []
    for s_idx, server in enumerate(servers):
        ax = axes[s_idx]
        for v_idx, validator in enumerate(validators):
            linestyle, marker, markersize, markeredgewidth = styles[validator]
            (line,) = ax.plot(
                x,
                data[s_idx][v_idx],
                alpha=0.7,
                marker=marker,
                markersize=markersize,
                markeredgewidth=markeredgewidth,
                linestyle=linestyle,
                linewidth=2,
            )
            if s_idx == 0:
                handles.append(line)
                labels.append(validator)
        _default_xticks(ax, num_epochs)
        ax.set_xlabel("Epoch")
        if s_idx == 0:
            ax.set_ylabel("Bond Ratio" if normalize else "Bond Value")
        ax.set_title(server)
        ax.grid(True)
        if normalize:
            ax.set_ylim(0, 1.05)

    fig.suptitle(
        f"Validators bonds per Server{' normalized' if normalize else ''}\n{case_name}",
        fontsize=14,
    )
    fig.legend(
        handles,
        labels,
        loc="lower center",
        ncol=len(validators),
        bbox_to_anchor=(0.5, 0.02),
    )
    plt.tight_layout(rect=(0, 0.05, 0.98, 0.95))

    if to_base64:
        return _to_base64_img()
    plt.show()
    return None


def plot_validator_server_weights(
    validators: Sequence[str],
    weights_epochs: Sequence[np.ndarray],
    servers: Sequence[str],
    num_epochs: int,
    case_name: str,
    to_base64: bool = False,
) -> Optional[str]:
    """Validator->server weight trajectories with adaptive y-ticks
    (reference charts_utils.py:201-301)."""
    styles = _styles_for(validators)
    W = np.asarray([np.asarray(w, float) for w in weights_epochs])  # [E, V, M]
    server2 = W[:num_epochs, : len(validators), 1]  # weight on Server 2

    # Build y-ticks: the two server lines plus any distinct intermediate
    # levels, labeled as percentages, spaced at least 0.05 apart.
    positions = [0.0, 1.0]
    tick_labels = [servers[0], servers[1]]
    for y in sorted(set(server2.flatten().tolist())):
        if y in (0.0, 1.0) or abs(y) < 0.02 or abs(y - 1.0) < 0.02:
            continue
        if all(abs(y - p) >= 0.05 for p in positions):
            positions.append(y)
            pct = y * 100
            tick_labels.append(
                f"{pct:.0f}%" if float(pct).is_integer() else f"{pct:.1f}%"
            )
    order = np.argsort(positions)
    positions = [positions[i] for i in order]
    tick_labels = [tick_labels[i] for i in order]

    fig_height = 1 if len(positions) <= 2 else 3
    _, ax = plt.subplots(figsize=(14, fig_height))
    ax.set_ylim(-0.05, 1.05)

    for v_idx, validator in enumerate(validators):
        linestyle, marker, markersize, markeredgewidth = styles[validator]
        ax.plot(
            range(num_epochs),
            server2[:, v_idx],
            label=validator,
            marker=marker,
            linestyle=linestyle,
            markersize=markersize,
            markeredgewidth=markeredgewidth,
            linewidth=2,
        )

    ax.set_yticks(positions)
    ax.set_yticklabels(tick_labels)
    _default_xticks(ax, num_epochs)
    ax.set_xlabel("Epoch")
    ax.set_title(f"Validators Weights to Servers \n{case_name}")
    ax.legend()
    ax.grid(True)

    if to_base64:
        return _to_base64_img()
    plt.show()
    return None


def plot_incentives(
    servers: Sequence[str],
    server_incentives_per_epoch: Sequence[np.ndarray],
    num_epochs: int,
    case_name: str,
    to_base64: bool = False,
) -> Optional[str]:
    """Server incentive trajectories (reference charts_utils.py:304-335)."""
    x = np.arange(num_epochs)
    _, ax = plt.subplots(figsize=(14, 3))
    incentives = np.asarray(
        [np.asarray(e, float) for e in server_incentives_per_epoch]
    )  # [E, M]
    for s_idx, server in enumerate(servers):
        ax.plot(x, incentives[:, s_idx], label=server)
    _default_xticks(ax, num_epochs)
    ax.set_xlabel("Epoch")
    ax.set_ylabel("Server Incentive")
    ax.set_title(f"Server Incentives\n{case_name}")
    ax.set_ylim(-0.05, 1.05)
    ax.legend()
    ax.grid(True)

    if to_base64:
        return _to_base64_img()
    plt.show()
    return None
