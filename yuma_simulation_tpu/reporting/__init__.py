"""Host-side reporting: dividend tables, matplotlib charts, HTML assembly."""

from yuma_simulation_tpu.reporting.charts import (  # noqa: F401
    plot_bonds,
    plot_dividends,
    plot_incentives,
    plot_validator_server_weights,
)
from yuma_simulation_tpu.reporting.tables import (  # noqa: F401
    calculate_total_dividends,
    generate_draggable_html_table,
    generate_ipynb_table,
    generate_total_dividends_table,
)
