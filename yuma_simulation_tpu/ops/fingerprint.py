"""The ONE bit-level tensor fingerprint spelling every engine shares.

The paper's cross-engine contract (fused_scan_mxu == fused_scan == xla,
bitwise) is enforced by tests but — before the numerics flight recorder
— observed by nothing in production: a single flipped dividend cell
would change no shape, no norm anyone checks, and no log line. The
fingerprint here is the observable that closes that gap, and it is
deliberately NOT a hash:

- every float is **bit-cast to an unsigned integer** (f32 -> u32; f64
  folds its u64 bits to u32 by xor-ing the halves), then
- the integers are **summed mod 2^32**.

Wrapping integer addition is exact, associative and commutative, so the
reduction is *partition- and chunk-invariant by construction*: a
miner-sharded psum, a streamed per-chunk capture and a monolithic scan
all produce the identical fingerprint for identical bits — no
`miner_sum`-style blocked spelling needed (the property the float
reductions in :mod:`.normalize` have to buy structurally, integers get
for free). And because adjacent same-sign f32 values differ by exactly
1 in their bit patterns, the fingerprint DELTA between two captures of
the same tensor is the signed sum of per-element ulp distances — a
single-ulp lane flip moves the fingerprint by exactly 1, which is what
``tools/driftreport.py`` renders as the ulp distance per lane.

Every capture site (the XLA scan step, the fused-kernel wrapper, the
sharded Monte-Carlo paths) must call THESE functions; a second spelling
would fork the observable exactly the way forked reductions fork the
consensus (see `dyadic_grid_denom`'s "one shared spelling" rule).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def bits_u32(x: jnp.ndarray) -> jnp.ndarray:
    """`x`'s raw bits as uint32, elementwise. f32 bit-casts directly;
    f64 (the x64 parity harness) folds the u64 bits to u32 by xor-ing
    the high and low halves — still a pure function of the bits, so
    bitwise-equal tensors fingerprint equal and any single-bit flip
    changes the result. Non-float inputs are cast to f32 first (the
    stats streams are float-valued by contract)."""
    dtype = jnp.asarray(x).dtype
    if dtype == jnp.float32:
        return lax.bitcast_convert_type(x, jnp.uint32)
    if dtype == jnp.float64:
        b = lax.bitcast_convert_type(x, jnp.uint64)
        return (
            (b & jnp.uint64(0xFFFFFFFF)) ^ (b >> jnp.uint64(32))
        ).astype(jnp.uint32)
    return lax.bitcast_convert_type(
        jnp.asarray(x, jnp.float32), jnp.uint32
    )


def fingerprint_u32(x: jnp.ndarray, axes=None) -> jnp.ndarray:
    """Wrapping-u32 sum of `x`'s bits over `axes` (None = all axes).
    Order-independent by construction — see the module docstring."""
    return jnp.sum(bits_u32(x), axis=axes, dtype=jnp.uint32)


def flip_ulp(x: jnp.ndarray) -> jnp.ndarray:
    """`x` with every element's bit pattern incremented by one — the
    adjacent float for positive finite values (one ulp up). The
    fault-injection primitive behind `resilience.faults.DriftFault`:
    the smallest representable drift the numerics canary must catch."""
    dtype = jnp.asarray(x).dtype
    if dtype == jnp.float64:
        return lax.bitcast_convert_type(
            lax.bitcast_convert_type(x, jnp.uint64) + jnp.uint64(1),
            jnp.float64,
        )
    return lax.bitcast_convert_type(
        lax.bitcast_convert_type(x, jnp.uint32) + jnp.uint32(1),
        jnp.float32,
    )


def ulp_delta(a: int, b: int) -> int:
    """Host-side: the signed mod-2^32 distance between two fingerprints
    — the summed per-element ulp distance when the underlying tensors
    differ only in same-sign neighbourhoods (the drift-canary case).
    Returns the minimal-magnitude representative in [-2^31, 2^31)."""
    d = (int(b) - int(a)) % (1 << 32)
    return d - (1 << 32) if d >= (1 << 31) else d
