"""Stake-weighted-median consensus, vectorized whole-array on the VPU.

The reference computes consensus with a per-miner Python `while` bisection
(reference yumas.py:83-95 and the four duplicates), which is the measured
hot spot (~83% of kernel time on CPU). Here the bisection runs as a fixed
number of whole-array iterations: each step evaluates the stake support of
every miner at once with one masked whole-array reduction instead of `M`
Python loop bodies. The support test itself runs on canonical fixed-point
integers (:func:`support_fixed_stakes`) shared by every consensus engine
in the package, so the strict `support > kappa` decision is exact and
independent of reduction order — no engine pair can disagree at
knife-edge ties (the round-3 CROSS_ENGINE.json failure mode).

Exactness: the reference loop `while (c_high - c_low) > 1/precision` from the
interval [0, 1] runs exactly `ceil(log2(precision))` halvings (17 for the
default precision of 100 000, yumas.py:14). Every midpoint is a dyadic
rational `k/2^17`, exactly representable in float32, so the fixed-iteration
vector form produces bit-identical `c_high` values away from knife-edge
ties; comparisons are strict `>` on both the weight and the kappa test, as
in the reference (yumas.py:89-91). AT a knife-edge tie (exact support
within f32 rounding noise of kappa) no deterministic implementation can
track the reference's order-dependent intermediate rounding — the
canonical test keeps its final-rounding semantics (see
:func:`support_rounded`) and discards only that noise.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
from jax import lax

from yuma_simulation_tpu.ops.normalize import miner_sum


def _bisection_iterations(precision: int) -> int:
    # Halving [0,1] k times gives interval width 2^-k; the loop stops once
    # that is <= 1/precision.
    return int(math.ceil(math.log2(precision)))


#: Fixed-point bits of the canonical support test (see below). 2^30 keeps
#: the sum of normalized stakes (<= ~1 + V/2^31) inside int32.
SUPPORT_FIXED_BITS = 30


def support_fixed_stakes(S: jnp.ndarray) -> jnp.ndarray:
    """Canonical fixed-point stake encoding for the consensus support test.

    Every engine (XLA bisection, sorted closed form, Pallas consensus
    kernel, fused epoch scan) evaluates the reference's strict support
    test `sum(S[W > c]) > kappa` (reference yumas.py:89-91) on THESE
    integers rather than on a floating-point sum: integer addition is
    exact and order-independent, so the test's outcome cannot depend on
    the engine's reduction tree. Floating-point support sums were the
    diagnosed source of cross-engine consensus flips at knife-edge
    `support == kappa` ties (CROSS_ENGINE.json, round 3): two correct f32
    summations of the same addends can land on opposite sides of the
    strict `>`.

    Precondition: `S` normalized (`S / S.sum()`, as every caller does).
    Accuracy: each addend is rounded to the nearest multiple of 2^-30, so
    the fixed-point sum differs from the exact real sum of the f32 stakes
    by <= V * 2^-31 — tighter than ANY f32 summation of V addends, whose
    rounding error scales with V * eps * partial-sum magnitudes (~V *
    6e-8). `S * 2^30` is an exact exponent shift in f32/f64, and the
    nearest-integer round is deterministic on every backend.
    """
    scale = jnp.asarray(2.0**SUPPORT_FIXED_BITS, S.dtype)
    return jnp.round(S * scale).astype(jnp.int32)


def support_rounded(support_int: jnp.ndarray, dtype) -> jnp.ndarray:
    """The canonical support VALUE the strict kappa comparison sees: the
    exact integer sum rounded ONCE to `dtype` (an int->float convert plus
    an exact exponent shift — both deterministic on every backend).

    The final rounding is semantically load-bearing, not a convenience:
    the reference compares an f32 support tensor against kappa
    (yumas.py:88-91), so a sum whose exact value sits within half an f32
    ulp ABOVE kappa still rounds onto kappa and fails the strict `>`.
    Hand stakes like [0.4, 0.3, 0.2, 0.1] manufacture exactly this
    (subset sums 0.5000000075 -> f32 0.5), and the kernel golden tests
    pin that behavior. Comparing the raw integers would resolve such
    ties by exact arithmetic instead and diverge from the reference.
    What this deliberately does NOT reproduce is the reference's
    order-dependent INTERMEDIATE rounding noise — that noise is exactly
    what made the round-3 engines disagree with each other.
    """
    scale = jnp.asarray(2.0**-SUPPORT_FIXED_BITS, dtype)
    return support_int.astype(dtype) * scale


def dyadic_grid_fits_int32(count: int, grid_bits: int) -> bool:
    """Whether `count` dyadic grid values `k * 2^-grid_bits` (k <=
    2^grid_bits) can be summed exactly in int32 — the shared guard of
    every exact-quantization denominator site."""
    return (count << grid_bits) < 2**31


def dyadic_grid_denom(C: jnp.ndarray, grid_bits: int) -> jnp.ndarray:
    """EXACT last-axis sum of dyadic grid values `k * 2^-grid_bits`,
    rounded once to `C.dtype` — the `_rust64_quantize` trick generalized
    (r4 verdict item 2). The integer sum is order-independent, so a
    miner-sharded psum and a single-device reduce produce the identical
    denominator; and whenever naive f32 partial sums would stay below
    2^24 (count <= 2^(24 - grid_bits)) the result is bitwise the naive
    sum. One shared spelling — quantize_u16, the fused Pallas kernels
    and any future engine must all call this, or the cross-engine
    bitwise consensus contract drifts. Callers guard with
    :func:`dyadic_grid_fits_int32` on the REAL (unpadded) value count
    (padded columns are zeroed and contribute k = 0).
    """
    k = jnp.round(C * jnp.asarray(float(2**grid_bits), C.dtype))
    K = jnp.sum(  # dtype pinned: x64 would promote i32 sums to i64,
        # which Mosaic cannot lower
        k.astype(jnp.int32), axis=-1, keepdims=True, dtype=jnp.int32
    )
    return K.astype(C.dtype) * jnp.asarray(2.0**-grid_bits, C.dtype)


#: Above this many `V x M` cells the sorted closed form's XLA program hits
#: pathological remote-compile times (minutes to hours at >= 512x8192 on
#: the remote-tunnel TPU runtime, vs seconds for bisection at every rung —
#: DESIGN.md "Operational caveats"). Both implementations produce bitwise
#: identical values (tests/unit/test_consensus_fuzz.py), so the gate only
#: trades compile time against a slightly cheaper runtime at small shapes.
SORTED_COMPILE_PATHOLOGY_CELLS = 512 * 8192


def default_consensus_impl(num_validators: int, num_miners: int) -> str:
    """Shape-gated consensus default: "sorted" below the documented
    compile-pathology threshold, "bisect" at or above it."""
    cells = num_validators * num_miners
    return "sorted" if cells < SORTED_COMPILE_PATHOLOGY_CELLS else "bisect"


def resolve_consensus_impl(
    consensus_impl: str, num_validators: int, num_miners: int
) -> str:
    """The one resolution/validation point every engine entry point
    shares: "auto" becomes the shape-gated default, "sorted"/"bisect"
    pass through, anything else raises (instead of silently running
    some dispatch fallback under the wrong label)."""
    if consensus_impl == "auto":
        return default_consensus_impl(num_validators, num_miners)
    if consensus_impl not in ("sorted", "bisect"):
        raise ValueError(
            f"unknown consensus_impl {consensus_impl!r}; "
            "expected 'auto', 'sorted' or 'bisect'"
        )
    return consensus_impl


def stake_weighted_median(
    W: jnp.ndarray,
    S: jnp.ndarray,
    kappa,
    precision: int = 100_000,
    *,
    precision_config: Optional[lax.Precision] = lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """Per-miner consensus weight via vectorized bisection.

    Args:
      W: row-normalized weights `[..., V, M]`.
      S: normalized stake `[..., V]`.
      kappa: consensus threshold (scalar or batched scalar `[...]`).
      precision: the reference's `consensus_precision` (static).
      precision_config: retained for signature compatibility; inert. The
        support test runs on the canonical fixed-point integers
        (:func:`support_fixed_stakes`), not a matmul, so no float
        contraction precision applies.

    Returns:
      `C`: consensus weight per miner `[..., M]` (the bisection's final
      `c_high`), in `W.dtype`.
    """
    del precision_config  # support test is canonical fixed-point; see docstring
    iters = _bisection_iterations(precision)
    dtype = W.dtype
    batch_m = W.shape[:-2] + W.shape[-1:]
    S_int = support_fixed_stakes(S)  # [..., V]
    kappa = jnp.asarray(kappa, dtype)
    if kappa.ndim:  # batched kappa broadcasts against [..., M]
        kappa = kappa[..., None]

    def body(_, carry):
        c_lo, c_hi = carry
        c_mid = (c_hi + c_lo) / 2.0
        support = jnp.sum(
            jnp.where(
                W > c_mid[..., None, :],
                S_int[..., :, None],
                jnp.zeros((), jnp.int32),
            ),
            axis=-2,
        )
        above = support_rounded(support, dtype) > kappa
        return jnp.where(above, c_mid, c_lo), jnp.where(above, c_hi, c_mid)

    c_lo = jnp.zeros(batch_m, dtype)
    c_hi = jnp.ones(batch_m, dtype)
    _, c_hi = lax.fori_loop(0, iters, body, (c_lo, c_hi), unroll=True)
    return c_hi


def quantize_u16(
    C: jnp.ndarray,
    *,
    sum_dtype: Optional[jnp.dtype] = None,
    out_dtype: jnp.dtype = jnp.float32,
    miner_mask: Optional[jnp.ndarray] = None,
    grid_bits: Optional[int] = None,
) -> jnp.ndarray:
    """Sum-normalize C and truncate onto the u16 grid.

    Mirrors `(C / C.sum() * 65_535).int() / 65_535` (reference yumas.py:97
    etc.): truncation toward zero, not rounding. `sum_dtype` selects the
    dtype of the normalizing division — the Yuma-0 variant performs it in
    float64 (yumas.py:81) while all others use float32; both end up float32
    after the integer division, which `out_dtype` reproduces.

    `grid_bits` (the engines pass `ceil(log2(consensus_precision))`)
    declares that every C value is a dyadic grid point `k * 2^-grid_bits`
    — true for all three consensus engines, whose outputs are bisection
    grid values. The f32 normalizing sum is then computed EXACTLY as an
    int32 sum of the `k` (the `_rust64_quantize` trick generalized, r4
    verdict item 2), rounded once to f32: order-independent by
    construction, so a miner-sharded psum and the single-device reduce
    cannot disagree. For `M <= 2^(31 - grid_bits - ...)`, i.e. whenever
    the naive f32 partial sums stay below 2^24 (M <= 128 at the default
    17-bit grid — every built-in case), the exact sum is bitwise the
    naive sum, so golden surfaces are unchanged. The f64 path needs no
    treatment: an f64 sum of u17-grid dyadics is already exact in any
    order (K < 2^53). Falls back to the naive sum when the int32 bound
    `M * 2^grid_bits < 2^31` fails.

    `miner_mask` (`[..., M]`, 1 = real miner, 0 = padding) zeroes padded
    columns *before* the sum so padding cannot perturb the grid of real
    miners. (A genuinely all-zero weight column still receives the small
    nonzero `c_high = 2^-17` exactly as in the reference.)
    """
    if miner_mask is not None:
        C = jnp.where(miner_mask.astype(bool), C, jnp.zeros_like(C))
    if sum_dtype is not None:
        C = C.astype(sum_dtype)
    if (
        grid_bits is not None
        and sum_dtype is None
        and dyadic_grid_fits_int32(C.shape[-1], grid_bits)
    ):
        # The int32 bound must hold for the worst case statically, so
        # the gate uses the (possibly padded) shape width even though
        # masked columns contribute k = 0 — a subnet padded past the
        # bound therefore falls back while its unpadded run would not
        # (conservative, never unsafe; heterogeneous padded suites run
        # the XLA engine only, so no cross-engine pairing exists there).
        denom = dyadic_grid_denom(C, grid_bits)
    else:
        # Partition-invariant fallback: beyond the int32 bound the sum
        # still must not depend on a miner mesh's psum order, so it
        # uses the blocked miner_sum spelling rather than a plain
        # backend-ordered reduce (bitwise the plain sum at M < 16,
        # which includes every golden case; f64 sums of dyadics are
        # exact in any order so the rust64 path is unaffected).
        denom = miner_sum(C, keepdims=True)
    scaled = C / denom * 65_535
    return scaled.astype(jnp.int32).astype(out_dtype) / 65_535


def consensus_weights(
    W: jnp.ndarray,
    S: jnp.ndarray,
    kappa,
    precision: int = 100_000,
    *,
    sum_dtype: Optional[jnp.dtype] = None,
    miner_mask: Optional[jnp.ndarray] = None,
    precision_config: Optional[lax.Precision] = lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """Bisection consensus followed by u16 quantization (the full C stage)."""
    C = stake_weighted_median(
        W, S, kappa, precision, precision_config=precision_config
    )
    return quantize_u16(
        C,
        sum_dtype=sum_dtype,
        out_dtype=W.dtype,
        miner_mask=miner_mask,
        grid_bits=_bisection_iterations(precision),
    )


def stake_weighted_median_sorted(
    W: jnp.ndarray,
    S: jnp.ndarray,
    kappa,
    precision: int = 100_000,
) -> jnp.ndarray:
    """Exact closed-form consensus via a per-column sort (opt-in fast path).

    The bisection converges to the unique dyadic grid point `g = k/2^p`
    (p = ceil(log2(precision))) with strict stake support `<= kappa` at `g`
    and `> kappa` at `g - 2^-p`. The support function
    `support(c) = sum(S[W > c])` is a non-increasing step function whose
    breakpoints are the weight values, so:

    - if `support(0+) <= kappa` (total stake on strictly positive weights
      never exceeds kappa) the bisection walks `c_high` all the way down to
      the smallest grid point `2^-p`;
    - otherwise the crossing point is `w* = min{w in column : support(w) <=
      kappa}` (> 0), and the answer is `w*` rounded up to the grid (staying
      put when `w*` already lies on it).

    One `sort` + two scans per column replaces the 17 support contractions.
    Produces values identical to :func:`stake_weighted_median`.

    Operational note: on remote-compile TPU runtimes this program's XLA
    compile time grows pathologically with shape (minutes-to-hours at
    >= 512x8192, vs seconds for the bisection at every measured shape —
    DESIGN.md "Memory envelope"). Prefer ``consensus_impl="bisect"`` or
    the fused Pallas paths for very large subnets.
    """
    iters = _bisection_iterations(precision)
    scale = float(2**iters)
    dtype = W.dtype
    kappa = jnp.asarray(kappa, dtype)
    batched_kappa = kappa.ndim > 0
    kap = kappa[..., None, None] if batched_kappa else kappa

    # Sort each miner column by weight, descending, carrying stakes along.
    # One stable multi-operand sort instead of argsort + two gathers: the
    # gathers are catastrophically slow on TPU (~100x) while a co-sorted
    # value operand is free; the permutation is identical (stable sort on
    # the negated key == stable argsort of the negated key).
    # Stakes ride along in the canonical fixed-point encoding so the
    # cumulative support below is the exact integer sum — bitwise the
    # same test every other engine runs, in any summation order.
    S_int = support_fixed_stakes(S)
    Wt = jnp.swapaxes(W, -1, -2)  # [..., M, V]
    St = jnp.broadcast_to(S_int[..., None, :], Wt.shape)
    w_neg, s_sorted = lax.sort(
        (-Wt, St), dimension=-1, num_keys=1, is_stable=True
    )
    w_sorted = -w_neg
    # Strict support at w_sorted[k] = total stake of entries with weight
    # strictly greater. Tied entries all share the support of the first
    # element of their run; forward-fill that value with a prefix max (the
    # exclusive cumsum is non-decreasing along the sorted order).
    excl = jnp.cumsum(s_sorted, axis=-1) - s_sorted
    first_of_run = jnp.concatenate(
        [
            jnp.ones_like(w_sorted[..., :1], dtype=bool),
            w_sorted[..., 1:] != w_sorted[..., :-1],
        ],
        axis=-1,
    )
    run_support = jnp.where(
        first_of_run, excl, jnp.iinfo(jnp.int32).min
    )
    support_at = lax.associative_scan(jnp.maximum, run_support, axis=-1)
    # Smallest qualifying weight; support at the max weight is 0 <= kappa,
    # so one always exists. The canonical rounded support value makes the
    # `<=` here the exact complement of the other engines' strict `>`.
    qualifies = support_rounded(support_at, dtype) <= kap
    w_star = jnp.min(jnp.where(qualifies, w_sorted, jnp.inf), axis=-1)

    # Round w* up to the dyadic grid without trusting f32 rounding of the
    # product near integers: take floor(w*·2^p) and pick the smallest of
    # {k-1, k, k+1} whose exact grid value is >= w* (grid values k·2^-p are
    # exactly representable, so these comparisons are exact).
    k = jnp.floor(w_star * scale)
    cand = jnp.stack([k - 1, k, k + 1], axis=-1)
    grid = (cand / scale).astype(dtype)
    ok = grid >= w_star[..., None]
    g = jnp.min(jnp.where(ok, grid, jnp.inf), axis=-1)

    # The support(0+) <= kappa regime: c_high bottoms out at 2^-p.
    support0 = jnp.sum(
        jnp.where(W > 0, S_int[..., :, None], jnp.zeros((), jnp.int32)),
        axis=-2,
    )
    kap0 = kappa[..., None] if batched_kappa else kappa
    floor_c = jnp.asarray(1.0 / scale, dtype)
    return jnp.where(
        support_rounded(support0, dtype) > kap0,
        jnp.maximum(g, floor_c),
        floor_c,
    ).astype(dtype)
