"""Liquid-alpha: consensus-dependent per-miner bond EMA rates.

Mirrors the liquid-alpha block duplicated through the reference kernels
(yumas.py:118-140, 231-253, 345-367, 546-568): fit a logistic between the
0.25/0.75 consensus quantiles (with overrides and a degenerate-quantile
fallback to the 0.99 quantile) and map each miner's consensus weight to an
EMA rate `bond_alpha in [1-alpha_high, 1-alpha_low]`.

Parity notes:
- `a`/`b` combine float64 Python `math.log` scalars with the float32
  quantile tensors, so they materialize as float32 — reproduced here by
  computing the logs in Python when the bounds are static floats;
- the logistic is evaluated as `e ** (-a*C + b)` (a power with base
  `math.e`), not `exp`, matching the reference's rounding behavior;
- the degenerate-quantile check is a data-dependent branch in the
  reference; under `jit` it becomes a `jnp.where` on identically computed
  quantities.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp


def _logit(x) -> float:
    # log(1/x - 1), the inverse sigmoid, on a static Python float.
    return math.log(1.0 / x - 1.0)


def _masked_quantile(C: jnp.ndarray, q: float, mask: jnp.ndarray) -> jnp.ndarray:
    """`jnp.quantile(C[mask], q)` with a traced mask: linear interpolation
    at position `q * (n - 1)` over the real entries only, so padded miner
    columns cannot shift the liquid-alpha quantiles."""
    dtype = C.dtype
    vals = jnp.where(mask.astype(bool), C, jnp.asarray(jnp.inf, dtype))
    s = jnp.sort(vals, axis=-1)
    n = mask.astype(dtype).sum(axis=-1)
    p = jnp.asarray(q, dtype) * (n - 1.0)
    lo = jnp.floor(p).astype(jnp.int32)
    hi = jnp.ceil(p).astype(jnp.int32)
    frac = p - lo.astype(dtype)
    v_lo = jnp.take_along_axis(s, lo[..., None], axis=-1)[..., 0]
    v_hi = jnp.take_along_axis(s, hi[..., None], axis=-1)[..., 0]
    return v_lo * (1.0 - frac) + v_hi * frac


def liquid_alpha_rate(
    C: jnp.ndarray,
    alpha_low,
    alpha_high,
    *,
    override_consensus_high: Optional[float] = None,
    override_consensus_low: Optional[float] = None,
    miner_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-miner EMA rate from quantized consensus.

    Args:
      C: quantized consensus weights `[..., M]`.
      alpha_low / alpha_high: sigmoid clamp bounds (static floats in the
        reference; traced scalars are also supported for sweeps).
      override_consensus_high / low: optional static quantile overrides.
      miner_mask: optional `[..., M]` 0/1 mask; quantiles are then taken
        over real miners only (padded suites).

    Returns:
      `(bond_alpha[..., M], a, b)` where `a`, `b` are the fitted logistic
      coefficients (scalars, or `[...]` when batched).
    """
    dtype = C.dtype

    def quant(q):
        if miner_mask is None:
            return jnp.quantile(C, q, axis=-1)
        return _masked_quantile(C, q, miner_mask)

    # Degenerate spread: fall back to the 0.99 quantile (yumas.py:132-133).
    # The reference runs this check AFTER substituting the overrides, so
    # it applies even when consensus_high is overridden (an override equal
    # to the low side still collapses the spread and must fall back). The
    # comparison's operand types mirror the reference per case: with BOTH
    # sides overridden it compares two raw Python floats (f64) — decided
    # statically here, so overrides distinct in f64 but equal after f32
    # rounding do NOT fire the fallback; with at most one override the
    # comparison involves an f32 quantile tensor and stays traced.
    if override_consensus_high is not None and override_consensus_low is not None:
        c_low = jnp.asarray(override_consensus_low, dtype)
        c_high = (
            quant(0.99)
            if override_consensus_high == override_consensus_low
            else jnp.asarray(override_consensus_high, dtype)
        )
    else:
        if override_consensus_high is not None:
            c_high = jnp.asarray(override_consensus_high, dtype)
        else:
            c_high = quant(0.75)
        if override_consensus_low is not None:
            c_low = jnp.asarray(override_consensus_low, dtype)
        else:
            c_low = quant(0.25)
        c_high = jnp.where(c_high == c_low, quant(0.99), c_high)

    if isinstance(alpha_high, (int, float)) and isinstance(alpha_low, (int, float)):
        logit_high = _logit(alpha_high)
        logit_low = _logit(alpha_low)
    else:
        alpha_high = jnp.asarray(alpha_high, dtype)
        alpha_low = jnp.asarray(alpha_low, dtype)
        logit_high = jnp.log(1.0 / alpha_high - 1.0)
        logit_low = jnp.log(1.0 / alpha_low - 1.0)

    a = (logit_high - logit_low) / (c_low - c_high)
    b = logit_low + a * c_low
    if a.ndim:  # batched quantiles broadcast against [..., M]
        a_b = a[..., None]
        b_b = b[..., None]
    else:
        a_b, b_b = a, b
    alpha = 1.0 / (1.0 + jnp.asarray(math.e, dtype) ** (-a_b * C + b_b))
    bond_alpha = 1.0 - jnp.clip(alpha, alpha_low, alpha_high)
    return bond_alpha.astype(dtype), a, b
