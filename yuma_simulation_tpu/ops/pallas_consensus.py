"""Fused Pallas TPU kernel for the consensus bisection.

The bisection in :mod:`yuma_simulation_tpu.ops.consensus` lowers to 17
XLA ops over the full `[V, M]` array, each a round trip through HBM when
the array is large. This kernel keeps one `[V, TILE_M]` weight block
resident in VMEM and runs all 17 halvings on it before moving to the next
block — a single HBM read of W per epoch, with the support reduction on
the VPU (8x128 lanes, reduction over the validator sublane axis).

Numerics follow the reference loop (reference yumas.py:83-95), with the
canonical fixed-point support test shared by every engine in the package
(ops/consensus.py — exact away from knife-edge ties, deterministic at
them):
midpoints are dyadic rationals `k/2^17` (exact in f32), comparisons are
strict `>` on both the weight and the kappa test, and the returned value
is the final `c_high`.

The kernel is an opt-in fast path (`consensus_impl="pallas"` on
`yuma_epoch` / the engine entry points); `interpret=True` runs it on CPU
for tests.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from yuma_simulation_tpu.ops.consensus import (
    support_fixed_stakes,
    support_rounded,
)

_LANES = 128
_SUBLANES = 8


def _round_up(x: int, mult: int) -> int:
    return (x + mult - 1) // mult * mult


def _consensus_kernel(kappa_ref, s_ref, w_ref, c_ref, *, iters: int):
    """One grid step: full bisection for a `[V, TILE_M]` weight block."""
    W = w_ref[:]  # [V, TILE_M], VMEM-resident for all iterations
    S = s_ref[:]  # [V, 1]
    # Canonical fixed-point support test: the SHARED helpers (plain jnp
    # ops, trace fine under Mosaic) guarantee this kernel's support
    # decisions stay bitwise those of every other consensus engine even
    # if the canonical definition evolves.
    S_int = support_fixed_stakes(S)
    kappa = kappa_ref[0]

    tile = (1, W.shape[1])
    c_lo = jnp.zeros(tile, W.dtype)
    c_hi = jnp.ones(tile, W.dtype)

    def body(_, carry):
        c_lo, c_hi = carry
        c_mid = (c_hi + c_lo) * 0.5
        support = jnp.sum(  # strict >, as the reference
            jnp.where(W > c_mid, S_int, jnp.zeros((), jnp.int32)),
            axis=0,
            keepdims=True,
            dtype=jnp.int32,  # x64 would promote to i64 (no Mosaic)
        )  # [1, TILE_M]
        above = support_rounded(support, W.dtype) > kappa
        return jnp.where(above, c_mid, c_lo), jnp.where(above, c_hi, c_mid)

    _, c_hi = jax.lax.fori_loop(0, iters, body, (c_lo, c_hi), unroll=True)
    c_ref[:] = c_hi


@functools.partial(
    jax.jit, static_argnames=("precision", "tile_m", "interpret")
)
def stake_weighted_median_pallas(
    W: jnp.ndarray,
    S: jnp.ndarray,
    kappa,
    precision: int = 100_000,
    *,
    tile_m: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in for :func:`ops.consensus.stake_weighted_median` on `[V, M]`.

    Pads V to the f32 sublane multiple (zero stake: contributes nothing to
    support) and M to the miner tile (zero weights: sliced off after), then
    sweeps miner tiles on a 1-D grid.
    """
    if W.ndim != 2:
        raise ValueError(f"pallas consensus expects [V, M] weights, got {W.shape}")
    V, M = W.shape
    dtype = W.dtype
    iters = int(math.ceil(math.log2(precision)))

    tile = min(tile_m, _round_up(M, _LANES))
    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, tile)
    W_p = jnp.zeros((Vp, Mp), dtype).at[:V, :M].set(W)
    S_p = jnp.zeros((Vp, 1), dtype).at[:V, 0].set(jnp.asarray(S, dtype))
    kappa_arr = jnp.reshape(jnp.asarray(kappa, dtype), (1,))

    c = pl.pallas_call(
        functools.partial(_consensus_kernel, iters=iters),
        grid=(Mp // tile,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((Vp, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Vp, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, Mp), dtype),
        interpret=interpret,
    )(kappa_arr, S_p, W_p)
    return c[0, :M]
