"""Row/column normalizations with the reference's exact epsilon conventions.

Parity notes (reference = /root/reference/src/yuma_simulation/_internal/yumas.py):
- weight rows are normalized with a `+1e-6` denominator guard (yumas.py:72,186,297,411,505);
- stake is normalized with a bare sum, no epsilon (yumas.py:75,189,303,414,508).

All functions broadcast over arbitrary leading batch dimensions, so the same
code path serves the single-scenario kernel, `vmap` sweeps, and `shard_map`
shards.
"""

from __future__ import annotations

import jax.numpy as jnp

WEIGHT_EPS = 1e-6

#: Fixed block count of the partition-invariant miner-axis sum. Miner
#: meshes up to this many shards see block boundaries that coincide with
#: shard boundaries, so each block partial is shard-local.
SUM_BLOCKS = 8


def miner_sum(x: jnp.ndarray, keepdims: bool = False) -> jnp.ndarray:
    """Partition-invariant sum over the (possibly miner-sharded) last axis.

    A plain `x.sum(-1)` leaves the reduction order to the backend: under
    GSPMD a miner-sharded array reduces shard-locally and then psums the
    partials, and that combine order differs from the unsharded reduce —
    flipping the strict bisection compare (and every downstream
    normalization) by one ulp at knife-edge values, which is exactly the
    r4 "sharded agrees only to one u16 grid step" caveat. Here the sum
    is SPELLED with a fixed shape-independent structure instead: 8 fixed
    blocks reduced independently (each block shard-local for any mesh
    whose size divides 8), then combined by an explicit sequential add
    chain — XLA does not reassociate explicit adds, so sharded and
    unsharded runs execute the same additions in the same order and the
    result is bitwise identical on any mesh (pinned by
    tests/unit/test_multichip.py's assert_array_equal upgrade, r4
    verdict item 2).

    Small or non-8-divisible miner counts (every built-in case is M=2)
    keep the plain reduce, so all golden/CSV parity surfaces are
    bit-for-bit unchanged.

    Spelling note: the blocks come from a RESHAPE and the partials from
    one `[.., 8, M/8]` reduce. Two faster spellings were measured and
    REJECTED because they break the partition-invariance this function
    exists for (r5, CPU-mesh probes): plain `x.sum(-1)` is the baseline
    order-dependence; strided slice-reduces (with or without
    `optimization_barrier` around each partial) are ~30-40% faster on
    the hoisted microbench because the elementwise producer fuses into
    each block, but XLA's simplifier/partitioner re-associates them —
    the 2-shard mesh drifted from the unsharded run by one ulp of the
    total. The reshape costs a producer materialization (~69k vs 90k
    plain eps on the hoisted-shape microbench) and is the only spelling
    measured bitwise across 1, 2 and 8 shards; the flagship fused
    kernels are unaffected (they keep their in-kernel reduces).
    """
    M = x.shape[-1]
    if M % SUM_BLOCKS or M < 2 * SUM_BLOCKS:
        return x.sum(axis=-1, keepdims=keepdims)
    part = x.reshape(x.shape[:-1] + (SUM_BLOCKS, M // SUM_BLOCKS)).sum(-1)
    total = part[..., 0]
    for i in range(1, SUM_BLOCKS):
        total = total + part[..., i]
    return total[..., None] if keepdims else total


def normalize_weight_rows(W: jnp.ndarray, eps: float = WEIGHT_EPS) -> jnp.ndarray:
    """Normalize each validator's weight row to (approximately) sum to 1.

    `W` has shape `[..., V, M]`; rows that sum to zero map to zero rows
    (the epsilon keeps the division finite), which is what makes padded
    validators safe in batched sweeps. The row sum uses the
    partition-invariant :func:`miner_sum` spelling so miner-sharded and
    single-device runs normalize bitwise identically.
    """
    return W / (miner_sum(W, keepdims=True) + eps)


def normalize_stake(S: jnp.ndarray) -> jnp.ndarray:
    """Normalize the stake vector `[..., V]` to sum to 1 (no epsilon)."""
    return S / S.sum(axis=-1, keepdims=True)
