"""Row/column normalizations with the reference's exact epsilon conventions.

Parity notes (reference = /root/reference/src/yuma_simulation/_internal/yumas.py):
- weight rows are normalized with a `+1e-6` denominator guard (yumas.py:72,186,297,411,505);
- stake is normalized with a bare sum, no epsilon (yumas.py:75,189,303,414,508).

All functions broadcast over arbitrary leading batch dimensions, so the same
code path serves the single-scenario kernel, `vmap` sweeps, and `shard_map`
shards.
"""

from __future__ import annotations

import jax.numpy as jnp

WEIGHT_EPS = 1e-6


def normalize_weight_rows(W: jnp.ndarray, eps: float = WEIGHT_EPS) -> jnp.ndarray:
    """Normalize each validator's weight row to (approximately) sum to 1.

    `W` has shape `[..., V, M]`; rows that sum to zero map to zero rows
    (the epsilon keeps the division finite), which is what makes padded
    validators safe in batched sweeps.
    """
    return W / (W.sum(axis=-1, keepdims=True) + eps)


def normalize_stake(S: jnp.ndarray) -> jnp.ndarray:
    """Normalize the stake vector `[..., V]` to sum to 1 (no epsilon)."""
    return S / S.sum(axis=-1, keepdims=True)
