"""Fused Pallas TPU kernels: whole consensus epochs (and whole epoch
scans) resident in VMEM.

The unfused epoch (`models/epoch.py::yuma_epoch`) lowers to ~45 XLA
elementwise passes over the `[V, M]` weight/bond arrays; at 256x4096 that
is VPU-roofline-bound at ~55 us/epoch on a v5e chip. This kernel runs the
entire epoch pipeline —

    scale -> row-normalize -> 17-step bisection consensus -> u16 quantize
    -> clip -> rank/incentive -> bond update -> dividends

(bond update = blended/column-normalized EMA for the Yuma 0/1/2 family;
:func:`fused_ema_scan` additionally covers the Yuma 3 capacity-purchase
and Yuma 4 relative-bond models plus liquid alpha, so every named
version has a fused scan path, including Yuma 0 in x64 parity mode via
the double-single quantization emulation, `_rust64_quantize`)

— as ONE Pallas program with W, B, and every intermediate resident in
VMEM, and (optionally) the two stake contractions (bisection support,
rank, nothing else reduces over V) on the MXU instead of the VPU. At
256x4096 with weights varying every epoch (nothing hoistable) and long
scans (per-dispatch tunnel latency amortized), :func:`fused_ema_scan` —
the whole scan as a single Pallas program with the bond state never
leaving VMEM — runs ~60k epochs/s (~17 us/epoch) with the exact MXU
support (the bench.py headline; `auto` selects it) and ~37k (~27
us/epoch) on the all-VPU path, vs ~17k for the unfused XLA epoch
(~59 us/epoch) on one v5e chip. The VPU scan is VMEM-bandwidth-bound:
the 17 bisection halvings each traverse the [V, M] weights, so the
select is fused straight into the stake reduce (`_epoch_math`), and
batching scenarios only pays at small shapes where a single run is
latency-bound (DESIGN.md "Utilization", measured bandwidth ceiling
~4.3 TB/s); the MXU path moves those traversals onto the systolic
array.

Numerics (both paths share one parity contract since r4):
- The consensus support test runs on the canonical fixed-point integers
  shared by every engine (ops/consensus.py::support_fixed_stakes /
  support_rounded), so consensus agrees BITWISE with the XLA kernels by
  construction — including knife-edge ties (CROSS_ENGINE.json: 0
  mismatch runs).
- `mxu=False`: the integer support sum is a VPU select-into-reduce.
- `mxu=True`: the SAME integer sum computed on the MXU via the
  bf16-term limb split (`_stake_limb_split` / `_support_limbs_mxu` —
  every operand cast, product and f32 partial sum exact; verified on
  chip). Rank stays on the VPU, so the whole scan is bitwise the VPU
  scan (checked on chip at 256x4096 over 512 epochs), ~1.6x faster;
  requires V <= 2^14 and a single scenario (the dot shapes are 2-D).
- Everything else stays f32 on the VPU and matches the XLA kernel to
  reduction-order rounding (~1e-9 on bonds at 256x4096).

Reference semantics reproduced (same as `yuma_epoch`, reference
yumas.py:61-282): `+1e-6` row-normalization epsilon, strict `>` in the
bisection support test (yumas.py:89-91), truncating u16 quantization
(yumas.py:97), epsilon-free column normalization for Yuma 1/2 bonds
(yumas.py:228) vs `+1e-6` + EMA re-norm for Yuma 0 (yumas.py:113-116,
147-149), first-epoch bond adoption (yumas.py:145), and the `1e-6`
dividend-normalization epsilon (yumas.py:262).

Liquid alpha (per-miner EMA rates from consensus quantiles) is fused in
the scan kernel: the quantiles are order statistics on the u16 grid,
selected by an integer counting-bisection (no sort needed — see
`_liquid_rate_on_grid`), with the static quantile *overrides* embedded
as compile-time constants. The per-epoch `fused_ema_epoch` remains
liquid-free. The x64 parity mode's Yuma-0 float64 quantization divide
(reference yumas.py:81,97) is emulated in double-single f32
(`_rust64_quantize`) — Pallas TPU kernels are f32-only, but the
divide's operands are exactly representable as int32 + two-f32 pairs,
so the fused paths track the XLA engine's f64 grid to ~2^-24 grid
units. Padded miner columns (from heterogeneous-case
batching) are handled by passing the true miner count `m_real`; padded
columns are excluded from the quantization sum and produce zero
bonds/incentive.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from yuma_simulation_tpu.models.epoch import _EMA_MODES, MAXINT, BondsMode
from yuma_simulation_tpu.models.variants import ResetMode
from yuma_simulation_tpu.ops.consensus import (
    dyadic_grid_denom as _dyadic_grid_denom,
    dyadic_grid_fits_int32 as _dyadic_grid_fits_int32,
    support_fixed_stakes as _support_fixed_stakes,
    support_rounded as _support_rounded,
)

_LANES = 128
_SUBLANES = 8
#: Scoped-VMEM cap handed to Mosaic (the hardware size; without an
#: explicit CompilerParams the default is a misleading 16 MB). Shape
#: admission is governed separately by _fits_vmem's measured budget.
_VMEM_LIMIT = 128 * 1024 * 1024


def _round_up(x: int, mult: int) -> int:
    return (x + mult - 1) // mult * mult


def _support(S_col, mask):
    """Float stake contraction over validators on the VPU:
    `[..., V, 1] x [..., V, T] -> [..., 1, T]`. Used for the
    once-per-epoch rank contraction (every epoch path, MXU mode
    included — rank has no exact integer form, and keeping it on the
    VPU preserves the MXU scan's bitwise-VPU contract). An approximate
    MXU dot and a HIGHEST-precision (bf16x6) variant were both
    measured and rejected (DESIGN.md "Utilization"; the consensus
    support rides the MXU exactly via `_support_limbs_mxu` instead)."""
    return jnp.sum(mask * S_col, axis=-2, keepdims=True)


def _stake_limb_split(S_int, Vp: int, dtype):
    """Split the canonical fixed-point stakes `[Vp, 1] int32` into a
    `[2 * n_limbs, Vp]` float matrix whose single-pass-bf16 MXU
    contraction against a 0/1 mask is EXACT:

    - the stakes are first cut into small integer limbs, and each limb
      into its bf16 head + residual — both exactly bf16-representable,
      so the MXU's operand cast (default dot precision; Mosaic lowers
      neither HIGH nor HIGHEST here) rounds nothing;
    - products against a 0/1 mask are exact, and every f32 partial sum
      is an integer below 2^24 (head-row sums <= Vp * 2^limb_bits,
      residual-row sums <= Vp * 2^7), so accumulation rounds nothing
      either — verified on chip at 256x4096.

    15-bit limbs satisfy the sum bound for Vp <= 512; 10-bit limbs
    extend exactness to Vp <= 2^14. Larger V has no MXU fast path
    (callers fall back to the VPU reduce).
    Returns `(rows [..., 2n, Vp], limb_bits)` — per limb, head row then
    residual row, most-significant limb first; leading batch dims (the
    batched scan) pass through.
    """
    if Vp <= 512:
        bits, n = 15, 2
    elif Vp <= 2**14:
        bits, n = 10, 3
    else:
        raise ValueError(f"no exact MXU stake split for V={Vp}")
    S_flat = S_int[..., 0]  # [..., Vp]
    rows = []
    for i in reversed(range(n)):  # most-significant limb first
        limb = (S_flat >> (bits * i)) & ((1 << bits) - 1)
        if i == n - 1:
            # Top limb unmasked: it may carry the 2^30 == stake-1.0 bit,
            # so S_int == sum of limbs exactly.
            limb = S_flat >> (bits * i)
        limb_f = limb.astype(dtype)
        head = limb_f.astype(jnp.bfloat16).astype(dtype)
        rows += [head, limb_f - head]  # residual is an exact small int
    return jnp.stack(rows, axis=-2), bits


def _support_limbs_mxu(S_rows, limb_bits: int, mask):
    """EXACT consensus support on the MXU: one `[..., 2n, V] x
    [..., V, M]` default-precision contraction of the bf16-term stake
    rows (:func:`_stake_limb_split`) against the 0/1 mask (leading dims
    are dot batch dims — the batched scan), recombined in int32.
    Bitwise-identical to the VPU `where(mask, S_int, 0).sum()` by
    construction (every operand cast, product and partial sum is
    exact), so the MXU scan shares the VPU scan's parity contract."""
    nb = S_rows.ndim - 2  # leading batch dims
    out = jax.lax.dot_general(
        S_rows,
        mask,
        (
            ((S_rows.ndim - 1,), (mask.ndim - 2,)),
            (tuple(range(nb)), tuple(range(nb))),
        ),
        preferred_element_type=jnp.float32,
    )  # [..., 2n, M]
    n = out.shape[-2] // 2
    support = jnp.zeros_like(
        lax.index_in_dim(out, 0, axis=-2, keepdims=True), dtype=jnp.int32
    )
    for j in range(n):
        pair = lax.index_in_dim(
            out, 2 * j, axis=-2, keepdims=True
        ).astype(jnp.int32) + lax.index_in_dim(
            out, 2 * j + 1, axis=-2, keepdims=True
        ).astype(jnp.int32)
        support = (support << limb_bits) + pair
    return support  # [..., 1, M] int32


def _ds_split(a):
    """Dekker split of an f32 into two 12-bit halves (hi + lo == a
    exactly). Relies on correctly-rounded f32 multiply/add, which the
    VPU provides; XLA does not reassociate float ops, so the algebra
    survives compilation."""
    c = a * 4097.0  # 2^12 + 1
    hi = c - (c - a)
    return hi, a - hi


def _ds_two_prod(a, b):
    """Exact f32 product as a (head, tail) pair: head + tail == a * b."""
    p = a * b
    ah, al = _ds_split(a)
    bh, bl = _ds_split(b)
    err = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, err


def _rust64_quantize(c_hi, dtype, iters: int):
    """Yuma-0's float64 quantization divide
    `int(C / C.sum() * 65535) / 65535` (reference yumas.py:81,97)
    emulated in double-single f32 — the piece that kept the fused scan
    off-limits in x64 parity mode (Pallas TPU is f32-only).

    Exactness structure: every bisection output is a dyadic grid point
    `k * 2^-iters` (k integer <= 2^iters; iters = ceil(log2(
    consensus_precision)), 17 at the default precision), so the column
    sum is `K * 2^-iters` with `K = sum(k)` computed EXACTLY in int32
    (callers guard `M * 2^iters < 2^31`), and the f64 divide's
    operand values are represented here without loss (`K` as a two-f32
    head/tail pair). The quotient-and-scale `(k / K) * 65535` is then
    computed to ~2^-24 absolute accuracy in grid units via one Newton
    residual step (Dekker products, no FMA needed) — vs f64's ~1e-11.
    The two agree except when the exact rational `k * 65535 / K` lies
    within ~1e-7 of a truncation boundary; boundaries are spaced
    `1/K >= 2^-29` apart, so disagreement needs K >~ 2^23 AND a
    near-boundary cell. On the golden surface (M = 2, K <= 2^18,
    boundaries >= 4e-6 apart — and measured: zero f32-vs-f64 flips over
    all 1120 cells) agreement is certain; the residual risk class is
    documented in DESIGN.md "Precision policy".
    """
    k = jnp.round(c_hi * float(2**iters))  # exact dyadic ints <= 2^iters
    K_int = jnp.sum(  # dtype pinned: x64 would promote i32 sums to i64,
        # which Mosaic cannot lower
        k.astype(jnp.int32), axis=-1, keepdims=True, dtype=jnp.int32
    )
    y_hi = K_int.astype(dtype)  # 24-bit head of K
    y_lo = (K_int - y_hi.astype(jnp.int32)).astype(dtype)  # exact tail
    # q1 + q2 ~= k / K (double-single): one coarse quotient plus the
    # exactly-computed residual re-divided.
    q1 = k / y_hi
    p, e = _ds_two_prod(q1, y_hi)
    pl, el = _ds_two_prod(q1, y_lo)
    r = ((k - p) - e) - pl - el  # k - q1 * K, exact to f32 rounding
    q2 = r / y_hi
    # (q1 + q2) * 65535, head exact via Dekker.
    p1, e1 = _ds_two_prod(q1, jnp.asarray(65535.0, dtype))
    p2 = q2 * 65535.0 + e1
    t = jnp.floor(p1)
    d = (p1 - t) + p2  # fractional part in DS; may be slightly <0 or >=1
    n = t + jnp.floor(d)
    return n.astype(dtype) / 65535.0


def _liquid_rate_on_grid(
    C,
    logit_low,
    logit_num,
    alpha_low,
    alpha_high,
    *,
    n: int,
    override_high: float | None = None,
    override_low: float | None = None,
):
    """Per-miner liquid-alpha EMA rate from the quantized consensus row
    `[..., 1, Mp]`, computed WITHOUT a sort (Mosaic has none): every C
    value lies on the u16 grid, so each quantile's order statistics are
    found by a 16-halving integer counting-bisection — a rounding-free
    exact selection. All ranks the 0.25/0.75/0.99 quantiles need (at
    most 6 after dedup) are selected JOINTLY: each halving issues ONE
    `[K, Mp]` count that serves every rank, so the sequential depth is
    16 counting passes instead of the 96 (6 ranks x 16 halvings) of
    independent per-rank bisections — the r2 liquid scan's 3.3x
    throughput gap came from exactly that serialization. Linear
    interpolation between adjacent order statistics then matches
    `jnp.quantile`'s "linear" method to f32 rounding; the logistic fit
    mirrors :func:`yuma_simulation_tpu.ops.liquid.liquid_alpha_rate`'s
    traced-scalar branch (the one the jitted XLA oracle takes), with
    `logit_num = logit_high - logit_low` precomputed by the caller.
    `n` is the (static) real miner count; padded columns are excluded
    from the counts but still receive a rate (their bonds are zero).

    Degenerate-spread detection (the 0.99-quantile fallback, reference
    yumas.py:132-133) compares the EXACT integer order statistics —
    degenerate iff the 0.25-quantile's floor rank and the 0.75-quantile's
    ceil rank select the same grid value (by monotonicity all four ranks
    then coincide). The XLA oracle compares the f32/f64-interpolated
    quantile values instead; the two tests agree except on interpolation
    coincidences (unequal order statistics whose interpolations round to
    the same float — never observed on real consensus data, and the
    integer test is the numerically robust side of the pair).

    Supports leading batch dims (the batched scan): counts reduce over
    the miner axis only.

    `override_high` / `override_low` are the STATIC consensus-quantile
    overrides (reference yumas.py:124-133): a set override replaces the
    corresponding quantile selection with a compile-time constant (its
    ranks are simply dropped from the joint bisection). The degenerate
    fallback to the 0.99 quantile still applies — the reference's
    `consensus_high == consensus_low` check runs after the overrides are
    substituted — and its comparison mirrors the reference's operand
    types per case:

    - BOTH overridden: the reference compares two raw Python floats
      (f64), so the test is decided STATICALLY here (`override_high ==
      override_low` at trace time). Overrides distinct in f64 but equal
      after f32 rounding therefore do NOT fire the fallback, exactly as
      in the reference; and in the common non-degenerate case the whole
      counting bisection is skipped (no ranks needed at all).
    - exactly ONE overridden: the reference compares the override float
      against an f32 quantile tensor (an f32 comparison), reproduced as
      a traced f32 equality. Caveat (same class as the documented
      interpolation-coincidence edge): the computed side's last-ulp
      interpolation rounding can differ between this kernel and
      `jnp.quantile`, so an override bit-equal to one engine's
      interpolation but one ulp off the other's would fire the fallback
      on one side only. Constructing that requires tuning an override
      to a data-dependent quantile to 2^-24; never observed on real
      data, and there is no order-independent value to canonicalize —
      the interpolations themselves differ (precision policy).
    - NEITHER overridden: the exact integer-order-statistic test
      (degenerate iff the 0.25-floor and 0.75-ceil ranks select the
      same grid value), as documented above.
    """
    dtype = C.dtype
    Mp = C.shape[-1]
    col = lax.broadcasted_iota(jnp.int32, (1, Mp), 1)
    real = col < n
    C_int = jnp.round(C * 65535.0).astype(jnp.int32)  # [..., 1, Mp]

    # Which degenerate-test regime applies is static (see docstring).
    both_static = override_high is not None and override_low is not None
    static_degenerate = both_static and override_high == override_low

    # Ranks (0-indexed order statistics) needed by the computed
    # quantiles. Overridden quantiles need no selection; with both
    # overridden the fallback is decided statically, so 0.99 is needed
    # only when it actually fires (or may fire at runtime).
    quantiles = []
    if override_high is None:
        quantiles.append(0.75)
    if override_low is None:
        quantiles.append(0.25)
    if not both_static or static_degenerate:
        quantiles.append(0.99)
    pos: dict[float, tuple[int, int, float]] = {}
    ks: list[int] = []
    for q in quantiles:
        p = q * (n - 1)
        lo_i, hi_i = int(math.floor(p)), int(math.ceil(p))
        pos[q] = (lo_i, hi_i, p - lo_i)
        for k in (lo_i, hi_i):
            if k not in ks:
                ks.append(k)
    K = len(ks)
    if K:
        # Built from an iota + static scalars (a materialized constant
        # array would be a captured const, which Pallas kernels reject).
        iota_k = lax.broadcasted_iota(jnp.int32, (K, 1), 0)
        thresh = jnp.zeros((K, 1), jnp.int32)
        for i, k in enumerate(ks):
            thresh = jnp.where(iota_k == i, k + 1, thresh)
        batch = C.shape[:-2]

        def body(_, carry):
            lo, hi = carry  # [..., K, 1]
            mid = (lo + hi) // 2
            # [..., 1, Mp] vs [..., K, 1] -> one [..., K, Mp] count per
            # halving covering every rank at once.
            cnt = jnp.sum(
                jnp.where(real & (C_int <= mid), 1, 0),
                axis=-1,
                keepdims=True,
                dtype=jnp.int32,  # x64 would promote to i64 (no Mosaic)
            )
            ok = cnt >= thresh
            return jnp.where(ok, lo, mid + 1), jnp.where(ok, mid, hi)

        lo0 = jnp.zeros(batch + (K, 1), jnp.int32)
        hi0 = jnp.full(batch + (K, 1), 65535, jnp.int32)
        _, sel = lax.fori_loop(0, 16, body, (lo0, hi0), unroll=True)
        # Same division that built C, so the values are bitwise C's.
        stats = sel.astype(dtype) / 65535.0  # [..., K, 1]

    def stat_i(k: int):
        return lax.index_in_dim(sel, ks.index(k), axis=-2, keepdims=True)

    def stat(k: int):  # [..., 1, 1]
        return lax.index_in_dim(stats, ks.index(k), axis=-2, keepdims=True)

    def quant(q: float):
        lo_i, hi_i, frac = pos[q]
        v_lo = stat(lo_i)
        if hi_i == lo_i:
            return v_lo
        return v_lo * (1.0 - frac) + stat(hi_i) * frac

    if both_static:
        # Reference compares the two raw Python floats (f64); decided at
        # trace time, and the non-degenerate case runs no bisection.
        c_low = jnp.asarray(override_low, dtype)
        c_high = (
            quant(0.99)
            if static_degenerate
            else jnp.asarray(override_high, dtype)
        )
    else:
        c_high0 = (
            quant(0.75)
            if override_high is None
            else jnp.asarray(override_high, dtype)
        )
        c_low = (
            quant(0.25)
            if override_low is None
            else jnp.asarray(override_low, dtype)
        )
        # Degenerate spread -> 0.99-quantile fallback (runs even when
        # one side is overridden, reference yumas.py:132-133): exact
        # integer grid test when both quantiles are computed, f32 value
        # equality when one is an override (see docstring).
        if override_high is None and override_low is None:
            degenerate = stat_i(pos[0.75][1]) == stat_i(pos[0.25][0])
        else:
            degenerate = c_high0 == c_low
        c_high = jnp.where(degenerate, quant(0.99), c_high0)
    a = logit_num / (c_low - c_high)
    b = logit_low + a * c_low
    sig = 1.0 / (1.0 + jnp.asarray(math.e, dtype) ** (-a * C + b))
    return (1.0 - jnp.clip(sig, alpha_low, alpha_high)).astype(dtype)


def _consensus_phase(
    W,
    S,
    kappa,
    *,
    iters: int,
    mxu: bool,
    m_real: int,
    rust64: bool = False,
):
    """The bond-independent front half of the epoch pipeline:
    row-normalize -> bisection consensus -> u16 quantize. Split out of
    :func:`_epoch_math` (ops and order unchanged, so per-epoch values
    stay bitwise the per-epoch kernels') because nothing here reads the
    bond state — which is what lets :func:`fused_varying_scan` run it
    for a whole EPOCH TILE at once: a `[T, ..., Vp, Mp]` call computes
    T independent epochs' consensus in one vectorized pass, filling the
    (8, 128) tile that a single small suite would waste. Leading batch
    dims (scenario batch AND epoch tile) flow through every reduction;
    the MXU support contraction treats them as dot batch dimensions.
    Returns `(W_n, C [..., 1, Mp])`."""
    Mp = W.shape[-1]

    W_n = W / (jnp.sum(W, axis=-1, keepdims=True) + 1e-6)

    # Bisection consensus on this epoch's weights (always W_n — the
    # EMA_PREV variant clips/bonds against previous weights but computes
    # consensus from the current ones, reference yumas.py:309-325).
    c_lo = jnp.zeros(W.shape[:-2] + (1, Mp), W.dtype)
    c_hi = jnp.ones(W.shape[:-2] + (1, Mp), W.dtype)
    # Canonical fixed-point support test, via the SHARED helpers
    # (ops/consensus.py — plain jnp ops, trace fine under Mosaic): the
    # integer sum is exact and order-independent, then rounded ONCE to
    # W.dtype before the strict `> kappa` compare, so the decision here
    # is bitwise the XLA engines' decision — no cross-engine tie flips.
    # The i32 select-into-reduce has the same VMEM traffic as the f32
    # one it replaces; the int->float convert touches only the
    # [.., 1, Mp] support row.
    S_int = _support_fixed_stakes(S)
    if mxu:
        S_limbs, limb_bits = _stake_limb_split(S_int, W.shape[-2], W.dtype)

    def body(_, carry):
        c_lo, c_hi = carry
        c_mid = (c_hi + c_lo) * 0.5
        if mxu:
            # EXACT MXU support: the limb-split canonical stakes against
            # the strict-> mask, recombined in int32 — bitwise the VPU
            # branch's decision (see _support_limbs_mxu), at MXU speed.
            mask = (W_n > c_mid).astype(W.dtype)  # strict, as the reference
            support = _support_limbs_mxu(S_limbs, limb_bits, mask)
            above = _support_rounded(support, W.dtype) > kappa
        else:
            # One fused traversal (select straight into the reduce): the
            # compare->astype->multiply->reduce chain costs ~3 VMEM passes
            # over [V, M] per halving and dominates the whole VPU epoch;
            # selecting the integer addends straight into the reduce keeps
            # that shape (measured ~2.4x faster than the mask-multiply
            # form when this was f32; i32 adds run at the same VPU rate).
            support = jnp.sum(
                jnp.where(W_n > c_mid, S_int, jnp.zeros((), jnp.int32)),
                axis=-2,
                keepdims=True,
                dtype=jnp.int32,  # x64 would promote to i64 (no Mosaic)
            )
            above = _support_rounded(support, W.dtype) > kappa
        return jnp.where(above, c_mid, c_lo), jnp.where(above, c_hi, c_mid)

    _, c_hi = lax.fori_loop(0, iters, body, (c_lo, c_hi), unroll=True)

    # Truncating u16 quantization; padded columns are excluded from the
    # normalization sum (an all-zero real column still contributes its
    # 2^-17 floor, exactly as the unfused quantize_u16 with miner_mask).
    if m_real != Mp:
        col = lax.broadcasted_iota(jnp.int32, (1, Mp), 1)
        c_hi = jnp.where(col < m_real, c_hi, jnp.zeros_like(c_hi))
    if rust64:
        C = _rust64_quantize(c_hi, W.dtype, iters)
    else:
        # Exact integer quantization sum on the dyadic grid — the ONE
        # shared spelling (ops/consensus.py::dyadic_grid_denom), bitwise
        # the XLA engines' quantize_u16(grid_bits=...) denominator. The
        # guard uses the REAL miner count (padded columns were zeroed
        # above and contribute k = 0), so the gate matches the XLA
        # engine's for the same subnet.
        if _dyadic_grid_fits_int32(m_real, iters):
            denom = _dyadic_grid_denom(c_hi, iters)
        else:
            denom = jnp.sum(c_hi, axis=-1, keepdims=True)
        C = c_hi / denom * 65535.0
        C = C.astype(jnp.int32).astype(W.dtype) / 65535.0
    return W_n, C


def _clip_rank_rate(
    S,
    C,
    clip_base,
    alpha,
    *,
    mode: BondsMode,
    m_real: int,
    liquid: bool = False,
    liquid_scal=None,
    liquid_overrides=(None, None),
):
    """Consensus clip, rank/incentive and the per-miner EMA rate — still
    bond-independent (split out of :func:`_epoch_math` unchanged for the
    same epoch-tile batching as :func:`_consensus_phase`). Returns
    `(W_clipped, incentive [..., 1, Mp], rate)`; `rate` is `alpha`
    passed through when liquid alpha is off."""
    W_clipped = jnp.minimum(clip_base, C)

    # Rank: once per epoch (vs 17 support halvings), always VPU f32.
    R = _support(S, W_clipped)
    incentive = jnp.nan_to_num(R / jnp.sum(R, axis=-1, keepdims=True))

    # Consensus-dependent per-miner EMA rate (liquid alpha); the CAPACITY
    # model never uses a rate (models/epoch.py: the fit is skipped there).
    rate = alpha
    if liquid and mode is not BondsMode.CAPACITY:
        rate = _liquid_rate_on_grid(
            C,
            *liquid_scal,
            n=m_real,
            override_high=liquid_overrides[0],
            override_low=liquid_overrides[1],
        )
    return W_clipped, incentive, rate


def _bond_phase(
    S,
    B_old,
    W_n,
    clip_base,
    W_clipped,
    incentive,
    rate,
    first,
    beta,
    *,
    mode: BondsMode,
    cap_alpha=None,
    decay=None,
):
    """The bond-state back half of the epoch pipeline: the only part of
    :func:`_epoch_math` that reads the carried bond state, so it is the
    only part :func:`fused_varying_scan` runs sequentially per epoch
    inside a tile. Ops and order are exactly `_epoch_math`'s. Returns
    `(B_next, D_n [..., V, 1])`."""
    if mode in _EMA_MODES:
        if mode is BondsMode.EMA_RUST:
            B_t = S * W_clipped
            B_t = jnp.nan_to_num(
                B_t / (jnp.sum(B_t, axis=-2, keepdims=True) + 1e-6)
            )
        else:
            bond_base = W_n if mode is BondsMode.EMA else clip_base
            W_b = (1.0 - beta) * bond_base + beta * W_clipped
            B_t = S * W_b
            # no epsilon (reference yumas.py:228, 342)
            B_t = jnp.nan_to_num(B_t / jnp.sum(B_t, axis=-2, keepdims=True))

        ema = rate * B_t + (1.0 - rate) * B_old
        B_next = jnp.where(first, B_t, ema)
        if mode is BondsMode.EMA_RUST:
            B_next = jnp.nan_to_num(
                B_next / (jnp.sum(B_next, axis=-2, keepdims=True) + 1e-6)
            )
        D = jnp.sum(B_next * incentive, axis=-1, keepdims=True)  # [..., V, 1]
    elif mode is BondsMode.CAPACITY:
        # Stake-capacity purchase, mirroring
        # models.epoch.capacity_bonds_update (reference yumas.py:455-472):
        # the 2^64-1 constant enters f32 arithmetic deliberately.
        cap_vec = S * jnp.asarray(MAXINT, S.dtype)  # [..., V, 1]
        remaining = jnp.clip(cap_vec - B_old, min=0.0)
        purchase = jnp.minimum(cap_alpha * cap_vec, remaining) * W_n
        B_next = (1.0 - decay) * B_old + purchase
        B_next = jnp.minimum(B_next, cap_vec)
        D = jnp.sum(B_next * incentive, axis=-1, keepdims=True)
    else:  # RELATIVE
        # Per-(validator, miner) bonds in [0, 1], mirroring
        # models.epoch.relative_bonds_update (reference yumas.py:574-590);
        # dividends are stake-scaled.
        B_dec = B_old * (1.0 - rate)
        remaining = jnp.clip(1.0 - B_dec, min=0.0)
        purchase = jnp.minimum(rate * W_n, remaining)
        B_next = jnp.clip(B_dec + purchase, max=1.0)
        D = S * jnp.sum(B_next * incentive, axis=-1, keepdims=True)

    # Two single-axis sums, NOT jnp.sum(D, axis=(-2, -1)): the multi-axis
    # reduce of a leading-batch [B, V, 1] array to [B, 1, 1] hits a Mosaic
    # layout abort (layout.h "arr.size() >= layout_rank" check) on real
    # TPU; the sequential form lowers cleanly and sums the same values in
    # the same (V-then-singleton) order.
    D_tot = jnp.sum(jnp.sum(D, axis=-1, keepdims=True), axis=-2, keepdims=True)
    D_n = D / (D_tot + 1e-6)
    return B_next, D_n


def _epoch_math(
    W,
    S,
    B_old,
    clip_prev,
    first,
    kappa,
    beta,
    alpha,
    *,
    iters: int,
    mode: BondsMode,
    mxu: bool,
    m_real: int,
    clip_fallback=None,
    cap_alpha=None,
    decay=None,
    liquid: bool = False,
    liquid_scal=None,  # (logit_low, logit_num, alpha_low, alpha_high)
    liquid_overrides=(None, None),  # static (override_high, override_low)
    rust64: bool = False,  # static: emulate Yuma-0's f64 quantize divide
):
    """The one shared epoch pipeline all fused kernels trace:
    row-normalize -> bisection -> u16 quantize -> clip -> incentive ->
    bond update (EMA / capacity purchase / relative) -> normalized
    dividends — composed from :func:`_consensus_phase`,
    :func:`_clip_rank_rate` and :func:`_bond_phase` (the split lets the
    epoch-tiled :func:`fused_varying_scan` batch the bond-independent
    phases over a whole tile; composition here is op-for-op the
    pre-split spelling, so per-epoch values are unchanged bitwise).

    `clip_prev` is the EMA_PREV clip source (ignored by the other modes;
    None means "clip against this epoch's W_n"). `first` is the traced
    first-epoch predicate for the EMA blend. `clip_fallback` (kwarg)
    additionally selects W_n over `clip_prev` when true — the scan kernel
    uses it at grid step 0 where its scratch is not yet a previous epoch;
    the per-epoch kernel resolves that fallback caller-side and passes
    None. Returns `(B_ema, D_n [..., V, 1], incentive [..., 1, Mp], W_n,
    C [..., 1, Mp])`.

    All reductions use negative axes so leading batch dims (the batched
    scan kernel: `[B, Vp, Mp]` arrays, one scenario per leading index)
    flow through unchanged; `S` is then `[..., Vp, 1]` and every
    normalization is per-scenario; the MXU support contraction treats
    leading dims as dot batch dimensions.
    """
    W_n, C = _consensus_phase(
        W, S, kappa, iters=iters, mxu=mxu, m_real=m_real, rust64=rust64
    )

    if clip_prev is not None:
        # Only the EMA_PREV callers pass this (both kernels guard it).
        # Grid step 0 of the scan falls back to this epoch's normalized
        # weights (reference yumas.py:299-300). A select, not an
        # arithmetic blend — a blend would do 0 * clip_prev, which
        # poisons on uninitialized scratch.
        clip_base = (
            clip_prev
            if clip_fallback is None
            else jnp.where(clip_fallback, W_n, clip_prev)
        )
    else:
        clip_base = W_n
    W_clipped, incentive, rate = _clip_rank_rate(
        S,
        C,
        clip_base,
        alpha,
        mode=mode,
        m_real=m_real,
        liquid=liquid,
        liquid_scal=liquid_scal,
        liquid_overrides=liquid_overrides,
    )
    B_next, D_n = _bond_phase(
        S,
        B_old,
        W_n,
        clip_base,
        W_clipped,
        incentive,
        rate,
        first,
        beta,
        mode=mode,
        cap_alpha=cap_alpha,
        decay=decay,
    )
    return B_next, D_n, incentive, W_n, C


def _fused_ema_epoch_kernel(
    scal_ref,
    s_ref,
    w_ref,
    *rest,
    iters: int,
    mode: BondsMode,
    mxu: bool,
    m_real: int,
    has_clip_base: bool,
    rust64: bool = False,
):
    """scal = [w_scale, kappa, beta, alpha, first]. `rest` is
    `([clip_ref,] b_ref, bout_ref, d_ref, inc_ref)` — the clip-base
    operand exists only for the EMA_PREV variant so the common case
    doesn't pay an extra 4 MB HBM read per epoch."""
    if has_clip_base:
        clip_ref, b_ref, bout_ref, d_ref, inc_ref = rest
    else:
        b_ref, bout_ref, d_ref, inc_ref = rest

    B_ema, D_n, incentive, _, _ = _epoch_math(
        w_ref[:] * scal_ref[0],
        s_ref[:],
        b_ref[:],
        clip_ref[:] if has_clip_base else None,
        scal_ref[4] > 0.5,
        scal_ref[1],
        scal_ref[2],
        scal_ref[3],
        iters=iters,
        mode=mode,
        mxu=mxu,
        m_real=m_real,
        rust64=rust64,
    )
    bout_ref[:] = B_ema
    d_ref[:] = D_n
    inc_ref[:] = incentive


#: Every bond model the scan kernel implements; a future BondsMode member
#: must be added here (and to _epoch_math) before the fused scan or the
#: `auto` predicate may accept it.
_SCAN_MODES = _EMA_MODES + (BondsMode.CAPACITY, BondsMode.RELATIVE)


#: Mosaic needs VMEM beyond the named resident mats for _epoch_math's
#: live temporaries (W_n, the clipped weights, the bond target, ...).
#: Measured on a v5e chip (128 MiB VMEM, r5): the batched case scan at
#: 4 x 256 x 4096 with 4 resident mats compiles and runs ((4+3) units =
#: 117 MiB under this model), the 5-scenario EMA_PREV scaled scan with
#: its 3 resident mats compiles ((3+3) units = 126 MiB), and every
#: config one step larger fails to compile — so the temporary allowance
#: is 3 units and the usable budget ~126 MiB. The former
#: `resident * 3 <= 110 MiB` rule modeled temporaries as
#: 2x-the-resident-set, which over-reserves exactly for the large-unit
#: configurations (scenario-batched 256x4096) where eligibility matters.
_TEMP_UNITS = 3
_VMEM_BUDGET = 126 * 1024 * 1024


def _fits_vmem(unit_bytes: int, mats: int) -> bool:
    """Whether `mats` resident [.., Vp, Mp]-unit mats plus the measured
    temporary allowance fit the VMEM budget — the one guard both fused
    scan kernels and both eligibility predicates share."""
    return (mats + _TEMP_UNITS) * unit_bytes <= _VMEM_BUDGET


def _unit_bytes(shape) -> int:
    """Bytes of one tile-padded `[.., Vp, Mp]` float32 mat (the leading
    scenario batch, if any, scales it)."""
    V, M = shape[-2:]
    Bb = shape[0] if len(shape) > 2 else 1
    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    return Bb * Vp * Mp * 4


def _scan_mats(mode: BondsMode, recompute_prev: bool = False) -> int:
    """EFFECTIVE resident mats of :func:`fused_ema_scan` for the VMEM
    admission model: W (fixed block, fetched once) + the bond scratch,
    plus for EMA_PREV either the previous-weights scratch mat or — in
    the recompute variant, which re-derives `W * scales[e-1]` in-kernel
    — one extra live temporary for that derivation (measured on chip:
    the 6-scenario recompute spelling fails exactly where the model's
    2-resident+1-extra-temporary count says it should, while the
    5-scenario scratch spelling compiles)."""
    if mode is BondsMode.EMA_PREV:
        # Same effective total either way — which is WHY the auto
        # fallback in fused_ema_scan never fires on this model: the
        # scratch spelling holds 3 resident mats, the recompute
        # spelling 2 resident plus 1 extra live temporary for the
        # W * scales[e-1] derivation (both boundaries measured on
        # chip). Spelled out so a future budget/temporary refinement
        # flows through instead of silently diverging from
        # fused_scan_eligible.
        return (2 + 1) if recompute_prev else 3
    return 2


def _scan_resident_bytes(
    shape, mode: BondsMode, recompute_prev: bool = False
) -> int:
    """VMEM bytes the fused scan keeps resident (W + B [+ W_prev]),
    padded to tile boundaries — the one source of truth for both the
    kernel's guard and the `auto` eligibility predicate. `shape` may be
    `[V, M]` or batched `[Bb, V, M]` (everything resident scales by Bb)."""
    return _scan_mats(mode, recompute_prev) * _unit_bytes(shape)


def exact_mxu_support_covers(num_validators: int) -> bool:
    """Whether the exact limb-split MXU support (`_stake_limb_split`)
    covers this validator count — the `auto` gate for preferring the
    MXU scan over the VPU scan. Beyond it the VPU reduce is the only
    exact form."""
    return num_validators <= 2**14


def fused_scan_eligible(shape, mode: BondsMode, config, dtype=None) -> bool:
    """Whether :func:`fused_ema_scan` can run this workload — the
    `epoch_impl="auto"` predicate: float32 arrays, within the VMEM
    budget, and on a real TPU (interpret mode would be slower than XLA,
    not faster). All five bond models are supported in-kernel — liquid
    alpha, its consensus-quantile overrides, and Yuma-0's x64 f64
    quantization divide (double-single emulation) included."""
    if mode not in _SCAN_MODES:
        return False
    if dtype is not None and jnp.dtype(dtype) != jnp.float32:
        # Pallas TPU kernels here are f32-only (module docstring); an
        # f64 input must fall back to XLA, not crash in Mosaic.
        return False
    if (
        mode is BondsMode.EMA_RUST
        and jax.config.jax_enable_x64
        and (shape[-1] << math.ceil(math.log2(config.consensus_precision)))
        >= 2**23
    ):
        # Parity-mode auto stays on the exactly-faithful XLA f64 path
        # wherever the double-single emulation's u16 cells could even in
        # principle flip vs f64 (boundary flips need the quantization
        # sum K >~ 2^23; K <= M * 2^iters bounds it conservatively —
        # advisor r4). The fused paths remain explicit opt-in up to
        # their int32 bound (M * 2^iters < 2^31, enforced in-kernel).
        return False
    if not _dyadic_grid_fits_int32(
        shape[-1], math.ceil(math.log2(config.consensus_precision))
    ):
        # Beyond the int32 exact-quantization bound the fused kernel's
        # denominator fallback is a plain in-kernel jnp.sum while the
        # XLA engine's quantize_u16 falls back to the blocked miner_sum
        # spelling — for M divisible by 8 the two can differ by one ulp
        # and flip a u16 cell, exactly where the fused scan could still
        # be VMEM-eligible (advisor r5 low). auto never pairs the two
        # fallbacks; explicit fused_scan* opt-in still works up to the
        # in-kernel int32 bound with the documented one-ulp caveat.
        return False
    if jax.default_backend() != "tpu":
        return False
    # The EMA_PREV recompute variant (prev weights re-derived from
    # W * scales[e-1]) is the smallest spelling; eligible iff it fits.
    return _fits_vmem(
        _unit_bytes(shape), _scan_mats(mode, recompute_prev=True)
    )


def _pack_hp(hp_vals, lead, dtype):
    """The per-scenario hyperparameter operand. One shared packer for
    every fused wrapper — the column order is parity-critical (the
    kernels read `sc(i)` by index), so it must never drift between call
    sites (same rule as engine.fused_hparams). Returns `(operand,
    per_hp)`: a `[Bb, 1, LANES]` VMEM array when any value is batched,
    else the classic `[9]` SMEM scalar vector."""
    per_hp = any(v.ndim > 0 for v in hp_vals)
    if per_hp and not lead:
        raise ValueError(
            "per-scenario hyperparameter vectors require a batched scan; "
            "got single-scenario inputs"
        )
    if not per_hp:
        return jnp.stack(hp_vals), False
    hp_arr = jnp.zeros(lead + (1, _LANES), dtype)
    for i, v in enumerate(hp_vals):
        hp_arr = hp_arr.at[:, 0, i].set(jnp.broadcast_to(v, lead))
    return hp_arr, True


def _fused_ema_scan_kernel(
    *rest,
    iters: int,
    mode: BondsMode,
    mxu: bool,
    m_real: int,
    num_epochs: int,
    liquid: bool,
    liquid_overrides: tuple = (None, None),
    rust64: bool = False,
    per_scenario_hp: bool = False,
    recompute_prev: bool = False,
):
    """One grid step = one epoch; the bond state lives in VMEM scratch for
    the WHOLE scan, so the per-epoch HBM traffic of the lax.scan carry
    (read B, write B — ~8 MB/epoch at 256x4096) disappears entirely, and
    W's block index never changes so Pallas fetches it once. scal =
    [kappa, beta, alpha, cap_alpha, decay, logit_low, logit_num,
    alpha_low, alpha_high]; scales is the per-epoch weight scale in
    SMEM. With `per_scenario_hp` (batched hyperparameter sweeps) the
    nine values come instead from an `[Bb, 1, LANES]` VMEM operand —
    column i is scenario-specific value i, read as a broadcastable
    `[Bb, 1, 1]` scalar — which REPLACES the SMEM operand, so a
    config_grid sweep is ONE dispatch."""
    rest = list(rest)
    hp_or_scal_ref = rest.pop(0)
    scales_ref, s_ref, w_ref, bout_ref, dtot_ref, b_scr, dacc_scr = rest[:7]
    wprev_scr = rest[7:]

    if per_scenario_hp:
        hp = hp_or_scal_ref[...]  # [Bb, 1, LANES]

        def sc(i):
            return hp[..., i : i + 1]  # [Bb, 1, 1]

    else:

        def sc(i):
            return hp_or_scal_ref[i]

    e = pl.program_id(0)
    first = e == 0
    keep_prev = mode is BondsMode.EMA_PREV and not recompute_prev

    @pl.when(first)
    def _init():
        b_scr[:] = jnp.zeros_like(b_scr)
        dacc_scr[:] = jnp.zeros_like(dacc_scr)
        if keep_prev:
            wprev_scr[0][:] = jnp.zeros_like(wprev_scr[0])

    if mode is BondsMode.EMA_PREV and recompute_prev:
        # Previous epoch's normalized weights, re-derived bitwise from
        # the resident W and scales[e-1] (the same multiply+normalize
        # _epoch_math performed at step e-1) instead of a third resident
        # [.., Vp, Mp] scratch mat — the VMEM saving that keeps Yuma 2
        # fused at the chip-filling scenario batch (r4 verdict item 3).
        # At e == 0 the value is discarded by the clip_fallback select.
        Wp = w_ref[:] * scales_ref[jnp.maximum(e - 1, 0)]
        clip_prev = Wp / (jnp.sum(Wp, axis=-1, keepdims=True) + 1e-6)
    elif mode is BondsMode.EMA_PREV:
        clip_prev = wprev_scr[0][:]
    else:
        clip_prev = None

    B_ema, D_n, _, W_n, _ = _epoch_math(
        w_ref[:] * scales_ref[e],
        s_ref[:],
        b_scr[:],
        clip_prev,
        first,
        sc(0),
        sc(1),
        sc(2),
        iters=iters,
        mode=mode,
        mxu=mxu,
        m_real=m_real,
        clip_fallback=first,
        cap_alpha=sc(3),
        decay=sc(4),
        liquid=liquid,
        liquid_scal=(sc(5), sc(6), sc(7), sc(8)),
        liquid_overrides=liquid_overrides,
        rust64=rust64,
    )

    b_scr[:] = B_ema
    dacc_scr[:] = dacc_scr[:] + D_n
    if keep_prev:
        wprev_scr[0][:] = W_n

    @pl.when(e == num_epochs - 1)
    def _emit():
        bout_ref[:] = b_scr[:]
        dtot_ref[:] = dacc_scr[:]


@functools.partial(
    jax.jit,
    static_argnames=(
        "mode",
        "mxu",
        "interpret",
        "precision",
        "liquid_alpha",
        "override_consensus_high",
        "override_consensus_low",
        "recompute_prev",
    ),
)
def fused_ema_scan(
    W: jnp.ndarray,
    S_n: jnp.ndarray,
    scales: jnp.ndarray,
    *,
    kappa=0.5,
    bond_penalty=1.0,
    bond_alpha=0.1,
    capacity_alpha=0.1,
    decay_rate=0.1,
    liquid_alpha: bool = False,
    alpha_low=0.7,
    alpha_high=0.9,
    override_consensus_high: float | None = None,
    override_consensus_low: float | None = None,
    mode: BondsMode = BondsMode.EMA,
    mxu: bool = False,
    precision: int = 100_000,
    recompute_prev: bool | None = None,
    interpret: bool | None = None,
):
    """The WHOLE epoch scan as one Pallas program (all five bond models,
    liquid alpha included, consensus-quantile overrides in-kernel as
    compile-time constants — they are static config fields,
    models/config.py).

    Epoch `e` simulates `W * scales[e]` (the epoch-varying workload of
    `simulate_scaled`). The grid iterates over epochs sequentially; the
    bond state and the dividend accumulator are VMEM scratch that persists
    across grid steps, and W's block index never changes so it is fetched
    from HBM once. Versus `lax.scan` over `fused_ema_epoch`, this removes
    the per-epoch kernel dispatch and the bond-carry HBM round-trip.

    `W`/`S_n` may carry a leading scenario-batch axis (`W [Bb, V, M]`,
    `S_n [Bb, V]`): every grid step then advances ALL `Bb` scenarios one
    epoch with `[Bb, Vp, Mp]`-shaped ops — a single run's arrays are
    too small to fill the chip (DESIGN.md "Utilization"), so batching is
    how varying-weights work saturates it. The batch shares `scales`;
    hyperparameters are shared scalars or per-scenario `[Bb]` vectors
    (see below); per-scenario normalizations reduce over the last two
    axes only. `mxu=True` works batched too — the leading dims ride the
    support dot's batch dimensions, bitwise the VPU path.

    Returns `(B_final [[Bb,] V, M], D_n_total [[Bb,] V])` where
    `D_n_total` is the sum over epochs of the per-epoch NORMALIZED
    dividends (the caller applies the per-validator dividend-per-1000-tao
    conversion, which is linear in `D_n`, to the sum).
    """
    if mode not in _SCAN_MODES:
        raise ValueError(f"fused scan does not implement bonds mode {mode}")
    # In x64 parity mode Yuma-0's f64 quantization divide is emulated
    # in-kernel with double-single f32 (_rust64_quantize); the flag is
    # static so f32 mode pays nothing. The emulation's exact integer
    # column sum needs M * 2^iters to fit int32 (default precision:
    # M < 2^14 miners) — beyond that the XLA f64 path is the only
    # faithful engine.
    rust64 = mode is BondsMode.EMA_RUST and bool(jax.config.jax_enable_x64)
    if W.ndim == 3:
        Bb, V, M = W.shape
        lead: tuple[int, ...] = (Bb,)
    else:
        V, M = W.shape
        lead = ()
    if mxu and not exact_mxu_support_covers(V):
        raise ValueError(
            f"the exact MXU stake split covers V <= 2^14 validators, got "
            f"V={V}; use the VPU path (mxu=False)"
        )
    E = scales.shape[0]
    if E < 1:
        # grid=(0,) does not compile, and the output refs would never be
        # written; the other epoch_impl paths return zeros for E=0.
        raise ValueError("fused scan requires at least one epoch")
    dtype = W.dtype
    iters = int(math.ceil(math.log2(precision)))
    if rust64 and (M << iters) >= 2**31:
        raise ValueError(
            "the double-single f64-quantize emulation needs M * 2^iters "
            f"< 2^31 for its exact int32 column sum (M={M}, "
            f"precision={precision}); use the XLA epoch path"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    # W + B (+ W_prev) resident plus Mosaic temporaries: stay within the
    # measured VMEM budget or refuse — there is no automatic fallback,
    # callers must choose the per-epoch "fused"/"fused_mxu" path (or a
    # smaller batch) for such shapes. EMA_PREV prefers the scratch mat
    # for the previous normalized weights (no recompute cost) and falls
    # back to re-deriving them from W * scales[e-1] in-kernel — bitwise
    # the same values — when the third mat would not fit (the Yuma-2
    # chip-filling-batch case, r4 verdict item 3).
    unit = _unit_bytes(W.shape)
    if recompute_prev is None:
        # Auto: keep the scratch spelling (no per-epoch recompute cost)
        # when it fits, else fall back to the recompute spelling if THAT
        # fits. On the measured v5e admission model both cost 3
        # effective units (the recompute variant trades the scratch mat
        # for an extra live temporary), so the fallback never fires
        # today — but it keeps `fused_scan_eligible` (which admits on
        # the smallest spelling) and this guard agreeing by construction
        # if the model is ever refined. The two spellings are
        # bitwise-identical (tests/unit/test_fused_epoch.py).
        recompute_prev = (
            mode is BondsMode.EMA_PREV
            and not _fits_vmem(unit, _scan_mats(mode, recompute_prev=False))
            and _fits_vmem(unit, _scan_mats(mode, recompute_prev=True))
        )
    recompute_prev = recompute_prev and mode is BondsMode.EMA_PREV
    if not _fits_vmem(unit, _scan_mats(mode, recompute_prev)):
        resident = _scan_resident_bytes(W.shape, mode, recompute_prev)
        raise ValueError(
            f"{list(W.shape)} too large for the VMEM-resident fused scan "
            f"(~{resident // 2**20} MiB resident); use the per-epoch path "
            "or a smaller scenario batch"
        )
    padded = (Vp, Mp) != (V, M)
    W_p = (
        jnp.zeros(lead + (Vp, Mp), dtype).at[..., :V, :M].set(W)
        if padded
        else W
    )
    S_p = (
        jnp.zeros(lead + (Vp, 1), dtype)
        .at[..., :V, 0]
        .set(jnp.asarray(S_n, dtype))
    )
    if liquid_alpha:
        # The traced-scalar logit branch of liquid_alpha_rate — the one
        # the jitted XLA oracle takes (alpha bounds are traced pytree
        # leaves), so the fused path mirrors its rounding.
        al = jnp.asarray(alpha_low, dtype)
        ah = jnp.asarray(alpha_high, dtype)
        logit_low = jnp.log(1.0 / al - 1.0)
        logit_num = jnp.log(1.0 / ah - 1.0) - logit_low
    else:
        al = ah = logit_low = logit_num = jnp.zeros((), dtype)
    hp_vals = [
        jnp.asarray(kappa, dtype),
        jnp.asarray(bond_penalty, dtype),
        jnp.asarray(bond_alpha, dtype),
        jnp.asarray(capacity_alpha, dtype),
        jnp.asarray(decay_rate, dtype),
        logit_low,
        logit_num,
        al,
        ah,
    ]
    # Per-scenario hyperparameters ([Bb]-vector values — config_grid
    # sweeps): ship the nine values as a [Bb, 1, LANES] VMEM operand
    # instead of SMEM scalars, so a whole hyperparameter grid runs as
    # ONE fused dispatch (r3 verdict item 5).
    hp_operand, per_hp = _pack_hp(hp_vals, lead, dtype)

    vm = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda e: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    scratch = [
        pltpu.VMEM(lead + (Vp, Mp), dtype),
        pltpu.VMEM(lead + (Vp, 1), dtype),
    ]
    if mode is BondsMode.EMA_PREV and not recompute_prev:
        scratch.append(pltpu.VMEM(lead + (Vp, Mp), dtype))

    if per_hp:
        operands = [hp_operand]
        in_specs = [vm(lead + (1, _LANES))]
    else:
        operands = [hp_operand]
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    operands += [scales.astype(dtype), S_p, W_p]
    in_specs += [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        vm(lead + (Vp, 1)),
        vm(lead + (Vp, Mp)),
    ]

    B_final, D_tot = pl.pallas_call(
        functools.partial(
            _fused_ema_scan_kernel,
            iters=iters,
            mode=mode,
            mxu=mxu,
            m_real=M,
            num_epochs=E,
            liquid=liquid_alpha,
            liquid_overrides=(
                override_consensus_high,
                override_consensus_low,
            ),
            rust64=rust64,
            per_scenario_hp=per_hp,
            recompute_prev=recompute_prev,
        ),
        grid=(E,),
        in_specs=in_specs,
        out_specs=[vm(lead + (Vp, Mp)), vm(lead + (Vp, 1))],
        out_shape=[
            jax.ShapeDtypeStruct(lead + (Vp, Mp), dtype),
            jax.ShapeDtypeStruct(lead + (Vp, 1), dtype),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT,
            dimension_semantics=("arbitrary",),
        ),
    )(*operands)
    return B_final[..., :V, :M], D_tot[..., :V, 0]


def _case_scan_mats(
    mode: BondsMode, save_bonds: bool, streaming: bool = False
) -> int:
    """Resident mats of the streamed case scan: the bond scratch, two
    pipelined per-epoch W blocks, the EMA_PREV weight scratch, and (when
    per-epoch bonds are emitted) two pipelined output blocks.
    `streaming` adds the chunk-carry residency (`carry=.../
    return_carry=True`, engine.simulate_streamed): the carry-bonds
    input is whole-grid resident, and EMA_PREV additionally carries the
    previous-weights mat in AND emits it out (the consensus rows are
    [1, Mp]-sized — noise). Without this the admission model under-
    counts streamed EMA_PREV by three units and Mosaic aborts at
    dispatch on exactly the beyond-HBM path."""
    mats = 3  # B scratch + double-buffered W blocks
    if mode is BondsMode.EMA_PREV:
        mats += 1
    if save_bonds:
        mats += 2
    if streaming:
        mats += 1  # carry bonds input
        if mode is BondsMode.EMA_PREV:
            mats += 2  # carry w_prev input + final_w_prev output
    return mats


def _case_scan_resident_bytes(
    shape, mode: BondsMode, save_bonds: bool
) -> int:
    """VMEM bytes the streamed case scan keeps live. `shape` is
    `[E, V, M]` or batched `[Bb, E, V, M]` (everything resident scales
    by Bb; the epoch axis streams, so it does not)."""
    V, M = shape[-2:]
    Bb = shape[0] if len(shape) == 4 else 1
    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    return _case_scan_mats(mode, save_bonds) * Bb * Vp * Mp * 4


def fused_case_scan_eligible(
    shape,
    mode: BondsMode,
    config,
    dtype=None,
    save_bonds: bool = True,
    streaming: bool = False,
) -> bool:
    """Whether :func:`fused_case_scan` can run this workload — the
    `epoch_impl="auto"` predicate of :func:`..simulation.engine.simulate`:
    float32 arrays, within the VMEM budget, and on a real TPU (interpret
    mode would be slower than XLA, not faster). `shape` is `[E, V, M]`
    or `[V, M]`; liquid alpha, its consensus-quantile overrides, and
    Yuma-0's x64 f64 quantization divide (double-single emulation) are
    all supported in-kernel."""
    if mode not in _SCAN_MODES:
        return False
    if dtype is not None and jnp.dtype(dtype) != jnp.float32:
        return False
    if (
        mode is BondsMode.EMA_RUST
        and jax.config.jax_enable_x64
        and (shape[-1] << math.ceil(math.log2(config.consensus_precision)))
        >= 2**23
    ):
        # Parity-mode auto stays on the exactly-faithful XLA f64 path
        # wherever the double-single emulation could even in principle
        # flip a u16 cell vs f64 (K >~ 2^23; bounded by M * 2^iters —
        # advisor r4). Explicit fused_scan* opt-in still works up to
        # the in-kernel int32 bound.
        return False
    if not _dyadic_grid_fits_int32(
        shape[-1], math.ceil(math.log2(config.consensus_precision))
    ):
        # Same fallback-pairing gate as fused_scan_eligible (advisor r5
        # low): beyond the int32 bound the fused quantize fallback
        # (plain jnp.sum) and the XLA fallback (blocked miner_sum) can
        # drift one ulp, so auto must not pair them.
        return False
    if jax.default_backend() != "tpu":
        return False
    Bb = shape[0] if len(shape) == 4 else 1
    unit = _unit_bytes(shape[-2:]) * Bb
    return _fits_vmem(unit, _case_scan_mats(mode, save_bonds, streaming))


def _fused_case_scan_kernel(
    *refs,
    iters: int,
    mode: BondsMode,
    mxu: bool,
    m_real: int,
    num_epochs: int,
    liquid: bool,
    reset_mode,
    save_bonds: bool,
    save_incentives: bool,
    save_consensus: bool,
    liquid_overrides: tuple = (None, None),
    rust64: bool = False,
    per_scenario_hp: bool = False,
    per_scenario_rst: bool = False,
    has_carry: bool = False,
    return_carry: bool = False,
):
    """One grid step = one epoch of the reference's REAL workload: this
    epoch's weight block `[1, (Bb,) Vp, Mp]` and stake block
    `[1, (Bb,) Vp, 1]` are streamed from HBM (Pallas prefetches step
    e+1's blocks during step e's compute), the bond state stays in VMEM
    scratch for the whole scan, and the variant's bond-reset rule
    (reference simulation_utils.py:62-88) is applied in-kernel against
    the previous epoch's consensus held in scratch. An optional leading
    scenario-batch dim advances a whole suite per grid step, with
    per-scenario hyperparameters / reset metadata carried as
    `[Bb, 1, LANES]` VMEM operands replacing the SMEM scalars (the
    `per_scenario_*` flags). scal/rst layouts are documented in
    :func:`fused_case_scan`.

    Chunked streaming (`has_carry`/`return_carry` + the `off` epoch-offset
    scalar): grid step `e` simulates GLOBAL epoch `e + off`, the scratch
    state is seeded from carry operands instead of zeros at local step 0,
    and the final consensus / previous-weights state is emitted alongside
    `final_bonds` so a host driver can thread `[E_chunk, V, M]` slabs
    through repeated dispatches with bitwise-identical results to one
    monolithic scan (engine.simulate_streamed)."""
    refs = list(refs)
    hp_or_scal_ref = refs.pop(0)
    rst_ref = refs.pop(0)
    off_ref = refs.pop(0)
    if has_carry:
        cb_ref = refs.pop(0)
        cc_ref = refs.pop(0)
        cwp_ref = refs.pop(0) if mode is BondsMode.EMA_PREV else None
    s_ref, w_ref, dn_ref, bfin_ref = refs[:4]
    outs = refs[4:]
    bonds_ref = outs.pop(0) if save_bonds else None
    inc_ref = outs.pop(0) if save_incentives else None
    cons_ref = outs.pop(0) if save_consensus else None
    cfin_ref = outs.pop(0) if return_carry else None
    wpfin_ref = (
        outs.pop(0)
        if return_carry and mode is BondsMode.EMA_PREV
        else None
    )
    b_scr = outs.pop(0)
    cprev_scr = outs.pop(0)
    wprev_scr = outs.pop(0) if mode is BondsMode.EMA_PREV else None

    if per_scenario_hp:
        hp = hp_or_scal_ref[...]  # [Bb, 1, LANES]

        def sc(i):
            return hp[..., i : i + 1]  # [Bb, 1, 1]

    else:

        def sc(i):
            return hp_or_scal_ref[i]

    e = pl.program_id(0)
    eg = e + off_ref[0]  # global epoch index across chunks
    first = eg == 0

    @pl.when(e == 0)
    def _init():
        if has_carry:
            b_scr[...] = cb_ref[...]
            cprev_scr[...] = cc_ref[...]
            if wprev_scr is not None:
                wprev_scr[...] = cwp_ref[...]
        else:
            b_scr[...] = jnp.zeros_like(b_scr)
            cprev_scr[...] = jnp.zeros_like(cprev_scr)
            if wprev_scr is not None:
                wprev_scr[...] = jnp.zeros_like(wprev_scr)

    Vp, Mp = b_scr.shape[-2:]
    W = w_ref[...].reshape(b_scr.shape)
    S = s_ref[...].reshape(b_scr.shape[:-1] + (1,))
    # normalize_stake (reference yumas.py:75); padded validator rows are
    # zero so they drop out of the sum. Per-scenario when batched.
    S_n = S / jnp.sum(S, axis=-2, keepdims=True)

    B = b_scr[...]
    if reset_mode is not ResetMode.NONE:
        # Bond-reset injection, mirroring engine._apply_reset (reference
        # simulation_utils.py:62-88): zero the reset miner's column when
        # the rule fires. `epoch > 0` because the reference only tracks
        # B_state/consensus from epoch 1 onward.
        if per_scenario_rst:
            rst = rst_ref[...]  # [Bb, 1, LANES] int32
            ri = rst[..., 0:1]  # [Bb, 1, 1]
            r_epoch = rst[..., 1:2]
        else:
            ri = rst_ref[0]
            r_epoch = rst_ref[1]
        colm = lax.broadcasted_iota(jnp.int32, (1, Mp), 1)
        do = (eg == r_epoch) & (eg > 0) & (ri >= 0)
        if reset_mode is ResetMode.CONDITIONAL:
            idx = jnp.clip(ri, 0, m_real - 1)
            prev_c = jnp.sum(
                jnp.where(colm == idx, cprev_scr[...], 0.0),
                axis=-1,
                keepdims=True,
            )
            do = do & (prev_c == 0.0)
        B = jnp.where((colm == ri) & do, jnp.zeros_like(B), B)

    B_next, D_n, incentive, W_n, C = _epoch_math(
        W,
        S_n,
        B,
        wprev_scr[...] if wprev_scr is not None else None,
        first,
        sc(0),
        sc(1),
        sc(2),
        iters=iters,
        mode=mode,
        mxu=mxu,
        m_real=m_real,
        clip_fallback=first,
        cap_alpha=sc(3),
        decay=sc(4),
        liquid=liquid,
        liquid_scal=(sc(5), sc(6), sc(7), sc(8)),
        liquid_overrides=liquid_overrides,
        rust64=rust64,
    )

    b_scr[...] = B_next
    cprev_scr[...] = C
    if wprev_scr is not None:
        wprev_scr[...] = W_n

    dn_ref[...] = D_n.reshape(dn_ref.shape)
    if bonds_ref is not None:
        bonds_ref[...] = B_next.reshape(bonds_ref.shape)
    if inc_ref is not None:
        inc_ref[...] = incentive.reshape(inc_ref.shape)
    if cons_ref is not None:
        cons_ref[...] = C.reshape(cons_ref.shape)

    @pl.when(e == num_epochs - 1)
    def _emit():
        bfin_ref[...] = b_scr[...]
        if cfin_ref is not None:
            cfin_ref[...] = cprev_scr[...]
        if wpfin_ref is not None:
            wpfin_ref[...] = wprev_scr[...]


@functools.lru_cache(maxsize=None)
def _case_scan_kernel_cached(**params):
    """Memoized kernel closure: repeated `fused_case_scan` call sites
    with identical static params (e.g. the unrolled chunk chain of
    `engine.simulate_generated`) must share ONE kernel-function identity
    — a fresh `functools.partial` per call site defeats the lowering
    cache and re-runs the minutes-scale remote Mosaic compile once per
    chunk instance."""
    return functools.partial(_fused_case_scan_kernel, **params)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mode",
        "reset_mode",
        "mxu",
        "interpret",
        "precision",
        "liquid_alpha",
        "override_consensus_high",
        "override_consensus_low",
        "save_bonds",
        "save_incentives",
        "save_consensus",
        "return_carry",
    ),
)
def fused_case_scan(
    W: jnp.ndarray,  # [E, V, M] per-epoch raw weights
    S: jnp.ndarray,  # [E, V] per-epoch raw stakes
    *,
    reset_index=-1,  # int32 scalar, -1 = none
    reset_epoch=-1,  # int32 scalar, -1 = none
    reset_mode=None,  # ResetMode; None = ResetMode.NONE
    kappa=0.5,
    bond_penalty=1.0,
    bond_alpha=0.1,
    capacity_alpha=0.1,
    decay_rate=0.1,
    liquid_alpha: bool = False,
    alpha_low=0.7,
    alpha_high=0.9,
    override_consensus_high: float | None = None,
    override_consensus_low: float | None = None,
    mode: BondsMode = BondsMode.EMA,
    mxu: bool = False,
    precision: int = 100_000,
    save_bonds: bool = True,
    save_incentives: bool = True,
    save_consensus: bool = False,
    carry: dict | None = None,
    epoch_offset=0,
    return_carry: bool = False,
    interpret: bool | None = None,
):
    """The reference's ACTUAL epoch loop — genuinely different weights
    and stakes every epoch, bond-reset injection included — as one
    Pallas program (all five bond models, liquid alpha and its static
    consensus-quantile overrides in-kernel).

    This is the r2 verdict's top item: `fused_ema_scan` only simulates
    scalar-scaled weights, so every real scenario (reference
    cases.py:51-597, driven by simulation_utils.py:44-107) fell back to
    the XLA scan. Here epoch `e`'s `W[e]`/`S[e]` blocks are streamed from
    HBM with a per-epoch BlockSpec index map — the fetch overlaps the
    previous epoch's compute — while the bond state never leaves VMEM.

    `W`/`S` may carry a leading scenario-batch axis (`W [Bb, E, V, M]`,
    `S [Bb, E, V]`): every grid step then advances the whole suite one
    epoch. Per-scenario reset metadata and hyperparameters (`[Bb]`
    vectors for reset_index/reset_epoch/kappa/bond_penalty/...) ride
    `[Bb, 1, LANES]` VMEM operands, so a case-suite x hyperparameter
    product is ONE dispatch; padded-miner masks are not supported
    batched (suites must share one real miner count — heterogeneous
    suites use the XLA batch engine).

    Chunked streaming (the r4 verdict's top item — true-weights runs
    whose `[E, V, M]` stack exceeds HBM): `carry` seeds the in-kernel
    state from a previous chunk's final state (`{"bonds": [(Bb,) V, M],
    "consensus": [(Bb,) M][, "w_prev": [(Bb,) V, M]]}`, the w_prev key
    required exactly for EMA_PREV), `epoch_offset` (traced int32) is the
    global index of this chunk's first epoch (reset rules and the
    first-epoch bond adoption key off the global index), and
    `return_carry=True` emits `final_consensus` (+ `final_w_prev` for
    EMA_PREV) so the host driver (`engine.simulate_streamed`) can thread
    chunks with bitwise-identical results to one monolithic scan.

    Returns a dict of per-epoch outputs shaped like the XLA engine's scan
    ys (normalized dividends `[(Bb,) E, V]`, plus bonds
    `[(Bb,) E, V, M]` / incentives / consensus per the save flags) plus
    `final_bonds [(Bb,) V, M]`. The dividend-per-1000-tao conversion is
    left to the caller (it needs the raw per-epoch stakes, which the
    caller already holds).
    """
    if reset_mode is None:
        reset_mode = ResetMode.NONE
    if mode not in _SCAN_MODES:
        raise ValueError(f"fused scan does not implement bonds mode {mode}")
    # In x64 parity mode Yuma-0's f64 quantization divide is emulated
    # in-kernel with double-single f32 (_rust64_quantize); the flag is
    # static so f32 mode pays nothing. The emulation's exact integer
    # column sum needs M * 2^iters to fit int32 (default precision:
    # M < 2^14 miners) — beyond that the XLA f64 path is the only
    # faithful engine.
    rust64 = mode is BondsMode.EMA_RUST and bool(jax.config.jax_enable_x64)
    if W.ndim == 4:
        Bb, E, V, M = W.shape
        lead: tuple[int, ...] = (Bb,)
    else:
        E, V, M = W.shape
        lead = ()
    if mxu and not exact_mxu_support_covers(V):
        raise ValueError(
            f"the exact MXU stake split covers V <= 2^14 validators, got "
            f"V={V}; use the VPU path (mxu=False)"
        )
    if E < 1:
        raise ValueError("fused scan requires at least one epoch")
    if S.shape != lead + (E, V):
        raise ValueError(
            f"stakes must be {lead + (E, V)}, got {S.shape}"
        )
    dtype = W.dtype
    iters = int(math.ceil(math.log2(precision)))
    if rust64 and (M << iters) >= 2**31:
        raise ValueError(
            "the double-single f64-quantize emulation needs M * 2^iters "
            f"< 2^31 for its exact int32 column sum (M={M}, "
            f"precision={precision}); use the XLA epoch path"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    if not _fits_vmem(
        _unit_bytes(W.shape[-2:]) * (Bb if lead else 1),
        _case_scan_mats(
            mode, save_bonds, streaming=carry is not None or return_carry
        ),
    ):
        resident = _case_scan_resident_bytes(W.shape, mode, save_bonds)
        raise ValueError(
            f"{list(lead) + [V, M]} too large for the VMEM-resident fused "
            f"case scan (~{resident // 2**20} MiB live); use the XLA path"
        )
    # Epoch-major layout for the per-epoch BlockSpec stream: the batch
    # (if any) rides between the epoch index and the [Vp, Mp] block.
    # Tile-aligned shapes skip the zero-init + set copy entirely — the
    # padded materialization is a full extra HBM pass over the largest
    # array on the hot streaming path (advisor r4 finding).
    padded = (Vp, Mp) != (V, M)
    W_em = jnp.moveaxis(W, -3, 0) if lead else W  # [E, (Bb,) V, M]
    S_em = jnp.moveaxis(jnp.asarray(S, dtype), -2, 0) if lead else jnp.asarray(S, dtype)
    W_p = (
        jnp.zeros((E,) + lead + (Vp, Mp), dtype)
        .at[..., :V, :M]
        .set(W_em)
        if padded
        else W_em
    )
    S_p = (
        jnp.zeros((E,) + lead + (Vp, 1), dtype)
        .at[..., :V, 0]
        .set(S_em)
        if Vp != V
        else S_em[..., None]
    )
    if liquid_alpha:
        # The traced-scalar logit branch of liquid_alpha_rate — the one
        # the jitted XLA oracle takes (alpha bounds are traced pytree
        # leaves), so the fused path mirrors its rounding.
        al = jnp.asarray(alpha_low, dtype)
        ah = jnp.asarray(alpha_high, dtype)
        logit_low = jnp.log(1.0 / al - 1.0)
        logit_num = jnp.log(1.0 / ah - 1.0) - logit_low
    else:
        al = ah = logit_low = logit_num = jnp.zeros((), dtype)
    hp_vals = [
        jnp.asarray(kappa, dtype),
        jnp.asarray(bond_penalty, dtype),
        jnp.asarray(bond_alpha, dtype),
        jnp.asarray(capacity_alpha, dtype),
        jnp.asarray(decay_rate, dtype),
        logit_low,
        logit_num,
        al,
        ah,
    ]
    hp_operand, per_hp = _pack_hp(hp_vals, lead, dtype)
    # Reset metadata: SMEM scalars unbatched; [Bb, 1, LANES] int32 VMEM
    # vectors (broadcast as needed) when batched.
    ri_v = jnp.asarray(reset_index, jnp.int32)
    re_v = jnp.asarray(reset_epoch, jnp.int32)
    per_rst = bool(lead)
    if per_rst:
        rst = jnp.zeros(lead + (1, _LANES), jnp.int32)
        rst = rst.at[:, 0, 0].set(jnp.broadcast_to(ri_v, lead))
        rst = rst.at[:, 0, 1].set(jnp.broadcast_to(re_v, lead))
    else:
        rst = jnp.stack([ri_v, re_v])
    off = jnp.asarray(epoch_offset, jnp.int32).reshape(1)

    has_carry = carry is not None
    carry_ops: list = []
    if has_carry:
        need = {"bonds", "consensus"} | (
            {"w_prev"} if mode is BondsMode.EMA_PREV else set()
        )
        if set(carry) != need:
            raise ValueError(
                f"carry must have exactly keys {sorted(need)} for "
                f"mode {mode}, got {sorted(carry)}"
            )

        def pad_vm(x):
            x = jnp.asarray(x, dtype)
            if x.shape != lead + (V, M):
                raise ValueError(
                    f"carry matrix must be {lead + (V, M)}, got {x.shape}"
                )
            if not padded:
                return x
            return jnp.zeros(lead + (Vp, Mp), dtype).at[..., :V, :M].set(x)

        cc = jnp.asarray(carry["consensus"], dtype)
        if cc.shape != lead + (M,):
            raise ValueError(
                f"carry consensus must be {lead + (M,)}, got {cc.shape}"
            )
        cc_p = (
            jnp.zeros(lead + (1, Mp), dtype).at[..., 0, :M].set(cc)
            if Mp != M
            else cc[..., None, :]
        )
        carry_ops = [pad_vm(carry["bonds"]), cc_p]
        if mode is BondsMode.EMA_PREV:
            carry_ops.append(pad_vm(carry["w_prev"]))

    per_epoch = lambda shape: pl.BlockSpec(  # noqa: E731
        (1,) + shape,
        lambda e: (e,) + tuple(0 for _ in shape),
        memory_space=pltpu.VMEM,
    )
    fixed = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda e: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )

    out_specs = [per_epoch(lead + (Vp, 1)), fixed(lead + (Vp, Mp))]
    out_shape = [
        jax.ShapeDtypeStruct((E,) + lead + (Vp, 1), dtype),
        jax.ShapeDtypeStruct(lead + (Vp, Mp), dtype),
    ]
    if save_bonds:
        out_specs.append(per_epoch(lead + (Vp, Mp)))
        out_shape.append(jax.ShapeDtypeStruct((E,) + lead + (Vp, Mp), dtype))
    if save_incentives:
        out_specs.append(per_epoch(lead + (1, Mp)))
        out_shape.append(jax.ShapeDtypeStruct((E,) + lead + (1, Mp), dtype))
    if save_consensus:
        out_specs.append(per_epoch(lead + (1, Mp)))
        out_shape.append(jax.ShapeDtypeStruct((E,) + lead + (1, Mp), dtype))
    if return_carry:
        out_specs.append(fixed(lead + (1, Mp)))
        out_shape.append(jax.ShapeDtypeStruct(lead + (1, Mp), dtype))
        if mode is BondsMode.EMA_PREV:
            out_specs.append(fixed(lead + (Vp, Mp)))
            out_shape.append(jax.ShapeDtypeStruct(lead + (Vp, Mp), dtype))

    scratch = [
        pltpu.VMEM(lead + (Vp, Mp), dtype),
        pltpu.VMEM(lead + (1, Mp), dtype),
    ]
    if mode is BondsMode.EMA_PREV:
        scratch.append(pltpu.VMEM(lead + (Vp, Mp), dtype))

    res = pl.pallas_call(
        _case_scan_kernel_cached(
            iters=iters,
            mode=mode,
            mxu=mxu,
            m_real=M,
            num_epochs=E,
            liquid=liquid_alpha,
            reset_mode=reset_mode,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=save_consensus,
            liquid_overrides=(
                override_consensus_high,
                override_consensus_low,
            ),
            rust64=rust64,
            per_scenario_hp=per_hp,
            per_scenario_rst=per_rst,
            has_carry=has_carry,
            return_carry=return_carry,
        ),
        grid=(E,),
        in_specs=[
            fixed(lead + (1, _LANES))
            if per_hp
            else pl.BlockSpec(memory_space=pltpu.SMEM),
            fixed(lead + (1, _LANES))
            if per_rst
            else pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ]
        + [fixed(op.shape) for op in carry_ops]
        + [
            per_epoch(lead + (Vp, 1)),
            per_epoch(lead + (Vp, Mp)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT,
            dimension_semantics=("arbitrary",),
        ),
    )(hp_operand, rst, off, *carry_ops, S_p, W_p)

    res = list(res)
    dn = res.pop(0)  # [E, (Bb,) Vp, 1]
    if lead:
        dn = jnp.moveaxis(dn, 0, 1)  # [Bb, E, Vp, 1]
    out = {
        "dividends_normalized": dn[..., :V, 0],
        "final_bonds": res.pop(0)[..., :V, :M],
    }
    if save_bonds:
        b = res.pop(0)
        out["bonds"] = (jnp.moveaxis(b, 0, 1) if lead else b)[..., :V, :M]
    if save_incentives:
        i = res.pop(0)
        out["incentives"] = (jnp.moveaxis(i, 0, 1) if lead else i)[..., 0, :M]
    if save_consensus:
        c = res.pop(0)
        out["consensus"] = (jnp.moveaxis(c, 0, 1) if lead else c)[..., 0, :M]
    if return_carry:
        out["final_consensus"] = res.pop(0)[..., 0, :M]
        if mode is BondsMode.EMA_PREV:
            out["final_w_prev"] = res.pop(0)[..., :V, :M]
    return out


# ---------------------------------------------------------------------------
# the epoch-tiled varying-weights scan (ISSUE 15): fused_case_scan's
# twin for workloads where one epoch's [Vp, Mp] block cannot fill the
# chip — T epochs' bond-independent math runs as ONE batched pass.

#: Epoch-tile ceiling for :func:`fused_varying_scan`. Beyond ~16 the
#: batched consensus phase is compute-bound anyway and the tile only
#: inflates the double-buffered slab residency; the admission model
#: (`_varying_scan_mats`) shrinks the tile below this wherever VMEM
#: demands it.
VARYING_EPOCH_TILE_MAX = 16


def _varying_scan_mats(
    epoch_tile: int, mode: BondsMode, save_bonds: bool,
    streaming: bool = False,
) -> int:
    """EFFECTIVE resident [.., Vp, Mp]-unit mats of
    :func:`fused_varying_scan` at a given epoch tile, for the shared
    VMEM admission model (:func:`_fits_vmem`): the double-buffered
    `[T, .., Vp, Mp]` weight slab (2T), the tile's batched `W_n` and
    `W_clipped` intermediates live across the phase boundary (2T), the
    bond scratch, for EMA_PREV the previous-weights scratch plus the
    tile-shifted clip base (T), per-epoch bond output blocks (2T) when
    saved, and the chunk-carry residency when streaming (same
    accounting as `_case_scan_mats`)."""
    mats = 4 * epoch_tile + 1
    if mode is BondsMode.EMA_PREV:
        mats += epoch_tile + 1
    if save_bonds:
        mats += 2 * epoch_tile
    if streaming:
        mats += 1
        if mode is BondsMode.EMA_PREV:
            mats += 2
    return mats


@functools.lru_cache(maxsize=1024)
def varying_scan_epoch_tile(
    shape,
    mode: BondsMode,
    save_bonds: bool = False,
    streaming: bool = False,
) -> int:
    """Largest epoch tile (<= :data:`VARYING_EPOCH_TILE_MAX`) that
    DIVIDES the workload's epoch count and whose resident set fits the
    measured VMEM budget — the planner's deeper-batching signal
    (`auto` prefers the varying scan only when the tile reaches 2,
    i.e. when the tiling actually buys parallelism over the per-epoch
    case scan). The divisibility requirement keeps the kernel free of
    epoch padding and validity masking: every grid step advances
    exactly `tile` real epochs, so drivers that control their own
    chunk lengths (the Monte-Carlo slab loop, the streaming re-slicer)
    pick tile-friendly chunks instead. Returns 0 when even a
    single-epoch tile does not fit."""
    E = shape[-3]
    Bb = shape[0] if len(shape) == 4 else 1
    unit = _unit_bytes(shape[-2:]) * Bb
    for et in range(min(VARYING_EPOCH_TILE_MAX, max(1, E)), 0, -1):
        if E % et == 0 and _fits_vmem(
            unit, _varying_scan_mats(et, mode, save_bonds, streaming)
        ):
            return et
    return 0


def fused_varying_scan_eligible(
    shape,
    mode: BondsMode,
    config,
    dtype=None,
    save_bonds: bool = True,
    streaming: bool = False,
) -> bool:
    """Whether :func:`fused_varying_scan` can run this workload — the
    `epoch_impl="auto"` predicate for the `fused_varying` /
    `fused_varying_mxu` rungs. Same correctness gates as
    :func:`fused_case_scan_eligible` (mode/dtype/x64 parity/dyadic
    int32/TPU backend) plus the epoch-tile VMEM admission."""
    if mode not in _SCAN_MODES:
        return False
    if dtype is not None and jnp.dtype(dtype) != jnp.float32:
        return False
    if (
        mode is BondsMode.EMA_RUST
        and jax.config.jax_enable_x64
        and (shape[-1] << math.ceil(math.log2(config.consensus_precision)))
        >= 2**23
    ):
        # Same parity-mode guard as the case scan (advisor r4).
        return False
    if not _dyadic_grid_fits_int32(
        shape[-1], math.ceil(math.log2(config.consensus_precision))
    ):
        # Same fallback-pairing gate as fused_case_scan_eligible
        # (advisor r5): auto must not pair the two u16 fallbacks.
        return False
    if jax.default_backend() != "tpu":
        return False
    return (
        varying_scan_epoch_tile(shape, mode, save_bonds, streaming) >= 1
    )


def _fused_varying_scan_kernel(
    *refs,
    iters: int,
    mode: BondsMode,
    mxu: bool,
    m_real: int,
    epoch_tile: int,
    num_tiles: int,
    liquid: bool,
    reset_mode,
    save_bonds: bool,
    save_incentives: bool,
    save_consensus: bool,
    liquid_overrides: tuple = (None, None),
    rust64: bool = False,
    per_scenario_hp: bool = False,
    per_scenario_rst: bool = False,
    has_carry: bool = False,
    return_carry: bool = False,
):
    """One grid step = one EPOCH TILE of `epoch_tile` epochs: the
    `[T, (Bb,) Vp, Mp]` weight slab and `[T, (Bb,) Vp, 1]` stake slab
    stream from HBM per step (Pallas double-buffers the next tile
    during this one's compute), the bond-independent epoch math —
    row-normalize, the 17-halving bisection, u16 quantize, clip, rank
    and the liquid rate — runs ONCE for the whole tile with the epoch
    axis as a leading batch dim (`_consensus_phase` /
    `_clip_rank_rate`: every reduction is per-epoch, so per-epoch
    values are bitwise the per-epoch kernels'), and only the cheap
    bond recurrence (`_bond_phase`) walks the tile sequentially in a
    statically unrolled loop. Small (3v x 2m-class) suites whose padded
    `[8, 128]` block wastes the tile thereby advance T epochs per
    traversal instead of one.

    The tile DIVIDES the epoch count by the wrapper's contract
    (`varying_scan_epoch_tile`), so there is no epoch padding and no
    validity masking: every grid step advances exactly `epoch_tile`
    real epochs. The chunked-streaming / suffix-resume carry contract
    (`has_carry` / `off` / `return_carry`) is the case-scan kernel's,
    unchanged."""
    refs = list(refs)
    hp_or_scal_ref = refs.pop(0)
    rst_ref = refs.pop(0)
    off_ref = refs.pop(0)
    if has_carry:
        cb_ref = refs.pop(0)
        cc_ref = refs.pop(0)
        cwp_ref = refs.pop(0) if mode is BondsMode.EMA_PREV else None
    s_ref, w_ref, dn_ref, bfin_ref = refs[:4]
    outs = refs[4:]
    bonds_ref = outs.pop(0) if save_bonds else None
    inc_ref = outs.pop(0) if save_incentives else None
    cons_ref = outs.pop(0) if save_consensus else None
    cfin_ref = outs.pop(0) if return_carry else None
    wpfin_ref = (
        outs.pop(0)
        if return_carry and mode is BondsMode.EMA_PREV
        else None
    )
    b_scr = outs.pop(0)
    cprev_scr = outs.pop(0)
    wprev_scr = outs.pop(0) if mode is BondsMode.EMA_PREV else None

    if per_scenario_hp:
        hp = hp_or_scal_ref[...]  # [Bb, 1, LANES]

        def sc(i):
            return hp[..., i : i + 1]  # [Bb, 1, 1]

    else:

        def sc(i):
            return hp_or_scal_ref[i]

    e = pl.program_id(0)
    T = epoch_tile

    @pl.when(e == 0)
    def _init():
        if has_carry:
            b_scr[...] = cb_ref[...]
            cprev_scr[...] = cc_ref[...]
            if wprev_scr is not None:
                wprev_scr[...] = cwp_ref[...]
        else:
            b_scr[...] = jnp.zeros_like(b_scr)
            cprev_scr[...] = jnp.zeros_like(cprev_scr)
            if wprev_scr is not None:
                wprev_scr[...] = jnp.zeros_like(wprev_scr)

    state_shape = b_scr.shape  # (Bb,) + (Vp, Mp) or (Vp, Mp)
    Mp = state_shape[-1]
    W = w_ref[...].reshape((T,) + state_shape)
    S = s_ref[...].reshape((T,) + state_shape[:-1] + (1,))
    # normalize_stake (reference yumas.py:75), per epoch per scenario.
    S_n = S / jnp.sum(S, axis=-2, keepdims=True)
    off = off_ref[0]

    # ---- phase 1: bond-independent math, ALL T epochs in one pass.
    W_n, C = _consensus_phase(
        W, S_n, sc(0), iters=iters, mxu=mxu, m_real=m_real, rust64=rust64
    )
    if mode is BondsMode.EMA_PREV:
        # Per-epoch first-global-epoch flags, broadcastable over the
        # tile (the clip fallback at global epoch 0).
        tt = lax.broadcasted_iota(
            jnp.int32, (T,) + (1,) * len(state_shape), 0
        )
        first_b = (e * T + tt + off) == 0
        # Previous epoch's normalized weights: in-tile a shift of W_n,
        # across the tile boundary the carried scratch mat. Valid
        # epochs are a contiguous tile prefix, so shifted values for
        # valid epochs always come from valid (or carried) epochs.
        prev0 = wprev_scr[...][None]
        clip_prev = (
            jnp.concatenate([prev0, W_n[:-1]], axis=0) if T > 1 else prev0
        )
        clip_base = jnp.where(first_b, W_n, clip_prev)
    else:
        clip_base = W_n
    W_clipped, incentive, rate = _clip_rank_rate(
        S_n,
        C,
        clip_base,
        sc(2),
        mode=mode,
        m_real=m_real,
        liquid=liquid,
        liquid_scal=(sc(5), sc(6), sc(7), sc(8)),
        liquid_overrides=liquid_overrides,
    )
    per_epoch_rate = liquid and mode is not BondsMode.CAPACITY

    if reset_mode is not ResetMode.NONE:
        if per_scenario_rst:
            rst = rst_ref[...]  # [Bb, 1, LANES] int32
            ri = rst[..., 0:1]  # [Bb, 1, 1]
            r_epoch = rst[..., 1:2]
        else:
            ri = rst_ref[0]
            r_epoch = rst_ref[1]
        colm = lax.broadcasted_iota(jnp.int32, (1, Mp), 1)

    # ---- phase 2: the bond recurrence, unrolled over the tile.
    B = b_scr[...]
    c_before = cprev_scr[...]
    dn_rows, bond_rows, inc_rows, cons_rows = [], [], [], []
    for t in range(T):
        eg = e * T + t + off  # global epoch index across chunks
        first = eg == 0
        if reset_mode is not ResetMode.NONE:
            # Bond-reset injection, exactly the case-scan kernel's
            # spelling (reference simulation_utils.py:62-88), against
            # the previous epoch's consensus (across the tile/chunk
            # boundary: the carried scratch row).
            do = (eg == r_epoch) & (eg > 0) & (ri >= 0)
            if reset_mode is ResetMode.CONDITIONAL:
                idx = jnp.clip(ri, 0, m_real - 1)
                prev_c = jnp.sum(
                    jnp.where(
                        colm == idx, c_before if t == 0 else C[t - 1], 0.0
                    ),
                    axis=-1,
                    keepdims=True,
                )
                do = do & (prev_c == 0.0)
            B = jnp.where((colm == ri) & do, jnp.zeros_like(B), B)
        B, D_n = _bond_phase(
            S_n[t],
            B,
            W_n[t],
            clip_base[t] if mode is BondsMode.EMA_PREV else W_n[t],
            W_clipped[t],
            incentive[t],
            rate[t] if per_epoch_rate else rate,
            first,
            sc(1),
            mode=mode,
            cap_alpha=sc(3),
            decay=sc(4),
        )
        dn_rows.append(D_n)
        if bonds_ref is not None:
            bond_rows.append(B)
        if inc_ref is not None:
            inc_rows.append(incentive[t])
        if cons_ref is not None:
            cons_rows.append(C[t])

    b_scr[...] = B
    cprev_scr[...] = C[T - 1]
    if wprev_scr is not None:
        wprev_scr[...] = W_n[T - 1]
    dn_ref[...] = jnp.stack(dn_rows, axis=0).reshape(dn_ref.shape)
    if bonds_ref is not None:
        bonds_ref[...] = jnp.stack(bond_rows, axis=0).reshape(bonds_ref.shape)
    if inc_ref is not None:
        inc_ref[...] = jnp.stack(inc_rows, axis=0).reshape(inc_ref.shape)
    if cons_ref is not None:
        cons_ref[...] = jnp.stack(cons_rows, axis=0).reshape(cons_ref.shape)

    @pl.when(e == num_tiles - 1)
    def _emit():
        bfin_ref[...] = b_scr[...]
        if cfin_ref is not None:
            cfin_ref[...] = cprev_scr[...]
        if wpfin_ref is not None:
            wpfin_ref[...] = wprev_scr[...]


@functools.lru_cache(maxsize=None)
def _varying_scan_kernel_cached(**params):
    """Memoized kernel closure — same rationale as
    :func:`_case_scan_kernel_cached`: repeated call sites with equal
    static params must share ONE kernel-function identity or the
    lowering cache (and the minutes-scale remote Mosaic compile) is
    defeated per call site."""
    return functools.partial(_fused_varying_scan_kernel, **params)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mode",
        "reset_mode",
        "mxu",
        "interpret",
        "precision",
        "liquid_alpha",
        "override_consensus_high",
        "override_consensus_low",
        "save_bonds",
        "save_incentives",
        "save_consensus",
        "return_carry",
        "epoch_tile",
    ),
)
def fused_varying_scan(
    W: jnp.ndarray,  # [E, V, M] per-epoch raw weights
    S: jnp.ndarray,  # [E, V] per-epoch raw stakes
    *,
    reset_index=-1,
    reset_epoch=-1,
    reset_mode=None,
    kappa=0.5,
    bond_penalty=1.0,
    bond_alpha=0.1,
    capacity_alpha=0.1,
    decay_rate=0.1,
    liquid_alpha: bool = False,
    alpha_low=0.7,
    alpha_high=0.9,
    override_consensus_high: float | None = None,
    override_consensus_low: float | None = None,
    mode: BondsMode = BondsMode.EMA,
    mxu: bool = False,
    precision: int = 100_000,
    save_bonds: bool = True,
    save_incentives: bool = True,
    save_consensus: bool = False,
    carry: dict | None = None,
    epoch_offset=0,
    return_carry: bool = False,
    epoch_tile: int | None = None,
    interpret: bool | None = None,
):
    """:func:`fused_case_scan`'s EPOCH-TILED twin — the varying-weights
    fused engine (ISSUE 15): same inputs, same outputs, same carry /
    `epoch_offset` / `return_carry` streaming contract, but each grid
    step advances `epoch_tile` epochs, running all bond-independent
    math (the 17 bisection traversals, the quantize, the rank, the
    liquid fit) as ONE `[T, (Bb,) Vp, Mp]` batched pass and only the
    bond recurrence sequentially. For workloads whose single-epoch
    block underfills the chip — the reference's 3v x 2m cases padded to
    one (8, 128) tile, per-epoch Monte-Carlo at small V x M — this is
    how the varying-weights rung stops paying one whole-chip traversal
    per tiny epoch.

    `epoch_tile=None` picks the largest tile (<=
    :data:`VARYING_EPOCH_TILE_MAX`) that DIVIDES E and fits the VMEM
    admission model; an explicit tile must divide E and fit. The tile
    changes HOW epochs are grouped, never the per-epoch math: the
    consensus / incentive surface is bitwise the per-epoch case scan
    for every tile length, and dividends/bonds match it (and the XLA
    rung) to reduction-order rounding — while runs sharing one
    program (same tile, same chunk length) are bitwise each other,
    which is the invariance the streaming / suffix-resume drivers
    thread chunks on (pinned by tests/unit/test_varying_scan.py).
    """
    if reset_mode is None:
        reset_mode = ResetMode.NONE
    if mode not in _SCAN_MODES:
        raise ValueError(f"fused scan does not implement bonds mode {mode}")
    rust64 = mode is BondsMode.EMA_RUST and bool(jax.config.jax_enable_x64)
    if W.ndim == 4:
        Bb, E, V, M = W.shape
        lead: tuple[int, ...] = (Bb,)
    else:
        E, V, M = W.shape
        lead = ()
    if mxu and not exact_mxu_support_covers(V):
        raise ValueError(
            f"the exact MXU stake split covers V <= 2^14 validators, got "
            f"V={V}; use the VPU path (mxu=False)"
        )
    if E < 1:
        raise ValueError("fused scan requires at least one epoch")
    if S.shape != lead + (E, V):
        raise ValueError(
            f"stakes must be {lead + (E, V)}, got {S.shape}"
        )
    dtype = W.dtype
    iters = int(math.ceil(math.log2(precision)))
    if rust64 and (M << iters) >= 2**31:
        raise ValueError(
            "the double-single f64-quantize emulation needs M * 2^iters "
            "< 2^31 for its exact int32 column sum "
            f"(M={M}, precision={precision}); use the XLA epoch path"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    streaming = carry is not None or return_carry
    if epoch_tile is None:
        epoch_tile = varying_scan_epoch_tile(
            W.shape, mode, save_bonds, streaming=streaming
        )
        if epoch_tile < 1:
            raise ValueError(
                f"{list(W.shape)} too large for the epoch-tiled varying "
                "scan at any tile; use the per-epoch case scan or the "
                "XLA path"
            )
    else:
        epoch_tile = int(epoch_tile)
        if epoch_tile < 1:
            raise ValueError(f"epoch_tile must be >= 1, got {epoch_tile}")
        if E % epoch_tile != 0:
            raise ValueError(
                f"epoch_tile={epoch_tile} must divide the epoch count "
                f"(E={E}): the kernel pads no epochs — drivers pick "
                "tile-friendly chunk lengths instead"
            )
        Bb_ = lead[0] if lead else 1
        if not _fits_vmem(
            _unit_bytes(W.shape[-2:]) * Bb_,
            _varying_scan_mats(epoch_tile, mode, save_bonds, streaming),
        ):
            raise ValueError(
                f"epoch_tile={epoch_tile} does not fit the VMEM budget "
                f"for {list(W.shape)}; lower the tile or use the "
                "per-epoch case scan"
            )
    num_tiles = E // epoch_tile

    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    padded = (Vp, Mp) != (V, M)
    # Epoch-major layout (batch between the tile index and the block),
    # tile-aligned shapes skip the padded materialization exactly as
    # the case scan does.
    W_em = jnp.moveaxis(W, -3, 0) if lead else W  # [E, (Bb,) V, M]
    S_em = (
        jnp.moveaxis(jnp.asarray(S, dtype), -2, 0)
        if lead
        else jnp.asarray(S, dtype)
    )
    W_p = (
        jnp.zeros((E,) + lead + (Vp, Mp), dtype)
        .at[..., :V, :M]
        .set(W_em)
        if padded
        else W_em
    )
    S_p = (
        jnp.zeros((E,) + lead + (Vp, 1), dtype)
        .at[..., :V, 0]
        .set(S_em)
        if Vp != V
        else S_em[..., None]
    )
    if liquid_alpha:
        al = jnp.asarray(alpha_low, dtype)
        ah = jnp.asarray(alpha_high, dtype)
        logit_low = jnp.log(1.0 / al - 1.0)
        logit_num = jnp.log(1.0 / ah - 1.0) - logit_low
    else:
        al = ah = logit_low = logit_num = jnp.zeros((), dtype)
    hp_vals = [
        jnp.asarray(kappa, dtype),
        jnp.asarray(bond_penalty, dtype),
        jnp.asarray(bond_alpha, dtype),
        jnp.asarray(capacity_alpha, dtype),
        jnp.asarray(decay_rate, dtype),
        logit_low,
        logit_num,
        al,
        ah,
    ]
    hp_operand, per_hp = _pack_hp(hp_vals, lead, dtype)
    ri_v = jnp.asarray(reset_index, jnp.int32)
    re_v = jnp.asarray(reset_epoch, jnp.int32)
    per_rst = bool(lead)
    if per_rst:
        rst = jnp.zeros(lead + (1, _LANES), jnp.int32)
        rst = rst.at[:, 0, 0].set(jnp.broadcast_to(ri_v, lead))
        rst = rst.at[:, 0, 1].set(jnp.broadcast_to(re_v, lead))
    else:
        rst = jnp.stack([ri_v, re_v])
    off = jnp.asarray(epoch_offset, jnp.int32).reshape(1)

    has_carry = carry is not None
    carry_ops: list = []
    if has_carry:
        need = {"bonds", "consensus"} | (
            {"w_prev"} if mode is BondsMode.EMA_PREV else set()
        )
        if set(carry) != need:
            raise ValueError(
                f"carry must have exactly keys {sorted(need)} for "
                f"mode {mode}, got {sorted(carry)}"
            )

        def pad_vm(x):
            x = jnp.asarray(x, dtype)
            if x.shape != lead + (V, M):
                raise ValueError(
                    f"carry matrix must be {lead + (V, M)}, got {x.shape}"
                )
            if not padded:
                return x
            return jnp.zeros(lead + (Vp, Mp), dtype).at[..., :V, :M].set(x)

        cc = jnp.asarray(carry["consensus"], dtype)
        if cc.shape != lead + (M,):
            raise ValueError(
                f"carry consensus must be {lead + (M,)}, got {cc.shape}"
            )
        cc_p = (
            jnp.zeros(lead + (1, Mp), dtype).at[..., 0, :M].set(cc)
            if Mp != M
            else cc[..., None, :]
        )
        carry_ops = [pad_vm(carry["bonds"]), cc_p]
        if mode is BondsMode.EMA_PREV:
            carry_ops.append(pad_vm(carry["w_prev"]))

    T = epoch_tile
    per_tile = lambda shape: pl.BlockSpec(  # noqa: E731
        (T,) + shape,
        lambda e: (e,) + tuple(0 for _ in shape),
        memory_space=pltpu.VMEM,
    )
    fixed = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda e: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )

    out_specs = [per_tile(lead + (Vp, 1)), fixed(lead + (Vp, Mp))]
    out_shape = [
        jax.ShapeDtypeStruct((E,) + lead + (Vp, 1), dtype),
        jax.ShapeDtypeStruct(lead + (Vp, Mp), dtype),
    ]
    if save_bonds:
        out_specs.append(per_tile(lead + (Vp, Mp)))
        out_shape.append(
            jax.ShapeDtypeStruct((E,) + lead + (Vp, Mp), dtype)
        )
    if save_incentives:
        out_specs.append(per_tile(lead + (1, Mp)))
        out_shape.append(
            jax.ShapeDtypeStruct((E,) + lead + (1, Mp), dtype)
        )
    if save_consensus:
        out_specs.append(per_tile(lead + (1, Mp)))
        out_shape.append(
            jax.ShapeDtypeStruct((E,) + lead + (1, Mp), dtype)
        )
    if return_carry:
        out_specs.append(fixed(lead + (1, Mp)))
        out_shape.append(jax.ShapeDtypeStruct(lead + (1, Mp), dtype))
        if mode is BondsMode.EMA_PREV:
            out_specs.append(fixed(lead + (Vp, Mp)))
            out_shape.append(jax.ShapeDtypeStruct(lead + (Vp, Mp), dtype))

    scratch = [
        pltpu.VMEM(lead + (Vp, Mp), dtype),
        pltpu.VMEM(lead + (1, Mp), dtype),
    ]
    if mode is BondsMode.EMA_PREV:
        scratch.append(pltpu.VMEM(lead + (Vp, Mp), dtype))

    res = pl.pallas_call(
        _varying_scan_kernel_cached(
            iters=iters,
            mode=mode,
            mxu=mxu,
            m_real=M,
            epoch_tile=T,
            num_tiles=num_tiles,
            liquid=liquid_alpha,
            reset_mode=reset_mode,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=save_consensus,
            liquid_overrides=(
                override_consensus_high,
                override_consensus_low,
            ),
            rust64=rust64,
            per_scenario_hp=per_hp,
            per_scenario_rst=per_rst,
            has_carry=has_carry,
            return_carry=return_carry,
        ),
        grid=(num_tiles,),
        in_specs=[
            fixed(lead + (1, _LANES))
            if per_hp
            else pl.BlockSpec(memory_space=pltpu.SMEM),
            fixed(lead + (1, _LANES))
            if per_rst
            else pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ]
        + [fixed(op.shape) for op in carry_ops]
        + [
            per_tile(lead + (Vp, 1)),
            per_tile(lead + (Vp, Mp)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT,
            dimension_semantics=("arbitrary",),
        ),
    )(hp_operand, rst, off, *carry_ops, S_p, W_p)

    res = list(res)

    def per_epoch_out(x):
        """Move the batch axis out front of the epoch stream."""
        return jnp.moveaxis(x, 0, 1) if lead else x

    dn = per_epoch_out(res.pop(0))  # [(Bb,) E, Vp, 1]
    out = {
        "dividends_normalized": dn[..., :V, 0],
        "final_bonds": res.pop(0)[..., :V, :M],
    }
    if save_bonds:
        out["bonds"] = per_epoch_out(res.pop(0))[..., :V, :M]
    if save_incentives:
        out["incentives"] = per_epoch_out(res.pop(0))[..., 0, :M]
    if save_consensus:
        out["consensus"] = per_epoch_out(res.pop(0))[..., 0, :M]
    if return_carry:
        out["final_consensus"] = res.pop(0)[..., 0, :M]
        if mode is BondsMode.EMA_PREV:
            out["final_w_prev"] = res.pop(0)[..., :V, :M]
    return out


@functools.partial(
    jax.jit,
    static_argnames=("mode", "mxu", "interpret", "precision", "m_real"),
)
def fused_ema_epoch(
    W: jnp.ndarray,
    S_n: jnp.ndarray,
    B_old: jnp.ndarray,
    *,
    w_scale=1.0,
    kappa=0.5,
    bond_penalty=1.0,
    bond_alpha=0.1,
    first_epoch=False,
    clip_base: jnp.ndarray | None = None,
    mode: BondsMode = BondsMode.EMA,
    mxu: bool = False,
    precision: int = 100_000,
    m_real: int | None = None,
    interpret: bool | None = None,
):
    """One fused EMA-family epoch.

    Args:
      W: raw weights `[V, M]` (scaled by `w_scale` in-kernel, so an
        epoch-varying scalar workload costs no extra HBM pass).
      S_n: NORMALIZED stake `[V]` (the kernel does not re-normalize).
      B_old: carried bond state `[V, M]` (zeros + `first_epoch=True` for
        the initial epoch).
      first_epoch: traced bool/0-1 scalar; selects bond adoption.
      clip_base: previous epoch's normalized weights (EMA_PREV only —
        other modes raise, matching yuma_epoch which ignores W_prev for
        them); None clips against this epoch's `W_n`.
      mode: EMA / EMA_RUST / EMA_PREV (CAPACITY/RELATIVE: use yuma_epoch).
      mxu: run stake contractions on the MXU (see module docstring).
      m_real: true miner count when the caller's arrays are already
        padded with dead columns (columns >= m_real are excluded from
        the quantization sum, like `yuma_epoch`'s trailing miner_mask).

    Returns:
      `(B_ema [V,M], D_normalized [V], incentive [M])` — the scan-relevant
      outputs of `yuma_epoch` (other named outputs are dead in the scan
      and intentionally not produced).
    """
    if mode not in _EMA_MODES:
        raise ValueError(f"fused epoch supports the EMA family only, got {mode}")
    if clip_base is not None and mode is not BondsMode.EMA_PREV:
        # The XLA reference kernel (yuma_epoch) ignores W_prev for the
        # other modes; silently honoring it here would diverge from it.
        raise ValueError("clip_base is only meaningful for EMA_PREV")
    # In x64 parity mode Yuma-0's f64 quantization divide is emulated
    # in-kernel with double-single f32 (_rust64_quantize); the flag is
    # static so f32 mode pays nothing. The emulation's exact integer
    # column sum needs M * 2^iters to fit int32 (default precision:
    # M < 2^14 miners) — beyond that the XLA f64 path is the only
    # faithful engine.
    rust64 = mode is BondsMode.EMA_RUST and bool(jax.config.jax_enable_x64)
    V, M = W.shape
    if mxu and not exact_mxu_support_covers(V):
        raise ValueError(
            f"the exact MXU stake split covers V <= 2^14 validators, got "
            f"V={V}; use the VPU path (mxu=False)"
        )
    dtype = W.dtype
    iters = int(math.ceil(math.log2(precision)))
    if rust64 and (M << iters) >= 2**31:
        raise ValueError(
            "the double-single f64-quantize emulation needs M * 2^iters "
            f"< 2^31 for its exact int32 column sum (M={M}, "
            f"precision={precision}); use the XLA epoch path"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    m_real = M if m_real is None else m_real
    if not 0 < m_real <= M:
        raise ValueError(f"m_real must be in (0, {M}], got {m_real}")
    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    padded = (Vp, Mp) != (V, M)

    def pad(x):
        if not padded:
            return x
        return jnp.zeros((Vp, Mp), dtype).at[:V, :M].set(x)

    W_p = pad(W)
    B_p = pad(B_old)
    S_p = jnp.zeros((Vp, 1), dtype).at[:V, 0].set(jnp.asarray(S_n, dtype))
    has_clip = clip_base is not None
    scal = jnp.stack(
        [
            jnp.asarray(w_scale, dtype),
            jnp.asarray(kappa, dtype),
            jnp.asarray(bond_penalty, dtype),
            jnp.asarray(bond_alpha, dtype),
            jnp.asarray(first_epoch, dtype),
        ]
    )

    vm = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    operands = [scal, S_p, W_p]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        vm((Vp, 1)),
        vm((Vp, Mp)),
    ]
    if has_clip:
        operands.append(pad(clip_base))
        in_specs.append(vm((Vp, Mp)))
    operands.append(B_p)
    in_specs.append(vm((Vp, Mp)))

    B_ema, D, inc = pl.pallas_call(
        functools.partial(
            _fused_ema_epoch_kernel,
            iters=iters,
            mode=mode,
            mxu=mxu,
            m_real=m_real,
            has_clip_base=has_clip,
            rust64=rust64,
        ),
        in_specs=in_specs,
        out_specs=[vm((Vp, Mp)), vm((Vp, 1)), vm((1, Mp))],
        out_shape=[
            jax.ShapeDtypeStruct((Vp, Mp), dtype),
            jax.ShapeDtypeStruct((Vp, 1), dtype),
            jax.ShapeDtypeStruct((1, Mp), dtype),
        ],
        interpret=interpret,
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
    )(*operands)
    return B_ema[:V, :M], D[:V, 0], inc[0, :M]
