"""Fused Pallas TPU kernels: whole consensus epochs (and whole epoch
scans) resident in VMEM.

The unfused epoch (`models/epoch.py::yuma_epoch`) lowers to ~45 XLA
elementwise passes over the `[V, M]` weight/bond arrays; at 256x4096 that
is VPU-roofline-bound at ~55 us/epoch on a v5e chip. This kernel runs the
entire epoch pipeline —

    scale -> row-normalize -> 17-step bisection consensus -> u16 quantize
    -> clip -> rank/incentive -> bond update -> dividends

(bond update = blended/column-normalized EMA for the Yuma 0/1/2 family;
:func:`fused_ema_scan` additionally covers the Yuma 3 capacity-purchase
and Yuma 4 relative-bond models plus liquid alpha, so every named
version has a fused scan path — Yuma 0 only outside x64 parity mode)

— as ONE Pallas program with W, B, and every intermediate resident in
VMEM, and (optionally) the three stake contractions (bisection support,
rank, nothing else reduces over V) on the MXU instead of the VPU. At
256x4096 with weights varying every epoch (nothing hoistable) and long
scans (per-dispatch tunnel latency amortized), the per-epoch MXU variant
runs ~47k epochs/s (~21 us/epoch) vs ~17k for the unfused XLA epoch
(~59 us/epoch) on one v5e chip; :func:`fused_ema_scan` — the whole scan
as a single Pallas program with the bond state never leaving VMEM —
reaches ~62k (~16 us/epoch), the bench.py headline.

Numerics:
- `mxu=False` (default): all reductions on the VPU in f32. Matches the
  XLA kernel to reduction-order rounding (~1e-9 on bonds at 256x4096);
  the bisection support sum is the same compare/select/sum sequence the
  XLA path fuses, so consensus grid flips do not occur in practice.
- `mxu=True` (bench fast path): support and rank ride the MXU's bf16x3
  f32 decomposition. Support values can differ from the VPU sum by ~1 ulp,
  which near `support == kappa` can flip one 2^-17 consensus grid point
  (observed max bond deviation ~4e-5 at 256x4096). Opt-in, for throughput
  sweeps where the CSV-parity contract is not in play.

Reference semantics reproduced (same as `yuma_epoch`, reference
yumas.py:61-282): `+1e-6` row-normalization epsilon, strict `>` in the
bisection support test (yumas.py:89-91), truncating u16 quantization
(yumas.py:97), epsilon-free column normalization for Yuma 1/2 bonds
(yumas.py:228) vs `+1e-6` + EMA re-norm for Yuma 0 (yumas.py:113-116,
147-149), first-epoch bond adoption (yumas.py:145), and the `1e-6`
dividend-normalization epsilon (yumas.py:262).

Liquid alpha (per-miner EMA rates from consensus quantiles) is fused in
the scan kernel: the quantiles are order statistics on the u16 grid,
selected by an integer counting-bisection (no sort needed — see
`_liquid_rate_on_grid`); only the static quantile *overrides* stay
XLA-only. The per-epoch `fused_ema_epoch` remains liquid-free. Likewise
the x64 parity mode's Yuma-0 float64 quantization divide (reference
yumas.py:81,97): Pallas TPU kernels are f32-only, so the EMA_RUST mode
raises under `jax_enable_x64` rather than silently diverging from the
XLA path's f64 grid. Padded miner columns (from heterogeneous-case
batching) are handled by passing the true miner count `m_real`; padded
columns are excluded from the quantization sum and produce zero
bonds/incentive.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from yuma_simulation_tpu.models.epoch import _EMA_MODES, MAXINT, BondsMode

_LANES = 128
_SUBLANES = 8
_VMEM_LIMIT = 110 * 1024 * 1024  # v5e has 128 MiB; leave headroom


def _round_up(x: int, mult: int) -> int:
    return (x + mult - 1) // mult * mult


def _support(S_col, mask, mxu: bool):
    """Stake contraction over validators: `[V,1] x [V,T] -> [1,T]`."""
    if mxu:
        return jax.lax.dot_general(
            S_col.T, mask, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    return jnp.sum(mask * S_col, axis=0, keepdims=True)


def _liquid_rate_on_grid(
    C, logit_low, logit_num, alpha_low, alpha_high, *, n: int
):
    """Per-miner liquid-alpha EMA rate from the quantized consensus row
    `[1, Mp]`, computed WITHOUT a sort (Mosaic has none): every C value
    lies on the u16 grid, so each quantile's order statistics are found
    by a 16-halving integer counting-bisection — `[Mp]`-wide counts, a
    rounding-free exact selection. Linear interpolation between the two
    adjacent order statistics then matches `jnp.quantile`'s "linear"
    method; the logistic fit mirrors
    :func:`yuma_simulation_tpu.ops.liquid.liquid_alpha_rate`'s
    traced-scalar branch (the one the jitted XLA oracle takes), with
    `logit_num = logit_high - logit_low` precomputed by the caller.
    `n` is the (static) real miner count; padded columns are excluded
    from the counts but still receive a rate (their bonds are zero).
    """
    dtype = C.dtype
    Mp = C.shape[-1]
    col = lax.broadcasted_iota(jnp.int32, (1, Mp), 1)
    real = col < n
    C_int = jnp.round(C * 65535.0).astype(jnp.int32)

    def kth(k: int):
        # Smallest grid integer v with #{real C_int <= v} >= k+1 — the
        # k-th smallest (0-indexed). 16 halvings cover [0, 65535].
        def body(_, carry):
            lo, hi = carry
            mid = (lo + hi) // 2
            cnt = jnp.sum(jnp.where(real & (C_int <= mid), 1, 0))
            ok = cnt >= k + 1
            return jnp.where(ok, lo, mid + 1), jnp.where(ok, mid, hi)

        _, hi = lax.fori_loop(
            0, 16, body, (jnp.int32(0), jnp.int32(65535)), unroll=True
        )
        # Same division that built C, so the value is bitwise C's.
        return hi.astype(dtype) / 65535.0

    def quant(q: float):
        p = q * (n - 1)
        lo_i, hi_i = int(math.floor(p)), int(math.ceil(p))
        v_lo = kth(lo_i)
        if hi_i == lo_i:
            return v_lo
        frac = p - lo_i
        return v_lo * (1.0 - frac) + kth(hi_i) * frac

    c_high0 = quant(0.75)
    c_low = quant(0.25)
    # Degenerate spread: fall back to the 0.99 quantile (yumas.py:132-133).
    c_high = jnp.where(c_high0 == c_low, quant(0.99), c_high0)
    a = logit_num / (c_low - c_high)
    b = logit_low + a * c_low
    sig = 1.0 / (1.0 + jnp.asarray(math.e, dtype) ** (-a * C + b))
    return (1.0 - jnp.clip(sig, alpha_low, alpha_high)).astype(dtype)


def _epoch_math(
    W,
    S,
    B_old,
    clip_prev,
    first,
    kappa,
    beta,
    alpha,
    *,
    iters: int,
    mode: BondsMode,
    mxu: bool,
    m_real: int,
    clip_fallback=None,
    cap_alpha=None,
    decay=None,
    liquid: bool = False,
    liquid_scal=None,  # (logit_low, logit_num, alpha_low, alpha_high)
):
    """The one shared epoch pipeline both fused kernels trace:
    row-normalize -> bisection -> u16 quantize -> clip -> incentive ->
    bond update (EMA / capacity purchase / relative) -> normalized
    dividends.

    `clip_prev` is the EMA_PREV clip source (ignored by the other modes;
    None means "clip against this epoch's W_n"). `first` is the traced
    first-epoch predicate for the EMA blend. `clip_fallback` (kwarg)
    additionally selects W_n over `clip_prev` when true — the scan kernel
    uses it at grid step 0 where its scratch is not yet a previous epoch;
    the per-epoch kernel resolves that fallback caller-side and passes
    None. Returns `(B_ema, D_n [V, 1], incentive [1, Mp], W_n)`.
    """
    Mp = W.shape[1]

    W_n = W / (jnp.sum(W, axis=1, keepdims=True) + 1e-6)

    # Bisection consensus on this epoch's weights (always W_n — the
    # EMA_PREV variant clips/bonds against previous weights but computes
    # consensus from the current ones, reference yumas.py:309-325).
    c_lo = jnp.zeros((1, Mp), W.dtype)
    c_hi = jnp.ones((1, Mp), W.dtype)

    def body(_, carry):
        c_lo, c_hi = carry
        c_mid = (c_hi + c_lo) * 0.5
        mask = (W_n > c_mid).astype(W.dtype)  # strict, as the reference
        above = _support(S, mask, mxu) > kappa
        return jnp.where(above, c_mid, c_lo), jnp.where(above, c_hi, c_mid)

    _, c_hi = lax.fori_loop(0, iters, body, (c_lo, c_hi), unroll=True)

    # Truncating u16 quantization; padded columns are excluded from the
    # normalization sum (an all-zero real column still contributes its
    # 2^-17 floor, exactly as the unfused quantize_u16 with miner_mask).
    if m_real != Mp:
        col = lax.broadcasted_iota(jnp.int32, (1, Mp), 1)
        c_hi = jnp.where(col < m_real, c_hi, jnp.zeros_like(c_hi))
    C = c_hi / jnp.sum(c_hi) * 65535.0
    C = C.astype(jnp.int32).astype(W.dtype) / 65535.0

    if clip_prev is not None:
        # Only the EMA_PREV callers pass this (both kernels guard it).
        # Grid step 0 of the scan falls back to this epoch's normalized
        # weights (reference yumas.py:299-300). A select, not an
        # arithmetic blend — a blend would do 0 * clip_prev, which
        # poisons on uninitialized scratch.
        clip_base = (
            clip_prev
            if clip_fallback is None
            else jnp.where(clip_fallback, W_n, clip_prev)
        )
    else:
        clip_base = W_n
    W_clipped = jnp.minimum(clip_base, C)

    R = _support(S, W_clipped, mxu)
    incentive = jnp.nan_to_num(R / jnp.sum(R))

    # Consensus-dependent per-miner EMA rate (liquid alpha); the CAPACITY
    # model never uses a rate (models/epoch.py: the fit is skipped there).
    rate = alpha
    if liquid and mode is not BondsMode.CAPACITY:
        rate = _liquid_rate_on_grid(C, *liquid_scal, n=m_real)

    # Bond update, by model family.
    if mode in _EMA_MODES:
        if mode is BondsMode.EMA_RUST:
            B_t = S * W_clipped
            B_t = jnp.nan_to_num(
                B_t / (jnp.sum(B_t, axis=0, keepdims=True) + 1e-6)
            )
        else:
            bond_base = W_n if mode is BondsMode.EMA else clip_base
            W_b = (1.0 - beta) * bond_base + beta * W_clipped
            B_t = S * W_b
            # no epsilon (reference yumas.py:228, 342)
            B_t = jnp.nan_to_num(B_t / jnp.sum(B_t, axis=0, keepdims=True))

        ema = rate * B_t + (1.0 - rate) * B_old
        B_next = jnp.where(first, B_t, ema)
        if mode is BondsMode.EMA_RUST:
            B_next = jnp.nan_to_num(
                B_next / (jnp.sum(B_next, axis=0, keepdims=True) + 1e-6)
            )
        D = jnp.sum(B_next * incentive, axis=1, keepdims=True)  # [V, 1]
    elif mode is BondsMode.CAPACITY:
        # Stake-capacity purchase, mirroring
        # models.epoch.capacity_bonds_update (reference yumas.py:455-472):
        # the 2^64-1 constant enters f32 arithmetic deliberately.
        cap_vec = S * jnp.asarray(MAXINT, W.dtype)  # [V, 1]
        remaining = jnp.clip(cap_vec - B_old, min=0.0)
        purchase = jnp.minimum(cap_alpha * cap_vec, remaining) * W_n
        B_next = (1.0 - decay) * B_old + purchase
        B_next = jnp.minimum(B_next, cap_vec)
        D = jnp.sum(B_next * incentive, axis=1, keepdims=True)
    else:  # RELATIVE
        # Per-(validator, miner) bonds in [0, 1], mirroring
        # models.epoch.relative_bonds_update (reference yumas.py:574-590);
        # dividends are stake-scaled.
        B_dec = B_old * (1.0 - rate)
        remaining = jnp.clip(1.0 - B_dec, min=0.0)
        purchase = jnp.minimum(rate * W_n, remaining)
        B_next = jnp.clip(B_dec + purchase, max=1.0)
        D = S * jnp.sum(B_next * incentive, axis=1, keepdims=True)

    D_n = D / (jnp.sum(D) + 1e-6)
    return B_next, D_n, incentive, W_n


def _fused_ema_epoch_kernel(
    scal_ref,
    s_ref,
    w_ref,
    *rest,
    iters: int,
    mode: BondsMode,
    mxu: bool,
    m_real: int,
    has_clip_base: bool,
):
    """scal = [w_scale, kappa, beta, alpha, first]. `rest` is
    `([clip_ref,] b_ref, bout_ref, d_ref, inc_ref)` — the clip-base
    operand exists only for the EMA_PREV variant so the common case
    doesn't pay an extra 4 MB HBM read per epoch."""
    if has_clip_base:
        clip_ref, b_ref, bout_ref, d_ref, inc_ref = rest
    else:
        b_ref, bout_ref, d_ref, inc_ref = rest

    B_ema, D_n, incentive, _ = _epoch_math(
        w_ref[:] * scal_ref[0],
        s_ref[:],
        b_ref[:],
        clip_ref[:] if has_clip_base else None,
        scal_ref[4] > 0.5,
        scal_ref[1],
        scal_ref[2],
        scal_ref[3],
        iters=iters,
        mode=mode,
        mxu=mxu,
        m_real=m_real,
    )
    bout_ref[:] = B_ema
    d_ref[:] = D_n
    inc_ref[:] = incentive


#: Every bond model the scan kernel implements; a future BondsMode member
#: must be added here (and to _epoch_math) before the fused scan or the
#: `auto` predicate may accept it.
_SCAN_MODES = _EMA_MODES + (BondsMode.CAPACITY, BondsMode.RELATIVE)


def _scan_resident_bytes(shape, mode: BondsMode) -> int:
    """VMEM bytes the fused scan keeps resident (W + B [+ W_prev]),
    padded to tile boundaries — the one source of truth for both the
    kernel's guard and the `auto` eligibility predicate."""
    V, M = shape
    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    return (3 if mode is BondsMode.EMA_PREV else 2) * Vp * Mp * 4


def fused_scan_eligible(shape, mode: BondsMode, config, dtype=None) -> bool:
    """Whether :func:`fused_ema_scan` can run this workload — the
    `epoch_impl="auto"` predicate: float32 arrays, no consensus-quantile
    overrides, not Yuma-0-under-x64, within the VMEM budget, and on a
    real TPU (interpret mode would be slower than XLA, not faster). All
    five bond models and liquid alpha are supported."""
    if mode not in _SCAN_MODES:
        return False
    if dtype is not None and jnp.dtype(dtype) != jnp.float32:
        # Pallas TPU kernels here are f32-only (module docstring); an
        # f64 input must fall back to XLA, not crash in Mosaic.
        return False
    if (
        config.liquid_alpha
        and mode is not BondsMode.CAPACITY  # CAPACITY skips the fit
        and (
            config.override_consensus_high is not None
            or config.override_consensus_low is not None
        )
    ):
        # The in-kernel quantile selection has no override path.
        return False
    if mode is BondsMode.EMA_RUST and jax.config.jax_enable_x64:
        return False
    if jax.default_backend() != "tpu":
        return False
    return _scan_resident_bytes(shape, mode) * 3 <= _VMEM_LIMIT


def _fused_ema_scan_kernel(
    scal_ref,
    scales_ref,
    s_ref,
    w_ref,
    bout_ref,
    dtot_ref,
    b_scr,
    dacc_scr,
    *wprev_scr,
    iters: int,
    mode: BondsMode,
    mxu: bool,
    m_real: int,
    num_epochs: int,
    liquid: bool,
):
    """One grid step = one epoch; the bond state lives in VMEM scratch for
    the WHOLE scan, so the per-epoch HBM traffic of the lax.scan carry
    (read B, write B — ~8 MB/epoch at 256x4096) disappears entirely, and
    W's block index never changes so Pallas fetches it once. scal =
    [kappa, beta, alpha, cap_alpha, decay, logit_low, logit_num,
    alpha_low, alpha_high]; scales is the per-epoch weight scale in
    SMEM."""
    e = pl.program_id(0)
    first = e == 0

    @pl.when(first)
    def _init():
        b_scr[:] = jnp.zeros_like(b_scr)
        dacc_scr[:] = jnp.zeros_like(dacc_scr)
        if mode is BondsMode.EMA_PREV:
            wprev_scr[0][:] = jnp.zeros_like(wprev_scr[0])

    B_ema, D_n, _, W_n = _epoch_math(
        w_ref[:] * scales_ref[e],
        s_ref[:],
        b_scr[:],
        wprev_scr[0][:] if mode is BondsMode.EMA_PREV else None,
        first,
        scal_ref[0],
        scal_ref[1],
        scal_ref[2],
        iters=iters,
        mode=mode,
        mxu=mxu,
        m_real=m_real,
        clip_fallback=first,
        cap_alpha=scal_ref[3],
        decay=scal_ref[4],
        liquid=liquid,
        liquid_scal=(scal_ref[5], scal_ref[6], scal_ref[7], scal_ref[8]),
    )

    b_scr[:] = B_ema
    dacc_scr[:] = dacc_scr[:] + D_n
    if mode is BondsMode.EMA_PREV:
        wprev_scr[0][:] = W_n

    @pl.when(e == num_epochs - 1)
    def _emit():
        bout_ref[:] = b_scr[:]
        dtot_ref[:] = dacc_scr[:]


@functools.partial(
    jax.jit,
    static_argnames=("mode", "mxu", "interpret", "precision", "liquid_alpha"),
)
def fused_ema_scan(
    W: jnp.ndarray,
    S_n: jnp.ndarray,
    scales: jnp.ndarray,
    *,
    kappa=0.5,
    bond_penalty=1.0,
    bond_alpha=0.1,
    capacity_alpha=0.1,
    decay_rate=0.1,
    liquid_alpha: bool = False,
    alpha_low=0.7,
    alpha_high=0.9,
    mode: BondsMode = BondsMode.EMA,
    mxu: bool = False,
    precision: int = 100_000,
    interpret: bool | None = None,
):
    """The WHOLE epoch scan as one Pallas program (all five bond models,
    liquid alpha included — quantile overrides stay on the XLA path).

    Epoch `e` simulates `W * scales[e]` (the epoch-varying workload of
    `simulate_scaled`). The grid iterates over epochs sequentially; the
    bond state and the dividend accumulator are VMEM scratch that persists
    across grid steps, and W's block index never changes so it is fetched
    from HBM once. Versus `lax.scan` over `fused_ema_epoch`, this removes
    the per-epoch kernel dispatch and the bond-carry HBM round-trip.

    Returns `(B_final [V, M], D_n_total [V])` where `D_n_total` is the sum
    over epochs of the per-epoch NORMALIZED dividends (the caller applies
    the per-validator dividend-per-1000-tao conversion, which is linear in
    `D_n`, to the sum).
    """
    if mode not in _SCAN_MODES:
        raise ValueError(f"fused scan does not implement bonds mode {mode}")
    if mode is BondsMode.EMA_RUST and jax.config.jax_enable_x64:
        raise ValueError(
            "the fused kernel cannot reproduce Yuma-0's float64 quantization "
            "divide (x64 parity mode); use the XLA epoch path"
        )
    V, M = W.shape
    E = scales.shape[0]
    if E < 1:
        # grid=(0,) does not compile, and the output refs would never be
        # written; the other epoch_impl paths return zeros for E=0.
        raise ValueError("fused scan requires at least one epoch")
    dtype = W.dtype
    iters = int(math.ceil(math.log2(precision)))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    # W + B (+ W_prev) resident plus Mosaic temporaries: stay well under
    # the VMEM budget or refuse — there is no automatic fallback, callers
    # must choose the per-epoch "fused"/"fused_mxu" path for such shapes.
    resident = _scan_resident_bytes(W.shape, mode)
    if resident * 3 > _VMEM_LIMIT:
        raise ValueError(
            f"[{V}, {M}] too large for the VMEM-resident fused scan "
            f"(~{resident // 2**20} MiB resident); use the per-epoch path"
        )
    padded = (Vp, Mp) != (V, M)
    W_p = (
        jnp.zeros((Vp, Mp), dtype).at[:V, :M].set(W) if padded else W
    )
    S_p = jnp.zeros((Vp, 1), dtype).at[:V, 0].set(jnp.asarray(S_n, dtype))
    if liquid_alpha:
        # The traced-scalar logit branch of liquid_alpha_rate — the one
        # the jitted XLA oracle takes (alpha bounds are traced pytree
        # leaves), so the fused path mirrors its rounding.
        al = jnp.asarray(alpha_low, dtype)
        ah = jnp.asarray(alpha_high, dtype)
        logit_low = jnp.log(1.0 / al - 1.0)
        logit_num = jnp.log(1.0 / ah - 1.0) - logit_low
    else:
        al = ah = logit_low = logit_num = jnp.zeros((), dtype)
    scal = jnp.stack(
        [
            jnp.asarray(kappa, dtype),
            jnp.asarray(bond_penalty, dtype),
            jnp.asarray(bond_alpha, dtype),
            jnp.asarray(capacity_alpha, dtype),
            jnp.asarray(decay_rate, dtype),
            logit_low,
            logit_num,
            al,
            ah,
        ]
    )

    vm = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda e: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    scratch = [
        pltpu.VMEM((Vp, Mp), dtype),
        pltpu.VMEM((Vp, 1), dtype),
    ]
    if mode is BondsMode.EMA_PREV:
        scratch.append(pltpu.VMEM((Vp, Mp), dtype))

    B_final, D_tot = pl.pallas_call(
        functools.partial(
            _fused_ema_scan_kernel,
            iters=iters,
            mode=mode,
            mxu=mxu,
            m_real=M,
            num_epochs=E,
            liquid=liquid_alpha,
        ),
        grid=(E,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            vm((Vp, 1)),
            vm((Vp, Mp)),
        ],
        out_specs=[vm((Vp, Mp)), vm((Vp, 1))],
        out_shape=[
            jax.ShapeDtypeStruct((Vp, Mp), dtype),
            jax.ShapeDtypeStruct((Vp, 1), dtype),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT,
            dimension_semantics=("arbitrary",),
        ),
    )(scal, scales.astype(dtype), S_p, W_p)
    return B_final[:V, :M], D_tot[:V, 0]


@functools.partial(
    jax.jit,
    static_argnames=("mode", "mxu", "interpret", "precision", "m_real"),
)
def fused_ema_epoch(
    W: jnp.ndarray,
    S_n: jnp.ndarray,
    B_old: jnp.ndarray,
    *,
    w_scale=1.0,
    kappa=0.5,
    bond_penalty=1.0,
    bond_alpha=0.1,
    first_epoch=False,
    clip_base: jnp.ndarray | None = None,
    mode: BondsMode = BondsMode.EMA,
    mxu: bool = False,
    precision: int = 100_000,
    m_real: int | None = None,
    interpret: bool | None = None,
):
    """One fused EMA-family epoch.

    Args:
      W: raw weights `[V, M]` (scaled by `w_scale` in-kernel, so an
        epoch-varying scalar workload costs no extra HBM pass).
      S_n: NORMALIZED stake `[V]` (the kernel does not re-normalize).
      B_old: carried bond state `[V, M]` (zeros + `first_epoch=True` for
        the initial epoch).
      first_epoch: traced bool/0-1 scalar; selects bond adoption.
      clip_base: previous epoch's normalized weights (EMA_PREV only —
        other modes raise, matching yuma_epoch which ignores W_prev for
        them); None clips against this epoch's `W_n`.
      mode: EMA / EMA_RUST / EMA_PREV (CAPACITY/RELATIVE: use yuma_epoch).
      mxu: run stake contractions on the MXU (see module docstring).
      m_real: true miner count when the caller's arrays are already
        padded with dead columns (columns >= m_real are excluded from
        the quantization sum, like `yuma_epoch`'s trailing miner_mask).

    Returns:
      `(B_ema [V,M], D_normalized [V], incentive [M])` — the scan-relevant
      outputs of `yuma_epoch` (other named outputs are dead in the scan
      and intentionally not produced).
    """
    if mode not in _EMA_MODES:
        raise ValueError(f"fused epoch supports the EMA family only, got {mode}")
    if clip_base is not None and mode is not BondsMode.EMA_PREV:
        # The XLA reference kernel (yuma_epoch) ignores W_prev for the
        # other modes; silently honoring it here would diverge from it.
        raise ValueError("clip_base is only meaningful for EMA_PREV")
    if mode is BondsMode.EMA_RUST and jax.config.jax_enable_x64:
        raise ValueError(
            "the fused kernel cannot reproduce Yuma-0's float64 quantization "
            "divide (x64 parity mode); use the XLA epoch path"
        )
    V, M = W.shape
    dtype = W.dtype
    iters = int(math.ceil(math.log2(precision)))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    m_real = M if m_real is None else m_real
    if not 0 < m_real <= M:
        raise ValueError(f"m_real must be in (0, {M}], got {m_real}")
    Vp, Mp = _round_up(V, _SUBLANES), _round_up(M, _LANES)
    padded = (Vp, Mp) != (V, M)

    def pad(x):
        if not padded:
            return x
        return jnp.zeros((Vp, Mp), dtype).at[:V, :M].set(x)

    W_p = pad(W)
    B_p = pad(B_old)
    S_p = jnp.zeros((Vp, 1), dtype).at[:V, 0].set(jnp.asarray(S_n, dtype))
    has_clip = clip_base is not None
    scal = jnp.stack(
        [
            jnp.asarray(w_scale, dtype),
            jnp.asarray(kappa, dtype),
            jnp.asarray(bond_penalty, dtype),
            jnp.asarray(bond_alpha, dtype),
            jnp.asarray(first_epoch, dtype),
        ]
    )

    vm = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    operands = [scal, S_p, W_p]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        vm((Vp, 1)),
        vm((Vp, Mp)),
    ]
    if has_clip:
        operands.append(pad(clip_base))
        in_specs.append(vm((Vp, Mp)))
    operands.append(B_p)
    in_specs.append(vm((Vp, Mp)))

    B_ema, D, inc = pl.pallas_call(
        functools.partial(
            _fused_ema_epoch_kernel,
            iters=iters,
            mode=mode,
            mxu=mxu,
            m_real=m_real,
            has_clip_base=has_clip,
        ),
        in_specs=in_specs,
        out_specs=[vm((Vp, Mp)), vm((Vp, 1)), vm((1, Mp))],
        out_shape=[
            jax.ShapeDtypeStruct((Vp, Mp), dtype),
            jax.ShapeDtypeStruct((Vp, 1), dtype),
            jax.ShapeDtypeStruct((1, Mp), dtype),
        ],
        interpret=interpret,
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
    )(*operands)
    return B_ema[:V, :M], D[:V, 0], inc[0, :M]
