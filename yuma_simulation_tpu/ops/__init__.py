"""Numerical building blocks of the Yuma epoch kernel (pure jittable functions)."""

from yuma_simulation_tpu.ops.consensus import (  # noqa: F401
    quantize_u16,
    stake_weighted_median,
)
from yuma_simulation_tpu.ops.liquid import liquid_alpha_rate  # noqa: F401
from yuma_simulation_tpu.ops.normalize import (  # noqa: F401
    normalize_stake,
    normalize_weight_rows,
)
