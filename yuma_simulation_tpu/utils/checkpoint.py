"""Checkpoint / resume for long Monte-Carlo sweeps.

The reference holds all state in loop locals and writes outputs once at
the end (SURVEY.md §5: checkpoint/resume absent). Sweeps here are pure
and deterministic, so recovery = re-run the missing shards: the sweep is
split into chunks, each chunk's reduced output is written as an `.npz`
snapshot keyed by chunk index, and a resumed run skips chunks whose
snapshot already exists. Orbax is unnecessary at these sizes — outputs
are `[chunk, V]` dividend totals, not model state.

Crash-safety contract (the resilience layer's checkpoint half — see
README.md "Failure semantics & recovery"):

- every file is *published atomically*: written to a temp name the
  completed-chunk glob cannot match, fsync'd, then `rename`d — a crash
  at any instant leaves either the previous state or the new one, never
  a half-written chunk under a valid name;
- every published chunk's sha256 is recorded in a `checksums.json`
  sidecar (itself published atomically), so corruption that happens
  AFTER publish (torn disk write, bit rot, a concurrent writer) is
  detected rather than silently loaded;
- on resume, chunks that fail verification are *requeued*: the corrupt
  file is removed, one `event=checkpoint_chunk_requeued` record is
  logged, and the chunk is re-executed — the resumed sweep's output is
  bitwise what an uninterrupted run produces (the chunk fns are pure);
- at final load, a chunk that fails verification (corruption racing
  the run) is re-executed once; a second failure raises
  :class:`..resilience.errors.CheckpointCorruptionError` instead of
  returning poisoned data.

Chunks published by older versions (no checksum entry) stay resumable:
they are verified by decode-probing the npz instead.
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import json
import logging
import os
import pathlib
import shutil
import uuid
from typing import Callable, Optional

import numpy as np

from yuma_simulation_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

_CHECKSUMS_NAME = "checksums.json"


def _fsync_write(path: pathlib.Path, write_fn) -> None:
    """Write via `write_fn(file)` to `path` with a flush+fsync before
    close, so the subsequent rename publishes durable bytes."""
    with open(path, "wb") as f:
        write_fn(f)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(directory: pathlib.Path) -> None:
    """fsync a directory (POSIX): a rename is only durable once the
    DIRECTORY entry itself is on stable storage — without this, a power
    loss immediately after `rename` can roll the directory back to the
    pre-publish state even though the file data was fsync'd. Best-effort
    (some filesystems/platforms refuse O_RDONLY dir fsync); never
    raises — the publish already happened, durability is the only thing
    at stake."""
    if os.name != "posix":
        return
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _publish(tmp: pathlib.Path, final: pathlib.Path) -> None:
    """Atomically move `tmp` over `final`. POSIX rename is atomic; a
    crash leaves either the old `final` or the new one. The parent
    directory is fsync'd after the rename so the published NAME survives
    power loss, not just the bytes (lease/claim files on a shared store
    depend on this). Cross-filesystem temp files (EXDEV — a caller
    staged `tmp` on local disk, `final` lives on the shared store) fall
    back to copy into the target directory + same-filesystem rename."""
    try:
        tmp.replace(final)
    except OSError as e:
        if e.errno != errno.EXDEV:
            raise
        # tmp and final are on different filesystems: rename cannot be
        # atomic across the boundary, so re-stage IN the target
        # directory (the copy gets its own fsync) and rename there.
        local_tmp = final.with_name(
            f".{final.name}.{uuid.uuid4().hex[:8]}.xdev.tmp"
        )
        with open(tmp, "rb") as src:
            _fsync_write(
                local_tmp,
                lambda f: shutil.copyfileobj(src, f),
            )
        local_tmp.replace(final)
        tmp.unlink(missing_ok=True)
    _fsync_dir(final.parent)


def publish_atomic(
    path: pathlib.Path, data: bytes, *, tmp_dir=None
) -> None:
    """Publish `data` at `path` under the crash-safety contract above:
    written to a temp name, fsync'd, renamed, parent directory fsync'd
    (a published claim/ledger record must survive power loss — the
    rename alone only orders the bytes, not the directory entry). The
    shared primitive for every durable sidecar in the resilience layer
    (checkpoint manifests and checksums here, the supervisor's
    :class:`~yuma_simulation_tpu.resilience.supervisor.FailureLedger`,
    the fleet fabric's lease and result stores).

    `tmp_dir` stages the temp file elsewhere (e.g. fast local disk when
    `path` lives on a shared network store); when that lands on a
    different filesystem the publish transparently falls back to
    copy + same-filesystem rename — still atomic at the target.

    The temp name is writer-unique (pid + nonce): two fleet hosts
    publishing the same shared-store path concurrently (a manifest
    race, a fleet-report refinalize) must not truncate each other's
    in-flight temp — each rename is atomic and the last writer wins
    whole, never interleaved."""
    path = pathlib.Path(path)
    nonce = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
    tmp_parent = pathlib.Path(tmp_dir) if tmp_dir is not None else path.parent
    tmp = tmp_parent / f".{path.name}.{nonce}.tmp"
    _fsync_write(tmp, lambda f: f.write(data))
    _publish(tmp, path)


def append_durable(path: pathlib.Path, data: bytes) -> None:
    """Append `data` to `path` with flush + fsync before returning —
    the crash-safety contract's APPEND half, for JSONL sinks whose
    whole-file republish would be O(total) on a hot thread (the serve
    tier's periodic span/numerics flushes). A crash mid-append can
    leave a torn TAIL line (readers are torn-tail tolerant:
    :func:`read_jsonl_tolerant`), but never a torn prefix — and the
    next full merge republish heals the tail atomically. Every durable
    append in the package routes here so the discipline is checkable
    (jaxlint JX102); parent directories are created on demand. When the
    append CREATES the sink, the parent directory is fsync'd too —
    bytes without a durable directory entry are a file that vanishes
    wholesale on power loss (the same reason `_publish` syncs it)."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    created = not path.exists()
    heal = b""
    if not created:
        # A predecessor killed mid-append leaves a torn tail with no
        # newline; appending straight after it would weld the fragment
        # onto THIS batch's first record and lose both. Terminating the
        # tail first confines the damage to the one already-torn line
        # (which the tolerant reader drops). Matters most under
        # segment rotation, where no later merge republish heals tails.
        try:
            with open(path, "rb") as r:
                r.seek(0, os.SEEK_END)
                if r.tell() > 0:
                    r.seek(-1, os.SEEK_END)
                    if r.read(1) != b"\n":
                        heal = b"\n"
        except OSError:
            heal = b""
    with open(path, "ab") as f:
        f.write(heal + data)
        f.flush()
        os.fsync(f.fileno())
    if created:
        _fsync_dir(path.parent)


def read_jsonl_tolerant(path: pathlib.Path) -> list[dict]:
    """Decode a JSONL sink under the crash-safety contract's reader
    half: torn/undecodable and non-dict lines are dropped with a
    warning, never fatal — a sink written by a crashed or pre-atomic
    writer must still load. The shared reader for every telemetry/
    ledger-style sidecar (spans, metrics, flight bundles)."""
    path = pathlib.Path(path)
    if not path.exists():
        return []
    out: list[dict] = []
    for lineno, line in enumerate(path.read_text().splitlines()):
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            logger.warning(
                "dropping undecodable line %d in %s", lineno, path
            )
            continue
        if isinstance(record, dict):
            out.append(record)
    return out


def _file_sha256(path: pathlib.Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


@dataclasses.dataclass
class CheckpointedSweep:
    """Chunked, resumable, corruption-detecting sweep driver.

    `fn(chunk_index) -> np.ndarray` computes one chunk (typically a
    `shard_map`'d Monte-Carlo batch). `run()` executes all chunks not yet
    on disk (requeueing any whose snapshot fails verification), snapshots
    each atomically with a sha256 recorded in `checksums.json`, and
    returns the concatenated `[total, ...]` result. Metadata
    (`num_chunks`, user `tag`, and a `config` fingerprint) is pinned in
    `manifest.json` and validated on resume so a stale directory cannot
    silently mix configurations.

    `config` should capture everything that determines a chunk's value —
    version name, shapes, seed, hyperparameters. Any JSON-serializable
    pytree works; it is canonicalized (sorted keys) and fingerprinted, so
    resuming with a different config in the same directory fails loudly
    instead of reusing stale `chunk_*.npz` results.
    """

    directory: str | pathlib.Path
    num_chunks: int
    tag: str = ""
    config: object = None

    def __post_init__(self) -> None:
        self.directory = pathlib.Path(self.directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        # In-memory view of checksums.json (this instance is the only
        # writer): loaded once, mutated alongside each publish — without
        # it a thousand-chunk sweep re-parses the growing sidecar per
        # chunk and resume becomes O(n^2) in JSON I/O.
        self._checksums: Optional[dict] = None
        manifest = self.directory / "manifest.json"
        try:
            # No `default=` fallback: a non-JSON value would fingerprint
            # as its repr (memory address) and never match on resume.
            fingerprint = json.dumps(self.config, sort_keys=True)
        except TypeError as e:
            raise TypeError(
                "CheckpointedSweep config must be JSON-serializable "
                f"(got {type(self.config).__name__}): {e}"
            ) from e
        meta = {
            "num_chunks": self.num_chunks,
            "tag": self.tag,
            "config_fingerprint": hashlib.sha256(
                fingerprint.encode()
            ).hexdigest(),
        }
        if manifest.exists():
            found = json.loads(manifest.read_text())
            # Key-by-key so a manifest written before `config_fingerprint`
            # existed (legacy layout) stays resumable; the missing key is
            # backfilled below rather than rejected.
            mismatched = {
                k: (found.get(k), v)
                for k, v in meta.items()
                if k in found and found[k] != v
            }
            if mismatched:
                raise ValueError(
                    f"checkpoint dir {self.directory} holds a different "
                    f"sweep: {mismatched}"
                )
            missing = set(meta) - set(found)
            if missing:
                if "config_fingerprint" in missing and self.completed_chunks():
                    # The legacy manifest never recorded what produced the
                    # existing chunks; stamping the current fingerprint is
                    # an assumption, not a verification.
                    logger.warning(
                        "legacy manifest in %s has no config_fingerprint; "
                        "existing chunks are assumed (not verified) to match "
                        "the current config",
                        self.directory,
                    )
                # Backfill only what's absent; keys written by a newer
                # version (present only in the old manifest) survive.
                self._write_json(manifest, found | {k: meta[k] for k in missing})
        else:
            self._write_json(manifest, meta)

    # -- atomic JSON sidecars ------------------------------------------

    def _write_json(self, path: pathlib.Path, obj) -> None:
        publish_atomic(path, json.dumps(obj).encode())

    def _load_checksums(self) -> dict:
        if self._checksums is not None:
            return self._checksums
        path = self.directory / _CHECKSUMS_NAME
        if not path.exists():
            self._checksums = {}
            return self._checksums
        try:
            self._checksums = json.loads(path.read_text())
        except (json.JSONDecodeError, OSError):
            # A corrupt sidecar must not brick the directory: fall back
            # to probe-based verification for every chunk.
            logger.warning(
                "unreadable %s in %s; falling back to decode-probe "
                "verification", _CHECKSUMS_NAME, self.directory,
            )
            self._checksums = {}
        return self._checksums

    def _record_checksum(self, i: int, digest: str) -> None:
        sums = self._load_checksums()
        sums[f"{i:05d}"] = digest
        self._write_json(self.directory / _CHECKSUMS_NAME, sums)

    def _drop_checksum(self, i: int) -> None:
        sums = self._load_checksums()
        if sums.pop(f"{i:05d}", None) is not None:
            self._write_json(self.directory / _CHECKSUMS_NAME, sums)

    # -- chunk inventory -----------------------------------------------

    def _chunk_path(self, i: int) -> pathlib.Path:
        return self.directory / f"chunk_{i:05d}.npz"

    def completed_chunks(self) -> list[int]:
        """Chunk indices with a PUBLISHED snapshot (name-level only; use
        :meth:`verify_chunk` / `run()` for integrity)."""
        done = []
        for p in self.directory.glob("chunk_*.npz"):
            # A crash can leave partial files behind; only fully published
            # chunks (exact chunk_NNNNN.npz names) count.
            tail = p.stem.split("_", 1)[1]
            if tail.isdigit():
                done.append(int(tail))
        return sorted(done)

    def verify_chunk(self, i: int) -> bool:
        """Whether chunk `i`'s snapshot is present and intact: sha256
        against the checksum sidecar when recorded, else (legacy chunks)
        a full decode probe."""
        path = self._chunk_path(i)
        if not path.exists():
            return False
        recorded = self._load_checksums().get(f"{i:05d}")
        if recorded is not None:
            return _file_sha256(path) == recorded
        try:
            with np.load(path, allow_pickle=False) as z:
                z["result"]
            return True
        except Exception:
            return False

    def _try_load(self, i: int):
        """Decode chunk `i`'s payload, or None if the file is missing or
        undecodable (the caller requeues)."""
        try:
            return np.load(self._chunk_path(i), allow_pickle=False)["result"]
        except Exception:
            return None

    def corrupt_chunks(self) -> list[int]:
        """Published chunks that fail verification (truncated, bit-rotted,
        or undecodable) — what `run()` will requeue."""
        return [i for i in self.completed_chunks() if not self.verify_chunk(i)]

    # -- execution ------------------------------------------------------

    def _execute_chunk(self, fn, i: int) -> None:
        """Run chunk `i`, publish its snapshot atomically, record its
        checksum. The temp name is one the completed-chunk glob cannot
        match, so a crash mid-write is invisible to resume."""
        result = np.asarray(fn(i))
        tmp = self.directory / f"partial_{i:05d}.tmp"
        # savez gets an open handle so it cannot append its own .npz
        # suffix to the temp name; fsync before the rename so the
        # published name always refers to durable bytes.
        _fsync_write(tmp, lambda f: np.savez(f, result=result))
        digest = _file_sha256(tmp)
        published_bytes = tmp.stat().st_size
        _publish(tmp, self._chunk_path(i))
        self._record_checksum(i, digest)
        try:
            from yuma_simulation_tpu.telemetry.metrics import get_registry

            get_registry().counter(
                "checkpoint_bytes",
                help="bytes of published checkpoint chunk snapshots",
            ).inc(published_bytes)
        except Exception:
            pass
        # Test-only hook: deterministic post-publish corruption
        # (resilience fault injection) to exercise detect-and-requeue.
        from yuma_simulation_tpu.resilience import faults

        faults.mangle_chunk_file(self._chunk_path(i), i)

    def run(
        self,
        fn: Callable[[int], np.ndarray],
        *,
        progress: Optional[Callable[[int, int], None]] = None,
    ) -> np.ndarray:
        """Execute missing chunks (requeueing corrupt ones), snapshot
        each, return all results concatenated along axis 0 in chunk
        order."""
        from yuma_simulation_tpu.resilience.errors import (
            CheckpointCorruptionError,
        )

        published = self.completed_chunks()
        done = set()
        recorded = self._load_checksums()
        for i in published:
            if self.verify_chunk(i):
                done.add(i)
                if f"{i:05d}" not in recorded:
                    # Legacy chunk that passed the decode probe: stamp
                    # its current digest so corruption from here on is
                    # checksum-detectable (the probe only proves the npz
                    # decodes today, not that it stays intact).
                    self._record_checksum(i, _file_sha256(self._chunk_path(i)))
            else:
                # Detect-and-requeue: remove the corrupt snapshot so the
                # chunk re-executes below; one structured record per
                # requeue so an operator can audit what was recomputed.
                log_event(
                    logger,
                    "checkpoint_chunk_requeued",
                    directory=str(self.directory),
                    chunk=i,
                    reason="verification_failed",
                )
                self._chunk_path(i).unlink(missing_ok=True)
                self._drop_checksum(i)
        if done:
            logger.info(
                "resuming sweep in %s: %d/%d chunks already done",
                self.directory,
                len(done),
                self.num_chunks,
            )
        executed = set()
        for i in range(self.num_chunks):
            if i in done:
                continue
            self._execute_chunk(fn, i)
            executed.add(i)
            if progress is not None:
                progress(i, self.num_chunks)
        parts = []
        for i in range(self.num_chunks):
            # Chunks already sha256-verified in the resume pre-pass are
            # not re-hashed (that would double every resume's I/O), but
            # every chunk must still DECODE — corruption racing a long
            # run surfaces as a load failure and requeues below. Chunks
            # executed THIS run are checksum-verified here, which is
            # where injected post-publish corruption (and any real torn
            # write) gets caught.
            part = None
            if i not in executed or self.verify_chunk(i):
                part = self._try_load(i)
            if part is None:
                # Re-execute once, then give up loudly rather than
                # concatenate poisoned bytes.
                log_event(
                    logger,
                    "checkpoint_chunk_requeued",
                    directory=str(self.directory),
                    chunk=i,
                    reason="post_run_verification_failed",
                )
                self._chunk_path(i).unlink(missing_ok=True)
                self._drop_checksum(i)
                self._execute_chunk(fn, i)
                part = self._try_load(i) if self.verify_chunk(i) else None
                if part is None:
                    raise CheckpointCorruptionError(
                        f"chunk {i} in {self.directory} failed "
                        "verification immediately after re-execution; "
                        "the storage under this directory is unreliable"
                    )
            parts.append(part)
        return np.concatenate(parts, axis=0)
