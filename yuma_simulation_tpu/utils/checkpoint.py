"""Checkpoint / resume for long Monte-Carlo sweeps.

The reference holds all state in loop locals and writes outputs once at
the end (SURVEY.md §5: checkpoint/resume absent). Sweeps here are pure
and deterministic, so recovery = re-run the missing shards: the sweep is
split into chunks, each chunk's reduced output is written as an `.npz`
snapshot keyed by chunk index, and a resumed run skips chunks whose
snapshot already exists. Orbax is unnecessary at these sizes — outputs
are `[chunk, V]` dividend totals, not model state.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import pathlib
from typing import Callable, Optional

import numpy as np

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CheckpointedSweep:
    """Chunked, resumable sweep driver.

    `fn(chunk_index) -> np.ndarray` computes one chunk (typically a
    `shard_map`'d Monte-Carlo batch). `run()` executes all chunks not yet
    on disk, snapshots each, and returns the concatenated `[total, ...]`
    result. Metadata (`num_chunks`, user `tag`, and a `config`
    fingerprint) is pinned in `manifest.json` and validated on resume so
    a stale directory cannot silently mix configurations.

    `config` should capture everything that determines a chunk's value —
    version name, shapes, seed, hyperparameters. Any JSON-serializable
    pytree works; it is canonicalized (sorted keys) and fingerprinted, so
    resuming with a different config in the same directory fails loudly
    instead of reusing stale `chunk_*.npz` results.
    """

    directory: str | pathlib.Path
    num_chunks: int
    tag: str = ""
    config: object = None

    def __post_init__(self) -> None:
        self.directory = pathlib.Path(self.directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        manifest = self.directory / "manifest.json"
        try:
            # No `default=` fallback: a non-JSON value would fingerprint
            # as its repr (memory address) and never match on resume.
            fingerprint = json.dumps(self.config, sort_keys=True)
        except TypeError as e:
            raise TypeError(
                "CheckpointedSweep config must be JSON-serializable "
                f"(got {type(self.config).__name__}): {e}"
            ) from e
        meta = {
            "num_chunks": self.num_chunks,
            "tag": self.tag,
            "config_fingerprint": hashlib.sha256(
                fingerprint.encode()
            ).hexdigest(),
        }
        if manifest.exists():
            found = json.loads(manifest.read_text())
            # Key-by-key so a manifest written before `config_fingerprint`
            # existed (legacy layout) stays resumable; the missing key is
            # backfilled below rather than rejected.
            mismatched = {
                k: (found.get(k), v)
                for k, v in meta.items()
                if k in found and found[k] != v
            }
            if mismatched:
                raise ValueError(
                    f"checkpoint dir {self.directory} holds a different "
                    f"sweep: {mismatched}"
                )
            missing = set(meta) - set(found)
            if missing:
                if "config_fingerprint" in missing and self.completed_chunks():
                    # The legacy manifest never recorded what produced the
                    # existing chunks; stamping the current fingerprint is
                    # an assumption, not a verification.
                    logger.warning(
                        "legacy manifest in %s has no config_fingerprint; "
                        "existing chunks are assumed (not verified) to match "
                        "the current config",
                        self.directory,
                    )
                # Backfill only what's absent; keys written by a newer
                # version (present only in the old manifest) survive.
                manifest.write_text(
                    json.dumps(found | {k: meta[k] for k in missing})
                )
        else:
            manifest.write_text(json.dumps(meta))

    def _chunk_path(self, i: int) -> pathlib.Path:
        return self.directory / f"chunk_{i:05d}.npz"

    def completed_chunks(self) -> list[int]:
        done = []
        for p in self.directory.glob("chunk_*.npz"):
            # A crash can leave partial files behind; only fully published
            # chunks (exact chunk_NNNNN.npz names) count.
            tail = p.stem.split("_", 1)[1]
            if tail.isdigit():
                done.append(int(tail))
        return sorted(done)

    def run(
        self,
        fn: Callable[[int], np.ndarray],
        *,
        progress: Optional[Callable[[int, int], None]] = None,
    ) -> np.ndarray:
        """Execute missing chunks, snapshot each, return all results
        concatenated along axis 0 in chunk order."""
        done = set(self.completed_chunks())
        if done:
            logger.info(
                "resuming sweep in %s: %d/%d chunks already done",
                self.directory,
                len(done),
                self.num_chunks,
            )
        for i in range(self.num_chunks):
            if i in done:
                continue
            result = np.asarray(fn(i))
            # Write to a name the completed-chunk glob cannot match, then
            # publish atomically. savez gets an open handle so it cannot
            # append its own .npz suffix to the temp name.
            tmp = self.directory / f"partial_{i:05d}.tmp"
            with open(tmp, "wb") as f:
                np.savez(f, result=result)
            tmp.rename(self._chunk_path(i))
            if progress is not None:
                progress(i, self.num_chunks)
        parts = [
            np.load(self._chunk_path(i), allow_pickle=False)["result"]
            for i in range(self.num_chunks)
        ]
        return np.concatenate(parts, axis=0)
