"""Profiling hooks: jax.profiler traces + wall-clock counters.

SURVEY.md §5 calls for `jax.profiler` trace hooks and epochs-per-second
counters around the scan — the replacement for the reference's total lack
of instrumentation.
"""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

import jax

logger = logging.getLogger(__name__)


def enable_compilation_cache(
    cache_dir: Optional[str] = None, min_compile_secs: float = 1.0
) -> str:
    """Turn on JAX's persistent compilation cache.

    On remote-tunnel TPU runtimes a cold compile of the epoch scan runs
    ~1 min at 256x4096 and grows steeply with shape; the persistent cache
    turns every repeat invocation (benches, probes, CLI runs) into a
    sub-second cache hit. Keyed on the HLO, so stale entries cannot be
    served after code changes. Returns the cache directory used.
    """
    import os

    if cache_dir is None:
        cache_dir = os.environ.get(
            "YUMA_TPU_JAX_CACHE",
            os.path.join(
                os.path.expanduser("~"), ".cache", "yuma_simulation_tpu_jax"
            ),
        )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_secs
    )
    return cache_dir


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Wrap a region in a `jax.profiler` trace (Perfetto/XPlane dump).

    No-op when `log_dir` is None, so call sites can thread a CLI flag
    (both CLIs expose it as ``--profile-dir``) straight through.

    The "trace written" pointer is logged in a ``finally``: the dump the
    profiler flushed on the way out of a FAILING region is exactly the
    one that explains the failure, and the exception must not eat the
    only pointer to it.
    """
    if log_dir is None:
        yield
        return
    try:
        with jax.profiler.trace(log_dir):
            yield
    finally:
        logger.info("profiler trace written to %s", log_dir)


class RecompilationBudgetExceeded(RuntimeError):
    """A :class:`RecompilationSentinel` region compiled more new jit-cache
    entries than its declared budget allows."""


class RecompilationSentinel:
    """Fail loudly when a region re-traces beyond a declared budget.

    The compile-time counterpart of jaxlint's static rules: JX001 can
    prove a *str/bool* param would silently key a recompile per value,
    but a hash-unstable static argument (an object whose ``__eq__`` /
    ``__hash__`` is identity, so every instance is a fresh cache key) or
    a drifting shape only shows up at runtime — on a remote-tunnel TPU
    runtime each such re-trace costs a minutes-scale Mosaic/XLA compile,
    which is exactly the failure this makes a test failure instead of a
    silent 100x slowdown.

    Usage::

        warmup()                       # compile once outside the region
        with RecompilationSentinel(_simulate_scan, budget=0):
            hot_loop()                 # any new cache entry -> raises

    Each tracked function must be a ``jax.jit`` product exposing the
    ``_cache_size()`` introspection hook (every ``PjitFunction`` does);
    entry/exit snapshots are differenced per function, so the report
    names *which* entry point re-traced. ``budget`` is the total number
    of NEW cache entries the region may add across all tracked
    functions (0 = the region must be compile-free; N allows the
    expected cold compiles of a first-call region).

    Under the AOT executable cache (:mod:`..simulation.aot`) the
    sentinel distinguishes cache-hit LOADS from true compiles: an AOT
    **build** (a cache miss that exported a program — a real compile)
    counts against the budget exactly like a tracked re-trace, so a
    budget-0 pin stays a zero-compile pin even when dispatches route
    around the tracked jit entries; a cache-hit load costs no budget
    (the whole point of the cache) but is reported on ``aot_hits`` so
    a region's cache effectiveness is assertable.

    The check runs on clean exit only — an exception inside the region
    propagates untouched (a failing test must not be masked by a
    budget report).
    """

    def __init__(self, *functions, budget: int = 0, label: str = "region"):
        if not functions:
            raise ValueError(
                "RecompilationSentinel needs at least one jitted function "
                "to track"
            )
        for fn in functions:
            if not hasattr(fn, "_cache_size"):
                raise TypeError(
                    f"{getattr(fn, '__name__', fn)!r} exposes no "
                    "_cache_size(); pass the jax.jit-wrapped callable "
                    "itself, not an unjitted wrapper"
                )
        self._functions = functions
        self.budget = budget
        self.label = label
        #: per-function new-entry counts, filled at exit:
        #: ``{qualname: (before, after)}``
        self.report: dict[str, tuple[int, int]] = {}
        self.new_entries: Optional[int] = None
        #: AOT executable-cache activity inside the region, filled at
        #: exit: hits are free loads, builds are true compiles (counted
        #: into ``new_entries``).
        self.aot_hits: int = 0
        self.aot_builds: int = 0

    @staticmethod
    def _name(fn) -> str:
        return getattr(fn, "__qualname__", None) or getattr(
            fn, "__name__", repr(fn)
        )

    @staticmethod
    def _aot_snapshot() -> tuple[int, int]:
        """(hits, builds) of the process AOT cache — zeros when none is
        active (the import is deferred so the sentinel keeps working in
        stripped environments)."""
        try:
            from yuma_simulation_tpu.simulation.aot import process_stats

            stats = process_stats()
            return stats.hits, stats.builds
        except Exception:
            return 0, 0

    def __enter__(self) -> "RecompilationSentinel":
        self._before = [fn._cache_size() for fn in self._functions]
        self._aot_before = self._aot_snapshot()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            return  # don't mask the region's own failure
        wall_seconds = time.perf_counter() - self._t0
        after = [fn._cache_size() for fn in self._functions]
        self.report = {}
        for fn, b, a in zip(self._functions, self._before, after):
            name = self._name(fn)
            while name in self.report:  # same-qualname closures
                name += "'"
            self.report[name] = (b, a)
        # Per-function positive deltas only: a cache shrink elsewhere
        # (eviction, jax.clear_caches) must not cancel out a genuine
        # re-trace in another tracked function.
        aot_after = self._aot_snapshot()
        self.aot_hits = max(0, aot_after[0] - self._aot_before[0])
        self.aot_builds = max(0, aot_after[1] - self._aot_before[1])
        # An AOT build IS a compile (a miss that exported a program);
        # only cache-hit LOADS are budget-free — without this, routing
        # a dispatch through the executable cache would let a cold
        # compile slip past a zero-warm-compile pin unseen.
        self.new_entries = (
            sum(max(0, a - b) for b, a in self.report.values())
            + self.aot_builds
        )
        if self.new_entries:
            # Observability side-channel: every new entry a sentinel
            # region observes lands on the process `recompiles` counter
            # (budget-busting ones included — the raise below must not
            # hide them from the metrics snapshot), and the region's
            # wall time lands on the `compile_seconds` histogram — an
            # upper bound on the compile cost (the region may also have
            # dispatched), which is what makes a SLOW-compile regression
            # visible in the snapshot, not just the cache-miss count.
            try:
                from yuma_simulation_tpu.telemetry.metrics import get_registry

                registry = get_registry()
                registry.counter(
                    "recompiles", help="new jit-cache entries observed"
                ).inc(self.new_entries)
                registry.histogram(
                    "compile_seconds",
                    help=(
                        "wall seconds of sentinel regions that added "
                        "jit-cache entries (compile-time upper bound)"
                    ),
                ).observe(wall_seconds)
                # The cold-start SLO signal (telemetry.slo): the same
                # wall-seconds upper bound, into the mergeable sketch
                # the burn-rate engine evaluates.
                from yuma_simulation_tpu.telemetry.slo import (
                    observe_duration,
                )

                observe_duration("compile_seconds", wall_seconds)
            except Exception:
                pass
        if self.new_entries > self.budget:
            detail = ", ".join(
                f"{name}: {b}->{a}"
                for name, (b, a) in self.report.items()
                if a != b
            )
            if self.aot_builds:
                detail = ", ".join(
                    part
                    for part in (detail, f"aot builds: {self.aot_builds}")
                    if part
                )
            raise RecompilationBudgetExceeded(
                f"{self.label}: {self.new_entries} new jit-cache "
                f"entr{'y' if self.new_entries == 1 else 'ies'} exceed the "
                f"compile budget of {self.budget} ({detail}). A re-trace "
                "in this region means a static arg is hash-unstable or a "
                "shape/dtype drifted — on TPU each one costs a "
                "minutes-scale compile."
            )


@dataclass
class timed:
    """Context manager measuring a block; with `epochs` it IS the
    epoch-rate reporting path: on clean exit the measurement routes
    through the telemetry metrics registry
    (:func:`..telemetry.metrics.record_epoch_rate` — `epochs_total`
    counter + `epochs_per_sec` gauge) and emits exactly one
    ``event=epoch_rate`` record, run/span-stamped like every other
    structured record. Without `epochs` it is a plain labeled timer.

    >>> with timed("scan", epochs=10_000) as t:
    ...     run()
    >>> t.seconds, t.epochs_per_sec
    """

    label: str = "block"
    epochs: Optional[int] = None
    seconds: float = field(default=0.0, init=False)

    def __enter__(self) -> "timed":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        if exc[0] is not None:
            return  # a failing block reports its failure, not a rate
        if self.epochs is not None and self.seconds > 0:
            from yuma_simulation_tpu.telemetry.metrics import record_epoch_rate

            record_epoch_rate(
                self.label,
                epochs=self.epochs,
                seconds=self.seconds,
                logger_=logger,
            )
        else:
            logger.info("%s: %.3fs", self.label, self.seconds)

    @property
    def epochs_per_sec(self) -> Optional[float]:
        if self.epochs is None or self.seconds == 0:
            return None
        return self.epochs / self.seconds
