"""Profiling hooks: jax.profiler traces + wall-clock counters.

SURVEY.md §5 calls for `jax.profiler` trace hooks and epochs-per-second
counters around the scan — the replacement for the reference's total lack
of instrumentation.
"""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

import jax

logger = logging.getLogger(__name__)


def enable_compilation_cache(
    cache_dir: Optional[str] = None, min_compile_secs: float = 1.0
) -> str:
    """Turn on JAX's persistent compilation cache.

    On remote-tunnel TPU runtimes a cold compile of the epoch scan runs
    ~1 min at 256x4096 and grows steeply with shape; the persistent cache
    turns every repeat invocation (benches, probes, CLI runs) into a
    sub-second cache hit. Keyed on the HLO, so stale entries cannot be
    served after code changes. Returns the cache directory used.
    """
    import os

    if cache_dir is None:
        cache_dir = os.environ.get(
            "YUMA_TPU_JAX_CACHE",
            os.path.join(
                os.path.expanduser("~"), ".cache", "yuma_simulation_tpu_jax"
            ),
        )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_secs
    )
    return cache_dir


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Wrap a region in a `jax.profiler` trace (Perfetto/XPlane dump).

    No-op when `log_dir` is None, so call sites can thread a CLI flag
    straight through.
    """
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
    logger.info("profiler trace written to %s", log_dir)


@dataclass
class timed:
    """Context manager measuring a block; optionally derives epochs/sec.

    >>> with timed("scan", epochs=10_000) as t:
    ...     run()
    >>> t.seconds, t.epochs_per_sec
    """

    label: str = "block"
    epochs: Optional[int] = None
    seconds: float = field(default=0.0, init=False)

    def __enter__(self) -> "timed":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        if exc[0] is None:
            logger.info("%s: %.3fs%s", self.label, self.seconds, self._rate())

    def _rate(self) -> str:
        if self.epochs is None or self.seconds == 0:
            return ""
        return f" ({self.epochs / self.seconds:,.0f} epochs/s)"

    @property
    def epochs_per_sec(self) -> Optional[float]:
        if self.epochs is None or self.seconds == 0:
            return None
        return self.epochs / self.seconds
