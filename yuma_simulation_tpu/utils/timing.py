"""The one benchmark-timing discipline, shared by bench.py and
tools/bench_matrix.py (they previously carried hand-synced near-copies).

Remote-TPU runtimes add ~0.1 s of per-dispatch tunnel latency, so any
timed run shorter than a couple of seconds measures mostly dispatch.
`time_best` therefore: warms (compiles) once, grows the work count `n`
ITERATIVELY until a single timed run lasts >= `target_seconds` (one
extrapolation is not enough — per-epoch cost drops as n grows), then
takes the best of `reps` timed runs. `np.asarray` forces the
device->host fetch; on remote runtimes `block_until_ready` alone can
return before execution finishes.
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

DEFAULT_TARGET_SECONDS = 2.0
DEFAULT_REPS = 4


def time_best(
    run: Callable[[int], object],
    n: int,
    *,
    max_n: int = 1 << 20,
    granularity: int = 1,
    target_seconds: float = DEFAULT_TARGET_SECONDS,
    reps: int = DEFAULT_REPS,
) -> tuple[float, int, list[float], float]:
    """Time `run(n)` (which returns a device value; the fetch is forced
    here) and return `(rate, n_timed, times_s, cv)` where
    `rate = n / best` and `cv` is the coefficient of variation
    (population stdev / mean) across the timed repeats — the dispersion
    `tools/perfgate.py` uses to widen its regression tolerance on noisy
    metrics instead of false-failing (0.0 when `reps == 1`).

    `granularity` rounds grown counts down to a multiple the runner can
    actually execute (e.g. whole passes of a fixed-length inner scan, or
    a Monte-Carlo shard count), so `n / best` never over-counts.
    """
    if max_n < granularity:
        # No grid multiple fits under the cap; silently timing one
        # granularity quantum would exceed a bound the caller may use as
        # a hard resource limit (e.g. a shard count).
        raise ValueError(
            f"max_n={max_n} < granularity={granularity}: no timeable "
            "work count satisfies both the divisibility contract and "
            "the cap"
        )

    def on_grid(x: int) -> int:
        # Cap at the largest grid multiple <= max_n so the result both
        # honors the divisibility contract and never exceeds the cap.
        cap = (max_n // granularity) * granularity
        return min(cap, max(granularity, x // granularity * granularity))

    n = on_grid(n)  # the caller's n must honor the divisibility contract too
    np.asarray(run(n))  # compile + warm up
    t0 = time.perf_counter()
    np.asarray(run(n))
    dt = max(time.perf_counter() - t0, 1e-9)  # coarse timers can report 0.0
    while dt < target_seconds:
        grown = on_grid(min(max_n, int(n * max(2.0, 1.25 * target_seconds / dt))))
        if grown <= n:
            # max_n (or its granularity floor) reached — re-timing the
            # same n forever would hang; accept the sub-window run.
            break
        n = grown
        np.asarray(run(n))  # recompile at the timed length
        t0 = time.perf_counter()
        np.asarray(run(n))
        dt = max(time.perf_counter() - t0, 1e-9)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(run(n))
        times.append(time.perf_counter() - t0)
    mean = sum(times) / len(times)
    cv = (
        float(np.std(times) / mean) if len(times) > 1 and mean > 0 else 0.0
    )
    return n / min(times), n, [round(t, 3) for t in times], round(cv, 4)
