"""Aux subsystems: profiling, checkpointed sweeps, structured logging.

The reference has none of these (SURVEY.md §5: tracing/checkpoint/
observability all absent — bare prints only); these are the TPU-native
equivalents sized to this framework's workloads.
"""

from yuma_simulation_tpu.utils.checkpoint import (  # noqa: F401
    CheckpointedSweep,
    append_durable,
    publish_atomic,
)
from yuma_simulation_tpu.utils.profiling import (  # noqa: F401
    enable_compilation_cache,
    profile_trace,
    timed,
)
from yuma_simulation_tpu.utils.logging import setup_logging  # noqa: F401
