"""Structured logging setup (the reference uses bare prints,
SURVEY.md §5)."""

from __future__ import annotations

import logging
import sys


def log_event(
    logger: logging.Logger,
    event: str,
    *,
    level: int = logging.WARNING,
    **fields,
) -> None:
    """Emit one machine-greppable `event=<name> key=value ...` record.

    The resilience layer's contract (engine demotions, quarantined
    lanes, checkpoint requeues) is that every recovery action leaves
    exactly one such line, so an operator can `grep event=` a sweep's
    log and reconstruct what degraded where — values are flat scalars
    on one line, not multi-line prose. Values containing whitespace,
    `=` or quotes (free-text labels, error messages) are double-quoted
    with inner quotes escaped so a key=value tokenizer still parses the
    record. Empty-string fields are dropped (optional labels)."""

    def fmt(v) -> str:
        s = str(v)
        if any(c in s for c in (" ", "\t", "=", '"')):
            return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'
        return s

    payload = " ".join(
        f"{k}={fmt(v)}" for k, v in fields.items() if v != ""
    )
    logger.log(level, "event=%s%s", event, f" {payload}" if payload else "")


def setup_logging(level: int = logging.INFO) -> None:
    """Configure framework-wide logging once, idempotently.

    Multi-host aware: non-zero JAX processes log at WARNING so a pod run
    emits one progress stream instead of `process_count` interleaved ones.
    The process index is only consulted when distributed mode is already
    initialized — `jax.process_index()` would otherwise initialize the
    local-only backend and break a later `initialize_distributed` call.
    """
    root = logging.getLogger("yuma_simulation_tpu")
    if root.handlers:
        return
    try:
        import jax

        if jax.distributed.is_initialized() and jax.process_index() != 0:
            level = max(level, logging.WARNING)
    except Exception:
        pass
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )
    )
    root.addHandler(handler)
    root.setLevel(level)
