"""Structured logging setup (the reference uses bare prints,
SURVEY.md §5)."""

from __future__ import annotations

import logging
import sys


def setup_logging(level: int = logging.INFO) -> None:
    """Configure framework-wide logging once, idempotently.

    Multi-host aware: non-zero JAX processes log at WARNING so a pod run
    emits one progress stream instead of `process_count` interleaved ones.
    The process index is only consulted when distributed mode is already
    initialized — `jax.process_index()` would otherwise initialize the
    local-only backend and break a later `initialize_distributed` call.
    """
    root = logging.getLogger("yuma_simulation_tpu")
    if root.handlers:
        return
    try:
        import jax

        if jax.distributed.is_initialized() and jax.process_index() != 0:
            level = max(level, logging.WARNING)
    except Exception:
        pass
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )
    )
    root.addHandler(handler)
    root.setLevel(level)
