"""Structured logging setup (the reference uses bare prints,
SURVEY.md §5)."""

from __future__ import annotations

import logging
import sys
from typing import Optional


def log_event(
    logger: logging.Logger,
    event: str,
    *,
    level: int = logging.WARNING,
    **fields,
) -> None:
    """Emit one machine-greppable `event=<name> key=value ...` record.

    The resilience layer's contract (engine demotions, quarantined
    lanes, checkpoint requeues) is that every recovery action leaves
    exactly one such line, so an operator can `grep event=` a sweep's
    log and reconstruct what degraded where — values are flat scalars
    on one line, not multi-line prose. Values containing whitespace,
    `=` or quotes (free-text labels, error messages) are double-quoted
    with inner quotes escaped so a key=value tokenizer still parses the
    record. Empty-string fields are dropped (optional labels).

    When a telemetry :class:`..telemetry.runctx.RunContext` is active,
    the record is additionally stamped with ``run_id`` (and
    ``span_id``/``parent_id`` under an open span) — the join key between
    the log stream, the FailureLedger and the flight-recorder span tree,
    so concurrent or resumed sweeps no longer interleave
    indistinguishably. Caller-passed fields of the same name win."""
    try:
        from yuma_simulation_tpu.telemetry.runctx import current_fields

        for key, value in current_fields().items():
            fields.setdefault(key, value)
    except Exception:
        # Telemetry must never break logging (import cycles during
        # interpreter teardown, partial installs).
        pass
    try:
        # The live-ops recent-events ring (telemetry.ops): every
        # structured record is also visible on GET /debug/vars of a
        # standing host. Same containment contract as above.
        from yuma_simulation_tpu.telemetry.ops import note_event

        note_event(event, fields)
    except Exception:
        pass

    def fmt(v) -> str:
        s = str(v)
        if any(c in s for c in (" ", "\t", "=", '"')):
            return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'
        return s

    payload = " ".join(
        f"{k}={fmt(v)}" for k, v in fields.items() if v != ""
    )
    logger.log(level, "event=%s%s", event, f" {payload}" if payload else "")


def parse_event_line(line: str) -> Optional[dict]:
    """Parse one `event=<name> key=value ...` record back into a dict —
    the exact inverse of :func:`log_event`'s quoting, so supervisor
    tests and operator tooling can consume recovery records structurally
    instead of regexing them.

    Anything before the first ``event=`` token (timestamp/level/logger
    prefixes from the formatter) is skipped; returns ``None`` for lines
    carrying no event record. All values come back as strings exactly as
    :func:`log_event` stringified them — double-quoted values are
    unescaped (``\\\\`` and ``\\"``), bare values taken verbatim. The
    returned dict includes the event name under ``"event"``.
    """
    idx = line.find("event=")
    if idx > 0 and line[idx - 1] not in (" ", "\t"):
        # `event=` embedded in some other token (e.g. a quoted message
        # containing the literal text) — not a record boundary.
        idx = -1
    if idx == -1:
        return None
    s = line[idx:].rstrip("\n")
    fields: dict = {}
    i, n = 0, len(s)
    while i < n:
        while i < n and s[i] in (" ", "\t"):
            i += 1
        if i >= n:
            break
        eq = s.find("=", i)
        if eq == -1:
            break
        key = s[i:eq]
        if not key or any(c in key for c in (" ", "\t", '"')):
            break
        i = eq + 1
        if i < n and s[i] == '"':
            i += 1
            buf = []
            closed = False
            while i < n:
                c = s[i]
                if c == "\\" and i + 1 < n:
                    buf.append(s[i + 1])
                    i += 2
                    continue
                if c == '"':
                    i += 1
                    closed = True
                    break
                buf.append(c)
                i += 1
            if not closed:
                # Torn record (crash mid-line): drop the dangling field,
                # keep what parsed completely.
                break
            fields[key] = "".join(buf)
        else:
            j = i
            while j < n and s[j] not in (" ", "\t"):
                j += 1
            fields[key] = s[i:j]
            i = j
    if "event" not in fields:
        return None
    return fields


def setup_logging(level: int = logging.INFO) -> None:
    """Configure framework-wide logging once, idempotently.

    Multi-host aware: non-zero JAX processes log at WARNING so a pod run
    emits one progress stream instead of `process_count` interleaved ones.
    The process index is only consulted when distributed mode is already
    initialized — `jax.process_index()` would otherwise initialize the
    local-only backend and break a later `initialize_distributed` call.
    """
    root = logging.getLogger("yuma_simulation_tpu")
    if root.handlers:
        return
    try:
        import jax

        if jax.distributed.is_initialized() and jax.process_index() != 0:
            level = max(level, logging.WARNING)
    except Exception:
        pass
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )
    )
    root.addHandler(handler)
    root.setLevel(level)
