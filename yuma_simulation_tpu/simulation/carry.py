"""Registered pytree dataclasses for the engine's ``lax.scan`` carries.

jaxlint rule JX008: every scan carry the engine constructs must be one
of these, never a raw tuple-of-dicts. The positional-tuple carries the
engine grew up with had two failure modes this fixes structurally:

- *positional-unpack drift*: adding a field (the quarantine state in
  PR 1) renumbers every ``carry[i]`` access, and a missed site reads the
  wrong tensor without any error — the dataclass gives each leg a stable
  name;
- *silent structure forks*: a tuple carry built slightly differently at
  two sites (e.g. ``()+()`` concatenation vs a literal) still traces,
  but keys a second compiled program; a single constructor per carry
  shape makes the pytree structure a reviewed, single-source contract.

All fields are pytree *data* (leaves); optional legs (``w_prev`` for
variants that don't carry previous weights, ``quarantine`` when the
non-finite guard is off) hold ``None``, which JAX treats as an empty
subtree — the structure stays static per trace, exactly like the old
conditionally-sized tuples, so compiled-program counts are unchanged
and outputs are bitwise identical.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from jax import tree_util


@tree_util.register_dataclass
@dataclass
class ScanCarry:
    """Carry of the per-epoch XLA case scan (:func:`.engine._simulate_scan`):
    ``(B, W_prev, C_prev)`` plus the optional quarantine provenance dict
    of :mod:`..resilience.guards`."""

    bonds: Any  # [V, M]
    w_prev: Any  # [V, M]
    consensus: Any  # [M]
    quarantine: Optional[dict] = None


@tree_util.register_dataclass
@dataclass
class NumericsSketch:
    """The per-epoch numerics observable of one tensor stream (the
    flight recorder's `numerics.jsonl` payload, computed INSIDE the
    jitted scan bodies — :mod:`..telemetry.numerics`). Each field is one
    value per epoch (scalars in the scan step; `[E]` after the scan
    stacks them; `[B, E]` under a vmapped batch). All reductions are
    exact and order-independent (integer counts, wrapping-u32 bit sums,
    min/max), so the sketch is bitwise invariant to chunked streaming
    and miner-axis sharding — merging chunked captures is plain
    concatenation along the epoch axis."""

    finite_frac: Any  # exact finite count / size, as the stream dtype
    lo: Any  # min
    hi: Any  # max
    absmax: Any  # max |x|
    fingerprint: Any  # wrapping-u32 sum of the raw bits (ops.fingerprint)


@tree_util.register_dataclass
@dataclass
class TotalsCarry:
    """Carry of the accumulate-in-carry throughput scans
    (:func:`.engine.simulate_constant`, the per-epoch Monte-Carlo shard
    body): full kernel state plus the running dividend total."""

    bonds: Any  # [V, M]
    w_prev: Any  # [V, M]
    consensus: Any  # [M]
    acc: Any  # [V]


@tree_util.register_dataclass
@dataclass
class ScaledCarry:
    """Carry of the epoch-varying throughput scan
    (:func:`.engine.simulate_scaled`): ``w_prev`` is ``None`` for
    variants that don't carry previous weights (empty subtree — same
    compiled-program structure as the old 2-tuple)."""

    bonds: Any  # [V, M]
    w_prev: Any  # [V, M] or None
    acc: Any  # [V]


@tree_util.register_dataclass
@dataclass
class HoistedCarry:
    """Carry of the hoisted constant-weights scan
    (:func:`.engine._simulate_constant_hoisted`): bonds recurrence plus
    the dividend accumulator — everything else is hoisted out."""

    bonds: Any  # [V, M]
    acc: Any  # [V]
