"""DispatchPlan: the one dispatch-planning decision for every engine path.

Before this module, the decisions that turn "a workload shape" into "a
compiled program on a device" — engine-rung choice, fused-kernel VMEM
admission, HBM preflight, ladder-rung eligibility, shape padding — were
duplicated across `simulation/engine.py` (`_resolve_case_engine` + an
inline preflight), `simulation/sweep.py` (a second auto-resolution block
for the batched scan), `parallel/sharded.py` (a third preflight with
different lane accounting), and `ops/pallas_epoch.py` (the eligibility
predicates each caller re-combined by hand). Every consumer now asks
:func:`plan_dispatch` once and receives a :class:`DispatchPlan`:

    shape bucket -> engine rung -> sharding layout -> memory plan
                 -> (optional) AOT cost estimate

- **shape bucket** (:class:`ShapeBucket`): the tile-aligned `[Vp, Mp]`
  target the donor-packing path pads small suites to (sublane 8 x lane
  128 — one MXU tile minimum), so heterogeneous suites ride ONE batched
  dispatch on a REUSED compiled shape instead of one program per ragged
  shape. Epochs are deliberately not bucketed: the epoch axis is data
  length, and masking it would change results.
- **engine rung**: the single "auto" resolution (fused_scan_mxu ->
  fused_scan -> xla) with every admission rule in one place, plus the
  resolved consensus impl for the chosen rung AND the XLA fallback
  consensus a ladder demotion needs.
- **ladder**: the rungs at and below the chosen engine —
  :func:`ladder_from` lives HERE now; `resilience.retry` re-exports it,
  so rung eligibility has one owner.
- **memory plan** (:class:`MemoryPlan`): the analytic HBM preflight
  verdict (`telemetry.cost`, zero compiles) plus the slab length the
  double-buffered streaming driver should use (`chunk_epochs`, sized so
  TWO slabs — the one computing and the one transferring — fit the
  device together).
- **AOT cost estimate**: opt-in via :meth:`DispatchPlan.attach_cost`
  (it compiles a program, so it never runs on the hot path).

Plans are frozen, deterministic, pure-host values: the same inputs
always produce an identical plan (pinned by
tests/unit/test_planner.py), and planning adds zero compiles (pinned by
tests/unit/test_recompilation.py). :meth:`DispatchPlan.record` emits
one structured ``event=dispatch_planned`` record and stamps a compact
summary on the open telemetry span, so flight bundles show *why* each
rung ran — it self-guards with the is-tracing check, because
`simulate_batch` re-enters planning inside the `shard_map` trace.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

logger = logging.getLogger(__name__)

#: The full case-scan ladder, most- to least-demanding. An explicitly
#: requested engine starts at its own rung and may only walk DOWN —
#: demotion must never silently upgrade a run onto an engine the caller
#: did not ask for. (Moved here from `resilience.retry`, which
#: re-exports it: rung eligibility and rung ordering are one decision.)
#: 0.19.0 adds the EPOCH-TILED varying-weights rungs at the top
#: (`ops.pallas_epoch.fused_varying_scan`): they demand the most VMEM
#: (a whole double-buffered epoch tile resident), so a VMEM-class
#: failure demotes tile -> per-epoch case scan -> XLA; the MXU twin of
#: each kernel family sits directly above its VPU twin so the default
#: numerics canary (one rung below the primary) always pairs
#: bitwise-identical programs.
ENGINE_LADDER = (
    "fused_varying_mxu",
    "fused_varying",
    "fused_scan_mxu",
    "fused_scan",
    "xla",
)

#: Every fused rung the case-scan entry points dispatch through
#: `engine._simulate_case_fused` — the one membership test the dispatch
#: stack shares (engine, sweep, sharded, serve, aot, cost).
FUSED_CASE_RUNGS = (
    "fused_varying_mxu",
    "fused_varying",
    "fused_scan_mxu",
    "fused_scan",
)


def rung_flags(engine: str) -> dict:
    """The static kernel-selection flags a fused rung name encodes —
    the ONE name -> (mxu, varying) spelling, so a dispatch site cannot
    pair the wrong kernel with a rung label."""
    return {
        "mxu": engine.endswith("_mxu"),
        "varying": engine.startswith("fused_varying"),
    }

#: The ONE documented accepted-drift class (ADVICE r5): an EXPLICIT
#: fused opt-in beyond the int32 dyadic-quantization bound pairs the
#: fused kernel's plain-sum u16-quantize fallback against the XLA
#: rung's blocked miner_sum fallback — a one-ulp drift surface the auto
#: planner refuses (eligibility gates) but an explicit request may
#: cross on demotion. Canary records crossing it are stamped
#: ``expected`` with this reason, so ``driftreport --check`` renders
#: it instead of failing the pipeline.
EXPECTED_DRIFT_U16_FALLBACK = (
    "u16-quantize fallback pairing: explicit fused opt-in beyond the "
    "int32 dyadic bound may differ from the XLA rung by one ulp "
    "(ADVICE r5; auto never pairs these)"
)

#: Tile geometry the donor-packing bucket targets: the VPU/MXU operate
#: on (8, 128) f32 tiles, so a padded batch below these bounds wastes
#: the very lanes packing exists to fill.
SUBLANE_TILE = 8
LANE_TILE = 128

#: How many epoch slabs the double-buffered streaming driver keeps live
#: at once: the slab being scanned plus the slab being transferred.
STREAM_BUFFERS = 2


def ladder_from(engine: str) -> tuple:
    """The rungs at and below `engine`, in demotion order. Unknown
    engines (e.g. the throughput paths' "fused"/"hoisted") get a
    single-rung ladder: retry in place, never demote onto a path with
    different output semantics."""
    if engine in ENGINE_LADDER:
        return ENGINE_LADDER[ENGINE_LADDER.index(engine):]
    return (engine,)


# ---------------------------------------------------------------------------
# plan components


@dataclasses.dataclass(frozen=True)
class ShapeBucket:
    """The compiled-shape target for one dispatch. `V`/`M` are the
    workload's real axes; `padded_V`/`padded_M` the tile-aligned bucket
    the donor-packing path pads to (equal to `V`/`M` when already
    aligned). `batch` counts scenario lanes (1 = unbatched)."""

    batch: int
    epochs: int
    V: int
    M: int
    padded_V: int
    padded_M: int

    @property
    def key(self) -> str:
        """The compile-cache-aligned bucket key: two suites with the
        same key trace the same batched program."""
        return (
            f"b{self.batch}e{self.epochs}"
            f"v{self.padded_V}m{self.padded_M}"
        )

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _round_up(n: int, mult: int) -> int:
    return max(mult, -(-int(n) // mult) * mult)


def bucket_shape(
    V: int, M: int, *, epochs: int = 0, batch: int = 1
) -> ShapeBucket:
    """Tile-align `[V, M]` to the (8, 128) f32 tile — the donor-packing
    target. Padding is semantically inert by the same mechanism
    `pad_scenarios` proves: zero stakes for padded validators, a miner
    mask excluding padded columns from the consensus grid."""
    return ShapeBucket(
        batch=int(batch),
        epochs=int(epochs),
        V=int(V),
        M=int(M),
        padded_V=_round_up(V, SUBLANE_TILE),
        padded_M=_round_up(M, LANE_TILE),
    )


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """The analytic memory decision for one dispatch — the HBM
    preflight verdict plus the streaming slab length.

    ``fits`` is None when device capacity is unknown (every CPU build):
    the preflight passes open rather than guessing. ``chunk_epochs`` is
    the per-slab epoch count the double-buffered streaming driver
    should cap slabs at — sized so :data:`STREAM_BUFFERS` slabs plus
    the `[V, M]` working set fit the budget — or None when the whole
    stack fits monolithically (or capacity is unknown)."""

    predicted_bytes: int
    capacity_bytes: Optional[int]
    fits: Optional[bool]
    resident_epochs: int
    chunk_epochs: Optional[int]
    double_buffered: bool
    suggestion: Optional[str] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class DispatchPlan:
    """One dispatch, fully decided. Frozen and deterministic: equal
    inputs to :func:`plan_dispatch` yield an equal plan."""

    label: str
    engine: str
    #: Consensus impl resolved FOR the chosen engine ("bisect" on the
    #: fused rungs — they bisect in-kernel).
    consensus_impl: str
    #: Consensus impl a ladder demotion onto the XLA rung must use —
    #: resolved from the caller's request exactly as a direct XLA
    #: request would have been.
    fallback_consensus: str
    ladder: tuple
    bucket: ShapeBucket
    miner_shards: int
    batch_lanes: int
    memory: MemoryPlan
    #: Why each decision fell the way it did, in decision order.
    reasons: tuple
    #: Optional AOT cost estimate (a `telemetry.cost.CostRecord` dict);
    #: populated only by :meth:`attach_cost` — never on the hot path.
    cost: Optional[dict] = None
    #: Optional resolved executable (a `simulation.aot.AotExecutable`);
    #: populated only by :meth:`attach_executable`. Excluded from
    #: equality/JSON — a plan with a warm executable is still the SAME
    #: plan (determinism pins compare the decisions, not the handle).
    executable: Optional[object] = dataclasses.field(
        default=None, compare=False, repr=False
    )

    def to_json(self) -> dict:
        out = dataclasses.asdict(dataclasses.replace(self, executable=None))
        out["ladder"] = list(self.ladder)
        out["reasons"] = list(self.reasons)
        out["executable"] = (
            self.executable.describe()
            if self.executable is not None
            else None
        )
        return out

    def span_attr(self) -> dict:
        """The compact summary stamped on telemetry spans (flat,
        JSON-able — span attrs are rendered inline by obsreport)."""
        attr = {
            "engine": self.engine,
            "consensus": self.consensus_impl,
            "bucket": self.bucket.key,
            "shards": self.miner_shards,
            "lanes": self.batch_lanes,
            "why": "; ".join(self.reasons),
        }
        if self.memory.predicted_bytes:
            # Engine-only plans (check_memory=False) carry no footprint;
            # a literal 0.0 GiB would read as a measurement.
            attr["hbm_gib"] = round(self.memory.predicted_bytes / 2**30, 3)
            attr["fits"] = self.memory.fits
            attr["chunk_epochs"] = self.memory.chunk_epochs
        return attr

    def record(self) -> None:
        """Emit one ``event=dispatch_planned`` record and stamp the
        plan summary on the open telemetry span. Inert at trace time
        (`simulate_batch` re-plans inside the `shard_map` trace) — the
        host-side log/span machinery must not bake into a program."""
        from yuma_simulation_tpu.telemetry.runctx import (
            _tracing_now,
            current_span,
        )

        if _tracing_now():
            return
        attr = self.span_attr()
        s = current_span()
        if s is not None:
            s.attrs["plan"] = attr
        from yuma_simulation_tpu.utils.logging import log_event

        log_event(
            logger,
            "dispatch_planned",
            level=logging.DEBUG,
            label=self.label,
            **{k: v for k, v in attr.items() if v is not None},
        )

    def demoted(self, rung: str) -> "DispatchPlan":
        """A copy of this plan re-anchored at a LOWER ladder rung — what
        the serving tier's circuit breaker hands out while an upper rung
        is tripped fleet-wide. Only rungs already in this plan's ladder
        are legal (demotion must never upgrade a run onto an engine the
        caller did not ask for, the same invariant `ladder_from` keeps);
        the consensus impl switches to the pre-resolved XLA fallback
        when the new rung is "xla"."""
        if rung == self.engine:
            return self
        if rung not in self.ladder:
            raise ValueError(
                f"cannot re-anchor plan at {rung!r}: not in ladder "
                f"{self.ladder} (demotion only walks DOWN)"
            )
        return dataclasses.replace(
            self,
            engine=rung,
            consensus_impl=(
                self.fallback_consensus if rung == "xla" else self.consensus_impl
            ),
            ladder=ladder_from(rung),
            reasons=self.reasons
            + (f"circuit breaker re-anchored dispatch at {rung!r}",),
            # An attached executable is the OLD rung's program — a
            # re-anchored plan must resolve its own.
            executable=None,
        )

    def attach_cost(self, yuma_version: str = "Yuma 1 (paper)") -> "DispatchPlan":
        """A copy of this plan with the chosen rung's AOT cost record
        attached (`telemetry.cost.capture_engine_cost`). COMPILES a
        program — explicit-call only (tools, the supervisor's opt-in
        capture), never the hot path."""
        from yuma_simulation_tpu.telemetry.cost import capture_engine_cost

        rec = capture_engine_cost(
            self.engine,
            self.bucket.V,
            self.bucket.M,
            max(1, self.bucket.epochs),
            yuma_version=yuma_version,
        )
        return dataclasses.replace(self, cost=rec.to_json())

    def attach_executable(
        self, yuma_version: str = "Yuma 1 (paper)", *, cache=None, **kwargs
    ) -> "DispatchPlan":
        """A copy of this plan with its engine rung's executable
        resolved through the AOT cache
        (:func:`..simulation.aot.executable_for_plan`): a cache hit
        deserializes the published artifact (milliseconds); a miss
        AOT-COMPILES and publishes it — so, like :meth:`attach_cost`,
        this is explicit-call only (serve warmup, fleet preload, tools),
        never the hot path. The resolved executable also lands in the
        process-wide memo the engine dispatch seam consults, which is
        what makes warmup-then-serve compile-free. `kwargs` forward to
        ``executable_for_plan`` (config/dtype/save flags). The plan is
        returned unchanged when the rung cannot resolve on this
        backend."""
        from yuma_simulation_tpu.simulation.aot import executable_for_plan

        exe = executable_for_plan(
            self, yuma_version, cache=cache, **kwargs
        )
        if exe is None:
            return self
        return dataclasses.replace(self, executable=exe)


# ---------------------------------------------------------------------------
# the planner


def _resolve_spec(spec_or_version):
    from yuma_simulation_tpu.models.variants import (
        VariantSpec,
        variant_for_version,
    )

    if isinstance(spec_or_version, VariantSpec):
        return spec_or_version
    return variant_for_version(spec_or_version)


def _plan_engine(
    epoch_impl: str,
    consensus_impl: str,
    shape: Sequence[int],
    spec,
    config,
    dtype,
    save_bonds: bool,
    mesh,
    streaming: bool,
    quarantine: bool,
    has_miner_mask: bool,
    reasons: list,
) -> tuple[str, str]:
    """The ONE engine/consensus resolution for every case-scan entry
    point (`simulate`, `simulate_streamed`, `simulate_generated`,
    `simulate_batch`): "auto" becomes the fused Pallas scan when
    eligible (MXU variant wherever the exact limb split covers V) else
    the XLA scan; the fused engines reject `consensus_impl="sorted"`
    (they bisect in-kernel), miner-sharding meshes, per-scenario miner
    masks, and the quarantine guard; the XLA engine resolves "auto"
    consensus to the shape-gated sorted/bisect default. Returns
    `(engine, consensus_impl)` fully resolved."""
    if consensus_impl not in ("auto", "sorted", "bisect"):
        raise ValueError(
            f"unknown consensus_impl {consensus_impl!r}; "
            "expected 'auto', 'sorted' or 'bisect'"
        )
    batched = len(shape) == 4
    if epoch_impl == "auto":
        from yuma_simulation_tpu.ops.pallas_epoch import (
            exact_mxu_support_covers,
            fused_case_scan_eligible,
            fused_varying_scan_eligible,
            varying_scan_epoch_tile,
        )

        epochs = shape[1] if batched else shape[0]
        base_ok = (
            mesh is None
            and not quarantine
            and not has_miner_mask
            and consensus_impl in ("auto", "bisect")
            and epochs >= 1
        )
        # Eligibility first: it short-circuits on the cheap gates
        # (mode/dtype/backend) before walking the divisor/VMEM tile
        # admission, so a CPU plan never pays the tile search; on the
        # eligible path the tile lookup below is a memo hit
        # (varying_scan_epoch_tile is lru-cached).
        tile = (
            varying_scan_epoch_tile(
                tuple(shape), spec.bonds_mode, save_bonds,
                streaming=streaming,
            )
            if base_ok
            and epochs >= 2
            and fused_varying_scan_eligible(
                tuple(shape), spec.bonds_mode, config, dtype, save_bonds,
                streaming=streaming,
            )
            else 0
        )
        if base_ok and tile >= 2:
            # The epoch-tiled varying scan wins exactly when it can
            # batch >= 2 epochs' bond-independent math per grid step —
            # otherwise it degenerates to the per-epoch case scan and
            # the battle-tested kernel keeps the dispatch.
            mxu = exact_mxu_support_covers(shape[-2])
            epoch_impl = "fused_varying_mxu" if mxu else "fused_varying"
            reasons.append(
                f"auto->{epoch_impl}: epoch-tiled varying scan eligible "
                f"(tile={tile})"
                + ("" if mxu else f" (limb split stops below V={shape[-2]})")
            )
        elif base_ok and fused_case_scan_eligible(
            tuple(shape), spec.bonds_mode, config, dtype, save_bonds,
            streaming=streaming,
        ):
            # Since r4 the MXU scan's consensus support is EXACT (the
            # limb-split integer contraction, ~1.6x the VPU scan) and
            # the whole scan is bitwise the VPU scan, so auto prefers
            # it wherever the limb split covers V.
            mxu = exact_mxu_support_covers(shape[-2])
            epoch_impl = "fused_scan_mxu" if mxu else "fused_scan"
            reasons.append(
                f"auto->{epoch_impl}: fused case scan eligible"
                + ("" if mxu else f" (limb split stops below V={shape[-2]})")
            )
        else:
            epoch_impl = "xla"
            reasons.append(
                "auto->xla: "
                + (
                    "miner-sharding mesh"
                    if mesh is not None
                    else "quarantine guard rides the XLA carry"
                    if quarantine
                    else "per-scenario miner mask"
                    if has_miner_mask
                    else f"consensus_impl={consensus_impl!r}"
                    if consensus_impl not in ("auto", "bisect")
                    else "zero epochs"
                    if epochs < 1
                    else "fused case scan ineligible "
                    "(backend/dtype/mode/VMEM)"
                )
            )
    if epoch_impl in FUSED_CASE_RUNGS:
        if mesh is not None:
            raise ValueError(
                "the fused case/varying scans are single-core Pallas "
                "programs; miner-axis sharding requires epoch_impl='xla'"
            )
        if quarantine:
            raise ValueError(
                "quarantine rides the XLA scan carry; the fused case scan "
                "cannot host it — use epoch_impl='xla' (or 'auto', which "
                "resolves to 'xla' under quarantine)"
            )
        if has_miner_mask:
            raise ValueError(
                "the batched fused case scan has no per-scenario miner "
                "masks; heterogeneous suites use epoch_impl='xla'"
            )
        if consensus_impl == "sorted":
            raise ValueError(
                "the fused case scan computes consensus by bisection; "
                "consensus_impl='sorted' requires epoch_impl='xla'"
            )
        if epoch_impl in ("fused_varying", "fused_varying_mxu"):
            from yuma_simulation_tpu.ops.pallas_epoch import (
                varying_scan_epoch_tile,
            )

            if (
                varying_scan_epoch_tile(
                    tuple(shape), spec.bonds_mode, save_bonds,
                    streaming=streaming,
                )
                < 1
            ):
                # Fail the plan, not the dispatch: the serving tier
                # admits requests through plan_dispatch, so a shape no
                # epoch tile can fit must become a typed admission
                # reject (a 400), not a mid-dispatch kernel error.
                raise ValueError(
                    f"{list(shape)} too large for the epoch-tiled "
                    "varying scan at any tile (VMEM admission); use "
                    "'fused_scan'/'fused_scan_mxu' or 'xla'"
                )
        import math

        from yuma_simulation_tpu.ops.consensus import dyadic_grid_fits_int32

        if not dyadic_grid_fits_int32(
            shape[-1], math.ceil(math.log2(config.consensus_precision))
        ):
            # An EXPLICIT fused opt-in beyond the int32 dyadic bound:
            # auto never lands here (the eligibility gates refuse, so
            # the planner cannot pair the two quantize fallbacks
            # unasked — ADVICE r5), but an explicit request is honored
            # with the caveat RECORDED: the fused in-kernel fallback
            # (plain sum) and the XLA blocked miner_sum fallback may
            # differ by one ulp, so a demotion or numerics canary
            # crossing this pairing is a DOCUMENTED accepted-drift
            # class (the supervisor stamps such canary records
            # `expected`, and driftreport renders instead of failing).
            reasons.append(EXPECTED_DRIFT_U16_FALLBACK)
        return epoch_impl, consensus_impl
    if epoch_impl != "xla":
        raise ValueError(
            f"unknown epoch_impl {epoch_impl!r}; "
            "expected 'auto', 'xla', 'fused_scan', 'fused_scan_mxu', "
            "'fused_varying' or 'fused_varying_mxu'"
        )
    from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

    return "xla", resolve_consensus_impl(consensus_impl, *shape[-2:])


def _plan_memory(
    label: str,
    V: int,
    M: int,
    epochs: int,
    itemsize: int,
    *,
    save_bonds: bool,
    save_incentives: bool,
    save_consensus: bool,
    miner_shards: int,
    batch_lanes: int,
    max_resident_epochs: Optional[int],
    streaming: bool,
    raise_on_reject: bool,
) -> MemoryPlan:
    """The analytic memory half of the plan: preflight the resident
    footprint and size the streaming slab. Pure host arithmetic
    (`telemetry.cost.estimate_hbm_bytes`) — zero compiles, zero
    allocation, exactly the hot-path discipline the preflight has
    always kept."""
    from yuma_simulation_tpu.telemetry.cost import (
        DEFAULT_MEMORY_FRACTION,
        estimate_hbm_bytes,
        preflight_hbm,
        resolve_device_spec,
    )

    resident = (
        min(epochs, max_resident_epochs)
        if max_resident_epochs is not None
        else epochs
    )
    kwargs = dict(
        itemsize=itemsize,
        save_bonds=save_bonds,
        save_incentives=save_incentives,
        save_consensus=save_consensus,
        miner_shards=miner_shards,
        batch_lanes=batch_lanes,
    )
    estimate = estimate_hbm_bytes(V, M, resident_epochs=resident, **kwargs)
    verdict = preflight_hbm(
        label,
        estimate,
        raise_on_reject=raise_on_reject and not streaming,
    )
    # Slab sizing for the double-buffered streaming driver: per-epoch
    # bytes from a 1-epoch estimate minus the fixed working set, then
    # chunk = (budget - fixed) / (STREAM_BUFFERS * per_epoch) so the
    # computing slab and the in-flight transfer fit together. Gated on
    # preflight_enabled(): YUMA_TPU_PREFLIGHT=0 is the documented "the
    # analytic model mis-models my device" escape hatch, and it must
    # disable slab re-slicing exactly as it disables rejection.
    chunk_epochs: Optional[int] = None
    from yuma_simulation_tpu.telemetry.cost import preflight_enabled

    spec = resolve_device_spec()
    if spec.memory_bytes and preflight_enabled():
        budget = int(spec.memory_bytes * DEFAULT_MEMORY_FRACTION)
        one = estimate_hbm_bytes(V, M, resident_epochs=1, **kwargs)
        zero = estimate_hbm_bytes(V, M, resident_epochs=0, **kwargs)
        per_epoch = max(1, one.total_bytes - zero.total_bytes)
        fixed = zero.total_bytes
        if budget > fixed:
            chunk_epochs = max(
                1, (budget - fixed) // (STREAM_BUFFERS * per_epoch)
            )
        elif streaming:
            # The FIXED [V, M] working set alone exceeds the budget: no
            # slab length can fix that, so a streaming plan rejects here
            # exactly like a monolithic one (typed event + error) —
            # streaming must not swallow a deterministic cannot-fit.
            preflight_hbm(
                label, zero, raise_on_reject=raise_on_reject
            )
            chunk_epochs = 1
        if not streaming and verdict.fits is not False:
            # Monolithic dispatch that fits: no slabbing needed.
            chunk_epochs = None
    return MemoryPlan(
        predicted_bytes=estimate.total_bytes,
        capacity_bytes=verdict.capacity_bytes,
        fits=verdict.fits,
        resident_epochs=resident,
        chunk_epochs=chunk_epochs,
        double_buffered=streaming,
        suggestion=verdict.suggestion,
    )


def plan_dispatch(
    label: str,
    shape: Sequence[int],
    spec_or_version,
    config,
    dtype,
    *,
    epoch_impl: str = "auto",
    consensus_impl: str = "bisect",
    save_bonds: bool = False,
    save_incentives: bool = False,
    save_consensus: bool = False,
    mesh=None,
    streaming: bool = False,
    quarantine: bool = False,
    has_miner_mask: bool = False,
    max_resident_epochs: Optional[int] = None,
    check_memory: bool = True,
    raise_on_reject: bool = True,
) -> DispatchPlan:
    """Plan one case-scan dispatch. `shape` is `[E, V, M]` or a batched
    `[B, E, V, M]`. Raises exactly the errors the legacy per-caller
    resolution raised (bad impl names, fused-rung preconditions,
    `telemetry.cost.HBMPreflightError` on an unfittable monolithic
    shape); streaming plans never raise on footprint — they size
    `memory.chunk_epochs` instead, which is the whole point of
    streaming.

    `check_memory=False` skips the preflight/slab arithmetic (the
    trace-re-entrant `simulate_batch` path: its memory is accounted at
    the entry point that placed the arrays).
    """
    import jax.numpy as jnp

    shape = tuple(int(d) for d in shape)
    if len(shape) == 3:
        batch, (E, V, M) = 1, shape
    elif len(shape) == 4:
        batch, E, V, M = shape
    else:
        raise ValueError(
            f"plan_dispatch expects [E, V, M] or [B, E, V, M], got {shape}"
        )
    spec = _resolve_spec(spec_or_version)
    reasons: list = []
    if epoch_impl != "auto":
        reasons.append(f"engine {epoch_impl!r} requested explicitly")
    engine, resolved_consensus = _plan_engine(
        epoch_impl,
        consensus_impl,
        shape,
        spec,
        config,
        dtype,
        save_bonds,
        mesh,
        streaming,
        quarantine,
        has_miner_mask,
        reasons,
    )
    # The XLA-rung consensus a ladder demotion needs: the fused
    # resolution leaves the request untouched ("auto"/"bisect"); resolve
    # it for the XLA engine exactly as a direct request would have been.
    if engine == "xla":
        fallback_consensus = resolved_consensus
    else:
        from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

        fallback_consensus = resolve_consensus_impl(consensus_impl, V, M)
    miner_shards = (
        1 if mesh is None else int(mesh.shape[mesh.axis_names[-1]])
    )
    if miner_shards > 1:
        reasons.append(f"miner axis sharded over {miner_shards} devices")
    if check_memory:
        memory = _plan_memory(
            label,
            V,
            M,
            E,
            jnp.dtype(dtype).itemsize,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=save_consensus,
            miner_shards=miner_shards,
            batch_lanes=batch,
            max_resident_epochs=max_resident_epochs,
            streaming=streaming,
            raise_on_reject=raise_on_reject,
        )
        if streaming and memory.chunk_epochs is not None:
            reasons.append(
                f"streaming slabs capped at {memory.chunk_epochs} epochs "
                f"({STREAM_BUFFERS} buffers resident)"
            )
    else:
        memory = MemoryPlan(
            predicted_bytes=0,
            capacity_bytes=None,
            fits=None,
            resident_epochs=E,
            chunk_epochs=None,
            double_buffered=streaming,
        )
    if max_resident_epochs is not None and streaming is False and E > max_resident_epochs:
        reasons.append(
            f"caller caps residency at {max_resident_epochs} epochs"
        )
    # Demotion rungs below the chosen engine must themselves be legal
    # for this workload: beyond the exact MXU limb split's V bound the
    # `_mxu` twins raise a caller error (which the retry ladder rightly
    # never retries), so they are dropped from the walk — the chosen
    # engine itself was already validated above.
    ladder = ladder_from(engine)
    from yuma_simulation_tpu.ops.pallas_epoch import exact_mxu_support_covers

    if not exact_mxu_support_covers(V):
        ladder = tuple(
            r for r in ladder if r == engine or not r.endswith("_mxu")
        )
    return DispatchPlan(
        label=label,
        engine=engine,
        consensus_impl=resolved_consensus,
        fallback_consensus=fallback_consensus,
        ladder=ladder,
        bucket=bucket_shape(V, M, epochs=E, batch=batch),
        miner_shards=miner_shards,
        batch_lanes=batch,
        memory=memory,
        reasons=tuple(reasons),
    )


# ---------------------------------------------------------------------------
# throughput-path resolutions (simulate_scaled / simulate_scaled_batch /
# montecarlo) — previously inline auto blocks in engine.py and sharded.py


def resolve_scaled_engine(
    shape: Sequence[int], mode, config, dtype, num_epochs: int
) -> str:
    """The `epoch_impl="auto"` resolution for the scalar-scaled
    throughput paths (`simulate_scaled` / `simulate_scaled_batch`):
    the exact-MXU fused scan where the limb split covers V, the VPU
    scan where VMEM admits it, else the XLA scan. Trace-time host
    arithmetic (both callers are jitted)."""
    from yuma_simulation_tpu.ops.pallas_epoch import (
        exact_mxu_support_covers,
        fused_scan_eligible,
    )

    if num_epochs >= 1 and fused_scan_eligible(
        tuple(shape), mode, config, dtype
    ):
        return (
            "fused_scan_mxu"
            if exact_mxu_support_covers(shape[-2])
            else "fused_scan"
        )
    return "xla"


def resolve_montecarlo_engine(epoch_impl: str, varying: bool) -> str:
    """The Monte-Carlo `epoch_impl="auto"` resolution: hoisted for
    epoch-constant weights (consensus runs once), the full per-epoch
    XLA kernel for `weights_mode="per_epoch"` (nothing is hoistable)."""
    if epoch_impl == "auto":
        return "xla" if varying else "hoisted"
    if epoch_impl not in ("hoisted", "xla"):
        raise ValueError(
            f"unknown epoch_impl {epoch_impl!r}; "
            "expected 'auto', 'hoisted' or 'xla'"
        )
    if varying and epoch_impl == "hoisted":
        raise ValueError(
            "weights_mode='per_epoch' re-perturbs the weights every "
            "epoch; nothing is hoistable — use epoch_impl='xla'/'auto'"
        )
    return epoch_impl
