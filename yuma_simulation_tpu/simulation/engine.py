"""The epoch loop as `lax.scan` + the reference-compatible driver API.

The reference's `run_simulation` (reference simulation_utils.py:26-112) is a
Python `for` over epochs carrying `(B_state, W_prev, server_consensus_weight)`
with per-epoch `.item()` host transfers. Here the whole loop — variant
dispatch, bond-reset injection, the kernel, and the dividend-per-1000-tao
conversion (simulation_utils.py:45-49, 95-107) — is one jitted
`lax.scan`: carry = `(B, W_prev, C_prev)`, xs = the scenario's stacked
`(W[E,V,M], S[E,V], epoch_index)`. A single device round-trip returns every
per-epoch output at once.

`simulate_constant` is the throughput path: weights constant across epochs
are closed over (no `[E, V, M]` HBM blow-up at 10k+ epochs) and total
dividends accumulate inside the carry.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.epoch import (
    _EMA_MODES,
    BondsMode,
    capacity_bonds_update,
    ema_bonds_update,
    relative_bonds_update,
    yuma_epoch,
)
from yuma_simulation_tpu.ops.liquid import liquid_alpha_rate
from yuma_simulation_tpu.models.variants import (
    ResetMode,
    VariantSpec,
    variant_for_version,
)
from yuma_simulation_tpu.ops.normalize import miner_sum, normalize_weight_rows
from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.simulation.carry import (
    HoistedCarry,
    ScaledCarry,
    ScanCarry,
    TotalsCarry,
)
from yuma_simulation_tpu.simulation.planner import (
    FUSED_CASE_RUNGS,
    plan_dispatch,
    resolve_scaled_engine,
    rung_flags,
)


@dataclass
class SimulationResult:
    """Host-side view of one simulated scenario."""

    dividends: np.ndarray  # [E, V] dividend per 1000 tao per epoch
    bonds: Optional[np.ndarray]  # [E, V, M] post-epoch bond state
    incentives: Optional[np.ndarray]  # [E, M] server incentive
    consensus: Optional[np.ndarray]  # [E, M] quantized consensus
    #: Engine-ladder demotions taken to produce this result (None when
    #: the run completed on the first-choice engine or no retry policy
    #: was armed) — tuple of resilience.retry.DemotionRecord.
    demotions: Optional[tuple] = None
    #: Per-epoch numerics sketches captured inside the engine dispatch
    #: (`{stream: ..carry.NumericsSketch of [E] numpy arrays}`, see
    #: telemetry.numerics) — None when YUMA_NUMERICS=0 disabled capture.
    numerics: Optional[dict] = None
    #: The consensus carry AFTER the last simulated epoch, as host
    #: arrays (``{"bonds" [V, M], "consensus" [M][, "w_prev" [V, M]]}``)
    #: — populated only when :func:`simulate` was called with
    #: ``return_state=True``. Feeding it back as ``initial_state=`` (+
    #: the matching ``epoch_offset=``) continues the trajectory
    #: bitwise-identically to an uninterrupted run — the suffix-resume
    #: contract the chain-replay state cache (:mod:`..replay.statecache`)
    #: is built on.
    final_state: Optional[dict] = None


def _miner_shardings(mesh: Mesh, num_miners: int):
    """`([V, M], [M])` NamedShardings with the miner axis over the mesh's
    last axis (the ``model`` axis of :func:`..parallel.mesh.make_mesh`).

    The miner axis is this framework's sequence-parallel analogue
    (SURVEY.md §5): the bisection/sort consensus is per-miner and stays
    shard-local; only the row-normalization sums, consensus-sum divide,
    liquid-alpha quantile sort and dividend reductions cross shards.

    The bitwise sharded == unsharded contract rests on the blocked
    `miner_sum` spelling, whose 8 fixed blocks are shard-local only
    when the miner-axis size divides SUM_BLOCKS — a larger mesh would
    silently reintroduce order-dependent cross-shard combines, so it
    is rejected here (use up to 8 miner shards; scale the rest of the
    pod on the data axis). The same contract also requires the blocked
    spelling to actually ENGAGE: `miner_sum` degrades to a plain
    backend-ordered reduce when `M % SUM_BLOCKS != 0` or
    `M < 2 * SUM_BLOCKS`, so a multi-shard mesh over such a miner count
    (e.g. M=20 on 2 shards) would silently lose the bitwise guarantee —
    rejected here too (advisor r5 medium): pad the miner axis to a
    multiple of SUM_BLOCKS, or run that subnet unsharded.
    """
    from yuma_simulation_tpu.ops.normalize import SUM_BLOCKS

    axis = mesh.axis_names[-1]
    shards = mesh.shape[axis]
    if SUM_BLOCKS % shards:
        raise ValueError(
            f"miner-axis sharding supports mesh sizes dividing "
            f"{SUM_BLOCKS} (got {shards}): the partition-invariant "
            "miner_sum blocks must be shard-local for the bitwise "
            "sharded==unsharded contract"
        )
    if shards > 1 and (
        num_miners % SUM_BLOCKS or num_miners < 2 * SUM_BLOCKS
    ):
        raise ValueError(
            f"miner-axis sharding over {shards} shards requires a miner "
            f"count that is a multiple of {SUM_BLOCKS} and at least "
            f"{2 * SUM_BLOCKS} (got M={num_miners}): below that, "
            "miner_sum's blocked partition-invariant spelling degrades "
            "to a plain reduce and the bitwise sharded==unsharded "
            "contract is lost — pad the miner axis or run unsharded"
        )
    vm = NamedSharding(mesh, PartitionSpec(None, axis))
    m = NamedSharding(mesh, PartitionSpec(axis))
    return vm, m


def _dividends_per_1k(D_n, S, config, dtype):
    """Dividend per 1000 tao (reference simulation_utils.py:45-49,
    95-107), from NORMALIZED dividends and the *raw* stakes. One shared
    definition: this arithmetic is parity-critical and every engine path
    (XLA scan, fused case scan, scaled/constant throughput paths) must
    apply bit-identical ops."""
    stakes_units = jnp.asarray(S, dtype) * config.total_subnet_stake / 1000.0
    emission = (
        config.validator_emission_ratio * D_n * config.total_epoch_emission
    )
    return jnp.where(stakes_units > 1e-6, emission / stakes_units, 0.0)


def fused_hparams(config: YumaConfig) -> dict:
    """The one config -> fused-kernel hyperparameter mapping. This
    spelling is parity-critical (a drifted field silently changes the
    simulated model), so every fused call site — the engine paths here
    and bench.py's true-weights runner — must build its kwargs through
    this helper."""
    return dict(
        kappa=config.kappa,
        bond_penalty=config.bond_penalty,
        bond_alpha=config.bond_alpha,
        capacity_alpha=config.capacity_alpha,
        decay_rate=config.decay_rate,
        liquid_alpha=config.liquid_alpha,
        alpha_low=config.alpha_low,
        alpha_high=config.alpha_high,
        override_consensus_high=config.override_consensus_high,
        override_consensus_low=config.override_consensus_low,
        precision=config.consensus_precision,
    )


def zero_carry(spec: VariantSpec, V: int, M: int, dtype) -> dict:
    """The streaming carry at global epoch 0 — bitwise what the kernels'
    zero-init produces, so chunk 0 can run the SAME has_carry program as
    every later chunk (a carry=None first chunk would compile a second
    kernel variant for no numerical difference)."""
    carry = {
        "bonds": jnp.zeros((V, M), dtype),
        "consensus": jnp.zeros((M,), dtype),
    }
    if spec.carries_prev_weights:
        carry["w_prev"] = jnp.zeros((V, M), dtype)
    return carry


def config_is_batched(config) -> bool:
    """Whether any float leaf of the config pytree carries a leading
    batch axis (a config_grid grid). One shared predicate — the engines
    must agree on what counts as batched."""
    return any(jnp.ndim(leaf) > 0 for leaf in jax.tree.leaves(config))


def config_vmap_axes(config):
    """Per-leaf vmap in_axes for a possibly partially-batched config:
    batched leaves map over axis 0, scalar leaves broadcast. (The fused
    kernels broadcast scalars the same way via _pack_hp, so both engines
    accept mixed configs.)"""
    return jax.tree.map(lambda l: 0 if jnp.ndim(l) else None, config)


def _apply_reset(B, C_prev, epoch, reset_index, reset_epoch, reset_mode, M):
    """Zero the reset miner's bond column when the variant's rule fires
    (reference simulation_utils.py:62-88). `reset_epoch < 0` disables.

    The reference can only reset from epoch 1 onward (`B_state`/
    `server_consensus_weight` are still None at epoch 0), hence the
    `epoch > 0` gate.
    """
    do = (epoch == reset_epoch) & (epoch > 0) & (reset_index >= 0)
    if reset_mode is ResetMode.CONDITIONAL:
        prev_c = jnp.take(C_prev, jnp.clip(reset_index, 0, M - 1))
        do = do & (prev_c == 0.0)
    col = (jnp.arange(M) == reset_index) & do
    return jnp.where(col[None, :], jnp.zeros_like(B), B)


@partial(
    jax.jit,
    static_argnames=(
        "spec",
        "save_bonds",
        "save_incentives",
        "save_consensus",
        "consensus_impl",
        "mesh",
        "return_carry",
        "guard_nonfinite",
        "capture_numerics",
    ),
)
def _simulate_scan(
    weights: jnp.ndarray,  # [E, V, M]
    stakes: jnp.ndarray,  # [E, V]
    reset_index: jnp.ndarray,  # int32 scalar, -1 = none
    reset_epoch: jnp.ndarray,  # int32 scalar, -1 = none
    config: YumaConfig,
    spec: VariantSpec,
    save_bonds: bool = True,
    save_incentives: bool = True,
    save_consensus: bool = False,
    consensus_impl: str = "bisect",
    miner_mask: Optional[jnp.ndarray] = None,  # [M] 1=real, 0=padding
    mesh: Optional[Mesh] = None,  # shard the miner axis over mesh's last axis
    carry: Optional[dict] = None,  # chunked streaming: previous chunk's state
    epoch_offset=0,  # traced int32: global index of this chunk's epoch 0
    return_carry: bool = False,
    guard_nonfinite: bool = False,
    nan_fault_epoch: Optional[jnp.ndarray] = None,  # i32 scalar, -1 = off
    capture_numerics: bool = False,
    drift_fault_epoch: Optional[jnp.ndarray] = None,  # i32 scalar, -1 = off
):
    """`guard_nonfinite` folds the resilience layer's numerical
    quarantine (:mod:`..resilience.guards`) into the scan carry: each
    epoch's outputs are isfinite-checked, the first failure latches
    `(first_bad_epoch, tensor_code)` provenance, and from that epoch on
    every output of this scenario is masked to zero. In a vmapped batch
    the state is per-lane, so one poisoned case quarantines alone while
    healthy lanes stay bit-for-bit identical to an unguarded run (the
    guard ops are `where(False, 0, x)` there). The final state rides the
    returned ys as `ys["quarantine"]`.

    `nan_fault_epoch` is the resilience layer's deterministic fault
    operand (:func:`..resilience.faults.active_nan_fault`): a traced
    int32 scalar (per lane under vmap) that, when >= 0, overwrites this
    lane's dividends with NaN at that global epoch — value-neutral
    (`where(False, nan, x)`) everywhere else. Armed only by
    fault-injection tests; production dispatches pass None and trace
    the exact pre-resilience program."""
    if guard_nonfinite and (carry is not None or return_carry):
        raise ValueError(
            "guard_nonfinite does not compose with chunked streaming "
            "carries; run the quarantine on monolithic scans"
        )
    from yuma_simulation_tpu.resilience.guards import (
        quarantine_init,
        quarantine_step,
    )

    E, V, M = weights.shape
    dtype = weights.dtype
    shardings = None if mesh is None else _miner_shardings(mesh, M)

    def step(carry, xs):
        B, W_prev, C_prev = carry.bonds, carry.w_prev, carry.consensus
        qstate = carry.quarantine
        W, S, epoch = xs
        first = epoch == 0
        if shardings is not None:
            # Re-pin the layouts every epoch so GSPMD keeps the miner axis
            # sharded through the whole scan instead of gathering the carry.
            vm, m = shardings
            W = lax.with_sharding_constraint(W, vm)
            B = lax.with_sharding_constraint(B, vm)
            W_prev = lax.with_sharding_constraint(W_prev, vm)
            C_prev = lax.with_sharding_constraint(C_prev, m)

        if spec.reset_mode is not ResetMode.NONE:
            B = _apply_reset(
                B, C_prev, epoch, reset_index, reset_epoch, spec.reset_mode, M
            )

        kernel_prev = None
        if spec.bonds_mode is BondsMode.EMA_PREV:
            # Epoch 0 falls back to this epoch's normalized weights
            # (reference yumas.py:299-300).
            kernel_prev = jnp.where(
                first, normalize_weight_rows(W.astype(dtype)), W_prev
            )

        res = yuma_epoch(
            W,
            S,
            B,
            config,
            bonds_mode=spec.bonds_mode,
            W_prev=kernel_prev,
            first_epoch=first,
            consensus_impl=consensus_impl,
            miner_mask=miner_mask,
        )

        B_next = res[spec.bond_state_key]
        W_prev_next = res["weight"] if spec.carries_prev_weights else W_prev
        C_next = res["server_consensus_weight"]
        if shardings is not None:
            vm, m = shardings
            B_next = lax.with_sharding_constraint(B_next, vm)
            W_prev_next = lax.with_sharding_constraint(W_prev_next, vm)
            C_next = lax.with_sharding_constraint(C_next, m)

        # Note the conversion uses the *raw* case stakes, not the
        # normalized kernel stakes.
        dividends = _dividends_per_1k(
            res["validator_reward_normalized"], S, config, dtype
        )

        if nan_fault_epoch is not None:
            # The poison literal carries the carry dtype explicitly
            # (jaxlint JX005): a bare float("nan") asarray would be
            # weak-f32 here but f64 under the x64 parity harness, and
            # dtype promotion through jnp.where would then poison the
            # whole dividend stream's dtype, not just the target epoch.
            dividends = jnp.where(
                epoch == nan_fault_epoch,
                jnp.asarray(float("nan"), dtype=dtype),
                dividends,
            )

        if drift_fault_epoch is not None:
            # The numerics-canary drill operand (resilience.faults
            # DriftFault): flip validator 0's dividend by EXACTLY one
            # ulp at the target epoch — the smallest representable
            # cross-engine drift, which the per-epoch fingerprint must
            # localize (delta of exactly 1 at that epoch). Value-neutral
            # (`where(False, ..)`) everywhere else; armed only inside
            # canary re-executions by the fault hooks.
            from yuma_simulation_tpu.ops.fingerprint import flip_ulp

            lane0 = jnp.arange(dividends.shape[-1]) == 0
            dividends = jnp.where(
                (epoch == drift_fault_epoch) & lane0,
                flip_ulp(dividends),
                dividends,
            )

        if guard_nonfinite:
            # Priority-ordered health check (codes index
            # guards.QUARANTINE_TENSORS); the mask zeroes this lane's
            # carry AND outputs from the first bad epoch on, so the NaN
            # neither propagates nor reaches the caller's reductions.
            # The incentive stream is checked only when it is actually
            # emitted: internally it feeds dividends (already checked),
            # and the kernel sanitizes it — but the guard's contract is
            # "every emitted output is isfinite-checked", not "trust the
            # kernel's internals".
            checks = [
                (0, dividends),
                (1, B_next),
                (2, C_next),
                (3, W_prev_next),
            ]
            if save_incentives:
                checks.append((4, res["server_incentive"]))
            qstate, qmask = quarantine_step(qstate, epoch, checks)
            dividends = qmask(dividends)
            B_next = qmask(B_next)
            W_prev_next = qmask(W_prev_next)
            C_next = qmask(C_next)

        ys = {"dividends": dividends}
        if save_bonds:
            ys["bonds"] = B_next
        if save_incentives:
            ys["incentives"] = (
                qmask(res["server_incentive"])
                if guard_nonfinite
                else res["server_incentive"]
            )
        if save_consensus:
            ys["consensus"] = C_next
        if capture_numerics:
            # The numerics flight recorder's per-epoch sketch
            # (telemetry.numerics), computed HERE in the scan step so
            # the capture rides the one traced program — no extra
            # dispatches, no host syncs, and the exact/order-independent
            # reductions make it bitwise invariant to chunked streaming
            # and miner-axis sharding. Captured post-quarantine: the
            # sketch observes what the engine EMITS.
            from yuma_simulation_tpu.telemetry.numerics import (
                capture_streams,
            )

            ys["numerics"] = capture_streams(
                {"dividends": dividends, "consensus": C_next}
            )
        return (
            ScanCarry(
                bonds=B_next,
                w_prev=W_prev_next,
                consensus=C_next,
                quarantine=qstate,
            ),
            ys,
        )

    if carry is None:
        carry0 = ScanCarry(
            bonds=jnp.zeros((V, M), dtype),
            w_prev=jnp.zeros((V, M), dtype),
            consensus=jnp.zeros((M,), dtype),
            quarantine=quarantine_init() if guard_nonfinite else None,
        )
    else:
        carry0 = ScanCarry(
            bonds=jnp.asarray(carry["bonds"], dtype),
            w_prev=jnp.asarray(
                carry.get("w_prev", jnp.zeros((V, M), dtype)), dtype
            ),
            consensus=jnp.asarray(carry["consensus"], dtype),
            quarantine=quarantine_init() if guard_nonfinite else None,
        )
    xs = (
        weights,
        stakes,
        jnp.arange(E, dtype=jnp.int32) + jnp.asarray(epoch_offset, jnp.int32),
    )
    carry_f, ys = lax.scan(step, carry0, xs)
    if guard_nonfinite:
        ys["quarantine"] = carry_f.quarantine
    if not return_carry:
        return ys
    carry_out = {"bonds": carry_f.bonds, "consensus": carry_f.consensus}
    if spec.carries_prev_weights:
        carry_out["w_prev"] = carry_f.w_prev
    return ys, carry_out


@partial(
    jax.jit,
    static_argnames=(
        "spec",
        "save_bonds",
        "save_incentives",
        "save_consensus",
        "mxu",
        "varying",
        "return_carry",
        "capture_numerics",
    ),
)
def _simulate_case_fused(
    weights: jnp.ndarray,  # [E, V, M] or batched [B, E, V, M]
    stakes: jnp.ndarray,  # [E, V] or [B, E, V]
    reset_index: jnp.ndarray,  # scalar, or [B] when batched
    reset_epoch: jnp.ndarray,
    config: YumaConfig,
    spec: VariantSpec,
    save_bonds: bool = True,
    save_incentives: bool = True,
    save_consensus: bool = False,
    mxu: bool = False,
    varying: bool = False,
    carry: Optional[dict] = None,
    epoch_offset=0,
    return_carry: bool = False,
    capture_numerics: bool = False,
):
    """The fused-Pallas twin of :func:`_simulate_scan`: the whole epoch
    loop — per-epoch weights/stakes streamed from HBM, reset injection,
    liquid alpha — runs as ONE Pallas program
    (:func:`yuma_simulation_tpu.ops.pallas_epoch.fused_case_scan`); only
    the dividend-per-1000-tao conversion (linear, needs the raw per-epoch
    stakes) happens out here. Returns the same ys dict as
    `_simulate_scan`.

    `varying=True` (static) selects the EPOCH-TILED varying-weights
    kernel instead (:func:`..ops.pallas_epoch.fused_varying_scan` — the
    `fused_varying` / `fused_varying_mxu` planner rungs, ISSUE 15):
    identical inputs, outputs and carry contract, but each grid step
    advances a whole epoch tile with the bond-independent math batched
    over it — the rung for workloads whose single-epoch block
    underfills the chip."""
    from yuma_simulation_tpu.ops.pallas_epoch import (
        fused_case_scan,
        fused_varying_scan,
    )

    dtype = weights.dtype
    res = (fused_varying_scan if varying else fused_case_scan)(
        weights,
        stakes,
        reset_index=reset_index,
        reset_epoch=reset_epoch,
        reset_mode=spec.reset_mode,
        mode=spec.bonds_mode,
        mxu=mxu,
        save_bonds=save_bonds,
        save_incentives=save_incentives,
        save_consensus=save_consensus,
        carry=carry,
        epoch_offset=epoch_offset,
        return_carry=return_carry,
        **fused_hparams(config),
    )
    if config_is_batched(config):
        # Batched [B] config leaves (a grid aligned with the scenario
        # axis): the kernel consumed them as per-scenario vectors; the
        # per-1000-tao conversion maps them the same way (scalar leaves
        # broadcast).
        dividends = jax.vmap(
            lambda d, s, c: _dividends_per_1k(d, s, c, dtype),
            in_axes=(0, 0, config_vmap_axes(config)),
        )(res["dividends_normalized"], stakes, config)
    else:
        dividends = _dividends_per_1k(
            res["dividends_normalized"], stakes, config, dtype
        )
    ys = {"dividends": dividends}
    for key in ("bonds", "incentives", "consensus"):
        if key in res:
            ys[key] = res[key]
    if capture_numerics:
        # The SAME per-epoch sketch spelling as the XLA scan step
        # (telemetry.numerics), computed on the kernel's stacked
        # outputs inside this jit — every reduction is exact and
        # order-independent, so a fused and an XLA run of bitwise-equal
        # tensors produce bitwise-equal sketches (the cross-engine
        # canary's comparison basis). Per-epoch consensus exists only
        # when the kernel was asked to save it; records compare on the
        # intersection of captured streams.
        from yuma_simulation_tpu.telemetry.numerics import capture_streams

        streams = {"dividends": ys["dividends"]}
        if "consensus" in ys:
            streams["consensus"] = ys["consensus"]
        ys["numerics"] = capture_streams(
            streams, epoch_axis=1 if weights.ndim == 4 else 0
        )
    if not return_carry:
        return ys
    carry_out = {
        "bonds": res["final_bonds"],
        "consensus": res["final_consensus"],
    }
    if spec.carries_prev_weights:
        carry_out["w_prev"] = res["final_w_prev"]
    return ys, carry_out


#: Streaming twins of the two case engines, identical programs with the
#: chunk carry DONATED: the `(bonds[, w_prev], consensus)` state is
#: replaced wholesale every chunk, so its input buffers can back the
#: next chunk's outputs instead of doubling the carry footprint while
#: the next slab's host->HBM transfer is already in flight. Donation
#: changes buffer lifetime only, never values — the streamed-vs-
#: monolithic bitwise pins of tests/unit/test_streamed.py run through
#: these. (Separate jit objects, not donate flags on the shared
#: engines: `simulate_generated` traces the plain engines INSIDE its
#: own jit, where donation annotations would be meaningless noise.)
_simulate_scan_streamed = partial(
    jax.jit,
    static_argnames=(
        "spec",
        "save_bonds",
        "save_incentives",
        "save_consensus",
        "consensus_impl",
        "mesh",
        "return_carry",
        "guard_nonfinite",
        "capture_numerics",
    ),
    donate_argnames=("carry",),
)(getattr(_simulate_scan, "__wrapped__"))

_simulate_case_fused_streamed = partial(
    jax.jit,
    static_argnames=(
        "spec",
        "save_bonds",
        "save_incentives",
        "save_consensus",
        "mxu",
        "varying",
        "return_carry",
        "capture_numerics",
    ),
    donate_argnames=("carry",),
)(getattr(_simulate_case_fused, "__wrapped__"))


#: Above this many bytes for one saved per-epoch output stream the
#: `save_bonds="auto"` / `save_incentives="auto"` defaults of
#: :func:`simulate` resolve to False: materializing (and host-fetching)
#: a multi-GiB `[E, V, M]` bond history is never what a caller who only
#: wanted dividends meant (r3/r4 verdict "weak" item). Explicit
#: True/False always wins.
SAVE_AUTO_LIMIT_BYTES = 1 << 30


def _resolve_save(flag, nbytes: int, name: str) -> bool:
    if flag == "auto":
        return nbytes <= SAVE_AUTO_LIMIT_BYTES
    if not isinstance(flag, bool):
        raise ValueError(
            f"{name} must be True, False or 'auto', got {flag!r}"
        )
    return flag


def validate_initial_state(
    initial_state, spec: VariantSpec, V: int, M: int
) -> dict:
    """The suffix-resume input contract: ``initial_state`` must be the
    carry dict a ``return_state=True`` run emitted — ``bonds [V, M]``,
    ``consensus [M]``, and ``w_prev [V, M]`` exactly when the variant
    carries previous weights. Shape mistakes fail HERE as a typed
    ValueError (a caller error the retry ladder must never burn
    attempts on), not as an XLA shape crash three layers down. Returns
    the validated dict of host/device arrays unchanged."""
    if not isinstance(initial_state, dict):
        raise ValueError(
            "initial_state must be the carry dict of a return_state=True "
            f"run, got {type(initial_state).__name__}"
        )
    want = {"bonds": (V, M), "consensus": (M,)}
    if spec.carries_prev_weights:
        want["w_prev"] = (V, M)
    extra = set(initial_state) - set(want)
    if extra:
        raise ValueError(
            f"initial_state carries unknown keys {sorted(extra)} "
            f"(this variant's carry is {sorted(want)})"
        )
    for key, shape in want.items():
        if key not in initial_state:
            raise ValueError(
                f"initial_state lacks {key!r} (this variant's carry is "
                f"{sorted(want)})"
            )
        got = np.shape(initial_state[key])
        if tuple(got) != shape:
            raise ValueError(
                f"initial_state[{key!r}] has shape {tuple(got)}, "
                f"expected {shape}"
            )
    return initial_state


def simulate(
    scenario: Scenario,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
    *,
    save_bonds="auto",
    save_incentives="auto",
    save_consensus: bool = False,
    consensus_impl: str = "bisect",
    epoch_impl: str = "auto",
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    max_resident_epochs: Optional[int] = None,
    retry_policy=None,
    deadline=None,
    initial_state: Optional[dict] = None,
    epoch_offset: int = 0,
    return_state: bool = False,
) -> SimulationResult:
    """Simulate one scenario under one named version; returns host arrays.

    `initial_state` / `epoch_offset` / `return_state` (0.18.0, additive
    — the suffix-resume contract of the chain-replay service): pass the
    ``final_state`` dict of a prior ``return_state=True`` run as
    ``initial_state=`` with ``epoch_offset=`` set to that run's epoch
    count, and this call continues the trajectory over the scenario's
    epochs as global epochs ``[offset, offset + E)`` — bitwise identical
    to the corresponding tail of one uninterrupted run, on every engine
    rung (the same carry-threading contract chunked streaming is pinned
    on, tests/unit/test_suffix_resume.py). The offset is a traced
    operand, so resuming at different epochs reuses one compiled
    program per suffix length. ``return_state=True`` additionally
    returns the post-final-epoch carry on
    :attr:`SimulationResult.final_state` (host arrays, serializable).
    The AOT executable-cache seam covers only offset-0 stateless
    dispatches; resume dispatches ride the ordinary jit cache (plus the
    persistent compilation cache when configured).

    `retry_policy` (a :class:`..resilience.retry.RetryPolicy`, default
    None = fail fast exactly as before): arm the engine-degradation
    ladder. A classified engine failure (VMEM/RESOURCE_EXHAUSTED,
    Mosaic/XLA compile abort) retries on the same engine with jittered
    backoff, then demotes one rung — fused_scan_mxu -> fused_scan ->
    xla — logging one structured `event=engine_demoted` record per step;
    the demotion history is returned on `SimulationResult.demotions`.
    Caller errors (bad impl names, shape mistakes) are never retried.

    `deadline` (a :class:`..resilience.watchdog.Deadline`, default None
    = unbounded): arm the deadline watchdog. Each engine dispatch runs
    on a supervised worker thread; a compile or dispatch that posts no
    heartbeat within the budget raises a typed `EngineStall` — which,
    combined with `retry_policy`, retries and demotes down the ladder
    exactly like a raising failure (a hung Mosaic compile must not
    wedge a sweep any harder than a VMEM exhaustion does).

    Memory note: `save_bonds`/`save_incentives` default "auto": True (the
    reference driver's outputs, simulation_utils.py:109-112) while the
    per-epoch stream stays under `SAVE_AUTO_LIMIT_BYTES`, False beyond it
    — a long-epoch dividends run must not silently materialize and fetch
    a multi-GiB `[E, V, M]` bond history. Pass True/False to override.

    `max_resident_epochs`: when set and the scenario is longer, the epoch
    stack is processed in `[chunk, V, M]` slabs through the chunked
    drivers (:func:`simulate_streamed`) with the carry threaded between
    dispatches and slab `k+1`'s host->HBM transfer overlapping the scan
    over slab `k` (the double buffer) — bitwise-identical results with
    ~two slabs resident on device at a time (single-chip only). When
    the device capacity is known, the dispatch plan may re-slice slabs
    further to its `memory.chunk_epochs` cap so BOTH buffers fit.
    Compile note: the chunk length is a static kernel parameter, so a
    run compiles at most TWO programs per distinct slab length (the
    full-size chunks and one trailing remainder when
    `E % max_resident_epochs != 0`); pick a divisor of E to compile one.

    `epoch_impl`:
      - "auto" (default): run the whole epoch loop as a single Pallas
        program (`fused_case_scan` — per-epoch weights/stakes streamed
        through VMEM, the flagship kernel) when the variant/config/shape
        allow it on a real TPU, else the XLA `lax.scan`. Prefers the
        MXU variant (exact limb-split support, bitwise the VPU scan,
        ~1.6x) wherever it covers V. The fused path matches the XLA
        path to reduction-order rounding (pinned against the golden CSV
        surface by tests/unit/test_fused_case_scan.py).
      - "xla": always the `lax.scan` over the unfused epoch kernel.
      - "fused_scan": require the fused path with VPU reductions (raises
        if ineligible; off-TPU it runs in interpret mode — correct but
        slow, for tests).
      - "fused_scan_mxu": the fused path with the consensus support on
        the MXU as the EXACT limb-split integer contraction (r4):
        bitwise-identical outputs to "fused_scan", ~1.6x faster, V <=
        2^14 — what "auto" selects on TPU (parity pinned on chip in
        MXU_PARITY.json via tools/tpu_parity.py).

    `consensus_impl`: "bisect" (default), "sorted" (bitwise twin — the
    fuzz battery pins them equal — but with pathological XLA compile
    times at >= 512x8192 cells), or "auto" (defer to the engine: the
    fused path when epoch_impl selects it, else the shape-gated
    sorted/bisect default).

    With ``mesh``, the miner axis of every `[V, M]` matrix is sharded over
    the mesh's last axis for the whole multi-epoch scan — the path for
    subnets whose `V x M` state outgrows one chip's HBM (XLA path only).
    Sharded results match the unsharded run to within one u16 consensus
    grid step — cross-shard psum ordering can flip the truncating
    quantizer by one 2^-17 step on knife-edge values — with bounds pinned
    by tests/unit/test_multichip.py.
    """
    config = config if config is not None else YumaConfig()
    spec = variant_for_version(yuma_version)
    E_, V_, M_ = np.shape(scenario.weights)
    if initial_state is not None:
        validate_initial_state(initial_state, spec, V_, M_)
    if epoch_offset < 0:
        raise ValueError(f"epoch_offset must be >= 0, got {epoch_offset}")
    itemsize = jnp.dtype(dtype).itemsize
    save_bonds = _resolve_save(
        save_bonds, E_ * V_ * M_ * itemsize, "save_bonds"
    )
    save_incentives = _resolve_save(
        save_incentives, E_ * M_ * itemsize, "save_incentives"
    )
    # The dispatch plan (simulation.planner): engine rung, consensus,
    # ladder, shape bucket and the analytic memory plan in ONE decision.
    # The embedded HBM preflight keeps its exact legacy contract: pure
    # host arithmetic on shapes — zero compiles, zero allocation — that
    # rejects a dispatch whose predicted peak footprint cannot fit the
    # device BEFORE XLA starts the minutes-scale compile that would
    # discover it the hard way, with one typed `event=preflight_rejected`
    # record + HBMPreflightError (a caller error: the ladder must not
    # retry a shape that deterministically cannot fit). Unknown-capacity
    # devices (every CPU build) pass open; YUMA_TPU_PREFLIGHT=0 disables
    # both the reject and the slab re-slicing. Streaming dispatches
    # reject only when the FIXED [V, M] working set cannot fit (no slab
    # length fixes that); an oversized epoch stack streams through the
    # memory plan's slab cap instead (that is what streaming is FOR).
    will_stream = max_resident_epochs is not None and E_ > max_resident_epochs
    plan = plan_dispatch(
        f"simulate:{yuma_version}",
        (E_, V_, M_),
        spec,
        config,
        dtype,
        epoch_impl=epoch_impl,
        consensus_impl=consensus_impl,
        save_bonds=save_bonds,
        save_incentives=save_incentives,
        save_consensus=save_consensus,
        mesh=mesh,
        streaming=will_stream,
        max_resident_epochs=max_resident_epochs,
    )
    if will_stream:
        if mesh is not None:
            raise ValueError(
                "max_resident_epochs streaming is single-chip; it cannot "
                "be combined with a miner-sharding mesh"
            )

        def chunk_gen():
            for lo in range(0, E_, max_resident_epochs):
                hi = min(lo + max_resident_epochs, E_)
                yield (
                    jnp.asarray(scenario.weights[lo:hi], dtype),
                    jnp.asarray(scenario.stakes[lo:hi], dtype),
                )

        return simulate_streamed(
            # Re-iterable (not a one-shot generator): the full arrays
            # live on the scenario, so an engine demotion under
            # retry_policy can restart the stream from chunk 0 no
            # matter which chunk the failure surfaced at.
            _ReiterableChunks(chunk_gen),
            yuma_version,
            config,
            reset_bonds_index=scenario.reset_bonds_index,
            reset_bonds_epoch=scenario.reset_bonds_epoch,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=save_consensus,
            consensus_impl=consensus_impl,
            epoch_impl=epoch_impl,
            dtype=dtype,
            retry_policy=retry_policy,
            initial_state=initial_state,
            epoch_offset=epoch_offset,
            return_state=return_state,
        )
    from yuma_simulation_tpu.resilience import faults

    weights = jnp.asarray(scenario.weights, dtype)
    stakes = jnp.asarray(scenario.stakes, dtype)
    reset_index = jnp.asarray(
        -1 if scenario.reset_bonds_index is None else scenario.reset_bonds_index,
        jnp.int32,
    )
    reset_epoch = jnp.asarray(
        -1 if scenario.reset_bonds_epoch is None else scenario.reset_bonds_epoch,
        jnp.int32,
    )
    # consensus_impl="auto" defers to the engine: the fused path (which
    # computes by bisection) when the plan selects it, else the
    # shape-gated sorted/bisect default (the two are bitwise twins —
    # tests/unit/test_consensus_fuzz.py — so this is purely a
    # compile/runtime-cost choice, ops/consensus.py). The plan also
    # pre-resolves the XLA-rung consensus a ladder demotion needs.
    plan.record()
    epoch_impl, consensus_impl = plan.engine, plan.consensus_impl

    def _dispatch(rung: str):
        # Host-side profiler step annotation: each engine dispatch gets
        # a process-monotonic step number recorded on the open telemetry
        # span, so a Perfetto trace's step lanes join against the
        # ledger/span tree (inert when no profiler/trace is active).
        from yuma_simulation_tpu.telemetry.runctx import dispatch_annotation

        with dispatch_annotation(f"simulate:{rung}"):
            return _dispatch_engine(rung)

    from yuma_simulation_tpu.telemetry.numerics import numerics_enabled

    capture = numerics_enabled()
    # Suffix-resume operands: the carry is data (fresh device arrays per
    # dispatch — the streamed twins DONATE carries, these engines don't,
    # but a ladder retry must still see untouched inputs) and the offset
    # is traced, so every resume epoch reuses one compiled program per
    # suffix length.
    resuming = (
        initial_state is not None or return_state or epoch_offset != 0
    )
    resume_kwargs: dict = {}
    if resuming:
        if initial_state is not None:
            resume_kwargs["carry"] = {
                k: jnp.asarray(np.asarray(v), dtype)
                for k, v in initial_state.items()
            }
        resume_kwargs["epoch_offset"] = jnp.asarray(
            epoch_offset, jnp.int32
        )
        resume_kwargs["return_carry"] = return_state

    def _dispatch_engine(rung: str):
        # The AOT executable-cache seam (simulation.aot): when a cache
        # is active and the dispatch carries no dynamic fault operands,
        # sharding, or suffix-resume carry, resolve the rung's program
        # by content — a hit dispatches the deserialized executable
        # directly (bitwise the JIT path, pinned by
        # tests/unit/test_aot.py); a miss JITs as today and publishes
        # the artifact. Inactive cache = None fast path, so the legacy
        # pipeline is untouched by default.
        from yuma_simulation_tpu.simulation.aot import dispatch_via_cache

        if rung in FUSED_CASE_RUNGS:
            faults.maybe_fail_fused_dispatch()
            fused_kwargs = dict(
                spec=spec,
                save_bonds=save_bonds,
                save_incentives=save_incentives,
                save_consensus=save_consensus,
                capture_numerics=capture,
                **rung_flags(rung),
            )
            out = (
                dispatch_via_cache(
                    _simulate_case_fused,
                    (weights, stakes, reset_index, reset_epoch, config),
                    fused_kwargs,
                    static_names=tuple(fused_kwargs),
                    label=f"simulate:{rung}",
                )
                if not resuming
                else None
            )
            if out is None:
                out = _simulate_case_fused(
                    weights,
                    stakes,
                    reset_index,
                    reset_epoch,
                    config,
                    **fused_kwargs,
                    **resume_kwargs,
                )
        else:
            # Demoted off a fused rung: the plan pre-resolved the
            # XLA-rung consensus exactly as a direct request would be.
            cons = (
                consensus_impl
                if rung == epoch_impl
                else plan.fallback_consensus
            )
            W = weights
            if mesh is not None:
                axis = mesh.axis_names[-1]
                W = jax.device_put(
                    W, NamedSharding(mesh, PartitionSpec(None, None, axis))
                )
            nf = faults.active_nan_fault()
            xla_kwargs = dict(
                spec=spec,
                save_bonds=save_bonds,
                save_incentives=save_incentives,
                save_consensus=save_consensus,
                consensus_impl=cons,
                capture_numerics=capture,
            )
            out = (
                dispatch_via_cache(
                    _simulate_scan,
                    (W, stakes, reset_index, reset_epoch, config),
                    xla_kwargs,
                    static_names=tuple(xla_kwargs),
                    label=f"simulate:{rung}",
                )
                if mesh is None and nf is None and not resuming
                else None
            )
            if out is None:
                out = _simulate_scan(
                    W,
                    stakes,
                    reset_index,
                    reset_epoch,
                    config,
                    mesh=mesh,
                    nan_fault_epoch=(
                        None
                        if nf is None or nf.case is not None
                        else jnp.asarray(nf.epoch, jnp.int32)
                    ),
                    **xla_kwargs,
                    **resume_kwargs,
                )
        if retry_policy is not None or deadline is not None:
            # Surface async dispatch failures (device OOM) inside the
            # ladder's/watchdog's try, not at some later host fetch.
            out = jax.block_until_ready(out)
        return out

    from yuma_simulation_tpu.utils.profiling import timed

    demotions = None
    engine_used = epoch_impl
    # The one epoch-rate record per run (satellite of the telemetry
    # tentpole): dispatch + host fetch timed together, routed through
    # the metrics registry (`epochs_total`/`epochs_per_sec`) and emitted
    # as one `event=epoch_rate` line by `timed` on clean exit.
    t_dispatch = timed(f"simulate:{yuma_version}", epochs=E_)
    with t_dispatch:
        if retry_policy is None and deadline is None:
            ys = _dispatch(epoch_impl)
        elif retry_policy is None:
            from yuma_simulation_tpu.resilience.watchdog import (
                run_with_deadline,
            )

            ys = run_with_deadline(
                lambda: _dispatch(epoch_impl), deadline, label=yuma_version
            )
        else:
            from yuma_simulation_tpu.resilience.retry import run_ladder

            ys, engine_used, records = run_ladder(
                _dispatch, epoch_impl, retry_policy, rungs=plan.ladder,
                label=yuma_version, deadline=deadline,
            )
            demotions = tuple(records) or None
        state_out = None
        if return_state:
            ys, state_out = ys
            state_out = jax.device_get(state_out)
        ys = jax.device_get(ys)
    # The always-on dispatch timing seam (continuous telemetry): one
    # host-side sketch observation per dispatched region, keyed by the
    # rung that actually ran (post-demotion), the plan's shape bucket,
    # and the backend — what tools/perfattrib.py joins against the AOT
    # cost records.
    from yuma_simulation_tpu.telemetry.slo import observe_dispatch

    observe_dispatch(
        engine=engine_used,
        bucket=plan.bucket.key,
        backend=jax.default_backend(),
        seconds=t_dispatch.seconds,
        epochs=E_,
    )
    return SimulationResult(
        dividends=ys["dividends"],
        bonds=ys.get("bonds"),
        incentives=ys.get("incentives"),
        consensus=ys.get("consensus"),
        demotions=demotions,
        numerics=ys.get("numerics"),
        final_state=state_out,
    )


def run_simulation(
    case: Scenario,
    yuma_version: str,
    yuma_config: Optional[YumaConfig] = None,
    *,
    supervised: bool = False,
    fleet=None,
) -> tuple[dict[str, list[float]], list[np.ndarray], list[np.ndarray]]:
    """Drop-in equivalent of the reference driver
    (simulation_utils.py:26-112): returns `(dividends_per_validator,
    bonds_per_epoch, server_incentives_per_epoch)` with numpy arrays in
    place of torch tensors.

    `supervised=True` (new, default off — byte-for-byte the reference
    behavior otherwise) arms the production resilience tier: the
    default engine-degradation ladder plus the default deadline
    watchdog, so a hung compile or engine failure degrades and retries
    instead of wedging/aborting the run (README "Supervised sweeps").

    `fleet=` (new; a shared store directory or a
    :class:`..fabric.FleetConfig`) runs the simulation under FLEET
    coordination: the case becomes one lease-claimed work unit in the
    shared store, so N processes invoked concurrently with the same
    store execute it exactly once between them, survive the executing
    process dying mid-run (lease expiry -> any peer re-executes), and
    all return the published result (README "Fleet sweeps"). Fleet runs
    always dispatch under the supervised resilience tier — they are
    unattended by construction.
    """
    if fleet is not None:
        from yuma_simulation_tpu.fabric.scheduler import run_fleet_case

        return run_fleet_case(
            case, yuma_version, yuma_config, fleet=fleet, supervised=True,
        )
    supervision = {}
    if supervised:
        from yuma_simulation_tpu.resilience.retry import default_retry_policy
        from yuma_simulation_tpu.resilience.supervisor import default_deadline

        supervision = {
            "retry_policy": default_retry_policy(),
            "deadline": default_deadline(),
        }
    result = simulate(
        case, yuma_version, yuma_config, save_bonds=True, save_incentives=True,
        **supervision,
    )
    dividends_per_validator = {
        validator: [float(x) for x in result.dividends[:, i]]
        for i, validator in enumerate(case.validators)
    }
    assert result.bonds is not None and result.incentives is not None
    bonds_per_epoch = list(result.bonds)
    server_incentives_per_epoch = list(result.incentives)
    return dividends_per_validator, bonds_per_epoch, server_incentives_per_epoch


def simulate_streamed(
    chunks,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
    *,
    reset_bonds_index: Optional[int] = None,
    reset_bonds_epoch: Optional[int] = None,
    save_bonds: bool = False,
    save_incentives: bool = False,
    save_consensus: bool = False,
    consensus_impl: str = "bisect",
    epoch_impl: str = "auto",
    dtype=jnp.float32,
    retry_policy=None,
    initial_state: Optional[dict] = None,
    epoch_offset: int = 0,
    return_state: bool = False,
) -> SimulationResult:
    """Chunked epoch streaming: true-per-epoch-weights runs beyond HBM.

    `initial_state` / `epoch_offset` / `return_state`: the same
    suffix-resume contract as :func:`simulate` — the stream's chunk 0
    starts from the supplied carry at global epoch ``epoch_offset``
    instead of the zero carry at epoch 0, and ``return_state=True``
    returns the post-final-chunk carry on
    :attr:`SimulationResult.final_state`. A fresh device copy of the
    initial carry is staged per ladder attempt (the streamed engine
    twins DONATE their carry buffers, so a demotion restart must never
    hand the consumed buffers back in).

    The reference's real workload shape is genuinely different `W[e]` /
    `S[e]` every epoch (reference simulation_utils.py:44-46 feeding
    yumas.py:175); a monolithic `[E, V, M]` stack caps such runs at
    E ~ 2000 for the 256x4096 stress shape on one v5e chip. Here
    `chunks` is any iterable/generator yielding `(W [Ec, V, M],
    S [Ec, V])` slabs (host numpy or device arrays — a generator may
    build each slab on device so no full stack ever exists anywhere);
    each slab runs through the SAME per-epoch pipeline as the monolithic
    engines (`fused_case_scan` on TPU, the XLA scan elsewhere) with the
    `(bonds, consensus[, w_prev])` carry threaded between dispatches and
    the global epoch index driving first-epoch adoption and bond-reset
    rules. Results are bitwise-identical to the monolithic scan of the
    concatenated stack (pinned by tests/unit/test_streamed.py); only the
    current slab (plus the one being transferred) is resident, so HBM
    stays flat in E.

    Per-epoch outputs are fetched to host asynchronously per chunk (the
    copy overlaps the next chunk's compute) and concatenated. Defaults
    save only the `[E, V]` dividends — the streaming use case is long E,
    where an `[E, V, M]` bond history would defeat the point; pass
    `save_bonds=True` if the host has room.

    Engine choice is resolved ONCE from the first chunk's shape and then
    pinned: mixing engines across chunks would break bitwise equality
    with the monolithic run (fused vs XLA agree only to reduction-order
    rounding).

    `save_bonds`/`save_incentives`/`save_consensus` must be real bools:
    the `"auto"` resolution of :func:`simulate` is sized against the
    whole run's output stream, and a lazy chunk stream's total length is
    unknown here — a string flag would otherwise be treated as truthy
    and silently materialize the full `[E, V, M]` history the streaming
    path exists to avoid (advisor r5).

    `retry_policy` arms the engine-degradation ladder around the WHOLE
    stream: the engine is pinned per attempt, so a demotion restarts the
    stream from chunk 0 on the lower rung (never mixes engines
    mid-stream). A one-shot generator can only be replayed when the
    failure hit the first chunk (the chunk in hand is re-fed); past
    that, pass a re-iterable sequence to make demotion possible —
    otherwise a typed ValueError explains exactly that.
    """
    for name, flag in (
        ("save_bonds", save_bonds),
        ("save_incentives", save_incentives),
        ("save_consensus", save_consensus),
    ):
        if not isinstance(flag, bool):
            raise ValueError(
                f"simulate_streamed {name} must be True or False, got "
                f"{flag!r}: the total stream length is unknown up front, "
                "so 'auto' cannot be sized here (resolve it against the "
                "full shape via simulate(), or pass an explicit bool)"
            )
    config = config if config is not None else YumaConfig()
    spec = variant_for_version(yuma_version)
    if epoch_offset < 0:
        raise ValueError(f"epoch_offset must be >= 0, got {epoch_offset}")
    if retry_policy is not None:
        return _simulate_streamed_ladder(
            chunks,
            yuma_version,
            config,
            reset_bonds_index=reset_bonds_index,
            reset_bonds_epoch=reset_bonds_epoch,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=save_consensus,
            consensus_impl=consensus_impl,
            epoch_impl=epoch_impl,
            dtype=dtype,
            retry_policy=retry_policy,
            initial_state=initial_state,
            epoch_offset=epoch_offset,
            return_state=return_state,
        )
    return _simulate_streamed_attempt(
        iter(chunks),
        yuma_version,
        config,
        spec,
        reset_bonds_index=reset_bonds_index,
        reset_bonds_epoch=reset_bonds_epoch,
        save_bonds=save_bonds,
        save_incentives=save_incentives,
        save_consensus=save_consensus,
        consensus_impl=consensus_impl,
        epoch_impl=epoch_impl,
        dtype=dtype,
        initial_state=initial_state,
        epoch_offset=epoch_offset,
        return_state=return_state,
    )


class _ReiterableChunks:
    """A chunk stream that can be iterated from the start any number of
    times — `iter()` invokes the factory afresh. What the streamed
    ladder needs to restart on a demoted engine rung regardless of
    where in the stream the failure surfaced."""

    def __init__(self, make_iter):
        self._make_iter = make_iter

    def __iter__(self):
        return iter(self._make_iter())


class _CountingIter:
    """Iterator wrapper that counts consumed chunks and holds the most
    recent TWO (the double-buffered driver keeps one slab in flight
    ahead of the one computing), so an early failure can be replayed on
    a lower engine rung without re-materializing the stream."""

    def __init__(self, it):
        import collections

        self._it = it
        self.consumed = 0
        self.recent = collections.deque(maxlen=2)

    def __iter__(self):
        return self

    def __next__(self):
        item = next(self._it)
        self.consumed += 1
        self.recent.append(item)
        return item


def _simulate_streamed_ladder(
    chunks,
    yuma_version: str,
    config: YumaConfig,
    *,
    reset_bonds_index,
    reset_bonds_epoch,
    save_bonds: bool,
    save_incentives: bool,
    save_consensus: bool,
    consensus_impl: str,
    epoch_impl: str,
    dtype,
    retry_policy,
    initial_state=None,
    epoch_offset: int = 0,
    return_state: bool = False,
):
    """The degradation ladder around a whole chunk stream (see
    :func:`simulate_streamed`): peek the first chunk to resolve the
    starting rung, then run each attempt with the engine PINNED; on a
    classified engine failure restart the stream on the next rung."""
    import itertools

    from yuma_simulation_tpu.resilience.retry import run_ladder

    spec = variant_for_version(yuma_version)
    it = iter(chunks)
    try:
        first = next(it)
    except StopIteration:
        raise ValueError("simulate_streamed received no chunks") from None
    # Shape-only peek: jnp.asarray here would pin a duplicate
    # chunk-sized device buffer for the whole ladder run — an extra
    # [E_chunk, V, M] slab exactly on the path meant to survive
    # RESOURCE_EXHAUSTED. check_memory=False: the rung choice is all
    # the ladder needs; each attempt plans (and records) in full.
    shape0 = np.shape(first[0])
    if len(shape0) != 3:
        raise ValueError(
            f"streamed chunks must be [E_chunk, V, M], got {shape0}"
        )
    plan0 = plan_dispatch(
        f"streamed:{yuma_version}",
        shape0,
        spec,
        config,
        dtype,
        epoch_impl=epoch_impl,
        consensus_impl=consensus_impl,
        save_bonds=save_bonds,
        streaming=True,
        check_memory=False,
    )
    impl0 = plan0.engine
    # Anything that is not its own iterator (lists, tuples, re-iterable
    # chunk factories like simulate()'s slab slicer) can restart from
    # chunk 0; a one-shot generator cannot.
    import collections.abc

    reiterable = not isinstance(chunks, collections.abc.Iterator)
    state = {"it": itertools.chain([first], it)}

    def _dispatch(rung: str):
        tracker = _CountingIter(state["it"])
        try:
            return _simulate_streamed_attempt(
                tracker,
                yuma_version,
                config,
                spec,
                reset_bonds_index=reset_bonds_index,
                reset_bonds_epoch=reset_bonds_epoch,
                save_bonds=save_bonds,
                save_incentives=save_incentives,
                save_consensus=save_consensus,
                consensus_impl=consensus_impl,
                epoch_impl=rung,
                dtype=dtype,
                block_per_chunk=True,
                initial_state=initial_state,
                epoch_offset=epoch_offset,
                return_state=return_state,
            )
        except BaseException as exc:
            from yuma_simulation_tpu.resilience.errors import classify_failure

            if classify_failure(exc) is None:
                raise  # caller error: no replay bookkeeping needed
            if reiterable:
                state["it"] = iter(chunks)
            elif tracker.consumed <= len(tracker.recent):
                # Every consumed chunk is still held (at most the two
                # the double-buffer had in flight); re-feed them ahead
                # of the untouched remainder of the generator.
                state["it"] = itertools.chain(
                    list(tracker.recent), tracker._it
                )
            else:
                raise ValueError(
                    "engine demotion needs to restart the stream from "
                    f"chunk 0, but {tracker.consumed} chunks of a "
                    "one-shot generator were already consumed — pass a "
                    "re-iterable sequence (list/tuple) of chunks to use "
                    "retry_policy with simulate_streamed"
                ) from exc
            raise

    result, _, records = run_ladder(
        _dispatch,
        impl0,
        retry_policy,
        rungs=plan0.ladder,
        label=f"streamed:{yuma_version}",
    )
    result.demotions = tuple(records) or None
    return result


def _simulate_streamed_attempt(
    chunks,
    yuma_version: str,
    config: YumaConfig,
    spec: VariantSpec,
    *,
    reset_bonds_index,
    reset_bonds_epoch,
    save_bonds: bool,
    save_incentives: bool,
    save_consensus: bool,
    consensus_impl: str,
    epoch_impl: str,
    dtype,
    block_per_chunk: bool = False,
    initial_state=None,
    epoch_offset: int = 0,
    return_state: bool = False,
) -> SimulationResult:
    """One engine-pinned, DOUBLE-BUFFERED pass over the stream — the
    pre-resilience body of :func:`simulate_streamed`.

    Pipeline shape (the per-epoch-weights gap this closes — ROADMAP
    item 5): slab `k` is dispatched asynchronously, then slab `k+1` is
    pulled from the source and its `jax.device_put` host->HBM transfer
    STARTED before anything waits on slab `k` — so the feed of the next
    weights overlaps the scan over the current ones in every mode,
    including the ladder's `block_per_chunk` (which previously
    serialized transfer -> compute -> transfer). The chunk carry rides
    the donating engine twins (`_simulate_scan_streamed` /
    `_simulate_case_fused_streamed`), so threading it costs no second
    copy of the `[V, M]` state. Incoming chunks larger than the memory
    plan's slab cap (`DispatchPlan.memory.chunk_epochs` — sized so TWO
    slabs fit the device together) are re-sliced to it, which is how
    the streamed path respects the HBM preflight's chunk-size
    suggestion instead of ignoring it.

    `block_per_chunk` (ladder mode) still waits out each chunk's
    dispatch so device failures surface at the chunk that caused them,
    inside the attempt's try — the wait just happens AFTER the next
    transfer is in flight."""
    from yuma_simulation_tpu.resilience import faults

    ri = jnp.asarray(
        -1 if reset_bonds_index is None else reset_bonds_index, jnp.int32
    )
    re_ = jnp.asarray(
        -1 if reset_bonds_epoch is None else reset_bonds_epoch, jnp.int32
    )
    from yuma_simulation_tpu.telemetry.numerics import numerics_enabled

    capture = numerics_enabled()
    state: dict = {}  # "plan": DispatchPlan, set on the first chunk
    host: dict[str, list] = {"dividends": []}
    #: Per-chunk numerics sketches; the chunk-invariant merge is plain
    #: concatenation along the epoch axis (telemetry.numerics).
    sketches: list = []
    if save_bonds:
        host["bonds"] = []
    if save_incentives:
        host["incentives"] = []
    if save_consensus:
        host["consensus"] = []

    def slabs():
        """Validate incoming chunks, plan once on the first, and
        re-slice anything longer than the plan's slab cap (host-side
        views — no copy until the staged device_put)."""
        for Wc, Sc in chunks:
            if np.ndim(Wc) != 3:
                raise ValueError(
                    "streamed chunks must be [E_chunk, V, M], got "
                    f"{np.shape(Wc)}"
                )
            if "plan" not in state:
                # Same resolution as simulate(), decided once on the
                # first chunk (eligibility depends on [V, M]/mode/
                # config, not the chunk length) and pinned for the
                # whole stream — mixing engines across chunks would
                # break bitwise equality with the monolithic run.
                plan = plan_dispatch(
                    f"streamed:{yuma_version}",
                    np.shape(Wc),
                    spec,
                    config,
                    dtype,
                    epoch_impl=epoch_impl,
                    consensus_impl=consensus_impl,
                    save_bonds=save_bonds,
                    save_incentives=save_incentives,
                    save_consensus=save_consensus,
                    streaming=True,
                )
                plan.record()
                state["plan"] = plan
            cap = state["plan"].memory.chunk_epochs
            n = int(np.shape(Wc)[0])
            if cap is None or n <= cap:
                yield Wc, Sc
            else:
                for lo in range(0, n, cap):
                    yield Wc[lo : lo + cap], Sc[lo : lo + cap]

    def stage(pair):
        """Start the host->HBM transfer of one slab NOW (async):
        `jnp.asarray` commits the slab to the default device and kicks
        off the copy — the transfer the double-buffer overlaps with the
        in-flight scan."""
        Wc, Sc = pair
        return jnp.asarray(Wc, dtype), jnp.asarray(Sc, dtype)

    def dispatch(Wc, Sc, carry, offset):
        impl = state["plan"].engine
        if impl in FUSED_CASE_RUNGS:
            faults.maybe_fail_fused_dispatch()
            return _simulate_case_fused_streamed(
                Wc,
                Sc,
                ri,
                re_,
                config,
                spec,
                save_bonds=save_bonds,
                save_incentives=save_incentives,
                save_consensus=save_consensus,
                carry=carry,
                epoch_offset=offset,
                return_carry=True,
                capture_numerics=capture,
                **rung_flags(impl),
            )
        return _simulate_scan_streamed(
            Wc,
            Sc,
            ri,
            re_,
            config,
            spec,
            save_bonds=save_bonds,
            save_incentives=save_incentives,
            save_consensus=save_consensus,
            consensus_impl=state["plan"].consensus_impl,
            carry=carry,
            epoch_offset=offset,
            return_carry=True,
            capture_numerics=capture,
        )

    def _flush(ys):
        # Materialize a chunk's outputs to numpy, dropping the device
        # buffers: keeping every chunk's [Ec, V, M] outputs alive as
        # jax.Arrays until the end would accumulate exactly the
        # beyond-HBM history streaming exists to avoid. The async copy
        # was started when the chunk was dispatched, so this wait
        # overlaps the NEXT chunk's compute, not this one's.
        for k, acc in host.items():
            acc.append(np.asarray(ys[k]))
        if "numerics" in ys:
            from yuma_simulation_tpu.telemetry.numerics import to_host

            sketches.append(to_host(ys["numerics"]))

    it = slabs()
    cur = next(it, None)
    if cur is None:
        raise ValueError("simulate_streamed received no chunks")
    cur = stage(cur)
    if initial_state is not None:
        validate_initial_state(
            initial_state, spec, cur[0].shape[-2], cur[0].shape[-1]
        )
        # Fresh device buffers per attempt: the streamed engines DONATE
        # the carry, so handing the caller's (or a prior attempt's)
        # arrays in directly would consume them.
        carry = {
            k: jnp.asarray(np.asarray(v), dtype)
            for k, v in initial_state.items()
        }
    else:
        # A zeros carry is bitwise the kernels' own epoch-0 init, and
        # keeps chunk 0 on the SAME compiled program as every later
        # chunk (a carry=None first dispatch would compile a second
        # kernel variant for no numerical difference).
        carry = zero_carry(spec, cur[0].shape[-2], cur[0].shape[-1], dtype)
    offset = epoch_offset
    pending: Optional[dict] = None
    while cur is not None:
        Wc, Sc = cur
        n_epochs = int(Wc.shape[0])
        ys, carry = dispatch(Wc, Sc, carry, offset)  # async
        cur = None  # drop our slab ref; the device frees it after use
        nxt = next(it, None)  # may BUILD the next slab (host generator)
        if nxt is not None:
            nxt = stage(nxt)  # transfer k+1 overlaps the scan over k
        if block_per_chunk:
            ys, carry = jax.block_until_ready((ys, carry))
        offset += n_epochs
        for k in host:
            try:
                ys[k].copy_to_host_async()
            except AttributeError:
                pass
        if pending is not None:
            _flush(pending)
        pending = ys
        cur = nxt
    _flush(pending)
    cat = {k: np.concatenate(v) for k, v in host.items()}
    numerics = None
    if sketches:
        from yuma_simulation_tpu.telemetry.numerics import concat_sketches

        numerics = concat_sketches(sketches)
    return SimulationResult(
        dividends=cat["dividends"],
        bonds=cat.get("bonds"),
        incentives=cat.get("incentives"),
        consensus=cat.get("consensus"),
        numerics=numerics,
        final_state=jax.device_get(carry) if return_state else None,
    )


@partial(
    jax.jit,
    static_argnames=("gen_fn", "spec", "num_chunks", "impl", "consensus_impl"),
)
def _simulate_generated_run(
    config, gen_fn, spec, num_chunks: int, impl: str, consensus_impl: str
):
    W0, S0 = jax.eval_shape(gen_fn, jnp.int32(0))
    CH, V, M = W0.shape
    dtype = W0.dtype
    ri = jnp.asarray(-1, jnp.int32)
    prev = spec.bonds_mode is BondsMode.EMA_PREV

    # Statically unrolled chunk loop: wrapping the Pallas case scan in a
    # lax.fori_loop hangs this runtime's remote XLA compile for many
    # minutes (same pathology class as the sorted-consensus compile,
    # DESIGN.md "Operational caveats"), while an unrolled chain of the
    # SAME kernel compiles in seconds (the Mosaic kernel itself is
    # compiled once and reused). XLA's buffer assignment still reuses
    # the [CH, V, M] slab across iterations, so residency stays one
    # chunk regardless of num_chunks.
    B = jnp.zeros((V, M), dtype)
    C = jnp.zeros((M,), dtype)
    Wp = jnp.zeros((V, M), dtype)
    D = jnp.zeros((num_chunks * CH, V), dtype)
    for i in range(num_chunks):
        idx = jnp.asarray(i, jnp.int32)
        W, S = gen_fn(idx)
        cin = {"bonds": B, "consensus": C}
        if prev:
            cin["w_prev"] = Wp
        if impl in FUSED_CASE_RUNGS:
            ys, cout = _simulate_case_fused(
                W, S, ri, ri, config, spec,
                save_bonds=False, save_incentives=False,
                carry=cin, epoch_offset=idx * CH, return_carry=True,
                **rung_flags(impl),
            )
        else:
            ys, cout = _simulate_scan(
                W, S, ri, ri, config, spec,
                save_bonds=False, save_incentives=False,
                consensus_impl=consensus_impl,
                carry=cin, epoch_offset=idx * CH, return_carry=True,
            )
        D = lax.dynamic_update_slice(
            D, ys["dividends"], (idx * CH, jnp.zeros((), jnp.int32))
        )
        B, C = cout["bonds"], cout["consensus"]
        Wp = cout.get("w_prev", Wp)
    return D, B


def simulate_generated(
    gen_fn,
    num_chunks: int,
    yuma_version: str,
    config: Optional[YumaConfig] = None,
    *,
    epoch_impl: str = "auto",
    consensus_impl: str = "bisect",
) -> tuple[np.ndarray, np.ndarray]:
    """On-device chunked streaming in ONE dispatch: `gen_fn(i)` (a
    traceable function of the chunk index) builds chunk `i`'s
    `(W [CH, V, M], S [CH, V])` on device inside a statically unrolled
    chunk chain (NOT a `lax.fori_loop` — see the compile note in
    `_simulate_generated_run`), and each chunk runs through the same
    carry-threaded per-epoch pipeline as :func:`simulate_streamed` —
    but with zero host round-trips, so a 10k-epoch 256x4096 run costs
    one dispatch while only one `[CH, V, M]` slab is live at a time
    (XLA's buffer assignment reuses the slab across the unrolled
    iterations; a monolithic 10k-epoch stack would be ~41 GiB, far
    beyond one chip's HBM). This is the streaming shape for
    synthetic/Monte-Carlo workloads whose weights are generated, not
    loaded; host-fed data uses :func:`simulate_streamed`.

    Bitwise-identical to the monolithic scan of the concatenated chunks
    (same per-epoch math, same carry handoff — tests/unit/test_streamed.py).

    Operational caveat (remote-compile runtimes): on the axon-tunnel TPU
    runtime, XLA's compile of a multi-chunk program at large shapes
    (e.g. 10 x [1024, 256, 4096]) takes tens of minutes — the same
    remote-compile pathology class as the sorted consensus closed form
    (DESIGN.md "Operational caveats"); a lax.fori_loop chunk loop is
    worse still. Small shapes compile in seconds. On such runtimes
    prefer :func:`simulate_streamed`'s host loop, which compiles the
    per-chunk program once (~35 ms/chunk dispatch overhead).

    Returns `(dividends [num_chunks * CH, V], final_bonds [V, M])` as
    host arrays.
    """
    config = config if config is not None else YumaConfig()
    spec = variant_for_version(yuma_version)
    W0, _ = jax.eval_shape(gen_fn, jnp.int32(0))
    # check_memory=False: the generated chunks never exist on the host
    # and XLA's buffer assignment holds one [CH, V, M] slab regardless
    # of num_chunks — the preflight's epoch-stack model does not apply.
    plan = plan_dispatch(
        f"generated:{yuma_version}",
        W0.shape,
        spec,
        config,
        W0.dtype,
        epoch_impl=epoch_impl,
        consensus_impl=consensus_impl,
        streaming=True,
        check_memory=False,
    )
    plan.record()
    impl, consensus_impl = plan.engine, plan.consensus_impl
    D, B = _simulate_generated_run(
        config, gen_fn, spec, num_chunks, impl, consensus_impl
    )
    return np.asarray(D), np.asarray(B)


@partial(
    jax.jit,
    static_argnames=("spec", "consensus_impl", "epoch_impl"),
)
def simulate_scaled(
    W: jnp.ndarray,  # [V, M] base weights
    S: jnp.ndarray,  # [V]
    scales: jnp.ndarray,  # [E] per-epoch weight scale (epoch e uses W*scales[e])
    config: YumaConfig,
    spec: VariantSpec,
    consensus_impl: str = "bisect",
    epoch_impl: str = "xla",
):
    """Epoch-VARYING throughput workload: epoch `e` simulates `W*scales[e]`.

    This is the honest full-kernel benchmark path: because the weights
    differ every epoch, XLA cannot hoist any of the consensus front half
    out of the scan (with constant weights XLA's loop-invariant code
    motion silently hoists most of the kernel even when
    `hoist_invariant=False` — measured ~3x optimistic at 256x4096). The
    scalar scale is numerically almost-neutral (row normalization divides
    it back out) but is opaque to the compiler, so every epoch pays the
    full per-epoch cost exactly like a real changing-weights workload.

    `epoch_impl`:
      - "auto": pick the fastest *parity-safe* path — the
        single-Pallas-program scan when the variant/config/shape allow
        it (any bonds model incl. liquid alpha, quantile overrides,
        Yuma-0 under x64, f32 arrays, fits the VMEM budget, on TPU,
        >= 1 epoch), otherwise the XLA path. Since r4 that means the
        MXU scan ("fused_scan_mxu") wherever the exact limb-split
        support covers V (<= 2^14): its consensus support is the exact
        canonical integer sum on the MXU and the whole scan is BITWISE
        the VPU scan, ~1.6x faster.
      - "xla": the unfused `yuma_epoch` (any variant/consensus_impl).
      - "fused": the Pallas VMEM-resident EMA-family epoch kernel
        (:func:`yuma_simulation_tpu.ops.pallas_epoch.fused_ema_epoch`),
        VPU reductions (matches XLA to ~1e-9).
      - "fused_mxu": same per-epoch kernel with the consensus support
        on the exact limb-split MXU contraction (bitwise the "fused"
        path since r4; requires V <= 2^14).
      - "fused_scan" / "fused_scan_mxu": the ENTIRE epoch scan as one
        Pallas program — bond state resident in VMEM scratch across grid
        steps, W fetched from HBM once, no per-epoch dispatch
        (:func:`yuma_simulation_tpu.ops.pallas_epoch.fused_ema_scan`).
        Covers all five bond models (capacity/relative included, unlike
        the per-epoch "fused" paths). The two are bitwise-identical
        (the MXU scan's support is the exact limb-split integer
        contraction); "fused_scan_mxu" is ~1.6x faster and needs
        V <= 2^14.

    Returns `(total_dividends[V], final_bonds[V, M])` like
    `simulate_constant`.
    """
    V, M = W.shape
    dtype = W.dtype
    # The fused branches bisect in-kernel and never read consensus_impl,
    # but resolve/validate it unconditionally so "auto" works and typos
    # raise on every path (one shared contract, ops/consensus.py).
    from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

    consensus_impl = resolve_consensus_impl(consensus_impl, V, M)

    def to_dividends(D_n):
        return _dividends_per_1k(D_n, S, config, dtype)

    if epoch_impl == "auto":
        # The planner's one scaled-path resolution (trace-time host
        # arithmetic): the exact-MXU scan where the limb split covers V,
        # the VPU scan where VMEM admits it, else XLA. E=0 falls back to
        # XLA, which returns zeros.
        epoch_impl = resolve_scaled_engine(
            W.shape, spec.bonds_mode, config, W.dtype, scales.shape[0]
        )

    if epoch_impl in ("fused_scan", "fused_scan_mxu"):
        from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_scan

        B_final, D_tot = fused_ema_scan(
            W,
            S / S.sum(),
            scales,
            mode=spec.bonds_mode,
            mxu=epoch_impl == "fused_scan_mxu",
            **fused_hparams(config),
        )
        # The per-1000-tao conversion is linear in D_n, so applying it to
        # the in-kernel epoch sum equals summing per-epoch conversions.
        return to_dividends(D_tot), B_final

    if epoch_impl in ("fused", "fused_mxu"):
        from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_epoch

        if spec.bonds_mode not in _EMA_MODES:
            raise ValueError("fused epoch_impl supports the EMA family only")
        if config.liquid_alpha:
            raise ValueError("fused epoch_impl does not support liquid alpha")
        mxu = epoch_impl == "fused_mxu"
        S_n = S / S.sum()  # stake is epoch-constant; normalize once
        # fused_ema_epoch takes only the EMA-family subset of the shared
        # mapping (no capacity/decay/liquid fields) — still sourced from
        # the one helper so the spellings cannot drift between impls.
        hp = fused_hparams(config)
        ema_hp = {k: hp[k] for k in ("kappa", "bond_penalty", "bond_alpha", "precision")}

        def epoch_body(B, W_prev, scale, first):
            clip = None
            if spec.bonds_mode is BondsMode.EMA_PREV:
                W_n_now = normalize_weight_rows(W * scale)
                clip = jnp.where(first, W_n_now, W_prev)
            B_next, D_n, _ = fused_ema_epoch(
                W,
                S_n,
                B,
                w_scale=scale,
                first_epoch=first,
                clip_base=clip,
                mode=spec.bonds_mode,
                mxu=mxu,
                **ema_hp,
            )
            return B_next, normalize_weight_rows(W * scale), D_n

    else:
        if epoch_impl != "xla":
            # A typo'd/unknown impl must not silently benchmark the XLA
            # path under the wrong label (simulate() validates the same
            # way).
            raise ValueError(
                f"unknown epoch_impl {epoch_impl!r}; expected 'auto', "
                "'xla', 'fused', 'fused_mxu', 'fused_scan' or "
                "'fused_scan_mxu'"
            )

        def epoch_body(B, W_prev, scale, first):
            Wv = W * scale
            kernel_prev = None
            if spec.bonds_mode is BondsMode.EMA_PREV:
                kernel_prev = jnp.where(
                    first, normalize_weight_rows(Wv), W_prev
                )
            res = yuma_epoch(
                Wv,
                S,
                B,
                config,
                bonds_mode=spec.bonds_mode,
                W_prev=kernel_prev,
                first_epoch=first,
                consensus_impl=consensus_impl,
            )
            return (
                res[spec.bond_state_key],
                res["weight"],
                res["validator_reward_normalized"],
            )

    carries_prev = spec.carries_prev_weights

    def step(carry, xs):
        scale, epoch = xs
        B_next, W_n_now, D_n = epoch_body(
            carry.bonds, carry.w_prev, scale, epoch == 0
        )
        return (
            ScaledCarry(
                bonds=B_next,
                w_prev=W_n_now if carries_prev else None,
                acc=carry.acc + to_dividends(D_n),
            ),
            None,
        )

    E = scales.shape[0]
    zero_b = jnp.zeros((V, M), dtype)
    carry0 = ScaledCarry(
        bonds=zero_b,
        w_prev=zero_b if carries_prev else None,
        acc=jnp.zeros((V,), dtype),
    )
    final, _ = lax.scan(
        step, carry0, (scales, jnp.arange(E, dtype=jnp.int32))
    )
    return final.acc, final.bonds


@partial(
    jax.jit,
    static_argnames=("spec", "consensus_impl", "epoch_impl"),
)
def simulate_scaled_batch(
    W: jnp.ndarray,  # [B, V, M] per-scenario base weights
    S: jnp.ndarray,  # [B, V]
    scales: jnp.ndarray,  # [E] shared per-epoch weight scale
    config: YumaConfig,
    spec: VariantSpec,
    consensus_impl: str = "bisect",
    epoch_impl: str = "xla",
):
    """A scenario batch of the epoch-varying throughput workload
    (:func:`simulate_scaled`), sharing one compiled program.

    A single 256x4096 run keeps the chip a few percent utilized
    (DESIGN.md "Utilization"): each of the ~45 VPU passes per epoch is
    latency- not bandwidth-bound at that size, and they are sequentially
    dependent. Batching advances all `B` scenarios together so every
    pass works on `B`-fold data — the chip-filling configuration for
    varying-weights work.

    `epoch_impl`: "xla" (`vmap` over the per-scenario scan),
    "fused_scan" (the batched single-Pallas-program scan, VPU
    reductions), or "fused_scan_mxu" (same scan with the exact
    limb-split MXU support — bitwise-identical, the batch rides the
    dot's batch dimensions; V <= 2^14). "auto" picks the MXU scan when
    eligible on this backend, else the VPU scan, else XLA.

    `config` may carry batched `[B]` float leaves (a
    :func:`..simulation.sweep.config_grid` grid): the fused path ships
    them to the kernel as per-scenario hyperparameter vectors (ONE
    dispatch for the whole grid) and the XLA path vmaps over them.

    Returns `(total_dividends [B, V], final_bonds [B, V, M])`.
    """
    from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

    consensus_impl = resolve_consensus_impl(consensus_impl, *W.shape[-2:])
    batched_cfg = config_is_batched(config)
    if epoch_impl == "auto":
        epoch_impl = resolve_scaled_engine(
            W.shape, spec.bonds_mode, config, W.dtype, scales.shape[0]
        )
    if epoch_impl in ("fused_scan", "fused_scan_mxu"):
        from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_scan

        B_final, D_tot = fused_ema_scan(
            W,
            S / S.sum(axis=-1, keepdims=True),
            scales,
            mode=spec.bonds_mode,
            mxu=epoch_impl == "fused_scan_mxu",
            **fused_hparams(config),
        )
        if batched_cfg:
            totals = jax.vmap(
                lambda d, s, c: _dividends_per_1k(d, s, c, W.dtype),
                in_axes=(0, 0, config_vmap_axes(config)),
            )(D_tot, S, config)
        else:
            totals = _dividends_per_1k(D_tot, S, config, W.dtype)
        return totals, B_final
    if epoch_impl != "xla":
        # A typo'd impl must not silently benchmark the XLA path under
        # the wrong label.
        raise ValueError(
            f"unknown epoch_impl {epoch_impl!r} for simulate_scaled_batch; "
            "expected 'auto', 'xla', 'fused_scan' or 'fused_scan_mxu'"
        )
    if batched_cfg:
        return jax.vmap(
            lambda w, s, c: simulate_scaled(
                w, s, scales, c, spec,
                consensus_impl=consensus_impl, epoch_impl="xla",
            ),
            in_axes=(0, 0, config_vmap_axes(config)),
        )(W, S, config)
    return jax.vmap(
        lambda w, s: simulate_scaled(
            w, s, scales, config, spec,
            consensus_impl=consensus_impl, epoch_impl="xla",
        )
    )(W, S)


@partial(
    jax.jit,
    static_argnames=(
        "num_epochs", "spec", "consensus_impl", "hoist_invariant", "mesh"
    ),
)
def simulate_constant(
    W: jnp.ndarray,  # [V, M], constant across epochs
    S: jnp.ndarray,  # [V]
    num_epochs: int,
    config: YumaConfig,
    spec: VariantSpec,
    consensus_impl: str = "bisect",
    hoist_invariant: bool = False,
    mesh: Optional[Mesh] = None,
):
    """Throughput path: fixed weights, total dividends accumulated in-carry.

    Returns `total_dividends[V]` (sum over epochs of dividend-per-1000-tao)
    and the final bond state. No per-epoch outputs are materialized, so 10k+
    epoch sweeps at 256x4096 stay well inside HBM.

    `num_epochs` must be >= 1 on the hoisted path (the plain scan form
    degenerates to zeros at 0 epochs; the hoisted form has no epoch to
    seed from).

    `consensus_impl="auto"` resolves to the shape-gated sorted/bisect
    default at trace time (sorted below the documented compile-pathology
    threshold — the two produce bitwise-identical values).

    `hoist_invariant=True` exploits the constant weights: the consensus
    front half (normalize, bisection, quantize, clip, incentive, liquid
    alpha) depends only on `(W, S)`, so it runs once and the scan carries
    only the bonds recurrence + dividend conversion — the same update ops
    on the same values (agreement exact up to XLA's own fusion-dependent
    ULP at very short scan lengths), ~2x faster at 256x4096; XLA does not
    perform this hoist on its own.

    With ``mesh``, the miner axis is sharded over the mesh's last axis
    across the whole scan (both paths), for subnets beyond one chip's HBM.
    """
    # Static-arg resolution/validation at trace time: "auto" becomes the
    # shape-gated sorted/bisect default (bitwise twins; compile-cost
    # choice only), unknown strings raise.
    from yuma_simulation_tpu.ops.consensus import resolve_consensus_impl

    consensus_impl = resolve_consensus_impl(consensus_impl, *W.shape)
    # HBM preflight (telemetry.cost): analytic, pre-compile. The
    # constant-weights paths hold no epoch stack — the footprint is the
    # [V, M] working set (W, carry, intermediates), divided across the
    # miner shards when a mesh is given. 8192x131072 on a 16 GiB part
    # rejects HERE with a typed event, not minutes into a remote compile.
    from yuma_simulation_tpu.telemetry.cost import (
        estimate_hbm_bytes,
        preflight_hbm,
    )

    preflight_hbm(
        "simulate_constant",
        estimate_hbm_bytes(
            *W.shape,
            resident_epochs=0,
            itemsize=jnp.dtype(W.dtype).itemsize,
            miner_shards=(
                1 if mesh is None else int(mesh.shape[mesh.axis_names[-1]])
            ),
        ),
    )
    if hoist_invariant:
        return _simulate_constant_hoisted(
            W, S, num_epochs, config, spec, consensus_impl, mesh
        )
    V, M = W.shape
    dtype = W.dtype
    shardings = None if mesh is None else _miner_shardings(mesh, M)
    if shardings is not None:
        W = lax.with_sharding_constraint(W, shardings[0])

    def step(carry, epoch):
        B, W_prev, C_prev = carry.bonds, carry.w_prev, carry.consensus
        first = epoch == 0
        if shardings is not None:
            vm, m = shardings
            B = lax.with_sharding_constraint(B, vm)
            W_prev = lax.with_sharding_constraint(W_prev, vm)
            C_prev = lax.with_sharding_constraint(C_prev, m)
        if spec.reset_mode is not ResetMode.NONE:
            B = _apply_reset(
                B, C_prev, epoch, jnp.int32(-1), jnp.int32(-1), spec.reset_mode, M
            )
        kernel_prev = None
        if spec.bonds_mode is BondsMode.EMA_PREV:
            kernel_prev = jnp.where(first, normalize_weight_rows(W), W_prev)
        res = yuma_epoch(
            W,
            S,
            B,
            config,
            bonds_mode=spec.bonds_mode,
            W_prev=kernel_prev,
            first_epoch=first,
            consensus_impl=consensus_impl,
        )
        dividends = _dividends_per_1k(
            res["validator_reward_normalized"], S, config, dtype
        )
        B_next = res[spec.bond_state_key]
        W_prev_next = res["weight"] if spec.carries_prev_weights else W_prev
        return (
            TotalsCarry(
                bonds=B_next,
                w_prev=W_prev_next,
                consensus=res["server_consensus_weight"],
                acc=carry.acc + dividends,
            ),
            None,
        )

    carry0 = TotalsCarry(
        bonds=jnp.zeros((V, M), dtype),
        w_prev=jnp.zeros((V, M), dtype),
        consensus=jnp.zeros((M,), dtype),
        acc=jnp.zeros((V,), dtype),
    )
    final, _ = lax.scan(
        step, carry0, jnp.arange(num_epochs, dtype=jnp.int32)
    )
    return final.acc, final.bonds


def _simulate_constant_hoisted(
    W, S, num_epochs: int, config: YumaConfig, spec: VariantSpec,
    consensus_impl: str, mesh: Optional[Mesh] = None,
):
    """Constant-weights fast path: one kernel front half + a bonds-only scan.

    Epoch 0 of the full kernel supplies every epoch-invariant quantity
    (normalized weights/stakes, consensus, clipped weights, incentive,
    liquid-alpha rate, and — for the EMA families — the purchase target);
    the scan then applies exactly the per-epoch update helpers the kernel
    itself uses (:mod:`yuma_simulation_tpu.models.epoch`). Bond resets
    don't apply (no scenario metadata in the constant path — as in
    `simulate_constant`'s reset-free scan).
    """
    if num_epochs < 1:
        raise ValueError("hoist_invariant path requires num_epochs >= 1")
    dtype = W.dtype
    shardings = None if mesh is None else _miner_shardings(mesh, W.shape[-1])
    if shardings is not None:
        W = lax.with_sharding_constraint(W, shardings[0])

    # Full kernel once; also the source of the final outputs' first step.
    res0 = yuma_epoch(
        W, S, None, config, bonds_mode=spec.bonds_mode,
        consensus_impl=consensus_impl,
    )
    W_n = res0["weight"]
    S_n = res0["stake"]
    incentive = res0["server_incentive"]
    # The EMA rate, exactly as the kernel derives it (epoch.py): the
    # liquid-alpha fit on this epoch's (invariant) consensus, else the
    # static scalar. RELATIVE mode doesn't export bond_alpha (the
    # reference's Yuma4 output dict has no such key, yumas.py:595-606),
    # so recompute rather than read it back.
    if config.liquid_alpha and spec.bonds_mode is not BondsMode.CAPACITY:
        rate, _, _ = liquid_alpha_rate(
            res0["server_consensus_weight"],
            config.alpha_low,
            config.alpha_high,
            override_consensus_high=config.override_consensus_high,
            override_consensus_low=config.override_consensus_low,
        )
    else:
        rate = jnp.asarray(config.bond_alpha, dtype)

    def dividends_of(B):
        # Same partition-invariant miner-axis spelling as the full
        # kernel (ops/normalize.py::miner_sum) — keeps hoisted == full
        # and sharded == unsharded bitwise.
        if spec.bonds_mode is BondsMode.RELATIVE:
            D = S_n * miner_sum(B * incentive)
        else:
            D = miner_sum(B * incentive)
        D_n = D / (D.sum() + 1e-6)
        return _dividends_per_1k(D_n, S, config, dtype)

    pin = (
        (lambda B: lax.with_sharding_constraint(B, shardings[0]))
        if shardings is not None
        else (lambda B: B)
    )

    if spec.bonds_mode in _EMA_MODES:
        B_target = res0["validator_bond"]
        renorm = spec.bonds_mode is BondsMode.EMA_RUST

        def bonds_update(B_prev):
            return pin(ema_bonds_update(B_target, pin(B_prev), rate, None, renorm))

        B0 = res0["validator_ema_bond"]
    elif spec.bonds_mode is BondsMode.CAPACITY:

        def bonds_update(B_prev):
            return pin(capacity_bonds_update(pin(B_prev), W_n, S_n, config))

        B0 = res0["validator_bonds"]
    else:  # RELATIVE

        def bonds_update(B_prev):
            return pin(relative_bonds_update(pin(B_prev), W_n, rate))

        B0 = res0["validator_bonds"]

    def step(carry, _):
        B_next = bonds_update(carry.bonds)
        return (
            HoistedCarry(bonds=B_next, acc=carry.acc + dividends_of(B_next)),
            None,
        )

    acc0 = dividends_of(B0)
    if num_epochs == 1:
        return acc0, B0
    final, _ = lax.scan(
        step, HoistedCarry(bonds=B0, acc=acc0), None, length=num_epochs - 1
    )
    return final.acc, final.bonds
